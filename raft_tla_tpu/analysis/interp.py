"""Abstract interpretation of action-kernel jaxprs.

The action kernels (``models/actions.py``) are pure, statically-shaped
JAX functions, so the model can be analyzed without running the state
space: trace each family once to a jaxpr, then re-evaluate that jaxpr
under an abstract domain instead of on device.  Two domains share one
evaluator:

- :class:`TaintDomain` (effects pass): each value carries ELEMENT-WISE
  dependency masks per ``StateBatch`` field, split into a value-level
  half (``vdeps`` — any element may depend on the masked field
  elements) and a positional half (``pdeps`` — element ``p`` depends on
  the field only through element ``p``), an element-wise "may differ
  from input field F at this position" mask (``origin`` / ``diff``),
  and a partial concrete evaluation (``known``/``vals``) so
  parameter-derived index masks like ``arange(N) == i`` stay exact and
  writes stay confined to the instance's own lanes.  Indexed accesses
  with parameter-concrete indices touch exactly their window; a
  state-dependent index component widens only its own axis.  The
  positional/value split is what makes point updates read only their
  own row: intersecting ``pdeps`` with a write's changed positions
  discards the identity pass-through.
- :class:`IntervalDomain` (bounds pass): each value is an element-wise
  integer interval ``[lo, hi]`` in int64, so packed-lane bounds and
  int32 wrap are decided by monotone transfer functions; parameters and
  literals are degenerate intervals, which makes the evaluation a
  partial evaluation of the kernel (concrete where the model is
  concrete, abstract only where state flows in).

Both domains are *conservative*: a primitive without a precise rule
falls back to "depends on everything that flowed in / full dtype range"
and records the imprecision in ``domain.notes`` so a pass can surface
it instead of silently claiming a proof.

Tracing happens once per action family with abstract scalar parameters;
per-instance results come from re-running the evaluator with that
instance's concrete parameter values.  This matches the executed
semantics exactly: ``build_expand`` vmaps the same kernels over the
same parameter arrays.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Dict, FrozenSet, List, Optional, Tuple

import numpy as np

_I64 = np.int64

# Call-like primitives whose single inner jaxpr is evaluated inline.
_CALL_PRIMS = ("pjit", "closed_call", "core_call", "remat", "checkpoint",
               "custom_jvp_call", "custom_vjp_call")


def _dtype_range(dtype) -> Tuple[int, int]:
    dtype = np.dtype(dtype)
    if dtype == np.bool_:
        return 0, 1
    info = np.iinfo(dtype)
    return int(info.min), int(info.max)


def _axes(eqn_params) -> Tuple[int, ...]:
    return tuple(eqn_params.get("axes", ()))


def _out_aval(eqn, k: int = 0):
    return eqn.outvars[k].aval


@functools.lru_cache(maxsize=1)
def _literal_cls():
    """``Literal`` moved to ``jax.extend.core`` (~0.4.35) and the
    ``jax.core`` alias is removed in jax >= 0.6 — the CI analyze job
    installs unpinned ``jax[cpu]``, so resolve it lazily."""
    try:
        from jax.extend.core import Literal
    except ImportError:        # older jax without jax.extend.core
        from jax.core import Literal
    return Literal


# ---------------------------------------------------------------------------
# Shared evaluator


def eval_jaxpr(closed, args: list, domain) -> list:
    """Evaluate a ClosedJaxpr under ``domain``.  ``args`` are domain
    values (or anything ``domain.lift`` accepts) for the invars."""
    jaxpr = closed.jaxpr
    env: Dict = {}

    def read(atom):
        if isinstance(atom, _literal_cls()):
            return domain.lift(np.asarray(atom.val))
        return env[atom]

    for var, const in zip(jaxpr.constvars, closed.consts):
        env[var] = domain.lift(np.asarray(const))
    assert len(jaxpr.invars) == len(args)
    for var, val in zip(jaxpr.invars, args):
        env[var] = domain.lift(val)

    for eqn in jaxpr.eqns:
        invals = [read(x) for x in eqn.invars]
        name = eqn.primitive.name
        if name in _CALL_PRIMS:
            inner = eqn.params.get("jaxpr") or eqn.params.get("call_jaxpr")
            if inner is not None and len(inner.jaxpr.invars) == len(invals):
                outs = eval_jaxpr(inner, invals, domain)
            else:
                outs = [domain.unknown(v.aval, invals, f"call:{name}")
                        for v in eqn.outvars]
        else:
            outs = domain.apply(name, eqn, invals)
        if not isinstance(outs, (list, tuple)):
            outs = [outs]
        for var, out in zip(eqn.outvars, outs):
            env[var] = out
    return [read(x) for x in jaxpr.outvars]


# ---------------------------------------------------------------------------
# Interval domain


@dataclasses.dataclass
class Interval:
    """Element-wise integer interval; ``lo``/``hi`` are int64 arrays of
    the value's shape, ``dtype`` the traced dtype (bools are 0/1)."""

    lo: np.ndarray
    hi: np.ndarray
    dtype: np.dtype

    @property
    def shape(self):
        return self.lo.shape

    @property
    def degenerate(self) -> np.ndarray:
        return self.lo == self.hi

    def is_concrete(self) -> bool:
        return bool(np.all(self.lo == self.hi))


def _ival(lo, hi, dtype) -> Interval:
    lo = np.asarray(lo, _I64)
    hi = np.asarray(hi, _I64)
    lo, hi = np.broadcast_arrays(lo, hi)
    return Interval(np.array(lo), np.array(hi), np.dtype(dtype))


def _bool_ival(must, may) -> Interval:
    return _ival(np.asarray(must, _I64), np.asarray(may, _I64), np.bool_)


def _or_upper(ha, hb):
    """Upper bound for x | y (and x ^ y) with x in [0,ha], y in [0,hb]:
    the all-ones value at the wider operand's bit length."""
    m = np.maximum(np.maximum(ha, hb), 0).astype(np.float64)
    bits = np.ceil(np.log2(m + 1)).astype(_I64)
    return (np.int64(1) << bits) - 1


class IntervalDomain:
    """Transfer functions over element-wise intervals.  Conservative:
    every rule's output interval contains every concretely reachable
    value; unhandled primitives widen to the full dtype range and are
    recorded in ``notes``.  Integer overflow of the *traced* dtype
    (e.g. int32 wrap inside a kernel) is recorded in ``wraps`` and the
    value widened to the dtype's range."""

    def __init__(self):
        self.notes: List[str] = []
        self.wraps: List[str] = []

    # -- lifting -------------------------------------------------------
    def lift(self, x):
        if isinstance(x, Interval):
            return x
        arr = np.asarray(x)
        return _ival(arr.astype(_I64), arr.astype(_I64), arr.dtype)

    def unknown(self, aval, invals, why: str) -> Interval:
        if why not in self.notes:
            self.notes.append(why)
        lo, hi = _dtype_range(aval.dtype)
        return _ival(np.full(aval.shape, lo), np.full(aval.shape, hi),
                     aval.dtype)

    # -- helpers -------------------------------------------------------
    def _wrap_check(self, prim: str, out: Interval) -> Interval:
        lo, hi = _dtype_range(out.dtype)
        if bool(np.any(out.lo < lo)) or bool(np.any(out.hi > hi)):
            self.wraps.append(prim)
            return _ival(np.clip(out.lo, lo, hi), np.clip(out.hi, lo, hi),
                         out.dtype)
        return out

    # -- dispatch ------------------------------------------------------
    def apply(self, name: str, eqn, invals):
        rule = getattr(self, "_p_" + name, None)
        if rule is None:
            return [self.unknown(v.aval, invals, f"primitive:{name}")
                    for v in eqn.outvars]
        out = rule(eqn, *invals)
        if isinstance(out, Interval):
            out = self._wrap_check(name, out)
        return out

    # -- arithmetic ----------------------------------------------------
    def _p_add(self, eqn, a, b):
        return _ival(a.lo + b.lo, a.hi + b.hi, _out_aval(eqn).dtype)

    def _p_sub(self, eqn, a, b):
        return _ival(a.lo - b.hi, a.hi - b.lo, _out_aval(eqn).dtype)

    def _p_mul(self, eqn, a, b):
        ps = [a.lo * b.lo, a.lo * b.hi, a.hi * b.lo, a.hi * b.hi]
        return _ival(np.minimum.reduce(ps), np.maximum.reduce(ps),
                     _out_aval(eqn).dtype)

    def _p_neg(self, eqn, a):
        return _ival(-a.hi, -a.lo, _out_aval(eqn).dtype)

    def _p_abs(self, eqn, a):
        lo = np.where((a.lo <= 0) & (a.hi >= 0), 0,
                      np.minimum(np.abs(a.lo), np.abs(a.hi)))
        return _ival(lo, np.maximum(np.abs(a.lo), np.abs(a.hi)),
                     _out_aval(eqn).dtype)

    def _p_max(self, eqn, a, b):
        return _ival(np.maximum(a.lo, b.lo), np.maximum(a.hi, b.hi),
                     _out_aval(eqn).dtype)

    def _p_min(self, eqn, a, b):
        return _ival(np.minimum(a.lo, b.lo), np.minimum(a.hi, b.hi),
                     _out_aval(eqn).dtype)

    def _p_clamp(self, eqn, lo_b, x, hi_b):
        return _ival(np.clip(x.lo, lo_b.lo, hi_b.lo),
                     np.clip(x.hi, lo_b.hi, hi_b.hi),
                     _out_aval(eqn).dtype)

    # -- comparisons ---------------------------------------------------
    def _p_eq(self, eqn, a, b):
        must = a.degenerate & b.degenerate & (a.lo == b.lo)
        may = (a.lo <= b.hi) & (b.lo <= a.hi)
        return _bool_ival(must, may)

    def _p_ne(self, eqn, a, b):
        eq = self._p_eq(eqn, a, b)
        return _bool_ival(1 - eq.hi, 1 - eq.lo)

    def _p_lt(self, eqn, a, b):
        return _bool_ival(a.hi < b.lo, a.lo < b.hi)

    def _p_le(self, eqn, a, b):
        return _bool_ival(a.hi <= b.lo, a.lo <= b.hi)

    def _p_gt(self, eqn, a, b):
        return _bool_ival(a.lo > b.hi, a.hi > b.lo)

    def _p_ge(self, eqn, a, b):
        return _bool_ival(a.lo >= b.hi, a.hi >= b.lo)

    # -- logic / bitwise -----------------------------------------------
    def _p_and(self, eqn, a, b):
        if np.dtype(_out_aval(eqn).dtype) == np.bool_:
            return _bool_ival(np.minimum(a.lo, b.lo), np.minimum(a.hi, b.hi))
        if np.all(a.lo >= 0) and np.all(b.lo >= 0):
            return _ival(0, np.minimum(a.hi, b.hi), _out_aval(eqn).dtype)
        return self.unknown(_out_aval(eqn), (a, b), "bitwise-and:negative")

    def _p_or(self, eqn, a, b):
        if np.dtype(_out_aval(eqn).dtype) == np.bool_:
            return _bool_ival(np.maximum(a.lo, b.lo), np.maximum(a.hi, b.hi))
        if np.all(a.lo >= 0) and np.all(b.lo >= 0):
            return _ival(np.maximum(a.lo, b.lo), _or_upper(a.hi, b.hi),
                         _out_aval(eqn).dtype)
        return self.unknown(_out_aval(eqn), (a, b), "bitwise-or:negative")

    def _p_xor(self, eqn, a, b):
        if np.dtype(_out_aval(eqn).dtype) == np.bool_:
            return _bool_ival(np.zeros_like(a.lo), np.ones_like(a.hi))
        if np.all(a.lo >= 0) and np.all(b.lo >= 0):
            return _ival(0, _or_upper(a.hi, b.hi), _out_aval(eqn).dtype)
        return self.unknown(_out_aval(eqn), (a, b), "bitwise-xor:negative")

    def _p_not(self, eqn, a):
        if np.dtype(_out_aval(eqn).dtype) == np.bool_:
            return _bool_ival(1 - a.hi, 1 - a.lo)
        return _ival(~a.hi, ~a.lo, _out_aval(eqn).dtype)   # monotone dec.

    def _p_shift_left(self, eqn, a, b):
        if np.all(a.lo >= 0) and np.all(b.lo >= 0):
            sh_lo = np.clip(b.lo, 0, 62)
            sh_hi = np.clip(b.hi, 0, 62)
            return _ival(a.lo << sh_lo, a.hi << sh_hi, _out_aval(eqn).dtype)
        return self.unknown(_out_aval(eqn), (a, b), "shift_left:negative")

    def _p_shift_right_arithmetic(self, eqn, a, b):
        sh_lo = np.clip(b.lo, 0, 62)
        sh_hi = np.clip(b.hi, 0, 62)
        return _ival(np.minimum(a.lo >> sh_lo, a.lo >> sh_hi),
                     np.maximum(a.hi >> sh_lo, a.hi >> sh_hi),
                     _out_aval(eqn).dtype)

    def _p_shift_right_logical(self, eqn, a, b):
        if np.all(a.lo >= 0):
            return self._p_shift_right_arithmetic(eqn, a, b)
        return self.unknown(_out_aval(eqn), (a, b), "shift_right:negative")

    # -- selection -----------------------------------------------------
    def _p_select_n(self, eqn, pred, *cases):
        shape = _out_aval(eqn).shape
        plo = np.broadcast_to(pred.lo, shape)
        phi = np.broadcast_to(pred.hi, shape)
        deg = plo == phi
        los = [np.broadcast_to(c.lo, shape) for c in cases]
        his = [np.broadcast_to(c.hi, shape) for c in cases]
        join_lo = np.minimum.reduce(los)
        join_hi = np.maximum.reduce(his)
        sel_lo = np.select([deg & (plo == k) for k in range(len(cases))],
                           los, join_lo)
        sel_hi = np.select([deg & (plo == k) for k in range(len(cases))],
                           his, join_hi)
        lo = np.where(deg, sel_lo, join_lo)
        hi = np.where(deg, sel_hi, join_hi)
        return _ival(lo, hi, _out_aval(eqn).dtype)

    # -- structure -----------------------------------------------------
    def _p_broadcast_in_dim(self, eqn, a):
        shape = tuple(eqn.params["shape"])
        bdims = tuple(eqn.params["broadcast_dimensions"])
        mid = [1] * len(shape)
        for opd, outd in enumerate(bdims):
            mid[outd] = a.lo.shape[opd]
        lo = np.broadcast_to(a.lo.reshape(mid), shape)
        hi = np.broadcast_to(a.hi.reshape(mid), shape)
        return _ival(lo, hi, _out_aval(eqn).dtype)

    def _p_reshape(self, eqn, a):
        shape = tuple(eqn.params["new_sizes"])
        return _ival(a.lo.reshape(shape), a.hi.reshape(shape),
                     _out_aval(eqn).dtype)

    def _p_squeeze(self, eqn, a):
        shape = _out_aval(eqn).shape
        return _ival(a.lo.reshape(shape), a.hi.reshape(shape),
                     _out_aval(eqn).dtype)

    def _p_expand_dims(self, eqn, a):
        shape = _out_aval(eqn).shape
        return _ival(a.lo.reshape(shape), a.hi.reshape(shape),
                     _out_aval(eqn).dtype)

    def _p_concatenate(self, eqn, *parts):
        d = eqn.params["dimension"]
        return _ival(np.concatenate([p.lo for p in parts], axis=d),
                     np.concatenate([p.hi for p in parts], axis=d),
                     _out_aval(eqn).dtype)

    def _p_slice(self, eqn, a):
        idx = tuple(slice(s, l, st or 1) for s, l, st in zip(
            eqn.params["start_indices"], eqn.params["limit_indices"],
            eqn.params["strides"] or [1] * len(eqn.params["start_indices"])))
        return _ival(a.lo[idx], a.hi[idx], _out_aval(eqn).dtype)

    def _p_transpose(self, eqn, a):
        perm = tuple(eqn.params["permutation"])
        return _ival(np.transpose(a.lo, perm), np.transpose(a.hi, perm),
                     _out_aval(eqn).dtype)

    def _p_rev(self, eqn, a):
        dims = tuple(eqn.params["dimensions"])
        return _ival(np.flip(a.lo, dims), np.flip(a.hi, dims),
                     _out_aval(eqn).dtype)

    def _p_iota(self, eqn):
        shape = tuple(eqn.params["shape"])
        dim = eqn.params["dimension"]
        mid = [1] * len(shape)
        mid[dim] = shape[dim]
        arr = np.broadcast_to(
            np.arange(shape[dim], dtype=_I64).reshape(mid), shape)
        return _ival(arr, arr, _out_aval(eqn).dtype)

    def _p_convert_element_type(self, eqn, a):
        dtype = np.dtype(_out_aval(eqn).dtype)
        if dtype == np.bool_:
            must = (a.lo > 0) | (a.hi < 0)
            may = ~((a.lo == 0) & (a.hi == 0))
            return _bool_ival(must, may)
        out = _ival(a.lo, a.hi, dtype)
        return out          # _wrap_check in apply() handles narrowing

    def _p_stop_gradient(self, eqn, a):
        return a

    def _p_copy(self, eqn, a):
        return a

    # -- reductions ----------------------------------------------------
    def _p_reduce_sum(self, eqn, a):
        ax = _axes(eqn.params)
        return _ival(a.lo.sum(axis=ax), a.hi.sum(axis=ax),
                     _out_aval(eqn).dtype)

    def _p_reduce_max(self, eqn, a):
        ax = _axes(eqn.params)
        return _ival(a.lo.max(axis=ax), a.hi.max(axis=ax),
                     _out_aval(eqn).dtype)

    def _p_reduce_min(self, eqn, a):
        ax = _axes(eqn.params)
        return _ival(a.lo.min(axis=ax), a.hi.min(axis=ax),
                     _out_aval(eqn).dtype)

    def _p_reduce_and(self, eqn, a):
        ax = _axes(eqn.params)
        return _bool_ival(a.lo.min(axis=ax), a.hi.min(axis=ax))

    def _p_reduce_or(self, eqn, a):
        ax = _axes(eqn.params)
        return _bool_ival(a.lo.max(axis=ax), a.hi.max(axis=ax))

    def _p_argmax(self, eqn, a):
        return self._arg_reduce(eqn, a, np.argmax)

    def _p_argmin(self, eqn, a):
        return self._arg_reduce(eqn, a, np.argmin)

    def _arg_reduce(self, eqn, a, fn):
        ax = tuple(eqn.params["axes"])[0]
        if a.is_concrete():
            out = fn(a.lo, axis=ax)
            return _ival(out, out, _out_aval(eqn).dtype)
        return _ival(np.zeros(_out_aval(eqn).shape, _I64),
                     np.full(_out_aval(eqn).shape, a.lo.shape[ax] - 1),
                     _out_aval(eqn).dtype)

    # -- indexed access ------------------------------------------------
    def _p_gather(self, eqn, operand, indices):
        dn = eqn.params["dimension_numbers"]
        slice_sizes = tuple(eqn.params["slice_sizes"])
        out_aval = _out_aval(eqn)
        # Restrict each indexed operand axis to the range the (possibly
        # abstract) start index admits — jax clamps starts into range —
        # then join (min/max) over the indexed axes, keeping window axes
        # positional.  Exact when indices are degenerate scalars and the
        # slice is size-1; conservative join otherwise.
        lo, hi = operand.lo, operand.hi
        idx_lo = indices.lo.reshape(-1, indices.lo.shape[-1]) \
            if indices.lo.ndim else indices.lo.reshape(1, -1)
        idx_hi = indices.hi.reshape(idx_lo.shape)
        n_batches = idx_lo.shape[0]
        exact = n_batches == 1
        for k, ax in enumerate(dn.start_index_map):
            size = slice_sizes[ax]
            dim = operand.lo.shape[ax]
            s_lo = int(np.clip(idx_lo[:, k].min(), 0, max(dim - size, 0)))
            s_hi = int(np.clip(idx_hi[:, k].max(), 0, max(dim - size, 0)))
            sl = [slice(None)] * operand.lo.ndim
            sl[ax] = slice(s_lo, s_hi + size)
            lo, hi = lo[tuple(sl)], hi[tuple(sl)]
            if s_lo != s_hi or not exact:
                # Join over the uncertainty window, collapse to width
                # ``size`` by pooling (sound: every possible slice of
                # width ``size`` is contained in the pooled join).
                lo = np.min(lo, axis=ax, keepdims=True)
                hi = np.max(hi, axis=ax, keepdims=True)
                reps = [1] * lo.ndim
                reps[ax] = size
                lo, hi = np.tile(lo, reps), np.tile(hi, reps)
        for ax in sorted(dn.collapsed_slice_dims, reverse=True):
            lo = np.squeeze(lo, axis=ax)
            hi = np.squeeze(hi, axis=ax)
        try:
            lo = np.broadcast_to(lo.reshape(lo.shape), out_aval.shape)
            hi = np.broadcast_to(hi.reshape(hi.shape), out_aval.shape)
        except ValueError:
            # Batched / reordered gather beyond the simple form: smear.
            lo = np.full(out_aval.shape, operand.lo.min())
            hi = np.full(out_aval.shape, operand.hi.max())
        return _ival(lo, hi, out_aval.dtype)

    def _p_scatter(self, eqn, operand, indices, updates):
        out_aval = _out_aval(eqn)
        dn = eqn.params["dimension_numbers"]
        if indices.is_concrete() and updates.lo.size == 1 \
                and len(dn.scatter_dims_to_operand_dims) == operand.lo.ndim:
            # Single fully-indexed scalar update (the ``.at[k].set(v)``
            # shape the kernels use): exact positional set.
            pos = tuple(int(x) for x in indices.lo.reshape(-1))
            lo, hi = operand.lo.copy(), operand.hi.copy()
            lo[pos] = updates.lo.reshape(())
            hi[pos] = updates.hi.reshape(())
            return _ival(lo, hi, out_aval.dtype)
        lo = np.minimum(operand.lo, updates.lo.min())
        hi = np.maximum(operand.hi, updates.hi.max())
        return _ival(lo, hi, out_aval.dtype)

    def _p_dynamic_slice(self, eqn, operand, *starts):
        sizes = tuple(eqn.params["slice_sizes"])
        lo, hi = operand.lo, operand.hi
        for ax, (st, size) in enumerate(zip(starts, sizes)):
            dim = operand.lo.shape[ax]
            s_lo = int(np.clip(st.lo, 0, max(dim - size, 0)))
            s_hi = int(np.clip(st.hi, 0, max(dim - size, 0)))
            sl = [slice(None)] * lo.ndim
            sl[ax] = slice(s_lo, s_hi + size)
            lo, hi = lo[tuple(sl)], hi[tuple(sl)]
            if s_lo != s_hi:
                lo = np.tile(np.min(lo, axis=ax, keepdims=True),
                             [size if i == ax else 1
                              for i in range(lo.ndim)])
                hi = np.tile(np.max(hi, axis=ax, keepdims=True),
                             [size if i == ax else 1
                              for i in range(hi.ndim)])
        return _ival(lo, hi, _out_aval(eqn).dtype)

    def _p_dynamic_update_slice(self, eqn, operand, update, *starts):
        lo, hi = operand.lo.copy(), operand.hi.copy()
        if all(s.is_concrete() for s in starts):
            pos = []
            for ax, st in enumerate(starts):
                dim = operand.lo.shape[ax]
                size = update.lo.shape[ax]
                pos.append(slice(
                    int(np.clip(st.lo, 0, dim - size)),
                    int(np.clip(st.lo, 0, dim - size)) + size))
            lo[tuple(pos)] = update.lo
            hi[tuple(pos)] = update.hi
            return _ival(lo, hi, _out_aval(eqn).dtype)
        # Unknown placement: any element may be original or updated.
        return _ival(np.minimum(lo, update.lo.min()),
                     np.maximum(hi, update.hi.max()),
                     _out_aval(eqn).dtype)


# ---------------------------------------------------------------------------
# Taint domain


_EMPTY: FrozenSet[str] = frozenset()

#: Element-wise dependency footprint: field name -> bool mask over THAT
#: FIELD's shape.  Masks are treated as immutable (never updated in
#: place), so dictionaries may share arrays freely.
Deps = Dict[str, np.ndarray]


def _dunion(*dicts: Deps) -> Deps:
    """Key-wise OR of dependency footprints."""
    out: Deps = {}
    for d in dicts:
        for f, m in d.items():
            prev = out.get(f)
            out[f] = m if prev is None else (prev | m)
    return out


def read_mask(t: "Taint") -> Deps:
    """The value's full element-wise read set (value-level join of the
    positional and value-level halves)."""
    return _dunion(t.vdeps, t.pdeps)


@dataclasses.dataclass
class Taint:
    """Element-wise dependency/identity abstraction.

    Dependencies are tracked per input-field ELEMENT, split in two:

    - ``vdeps[f]`` — value-level: ANY element of this value may depend
      on the masked elements of field ``f``.
    - ``pdeps[f]`` — positional: element ``p`` of this value may depend
      on field ``f`` only through ``f[p]`` (the mask marks which
      positions).  Only meaningful while the value's shape equals the
      field's shape; every shape-changing primitive graduates the
      positional half into ``vdeps`` (conservative).  This is what lets
      a point update like ``where(arange(N) == i, term + 1, term)``
      read only ``term[i]`` instead of the whole field: the changed
      positions (``diff``) intersect the positional mask.
    - ``origin``/``diff``: if ``origin`` is field F, elements where
      ``diff`` is False are *provably equal to input field F at the
      same position* — the write-set extractor reads successor fields'
      ``diff`` masks directly.
    - ``known``/``vals``: partial concrete evaluation (True where the
      value is a compile-time constant for this instance's parameters);
      keeps index masks like ``arange(N) == i`` exact so writes stay
      confined to the instance's own rows.
    """

    vdeps: Deps
    pdeps: Deps
    origin: Optional[str]
    diff: np.ndarray          # bool, value shape
    known: np.ndarray         # bool, value shape
    vals: np.ndarray          # int64, valid where known
    dtype: np.dtype

    @property
    def shape(self):
        return self.diff.shape

    @property
    def deps(self) -> FrozenSet[str]:
        """Field-level view of the read set (compat / summaries)."""
        return frozenset(set(self.vdeps) | set(self.pdeps))


def _taint(vdeps, pdeps, origin, diff, known, vals, dtype) -> Taint:
    diff = np.asarray(diff, bool)
    known = np.asarray(known, bool)
    vals = np.asarray(vals, _I64)
    diff, known, vals = np.broadcast_arrays(diff, known, vals)
    if known.all():
        vdeps, pdeps, origin = {}, {}, None
    vdeps = {f: m for f, m in vdeps.items() if m.any()}
    pdeps = {f: m for f, m in pdeps.items() if m.any()}
    return Taint(vdeps, pdeps, origin, np.array(diff), np.array(known),
                 np.array(vals), np.dtype(dtype))


def _opaque(vdeps, shape, dtype) -> Taint:
    """Depends (value-level) on ``vdeps``, nothing known element-wise."""
    z = np.zeros(shape, bool)
    return _taint(vdeps, {}, None, ~z, z, np.zeros(shape, _I64), dtype)


class TaintDomain:
    """Transfer functions for dependency/identity extraction.  The only
    precision that matters downstream: (1) ``vdeps``/``pdeps`` never
    lose a real dependency, (2) ``diff`` is True wherever the element
    can differ from its origin field, (3) parameter-concrete index
    arithmetic stays ``known`` so per-instance write masks are
    lane-accurate, (4) the positional half is claimed only through
    shape-preserving element-wise flows, so intersecting it with a
    write's ``diff`` mask yields a sound slot-precise read set."""

    #: numpy implementations for the concrete (known) path.
    _NP = {
        "add": np.add, "sub": np.subtract, "mul": np.multiply,
        "max": np.maximum, "min": np.minimum, "neg": np.negative,
        "abs": np.abs,
        "eq": np.equal, "ne": np.not_equal, "lt": np.less,
        "le": np.less_equal, "gt": np.greater, "ge": np.greater_equal,
        "and": np.logical_and, "or": np.logical_or,
        "xor": np.logical_xor, "not": np.logical_not,
        "shift_left": np.left_shift,
        "shift_right_arithmetic": np.right_shift,
        "shift_right_logical": np.right_shift,
    }

    def __init__(self):
        self.notes: List[str] = []

    def lift(self, x):
        if isinstance(x, Taint):
            return x
        arr = np.asarray(x)
        return _taint({}, {}, None, np.ones(arr.shape, bool),
                      np.ones(arr.shape, bool), arr.astype(_I64), arr.dtype)

    def unknown(self, aval, invals, why: str) -> Taint:
        if why not in self.notes:
            self.notes.append(why)
        vdeps = _dunion(*(read_mask(v) for v in invals)) if invals else {}
        return _opaque(vdeps, aval.shape, aval.dtype)

    def apply(self, name: str, eqn, invals):
        if name in self._NP and len(invals) <= 2:
            return self._elementwise(eqn, name, invals)
        rule = getattr(self, "_p_" + name, None)
        if rule is None:
            return [self.unknown(v.aval, invals, f"primitive:{name}")
                    for v in eqn.outvars]
        return rule(eqn, *invals)

    def _join_deps(self, shape, invals) -> Tuple[Deps, Deps]:
        """(vdeps, pdeps) of an element-wise combination: an input of
        the output's shape keeps its positional half; a broadcast input
        graduates it to value-level (element p of the output no longer
        maps to element p of the field)."""
        vd: List[Deps] = []
        pd: List[Deps] = []
        for v in invals:
            if v.shape == shape:
                vd.append(v.vdeps)
                pd.append(v.pdeps)
            else:
                vd.append(read_mask(v))
        return _dunion(*vd), _dunion(*pd)

    # -- elementwise with partial evaluation ---------------------------
    def _elementwise(self, eqn, name, invals):
        aval = _out_aval(eqn)
        shape = aval.shape
        knowns = [np.broadcast_to(v.known, shape) for v in invals]
        vals = [np.broadcast_to(v.vals, shape) for v in invals]
        known = np.logical_and.reduce(knowns)
        # Absorbing elements make the result known even when the other
        # operand is state-dependent: False & x, True | x, 0 * x.
        if len(invals) == 2:
            a_k, b_k = knowns
            a_v, b_v = vals
            if name == "and":
                known = known | (a_k & (a_v == 0)) | (b_k & (b_v == 0))
            elif name == "or":
                known = known | (a_k & (a_v != 0)) | (b_k & (b_v != 0))
            elif name == "mul":
                known = known | (a_k & (a_v == 0)) | (b_k & (b_v == 0))
        with np.errstate(over="ignore"):
            out_vals = self._NP[name](*vals) if vals else vals
        out_vals = np.asarray(out_vals)
        if np.dtype(aval.dtype) == np.bool_:
            out_vals = out_vals.astype(bool)
        vdeps, pdeps = self._join_deps(shape, invals)
        return _taint(vdeps, pdeps, None, np.ones(shape, bool), known,
                      out_vals.astype(_I64), aval.dtype)

    # -- selection -----------------------------------------------------
    def _p_select_n(self, eqn, pred, *cases):
        aval = _out_aval(eqn)
        shape = aval.shape
        pk = np.broadcast_to(pred.known, shape)
        pv = np.broadcast_to(pred.vals, shape)
        case_known = [np.broadcast_to(c.known, shape) for c in cases]
        case_vals = [np.broadcast_to(c.vals, shape) for c in cases]
        known = np.zeros(shape, bool)
        vals = np.zeros(shape, _I64)
        used = [False] * len(cases)
        for k in range(len(cases)):
            sel = pk & (pv == k)
            known |= sel & case_known[k]
            vals = np.where(sel, case_vals[k], vals)
            used[k] = bool(np.any(sel)) or not pk.all()
        # deps: predicate plus every case that can be selected somewhere.
        vdeps, pdeps = self._join_deps(
            shape, [pred] + [c for k, c in enumerate(cases) if used[k]])
        # origin/diff: keep identity only when exactly one input field
        # appears as a case origin.
        origins = {c.origin for c in cases if c.origin is not None}
        if len(origins) == 1:
            origin = next(iter(origins))
            diffs = [np.broadcast_to(c.diff, shape)
                     if c.origin == origin else np.ones(shape, bool)
                     for c in cases]
            chosen = np.select([pk & (pv == k) for k in range(len(cases))],
                               diffs, np.logical_or.reduce(diffs))
            diff = np.where(pk, chosen, np.logical_or.reduce(diffs))
        else:
            origin, diff = None, np.ones(shape, bool)
        return _taint(vdeps, pdeps, origin, diff, known, vals, aval.dtype)

    # -- structure -----------------------------------------------------
    def _p_broadcast_in_dim(self, eqn, a):
        aval = _out_aval(eqn)
        shape = tuple(eqn.params["shape"])
        bdims = tuple(eqn.params["broadcast_dimensions"])
        mid = [1] * len(shape)
        for opd, outd in enumerate(bdims):
            mid[outd] = a.shape[opd]
        known = np.broadcast_to(a.known.reshape(mid), shape)
        vals = np.broadcast_to(a.vals.reshape(mid), shape)
        same = shape == a.shape and bdims == tuple(range(len(shape)))
        origin = a.origin if same else None
        diff = np.broadcast_to(a.diff.reshape(mid), shape) if same \
            else np.ones(shape, bool)
        vdeps = a.vdeps if same else read_mask(a)
        pdeps = a.pdeps if same else {}
        return _taint(vdeps, pdeps, origin, diff, known, vals, aval.dtype)

    def _p_reshape(self, eqn, a):
        shape = tuple(eqn.params["new_sizes"])
        same = shape == a.shape
        return _taint(a.vdeps if same else read_mask(a),
                      a.pdeps if same else {},
                      a.origin, a.diff.reshape(shape),
                      a.known.reshape(shape), a.vals.reshape(shape),
                      _out_aval(eqn).dtype)

    def _p_squeeze(self, eqn, a):
        shape = _out_aval(eqn).shape
        return _taint(read_mask(a), {}, None, np.ones(shape, bool),
                      a.known.reshape(shape), a.vals.reshape(shape),
                      _out_aval(eqn).dtype)

    def _p_expand_dims(self, eqn, a):
        shape = _out_aval(eqn).shape
        return _taint(read_mask(a), {}, None, np.ones(shape, bool),
                      a.known.reshape(shape), a.vals.reshape(shape),
                      _out_aval(eqn).dtype)

    def _p_concatenate(self, eqn, *parts):
        d = eqn.params["dimension"]
        vdeps = _dunion(*(read_mask(p) for p in parts))
        return _taint(vdeps, {}, None,
                      np.ones(_out_aval(eqn).shape, bool),
                      np.concatenate([p.known for p in parts], axis=d),
                      np.concatenate([p.vals for p in parts], axis=d),
                      _out_aval(eqn).dtype)

    def _p_slice(self, eqn, a):
        idx = tuple(slice(s, l, st or 1) for s, l, st in zip(
            eqn.params["start_indices"], eqn.params["limit_indices"],
            eqn.params["strides"] or [1] * len(eqn.params["start_indices"])))
        # The untouched positional region is not read through this value.
        region = np.zeros(a.shape, bool)
        region[idx] = True
        vdeps = _dunion(a.vdeps, {f: m & region for f, m in a.pdeps.items()})
        return _taint(vdeps, {}, None,
                      np.ones(_out_aval(eqn).shape, bool),
                      a.known[idx], a.vals[idx], _out_aval(eqn).dtype)

    def _p_iota(self, eqn):
        shape = tuple(eqn.params["shape"])
        dim = eqn.params["dimension"]
        mid = [1] * len(shape)
        mid[dim] = shape[dim]
        arr = np.broadcast_to(
            np.arange(shape[dim], dtype=_I64).reshape(mid), shape)
        return self.lift(arr.astype(_out_aval(eqn).dtype))

    def _p_convert_element_type(self, eqn, a):
        dtype = np.dtype(_out_aval(eqn).dtype)
        vals = a.vals.astype(bool).astype(_I64) if dtype == np.bool_ \
            else a.vals
        return _taint(a.vdeps, a.pdeps, a.origin, a.diff, a.known, vals,
                      dtype)

    def _p_stop_gradient(self, eqn, a):
        return a

    def _p_copy(self, eqn, a):
        return a

    def _p_transpose(self, eqn, a):
        perm = tuple(eqn.params["permutation"])
        return _taint(read_mask(a), {}, None,
                      np.ones(_out_aval(eqn).shape, bool),
                      np.transpose(a.known, perm),
                      np.transpose(a.vals, perm), _out_aval(eqn).dtype)

    def _p_rev(self, eqn, a):
        dims = tuple(eqn.params["dimensions"])
        return _taint(read_mask(a), {}, None,
                      np.ones(_out_aval(eqn).shape, bool),
                      np.flip(a.known, dims), np.flip(a.vals, dims),
                      _out_aval(eqn).dtype)

    # -- reductions (concrete when input fully known) ------------------
    _REDUCE = {"reduce_sum": np.sum, "reduce_max": np.max,
               "reduce_min": np.min, "reduce_prod": np.prod,
               "reduce_and": np.all, "reduce_or": np.any}

    def _reduce(self, eqn, a, name):
        aval = _out_aval(eqn)
        if a.known.all():
            out = np.asarray(self._REDUCE[name](a.vals,
                                                axis=_axes(eqn.params)))
            return self.lift(out.astype(aval.dtype))
        return _opaque(read_mask(a), aval.shape, aval.dtype)

    def _p_reduce_sum(self, eqn, a):
        return self._reduce(eqn, a, "reduce_sum")

    def _p_reduce_max(self, eqn, a):
        return self._reduce(eqn, a, "reduce_max")

    def _p_reduce_min(self, eqn, a):
        return self._reduce(eqn, a, "reduce_min")

    def _p_reduce_prod(self, eqn, a):
        return self._reduce(eqn, a, "reduce_prod")

    def _p_reduce_and(self, eqn, a):
        return self._reduce(eqn, a, "reduce_and")

    def _p_reduce_or(self, eqn, a):
        return self._reduce(eqn, a, "reduce_or")

    def _p_argmax(self, eqn, a):
        return self._arg_reduce(eqn, a, np.argmax)

    def _p_argmin(self, eqn, a):
        return self._arg_reduce(eqn, a, np.argmin)

    def _arg_reduce(self, eqn, a, fn):
        aval = _out_aval(eqn)
        if a.known.all():
            out = np.asarray(fn(a.vals, axis=tuple(eqn.params["axes"])[0]))
            return self.lift(out.astype(aval.dtype))
        return _opaque(read_mask(a), aval.shape, aval.dtype)

    def _p_clamp(self, eqn, lo_b, x, hi_b):
        aval = _out_aval(eqn)
        known = lo_b.known & x.known & hi_b.known
        known = np.broadcast_to(known, aval.shape)
        vals = np.clip(np.broadcast_to(x.vals, aval.shape),
                       np.broadcast_to(lo_b.vals, aval.shape),
                       np.broadcast_to(hi_b.vals, aval.shape))
        vdeps, pdeps = self._join_deps(aval.shape, (lo_b, x, hi_b))
        return _taint(vdeps, pdeps, None, np.ones(aval.shape, bool), known,
                      vals, aval.dtype)

    # -- indexed access (element-precise where the indices are) --------
    #
    # These are the rules that turn whole-field footprints into
    # slot/column-granular ones: an access whose index components are
    # parameter-concrete touches exactly the indexed window; a
    # state-dependent component widens ONLY its own axis to the full
    # dimension.  The widening stays per-element — the touched region is
    # intersected with the operand's positional mask, so e.g.
    # ``st.msg[s]`` with a concrete slot parameter reads row ``s`` only,
    # while ``st.term[mdest]`` with a message-dependent index reads the
    # whole ``term`` field (genuine, not an analyzer artifact).

    @staticmethod
    def _index_region(operand_shape, indexed_axes, slice_sizes,
                      idx_known, idx_vals) -> np.ndarray:
        """Bool mask over the operand of positions the access may touch.
        ``indexed_axes`` maps index-vector component -> operand axis;
        ``idx_known``/``idx_vals`` are [B, k] (B index rows)."""
        comp = {ax: c for c, ax in enumerate(indexed_axes)}
        axis_masks = []
        for ax, dim in enumerate(operand_shape):
            size = slice_sizes[ax]
            m = np.zeros(dim, bool)
            c = comp.get(ax)
            if c is None:
                m[:size] = True
            elif bool(idx_known[:, c].all()):
                for s in np.unique(np.clip(idx_vals[:, c], 0,
                                           max(dim - size, 0))):
                    m[int(s):int(s) + size] = True
            else:
                m[:] = True
            axis_masks.append(m)
        region = axis_masks[0]
        for m in axis_masks[1:]:
            region = region[..., None] & m
        return region

    @staticmethod
    def _flat_indices(indices) -> Tuple[np.ndarray, np.ndarray]:
        if indices.vals.ndim:
            k = indices.vals.shape[-1]
            return (indices.known.reshape(-1, k),
                    indices.vals.reshape(-1, k))
        return indices.known.reshape(1, 1), indices.vals.reshape(1, 1)

    def _read_through(self, operand, region) -> Deps:
        """Element-wise read set of an access touching ``region`` of
        ``operand``: the positional half is restricted to the touched
        positions, the value-level half cannot be."""
        return _dunion(operand.vdeps,
                       {f: m & region for f, m in operand.pdeps.items()})

    @staticmethod
    def _bind_concrete(eqn, *arrays):
        """Evaluate the eqn's primitive eagerly on concrete numpy
        arrays (used to push partially-``known`` values through indexed
        access: the gather of a known mask is the output's known
        mask)."""
        import jax.numpy as jnp
        out = eqn.primitive.bind(*(jnp.asarray(a) for a in arrays),
                                 **eqn.params)
        return np.asarray(out)

    def _p_gather(self, eqn, operand, indices):
        aval = _out_aval(eqn)
        dn = eqn.params["dimension_numbers"]
        ik, iv = self._flat_indices(indices)
        region = self._index_region(
            operand.shape, tuple(dn.start_index_map),
            tuple(eqn.params["slice_sizes"]), ik, iv)
        vdeps = _dunion(read_mask(indices),
                        self._read_through(operand, region))
        if bool(indices.known.all()) and bool(operand.known.any()):
            known = self._bind_concrete(eqn, operand.known, indices.vals)
            vals = self._bind_concrete(eqn, operand.vals, indices.vals)
            return _taint(vdeps, {}, None, np.ones(aval.shape, bool),
                          known, vals, aval.dtype)
        return _opaque(vdeps, aval.shape, aval.dtype)

    def _p_dynamic_slice(self, eqn, operand, *starts):
        aval = _out_aval(eqn)
        ik = np.array([[bool(s.known.all()) for s in starts]])
        iv = np.array([[int(s.vals.reshape(-1)[0]) for s in starts]],
                      _I64)
        region = self._index_region(
            operand.shape, tuple(range(operand.vals.ndim)),
            tuple(eqn.params["slice_sizes"]), ik, iv)
        vdeps = _dunion(self._read_through(operand, region),
                        *(read_mask(s) for s in starts))
        if bool(ik.all()) and bool(operand.known.any()):
            svals = [s.vals.reshape(()) for s in starts]
            known = self._bind_concrete(eqn, operand.known, *svals)
            vals = self._bind_concrete(eqn, operand.vals, *svals)
            return _taint(vdeps, {}, None, np.ones(aval.shape, bool),
                          known, vals, aval.dtype)
        return _opaque(vdeps, aval.shape, aval.dtype)

    def _p_dynamic_update_slice(self, eqn, operand, update, *starts):
        aval = _out_aval(eqn)
        exact = all(bool(s.known.all()) for s in starts)
        if exact:
            pos = []
            for ax, st in enumerate(starts):
                dim = operand.shape[ax]
                size = update.shape[ax]
                p = int(np.clip(int(st.vals.reshape(-1)[0]), 0,
                                dim - size))
                pos.append(slice(p, p + size))
            region = np.zeros(operand.shape, bool)
            region[tuple(pos)] = True
            known = operand.known & ~region
            vals = operand.vals.copy()
            known = known.copy()
            known[tuple(pos)] = update.known
            vals[tuple(pos)] = update.vals
        else:
            region = np.ones(operand.shape, bool)
            known = np.zeros(operand.shape, bool)
            vals = np.zeros(operand.shape, _I64)
        vdeps = _dunion(operand.vdeps, read_mask(update),
                        *(read_mask(s) for s in starts))
        # Outside the (possibly unknown) window the operand flows
        # through positionally; inside it only where the window is
        # exact does the operand element stop mattering.
        pdeps = {f: (m & ~region if exact else m)
                 for f, m in operand.pdeps.items()}
        diff = operand.diff | region
        return _taint(vdeps, pdeps, operand.origin, diff, known, vals,
                      aval.dtype)

    def _p_scatter(self, eqn, operand, indices, updates):
        aval = _out_aval(eqn)
        dn = eqn.params["dimension_numbers"]
        ik, iv = self._flat_indices(indices)
        full = len(dn.scatter_dims_to_operand_dims) == operand.vals.ndim
        # "Exact" requires concrete IN-BOUNDS unique positions: an
        # out-of-bounds update is dropped by XLA (mode-dependent), so a
        # clipped position would both record a wrong known value and
        # unsoundly clear the positional dep of the untouched element.
        exact = full and bool(ik.all()) \
            and updates.vals.size == ik.shape[0]
        if exact:
            pos = [tuple(int(iv[r, c]) for c in range(iv.shape[1]))
                   for r in range(iv.shape[0])]
            exact = len(set(pos)) == len(pos) and all(
                0 <= p[c] < operand.shape[c]
                for p in pos for c in range(len(p)))
        if exact:
            region = np.zeros(operand.shape, bool)
            for p in pos:
                region[p] = True
            # Concrete semantics via the primitive itself — the known
            # mask and values are scattered exactly the way XLA would.
            known = self._bind_concrete(eqn, operand.known, indices.vals,
                                        updates.known)
            vals = self._bind_concrete(eqn, operand.vals, indices.vals,
                                       updates.vals)
        else:
            region = self._index_region(
                operand.shape, tuple(dn.scatter_dims_to_operand_dims),
                tuple(1 if full else d for d in operand.shape), ik, iv) \
                if full else np.ones(operand.shape, bool)
            known = operand.known & ~region
            vals = operand.vals
        vdeps = _dunion(operand.vdeps, read_mask(indices),
                        read_mask(updates))
        pdeps = {f: m & ~region for f, m in operand.pdeps.items()} \
            if exact else dict(operand.pdeps)
        diff = operand.diff | region
        return _taint(vdeps, pdeps, operand.origin, diff, known, vals,
                      aval.dtype)


# ---------------------------------------------------------------------------
# Tracing


def trace_family(kernel, dims, n_params: int):
    """Trace one action-family kernel to a ClosedJaxpr with abstract
    state fields and abstract scalar parameters.  Invars are the 13
    ``StateBatch`` fields (lane_map.FIELDS order) followed by the
    parameters; outvars are ``(enabled, overflow, *successor fields)``.
    Traced once per family — per-instance analysis re-evaluates the same
    jaxpr under a domain with that instance's concrete parameters, which
    matches ``build_expand``'s vmap over the same parameter arrays."""
    import jax
    import jax.numpy as jnp

    from ..models.schema import StateBatch
    from . import lane_map

    shapes = lane_map.field_shapes(dims)

    def flat(*args):
        st = StateBatch(*args[:len(lane_map.FIELDS)])
        en, ovf, succ = kernel(st, *args[len(lane_map.FIELDS):])
        return (en, ovf) + tuple(succ)

    in_avals = [jax.ShapeDtypeStruct(shapes[f], jnp.int32)
                for f in lane_map.FIELDS]
    in_avals += [jax.ShapeDtypeStruct((), jnp.int32)] * n_params
    return jax.make_jaxpr(flat)(*in_avals)


@functools.lru_cache(maxsize=8)
def traced_kernels(dims):
    """``build_kernels(dims)`` with each family already traced:
    ``((name, closed_jaxpr, params), ...)`` in ``dims.family_names``
    order.  Memoized on ``dims`` (a frozen dataclass) because every
    pass re-derives the same jaxprs — ``build_kernels`` returns fresh
    closures each call, so jax's own trace cache never hits across
    passes; without this, an ``analyze`` run traces the full kernel set
    once per pass instead of once per model."""
    from ..models.actions import build_kernels
    return tuple((name, trace_family(kern, dims, len(params)), params)
                 for name, kern, params in build_kernels(dims))
