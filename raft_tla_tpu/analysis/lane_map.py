"""The lane/field map: one queryable description of the packed encoding.

``schema.flatten_state`` packs a ``StateBatch`` into a uint8 row and
``schema.audit_lane_widths`` prose-documents which domain fits which
lane; this module is the machine-readable version both the analyzers
and the error paths share:

- :func:`row_layout` — packed-row offset -> (field, index) decoding;
- :func:`lane_capacities` — per field (and per message column) the
  range the packed row can represent;
- :func:`field_domains` — the *declared* per-field value domains (the
  audit table's assumptions, used by the bounds pass as its widening
  envelope and verified against the kernels there);
- :func:`msg_col_name` — semantic name of a message-row column;
- :data:`FIELD_WRITERS` — which base action families write each field
  (the effects pass cross-checks this table against the traced jaxprs
  in ``tests/test_analysis.py``, so it cannot silently drift).

Import-light on purpose: no jax, no schema import at module level, so
``schema.check_packable`` can pull the decoders into its error messages
without an import cycle.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

#: StateBatch field order (= schema.StateBatch._fields; asserted in tests).
FIELDS = ("term", "role", "voted_for", "log_term", "log_val", "log_len",
          "commit", "votes_resp", "votes_gran", "next_idx", "match_idx",
          "msg", "msg_cnt")

#: Base action families that WRITE each field (derived from the spec's
#: variable footprint, raft.tla:136-430; cross-checked against the traced
#: kernels by tests/test_analysis.py::test_field_writers_table).
FIELD_WRITERS: Dict[str, Tuple[str, ...]] = {
    "term": ("Timeout", "Receive"),
    "role": ("Restart", "Timeout", "BecomeLeader", "Receive"),
    "voted_for": ("Timeout", "Receive"),
    "log_term": ("ClientRequest", "Receive"),
    "log_val": ("ClientRequest", "Receive"),
    "log_len": ("ClientRequest", "Receive"),
    # Receive is absent: AppendEntriesAlreadyDone's :309 commit write is
    # conjoined with UNCHANGED logVars (:317, the replicated upstream
    # bug), so it is enabled only when the write is a no-op.
    "commit": ("Restart", "AdvanceCommitIndex"),
    "votes_resp": ("Restart", "Timeout", "Receive"),
    "votes_gran": ("Restart", "Timeout", "Receive"),
    "next_idx": ("Restart", "BecomeLeader", "Receive"),
    "match_idx": ("Restart", "BecomeLeader", "Receive"),
    "msg": ("RequestVote", "AppendEntries", "Receive", "DropMessage"),
    "msg_cnt": ("RequestVote", "AppendEntries", "Receive",
                "DuplicateMessage", "DropMessage"),
}

#: Fields whose growth is unbounded by the spec and whose packed-lane
#: fit is enforced at runtime by ``schema.build_pack_guard`` (overflow
#: is a hard engine error, never silent aliasing).  Lane findings on
#: these degrade to WARNING when no cfg constraint bounds the growth.
GROWTH_GUARDED = ("term", "log_term", "msg_cnt", "msg")


def field_shapes(dims) -> Dict[str, Tuple[int, ...]]:
    n, L = dims.n_servers, dims.max_log
    M, W = dims.n_msg_slots, dims.msg_width
    return {"term": (n,), "role": (n,), "voted_for": (n,),
            "log_term": (n, L), "log_val": (n, L), "log_len": (n,),
            "commit": (n,), "votes_resp": (n,), "votes_gran": (n,),
            "next_idx": (n, n), "match_idx": (n, n),
            "msg": (M, W), "msg_cnt": (M,)}


def row_layout(dims) -> List[Tuple[str, int, int, Tuple[int, ...]]]:
    """Packed uint8 row layout: ``[(field, offset, size, shape), ...]``
    in ``schema.flatten_state`` order (base layout; the value high-byte
    planes under ``value_bytes == 2`` follow after)."""
    out, off = [], 0
    for f in FIELDS:
        shp = field_shapes(dims)[f]
        size = 1
        for d in shp:
            size *= d
        out.append((f, off, size, shp))
        off += size
    return out


def decode_row_offset(dims, offset: int) -> Tuple[str, Tuple[int, ...]]:
    """Packed-row byte offset -> (field, element index)."""
    for f, off, size, shp in row_layout(dims):
        if off <= offset < off + size:
            rel, idx = offset - off, []
            for d in reversed(shp):
                idx.append(rel % d)
                rel //= d
            return f, tuple(reversed(idx))
    raise IndexError(offset)


def msg_col_name(col: int, dims) -> str:
    """Semantic name of message-row column ``col`` (the payload union of
    dims.py's slot layout)."""
    L = dims.max_log
    base = {0: "mtype+1", 1: "msource+1", 2: "mdest+1", 3: "mterm",
            4: "RVReq mlastLogTerm / RVResp mvoteGranted / "
               "AEReq mprevLogIndex / AEResp msuccess",
            5: "RVReq mlastLogIndex / RVResp Len(mlog) / "
               "AEReq mprevLogTerm / AEResp mmatchIndex",
            6: "AEReq Len(mentries) / RVResp mlog term lane 0",
            9: "AEReq mcommitIndex / RVResp mlog lane"}
    if col in base:
        return base[col]
    if 6 <= col < 6 + L:
        extra = " / AEReq entry term" if col == 7 else ""
        return f"RVResp mlog term lane {col - 6}{extra}"
    if 6 + L <= col < 6 + 2 * L:
        extra = " / AEReq entry value" if col == 8 else ""
        return f"RVResp mlog value lane {col - 6 - L}{extra}"
    return f"payload column {col}"


def lane_capacities(dims) -> Dict[str, Tuple[object, object]]:
    """Per-field packed-lane ranges ``{field: (lo, hi)}``; ``msg`` maps
    to per-column ``(lo[W], hi[W])`` lists.  This is what the uint8 row
    (plus the value high-byte planes under ``value_bytes == 2``) can
    represent without aliasing — the bound the bounds pass proves."""
    import numpy as np
    vmax = 256 ** dims.value_bytes - 1
    W = dims.msg_width
    caps: Dict[str, Tuple[object, object]] = {
        f: (0, 255) for f in FIELDS}
    caps["log_val"] = (0, vmax)
    col_lo = np.zeros(W, np.int64)
    col_hi = np.full(W, 255, np.int64)
    col_lo[4], col_hi[4] = -128, 127
    for c in _msg_value_cols(dims):
        col_hi[c] = vmax
    caps["msg"] = (col_lo, col_hi)
    return caps


def _msg_value_cols(dims):
    L = dims.max_log
    if dims.value_bytes == 2:
        return tuple(sorted({8, *range(6 + L, 6 + 2 * L)}))
    return ()


def field_domains(dims) -> Dict[str, Tuple[object, object]]:
    """Declared per-field value domains — the machine-readable version
    of the ``schema.audit_lane_widths`` table.  The bounds pass uses
    these only as its *widening envelope* for fields whose interval
    does not converge on its own (index-exchange cycles, unbounded
    growth), and reports every field where one action step escapes the
    envelope, so a wrong entry here is surfaced, not silently trusted.
    ``msg`` maps to per-column arrays."""
    import numpy as np
    n, L = dims.n_servers, dims.max_log
    W = dims.msg_width
    vmax = dims.max_log_value
    dom: Dict[str, Tuple[object, object]] = {
        "term": (0, 255),                  # growth lane (pack-guarded)
        "role": (0, 2),
        "voted_for": (0, n),
        "log_term": (0, 255),              # carries term values
        "log_val": (0, vmax),
        "log_len": (0, L),
        "commit": (0, L),
        "votes_resp": (0, (1 << n) - 1),
        "votes_gran": (0, (1 << n) - 1),
        "next_idx": (1, L + 1),
        "match_idx": (0, L),
        "msg_cnt": (0, 255),               # growth lane (pack-guarded)
    }
    col_lo = np.zeros(W, np.int64)
    col_hi = np.zeros(W, np.int64)
    col_hi[0] = 5                          # mtype+1 (0 = free slot)
    col_hi[1] = col_hi[2] = n              # msource+1 / mdest+1
    col_hi[3] = 255                        # mterm (growth, pack-guarded)
    col_lo[4], col_hi[4] = -1, 127         # index uses int8; term uses
    # Columns 5.. carry terms, mlog terms, counts, indices, or values —
    # all byte lanes (the term-carrying ones runtime-guarded via the
    # sender's mterm; audit_lane_widths docstring).
    for c in range(5, W):
        col_hi[c] = 255
    for c in _msg_value_cols(dims):
        col_hi[c] = max(col_hi[c], vmax)
    dom["msg"] = (col_lo, col_hi)
    return dom


def msg_type_domains(dims) -> List[Tuple[object, object]]:
    """Declared per-message-TYPE payload domains ``[(lo[W], hi[W])]``
    for mtype 0..3 (dims.py slot layout).  The bounds pass case-splits
    ``Receive`` on the received message's type with these, which is
    what keeps union payload lanes (e.g. column 5 = AEResp mmatchIndex
    OR AEReq mprevLogTerm) from smearing a term bound into an index
    computation.  Like :func:`field_domains` these are declared
    envelopes of the schemas raft.tla:443-475 under the uint8 packing;
    the runtime pack guard remains the backstop for the term-carrying
    columns."""
    import numpy as np
    n, L = dims.n_servers, dims.max_log
    W = dims.msg_width
    vmax = dims.max_log_value
    out = []
    for t in range(4):
        lo = np.zeros(W, np.int64)
        hi = np.zeros(W, np.int64)
        lo[0] = hi[0] = t + 1
        lo[1] = lo[2] = 1
        hi[1] = hi[2] = n
        hi[3] = 255                         # mterm (pack-guarded growth)
        if t == 0:      # RequestVoteRequest
            hi[4] = 127                     # mlastLogTerm (pack guard)
            hi[5] = L                       # mlastLogIndex
        elif t == 1:    # RequestVoteResponse
            hi[4] = 1                       # mvoteGranted
            hi[5] = L                       # Len(mlog)
            for c in range(6, 6 + L):       # mlog terms
                hi[c] = 255
            for c in range(6 + L, 6 + 2 * L):   # mlog values
                hi[c] = vmax
        elif t == 2:    # AppendEntriesRequest
            lo[4], hi[4] = -1, 127          # mprevLogIndex (int8 lane)
            hi[5] = 255                     # mprevLogTerm
            hi[6] = 1                       # Len(mentries) <= 1
            if W > 7:
                hi[7] = 255                 # entry term
            if W > 8:
                hi[8] = vmax                # entry value
            if W > 9:
                hi[9] = L                   # mcommitIndex
        else:           # AppendEntriesResponse
            hi[4] = 1                       # msuccess
            hi[5] = L + 1                   # mmatchIndex
        out.append((lo, hi))
    return out


def constraint_bounds(dims, bounds) -> Dict[str, Tuple[object, object]]:
    """Per-field clamps implied by the cfg's CONSTRAINT bounds
    (models/invariants.Bounds): constraint-violating states are counted
    but never *expanded*, so the bounds pass intersects its input states
    with these before applying a kernel."""
    out: Dict[str, Tuple[object, object]] = {}
    if bounds is None:
        return out
    if bounds.max_term is not None:
        out["term"] = (0, bounds.max_term)
    if bounds.max_log_len is not None:
        out["log_len"] = (0, bounds.max_log_len)
    if bounds.max_msg_count is not None:
        out["msg_cnt"] = (0, bounds.max_msg_count)
    return out


def describe_lane(field: str, index: Optional[Tuple[int, ...]],
                  dims) -> str:
    """Human-readable lane description for error messages: field name
    plus, for message rows, the decoded column meaning, plus the action
    families that write the field."""
    where = f"state field {field!r}"
    if field == "msg" and index is not None and len(index) == 2:
        slot, col = index
        where += (f" slot {slot} column {col} "
                  f"({msg_col_name(col, dims)})")
    elif index is not None:
        where += f" at index {tuple(index)}"
    writers = FIELD_WRITERS.get(field)
    if writers:
        where += f"; lane written by action families: {', '.join(writers)}"
    return where
