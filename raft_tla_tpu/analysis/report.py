"""Findings and the machine-readable analysis report.

Every analysis pass (effects / bounds / lint) emits :class:`Finding`
records; :class:`Report` aggregates them per pass, applies the allowlist,
and renders the one JSON document the ``analyze`` CLI subcommand and the
CI gate consume.  Severity policy:

- ``ERROR``   — the model/engine pair is broken or will break silently:
  a packed lane the configured state space provably overflows, int32
  wrap inside a kernel, a host callback in the compiled BFS step, a
  blocking device read planted in the chunk loop.  CI fails on these.
- ``WARNING`` — needs a human decision but the runtime has a guard:
  unbounded-growth lanes caught by ``build_pack_guard`` at runtime,
  un-timed host syncs in the engine loop, narrowing converts that look
  accidental.
- ``INFO``    — analysis facts worth surfacing (fixpoint round counts,
  non-inductive interval notes, intentional uint8 packing converts).

Allowlisting: a finding is identified by ``code`` or ``code:qualifier``
(the qualifier is the field/site the finding anchors to).  ``analyze
--allow code[:qualifier]`` downgrades matching ERRORs to WARNING and
marks them ``allowlisted`` in the report — the finding stays visible,
it just stops gating (README "Static analysis").
"""

from __future__ import annotations

import dataclasses
import json
from typing import Dict, List, Optional

ERROR, WARNING, INFO = "ERROR", "WARNING", "INFO"
SEVERITIES = (ERROR, WARNING, INFO)


@dataclasses.dataclass
class Finding:
    """One analysis result.  ``witness`` names the action instance that
    produces the reported behavior (e.g. ``"Timeout(i=0)"``) when the
    pass can point at one."""

    pass_name: str
    severity: str
    code: str                      # stable kebab-case id, e.g. lane-overflow
    message: str
    witness: Optional[str] = None
    field: Optional[str] = None    # StateBatch field / lane the finding is on
    details: Dict = dataclasses.field(default_factory=dict)
    allowlisted: bool = False

    @property
    def qualifier(self) -> Optional[str]:
        return self.field

    def to_json(self) -> dict:
        out = {"pass": self.pass_name, "severity": self.severity,
               "code": self.code, "message": self.message}
        for k in ("witness", "field"):
            v = getattr(self, k)
            if v is not None:
                out[k] = v
        if self.details:
            out["details"] = self.details
        if self.allowlisted:
            out["allowlisted"] = True
        return out


def _matches(finding: Finding, allow: str) -> bool:
    if ":" in allow:
        code, qual = allow.split(":", 1)
        return finding.code == code and finding.qualifier == qual
    return finding.code == allow


class Report:
    """Aggregated findings from one ``analyze`` run."""

    def __init__(self, model: Optional[dict] = None,
                 allowlist: Optional[List[str]] = None):
        self.model = model or {}
        self.allowlist = list(allowlist or [])
        self.findings: List[Finding] = []
        self.pass_summaries: Dict[str, dict] = {}

    def extend(self, findings: List[Finding]) -> None:
        for f in findings:
            if f.severity not in SEVERITIES:
                raise ValueError(f"unknown severity {f.severity!r}")
            if f.severity == ERROR and any(_matches(f, a)
                                           for a in self.allowlist):
                f = dataclasses.replace(f, severity=WARNING,
                                        allowlisted=True)
            self.findings.append(f)

    def summarize_pass(self, name: str, summary: dict) -> None:
        self.pass_summaries[name] = summary

    # -- readers -------------------------------------------------------
    def severity_counts(self, pass_name: Optional[str] = None) -> dict:
        counts = {s: 0 for s in SEVERITIES}
        for f in self.findings:
            if pass_name is None or f.pass_name == pass_name:
                counts[f.severity] += 1
        return counts

    def errors(self) -> List[Finding]:
        return [f for f in self.findings if f.severity == ERROR]

    @property
    def ok(self) -> bool:
        return not self.errors()

    def first_witness(self, pass_name: Optional[str] = None):
        for f in self.findings:
            if f.severity == ERROR and f.witness is not None \
                    and (pass_name is None or f.pass_name == pass_name):
                return f.witness
        return None

    def to_json(self) -> dict:
        passes: Dict[str, dict] = {}
        for f in self.findings:
            p = passes.setdefault(
                f.pass_name,
                {"findings": [], "severity_counts": None, "summary": {}})
            p["findings"].append(f.to_json())
        for name, p in passes.items():
            p["severity_counts"] = self.severity_counts(name)
        for name, summary in self.pass_summaries.items():
            passes.setdefault(
                name,
                {"findings": [], "severity_counts": self.severity_counts(name),
                 "summary": {}})["summary"] = summary
        return {"model": self.model,
                "allowlist": self.allowlist,
                "passes": passes,
                "severity_counts": self.severity_counts(),
                "ok": self.ok}

    def render_text(self) -> str:
        lines = []
        for f in self.findings:
            if f.severity == INFO:
                continue
            loc = f" [{f.field}]" if f.field else ""
            wit = f" (witness: {f.witness})" if f.witness else ""
            mark = " (allowlisted)" if f.allowlisted else ""
            lines.append(
                f"{f.severity:7s} {f.pass_name}/{f.code}{loc}: "
                f"{f.message}{wit}{mark}")
        if "por" in self.pass_summaries:
            lines.append(render_por_table(self.pass_summaries["por"]))
        c = self.severity_counts()
        lines.append(f"analysis: {c[ERROR]} error(s), {c[WARNING]} "
                     f"warning(s), {c[INFO]} info note(s) — "
                     + ("OK" if self.ok else "FAILED"))
        return "\n".join(lines)

    def write_json(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(self.to_json(), f, indent=2, sort_keys=True)
            f.write("\n")


def render_por_table(summary: dict) -> str:
    """Text rendering of the POR pass summary: per-family certified /
    blocked counts, the closure-refutation verdict, and the top
    blocking ``(family, field, slot)`` triples — the precision worklist
    readable straight off ``analyze`` output, no JSON spelunking."""
    fams = summary.get("families", {})
    lines = [f"por: {summary.get('certified', 0)}/"
             f"{summary.get('n_instances', 0)} instance(s) certified"]
    if not fams:
        return "\n".join(lines)
    name_w = max(len(n) for n in fams) + 2
    header = (f"  {'family':<{name_w}}{'inst':>5} {'cert':>5} "
              f"{'closure':>8}  top blocking element")
    lines.append(header)
    for fam, d in fams.items():
        ref = d.get("closure_refutation")
        if d.get("certified") == d.get("instances"):
            closure = "proved"
        elif ref is None:
            closure = "blocked"
        elif ref.get("open"):
            closure = "open"          # precision worklist
        else:
            closure = "inherent"      # machine-checked impossibility
        top = d.get("blocking_elements") or []
        top_s = (f"{top[0]['family']} {top[0]['kind']} "
                 f"{top[0]['element']} ({top[0]['pairs']} pairs)") \
            if top else "-"
        lines.append(f"  {fam:<{name_w}}{d.get('instances', 0):>5} "
                     f"{d.get('certified', 0):>5} {closure:>8}  {top_s}")
    ref = summary.get("closure_refutation", {})
    if ref.get("ran"):
        lines.append(
            f"  closure refutation: {ref.get('witnessed', 0)} instance(s) "
            f"witnessed non-commuting, {ref.get('vacuous', 0)} provably "
            f"never enabled, {len(ref.get('open', []))} open")
    return "\n".join(lines)
