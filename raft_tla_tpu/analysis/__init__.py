"""Static model analysis — proofs and lints that never run the state space.

Every action kernel is a pure, statically-shaped JAX function over the
packed ``StateBatch`` encoding, so the *model itself* is analyzable at
trace time.  Three passes share one jaxpr evaluator (``interp.py``) and
one findings/report spine (``report.py``):

- :mod:`.effects` — per-action read/write sets from the kernel jaxprs:
  the action dependence matrix (which instances provably commute — the
  fact partial-order reduction and BLEST-style tensor-core batching
  need), guard-independence, and dead packed lanes;
- :mod:`.bounds` — interval abstract interpretation of every kernel to
  a reachable-envelope fixpoint: proves each packed lane wide enough
  (or names the witness action that overflows it) and flags int32 wrap,
  turning ``schema.audit_lane_widths``/``check_packable`` from runtime
  guards into trace-time proofs;
- :mod:`.lint` — TPU-throughput hazards in the compiled BFS step /
  fingerprint / FPSet kernels (host callbacks, dynamic shapes,
  non-deterministic reductions, accidental narrowing) plus an AST check
  that the host chunk loop only blocks on device data at sanctioned
  sync points.

``run_analysis`` executes the passes and aggregates one
:class:`~.report.Report`; the ``analyze`` CLI subcommand and the CI
gate consume its JSON (README "Static analysis").  Findings feed the
telemetry spine (obs/): an ``analysis`` run event per pass and
``analysis/errors`` / ``analysis/warnings`` counters.
"""

from __future__ import annotations

from typing import List, Optional

from .report import ERROR, INFO, Report, WARNING  # noqa: F401

#: Pass registry, in execution order.
PASSES = ("effects", "bounds", "lint")


def run_analysis(dims, bounds=None, init_states=None,
                 passes=PASSES, allowlist: Optional[List[str]] = None,
                 lane_caps=None, lint_targets=None,
                 metrics=None, evlog=None) -> Report:
    """Run the requested passes over one model.

    ``bounds`` is the cfg's CONSTRAINT bounds (models/invariants.Bounds),
    ``init_states`` concrete roots to seed the bounds fixpoint (None or
    randomized-smoke roots fall back to the declared domain envelope),
    ``lane_caps``/``lint_targets`` are test/fixture overrides passed to
    their passes.  ``metrics`` (MetricsRegistry) and ``evlog``
    (RunEventLog) receive the per-pass telemetry when given."""
    report = Report(model={"dims": repr(dims),
                           "model_class": type(dims).__name__},
                    allowlist=allowlist)
    for name in passes:
        if name == "effects":
            from . import effects
            summary, findings = effects.analyze(dims)
            summary = effects.summary_json(summary)
        elif name == "bounds":
            from . import bounds as bounds_mod
            summary, findings = bounds_mod.analyze(
                dims, bounds=bounds, init_states=init_states,
                lane_caps=lane_caps)
        elif name == "lint":
            from . import lint
            summary, findings = lint.analyze(dims, targets=lint_targets)
        else:
            raise ValueError(f"unknown analysis pass {name!r}; "
                             f"registered: {PASSES}")
        report.extend(findings)
        report.summarize_pass(name, summary)
        counts = report.severity_counts(name)
        if metrics is not None:
            metrics.counter("analysis/errors", counts[ERROR])
            metrics.counter("analysis/warnings", counts[WARNING])
        if evlog is not None:
            evlog.emit("analysis", pass_name=name,
                       severity_counts=counts,
                       witness=report.first_witness(name))
    return report
