"""Static model analysis — proofs and lints that never run the state space.

Every action kernel is a pure, statically-shaped JAX function over the
packed ``StateBatch`` encoding, so the *model itself* is analyzable at
trace time.  Four passes share one jaxpr evaluator (``interp.py``) and
one findings/report spine (``report.py``):

- :mod:`.effects` — per-action read/write sets from the kernel jaxprs:
  the action dependence matrix (which instances provably commute — the
  fact partial-order reduction and BLEST-style tensor-core batching
  need), guard-independence, and dead packed lanes;
- :mod:`.bounds` — interval abstract interpretation of every kernel to
  a reachable-envelope fixpoint: proves each packed lane wide enough
  (or names the witness action that overflows it) and flags int32 wrap,
  turning ``schema.audit_lane_widths``/``check_packable`` from runtime
  guards into trace-time proofs;
- :mod:`.lint` — TPU-throughput hazards in the compiled BFS step /
  fingerprint / FPSet kernels (host callbacks, dynamic shapes,
  non-deterministic reductions, accidental narrowing) plus an AST check
  that the host chunk loop only blocks on device data at sanctioned
  sync points, plus an analyzer-vs-analyzer read-set self-check;
- :mod:`.por` — static partial-order reduction: per-instance ample-set
  certificates proved from the effects matrices (closure, invariant
  visibility, cycle proviso), packed into the device-consumable
  reduction table ``EngineConfig.por`` applies in the expansion stage.

``run_analysis`` executes the passes and aggregates one
:class:`~.report.Report`; the ``analyze`` CLI subcommand and the CI
gate consume its JSON (README "Static analysis").  Findings feed the
telemetry spine (obs/): an ``analysis`` run event per pass and
``analysis/errors`` / ``analysis/warnings`` counters.
"""

from __future__ import annotations

from typing import List, Optional

from .report import ERROR, INFO, Report, WARNING  # noqa: F401

#: Pass registry, in execution order.
PASSES = ("effects", "bounds", "lint", "por")


def run_analysis(dims, bounds=None, init_states=None,
                 passes=PASSES, allowlist: Optional[List[str]] = None,
                 lane_caps=None, lint_targets=None, invariant_names=None,
                 metrics=None, evlog=None) -> Report:
    """Run the requested passes over one model.

    ``bounds`` is the cfg's CONSTRAINT bounds (models/invariants.Bounds),
    ``init_states`` concrete roots to seed the bounds fixpoint (None or
    randomized-smoke roots fall back to the declared domain envelope),
    ``lane_caps``/``lint_targets`` are test/fixture overrides passed to
    their passes, ``invariant_names`` the cfg's INVARIANT list for the
    POR visibility condition (None = the conservative full registry).
    ``metrics`` (MetricsRegistry) and ``evlog`` (RunEventLog) receive
    the per-pass telemetry when given."""
    report = Report(model={"dims": repr(dims),
                           "model_class": type(dims).__name__},
                    allowlist=allowlist)
    # The effects summary is shared downstream: lint's read-set
    # self-check and por's certificates consume the SAME matrices the
    # effects pass serialized (no re-tracing within one invocation).
    eff_summary = None
    for name in passes:
        if name == "effects":
            from . import effects
            eff_summary, findings = effects.analyze(dims)
            summary = effects.summary_json(eff_summary)
        elif name == "bounds":
            from . import bounds as bounds_mod
            summary, findings = bounds_mod.analyze(
                dims, bounds=bounds, init_states=init_states,
                lane_caps=lane_caps)
        elif name == "lint":
            from . import lint
            summary, findings = lint.analyze(dims, targets=lint_targets,
                                             effect_summary=eff_summary)
        elif name == "por":
            from . import por
            summary, findings = por.analyze(
                dims, bounds=bounds, invariant_names=invariant_names,
                effect_summary=eff_summary)
        else:
            raise ValueError(f"unknown analysis pass {name!r}; "
                             f"registered: {PASSES}")
        report.extend(findings)
        report.summarize_pass(name, summary)
        counts = report.severity_counts(name)
        if metrics is not None:
            metrics.counter("analysis/errors", counts[ERROR])
            metrics.counter("analysis/warnings", counts[WARNING])
        if evlog is not None:
            evlog.emit("analysis", pass_name=name,
                       severity_counts=counts,
                       witness=report.first_witness(name))
    return report
