"""Static model analysis — proofs and lints that never run the state space.

Every action kernel is a pure, statically-shaped JAX function over the
packed ``StateBatch`` encoding, so the *model itself* is analyzable at
trace time.  Four passes share one jaxpr evaluator (``interp.py``) and
one findings/report spine (``report.py``):

- :mod:`.effects` — per-action ELEMENT-WISE (slot/column-granular)
  read/write masks from the kernel jaxprs: the element-granular action
  dependence matrix (which instances provably commute — the fact
  partial-order reduction and BLEST-style tensor-core batching need),
  guard-independence, dead packed lanes, and the versioned footprint
  serialization downstream tooling decodes instead of re-tracing;
- :mod:`.bounds` — interval abstract interpretation of every kernel to
  a reachable-envelope fixpoint: proves each packed lane wide enough
  (or names the witness action that overflows it) and flags int32 wrap,
  turning ``schema.audit_lane_widths``/``check_packable`` from runtime
  guards into trace-time proofs;
- :mod:`.lint` — TPU-throughput hazards in the compiled BFS step /
  fingerprint / FPSet kernels (host callbacks, dynamic shapes,
  non-deterministic reductions, accidental narrowing) plus an AST check
  that the host chunk loop only blocks on device data at sanctioned
  sync points, plus an analyzer-vs-analyzer read-set self-check;
- :mod:`.por` — static partial-order reduction: per-instance ample-set
  certificates proved from the element-wise effects matrices (closure,
  invariant visibility, cycle proviso), packed into the
  device-consumable reduction table ``EngineConfig.por`` applies in
  the expansion stage; closure blocks are classified by a concrete
  non-commutation witness search (machine-checked impossibility vs
  precision worklist).

``run_analysis`` executes the passes and aggregates one
:class:`~.report.Report`; the ``analyze`` CLI subcommand and the CI
gate consume its JSON (README "Static analysis").  Findings feed the
telemetry spine (obs/): an ``analysis`` run event per pass and
``analysis/errors`` / ``analysis/warnings`` counters.
"""

from __future__ import annotations

from typing import List, Optional

from .report import ERROR, INFO, Report, WARNING  # noqa: F401

#: Pass registry, in execution order.
PASSES = ("effects", "bounds", "lint", "por")

#: Inter-pass data dependencies: ``lint``'s read-set self-check and
#: ``por``'s certificates consume the effects pass's live summary.
#: ``resolve_passes`` inserts prerequisites automatically so a user can
#: run ``analyze --passes por`` without spelling out the pipeline.
PASS_DEPS = {"lint": ("effects",), "por": ("effects",)}


def resolve_passes(requested) -> tuple:
    """Close the requested pass list under :data:`PASS_DEPS` and return
    it in registry (topological) order.  Unknown names raise — a typo
    must never produce a silent no-op run."""
    requested = tuple(requested)
    unknown = [p for p in requested if p not in PASSES]
    if unknown or not requested:
        raise ValueError(
            f"unknown analysis pass(es) "
            f"{', '.join(unknown) or '(none given)'}; registered: "
            f"{', '.join(PASSES)}")
    want = set(requested)
    # PASS_DEPS is one level deep today; iterate to a fixpoint anyway so
    # a deeper chain added later cannot silently under-resolve.
    while True:
        more = {d for p in want for d in PASS_DEPS.get(p, ())} - want
        if not more:
            break
        want |= more
    return tuple(p for p in PASSES if p in want)


def run_analysis(dims, bounds=None, init_states=None,
                 passes=PASSES, allowlist: Optional[List[str]] = None,
                 lane_caps=None, lint_targets=None, invariant_names=None,
                 metrics=None, evlog=None) -> Report:
    """Run the requested passes over one model.

    ``bounds`` is the cfg's CONSTRAINT bounds (models/invariants.Bounds),
    ``init_states`` concrete roots to seed the bounds fixpoint and the
    POR closure-refutation probe pool (None or randomized-smoke roots
    fall back to the declared domain envelope / the model's probe
    states), ``lane_caps``/``lint_targets`` are test/fixture overrides
    passed to their passes, ``invariant_names`` the cfg's INVARIANT
    list for the POR visibility condition (None = the conservative full
    registry).  ``passes`` is closed under :data:`PASS_DEPS` — asking
    for ``por`` alone runs ``effects`` first.  ``metrics``
    (MetricsRegistry) and ``evlog`` (RunEventLog) receive the per-pass
    telemetry when given."""
    passes = resolve_passes(passes)
    report = Report(model={"dims": repr(dims),
                           "model_class": type(dims).__name__},
                    allowlist=allowlist)
    # The effects summary is shared downstream: lint's read-set
    # self-check and por's certificates consume the SAME matrices the
    # effects pass serialized (no re-tracing within one invocation).
    eff_summary = None
    for name in passes:
        if name == "effects":
            from . import effects
            eff_summary, findings = effects.analyze(dims)
            summary = effects.summary_json(eff_summary)
        elif name == "bounds":
            from . import bounds as bounds_mod
            summary, findings = bounds_mod.analyze(
                dims, bounds=bounds, init_states=init_states,
                lane_caps=lane_caps)
        elif name == "lint":
            from . import lint
            summary, findings = lint.analyze(dims, targets=lint_targets,
                                             effect_summary=eff_summary)
        elif name == "por":
            from . import por
            summary, findings = por.analyze(
                dims, bounds=bounds, invariant_names=invariant_names,
                effect_summary=eff_summary, init_states=init_states)
        else:
            raise ValueError(f"unknown analysis pass {name!r}; "
                             f"registered: {PASSES}")
        report.extend(findings)
        report.summarize_pass(name, summary)
        counts = report.severity_counts(name)
        if metrics is not None:
            metrics.counter("analysis/errors", counts[ERROR])
            metrics.counter("analysis/warnings", counts[WARNING])
        if evlog is not None:
            evlog.emit("analysis", pass_name=name,
                       severity_counts=counts,
                       witness=report.first_witness(name))
    return report
