"""Effect extraction: per-action read/write sets from the kernel jaxprs.

Each action-family kernel is traced once (``interp.trace_family``) and
re-evaluated per instance under the taint domain with that instance's
concrete parameters.  The result, per action instance:

- ``guard_reads`` — fields the ``enabled`` predicate depends on;
- ``reads``      — fields any non-identity output depends on (guards,
  overflow, and every written field's new value);
- ``writes``     — per written field, the element-wise mask of lanes
  that can differ from the parent state (exact down to the instance's
  own server row where the kernel's index masks are parameter-concrete;
  conservatively whole-field where the write target is state-dependent,
  e.g. ``Receive``'s reply slot).

From these the pass derives the action dependence matrix (instances
whose effects provably commute at this granularity), the provably
independent guard/effect pairs POR-style optimizations need, and the
dead-lane check (state elements no action ever writes).  Everything is
sound w.r.t. the traced kernels: an unhandled primitive degrades to
"may read/write everything it touched" and is reported, never dropped.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, FrozenSet, List, Tuple

import numpy as np

from . import lane_map
from .interp import TaintDomain, Taint, _taint, eval_jaxpr, traced_kernels
from .report import Finding, INFO, WARNING

PASS = "effects"


@dataclasses.dataclass
class InstanceEffect:
    grid_index: int
    family: str
    label: str
    guard_reads: FrozenSet[str]
    reads: FrozenSet[str]
    writes: Dict[str, np.ndarray]       # field -> bool mask (field shape)

    @property
    def write_fields(self) -> FrozenSet[str]:
        return frozenset(self.writes)


@dataclasses.dataclass
class EffectSummary:
    instances: List[InstanceEffect]
    #: family -> {"reads", "writes", "guard_reads"} field-name sets.
    families: Dict[str, Dict[str, FrozenSet[str]]]
    #: [G, G] bool — True where the two instances provably commute at
    #: this granularity (disjoint writes, and neither writes what the
    #: other reads).
    independent: np.ndarray
    #: [G, G] bool — True where neither instance writes a field the
    #: other's GUARD reads (enabledness commutes; the weaker relation
    #: partial-order reduction needs).
    guard_independent: np.ndarray
    #: field -> bool mask of elements written by no action instance.
    dead_lanes: Dict[str, np.ndarray]


def _state_taints(dims) -> List[Taint]:
    shapes = lane_map.field_shapes(dims)
    out = []
    for f in lane_map.FIELDS:
        shp = shapes[f]
        out.append(_taint(frozenset({f}), f, np.zeros(shp, bool),
                          np.zeros(shp, bool), np.zeros(shp, np.int64),
                          np.int32))
    return out


def analyze(dims) -> Tuple[EffectSummary, List[Finding]]:
    """Run effect extraction over the full action-instance grid."""
    kernels = traced_kernels(dims)
    assert tuple(k[0] for k in kernels) == dims.family_names
    findings: List[Finding] = []
    domain = TaintDomain()
    state = _state_taints(dims)
    instances: List[InstanceEffect] = []

    for (name, closed, params), off in zip(kernels, dims.family_offsets):
        grids = np.stack([np.asarray(p) for p in params], axis=-1) \
            if params else np.zeros((1, 0), np.int64)
        for k in range(grids.shape[0]):
            g = off + k
            args = state + [np.int32(v) for v in grids[k]]
            outs = eval_jaxpr(closed, args, domain)
            en, ovf = outs[0], outs[1]
            succ = outs[2:]
            writes: Dict[str, np.ndarray] = {}
            reads = set(en.deps) | set(ovf.deps)
            for f, out in zip(lane_map.FIELDS, succ):
                mask = out.diff if out.origin == f \
                    else np.ones(out.shape, bool)
                if mask.any():
                    writes[f] = mask
                    reads |= out.deps
            instances.append(InstanceEffect(
                grid_index=g, family=name,
                label=dims.describe_instance(g),
                guard_reads=frozenset(en.deps),
                reads=frozenset(reads), writes=writes))

    families: Dict[str, Dict[str, FrozenSet[str]]] = {}
    for inst in instances:
        fam = families.setdefault(
            inst.family, {"reads": frozenset(), "writes": frozenset(),
                          "guard_reads": frozenset()})
        fam["reads"] |= inst.reads
        fam["writes"] |= inst.write_fields
        fam["guard_reads"] |= inst.guard_reads

    independent, guard_independent = _dependence_matrices(instances)
    dead = _dead_lanes(dims, instances)
    for f, mask in dead.items():
        if mask.all():
            findings.append(Finding(
                PASS, WARNING, "dead-field", field=f,
                message=f"state field {f!r} is written by no action "
                        "instance — a dead lane in the packed encoding"))
        elif mask.any():
            findings.append(Finding(
                PASS, INFO, "dead-lanes", field=f,
                message=f"{int(mask.sum())}/{mask.size} elements of "
                        f"field {f!r} are written by no action instance",
                details={"unwritten": int(mask.sum())}))
    for note in domain.notes:
        findings.append(Finding(
            PASS, INFO, "analysis-imprecision",
            message=f"taint analysis fell back to a conservative rule "
                    f"({note}); read/write sets remain sound but may "
                    "over-approximate"))
    return (EffectSummary(instances=instances, families=families,
                          independent=independent,
                          guard_independent=guard_independent,
                          dead_lanes=dead),
            findings)


def _dependence_matrices(instances) -> Tuple[np.ndarray, np.ndarray]:
    G = len(instances)
    indep = np.zeros((G, G), bool)
    gindep = np.zeros((G, G), bool)
    for a in range(G):
        ia = instances[a]
        for b in range(a, G):
            ib = instances[b]
            # Full independence: element-disjoint writes AND neither
            # writes a field the other reads (field granularity for
            # reads — conservative).
            ok = True
            for f, m in ia.writes.items():
                if f in ib.reads:
                    ok = False
                    break
                mb = ib.writes.get(f)
                if mb is not None and bool((m & mb).any()):
                    ok = False
                    break
            if ok:
                for f in ib.writes:
                    if f in ia.reads:
                        ok = False
                        break
            indep[a, b] = indep[b, a] = ok and a != b
            gok = not (ia.write_fields & ib.guard_reads) \
                and not (ib.write_fields & ia.guard_reads)
            gindep[a, b] = gindep[b, a] = gok and a != b
    return indep, gindep


def _dead_lanes(dims, instances) -> Dict[str, np.ndarray]:
    shapes = lane_map.field_shapes(dims)
    written = {f: np.zeros(shapes[f], bool) for f in lane_map.FIELDS}
    for inst in instances:
        for f, m in inst.writes.items():
            written[f] |= m
    return {f: ~w for f, w in written.items()}


def _pack_matrix_hex(mat: np.ndarray) -> List[str]:
    """[G,G] bool -> one hex bitmask string per row (bit h = column h).
    Stable, compact serialization for the analyze report — the POR pass
    and future BLEST-style batching consume this artifact instead of
    re-tracing the kernels."""
    out = []
    for row in np.asarray(mat, bool):
        v = 0
        for h in np.nonzero(row)[0]:
            v |= 1 << int(h)
        out.append(format(v, "x"))
    return out


def _unpack_matrix_hex(rows: List[str], G: int) -> np.ndarray:
    mat = np.zeros((G, G), bool)
    for g, hexrow in enumerate(rows):
        v = int(hexrow, 16)
        while v:
            h = v.bit_length() - 1
            mat[g, h] = True
            v &= ~(1 << h)
    return mat


def matrices_from_json(summary: dict) -> Tuple[np.ndarray, np.ndarray]:
    """(independent, guard_independent) matrices from a serialized
    effects report (``summary_json`` output) — the stable consumer-side
    decoder for POR/BLEST tooling."""
    G = summary["n_instances"]
    return (_unpack_matrix_hex(summary["independent_hex"], G),
            _unpack_matrix_hex(summary["guard_independent_hex"], G))


def summary_json(summary: EffectSummary) -> dict:
    """Compact JSON view: per-family sets, matrix statistics, the
    family-level independent pairs, and the full per-instance dependence
    / guard-independence matrices (hex row bitmasks + instance labels —
    decode with :func:`matrices_from_json`)."""
    fams = {name: {k: sorted(v) for k, v in d.items()}
            for name, d in summary.families.items()}
    G = len(summary.instances)
    pairs = G * (G - 1) // 2
    fam_of = [i.family for i in summary.instances]
    fam_names = sorted({f for f in fam_of})
    fam_indep = []
    for i, fa in enumerate(fam_names):
        for fb in fam_names[i:]:
            idx_a = [k for k, f in enumerate(fam_of) if f == fa]
            idx_b = [k for k, f in enumerate(fam_of) if f == fb]
            sub = summary.independent[np.ix_(idx_a, idx_b)]
            if fa == fb:
                if len(idx_a) > 1 and bool(
                        sub[~np.eye(len(idx_a), dtype=bool)].all()):
                    fam_indep.append([fa, fb])
            elif bool(sub.all()):
                fam_indep.append([fa, fb])
    return {
        "n_instances": G,
        "families": fams,
        "instances": [i.label for i in summary.instances],
        "independent_hex": _pack_matrix_hex(summary.independent),
        "guard_independent_hex": _pack_matrix_hex(
            summary.guard_independent),
        "independent_pairs": int(np.triu(summary.independent, 1).sum()),
        "guard_independent_pairs": int(
            np.triu(summary.guard_independent, 1).sum()),
        "total_pairs": pairs,
        "independent_family_pairs": fam_indep,
        "dead_lane_counts": {f: int(m.sum())
                             for f, m in summary.dead_lanes.items()
                             if m.any()},
    }
