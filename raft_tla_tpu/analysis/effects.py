"""Effect extraction: per-action read/write sets from the kernel jaxprs.

Each action-family kernel is traced once (``interp.trace_family``) and
re-evaluated per instance under the taint domain with that instance's
concrete parameters.  The result, per action instance, is ELEMENT-WISE
(slot/column-granular) since the taint domain tracks per-element
dependency masks:

- ``guard_reads`` — per field, the element mask the ``enabled``
  predicate may depend on;
- ``reads``      — per field, the element mask any non-identity output
  depends on (guards, overflow, and every written field's new value —
  identity pass-through of an unchanged lane is NOT a read);
- ``writes``     — per written field, the element-wise mask of lanes
  that can differ from the parent state (exact down to the instance's
  own server row where the kernel's index masks are parameter-concrete;
  conservatively whole-field where the write target is state-dependent,
  e.g. ``Receive``'s reply-slot allocation scan).

From these the pass derives the action dependence matrix (instances
whose effects provably commute at ELEMENT granularity), the provably
independent guard/effect pairs POR-style optimizations need, and the
dead-lane check (state elements no action ever writes).  Everything is
sound w.r.t. the traced kernels: an unhandled primitive degrades to
"may read/write everything it touched" and is reported, never dropped.

The per-instance footprints are serialized into the analyze report as a
VERSIONED hex encoding (``FOOTPRINTS_VERSION``); POR/BLEST tooling
decodes them with :func:`footprints_from_json` instead of re-tracing.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, FrozenSet, List, Tuple

import numpy as np

from . import lane_map
from .interp import (TaintDomain, Taint, _dunion, _taint, eval_jaxpr,
                     read_mask, traced_kernels)
from .report import Finding, INFO, WARNING

PASS = "effects"

#: Version of the serialized per-instance footprint encoding in
#: ``summary_json`` (bumped when the mask semantics or packing change;
#: consumers reject a mismatch instead of misreading slot masks).
FOOTPRINTS_VERSION = 2

Masks = Dict[str, np.ndarray]           # field -> bool mask (field shape)


@dataclasses.dataclass
class InstanceEffect:
    grid_index: int
    family: str
    label: str
    guard_reads: Masks
    reads: Masks
    writes: Masks

    @property
    def write_fields(self) -> FrozenSet[str]:
        return frozenset(self.writes)

    @property
    def read_fields(self) -> FrozenSet[str]:
        return frozenset(self.reads)

    @property
    def guard_read_fields(self) -> FrozenSet[str]:
        return frozenset(self.guard_reads)


@dataclasses.dataclass
class EffectSummary:
    instances: List[InstanceEffect]
    #: family -> {"reads", "writes", "guard_reads"} field-name sets
    #: (the coarse view; element masks live on the instances).
    families: Dict[str, Dict[str, FrozenSet[str]]]
    #: [G, G] bool — True where the two instances provably commute at
    #: element granularity (element-disjoint writes, and neither writes
    #: an element the other reads).
    independent: np.ndarray
    #: [G, G] bool — True where neither instance writes an element the
    #: other's GUARD reads (enabledness commutes; the weaker relation
    #: partial-order reduction needs).
    guard_independent: np.ndarray
    #: field -> bool mask of elements written by no action instance.
    dead_lanes: Dict[str, np.ndarray]


def _state_taints(dims) -> List[Taint]:
    shapes = lane_map.field_shapes(dims)
    out = []
    for f in lane_map.FIELDS:
        shp = shapes[f]
        out.append(_taint({}, {f: np.ones(shp, bool)}, f,
                          np.zeros(shp, bool), np.zeros(shp, bool),
                          np.zeros(shp, np.int64), np.int32))
    return out


def _write_reads(out: Taint, changed: np.ndarray) -> Masks:
    """Element-wise reads that determine a written field's new value and
    where it lands: the value-level half in full, the positional half
    only at the CHANGED positions — identity pass-through of an
    untouched lane is not a read (the distinction the lint self-check
    draws syntactically)."""
    pos = {}
    for f, m in out.pdeps.items():
        pos[f] = (m & changed) if m.shape == changed.shape else m
    return _dunion(out.vdeps, pos)


def _extract_effect(outs) -> Dict[str, Masks]:
    """(guard_reads, reads, writes) element masks from one kernel
    evaluation's outputs — THE extraction rule, shared by the
    per-instance pass and the Receive case-split so the two can never
    drift apart."""
    en, ovf = outs[0], outs[1]
    writes: Masks = {}
    reads = _dunion(read_mask(en), read_mask(ovf))
    for f, out in zip(lane_map.FIELDS, outs[2:]):
        mask = out.diff if out.origin == f else np.ones(out.shape, bool)
        if mask.any():
            writes[f] = mask
            reads = _dunion(reads, _write_reads(out, mask))
    return {"guard_reads": read_mask(en), "reads": reads,
            "writes": writes}


def _instance_effect(dims, domain, state, closed, name, g, params_row
                     ) -> InstanceEffect:
    args = state + [np.int32(v) for v in params_row]
    eff = _extract_effect(eval_jaxpr(closed, args, domain))
    return InstanceEffect(
        grid_index=g, family=name, label=dims.describe_instance(g),
        guard_reads=eff["guard_reads"], reads=eff["reads"],
        writes=eff["writes"])


def analyze(dims) -> Tuple[EffectSummary, List[Finding]]:
    """Run effect extraction over the full action-instance grid."""
    kernels = traced_kernels(dims)
    assert tuple(k[0] for k in kernels) == dims.family_names
    findings: List[Finding] = []
    domain = TaintDomain()
    state = _state_taints(dims)
    instances: List[InstanceEffect] = []

    for (name, closed, params), off in zip(kernels, dims.family_offsets):
        grids = np.stack([np.asarray(p) for p in params], axis=-1) \
            if params else np.zeros((1, 0), np.int64)
        for k in range(grids.shape[0]):
            instances.append(_instance_effect(
                dims, domain, state, closed, name, off + k, grids[k]))

    families: Dict[str, Dict[str, FrozenSet[str]]] = {}
    for inst in instances:
        fam = families.setdefault(
            inst.family, {"reads": frozenset(), "writes": frozenset(),
                          "guard_reads": frozenset()})
        fam["reads"] |= inst.read_fields
        fam["writes"] |= inst.write_fields
        fam["guard_reads"] |= inst.guard_read_fields

    independent, guard_independent = _dependence_matrices(instances)
    dead = _dead_lanes(dims, instances)
    for f, mask in dead.items():
        if mask.all():
            findings.append(Finding(
                PASS, WARNING, "dead-field", field=f,
                message=f"state field {f!r} is written by no action "
                        "instance — a dead lane in the packed encoding"))
        elif mask.any():
            findings.append(Finding(
                PASS, INFO, "dead-lanes", field=f,
                message=f"{int(mask.sum())}/{mask.size} elements of "
                        f"field {f!r} are written by no action instance",
                details={"unwritten": int(mask.sum())}))
    for note in domain.notes:
        findings.append(Finding(
            PASS, INFO, "analysis-imprecision",
            message=f"taint analysis fell back to a conservative rule "
                    f"({note}); read/write sets remain sound but may "
                    "over-approximate"))
    return (EffectSummary(instances=instances, families=families,
                          independent=independent,
                          guard_independent=guard_independent,
                          dead_lanes=dead),
            findings)


# ---------------------------------------------------------------------------
# Receive case-split (the taint twin of the bounds pass's per-type split)


def receive_case_effects(dims, slot: int = 0) -> Dict[Tuple[int, int, int],
                                                      Dict[str, Masks]]:
    """Per-(mtype, dest ``i``, source ``j``) footprints of ``Receive`` on
    one slot: re-evaluates the traced kernel with the slot's message
    HEADER columns (type / source / dest — ``lane_map.msg_col_name``
    0..2) pinned to the case, the same split ``bounds.py`` applies via
    ``lane_map.msg_type_domains``.  Each case's server-field footprint
    is row-local to its ``i`` (that is the slot-local write mask the POR
    worklist asks for), and the union over cases reproduces the
    instance's conservative whole-field footprint — which is the
    machine-readable explanation of WHY the union cannot shrink: the
    header columns are state, so every (mtype, i, j) case is reachable
    for any slot content."""
    kernels = {name: (closed, params)
               for name, closed, params in traced_kernels(dims)}
    closed, _params = kernels["Receive"]
    n = dims.n_servers
    n_types = len(lane_map.msg_type_domains(dims))
    out: Dict[Tuple[int, int, int], Dict[str, Masks]] = {}
    for t in range(n_types):
        for i in range(n):
            for j in range(n):
                state = _state_taints(dims)
                mi = lane_map.FIELDS.index("msg")
                m = state[mi]
                known = m.known.copy()
                vals = m.vals.copy()
                # Case assumption: the header equals these constants
                # (and still equals the input field — diff stays False).
                for col, v in ((0, t + 1), (1, j + 1), (2, i + 1)):
                    known[slot, col] = True
                    vals[slot, col] = v
                state[mi] = Taint(m.vdeps, m.pdeps, m.origin, m.diff,
                                  known, vals, m.dtype)
                domain = TaintDomain()
                args = state + [np.int32(slot)]
                out[(t, i, j)] = _extract_effect(
                    eval_jaxpr(closed, args, domain))
    return out


# ---------------------------------------------------------------------------
# Dependence matrices


def conflict_elements(ia: InstanceEffect, ib: InstanceEffect
                      ) -> List[Tuple[str, str, np.ndarray]]:
    """The element-level evidence that two instances do NOT commute:
    ``[(kind, field, mask), ...]`` with kind in ``write/write``,
    ``write/read`` (a writes what b reads) and ``read/write``."""
    out: List[Tuple[str, str, np.ndarray]] = []
    for f, m in ia.writes.items():
        mb = ib.writes.get(f)
        if mb is not None and bool((m & mb).any()):
            out.append(("write/write", f, m & mb))
        rb = ib.reads.get(f)
        if rb is not None and bool((m & rb).any()):
            out.append(("write/read", f, m & rb))
    for f, m in ib.writes.items():
        ra = ia.reads.get(f)
        if ra is not None and bool((m & ra).any()):
            out.append(("read/write", f, m & ra))
    return out


def _dependence_matrices(instances) -> Tuple[np.ndarray, np.ndarray]:
    G = len(instances)
    indep = np.zeros((G, G), bool)
    gindep = np.zeros((G, G), bool)

    def _overlap(wa: Masks, rb: Masks) -> bool:
        for f, m in wa.items():
            mb = rb.get(f)
            if mb is not None and bool((m & mb).any()):
                return True
        return False

    for a in range(G):
        ia = instances[a]
        for b in range(a, G):
            ib = instances[b]
            # Full independence at element granularity: element-disjoint
            # writes AND neither writes an element the other reads.
            ok = not (_overlap(ia.writes, ib.writes)
                      or _overlap(ia.writes, ib.reads)
                      or _overlap(ib.writes, ia.reads))
            indep[a, b] = indep[b, a] = ok and a != b
            gok = not (_overlap(ia.writes, ib.guard_reads)
                       or _overlap(ib.writes, ia.guard_reads))
            gindep[a, b] = gindep[b, a] = gok and a != b
    return indep, gindep


def _dead_lanes(dims, instances) -> Dict[str, np.ndarray]:
    shapes = lane_map.field_shapes(dims)
    written = {f: np.zeros(shapes[f], bool) for f in lane_map.FIELDS}
    for inst in instances:
        for f, m in inst.writes.items():
            written[f] |= m
    return {f: ~w for f, w in written.items()}


# ---------------------------------------------------------------------------
# Serialization


def _pack_matrix_hex(mat: np.ndarray) -> List[str]:
    """[G,G] bool -> one hex bitmask string per row (bit h = column h).
    Stable, compact serialization for the analyze report — the POR pass
    and future BLEST-style batching consume this artifact instead of
    re-tracing the kernels."""
    out = []
    for row in np.asarray(mat, bool):
        out.append(_pack_mask_hex(row))
    return out


def _pack_mask_hex(mask: np.ndarray) -> str:
    """Flattened (row-major) bool mask -> hex bitmask (bit k = element
    k of the C-ordered flattening)."""
    v = 0
    for k in np.flatnonzero(np.asarray(mask, bool).reshape(-1)):
        v |= 1 << int(k)
    return format(v, "x")


def _unpack_mask_hex(hexmask: str, shape) -> np.ndarray:
    flat = np.zeros(int(np.prod(shape)) if shape else 1, bool)
    v = int(hexmask, 16)
    while v:
        k = v.bit_length() - 1
        flat[k] = True
        v &= ~(1 << k)
    return flat.reshape(shape)


def _unpack_matrix_hex(rows: List[str], G: int) -> np.ndarray:
    mat = np.zeros((G, G), bool)
    for g, hexrow in enumerate(rows):
        mat[g] = _unpack_mask_hex(hexrow, (G,))
    return mat


def matrices_from_json(summary: dict) -> Tuple[np.ndarray, np.ndarray]:
    """(independent, guard_independent) matrices from a serialized
    effects report (``summary_json`` output) — the stable consumer-side
    decoder for POR/BLEST tooling.  Rejects a report whose footprint
    encoding version is unknown (slot-level masks would be misread)."""
    ver = summary.get("footprints_version")
    if ver is not None and ver != FOOTPRINTS_VERSION:
        raise ValueError(
            f"effects report footprint encoding v{ver} != supported "
            f"v{FOOTPRINTS_VERSION}; regenerate with "
            "`analyze --passes effects`")
    G = summary["n_instances"]
    return (_unpack_matrix_hex(summary["independent_hex"], G),
            _unpack_matrix_hex(summary["guard_independent_hex"], G))


def footprints_from_json(summary: dict) -> List[Dict[str, Masks]]:
    """Per-instance element footprints (reads/writes/guard_reads masks)
    from a serialized effects report.  Requires the versioned slot-level
    encoding (``footprints_version`` >= 2) — a field-granular legacy
    report has no element masks to decode."""
    ver = summary.get("footprints_version")
    if ver != FOOTPRINTS_VERSION:
        raise ValueError(
            f"effects report carries footprint encoding v{ver}, need "
            f"v{FOOTPRINTS_VERSION} (slot-level masks); regenerate with "
            "`analyze --passes effects`")
    shapes = {f: tuple(s) for f, s in summary["field_shapes"].items()}
    out: List[Dict[str, Masks]] = []
    for fp in summary["footprints"]:
        out.append({kind: {f: _unpack_mask_hex(h, shapes[f])
                           for f, h in fp[kind].items()}
                    for kind in ("reads", "writes", "guard_reads")})
    return out


def summary_json(summary: EffectSummary) -> dict:
    """Compact JSON view: per-family sets, matrix statistics, the
    family-level independent pairs, the full per-instance dependence /
    guard-independence matrices (hex row bitmasks + instance labels —
    decode with :func:`matrices_from_json`) and the versioned
    per-instance element footprints (:func:`footprints_from_json`)."""
    fams = {name: {k: sorted(v) for k, v in d.items()}
            for name, d in summary.families.items()}
    G = len(summary.instances)
    pairs = G * (G - 1) // 2
    fam_of = [i.family for i in summary.instances]
    fam_names = sorted({f for f in fam_of})
    fam_indep = []
    for i, fa in enumerate(fam_names):
        for fb in fam_names[i:]:
            idx_a = [k for k, f in enumerate(fam_of) if f == fa]
            idx_b = [k for k, f in enumerate(fam_of) if f == fb]
            sub = summary.independent[np.ix_(idx_a, idx_b)]
            if fa == fb:
                if len(idx_a) > 1 and bool(
                        sub[~np.eye(len(idx_a), dtype=bool)].all()):
                    fam_indep.append([fa, fb])
            elif bool(sub.all()):
                fam_indep.append([fa, fb])
    shapes = {}
    for inst in summary.instances:
        for masks in (inst.reads, inst.writes, inst.guard_reads):
            for f, m in masks.items():
                shapes[f] = list(m.shape)
    return {
        "n_instances": G,
        "families": fams,
        "instances": [i.label for i in summary.instances],
        "independent_hex": _pack_matrix_hex(summary.independent),
        "guard_independent_hex": _pack_matrix_hex(
            summary.guard_independent),
        "independent_pairs": int(np.triu(summary.independent, 1).sum()),
        "guard_independent_pairs": int(
            np.triu(summary.guard_independent, 1).sum()),
        "total_pairs": pairs,
        "independent_family_pairs": fam_indep,
        "footprints_version": FOOTPRINTS_VERSION,
        "field_shapes": shapes,
        "footprints": [
            {"reads": {f: _pack_mask_hex(m)
                       for f, m in inst.reads.items()},
             "writes": {f: _pack_mask_hex(m)
                        for f, m in inst.writes.items()},
             "guard_reads": {f: _pack_mask_hex(m)
                             for f, m in inst.guard_reads.items()}}
            for inst in summary.instances],
        "dead_lane_counts": {f: int(m.sum())
                             for f, m in summary.dead_lanes.items()
                             if m.any()},
    }
