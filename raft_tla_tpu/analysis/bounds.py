"""Interval bound analysis: trace-time proofs of packed-lane safety.

``schema.audit_lane_widths`` audits *declared* domain maxima at
construction and ``schema.build_pack_guard`` aborts a run when a
runtime-growing value outgrows its lane.  This pass closes the gap
between the two: it abstract-interprets every action kernel's jaxpr
over element-wise integer intervals (``interp.IntervalDomain``),
iterates reachable per-field intervals to a fixpoint under the model's
constraints, and checks every action's *successor* intervals against
the packed-lane capacities (``lane_map.lane_capacities``) — so a lane
that can overflow is reported at ``analyze`` time with a named witness
action instead of at depth 40 of a TPU run.

Method notes (all surfaced in the report, never silently assumed):

- The abstract state is reduced by the model's server/slot symmetry
  (one interval per field element class: per message column, per log
  lane, scalar for server-indexed fields) and only representative
  instances are evaluated — sound because the kernels are equivariant
  under server/slot permutation and the reduced state is permutation-
  invariant by construction.
- ``Receive`` is case-split on the received message's type using the
  declared per-type payload domains (``lane_map.msg_type_domains``):
  payload columns are unions (mmatchIndex shares column 5 with
  mprevLogTerm), and without the split a term bound smears into index
  arithmetic and nothing converges.
- Fields whose interval has not converged after ``watch_rounds``
  (unbounded growth like ``term``; the nextIndex/mmatchIndex exchange
  cycle, which provably has no finite non-relational invariant) are
  widened to the declared domain envelope (``lane_map.field_domains``)
  and reported; a field whose one-step image then still escapes the
  envelope yields an INFO "not inductive" note rather than a silent
  clamp.
- Severity: a lane overflow is an ERROR when it is silent-corruption
  class (no runtime guard) or when the cfg's own CONSTRAINT bounds
  admit it (e.g. ``MaxTerm = 300`` — every run would hard-stop on the
  pack guard); unbounded pack-guarded growth without a constraint is a
  WARNING (the runtime guard turns it into a clean abort).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

import numpy as np

from . import lane_map
from .interp import Interval, IntervalDomain, _ival, eval_jaxpr, traced_kernels
from .report import ERROR, Finding, INFO, WARNING

PASS = "bounds"
_I64 = np.int64

#: Reduced abstract-state shapes: intervals per symmetry class.
_REDUCED_AXES = {  # field -> axes of the full shape joined away
    "term": (0,), "role": (0,), "voted_for": (0,), "log_len": (0,),
    "commit": (0,), "votes_resp": (0,), "votes_gran": (0,),
    "log_term": (0,), "log_val": (0,),
    "next_idx": (0, 1), "match_idx": (0, 1),
    "msg": (0,), "msg_cnt": (0,),
}


@dataclasses.dataclass
class BoundsResult:
    intervals: Dict[str, Tuple[np.ndarray, np.ndarray]]   # reduced (lo, hi)
    rounds: int
    widened: List[str]
    converged: bool


def _reduce(field: str, lo: np.ndarray, hi: np.ndarray):
    ax = _REDUCED_AXES[field]
    return lo.min(axis=ax), hi.max(axis=ax)


def _reduced_shape(field: str, dims) -> tuple:
    shp = lane_map.field_shapes(dims)[field]
    ax = _REDUCED_AXES[field]
    return tuple(d for i, d in enumerate(shp) if i not in ax)


def _expand_field(field: str, lo, hi, shapes):
    shp = shapes[field]
    if field in ("msg",):
        return (np.broadcast_to(lo[None, :], shp),
                np.broadcast_to(hi[None, :], shp))
    if field in ("log_term", "log_val"):
        return (np.broadcast_to(lo[None, :], shp),
                np.broadcast_to(hi[None, :], shp))
    return np.broadcast_to(lo, shp), np.broadcast_to(hi, shp)


def _join(a, b):
    return np.minimum(a[0], b[0]), np.maximum(a[1], b[1])


def _clamp(lo, hi, c_lo, c_hi):
    """Intersect an interval with a clamp window, keeping it non-empty
    (an empty intersection collapses to the nearer clamp bound — the
    conservative direction for a reachability envelope)."""
    lo2 = np.clip(lo, c_lo, c_hi)
    hi2 = np.clip(hi, c_lo, c_hi)
    return np.minimum(lo2, hi2), np.maximum(lo2, hi2)


def _seed_state(dims, init_states) -> Dict[str, Tuple]:
    """Reduced intervals joining the (concrete) initial states; falls
    back to the declared domain envelope when roots are unavailable or
    randomized (smoke configs)."""
    from ..models.schema import encode_state
    if not init_states:
        dom = lane_map.field_domains(dims)
        return {f: (np.broadcast_to(np.asarray(dom[f][0], _I64),
                                    _reduced_shape(f, dims)).copy(),
                    np.broadcast_to(np.asarray(dom[f][1], _I64),
                                    _reduced_shape(f, dims)).copy())
                for f in lane_map.FIELDS}
    state = None
    for s in init_states:
        enc = encode_state(s, dims)
        red = {}
        for f in lane_map.FIELDS:
            arr = np.asarray(getattr(enc, f), _I64)
            red[f] = _reduce(f, arr, arr)
        state = red if state is None else {
            f: _join(state[f], red[f]) for f in lane_map.FIELDS}
    return state


def _rep_instances(dims, max_extra: int = 16):
    """Representative (family, k) instances: one per symmetry class of
    the base grid (plus all-v for ClientRequest and both i==j / i!=j
    for the (i,j) families); every instance of variant extras, capped."""
    n, v = dims.n_servers, dims.n_values
    reps: List[Tuple[int, int]] = []
    truncated = []
    for fi, name in enumerate(dims.family_names):
        size = dims.family_sizes[fi]
        if fi in (2, 6):                    # RequestVote / AppendEntries
            ks = [0, 1] if n > 1 else [0]   # (i=0,j=0) and (i=0,j=1)
        elif fi == 4:                       # ClientRequest: all values
            ks = list(range(min(v, size)))
        elif fi < 10:                       # other base families
            ks = [0]
        else:                               # variant extras
            ks = list(range(min(size, max_extra)))
            if size > max_extra:
                truncated.append(name)
        reps.extend((fi, k) for k in ks)
    return reps, truncated


def _param_values(params, k: int) -> List[np.ndarray]:
    return [np.asarray(p)[k].astype(np.int64) for p in params]


def analyze(dims, bounds=None, init_states=None,
            lane_caps: Optional[Dict] = None,
            max_rounds: int = 64, watch_rounds: int = 12
            ) -> Tuple[dict, List[Finding]]:
    """Run the fixpoint and the lane checks.  ``lane_caps`` overrides
    ``lane_map.lane_capacities(dims)`` (tests shrink a lane with it).
    Returns (summary dict, findings)."""
    kernels = traced_kernels(dims)
    findings: List[Finding] = []
    caps = dict(lane_map.lane_capacities(dims))
    if lane_caps:
        # Scalar overrides broadcast to the reference capacity's shape —
        # 'msg' capacities are per-column [W] arrays and _check_lane
        # indexes them by column, so a bare (0, HI) must fan out to W.
        for f, (olo, ohi) in lane_caps.items():
            ref_lo, ref_hi = caps[f]
            caps[f] = (np.broadcast_to(np.asarray(olo, _I64),
                                       np.shape(ref_lo)),
                       np.broadcast_to(np.asarray(ohi, _I64),
                                       np.shape(ref_hi)))
    shapes = lane_map.field_shapes(dims)
    domain = IntervalDomain()
    dom_env = lane_map.field_domains(dims)
    cons = lane_map.constraint_bounds(dims, bounds)
    type_doms = lane_map.msg_type_domains(dims)

    jaxprs = {}
    for (name, closed, params), off in zip(kernels, dims.family_offsets):
        jaxprs[name] = (closed, params, off)
    reps, truncated = _rep_instances(dims)
    for name in truncated:
        findings.append(Finding(
            PASS, INFO, "instances-truncated",
            message=f"variant family {name!r} analyzed on the first 16 "
                    "instances only"))

    state = _seed_state(dims, init_states)
    widened: List[str] = []

    def input_intervals(st) -> Dict[str, Tuple]:
        out = {}
        for f in lane_map.FIELDS:
            lo, hi = st[f]
            if f in widened:
                lo, hi = _clamp(lo, hi, *dom_env[f])
            if f in cons:
                lo, hi = _clamp(lo, hi, *cons[f])
            out[f] = (lo, hi)
        return out

    def eval_rep(fi, k, inp, msg_override=None):
        """Evaluate one representative instance on reduced input
        intervals; returns (enabled, succ field intervals) or None."""
        name = dims.family_names[fi]
        closed, params, _off = jaxprs[name]
        args = []
        for f in lane_map.FIELDS:
            lo, hi = _expand_field(f, *inp[f], shapes)
            if f == "msg" and msg_override is not None:
                lo = np.array(lo)
                hi = np.array(hi)
                lo[0], hi[0] = msg_override
            args.append(_ival(lo, hi, np.int32))
        args += [_ival(p, p, np.int32) for p in _param_values(params, k)]
        outs = eval_jaxpr(closed, args, domain)
        en = outs[0]
        if int(en.hi.max()) == 0:
            return None                      # provably disabled
        return en, outs[2:]

    def successors(inp):
        """All (label, {field: (lo, hi) reduced}) for the reps, with the
        Receive type split applied."""
        out = []
        for fi, k in reps:
            name = dims.family_names[fi]
            off = jaxprs[name][2]
            label = dims.describe_instance(off + k)
            if name == "Receive":
                m_lo, m_hi = inp["msg"]
                for t, (t_lo, t_hi) in enumerate(type_doms):
                    if m_hi[0] < t + 1 or m_lo[0] > t + 1:
                        continue             # no such message in flight
                    row = _clamp(m_lo, m_hi, t_lo, t_hi)
                    r = eval_rep(fi, k, inp, msg_override=row)
                    if r is not None:
                        out.append((f"{label}[mtype={t}]", r[1]))
            else:
                r = eval_rep(fi, k, inp)
                if r is not None:
                    out.append((label, r[1]))
        return out

    rounds = 0
    converged = False
    while rounds < max_rounds:
        rounds += 1
        inp = input_intervals(state)
        new_state = {f: (state[f][0].copy(), state[f][1].copy())
                     for f in lane_map.FIELDS}
        for _label, succ in successors(inp):
            for f, val in zip(lane_map.FIELDS, succ):
                red = _reduce(f, val.lo, val.hi)
                new_state[f] = _join(new_state[f], red)
        # A widened field's STATE jumps straight to the declared envelope
        # (classic widening-to-top over the declared domain): clamping
        # alone would let a +1-per-round lane (term) crawl toward 255 one
        # fixpoint round at a time and never converge.  One-step escapes
        # beyond the envelope are surfaced by the not-inductive check in
        # the final round, never silently swallowed.
        for f in widened:
            shp = _reduced_shape(f, dims)
            new_state[f] = (
                np.broadcast_to(np.asarray(dom_env[f][0], _I64), shp).copy(),
                np.broadcast_to(np.asarray(dom_env[f][1], _I64), shp).copy())
        changed = [f for f in lane_map.FIELDS
                   if not (np.array_equal(new_state[f][0], state[f][0])
                           and np.array_equal(new_state[f][1],
                                              state[f][1]))]
        state = new_state
        if not changed:
            converged = True
            break
        if rounds >= watch_rounds:
            for f in changed:
                if f not in widened:
                    widened.append(f)

    for f in sorted(widened):
        findings.append(Finding(
            PASS, INFO, "widened", field=f,
            message=f"interval for field {f!r} did not converge in "
                    f"{watch_rounds} rounds; widened to the declared "
                    f"domain envelope {_env_str(dom_env[f])}"))
    if not converged:
        findings.append(Finding(
            PASS, ERROR, "no-fixpoint",
            message=f"interval fixpoint not reached in {max_rounds} "
                    "rounds even after widening — analysis defect, "
                    "bounds unproven"))

    # -- final check round: every rep's successor vs lane capacity ----
    # The check asks the operative question: starting from any state that
    # FITS the packed lanes (input intersected with the capacities), which
    # action's one-step image escapes them?  That names the *raising*
    # action as the witness (Timeout for a shrunken term lane, not
    # whichever family happens to come first carrying an already-
    # overflowed parent value).  Per-lane policy:
    #
    # - GROWTH lanes (term/log_term/msg_cnt and the term-carrying message
    #   columns) keep their raw HIGH side — growth past the lane is the
    #   finding, graded WARNING/ERROR by _check_lane's guard/cfg logic;
    # - every other lane's image is intersected with the declared domain
    #   envelope: a one-step escape there is guard imprecision the
    #   interval domain cannot resolve, reported as a not-inductive INFO
    #   (so a wrong field_domains entry is surfaced, never trusted
    #   silently), while an envelope that itself exceeds the lane still
    #   flags as the real overflow it is;
    # - LOW sides are floored at the envelope on all lanes: the packed
    #   fields are unsigned (column 4 excepted, its envelope says so) and
    #   negative lows only arise from guarded-decrement imprecision.
    inp = input_intervals(state)
    chk_inp = {}
    for f in lane_map.FIELDS:
        c_lo, c_hi = caps[f]
        chk_inp[f] = _clamp(*inp[f], np.asarray(c_lo, _I64),
                            np.asarray(c_hi, _I64))
    W = dims.msg_width
    msg_growth = np.array([_growth_guarded("msg", c, dims)
                           for c in range(W)])
    reported = set()
    for label, succ in successors(chk_inp):
        for f, val in zip(lane_map.FIELDS, succ):
            red_lo, red_hi = _reduce(f, val.lo, val.hi)
            e_lo = np.asarray(dom_env[f][0], _I64)
            e_hi = np.asarray(dom_env[f][1], _I64)
            if (bool(np.any(red_lo < e_lo)) or bool(np.any(red_hi > e_hi))) \
                    and ("noninductive", f) not in reported:
                reported.add(("noninductive", f))
                findings.append(Finding(
                    PASS, INFO, "not-inductive", field=f,
                    witness=label,
                    message=f"one action step escapes the declared "
                            f"domain envelope of {f!r} "
                            f"({_env_str(dom_env[f])} -> "
                            f"{_env_str((red_lo, red_hi))}); excess "
                            "is within the packed lane, bounded by "
                            "guards the interval domain cannot see"))
            chk_lo = np.maximum(red_lo, np.broadcast_to(e_lo, red_lo.shape))
            if f == "msg":
                chk_hi = np.where(msg_growth, red_hi,
                                  np.minimum(red_hi, e_hi))
            elif _growth_guarded(f, None, dims):
                chk_hi = red_hi
            else:
                chk_hi = np.minimum(red_hi, np.broadcast_to(
                    e_hi, red_hi.shape))
            chk_lo = np.minimum(chk_lo, chk_hi)   # keep non-empty
            _check_lane(dims, bounds, f, chk_lo, chk_hi, caps, label,
                        reported, findings)
    for prim in sorted(set(domain.wraps)):
        findings.append(Finding(
            PASS, ERROR, "int32-wrap",
            message=f"kernel arithmetic ({prim}) can exceed the traced "
                    "integer dtype's range — silent wraparound on "
                    "device"))
    for note in sorted(set(domain.notes)):
        findings.append(Finding(
            PASS, INFO, "analysis-imprecision",
            message=f"interval analysis fell back to a conservative "
                    f"rule ({note})"))

    summary = {
        "rounds": rounds, "converged": converged,
        "widened": sorted(widened),
        "intervals": {f: _env_str(state[f]) for f in lane_map.FIELDS},
        "constraints": {f: _env_str(c) for f, c in cons.items()},
    }
    return summary, findings


def _env_str(pair) -> str:
    lo, hi = (np.asarray(pair[0], _I64), np.asarray(pair[1], _I64))
    if lo.ndim == 0 or lo.size == 1:
        return f"[{int(lo.min())}, {int(hi.max())}]"
    return (f"[{int(lo.min())}, {int(hi.max())}] "
            f"(per-lane hi: {hi.tolist()})")


def _guard_bound(field: str, col: Optional[int], dims) -> Optional[int]:
    """The runtime pack guard's bound for this growth lane (the value
    ``schema.build_pack_guard`` hard-aborts past), or None when the lane
    has no growth guard.  Per build_pack_guard (term/msg_cnt/mterm at
    255, the sign-extended column 4 at 127) plus the audit docstring's
    sender-mterm argument for the term-carrying payload columns."""
    if field in ("term", "log_term", "msg_cnt"):
        return 255
    if field == "msg" and col is not None:
        L = dims.max_log
        if col == 4:
            return 127
        if col in (3, 5) or 6 <= col < 6 + L:
            return 255
    return None


def _growth_guarded(field: str, col: Optional[int], dims) -> bool:
    return _guard_bound(field, col, dims) is not None


def _bounded_by_cfg(field: str, col: Optional[int], bounds) -> bool:
    """Does a cfg CONSTRAINT bound this lane's driving quantity?  If so,
    an overflow is reachable inside the *intended* state space."""
    if bounds is None:
        return False
    if field in ("term", "log_term", "msg"):   # term-carrying lanes
        return bounds.max_term is not None
    if field == "msg_cnt":
        return bounds.max_msg_count is not None
    return False


def _check_lane(dims, bounds, field, lo, hi, caps, label, reported,
                findings) -> None:
    cap_lo, cap_hi = caps[field]
    cap_lo = np.asarray(cap_lo, _I64)
    cap_hi = np.asarray(cap_hi, _I64)
    over = (lo < cap_lo) | (hi > cap_hi)
    if not bool(np.any(over)):
        return
    if field == "msg":                      # reduced to per-column [W]
        for col in np.flatnonzero(over):
            col = int(col)
            key = (field, col)
            if key in reported:
                continue
            reported.add(key)
            # The runtime pack guard covers the lane only when the lane
            # really holds the guard's bound — a narrower lane overflows
            # BELOW the guard's trigger, silently.
            gb = _guard_bound(field, col, dims)
            guarded = gb is not None and int(cap_hi[col]) >= gb
            sev = ERROR if (not guarded
                            or _bounded_by_cfg(field, col, bounds)) \
                else WARNING
            findings.append(Finding(
                PASS, sev, "lane-overflow", field=f"msg[{col}]",
                witness=label,
                message=f"message column {col} "
                        f"({lane_map.msg_col_name(col, dims)}) can reach "
                        f"[{int(lo[col])}, {int(hi[col])}] but its "
                        f"packed lane holds [{int(cap_lo[col])}, "
                        f"{int(cap_hi[col])}]"
                        + ("" if sev == ERROR else
                           " (runtime pack guard aborts, no aliasing)")))
        return
    key = (field, None)
    if key in reported:
        return
    reported.add(key)
    gb = _guard_bound(field, None, dims)
    guarded = gb is not None and int(cap_hi.max()) >= gb
    sev = ERROR if (not guarded or _bounded_by_cfg(field, None, bounds)) \
        else WARNING
    findings.append(Finding(
        PASS, sev, "lane-overflow", field=field, witness=label,
        message=f"field {field!r} can reach [{int(lo.min())}, "
                f"{int(hi.max())}] but its packed lane holds "
                f"[{int(cap_lo.min())}, {int(cap_hi.max())}]"
        + ("" if sev == ERROR else
           " (runtime pack guard aborts, no aliasing)")))
