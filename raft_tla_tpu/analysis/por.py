"""Static partial-order reduction: ample-set certificates from the
dependence matrices.

The effects pass (``effects.py``) already proves, per action instance,
element-wise write masks and field-level read sets, and folds them into
the action dependence matrix.  This pass consumes those matrices and
asks, for every instance ``g``: *is the singleton ``{g}`` a valid ample
set at every state where ``g`` is enabled?*  If yes, the engine may
expand ONLY ``g`` from such a state and provably misses no invariant
verdict.  Four side conditions, each proved statically or the instance
is conservatively widened to "never ample" (a WARNING, never a silent
claim — the ``bounds.py`` contract):

- **C0 non-emptiness** — structural: the engine applies the reduction
  only at states whose enabled set contains a certified instance, so
  the chosen ample set is never empty.
- **C1 closure (stubbornness)** — ``g`` must be independent of EVERY
  other instance (``effects.independent`` row complete off-diagonal).
  Independence there means element-disjoint writes and neither touches
  what the other reads, so ``{g}`` is a persistent set wherever ``g``
  is enabled: no action executable before ``g`` — now or after any
  deferred sequence — conflicts with it, and nothing can disable it.
  Anything weaker is unsound: a dependent action that is merely
  *disabled right now* can become enabled along a deferred path and
  observe ``g``'s writes (see tests for the concrete counterexample
  family), so no enabled-set-only refinement is offered.
- **C2 invariant visibility** — ``g``'s written fields must be disjoint
  from the read set of every checked predicate: the configured
  INVARIANTs (models/invariants.py TypeOK + the models/safety.py suite
  by default) AND the cfg CONSTRAINT (constraint reads gate expansion).
  Read sets are traced through the same jaxpr taint interpreter as the
  effects pass, so a predicate's footprint can never silently drift
  from its kernel.  Without this condition a pruned sibling state could
  carry the only violating valuation.
- **C3 cycle proviso** — ``g`` must be provably *self-disabling*: the
  kernel's guard, re-evaluated under the interval domain on ``g``'s own
  successor envelope, must be must-false.  Together with C1 (no other
  instance writes ``g``'s guard reads) this kills the ignoring problem:
  an ample-only path can execute each certified instance at most once,
  so no cycle of the reduced graph consists solely of ample steps, and
  a certified instance can never produce a pruning self-loop (if
  ``s·g = s`` then ``g`` would still be enabled at ``s·g``,
  contradicting the proof).

On the base Raft alphabet this is an honest negative result: every
instance fails C1 because ``Receive``'s reply-slot allocation scans the
whole message bag (conservative whole-field ``msg``/server-field
writes), making it statically dependent on every other family — the
pass reports exactly which conditions block each family instead of
claiming a reduction it cannot prove.  The machinery (certificates,
packed device table, engine masking, coverage accounting) is exercised
end-to-end by the oracle differentials in ``tests/test_por.py``; finer
read/write granularity can flip families to certified without touching
the engine.

The emitted :class:`PorTable` is the device-consumable artifact: a
per-instance ``ample_mask`` + ``priority`` order packed for the engines
(``EngineConfig.por`` / ``por_table``), serialized into the ``analyze
--json`` report and an optional versioned artifact file.  The table is
fingerprinted over its full payload; the engine re-verifies fingerprint,
model signature, and predicate coverage before applying a mask, so a
hand-edited certificate is rejected, never silently trusted.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
from typing import Dict, FrozenSet, List, Optional, Tuple

import numpy as np

from . import lane_map
from .interp import (IntervalDomain, TaintDomain, _ival, eval_jaxpr,
                     traced_kernels)
from .report import ERROR, Finding, INFO, WARNING

PASS = "por"
TABLE_VERSION = 1

#: C1/C2/C3 condition names, report order.
CONDITIONS = ("nonempty", "closure", "visibility", "proviso")


# ---------------------------------------------------------------------------
# Predicate read sets (invariant-visibility inputs)


def trace_predicate(kernel, dims):
    """Trace one state predicate ``kernel(StateBatch) -> bool`` to a
    ClosedJaxpr over the 13 abstract state fields (lane_map.FIELDS
    order) — the invariant-side twin of ``interp.trace_family``."""
    import jax
    import jax.numpy as jnp

    from ..models.schema import StateBatch

    shapes = lane_map.field_shapes(dims)

    def flat(*fields):
        return kernel(StateBatch(*fields))

    in_avals = [jax.ShapeDtypeStruct(shapes[f], jnp.int32)
                for f in lane_map.FIELDS]
    return jax.make_jaxpr(flat)(*in_avals)


def predicate_read_sets(dims, predicates) -> Tuple[Dict[str, FrozenSet[str]],
                                                   List[str]]:
    """``{name: fields the predicate may read}`` for ``[(name, kernel)]``,
    via the taint domain (sound: a dropped dependency would be an interp
    bug — the lint pass's read-set self-check guards the same property
    on the action kernels).  Also returns the domain's imprecision
    notes."""
    from .effects import _state_taints
    domain = TaintDomain()
    state = _state_taints(dims)
    out: Dict[str, FrozenSet[str]] = {}
    for name, kernel in predicates:
        closed = trace_predicate(kernel, dims)
        res = eval_jaxpr(closed, list(state), domain)
        out[name] = frozenset(res[0].deps)
    return out, list(domain.notes)


# ---------------------------------------------------------------------------
# C3: self-disabling proof (interval domain)


def _envelope_intervals(dims, bounds=None):
    """Declared per-field domains (lane_map.field_domains — the same
    widening envelope the bounds pass uses), intersected with the cfg's
    CONSTRAINT clamps, as interval-domain state values."""
    domains = lane_map.field_domains(dims)
    clamps = lane_map.constraint_bounds(dims, bounds)
    shapes = lane_map.field_shapes(dims)
    out = []
    for f in lane_map.FIELDS:
        lo, hi = domains[f]
        lo = np.broadcast_to(np.asarray(lo, np.int64), shapes[f])
        hi = np.broadcast_to(np.asarray(hi, np.int64), shapes[f])
        if f in clamps:
            clo, chi = clamps[f]
            lo = np.maximum(lo, clo)
            hi = np.minimum(hi, chi)
        out.append(_ival(lo, hi, np.int32))
    return out


def self_disabling(closed, params, env_state) -> Tuple[bool, List[str]]:
    """Prove the instance's guard false on its own successors.

    Evaluates the family jaxpr once on the reachable envelope (successor
    intervals over-approximate every ``g``-successor of every state in
    the envelope), then re-evaluates the same jaxpr on those successor
    intervals and requires the ``enabled`` output to be must-false.
    Conservative both ways: an imprecision widens the guard toward
    "maybe enabled" and the proof simply fails."""
    domain = IntervalDomain()
    pvals = [np.int32(v) for v in params]
    outs = eval_jaxpr(closed, list(env_state) + pvals, domain)
    succ = outs[2:]
    outs2 = eval_jaxpr(closed, list(succ) + pvals, domain)
    en2 = outs2[0]
    proved = bool(np.all(np.asarray(en2.hi) == 0))
    return proved, list(domain.notes)


# ---------------------------------------------------------------------------
# Certificates and the packed table


@dataclasses.dataclass
class Certificate:
    """Per-instance ample-set certificate: condition -> (proved, why)."""

    grid_index: int
    family: str
    label: str
    conditions: Dict[str, Tuple[bool, str]]

    @property
    def ample(self) -> bool:
        return all(ok for ok, _why in self.conditions.values())

    def blocking(self) -> List[str]:
        return [c for c in CONDITIONS if not self.conditions[c][0]]


@dataclasses.dataclass
class PorTable:
    """The device-consumable reduction table (versioned artifact).

    ``ample_mask[g]`` — instance ``g`` is a certified singleton ample
    set wherever enabled; ``priority[g]`` — selection order when several
    certified instances are enabled in one state (lowest value wins;
    grid order by default, reorderable by future cost models without a
    schema change).  ``predicates`` names every state predicate the
    visibility condition was proved against — a run checking anything
    outside this list must reject the table.  ``fingerprint`` is a
    sha256 over the canonical payload: a hand-edited mask no longer
    matches and is rejected at load (tests plant exactly that)."""

    model: str
    n_instances: int
    ample_mask: np.ndarray          # [G] bool
    priority: np.ndarray            # [G] int32
    predicates: Tuple[str, ...]
    version: int = TABLE_VERSION

    def __post_init__(self):
        self.ample_mask = np.asarray(self.ample_mask, bool)
        self.priority = np.asarray(self.priority, np.int32)
        if self.ample_mask.shape != (self.n_instances,) \
                or self.priority.shape != (self.n_instances,):
            raise ValueError("table arrays must be [n_instances]")

    @property
    def certified(self) -> int:
        return int(self.ample_mask.sum())

    def payload(self) -> dict:
        return {"version": self.version, "model": self.model,
                "n_instances": self.n_instances,
                "predicates": sorted(self.predicates),
                "ample_mask": [int(b) for b in self.ample_mask],
                "priority": [int(p) for p in self.priority]}

    @property
    def fingerprint(self) -> str:
        blob = json.dumps(self.payload(), sort_keys=True,
                          separators=(",", ":")).encode()
        return hashlib.sha256(blob).hexdigest()

    def to_json(self) -> dict:
        out = self.payload()
        out["fingerprint"] = self.fingerprint
        return out

    @classmethod
    def from_json(cls, d: dict) -> "PorTable":
        if d.get("version") != TABLE_VERSION:
            raise ValueError(
                f"POR table version {d.get('version')!r} != supported "
                f"{TABLE_VERSION}; regenerate with `analyze --passes por`")
        table = cls(model=d["model"], n_instances=int(d["n_instances"]),
                    ample_mask=np.asarray(d["ample_mask"], bool),
                    priority=np.asarray(d["priority"], np.int32),
                    predicates=tuple(d["predicates"]))
        want = d.get("fingerprint")
        if want != table.fingerprint:
            raise ValueError(
                "POR table fingerprint mismatch (edited by hand, or "
                "truncated): the certificate no longer matches its "
                "payload; regenerate with `analyze --passes por "
                "--por-artifact FILE`")
        return table

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(self.to_json(), f, indent=2, sort_keys=True)
            f.write("\n")


def load_table(path: str) -> PorTable:
    with open(path) as f:
        return PorTable.from_json(json.load(f))


def check_table(table: PorTable, dims, invariant_names=None,
                has_constraint: bool = False) -> None:
    """Engine-side admission check: model signature, instance count, and
    predicate coverage.  Raises ValueError on any mismatch — a reduction
    certified for a different model (or for fewer predicates than the
    run checks) must never be applied."""
    if table.model != repr(dims):
        raise ValueError(
            f"POR table was certified for model {table.model!r}, "
            f"engine runs {repr(dims)!r}")
    if table.n_instances != dims.n_instances:
        raise ValueError(
            f"POR table covers {table.n_instances} action instances, "
            f"model has {dims.n_instances}")
    missing = sorted(set(invariant_names or []) - set(table.predicates))
    if missing:
        raise ValueError(
            f"POR table visibility was not proved against checked "
            f"invariant(s) {missing}; certified predicates: "
            f"{sorted(table.predicates)}")
    if has_constraint:
        # Strict like the invariant check above (even for an all-
        # conservative mask): a certificate applied outside the
        # predicate set it was proved under is a config error worth
        # surfacing before it matters.
        from ..models.invariants import CONSTRAINT_PREDICATE
        if CONSTRAINT_PREDICATE not in table.predicates:
            raise ValueError(
                "POR table was certified without a CONSTRAINT predicate "
                "but the run applies one; constraint reads gate "
                "expansion and must be part of the visibility condition")


# ---------------------------------------------------------------------------
# The pass


def _build_certificates(dims, summary, read_sets, bounds):
    """One :class:`Certificate` per action instance."""
    instances = summary.instances
    G = len(instances)
    indep = summary.independent
    pred_reads: FrozenSet[str] = frozenset().union(*read_sets.values()) \
        if read_sets else frozenset()
    env = _envelope_intervals(dims, bounds)
    kernels = {name: (closed, params)
               for name, closed, params in traced_kernels(dims)}
    # Per-(family, param row) proviso proofs — instances of one family
    # share a jaxpr, so memoize on the concrete parameter tuple.
    proviso_cache: Dict[Tuple, Tuple[bool, List[str]]] = {}

    certs: List[Certificate] = []
    for g, inst in enumerate(instances):
        conds: Dict[str, Tuple[bool, str]] = {}
        # C0: the engine masks only states where this instance is
        # enabled, so the chosen ample set is non-empty by construction.
        conds["nonempty"] = (True, "ample applied only where enabled")

        dep_fams = sorted({instances[h].family for h in range(G)
                           if h != g and not indep[g, h]})
        if dep_fams:
            conds["closure"] = (
                False, "statically dependent on instance(s) of "
                       f"{', '.join(dep_fams)} — a deferred dependent "
                       "action could observe this instance's writes")
        else:
            conds["closure"] = (True, "independent of every other "
                                      "instance (persistent singleton)")

        vis = sorted(set(inst.writes) & pred_reads)
        if vis:
            blockers = sorted(name for name, reads in read_sets.items()
                              if set(inst.writes) & reads)
            conds["visibility"] = (
                False, f"writes {', '.join(vis)} read by checked "
                       f"predicate(s) {', '.join(blockers)}")
        else:
            conds["visibility"] = (True, "writes invisible to every "
                                         "checked predicate")

        closed, params_arrays = kernels[inst.family]
        row = tuple(int(np.asarray(p)[g - dims.family_offsets[
            dims.family_names.index(inst.family)]])
            for p in params_arrays)
        key = (inst.family, row)
        if key not in proviso_cache:
            proviso_cache[key] = self_disabling(closed, row, env)
        proved, _notes = proviso_cache[key]
        conds["proviso"] = (
            (True, "guard proved false on own successors "
                   "(self-disabling)") if proved else
            (False, "cannot prove the guard false on the instance's own "
                    "successors — an ample chain could ignore deferred "
                    "actions"))
        certs.append(Certificate(grid_index=g, family=inst.family,
                                 label=inst.label, conditions=conds))
    return certs


def _verify_certified(certs, summary, read_sets, dims,
                      bounds) -> List[Finding]:
    """Defense-in-depth re-check of every CERTIFIED instance against the
    raw inputs: C1 straight off the dependence matrix, C2 off the
    predicate read sets, and C3 by re-running the self-disabling proof
    with the instance parameters re-derived through ``instance_info``
    (independent of the builder's offset arithmetic and its memoization).
    Any failure is an ERROR — the pass then exits nonzero rather than
    emitting a table whose side conditions do not hold."""
    findings = []
    pred_reads = frozenset().union(*read_sets.values()) if read_sets \
        else frozenset()
    G = len(summary.instances)
    if any(c.ample for c in certs):
        env = _envelope_intervals(dims, bounds)
        kernels = {name: closed
                   for name, closed, _p in traced_kernels(dims)}
    for cert in certs:
        if not cert.ample:
            continue
        g = cert.grid_index
        fam_code, params = dims.instance_info(g)
        row = tuple(params.values())
        proviso_ok, _n = self_disabling(
            kernels[dims.family_names[fam_code]], row, env)
        ok = int(summary.independent[g].sum()) == G - 1 \
            and not (set(summary.instances[g].writes) & pred_reads) \
            and proviso_ok
        if not ok:
            findings.append(Finding(
                PASS, ERROR, "certificate-unsound",
                witness=cert.label,
                message=f"certificate for {cert.label} fails re-"
                        "verification against the dependence matrix / "
                        "predicate read sets / proviso proof — refusing "
                        "to emit the reduction table"))
    return findings


def analyze(dims, bounds=None, invariant_names=None, invariants=None,
            constraint=None, effect_summary=None
            ) -> Tuple[dict, List[Finding]]:
    """Run the POR pass.  Returns ``(summary_json, findings)``; the
    packed table rides in ``summary_json["table"]``.

    ``invariants`` (name -> kernel dict) takes precedence over
    ``invariant_names`` (registry lookup; None = the conservative full
    suite); ``constraint`` is the evaluated CONSTRAINT kernel (falls
    back to one built from ``bounds``).  ``effect_summary`` reuses the
    effects pass's live result when both passes run in one invocation."""
    from ..models.invariants import CONSTRAINT_PREDICATE, \
        checkable_predicates
    from . import effects

    findings: List[Finding] = []
    if effect_summary is None:
        effect_summary, _eff_findings = effects.analyze(dims)

    if invariants is not None:
        predicates = list(invariants.items())
        if constraint is not None:
            predicates.append((CONSTRAINT_PREDICATE, constraint))
    else:
        predicates = checkable_predicates(
            dims, invariant_names=invariant_names, bounds=bounds,
            constraint=constraint)
    read_sets, notes = predicate_read_sets(dims, predicates)
    for note in notes:
        findings.append(Finding(
            PASS, INFO, "analysis-imprecision",
            message="predicate read-set extraction fell back to a "
                    f"conservative rule ({note}); read sets remain "
                    "sound but may over-approximate"))

    certs = _build_certificates(dims, effect_summary, read_sets, bounds)
    findings.extend(_verify_certified(certs, effect_summary, read_sets,
                                      dims, bounds))

    # Aggregate per family: one WARNING per widened family (conservative
    # toward full expansion), one INFO per certified family.
    by_family: Dict[str, List[Certificate]] = {}
    for c in certs:
        by_family.setdefault(c.family, []).append(c)
    fam_json = {}
    for fam, group in by_family.items():
        n_cert = sum(c.ample for c in group)
        blocked: Dict[str, int] = {}
        for c in group:
            for cond in c.blocking():
                blocked[cond] = blocked.get(cond, 0) + 1
        fam_json[fam] = {"instances": len(group), "certified": n_cert,
                         "blocked_by": blocked}
        if n_cert == len(group):
            findings.append(Finding(
                PASS, INFO, "por-certified", field=fam,
                message=f"all {len(group)} instance(s) of {fam} carry a "
                        "proved ample certificate",
                details={"instances": len(group)}))
        else:
            first = next(c for c in group if not c.ample)
            cond = first.blocking()[0]
            findings.append(Finding(
                PASS, WARNING, "por-widened", field=fam,
                witness=first.label,
                message=f"{fam}: {len(group) - n_cert}/{len(group)} "
                        f"instance(s) widened to full expansion — "
                        f"{cond} unproved: "
                        f"{first.conditions[cond][1]}",
                details={"blocked_by": blocked}))

    mask = np.array([c.ample for c in certs], bool)
    priority = np.arange(len(certs), dtype=np.int32)
    table = PorTable(model=repr(dims), n_instances=len(certs),
                     ample_mask=mask, priority=priority,
                     predicates=tuple(name for name, _k in predicates))
    summary = {
        "n_instances": len(certs),
        "certified": table.certified,
        "predicates": {name: sorted(fields)
                       for name, fields in read_sets.items()},
        "families": fam_json,
        "table": table.to_json(),
    }
    return summary, findings


def build_table(dims, bounds=None, invariant_names=None, invariants=None,
                constraint=None, effect_summary=None) -> PorTable:
    """One-call table construction (the engine's ``por=True`` path).
    Raises if any certificate fails its side conditions — the same gate
    as the CLI's nonzero exit."""
    summary, findings = analyze(
        dims, bounds=bounds, invariant_names=invariant_names,
        invariants=invariants, constraint=constraint,
        effect_summary=effect_summary)
    errors = [f for f in findings if f.severity == ERROR]
    if errors:
        raise ValueError(f"POR certification failed: {errors[0].message}")
    return PorTable.from_json(summary["table"])
