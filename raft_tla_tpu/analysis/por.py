"""Static partial-order reduction: ample-set certificates from the
dependence matrices.

The effects pass (``effects.py``) already proves, per action instance,
element-wise write masks and field-level read sets, and folds them into
the action dependence matrix.  This pass consumes those matrices and
asks, for every instance ``g``: *is the singleton ``{g}`` a valid ample
set at every state where ``g`` is enabled?*  If yes, the engine may
expand ONLY ``g`` from such a state and provably misses no invariant
verdict.  Four side conditions, each proved statically or the instance
is conservatively widened to "never ample" (a WARNING, never a silent
claim — the ``bounds.py`` contract):

- **C0 non-emptiness** — structural: the engine applies the reduction
  only at states whose enabled set contains a certified instance, so
  the chosen ample set is never empty.
- **C1 closure (stubbornness)** — ``g`` must be independent of EVERY
  other instance (``effects.independent`` row complete off-diagonal).
  Independence there means element-disjoint writes and neither touches
  what the other reads, so ``{g}`` is a persistent set wherever ``g``
  is enabled: no action executable before ``g`` — now or after any
  deferred sequence — conflicts with it, and nothing can disable it.
  Anything weaker is unsound: a dependent action that is merely
  *disabled right now* can become enabled along a deferred path and
  observe ``g``'s writes (see tests for the concrete counterexample
  family), so no enabled-set-only refinement is offered.
- **C2 invariant visibility** — ``g``'s written fields must be disjoint
  from the read set of every checked predicate: the configured
  INVARIANTs (models/invariants.py TypeOK + the models/safety.py suite
  by default) AND the cfg CONSTRAINT (constraint reads gate expansion).
  Read sets are traced through the same jaxpr taint interpreter as the
  effects pass, so a predicate's footprint can never silently drift
  from its kernel.  Without this condition a pruned sibling state could
  carry the only violating valuation.
- **C3 cycle proviso** — ``g`` must be provably *self-disabling*: the
  kernel's guard, re-evaluated under the interval domain on ``g``'s own
  successor envelope, must be must-false.  Together with C1 (no other
  instance writes ``g``'s guard reads) this kills the ignoring problem:
  an ample-only path can execute each certified instance at most once,
  so no cycle of the reduced graph consists solely of ample steps, and
  a certified instance can never produce a pruning self-loop (if
  ``s·g = s`` then ``g`` would still be enabled at ``s·g``,
  contradicting the proof).

On the base Raft alphabet this is an honest negative result, and with
the element-granular footprints it is now a PROVEN one: every instance
fails C1, and the closure-refutation search (below) exhibits, for every
non-vacuous instance, a concrete two-action non-commutation witness —
executing the compiled kernels on type-correct probe states — so the
block is inherent to the Raft alphabet (``Receive`` can address any
server and its reply allocation scans the whole bag), not analyzer
imprecision.  No footprint abstraction at any granularity can certify a
singleton ample set here; the ``por-impossible`` findings carry the
machine-checked witnesses, and the remaining ``blocked_by`` /
``blocking_elements`` tables stay the exact worklist for model variants
and simpler alphabets (ROADMAP item 4), where the same pass can
certify.  The machinery (certificates, packed device table, engine
masking, coverage accounting) is exercised end-to-end by the oracle
differentials in ``tests/test_por.py`` via forged certifying tables.

The emitted :class:`PorTable` is the device-consumable artifact: a
per-instance ``ample_mask`` + ``priority`` order packed for the engines
(``EngineConfig.por`` / ``por_table``), serialized into the ``analyze
--json`` report and an optional versioned artifact file.  The table is
fingerprinted over its full payload; the engine re-verifies fingerprint,
model signature, and predicate coverage before applying a mask, so a
hand-edited certificate is rejected, never silently trusted.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
from typing import Dict, List, Optional, Tuple

import numpy as np

from . import lane_map
from .interp import (IntervalDomain, TaintDomain, _ival, eval_jaxpr,
                     traced_kernels)
from .report import ERROR, Finding, INFO, WARNING

PASS = "por"
#: v2: certificates are proved from ELEMENT-granular (slot/column)
#: footprints and the payload records the granularity — v1 artifacts
#: (field-granular proofs) are rejected at load and must be
#: regenerated, so an engine can never apply a certificate proved under
#: a coarser footprint encoding than the analyzer now emits.
TABLE_VERSION = 2
GRANULARITY = "element"

#: C1/C2/C3 condition names, report order.
CONDITIONS = ("nonempty", "closure", "visibility", "proviso")


# ---------------------------------------------------------------------------
# Predicate read sets (invariant-visibility inputs)


def trace_predicate(kernel, dims):
    """Trace one state predicate ``kernel(StateBatch) -> bool`` to a
    ClosedJaxpr over the 13 abstract state fields (lane_map.FIELDS
    order) — the invariant-side twin of ``interp.trace_family``."""
    import jax
    import jax.numpy as jnp

    from ..models.schema import StateBatch

    shapes = lane_map.field_shapes(dims)

    def flat(*fields):
        return kernel(StateBatch(*fields))

    in_avals = [jax.ShapeDtypeStruct(shapes[f], jnp.int32)
                for f in lane_map.FIELDS]
    return jax.make_jaxpr(flat)(*in_avals)


def predicate_read_sets(dims, predicates) -> Tuple[Dict[str, Dict],
                                                   List[str]]:
    """``{name: {field: element mask}}`` for ``[(name, kernel)]``, via
    the taint domain (sound: a dropped dependency would be an interp
    bug — the lint pass's read-set self-check guards the same property
    on the action kernels).  Element-wise since the taint domain tracks
    per-element masks; an invariant that reads only some lanes of a
    field no longer blocks visibility for writes to the others.  Also
    returns the domain's imprecision notes."""
    from .effects import _state_taints
    from .interp import read_mask
    domain = TaintDomain()
    state = _state_taints(dims)
    out: Dict[str, Dict] = {}
    for name, kernel in predicates:
        closed = trace_predicate(kernel, dims)
        res = eval_jaxpr(closed, list(state), domain)
        out[name] = read_mask(res[0])
    return out, list(domain.notes)


# ---------------------------------------------------------------------------
# C3: self-disabling proof (interval domain)


def _envelope_intervals(dims, bounds=None):
    """Declared per-field domains (lane_map.field_domains — the same
    widening envelope the bounds pass uses), intersected with the cfg's
    CONSTRAINT clamps, as interval-domain state values."""
    domains = lane_map.field_domains(dims)
    clamps = lane_map.constraint_bounds(dims, bounds)
    shapes = lane_map.field_shapes(dims)
    out = []
    for f in lane_map.FIELDS:
        lo, hi = domains[f]
        lo = np.broadcast_to(np.asarray(lo, np.int64), shapes[f])
        hi = np.broadcast_to(np.asarray(hi, np.int64), shapes[f])
        if f in clamps:
            clo, chi = clamps[f]
            lo = np.maximum(lo, clo)
            hi = np.minimum(hi, chi)
        out.append(_ival(lo, hi, np.int32))
    return out


def self_disabling(closed, params, env_state) -> Tuple[bool, List[str]]:
    """Prove the instance's guard false on its own successors.

    Evaluates the family jaxpr once on the reachable envelope (successor
    intervals over-approximate every ``g``-successor of every state in
    the envelope), then re-evaluates the same jaxpr on those successor
    intervals and requires the ``enabled`` output to be must-false.
    Conservative both ways: an imprecision widens the guard toward
    "maybe enabled" and the proof simply fails."""
    domain = IntervalDomain()
    pvals = [np.int32(v) for v in params]
    outs = eval_jaxpr(closed, list(env_state) + pvals, domain)
    succ = outs[2:]
    outs2 = eval_jaxpr(closed, list(succ) + pvals, domain)
    en2 = outs2[0]
    proved = bool(np.all(np.asarray(en2.hi) == 0))
    return proved, list(domain.notes)


# ---------------------------------------------------------------------------
# Certificates and the packed table


@dataclasses.dataclass
class Certificate:
    """Per-instance ample-set certificate: condition -> (proved, why)."""

    grid_index: int
    family: str
    label: str
    conditions: Dict[str, Tuple[bool, str]]

    @property
    def ample(self) -> bool:
        return all(ok for ok, _why in self.conditions.values())

    def blocking(self) -> List[str]:
        return [c for c in CONDITIONS if not self.conditions[c][0]]


@dataclasses.dataclass
class PorTable:
    """The device-consumable reduction table (versioned artifact).

    ``ample_mask[g]`` — instance ``g`` is a certified singleton ample
    set wherever enabled; ``priority[g]`` — selection order when several
    certified instances are enabled in one state (lowest value wins;
    grid order by default, reorderable by future cost models without a
    schema change).  ``predicates`` names every state predicate the
    visibility condition was proved against — a run checking anything
    outside this list must reject the table.  ``fingerprint`` is a
    sha256 over the canonical payload: a hand-edited mask no longer
    matches and is rejected at load (tests plant exactly that)."""

    model: str
    n_instances: int
    ample_mask: np.ndarray          # [G] bool
    priority: np.ndarray            # [G] int32
    predicates: Tuple[str, ...]
    version: int = TABLE_VERSION
    granularity: str = GRANULARITY

    def __post_init__(self):
        self.ample_mask = np.asarray(self.ample_mask, bool)
        self.priority = np.asarray(self.priority, np.int32)
        if self.ample_mask.shape != (self.n_instances,) \
                or self.priority.shape != (self.n_instances,):
            raise ValueError("table arrays must be [n_instances]")

    @property
    def certified(self) -> int:
        return int(self.ample_mask.sum())

    def payload(self) -> dict:
        return {"version": self.version, "model": self.model,
                "granularity": self.granularity,
                "n_instances": self.n_instances,
                "predicates": sorted(self.predicates),
                "ample_mask": [int(b) for b in self.ample_mask],
                "priority": [int(p) for p in self.priority]}

    @property
    def fingerprint(self) -> str:
        blob = json.dumps(self.payload(), sort_keys=True,
                          separators=(",", ":")).encode()
        return hashlib.sha256(blob).hexdigest()

    def to_json(self) -> dict:
        out = self.payload()
        out["fingerprint"] = self.fingerprint
        return out

    @classmethod
    def from_json(cls, d: dict) -> "PorTable":
        if d.get("version") != TABLE_VERSION \
                or d.get("granularity", GRANULARITY) != GRANULARITY:
            raise ValueError(
                f"POR table version {d.get('version')!r} "
                f"(granularity {d.get('granularity')!r}) != supported "
                f"{TABLE_VERSION}/{GRANULARITY!r} — certificates proved "
                "under a coarser footprint encoding; regenerate with "
                "`analyze --passes por`")
        table = cls(model=d["model"], n_instances=int(d["n_instances"]),
                    ample_mask=np.asarray(d["ample_mask"], bool),
                    priority=np.asarray(d["priority"], np.int32),
                    predicates=tuple(d["predicates"]))
        want = d.get("fingerprint")
        if want != table.fingerprint:
            raise ValueError(
                "POR table fingerprint mismatch (edited by hand, or "
                "truncated): the certificate no longer matches its "
                "payload; regenerate with `analyze --passes por "
                "--por-artifact FILE`")
        return table

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(self.to_json(), f, indent=2, sort_keys=True)
            f.write("\n")


def load_table(path: str) -> PorTable:
    with open(path) as f:
        return PorTable.from_json(json.load(f))


def check_table(table: PorTable, dims, invariant_names=None,
                has_constraint: bool = False) -> None:
    """Engine-side admission check: model signature, instance count, and
    predicate coverage.  Raises ValueError on any mismatch — a reduction
    certified for a different model (or for fewer predicates than the
    run checks) must never be applied."""
    if table.model != repr(dims):
        raise ValueError(
            f"POR table was certified for model {table.model!r}, "
            f"engine runs {repr(dims)!r}")
    if table.n_instances != dims.n_instances:
        raise ValueError(
            f"POR table covers {table.n_instances} action instances, "
            f"model has {dims.n_instances}")
    missing = sorted(set(invariant_names or []) - set(table.predicates))
    if missing:
        raise ValueError(
            f"POR table visibility was not proved against checked "
            f"invariant(s) {missing}; certified predicates: "
            f"{sorted(table.predicates)}")
    if has_constraint:
        # Strict like the invariant check above (even for an all-
        # conservative mask): a certificate applied outside the
        # predicate set it was proved under is a config error worth
        # surfacing before it matters.
        from ..models.invariants import CONSTRAINT_PREDICATE
        if CONSTRAINT_PREDICATE not in table.predicates:
            raise ValueError(
                "POR table was certified without a CONSTRAINT predicate "
                "but the run applies one; constraint reads gate "
                "expansion and must be part of the visibility condition")


# ---------------------------------------------------------------------------
# The pass


# ---------------------------------------------------------------------------
# Closure refutation: machine-checked impossibility witnesses
#
# A family blocked on C1 by the dependence matrix could in principle be
# an analyzer artifact (over-approximate footprints) — the precision
# worklist — or INHERENT: the actions genuinely do not commute, so no
# sound footprint abstraction at any granularity can ever certify the
# singleton.  The distinction is decided concretely: for each blocked
# instance the pass searches a small pool of type-correct probe states
# (``models.pystate.probe_states`` + the run's roots) for a two-action
# non-commutation witness — a state where both actions are enabled and
# either one disables the other or the diamond closes on different
# states.  The check executes the COMPILED kernels (``build_expand``,
# the exact programs the engine runs) on concrete states; a found
# witness is therefore a semantic refutation of independence, not an
# abstract-domain claim.  Instances whose guard is must-false on the
# declared domain envelope (interval proof) are vacuous: they can never
# execute, so no witness exists or is needed — a certificate for them
# could never prune anything.
#
# Probe states need not be reachable: C1's independence requirement is
# a property over the declared state domain (the same envelope every
# other condition is proved against), so any type-correct witness
# refutes it for every sound analyzer.


@dataclasses.dataclass
class ClosureRefutation:
    """Per-instance outcome of the witness search."""

    label: str
    #: "witnessed" (concrete non-commutation found), "vacuous" (guard
    #: must-false on the declared envelope), or "open" (no witness in
    #: the probe pool — genuine precision worklist).
    status: str
    #: witnessed: the conflicting instance, the witness kind
    #: ("disables", "disabled-by", "diamond") and the probe state index.
    conflicts_with: Optional[str] = None
    kind: Optional[str] = None
    probe_state: Optional[int] = None

    def to_json(self) -> dict:
        out = {"label": self.label, "status": self.status}
        if self.conflicts_with is not None:
            out.update(conflicts_with=self.conflicts_with, kind=self.kind,
                       probe_state=self.probe_state)
        return out


def _canonical_state(tree, idx) -> tuple:
    """Hashable canonical view of one successor state slice: plain
    fields verbatim, the message bag as a sorted multiset of occupied
    (row, count) pairs — slot-permutation invariant, so two orders of a
    commuting pair that allocate reply slots differently still compare
    equal."""
    fields = {f: np.asarray(getattr(tree, f))[idx] for f in tree._fields}
    msg, cnt = fields.pop("msg"), fields.pop("msg_cnt")
    occ = cnt > 0
    bag = sorted((tuple(int(x) for x in row), int(c))
                 for row, c in zip(msg[occ], cnt[occ]))
    plain = tuple((f, tuple(np.asarray(v).reshape(-1).tolist()))
                  for f, v in sorted(fields.items()))
    return plain, tuple(bag)


def _vacuous_instances(dims, env) -> Dict[int, bool]:
    """{grid index: guard is must-false on the declared envelope} — the
    interval-domain proof that an instance can never execute (e.g. the
    ``AppendEntries(i, i)`` grid corners, whose guard is
    parameter-concrete False)."""
    out: Dict[int, bool] = {}
    kernels = {name: (closed, params)
               for name, closed, params in traced_kernels(dims)}
    for g in range(dims.n_instances):
        fam_code, params = dims.instance_info(g)
        closed, _arrays = kernels[dims.family_names[fam_code]]
        domain = IntervalDomain()
        pvals = [np.int32(v) for v in params.values()]
        outs = eval_jaxpr(closed, list(env) + pvals, domain)
        out[g] = bool(np.all(np.asarray(outs[0].hi) == 0))
    return out


def closure_refutations(dims, probe_pool, env) -> List[ClosureRefutation]:
    """Run the witness search over ``probe_pool`` (PyStates).  Returns
    one :class:`ClosureRefutation` per action instance."""
    import jax

    from ..models.actions import build_expand
    from ..models.schema import encode_state

    G = dims.n_instances
    labels = [dims.describe_instance(g) for g in range(G)]
    vac = _vacuous_instances(dims, env)
    out: Dict[int, ClosureRefutation] = {
        g: ClosureRefutation(labels[g], "vacuous")
        for g in range(G) if vac[g]}

    expand = jax.jit(build_expand(dims))
    expand_v = jax.jit(jax.vmap(build_expand(dims)))
    for si, ps in enumerate(probe_pool):
        if len(out) == G:
            break
        enc = encode_state(ps, dims)
        cands, en, ovf = expand(enc)
        en = np.asarray(en) & ~np.asarray(ovf)
        if not en.any():
            continue
        c2, en2, ovf2 = expand_v(cands)
        en2, ovf2 = np.asarray(en2), np.asarray(ovf2)
        canon: Dict[Tuple[int, int], tuple] = {}

        def second(g, h):
            if (g, h) not in canon:
                canon[(g, h)] = _canonical_state(c2, (g, h))
            return canon[(g, h)]

        for g in range(G):
            if g in out or not en[g]:
                continue
            for h in range(G):
                if h == g or not en[h]:
                    continue
                # Disabling counts only when the second step is cleanly
                # disabled, not when its ENCODING overflowed (an
                # overflow lane reports enabled=False with the overflow
                # flag set — that is a capacity artifact, not semantics).
                if not en2[g, h] and not ovf2[g, h]:
                    out[g] = ClosureRefutation(
                        labels[g], "witnessed", labels[h], "disables", si)
                    break
                if not en2[h, g] and not ovf2[h, g]:
                    out[g] = ClosureRefutation(
                        labels[g], "witnessed", labels[h], "disabled-by",
                        si)
                    break
                if not (en2[g, h] and en2[h, g]) \
                        or ovf2[g, h] or ovf2[h, g]:
                    continue
                if second(g, h) != second(h, g):
                    out[g] = ClosureRefutation(
                        labels[g], "witnessed", labels[h], "diamond", si)
                    break
    for g in range(G):
        if g not in out:
            out[g] = ClosureRefutation(labels[g], "open")
    return [out[g] for g in range(G)]


# ---------------------------------------------------------------------------
# The pass


def _mask_overlap(writes: Dict[str, np.ndarray],
                  reads: Dict[str, np.ndarray]) -> List[Tuple[str,
                                                              np.ndarray]]:
    """Element-wise intersection of a write and a read footprint:
    ``[(field, overlap mask), ...]`` for the fields that clash."""
    out = []
    for f, m in writes.items():
        r = reads.get(f)
        if r is not None and bool((m & r).any()):
            out.append((f, m & r))
    return out


def element_label(field: str, mask: np.ndarray) -> str:
    """Human-readable label of the first blocking element of a mask —
    the ``(family, field, slot)`` triple rendering the worklist uses.
    A fully-set mask reads as the whole field."""
    if mask.all():
        return f"{field}[*]"
    idx = np.unravel_index(int(np.flatnonzero(mask.reshape(-1))[0]),
                           mask.shape)
    if mask.ndim == 2 and mask[idx[0]].all():
        return f"{field}[{idx[0]},*]"
    return f"{field}[{','.join(str(int(k)) for k in idx)}]"


def _build_certificates(dims, summary, read_sets, bounds):
    """One :class:`Certificate` per action instance."""
    from .effects import conflict_elements
    instances = summary.instances
    G = len(instances)
    indep = summary.independent
    env = _envelope_intervals(dims, bounds)
    kernels = {name: (closed, params)
               for name, closed, params in traced_kernels(dims)}
    # Per-(family, param row) proviso proofs — instances of one family
    # share a jaxpr, so memoize on the concrete parameter tuple.
    proviso_cache: Dict[Tuple, Tuple[bool, List[str]]] = {}

    certs: List[Certificate] = []
    for g, inst in enumerate(instances):
        conds: Dict[str, Tuple[bool, str]] = {}
        # C0: the engine masks only states where this instance is
        # enabled, so the chosen ample set is non-empty by construction.
        conds["nonempty"] = (True, "ample applied only where enabled")

        dep = [h for h in range(G) if h != g and not indep[g, h]]
        if dep:
            dep_fams = sorted({instances[h].family for h in dep})
            # Name the first blocking element — the precision worklist's
            # exact next step for this instance.
            kind, fld, mask = conflict_elements(inst, instances[dep[0]])[0]
            conds["closure"] = (
                False, "statically dependent on instance(s) of "
                       f"{', '.join(dep_fams)} — a deferred dependent "
                       "action could observe this instance's writes; "
                       f"first blocking element: {kind} on "
                       f"{element_label(fld, mask)} vs "
                       f"{instances[dep[0]].label}")
        else:
            conds["closure"] = (True, "independent of every other "
                                      "instance (persistent singleton)")

        vis = []
        blockers = set()
        for name, reads in read_sets.items():
            clash = _mask_overlap(inst.writes, reads)
            if clash:
                blockers.add(name)
                vis.extend(element_label(f, m) for f, m in clash)
        if vis:
            conds["visibility"] = (
                False, f"writes {', '.join(sorted(set(vis)))} read by "
                       f"checked predicate(s) {', '.join(sorted(blockers))}")
        else:
            conds["visibility"] = (True, "writes invisible to every "
                                         "checked predicate")

        closed, params_arrays = kernels[inst.family]
        row = tuple(int(np.asarray(p)[g - dims.family_offsets[
            dims.family_names.index(inst.family)]])
            for p in params_arrays)
        key = (inst.family, row)
        if key not in proviso_cache:
            proviso_cache[key] = self_disabling(closed, row, env)
        proved, _notes = proviso_cache[key]
        conds["proviso"] = (
            (True, "guard proved false on own successors "
                   "(self-disabling)") if proved else
            (False, "cannot prove the guard false on the instance's own "
                    "successors — an ample chain could ignore deferred "
                    "actions"))
        certs.append(Certificate(grid_index=g, family=inst.family,
                                 label=inst.label, conditions=conds))
    return certs


def _verify_certified(certs, summary, read_sets, dims,
                      bounds) -> List[Finding]:
    """Defense-in-depth re-check of every CERTIFIED instance against the
    raw inputs: C1 straight off the dependence matrix, C2 off the
    predicate read sets, and C3 by re-running the self-disabling proof
    with the instance parameters re-derived through ``instance_info``
    (independent of the builder's offset arithmetic and its memoization).
    Any failure is an ERROR — the pass then exits nonzero rather than
    emitting a table whose side conditions do not hold."""
    findings = []
    G = len(summary.instances)
    if any(c.ample for c in certs):
        env = _envelope_intervals(dims, bounds)
        kernels = {name: closed
                   for name, closed, _p in traced_kernels(dims)}
    for cert in certs:
        if not cert.ample:
            continue
        g = cert.grid_index
        fam_code, params = dims.instance_info(g)
        row = tuple(params.values())
        proviso_ok, _n = self_disabling(
            kernels[dims.family_names[fam_code]], row, env)
        visible = any(_mask_overlap(summary.instances[g].writes, reads)
                      for reads in read_sets.values())
        ok = int(summary.independent[g].sum()) == G - 1 \
            and not visible \
            and proviso_ok
        if not ok:
            findings.append(Finding(
                PASS, ERROR, "certificate-unsound",
                witness=cert.label,
                message=f"certificate for {cert.label} fails re-"
                        "verification against the dependence matrix / "
                        "predicate read sets / proviso proof — refusing "
                        "to emit the reduction table"))
    return findings


def analyze(dims, bounds=None, invariant_names=None, invariants=None,
            constraint=None, effect_summary=None, init_states=None,
            refute=True) -> Tuple[dict, List[Finding]]:
    """Run the POR pass.  Returns ``(summary_json, findings)``; the
    packed table rides in ``summary_json["table"]``.

    ``invariants`` (name -> kernel dict) takes precedence over
    ``invariant_names`` (registry lookup; None = the conservative full
    suite); ``constraint`` is the evaluated CONSTRAINT kernel (falls
    back to one built from ``bounds``).  ``effect_summary`` reuses the
    effects pass's live result when both passes run in one invocation.
    ``init_states`` (PyStates) extend the probe pool of the closure
    refutation search; ``refute=False`` skips that search (pure
    trace-time analysis, e.g. for variant models without probe
    states)."""
    from ..models.invariants import CONSTRAINT_PREDICATE, \
        checkable_predicates
    from . import effects

    findings: List[Finding] = []
    if effect_summary is None:
        effect_summary, _eff_findings = effects.analyze(dims)

    if invariants is not None:
        predicates = list(invariants.items())
        if constraint is not None:
            predicates.append((CONSTRAINT_PREDICATE, constraint))
    else:
        predicates = checkable_predicates(
            dims, invariant_names=invariant_names, bounds=bounds,
            constraint=constraint)
    read_sets, notes = predicate_read_sets(dims, predicates)
    for note in notes:
        findings.append(Finding(
            PASS, INFO, "analysis-imprecision",
            message="predicate read-set extraction fell back to a "
                    f"conservative rule ({note}); read sets remain "
                    "sound but may over-approximate"))

    certs = _build_certificates(dims, effect_summary, read_sets, bounds)
    findings.extend(_verify_certified(certs, effect_summary, read_sets,
                                      dims, bounds))

    # Closure refutation (machine-checked impossibility) for instances
    # the dependence matrix blocks on C1: concrete non-commutation
    # witnesses split "blocked by analyzer imprecision" (worklist) from
    # "blocked inherently" (no footprint precision can ever certify).
    refutations: Dict[str, ClosureRefutation] = {}
    blocked_closure = [c for c in certs if "closure" in c.blocking()]
    if refute and blocked_closure:
        from ..models.pystate import probe_states
        pool = list(init_states or []) + probe_states(dims)
        env = _envelope_intervals(dims, bounds)
        refutations = {r.label: r
                       for r in closure_refutations(dims, pool, env)}

    # Aggregate per family: one WARNING per widened family (conservative
    # toward full expansion), one INFO per certified family, one INFO
    # per family whose closure block is fully witnessed (impossible).
    by_family: Dict[str, List[Certificate]] = {}
    for c in certs:
        by_family.setdefault(c.family, []).append(c)
    fam_json = {}
    instances = effect_summary.instances
    by_label = {i.label: k for k, i in enumerate(instances)}
    for fam, group in by_family.items():
        n_cert = sum(c.ample for c in group)
        blocked: Dict[str, int] = {}
        for c in group:
            for cond in c.blocking():
                blocked[cond] = blocked.get(cond, 0) + 1
        fam_json[fam] = {"instances": len(group), "certified": n_cert,
                         "blocked_by": blocked}
        # Top blocking elements for the worklist rendering: count, per
        # (other family, element) pair, how many of this family's
        # dependence conflicts anchor there.
        triples: Dict[Tuple[str, str, str], int] = {}
        for c in group:
            g = by_label[c.label]
            ia = instances[g]
            for h in np.flatnonzero(~effect_summary.independent[g]):
                if h == g:
                    continue
                ib = instances[int(h)]
                for kind, fld, m in effects.conflict_elements(ia, ib):
                    key = (ib.family, element_label(fld, m), kind)
                    triples[key] = triples.get(key, 0) + 1
        fam_json[fam]["blocking_elements"] = [
            {"family": f, "element": e, "kind": k, "pairs": n}
            for (f, e, k), n in sorted(triples.items(),
                                       key=lambda kv: -kv[1])[:5]]
        if fam == "Receive" and refute and blocked.get("closure"):
            # The mtype/(i, j) case-split (the taint twin of bounds.py's
            # Receive split): every case's server-field writes are
            # row-local to that case's dest — machine-readable evidence
            # that the whole-field union is forced by reachable message
            # headers, not by analyzer widening.
            cases = effects.receive_case_effects(dims)
            server_rows = {f: s for f, s in
                           lane_map.field_shapes(dims).items()
                           if f not in ("msg", "msg_cnt")}
            row_local = 0
            for (_t, i, _j), fp in cases.items():
                rows = {int(r) for f, m in fp["writes"].items()
                        if f in server_rows for r in np.nonzero(m)[0]}
                row_local += rows <= {i}
            fam_json[fam]["case_split"] = {
                "slot": 0, "cases": len(cases),
                "server_writes_row_local": row_local,
                "example": {
                    f"mtype={t},i={i},j={j}":
                        sorted(element_label(f, m)
                               for f, m in fp["writes"].items())
                    for (t, i, j), fp in list(cases.items())[:1]},
            }
        if refutations:
            # Only closure-BLOCKED instances need (or can have) a
            # witness: a certified instance is independent of
            # everything, so no non-commutation witness exists and
            # counting it as "open" would mislabel a partially
            # certified family as precision worklist.
            rs = [refutations[c.label] for c in group
                  if "closure" in c.blocking()]
            fam_json[fam]["closure_refutation"] = {
                "witnessed": sum(r.status == "witnessed" for r in rs),
                "vacuous": sum(r.status == "vacuous" for r in rs),
                "open": [r.label for r in rs if r.status == "open"],
                "witnesses": [r.to_json() for r in rs
                              if r.status == "witnessed"][:3],
            }
        if n_cert == len(group):
            findings.append(Finding(
                PASS, INFO, "por-certified", field=fam,
                message=f"all {len(group)} instance(s) of {fam} carry a "
                        "proved ample certificate",
                details={"instances": len(group)}))
        else:
            first = next(c for c in group if not c.ample)
            cond = first.blocking()[0]
            findings.append(Finding(
                PASS, WARNING, "por-widened", field=fam,
                witness=first.label,
                message=f"{fam}: {len(group) - n_cert}/{len(group)} "
                        f"instance(s) widened to full expansion — "
                        f"{cond} unproved: "
                        f"{first.conditions[cond][1]}",
                details={"blocked_by": blocked}))
        if refutations and blocked.get("closure"):
            ref = fam_json[fam]["closure_refutation"]
            if not ref["open"]:
                wit = ref["witnesses"][0] if ref["witnesses"] else None
                findings.append(Finding(
                    PASS, INFO, "por-impossible", field=fam,
                    witness=wit["label"] if wit else None,
                    message=f"{fam}: the closure block is INHERENT, not "
                            "analyzer imprecision — every instance has "
                            "a concrete two-action non-commutation "
                            "witness (or a proof it can never execute)"
                            + (f"; e.g. {wit['label']} vs "
                               f"{wit['conflicts_with']} "
                               f"({wit['kind']})" if wit else ""),
                    details=ref))

    mask = np.array([c.ample for c in certs], bool)
    priority = np.arange(len(certs), dtype=np.int32)
    table = PorTable(model=repr(dims), n_instances=len(certs),
                     ample_mask=mask, priority=priority,
                     predicates=tuple(name for name, _k in predicates))
    summary = {
        "n_instances": len(certs),
        "certified": table.certified,
        "predicates": {name: sorted(fields)
                       for name, fields in read_sets.items()},
        "predicate_elements": {
            name: {f: int(m.sum()) for f, m in fields.items()}
            for name, fields in read_sets.items()},
        "families": fam_json,
        "closure_refutation": _refutation_totals(certs, refutations),
        "table": table.to_json(),
    }
    return summary, findings


def _refutation_totals(certs, refutations) -> dict:
    """Top-level witness-search tally over the closure-BLOCKED
    instances only (certified instances have no witness to find)."""
    rs = [refutations[c.label] for c in certs
          if refutations and "closure" in c.blocking()]
    return {
        "ran": bool(refutations),
        "witnessed": sum(r.status == "witnessed" for r in rs),
        "vacuous": sum(r.status == "vacuous" for r in rs),
        "open": sorted(r.label for r in rs if r.status == "open"),
    }


def build_table(dims, bounds=None, invariant_names=None, invariants=None,
                constraint=None, effect_summary=None) -> PorTable:
    """One-call table construction (the engine's ``por=True`` path).
    Raises if any certificate fails its side conditions — the same gate
    as the CLI's nonzero exit."""
    summary, findings = analyze(
        dims, bounds=bounds, invariant_names=invariant_names,
        invariants=invariants, constraint=constraint,
        effect_summary=effect_summary,
        # The witness search classifies blocked instances but never
        # changes the mask — skip it on the engine-construction path.
        refute=False)
    errors = [f for f in findings if f.severity == ERROR]
    if errors:
        raise ValueError(f"POR certification failed: {errors[0].message}")
    return PorTable.from_json(summary["table"])
