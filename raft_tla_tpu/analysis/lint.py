"""Hot-loop lint: TPU-throughput hazards in the compiled step + host loop.

Three halves, one pass:

- **Jaxpr lint**: trace the BFS chunk body (the per-batch pipeline the
  engines run thousands of times per second — both the v1 expand path
  and the v2 delta path), the fingerprint kernel, and the FPSet insert,
  then walk every equation (recursing into pjit / while / cond / scan
  sub-jaxprs) for ops that silently wreck device throughput: host
  callbacks and infeed/outfeed (ERROR — a host round-trip per batch),
  dynamic shapes (ERROR — recompilation per shape), non-deterministic
  floating-point reductions (WARNING — the engines' bit-identical
  cross-engine contract assumes integer determinism), and
  dtype-narrowing converts (intentional uint8 row packing is an INFO
  count; any *other* integer narrowing is a WARNING, because that is
  exactly how a lane silently loses bits).

- **Host-loop AST lint**: the steady-state loop (``engine/chunk.py``
  and ``_run_impl`` in ``engine/bfs.py``) must fetch device data only
  at sanctioned sync points; any other blocking device read
  (``np.asarray`` / ``jax.device_get`` / ``block_until_ready``) inside
  a loop serializes the dispatch pipeline on the TPU tunnel.
  Sanctioned means: under a ``with <registry>.phase_timer(...)`` block
  (the engines' audited sync points — the telemetry contract makes
  every sync visible in the phase breakdown), or inside a branch that
  exits the loop (violation / deadlock reporting runs once, off the
  steady state).

- **Read-set self-check**: analyzer-vs-analyzer consistency — any state
  lane a kernel jaxpr demonstrably reads (consumed by a non-identity
  primitive on the way to the outputs) must be inside the read set the
  effects pass reports for that family.  A mismatch means the taint
  interpreter dropped a dependency, which would make downstream
  consumers (the POR certificates) unsound — ERROR.

Everything here is trace/parse-time only: no device execution, no
compilation — safe to run in CI on a CPU-only runner.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from .report import ERROR, Finding, INFO, WARNING

PASS = "lint"

#: Primitive names (exact or substring "callback") that move data or
#: control to the host from inside a compiled program.
_HOST_PRIMS = ("infeed", "outfeed", "host_local_array_to_global_array")
#: Reductions whose result depends on accumulation order for floats.
_ORDER_SENSITIVE = ("reduce_sum", "reduce_prod", "dot_general", "add_any",
                    "cumsum", "cumprod")


# ---------------------------------------------------------------------------
# Jaxpr lint


def _sub_jaxprs(params) -> Iterable:
    """Every jaxpr nested in an eqn's params (pjit/while/cond/scan...)."""
    for v in params.values():
        vals = v if isinstance(v, (list, tuple)) else (v,)
        for x in vals:
            if hasattr(x, "jaxpr") or hasattr(x, "eqns"):
                yield x


def _walk_eqns(jaxpr):
    closed = getattr(jaxpr, "jaxpr", jaxpr)
    for eqn in closed.eqns:
        yield eqn
        for sub in _sub_jaxprs(eqn.params):
            yield from _walk_eqns(sub)


def lint_jaxpr(closed, kernel: str) -> Tuple[dict, List[Finding]]:
    """Lint one traced kernel.  Returns (summary, findings)."""
    findings: List[Finding] = []
    n_eqns = 0
    pack_narrows = 0
    narrow_prims: Dict[str, int] = {}
    seen_codes = set()

    def once(code, qual, sev, msg, **kw):
        key = (code, qual)
        if key in seen_codes:
            return
        seen_codes.add(key)
        findings.append(Finding(PASS, sev, code, field=kernel,
                                message=msg, **kw))

    for eqn in _walk_eqns(closed):
        n_eqns += 1
        name = eqn.primitive.name
        if "callback" in name or name in _HOST_PRIMS:
            once("host-callback", name, ERROR,
                 f"compiled kernel {kernel!r} contains host-transfer "
                 f"primitive {name!r} — a host round-trip inside the "
                 "device loop throttles every batch")
        elif name == "debug_print":
            once("debug-print", name, WARNING,
                 f"compiled kernel {kernel!r} contains debug_print — "
                 "host formatting inside the device loop")
        for v in eqn.outvars:
            shape = getattr(v.aval, "shape", ())
            if any(not isinstance(d, int) for d in shape):
                once("dynamic-shape", name, ERROR,
                     f"kernel {kernel!r}: primitive {name!r} has a "
                     f"dynamically-shaped output {shape} — every new "
                     "shape recompiles the step")
        if name in _ORDER_SENSITIVE:
            in_dt = np.dtype(eqn.invars[0].aval.dtype)
            if in_dt.kind == "f":
                once("nondet-reduction", name, WARNING,
                     f"kernel {kernel!r}: float {name} — accumulation "
                     "order is backend-dependent, breaking the engines' "
                     "bit-identical cross-engine contract")
        if name == "convert_element_type":
            in_dt = np.dtype(eqn.invars[0].aval.dtype)
            out_dt = np.dtype(eqn.outvars[0].aval.dtype)
            if (in_dt.kind in "iu" and out_dt.kind in "iu"
                    and out_dt.itemsize < in_dt.itemsize):
                if out_dt == np.uint8:
                    pack_narrows += 1       # the row packing, by design
                else:
                    narrow_prims[f"{in_dt}->{out_dt}"] = \
                        narrow_prims.get(f"{in_dt}->{out_dt}", 0) + 1
    for conv, cnt in sorted(narrow_prims.items()):
        findings.append(Finding(
            PASS, WARNING, "narrowing-convert", field=kernel,
            message=f"kernel {kernel!r}: {cnt} integer-narrowing "
                    f"convert(s) {conv} outside the uint8 row packing — "
                    "a lane silently loses bits if the value can exceed "
                    "the target width",
            details={"convert": conv, "count": cnt}))
    if pack_narrows:
        findings.append(Finding(
            PASS, INFO, "packing-converts", field=kernel,
            message=f"kernel {kernel!r}: {pack_narrows} intentional "
                    "uint8 row-packing convert(s) (pack-guarded)",
            details={"count": pack_narrows}))
    return {"eqns": n_eqns, "packing_converts": pack_narrows}, findings


def _trace_engine_kernels(dims, batch: int = 4):
    """Trace the kernels the single-chip engine actually runs, with tiny
    capacities (tracing only — nothing executes).  Yields
    (kernel name, ClosedJaxpr)."""
    import jax
    import jax.numpy as jnp

    from ..engine.chunk import build_chunk_body
    from ..models.actions import build_expand
    from ..models.invariants import build_type_ok
    from ..models.schema import StateBatch, build_pack_guard, state_width
    from ..ops import compact as compact_mod
    from ..ops import fpset
    from ..ops.fingerprint import build_fingerprint
    from . import lane_map

    expand = build_expand(dims)
    fingerprint = build_fingerprint(dims)
    pack_ok = build_pack_guard(dims)
    inv_fns = [build_type_ok(dims)]
    sw = state_width(dims)
    B, G = batch, dims.n_instances
    K = compact_mod.choose_k(B, G, None)
    Q = max(B, K)
    QA = Q + max(B, K)
    TQ = Q + K

    shapes = lane_map.field_shapes(dims)
    state1 = [jax.ShapeDtypeStruct(shapes[f], jnp.int32)
              for f in lane_map.FIELDS]
    yield "fingerprint", jax.make_jaxpr(
        lambda *a: fingerprint(StateBatch(*a)))(*state1)

    seen = fpset.empty(1024)
    keys = jax.ShapeDtypeStruct((K,), jnp.uint32)
    valid = jax.ShapeDtypeStruct((K,), jnp.bool_)
    yield "fpset_insert", jax.make_jaxpr(fpset.insert)(
        seen, keys, keys, valid)

    def carry(seen):
        i32 = jax.ShapeDtypeStruct((), jnp.int32)
        return (
            i32, i32,
            jax.ShapeDtypeStruct((QA, sw), jnp.uint8), i32, seen,
            tuple(jax.ShapeDtypeStruct((TQ + K,), dt) for dt in
                  (jnp.uint32, jnp.uint32, jnp.uint32, jnp.uint32,
                   jnp.int32)),
            i32, i32, i32, i32,
            jax.ShapeDtypeStruct((), jnp.bool_),
            jax.ShapeDtypeStruct((sw,), jnp.uint8),
            jax.ShapeDtypeStruct((), jnp.bool_), i32,
            jax.ShapeDtypeStruct((sw,), jnp.uint8),
            jax.ShapeDtypeStruct((), jnp.uint32),
            jax.ShapeDtypeStruct((), jnp.uint32),
            jax.ShapeDtypeStruct((), jnp.bool_),
            # fam_counts, fam_new (coverage), expanded, fam_pruned (POR)
            # — the 22-field carry (engine/chunk.py layout).
            jax.ShapeDtypeStruct((len(dims.family_sizes),), jnp.int32),
            jax.ShapeDtypeStruct((len(dims.family_sizes),), jnp.int32),
            i32,
            jax.ShapeDtypeStruct((len(dims.family_sizes),), jnp.int32))

    qcur = jax.ShapeDtypeStruct((QA, sw), jnp.uint8)
    cnt = jax.ShapeDtypeStruct((), jnp.int32)

    def step_jaxpr(v2):
        body = build_chunk_body(
            dims=dims, expand=expand, fingerprint=fingerprint,
            pack_ok=pack_ok, inv_fns=inv_fns, constraint=None,
            B=B, G=G, K=K, Q=Q, TQ=TQ, record_static=True,
            compactor=compact_mod.build_compactor(B, G, K),
            insert_fn=fpset.insert, v2=v2)
        return jax.make_jaxpr(body)(qcur, cnt, carry(seen))

    yield "bfs_step_v1", step_jaxpr(None)
    from ..models.actions2 import V2Unavailable, build_v2
    try:
        v2 = build_v2(dims)
    except V2Unavailable:
        v2 = None
    if v2 is not None:
        yield "bfs_step_v2", step_jaxpr(v2)


# ---------------------------------------------------------------------------
# Analyzer-vs-analyzer read-set self-check
#
# The effects pass's read sets feed the POR certificates, so a taint
# dependency silently dropped by the interpreter would turn into an
# unsound reduction.  This check re-derives a SYNTACTIC read set per
# action family — every state invar consumed by at least one
# non-value-preserving primitive on the way to the outputs — and flags
# any lane the jaxpr demonstrably reads that the effects pass does not
# report.  Pure pass-through (an unchanged successor field flowing
# identically to an outvar) is not a read; that is exactly the
# distinction the taint domain draws, so the two analyzers must agree.

#: Primitives that move values without consuming them (reshape-like).
_IDENTITY_PRIMS = frozenset({
    "copy", "reshape", "squeeze", "expand_dims", "transpose", "rev",
    "broadcast_in_dim", "convert_element_type", "stop_gradient", "slice",
})

_CALL_PRIMS = ("pjit", "closed_call", "core_call", "remat", "checkpoint",
               "custom_jvp_call", "custom_vjp_call")


def syntactic_real_reads(closed, n_state: int) -> set:
    """Indices (0..n_state-1) of state invars consumed by a non-identity
    primitive anywhere in the jaxpr (recursing into call sub-jaxprs)."""
    reads: set = set()

    def walk(jaxpr, env):
        for eqn in jaxpr.eqns:
            from .interp import _literal_cls
            srcs = [env.get(v, frozenset()) for v in eqn.invars
                    if not isinstance(v, _literal_cls())]
            union = frozenset().union(*srcs) if srcs else frozenset()
            name = eqn.primitive.name
            if name in _CALL_PRIMS:
                inner = eqn.params.get("jaxpr") or \
                    eqn.params.get("call_jaxpr")
                if inner is not None:
                    ij = getattr(inner, "jaxpr", inner)
                    sub_env = {}
                    live = [v for v in eqn.invars
                            if not isinstance(v, _literal_cls())]
                    for var, outer in zip(ij.invars, live):
                        sub_env[var] = env.get(outer, frozenset())
                    walk(ij, sub_env)
                    for outv, innerv in zip(eqn.outvars, ij.outvars):
                        if not isinstance(innerv, _literal_cls()):
                            env[outv] = sub_env.get(innerv, frozenset())
                    continue
            if name in _IDENTITY_PRIMS:
                for outv in eqn.outvars:
                    env[outv] = union
            else:
                reads.update(union)
                for outv in eqn.outvars:
                    env[outv] = union

    jaxpr = closed.jaxpr
    env = {v: frozenset([k]) for k, v in enumerate(jaxpr.invars[:n_state])}
    walk(jaxpr, env)
    return reads


def read_set_check(dims, family_reads=None,
                   effect_summary=None) -> List[Finding]:
    """Flag any action kernel whose jaxpr reads a packed lane outside
    the read set the effects pass reports for it.  ``family_reads``
    overrides the effects-derived ``{family: fields}`` map (tests plant
    a missing field there to prove the check fires).

    Element granularity: the effects pass now reports per-element
    masks, so a FIELD the pass claims to read with an all-empty mask
    would slip past a set-membership comparison — membership here is
    therefore derived from the per-instance masks (``.any()``), and two
    mask-level invariants of the extraction are re-checked per
    instance: the guard's read mask is contained in the full read mask,
    and every reported mask has the field's declared shape (a
    wrong-shaped mask would make every element-wise intersection
    downstream silently wrong)."""
    from . import lane_map
    from .interp import traced_kernels
    findings: List[Finding] = []
    if family_reads is None:
        if effect_summary is None:
            from . import effects
            effect_summary, _f = effects.analyze(dims)
        shapes = lane_map.field_shapes(dims)
        family_reads = {}
        for inst in effect_summary.instances:
            fam = family_reads.setdefault(inst.family, set())
            fam.update(f for f, m in inst.reads.items() if m.any())
            fam.update(f for f, m in inst.guard_reads.items() if m.any())
            bad_shape = sorted(
                f for masks in (inst.reads, inst.writes, inst.guard_reads)
                for f, m in masks.items() if m.shape != shapes[f])
            if bad_shape:
                findings.append(Finding(
                    PASS, ERROR, "footprint-shape-mismatch",
                    field=inst.family, witness=inst.label,
                    message=f"{inst.label}: footprint mask(s) for "
                            f"{', '.join(bad_shape)} do not match the "
                            "declared field shape — element-wise "
                            "intersections downstream would be wrong"))
            leaked = sorted(
                f for f, m in inst.guard_reads.items()
                if bool((m & ~inst.reads.get(f, np.zeros_like(m))).any()))
            if leaked:
                findings.append(Finding(
                    PASS, ERROR, "guard-read-leak",
                    field=inst.family, witness=inst.label,
                    message=f"{inst.label}: guard reads element(s) of "
                            f"{', '.join(leaked)} missing from the "
                            "full read mask — the dependence matrix "
                            "under-approximates (POR certificates "
                            "would be unsound)"))
    n_state = len(lane_map.FIELDS)
    for name, closed, _params in traced_kernels(dims):
        syn = {lane_map.FIELDS[k]
               for k in syntactic_real_reads(closed, n_state)}
        extra = sorted(syn - set(family_reads.get(name, frozenset())))
        if extra:
            findings.append(Finding(
                PASS, ERROR, "read-set-mismatch", field=name,
                message=f"kernel {name!r} syntactically reads state "
                        f"field(s) {', '.join(extra)} that the effects "
                        "pass does not report — the taint interpreter "
                        "dropped a dependency (POR certificates would "
                        "be unsound)",
                details={"extra_reads": extra}))
    return findings


# ---------------------------------------------------------------------------
# Host-loop AST lint


def _is_blocking_read(call: ast.Call) -> Optional[str]:
    f = call.func
    if isinstance(f, ast.Attribute):
        base = f.value.id if isinstance(f.value, ast.Name) else None
        if f.attr == "block_until_ready":
            return "block_until_ready()"
        if base in ("np", "numpy") and f.attr in ("asarray", "array"):
            return f"np.{f.attr}"
        if base == "jax" and f.attr == "device_get":
            return "jax.device_get"
    elif isinstance(f, ast.Name) and f.id == "device_get":
        return "device_get"
    return None


def _is_phase_timer_with(node: ast.With) -> bool:
    for item in node.items:
        ctx = item.context_expr
        if isinstance(ctx, ast.Call):
            f = ctx.func
            name = f.attr if isinstance(f, ast.Attribute) else \
                (f.id if isinstance(f, ast.Name) else None)
            if name == "phase_timer":
                return True
    return False


def _branch_exits(stmts: Sequence[ast.stmt]) -> bool:
    """Does this if-branch leave the loop (break/return/raise anywhere
    in its subtree)?  Conservative: a nested loop's break also counts —
    acceptable, these are one-shot reporting branches either way."""
    for st in stmts:
        for n in ast.walk(st):
            if isinstance(n, (ast.Break, ast.Return, ast.Raise)):
                return True
    return False


def _scan_block(stmts, in_loop: bool, sanctioned: bool, hits: list):
    for st in stmts:
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # A nested def's loops are scanned in their own right (the
            # engines' nested helpers run inside the hot loop).
            _scan_block(st.body, in_loop, sanctioned, hits)
        elif isinstance(st, (ast.While, ast.For, ast.AsyncFor)):
            for sub in ast.walk(st.test if isinstance(st, ast.While)
                                else st.iter):
                if isinstance(sub, ast.Call):
                    kind = _is_blocking_read(sub)
                    if kind and not sanctioned:
                        hits.append((sub.lineno, kind))
            _scan_block(st.body, True, sanctioned, hits)
            _scan_block(st.orelse, in_loop, sanctioned, hits)
        elif isinstance(st, ast.With):
            _scan_block(st.body, in_loop,
                        sanctioned or _is_phase_timer_with(st), hits)
        elif isinstance(st, ast.If):
            _scan_block(st.body, in_loop,
                        sanctioned or (in_loop and _branch_exits(st.body)),
                        hits)
            _scan_block(st.orelse, in_loop,
                        sanctioned or (in_loop
                                       and _branch_exits(st.orelse)),
                        hits)
        elif isinstance(st, ast.Try):
            for blk in (st.body, st.orelse, st.finalbody):
                _scan_block(blk, in_loop, sanctioned, hits)
            for h in st.handlers:
                _scan_block(h.body, in_loop, sanctioned, hits)
        else:
            if in_loop and not sanctioned:
                for n in ast.walk(st):
                    if isinstance(n, ast.Call):
                        kind = _is_blocking_read(n)
                        if kind:
                            hits.append((n.lineno, kind))


def scan_host_loops(path: str, scope: Optional[Sequence[str]] = None
                    ) -> List[Finding]:
    """AST lint one file for blocking device reads inside loops outside
    sanctioned sync points.  ``scope`` restricts the scan to the named
    function defs (at any nesting depth); None scans the whole module."""
    with open(path, encoding="utf-8") as f:
        tree = ast.parse(f.read(), filename=path)
    roots: List[Sequence[ast.stmt]] = []
    if scope is None:
        roots.append(tree.body)
    else:
        want = set(scope)
        for n in ast.walk(tree):
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and n.name in want:
                roots.append(n.body)
    hits: List[Tuple[int, str]] = []
    for body in roots:
        _scan_block(body, in_loop=False, sanctioned=False, hits=hits)
    rel = os.path.relpath(path, start=os.getcwd()) \
        if os.path.isabs(path) else path
    return [Finding(
        PASS, ERROR, "blocking-read-in-loop", field=f"{rel}:{ln}",
        message=f"{rel}:{ln}: {kind} inside the hot loop outside a "
                "sanctioned sync point (phase_timer block or loop-exit "
                "branch) — serializes the dispatch pipeline on the TPU "
                "tunnel") for ln, kind in hits]


#: (file, scope) pairs the default scan covers: the whole shared chunk
#: body module plus the single-chip engine's steady-state loop.
def _default_targets() -> List[Tuple[str, Optional[Tuple[str, ...]]]]:
    eng = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "engine")
    return [(os.path.join(eng, "chunk.py"), None),
            (os.path.join(eng, "bfs.py"), ("_run_impl",))]


# ---------------------------------------------------------------------------
# The pass


def analyze(dims, targets=None,
            effect_summary=None) -> Tuple[dict, List[Finding]]:
    """Run all lint halves.  ``targets`` overrides the host-loop file
    list (``[(path, scope-or-None), ...]``; tests plant fixtures here);
    ``effect_summary`` reuses the effects pass's result for the read-set
    self-check when both passes run in one invocation."""
    findings: List[Finding] = []
    kernels: Dict[str, dict] = {}
    for kernel, closed in _trace_engine_kernels(dims):
        summ, fs = lint_jaxpr(closed, kernel)
        kernels[kernel] = summ
        findings.extend(fs)
    scanned = []
    for path, scope in (_default_targets() if targets is None else targets):
        findings.extend(scan_host_loops(path, scope))
        scanned.append(os.path.basename(path))
    rs = read_set_check(dims, effect_summary=effect_summary)
    findings.extend(rs)
    return {"kernels": kernels, "host_files": scanned,
            "read_set_mismatches": len(rs)}, findings
