"""Mesh-sharded simulation — TLC's ``-simulate`` worker pool on a device
mesh.

Simulation is embarrassingly parallel (SURVEY §3.4: independent random
walkers, no seen-set, no communication), so the mesh version is simply n
independent walker fleets — the same scan'd chunk program as the
single-chip Simulator (engine/simulate.py build_sim_chunk), shard_map'd
over a 1-D mesh with a distinct PRNG key per chip.  Violation latches
are per-chip; the host picks the first latched chip and replays its
(root, action sequence) through the expand kernel exactly like the
single-chip path.  Aggregate throughput scales linearly with chips —
this is the TLC ``-workers N`` analog for simulation mode.
"""

from __future__ import annotations

import time
from functools import partial
from typing import Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..engine.simulate import SimResult, Simulator, build_sim_chunk
from ..models.dims import RaftDims
from ..models.pystate import PyState


class MeshSimulator:
    """n independent walker fleets of ``batch`` walkers each."""

    def __init__(self, dims: RaftDims,
                 invariants: Optional[Dict[str, Callable]] = None,
                 constraint: Optional[Callable] = None,
                 batch: int = 256, depth: int = 100, chunk: int = 128,
                 devices=None):
        self.dims = dims
        self.inv_names = list((invariants or {}).keys())
        inv_fns = list((invariants or {}).values())
        self.batch, self.depth, self.chunk = batch, depth, chunk
        devices = devices if devices is not None else jax.devices()
        self.n_dev = n = len(devices)
        self.mesh = Mesh(np.asarray(devices), ("x",))
        chunk_fn = build_sim_chunk(dims, inv_fns, constraint, batch, depth,
                                   chunk)

        def sharded(rows, roots, tstep, cur_root, abuf, keys):
            # Leading device axis of size 1 inside shard_map.
            carry = chunk_fn(rows[0], roots, tstep[0], cur_root[0],
                             abuf[0], keys[0])
            rows_o, _roots, tstep_o, cur_root_o, abuf_o, restarts, \
                latch = carry
            vf, vinv, vroot, vlen, vacts, vchoice = latch
            return (rows_o[None], tstep_o[None], cur_root_o[None],
                    abuf_o[None], restarts[None], vf[None], vinv[None],
                    vroot[None], vlen[None], vacts[None], vchoice[None])

        shard = partial(jax.shard_map, mesh=self.mesh, check_vma=False)
        sx, rep = P("x"), P()
        self._chunk = jax.jit(shard(
            sharded,
            in_specs=(sx, rep, sx, sx, sx, sx),
            out_specs=(sx,) * 11), donate_argnums=(0, 4))

        # Root checking + replay reuse the single-chip machinery (its
        # chunk program is jit-lazy and never traced here — only
        # _roots_inv, _reconstruct, and _prepare_roots are used).
        self._single = Simulator(dims, invariants=invariants,
                                 constraint=constraint, batch=batch,
                                 depth=depth, chunk=chunk)

    # ------------------------------------------------------------------
    def run(self, roots: List[PyState], num_steps: int, seed: int = 0,
            max_seconds: Optional[float] = None) -> SimResult:
        dims, n, B, D = self.dims, self.n_dev, self.batch, self.depth
        res = SimResult()
        t0 = time.time()
        roots_np = self._single._prepare_roots(roots, res, t0)
        if roots_np is None:
            return res
        roots_j = jnp.asarray(roots_np)

        sh = NamedSharding(self.mesh, P("x"))
        key = jax.random.PRNGKey(seed)
        key, sub = jax.random.split(key)
        start = np.asarray(
            jax.random.randint(sub, (n, B), 0, len(roots))).astype(np.int32)
        rows = jax.device_put(roots_np[start], sh)
        cur_root = jax.device_put(start, sh)
        tstep = jax.device_put(np.zeros((n, B), np.int32), sh)
        abuf = jax.device_put(np.zeros((n, B, D), np.int32), sh)
        res.traces = n * B

        while res.steps < num_steps:
            key, sub = jax.random.split(key)
            keys = jax.device_put(
                np.asarray(jax.random.split(sub, n)), sh)
            out = self._chunk(rows, roots_j, tstep, cur_root, abuf, keys)
            (rows, tstep, cur_root, abuf, restarts, vf, vinv, vroot,
             vlen, vacts, vchoice) = out
            res.steps += n * B * self.chunk
            res.traces += int(np.asarray(restarts).sum())
            vf_h = np.asarray(vf)
            if vf_h.any():
                d = int(np.argmax(vf_h))
                self._single._reconstruct(
                    res, roots, int(np.asarray(vinv)[d]),
                    int(np.asarray(vroot)[d]), int(np.asarray(vlen)[d]),
                    np.asarray(vacts)[d], int(np.asarray(vchoice)[d]))
                break
            if max_seconds is not None and time.time() - t0 > max_seconds:
                break
        res.wall_seconds = time.time() - t0
        return res
