"""Mesh-sharded simulation — TLC's ``-simulate`` worker pool on a device
mesh.

Simulation is embarrassingly parallel (SURVEY §3.4: independent random
walkers, no seen-set, no communication), so the mesh version is simply n
independent walker fleets — the same scan'd chunk program as the
single-chip Simulator (engine/simulate.py build_sim_chunk), shard_map'd
over a 1-D mesh with a distinct PRNG key per chip.  Violation latches
are per-chip; the host picks the first latched chip and replays its
(root, action sequence) through the expand kernel exactly like the
single-chip path.  Aggregate throughput scales linearly with chips —
this is the TLC ``-workers N`` analog for simulation mode.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ..engine.simulate import SimResult, Simulator, build_sim_chunk
from ..models.dims import RaftDims
from ..models.pystate import PyState


class MeshSimulator:
    """n independent walker fleets of ``batch`` walkers each."""

    def __init__(self, dims: RaftDims,
                 invariants: Optional[Dict[str, Callable]] = None,
                 constraint: Optional[Callable] = None,
                 batch: int = 256, depth: int = 100, chunk: int = 128,
                 devices=None, pipeline: str = "auto", metrics=None):
        self.dims = dims
        self.inv_names = list((invariants or {}).keys())
        inv_fns = list((invariants or {}).values())
        self.batch, self.depth, self.chunk = batch, depth, chunk
        devices = devices if devices is not None else jax.devices()
        self.n_dev = n = len(devices)
        self.mesh = Mesh(np.asarray(devices), ("x",))
        chunk_fn = build_sim_chunk(dims, inv_fns, constraint, batch, depth,
                                   chunk, pipeline=pipeline)

        def sharded(rows, roots, tstep, cur_root, abuf, keys):
            # Leading device axis of size 1 inside shard_map.
            carry = chunk_fn(rows[0], roots, tstep[0], cur_root[0],
                             abuf[0], keys[0])
            rows_o, _roots, tstep_o, cur_root_o, abuf_o, restarts, \
                latch = carry
            vf, vinv, vroot, vlen, vacts, vchoice = latch
            # Everything the host READS is psum-replicated so the loop is
            # multi-controller-safe (parallel/multihost.py rules): the
            # lowest-indexed latched chip's violation wins everywhere.
            from .multihost import bcast_lowest_flagged
            (g_vf, g_vinv, g_vroot, g_vlen, g_vacts,
             g_vchoice) = bcast_lowest_flagged(
                "x", vf, vinv, vroot, vlen, vacts, vchoice)
            return (rows_o[None], tstep_o[None], cur_root_o[None],
                    abuf_o[None], jax.lax.psum(restarts, "x"),
                    g_vf, g_vinv, g_vroot, g_vlen, g_vacts, g_vchoice)

        from ..utils.platform import compat_shard_map
        shard = compat_shard_map(self.mesh)
        sx, rep = P("x"), P()
        self._chunk = jax.jit(shard(
            sharded,
            in_specs=(sx, rep, sx, sx, sx, sx),
            out_specs=(sx, sx, sx, sx) + (rep,) * 7),
            donate_argnums=(0, 4))

        # Root checking + replay reuse the single-chip machinery (its
        # chunk program is jit-lazy and never traced here — only
        # _roots_inv, _reconstruct, and _prepare_roots are used).
        self._single = Simulator(dims, invariants=invariants,
                                 constraint=constraint, batch=batch,
                                 depth=depth, chunk=chunk, metrics=metrics)
        self.metrics = self._single.metrics   # one registry, both paths

    # ------------------------------------------------------------------
    def run(self, roots: List[PyState], num_steps: int, seed: int = 0,
            max_seconds: Optional[float] = None) -> SimResult:
        from . import multihost as mh
        dims, n, B, D = self.dims, self.n_dev, self.batch, self.depth
        res = SimResult()
        t0 = time.time()
        roots_np = self._single._prepare_roots(roots, res, t0)
        if roots_np is None:
            return res
        mesh = self.mesh

        # All inputs are computed identically on every process (same seed)
        # and sharded via put_global — each process materializes only its
        # own shards, so the same code drives one host or a DCN cluster.
        key = jax.random.PRNGKey(seed)
        key, sub = jax.random.split(key)
        start = np.asarray(
            jax.random.randint(sub, (n, B), 0, len(roots))).astype(np.int32)
        roots_j = mh.put_global(roots_np, mesh, P())
        rows = mh.put_global(roots_np[start], mesh, P("x"))
        cur_root = mh.put_global(start, mesh, P("x"))
        tstep = mh.put_global(np.zeros((n, B), np.int32), mesh, P("x"))
        abuf = mh.put_global(np.zeros((n, B, D), np.int32), mesh, P("x"))
        res.traces = n * B
        # Wall clocks differ per host: a duration stop must be agreed
        # collectively or the processes' trip counts diverge and the next
        # all_to_all deadlocks (multihost.py rule 4).  The agreement round
        # trip is only paid when it can matter (multi-process AND a
        # duration budget; max_seconds is identical everywhere, so the
        # gate itself is collective-safe).
        any_flag = (mh.build_any(mesh)
                    if mh.is_multiprocess() and max_seconds is not None
                    else None)

        mt = self.metrics
        while res.steps < num_steps:
            key, sub = jax.random.split(key)
            keys = mh.put_global(np.asarray(jax.random.split(sub, n)),
                                 mesh, P("x"))
            with mt.phase_timer("sim_chunk"):
                out = self._chunk(rows, roots_j, tstep, cur_root, abuf,
                                  keys)
            (rows, tstep, cur_root, abuf, g_restarts, g_vf, g_vinv,
             g_vroot, g_vlen, g_vacts, g_vchoice) = out
            res.steps += n * B * self.chunk
            with mt.phase_timer("sim_fetch"):
                res.traces += int(np.asarray(g_restarts))
            mt.counter("sim/steps", n * B * self.chunk)
            mt.gauge("sim/traces", res.traces)
            if bool(np.asarray(g_vf)):
                self._single._reconstruct(
                    res, roots, int(np.asarray(g_vinv)),
                    int(np.asarray(g_vroot)), int(np.asarray(g_vlen)),
                    np.asarray(g_vacts), int(np.asarray(g_vchoice)))
                break
            over = (max_seconds is not None
                    and time.time() - t0 > max_seconds)
            if any_flag is not None:
                over = any_flag(over)
            if over:
                break
        res.wall_seconds = time.time() - t0
        return res
