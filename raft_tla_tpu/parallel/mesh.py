"""Mesh-sharded BFS — distributed TLC over a jax device mesh.

TLC scales with a multi-threaded worker pool and an RMI-based distributed
mode [TLC semantics — external; SURVEY §2.4 R7].  The TPU-native equivalent
shards the level-synchronous BFS over a 1-D ``jax.sharding.Mesh`` with
``shard_map``; collectives ride ICI (and DCN across hosts, transparently —
the program is identical):

- the frontier queue, next-level queue, and FPSet are sharded per chip;
- each chip expands its local batch and fingerprints its candidates;
- **fingerprint-owner dedup**: candidate fps are routed to their owner chip
  (``fp_hi mod n``) with one ``all_to_all``; the owner runs the same
  batched hash-table insert (ops/fpset.py) as the single-chip engine on the
  union of arriving queries, then a reverse ``all_to_all`` returns one
  novelty bit per query.  Exactly one copy of each globally-new state gets
  the bit, so states enqueue on the chip that *generated* them — only
  8-byte fingerprints ever cross the interconnect, never state rows;
- stats (new/generated/overflow/deadlock/violation) combine with ``psum``.

Runtime parity with the single-chip engine (engine/bfs.py):

- **device-resident chunk loop**: up to ``sync_every`` batches run per host
  round-trip inside a ``lax.while_loop`` whose continue condition is a
  replicated psum-reduction (all chips iterate in lockstep — a collective
  inside the body requires every chip to take the same trip count);
- **host spill**: when any chip's next-level queue passes its watermark the
  chunk exits and the host drains ALL chips' queues into one host pool
  (TLC's disk queue); pool segments re-upload *balanced* across chips, so
  spill doubles as load rebalancing;
- **seen-set growth**: when any shard passes half load the host pulls its
  keys and rebuilds every shard at double capacity (owner = fp mod n is
  unchanged, so keys stay on their chips);
- **checkpoint/resume**: level-boundary snapshots in the SAME format as the
  single-chip engine (frontier rows + flat key set) — a run checkpointed on
  the mesh can resume single-chip and vice versa; the key→owner and
  frontier layouts are recomputed on load, so even the device count may
  change across a resume.

Tested on a virtual 8-device CPU mesh (SURVEY §4.5); the program is
identical on a real TPU slice.
"""

from __future__ import annotations

import contextlib
import os
import time
from typing import Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..engine.chunk import build_chunk_body
from ..engine.bfs import (EngineConfig, EngineResult, TraceStore, Violation,
                          _exit_condition_hit, _family_groups_meta,
                          _progress_line, build_root_check,
                          find_root_violation, make_trace_store)
from ..models.actions import build_expand
from ..models.dims import RaftDims
from ..models.invariants import build_inv_id
from ..models.pystate import PyState
from ..models.schema import (ROW_DTYPE, build_pack_guard, check_packable,
                             decode_state, encode_state, flatten_state,
                             state_width, unflatten_state)
from ..obs import MetricsRegistry, RunEventLog, events_path
from ..obs.flight import RECORDER as _flight_rec
from ..ops import compact as compact_mod
from ..ops import fpset
from ..ops.fingerprint import SENTINEL, build_fingerprint
from ..resilience import faults as _faults

_I32 = jnp.int32
_U32 = jnp.uint32


class MeshBFSEngine:
    """Exhaustive checker sharded over an n-device mesh."""

    def __init__(self, dims: RaftDims,
                 invariants: Optional[Dict[str, Callable]] = None,
                 constraint: Optional[Callable] = None,
                 config: Optional[EngineConfig] = None,
                 devices=None):
        self.dims = dims
        self.config = config or EngineConfig()
        cfg = self.config
        # Telemetry spine (obs/), shared with the single-chip engine.
        # ``_rebuild_programs`` re-enters __init__ MID-RUN (seen-set
        # growth), so an existing registry and open event log must
        # survive the re-init — losing them would silently drop every
        # phase total and event recorded before the first growth.
        self.metrics = (cfg.metrics or getattr(self, "metrics", None)
                        or MetricsRegistry())
        if not hasattr(self, "_evlog"):
            self._evlog = RunEventLog(None)
            self._phase_base = {}
        # Span tracer (obs/tracing.py): survives the re-entrant re-init
        # like the registry; attached to the registry it mirrors every
        # phase_timer block into a Chrome-trace span.  Multi-host runs
        # get one trace per controller (piece suffix, like event logs).
        if not hasattr(self, "tracer"):
            from ..obs import SpanTracer
            trace_out = cfg.trace_out
            if trace_out is not None:
                try:
                    pi, pc = jax.process_index(), jax.process_count()
                except Exception:
                    pi, pc = 0, 1
                if pc > 1:
                    root, ext = os.path.splitext(trace_out)
                    trace_out = f"{root}.p{pi}of{pc}{ext or '.json'}"
            self.tracer = SpanTracer(trace_out)
        self.metrics.tracer = self.tracer
        # The per-stage chunk profiler is a single-chip instrument
        # (EngineConfig.profile_chunks_every rationale); the mesh's
        # observability is spans + phases + coverage.
        self._profiler = None
        if cfg.checkpoint_dir:
            # Fail at construction, not at the first level-boundary write.
            from ..engine import checkpoint as _ckpt
            _ckpt.check_dims_checkpointable(dims)
        if cfg.insert_method != "xla":
            # The shard-local insert runs inside shard_map; the Pallas
            # lowering is a single-host experiment (NORTHSTAR.md §d) and
            # must not be silently ignored here.
            raise NotImplementedError(
                "MeshEngine supports insert_method='xla' only")
        devices = devices if devices is not None else jax.devices()
        self.n_dev = n = len(devices)
        self.mesh = Mesh(np.asarray(devices), ("x",))
        self.inv_names = list((invariants or {}).keys())
        self._inv_fns = inv_fns = list((invariants or {}).values())
        self._constraint = constraint
        expand = build_expand(dims)
        fingerprint = build_fingerprint(dims)
        pack_ok = build_pack_guard(dims)
        from ..engine.bfs import (_resolve_pipeline, por_device_arrays,
                                  resolve_por)
        self._v2 = _resolve_pipeline(cfg.pipeline, dims)
        # POR reduction table (analysis/por.py): resolved/verified once
        # on the host; the [G] mask/priority arrays are closed over by
        # the chunk body below, so shard_map replicates them to every
        # chip (the mask broadcast) — each chip applies the identical
        # reduction, keeping the engines' bit-identical-per-batch
        # contract intact.
        if not hasattr(self, "_por_table"):   # growth-path re-init reuses
            self._por_table = resolve_por(
                cfg, dims, dict(zip(self.inv_names, inv_fns)), constraint)
        por_mask, por_priority = por_device_arrays(self._por_table)
        sw = state_width(dims)
        B, G = cfg.batch, dims.n_instances
        # Compacted-candidate lanes per chip (ops/compact.py): only K
        # lanes go through owner routing, the hash insert, row
        # materialization, and enqueue — and only K fingerprints per chip
        # cross the ICI per batch, not B*G.
        K = compact_mod.choose_k(B, G, cfg.compact_lanes)
        self._check_deadlock = (True if cfg.check_deadlock is None
                                else cfg.check_deadlock)
        # Per-chip capacities; None resolves through the same HBM
        # auto-sizing as the single-chip engine (per-chip budget).
        from ..engine.bfs import _auto_capacities
        qreq, sreq = cfg.queue_capacity, cfg.seen_capacity
        if qreq is None or sreq is None:
            auto_q, auto_s = _auto_capacities(sw, B, cfg.record_trace)
            qreq = auto_q if qreq is None else qreq
            sreq = auto_s if sreq is None else sreq
        # Queue: batch-multiple, floored at one worst-case batch (K new
        # rows) — a batch can never overflow mid-chunk; the watermark
        # below spills *between* batches (engine/bfs.py invariant).  The
        # allocation carries PAD extra rows: B of slice overrun + K of
        # scatter trash (distinct per-lane addresses for masked-off
        # enqueue lanes — ops/fpset.py design note 3).
        per_chip = -(-qreq // n)
        QL = max(-(-per_chip // B) * B, K)
        PAD = max(B, K)
        # Seen shard: each chip receives up to n*K owner-routed queries
        # per batch in the worst case, but only ~K on average; the same
        # 8-batch floor as the single-chip engine keeps the growth
        # threshold (half load) safely ahead of probe failure.
        CL = fpset._capacity(max(-(-sreq // n), 8 * K))
        self._sw, self._B, self._G, self._QL, self._CL = sw, B, G, QL, CL
        self._K, self._PAD = K, PAD
        self._QTH = QL - K
        CH = self._CH = max(1, cfg.sync_every)
        record_static = cfg.record_trace
        TQ = QL + K if record_static else 8
        self._TQ = TQ
        self._TA = TQ + K if record_static else 8
        check_deadlock_static = self._check_deadlock
        # pmin keeps every chip's offset advance identical — the chunk
        # body contains collectives, so trip counts must agree.
        compactor = compact_mod.build_compactor(
            B, G, K, reduce_p=lambda p: jax.lax.pmin(p, "x"),
            method=cfg.compact_method)

        def route_insert(seen_local, fph, fpl, valid):
            """Cross-chip owner dedup: route each valid fingerprint to its
            owner chip (fp_hi mod n) with one all_to_all, insert the union
            of arrivals into the local shard, route the novelty bits back.
            Exactly one copy of each globally-new key (across all chips)
            gets the bit."""
            k = fph.shape[0]
            fph = jnp.where(valid, fph, SENTINEL)
            fpl = jnp.where(valid, fpl, SENTINEL)
            owner = (fph % _U32(n)).astype(_I32)
            perm = jnp.argsort(owner, stable=True)
            osort = owner[perm]
            q_hi, q_lo = fph[perm], fpl[perm]
            block_start = jnp.searchsorted(osort, jnp.arange(n, dtype=_I32))
            rank = jnp.arange(k, dtype=_I32) - block_start[osort]
            bh = jnp.full((n, k), SENTINEL, _U32).at[osort, rank].set(q_hi)
            bl = jnp.full((n, k), SENTINEL, _U32).at[osort, rank].set(q_lo)
            bh = jax.lax.all_to_all(bh, "x", 0, 0, tiled=True)
            bl = jax.lax.all_to_all(bl, "x", 0, 0, tiled=True)
            rh, rl = bh.reshape(-1), bl.reshape(-1)
            rvalid = ~((rh == SENTINEL) & (rl == SENTINEL))
            seen_local, qnew, fail = fpset.insert(seen_local, rh, rl, rvalid)
            nov = jax.lax.all_to_all(qnew.reshape(n, k), "x", 0, 0,
                                     tiled=True)
            new_sortpos = nov[osort, rank]
            new = jnp.zeros((k,), bool).at[perm].set(new_sortpos)
            return seen_local, new, fail

        def local_absorb(crows, cands, en, parent_hi, parent_lo, actions,
                         qnext, next_count, seen_local, tbuf, tcount):
            """Per-chip tail with cross-chip owner dedup.  All arrays are
            this chip's shard (no leading device axis).  Ingest-sized (k
            <= B); the chunk path below compacts first."""
            k = crows.shape[0]
            fph, fpl = jax.vmap(fingerprint)(cands)
            seen_local, new, fail = route_insert(seen_local, fph, fpl, en)
            fph = jnp.where(en, fph, SENTINEL)
            fpl = jnp.where(en, fpl, SENTINEL)

            n_new = jnp.sum(new, dtype=_I32)      # local share of global new

            if inv_fns:
                inv = jax.vmap(build_inv_id(inv_fns))(cands)
            else:
                inv = jnp.full((k,), -1, _I32)
            viol = new & (inv >= 0)
            viol_any = jnp.any(viol)
            vpos = jnp.argmax(viol)

            if constraint is not None:
                cons_ok = jax.vmap(constraint)(cands)
            else:
                cons_ok = jnp.ones((k,), bool)
            enq = new & cons_ok
            pos = next_count + jnp.cumsum(enq.astype(_I32)) - 1
            # Per-lane trash rows past QL (PAD = max(B, K) >= k): a single
            # shared trash index serializes the scatter on TPU (ops/fpset.py
            # design note 3).
            pos = jnp.where(enq, pos, QL + jnp.arange(k, dtype=_I32))
            qnext = qnext.at[pos].set(crows, mode="drop")
            next_count = next_count + jnp.sum(enq, dtype=_I32)

            if record_static:
                tpos = jnp.where(
                    new, tcount + jnp.cumsum(new.astype(_I32)) - 1,
                    TQ + jnp.arange(k, dtype=_I32))  # TA = TQ + K >= TQ + k
                tbuf = tuple(
                    buf.at[tpos].set(col, mode="drop")
                    for buf, col in zip(
                        tbuf, (fph, fpl, parent_hi, parent_lo, actions)))
                tcount = tcount + n_new

            vinfo = (viol_any, inv[vpos], crows[vpos], fph[vpos], fpl[vpos])
            return (qnext, next_count, seen_local, tbuf, tcount, n_new,
                    fail, vinfo)

        # v3 on the mesh: the collective-coupled stages (pmin-replicated
        # compact, owner-routed insert) stay XLA by design — the plan
        # records why — and the enqueue stage rides the Pallas
        # run-coalesced append inside shard_map.  Bit-identical either
        # way (the engines' shared-body contract).
        enqueue_method = cfg.enqueue_method
        if cfg.pipeline == "v3":
            from ..ops import pipeline_v3
            self._v3_plan = pipeline_v3.resolve_plan(
                B, G, K, Q=QL, sw=sw, mesh=True,
                enqueue_method=cfg.enqueue_method,
                force=cfg.v3_force_stages)
            enqueue_method = self._v3_plan.enqueue_method
        elif cfg.pipeline == "v4":
            # v4 on the mesh degrades to the v3 arrangement (the plan
            # records why: the front's compact P is pmin-replicated and
            # the dedup is an all_to_all — collectives cannot live in
            # the megakernels), so front/tail stay None here.
            from ..ops import pipeline_v4
            self._v3_plan = pipeline_v4.resolve_plan(
                B, G, K, Q=QL, sw=sw, mesh=True,
                enqueue_method=cfg.enqueue_method,
                force=cfg.v4_force_stages)
            enqueue_method = self._v3_plan.enqueue_method
        else:
            self._v3_plan = None

        # The per-batch pipeline body is shared with the single-chip
        # engine (engine/chunk.py); here the insert routes fingerprints
        # to their owner chips, and P is pmin-replicated via the
        # compactor's reduce_p hook so all chips advance in lockstep.
        chunk_body = build_chunk_body(
            dims=dims, expand=expand, fingerprint=fingerprint,
            pack_ok=pack_ok, inv_fns=inv_fns, constraint=constraint,
            B=B, G=G, K=K, Q=QL, TQ=TQ, record_static=record_static,
            compactor=compactor, insert_fn=route_insert, v2=self._v2,
            enqueue_method=enqueue_method,
            por_mask=por_mask, por_priority=por_priority)

        def sharded_chunk(qcur, cur_counts, offset0, qnext, next_counts,
                          shi, slo, ssize, tbuf, tcount0, max_steps):
            # Shapes inside shard_map: leading device axis of size 1.
            qcur_l, qnext_l = qcur[0], qnext[0]
            cnt_l, ncnt_l = cur_counts[0], next_counts[0]
            # The level width is derived IN-program (pmax over chips), so
            # the host never needs a global view of the per-chip counts —
            # a multi-controller requirement (parallel/multihost.py).
            max_count = jax.lax.pmax(cnt_l, "x")
            seen_l = fpset.FPSet(hi=shi[0], lo=slo[0], size=ssize[0])
            tbuf_l = tuple(t[0] for t in tbuf)
            init = (offset0, jnp.int32(0), qnext_l, ncnt_l, seen_l, tbuf_l,
                    tcount0[0], jnp.int32(0), jnp.int32(0), jnp.int32(0),
                    jnp.bool_(False), jnp.zeros((sw,), jnp.uint8),
                    jnp.bool_(False), jnp.int32(-1),
                    jnp.zeros((sw,), jnp.uint8),
                    jnp.uint32(0), jnp.uint32(0), jnp.bool_(False),
                    jnp.zeros((len(dims.family_sizes),), _I32),
                    jnp.zeros((len(dims.family_sizes),), _I32),
                    jnp.int32(0),
                    jnp.zeros((len(dims.family_sizes),), _I32))

            def cond(c):
                (offset, steps, _qn, ncnt_c, seen_c, _tb, tcnt_c,
                 _g, _n, ovfc, dead_any, _dr, viol_any, _vi, _vr, _vh,
                 _vl, fail_any, _fam, _famn, _exp, _famp) = c
                # Every term is reduced to a REPLICATED bool so all chips
                # take the same trip count (the body contains all_to_all).
                more = (offset < max_count) & (steps < max_steps)
                blocked = (ncnt_c > QL - K).astype(_I32) \
                    + (seen_c.size > CL // 2).astype(_I32)
                stop = viol_any.astype(_I32) + (ovfc > 0).astype(_I32) \
                    + fail_any.astype(_I32)
                if check_deadlock_static:
                    stop = stop + dead_any.astype(_I32)
                if record_static:
                    blocked = blocked + (tcnt_c > TQ - K).astype(_I32)
                return more & (jax.lax.psum(blocked + stop, "x") == 0)

            out = jax.lax.while_loop(
                cond, lambda c: chunk_body(qcur_l, cnt_l, c), init)
            (offset, steps, qnext_l, ncnt_l, seen_l, tbuf_l, tcnt_l,
             gen, newc, ovfc, dead_any, drow, viol_any, vinv, vrow,
             vhi, vlo, fail_any, fam_counts, fam_new, expanded,
             fam_pruned) = out
            g_gen = jax.lax.psum(gen, "x")
            g_new = jax.lax.psum(newc, "x")
            g_ovf = jax.lax.psum(ovfc, "x")
            g_fail = jax.lax.psum(fail_any.astype(_I32), "x")
            # Violation/deadlock rows are broadcast from the lowest-indexed
            # flagged chip so EVERY host reads identical replicated values
            # — no per-chip inspection on the host side.
            from .multihost import bcast_lowest_flagged
            v_any, vinv_g, vrow_g, vhi_g, vlo_g = bcast_lowest_flagged(
                "x", viol_any, vinv, vrow, vhi, vlo)
            d_any, drow_g = bcast_lowest_flagged("x", dead_any, drow)

            # Packed replicated stats: one host fetch per call
            # (engine/bfs.py contract).  Layout documented at the read
            # site in run().
            stats = jnp.concatenate([
                jnp.stack([offset, steps, g_gen, g_new, g_ovf, g_fail,
                           max_count,
                           jax.lax.pmax(ncnt_l, "x"),
                           jax.lax.psum(ncnt_l, "x"),
                           jax.lax.psum(
                               jnp.maximum(cnt_l - offset, 0), "x"),
                           jax.lax.pmax(seen_l.size, "x"),
                           v_any.astype(_I32),
                           d_any.astype(_I32),
                           vinv_g,
                           jax.lax.psum(cnt_l, "x"),
                           jax.lax.psum(expanded, "x")]),
                jax.lax.psum(fam_counts, "x"),
                jax.lax.psum(fam_new, "x"),
                jax.lax.psum(fam_pruned, "x")])
            vfp_g = jnp.stack([vhi_g, vlo_g])
            return (qnext_l[None], ncnt_l[None], seen_l.hi[None],
                    seen_l.lo[None], seen_l.size[None],
                    tuple(t[None] for t in tbuf_l), tcnt_l[None],
                    stats, drow_g, vrow_g, vfp_g)

        def sharded_ingest(rows, valid, qnext, next_counts, shi, slo, ssize,
                           tbuf, tcount0):
            rows_l, valid_l = rows[0], valid[0]
            states = jax.vmap(unflatten_state, (0, None))(rows_l, dims)
            sent = jnp.zeros(rows_l.shape[:1], _U32)
            acts = jnp.full(rows_l.shape[:1], -1, _I32)
            seen_l = fpset.FPSet(hi=shi[0], lo=slo[0], size=ssize[0])
            tbuf_l = tuple(t[0] for t in tbuf)
            (qnext_l, ncnt_l, seen_l, tbuf_l, tcnt_l, n_new, fail,
             vinfo) = local_absorb(
                rows_l, states, valid_l, sent, sent, acts,
                qnext[0], next_counts[0], seen_l, tbuf_l, tcount0[0])
            viol_any, vinv, vrow, vhi, vlo = vinfo
            # Replicated stats + lowest-flagged-chip violation broadcast
            # (sharded_chunk rationale): the host reads no per-chip values.
            from .multihost import bcast_lowest_flagged
            v_any, vinv_g, vrow_g, vhi_g, vlo_g = bcast_lowest_flagged(
                "x", viol_any, vinv, vrow, vhi, vlo)
            stats = jnp.stack([
                jax.lax.psum(n_new, "x"),
                jax.lax.psum(fail.astype(_I32), "x"),
                jax.lax.pmax(ncnt_l, "x"),
                jax.lax.psum(ncnt_l, "x"),
                v_any.astype(_I32),
                vinv_g,
                jax.lax.pmax(seen_l.size, "x")])
            vfp = jnp.stack([vhi_g, vlo_g])
            return (qnext_l[None], ncnt_l[None], seen_l.hi[None],
                    seen_l.lo[None], seen_l.size[None],
                    tuple(t[None] for t in tbuf_l), tcnt_l[None],
                    stats, vrow_g, vfp)

        from ..utils.platform import compat_shard_map
        shard = compat_shard_map(self.mesh)
        sx = P("x")
        rep = P()
        self._chunk = jax.jit(shard(
            sharded_chunk,
            in_specs=(sx, sx, rep, sx, sx, sx, sx, sx, sx, sx, rep),
            out_specs=(sx, sx, sx, sx, sx, (sx,) * 5, sx, rep, rep, rep,
                       rep)),
            donate_argnums=(3, 5, 6, 7, 8))
        self._ingest = jax.jit(shard(
            sharded_ingest,
            in_specs=(sx, sx, sx, sx, sx, sx, sx, sx, sx),
            out_specs=(sx, sx, sx, sx, sx, (sx,) * 5, sx, rep, rep, rep)),
            donate_argnums=(2, 4, 5, 6, 7))
        # Performance observatory (obs/perf.py; EngineConfig.perf):
        # launch model from THE sharded chunk program just built — the
        # walk recurses through shard_map, so collectives (all_to_all
        # owner routing, psum'd stats) are counted per batch alongside
        # the device ops.  The roofline's per-stage measured half is a
        # single-chip instrument (the profiler rationale above), so the
        # mesh block carries launch accounting + the modeled collective
        # share, not stage fractions.  Fail-soft like the single-chip
        # engine.
        self._last_skew = None
        self._perf = None
        if cfg.perf:
            from ..obs import perf as perf_mod
            i32s = jax.ShapeDtypeStruct((n,), _I32)
            scalar = jax.ShapeDtypeStruct((), _I32)
            qav = jax.ShapeDtypeStruct((n, QL + PAD, sw), jnp.uint8)
            sh_av = jax.ShapeDtypeStruct((n, self._CL), _U32)
            tbuf_av = tuple(
                jax.ShapeDtypeStruct((n, self._TA), d)
                for d in (jnp.uint32, jnp.uint32, jnp.uint32,
                          jnp.uint32, _I32))
            self._perf = perf_mod.build_accounting(
                pipeline=(cfg.pipeline
                          if cfg.pipeline in ("v3", "v4")
                          else "v2" if self._v2 is not None
                          else "v1"),
                chunk_fn=self._chunk,
                chunk_avals=(qav, i32s, scalar, qav, i32s, sh_av,
                             sh_av, i32s, tbuf_av, i32s, scalar),
                plan=self._v3_plan, with_stages=False,
                metrics=self.metrics, engine="mesh")

        def fp_rows(rows):
            return jax.vmap(fingerprint)(
                jax.vmap(unflatten_state, (0, None))(rows, dims))

        self._fp_rows = jax.jit(fp_rows)
        self._expand1 = jax.jit(expand)
        self._fp_batch = jax.jit(jax.vmap(fingerprint))
        self._root_check = (build_root_check(inv_fns, fingerprint)
                            if inv_fns else None)

    # ------------------------------------------------------------------
    def _grow_seen(self, shi, slo, ssize, new_cl=None):
        """Rebuild this controller's shards at double (or given) capacity.
        Owner assignment (fp_hi mod n) is capacity-independent, so keys
        stay on their chips; every controller rehashes only its
        addressable shards and the arrays are reassembled shard-by-shard
        (multi-controller rule 3).  The chunk program recompiles for the
        new shape — identically everywhere."""
        n = self.n_dev
        new_cl = fpset._capacity(new_cl or 2 * self._CL)

        def by_row(arr):
            return {s.index[0].start: np.asarray(s.data)[0]
                    for s in arr.addressable_shards}

        his, los = by_row(shi), by_row(slo)
        hi_b, lo_b, sz_b = {}, {}, {}
        for d, hi_h in his.items():
            lo_h = los[d]
            real = ~((hi_h == SENTINEL) & (lo_h == SENTINEL))
            s = fpset.from_host_keys(hi_h[real], lo_h[real], new_cl)
            hi_b[d] = np.asarray(s.hi)[None]
            lo_b[d] = np.asarray(s.lo)[None]
            sz_b[d] = np.asarray(s.size, np.int32).reshape(1)
        self._CL = new_cl
        self._rebuild_programs()
        return self._assemble_sharded_fpset(hi_b, lo_b, sz_b)

    def _assemble_sharded_fpset(self, hi_b, lo_b, sz_b):
        """(shi, slo, ssize) sharded arrays from per-LOCAL-device host
        shards ({global chip row -> [1, CL] / [1] arrays}); other
        controllers supply their own rows via the same callbacks."""
        n, cl = self.n_dev, self._CL
        sh = NamedSharding(self.mesh, P("x"))
        return (jax.make_array_from_callback(
                    (n, cl), sh, lambda idx: hi_b[idx[0].start]),
                jax.make_array_from_callback(
                    (n, cl), sh, lambda idx: lo_b[idx[0].start]),
                jax.make_array_from_callback(
                    (n,), sh, lambda idx: sz_b[idx[0].start]))

    def _shards_from_keys(self, keys_hi, keys_lo):
        """Rebuild the sharded FPSet arrays from a global flat key set
        (owner = fp_hi mod n); each controller materializes only its
        addressable shards, shard-by-shard (never the whole n-chip table
        on one device)."""
        owner = (keys_hi % self.n_dev).astype(np.int64)
        me = jax.process_index()
        hi_b, lo_b, sz_b = {}, {}, {}
        for d in (i for i, dev in enumerate(self.mesh.devices.flat)
                  if dev.process_index == me):
            sel = owner == d
            s = fpset.from_host_keys(keys_hi[sel].astype(np.uint32),
                                     keys_lo[sel].astype(np.uint32),
                                     self._CL)
            hi_b[d] = np.asarray(s.hi)[None]
            lo_b[d] = np.asarray(s.lo)[None]
            sz_b[d] = np.asarray(s.size, np.int32).reshape(1)
        return self._assemble_sharded_fpset(hi_b, lo_b, sz_b)

    def _rebuild_programs(self):
        """Re-trace chunk/ingest for a changed seen-shard shape."""
        MeshBFSEngine.__init__(
            self, self.dims,
            invariants=dict(zip(self.inv_names, self._inv_fns)),
            constraint=self._constraint,
            config=self._cfg_with_seen(self._CL * self.n_dev),
            devices=list(self.mesh.devices.ravel()))

    def _cfg_with_seen(self, total):
        import dataclasses as _dc
        return _dc.replace(self.config, seen_capacity=total)

    # ------------------------------------------------------------------
    def run(self, init_states: Optional[List[PyState]] = None,
            resume=None) -> EngineResult:
        """Telemetry wrapper (engine/bfs.py rationale): run_start/run_end
        events bracket the run, phases are scoped to it.  Shared via duck
        typing, like replay() — as is the OOM degradation wrapper
        (single-controller only; a process group re-raises and the
        supervisor restarts the whole fleet)."""
        from ..engine.bfs import BFSEngine

        def impl(states, resume=None):
            return BFSEngine._run_degradable(self, states, resume=resume)

        return BFSEngine._telemetry_run(self, impl, init_states,
                                        resume=resume)

    def _rebuild_at_batch(self, new_batch: int) -> None:
        """Recompile the mesh programs at a smaller batch (the re-entrant
        __init__ path growth already uses); registry/event log survive."""
        import dataclasses as _dc
        MeshBFSEngine.__init__(
            self, self.dims,
            invariants=dict(zip(self.inv_names, self._inv_fns)),
            constraint=self._constraint,
            config=_dc.replace(self.config, batch=new_batch),
            devices=list(self.mesh.devices.ravel()))

    def _events_path(self):
        """One event-log piece per controller (multi-host checkpoint
        model); single-controller resolution is unchanged."""
        return events_path(self.config.events_out,
                           self.config.checkpoint_dir,
                           jax.process_index(), jax.process_count())

    def _postmortem_path(self):
        """One postmortem piece per controller (the event-log model):
        two crashing controllers on a shared filesystem must never race
        one dump file."""
        from ..engine.bfs import BFSEngine
        base = BFSEngine._postmortem_path(self)
        if base is None:
            return None
        return events_path(base, None, jax.process_index(),
                           jax.process_count())

    def _xla_profile_dir(self):
        from ..engine.bfs import BFSEngine
        return BFSEngine._xla_profile_dir(self)

    def _emit_level_event(self, res, frontier_rows):
        from ..engine.bfs import BFSEngine
        BFSEngine._emit_level_event(self, res, frontier_rows)

    def _sample_skew(self, res, next_counts, ssize) -> None:
        """Per-shard balance telemetry, sampled at each level boundary
        (ROADMAP item 5's first observability surface): this
        controller's shard next-level counts and seen-set sizes ->
        ``mesh/*`` balance gauges, skew fields on the level_complete
        event (via ``_last_skew``, read by the shared emit), and a
        ``skew`` WARNING event when max/mean frontier imbalance reaches
        ``EngineConfig.skew_warn_ratio``.  Host-side reads of a handful
        of addressable-shard ints per level — observational by
        construction (bit-identity asserted in tests/test_perf.py).
        Caveats: under a process group each controller samples its own
        shards (the union is the global picture, one event log piece
        each); a level whose rows were already drained to the host pool
        samples the device-resident remainder only.

        With ``--perf`` on, also times one psum agreement round (the
        collective-latency probe behind the perf block's modeled
        collective share) — that half is gated: it costs a compile +
        a collective round, unlike the free shard reads."""
        try:
            fr = self._local_counts(next_counts)
            sz = self._local_counts(ssize)
        except Exception:
            self._last_skew = None
            return
        vals = [int(v) for _k, v in sorted(fr.items())]
        sizes = [int(v) for _k, v in sorted(sz.items())]

        def ratio(xs):
            mean = sum(xs) / len(xs) if xs else 0.0
            return round(max(xs) / mean, 4) if mean > 0 else None

        fsk, ssk = ratio(vals), ratio(sizes)
        mt = self.metrics
        if vals:
            mt.gauge("mesh/shard_frontier_max", max(vals))
            mt.gauge("mesh/shard_frontier_min", min(vals))
        if fsk is not None:
            mt.gauge("mesh/frontier_skew", fsk)
        if sizes:
            mt.gauge("mesh/shard_seen_max", max(sizes))
        if ssk is not None:
            mt.gauge("mesh/seen_skew", ssk)
        self._last_skew = {"frontier_skew": fsk, "seen_skew": ssk,
                           "shard_frontier": vals, "shard_seen": sizes}
        thr = self.config.skew_warn_ratio
        if fsk is not None and thr and fsk >= thr:
            mt.counter("mesh/skew_warnings")
            self._evlog.emit("skew", balance={
                "level": res.diameter, "frontier_skew": fsk,
                "seen_skew": ssk, "shard_frontier": vals,
                "threshold": thr})
        if self._perf is not None:
            try:
                if not hasattr(self, "_psum_probe"):
                    from . import multihost as mh
                    self._psum_probe = mh.build_sum(self.mesh)
                    self._psum_probe(1)   # warm once: compile off the
                from ..obs import perf as perf_mod  # timed samples
                self._perf.note_collective_probe(
                    perf_mod.timed_collective_probe(self._psum_probe, 1,
                                                    warm=False))
            except Exception:
                pass                 # the probe is a nicety, never fatal

    def _counterexample_base(self) -> str:
        """Per-controller counterexample file stem (the event-log piece
        model): under a process group every controller renders — each
        merged its siblings' trace pieces at replay, so the contents
        agree — but two controllers must never race one filename on the
        shared filesystem.  Single-controller resolution is unchanged."""
        if jax.process_count() <= 1:
            return "counterexample"
        return (f"counterexample.p{jax.process_index()}"
                f"of{jax.process_count()}")

    def _run_impl(self, init_states: Optional[List[PyState]] = None,
                  resume=None) -> EngineResult:
        from ..engine import checkpoint as ckpt_mod
        from . import multihost as mh
        dims, cfg = self.dims, self.config
        n, sw, B, QL = self.n_dev, self._sw, self._B, self._QL
        if resume is not None and isinstance(resume, str):
            resume_path = resume
            resume = ckpt_mod.load(resume)
            if mh.is_multiprocess():
                # latest() reads a host-local directory listing, which can
                # lag on a shared filesystem (NFS attribute caching) — all
                # controllers must resume the SAME snapshot or the
                # replicated counters diverge (multihost.py rule 4).  The
                # oldest level any controller found is the safe agreement.
                agreed = mh.build_min(self.mesh)(resume.diameter)
                if agreed != resume.diameter:
                    import glob as _glob
                    import os as _os
                    d = _os.path.dirname(_os.path.abspath(resume_path))
                    # The agreed level's snapshot may be a piece group
                    # from ANY writer count (load() resolves siblings
                    # from any one piece) or a single file.
                    cands = sorted(_glob.glob(_os.path.join(
                        d, f"level_{agreed:05d}.p0of*.npz")))
                    alt = cands[0] if cands else _os.path.join(
                        d, f"level_{agreed:05d}.npz")
                    resume = ckpt_mod.load(alt)
        if resume is not None and resume.dims != dims:
            raise ValueError(
                f"checkpoint dims {resume.dims} != engine dims {dims}")
        if resume is None and init_states is None:
            raise ValueError("need init_states or resume")
        mp = mh.is_multiprocess()
        if mp:
            # Multi-controller trace recording: each controller's store
            # accumulates its own chips' records (_flush_trace) and the
            # stores are exchanged as per-controller piece files on the
            # shared filesystem (same R8 assumption as multi-host
            # checkpoints), merged lazily at replay().  That exchange
            # needs a directory every controller can see — require the
            # checkpoint_dir rather than silently recording a trace no
            # replay could complete.
            if cfg.record_trace and not (cfg.trace_dir
                                         or cfg.checkpoint_dir):
                raise NotImplementedError(
                    "multi-host trace recording needs trace_dir (or "
                    "checkpoint_dir) — a shared filesystem path, as for "
                    "multi-host checkpoints: controllers exchange their "
                    "trace stores as piece files there.  Alternatively "
                    "run with record_trace=False and pass the "
                    "violation's .state to engine.check.path_to_state "
                    "on one host — BFS order makes the result a "
                    "minimal-depth trace")
        # Collective agreement on host-local facts (clocks); identical-
        # everywhere decisions skip the round trip (multihost.py rule 4).
        any_flag = mh.build_any(self.mesh) if mp else None
        budget_agree = mh.build_budget_agree(self.mesh) if mp else None
        # TLCGet("queue") consults the per-controller pools; under a
        # process group the totals are psum-agreed (one extra round trip
        # per check — only paid when a queue budget is actually set).
        has_queue_budget = any(c == "queue" for c, _t in cfg.exit_conditions)
        pool_sum = (mh.build_sum(self.mesh)
                    if mp and has_queue_budget else None)
        if mp and cfg.record_trace:
            # Per-run piece-file id, agreed across controllers (min of
            # local clocks): a reused trace/checkpoint directory can
            # then never alias this run's pieces with a previous run's.
            # int32 — the agreement primitive's width; millisecond
            # clocks mod 2^31 collide across runs only at the same ms
            # within a ~24-day wrap, and only in a REUSED directory.
            self._trace_run_id = mh.build_min(self.mesh)(
                int(time.time() * 1000) & 0x7FFFFFFF)
        res = EngineResult(
            pipeline=(cfg.pipeline if self._v3_plan is not None
                      else "v2" if self._v2 is not None else "v1"),
            fused_stages=(dict(self._v3_plan.stages)
                          if self._v3_plan is not None else {}),
            fused_reasons=(dict(self._v3_plan.reasons)
                           if self._v3_plan is not None else {}),
            por_instances=(self._por_table.certified
                           if self._por_table is not None else 0),
            family_groups=_family_groups_meta(self.dims))
        self._cur_res = res     # run_end event reads it on error exits
        mt, evlog = self.metrics, self._evlog
        self._growth_stalls = res.growth_stalls
        # TLC-style per-action coverage (obs/coverage.py); stats are
        # psum-replicated, so every controller accumulates identical
        # global counts.
        from ..obs import ActionCoverage
        coverage = self.coverage = ActionCoverage(dims.family_names,
                                                  dims.family_sizes)
        t_enter = time.time()
        trace = make_trace_store() if cfg.record_trace else TraceStore()
        self.trace = trace

        if resume is not None:
            # Shards must hold the checkpointed keys at <= half load.
            per_owner = np.asarray(resume.seen_hi, np.uint64) % n
            max_keys = max((int((per_owner == d).sum()) for d in range(n)),
                           default=0)
            while max_keys > self._CL // 2:
                self._CL *= 2
                self._rebuild_programs()

        CL = self._CL
        QLA = QL + self._PAD     # live rows + slice-overrun/scatter trash

        # Every device-resident buffer is allocated ALREADY SHARDED over
        # the mesh (zeros/fills jitted with explicit out_shardings): a
        # plain jnp.zeros would land the full n-chip array on one device
        # — invisible on the virtual CPU mesh, an instant OOM on a real
        # pod where per-chip capacities are sized to chip HBM.
        def sharded_full(shape, dtype, fill=0):
            sh = NamedSharding(self.mesh, P("x"))
            return jax.jit(lambda: jnp.full(shape, fill, dtype),
                           out_shardings=sh)()

        qcur = sharded_full((n, QLA, sw), jnp.uint8)
        qnext = sharded_full((n, QLA, sw), jnp.uint8)
        shi = sharded_full((n, CL), _U32, SENTINEL)
        slo = sharded_full((n, CL), _U32, SENTINEL)
        ssize = sharded_full((n,), _I32)
        next_counts = sharded_full((n,), _I32)
        tbuf = tuple(sharded_full((n, self._TA), d)
                     for d in (jnp.uint32, jnp.uint32, jnp.uint32,
                               jnp.uint32, _I32))
        tcount = sharded_full((n,), _I32)
        from ..engine.spillpool import SpillPool
        pending = SpillPool(cfg.spill_dir)   # host pool (rows), global
        spill_next = SpillPool(cfg.spill_dir)
        # Async spill (engine/bfs.py): drains ride behind compute via a
        # spare next-queue; resolved at the next drain or level boundary.
        free_q: List = [sharded_full((n, QLA, sw), jnp.uint8)]
        inflight: List = []              # [(device array, per-chip counts)]

        def resolve_spill():
            while inflight:
                with mt.phase_timer("spill"):
                    arr, cnts = inflight.pop(0)
                    # _drain copies per-chip slices (np.concatenate), so
                    # no view into the recycled buffer survives.  A
                    # controller whose shards were all empty contributes
                    # no segment.
                    rows = self._drain(arr, cnts)
                    if len(rows):
                        spill_next.append(rows)
                    free_q.append(arr)

        if resume is None:
            encoded = [encode_state(s, dims) for s in init_states]
            if self._root_check is not None:
                with mt.phase_timer("root_check"):
                    v = find_root_violation(self._root_check, encoded,
                                            init_states, B, self.inv_names)
                if v is not None:   # before warm-up: no checking time spent
                    if cfg.record_trace:
                        # Depth-0 counterexample must stay replayable:
                        # register the violating root under the Violation's
                        # fingerprint (engine/bfs.py rationale), and under
                        # a process group ALSO write this controller's
                        # trace piece — every controller takes this same
                        # early return (roots are replicated), and a
                        # sibling's replay() would otherwise block in
                        # _merge_trace_pieces waiting for a piece that was
                        # never written.
                        trace.roots.setdefault(v.fingerprint, v.state)
                        if mp:
                            self._write_trace_piece(trace)
                            self._trace_merged = False
                    res.violation = v
                    res.stop_reason = "violation"
                    res.levels.append(0)
                    res.wall_seconds = time.time() - t_enter
                    evlog.emit("violation", invariant=v.invariant,
                               fingerprint=hex(v.fingerprint), level=0)
                    return res
            for e in encoded:       # reject silently-aliasing roots
                check_packable(e, self.dims)
            rows_np = np.stack([flatten_state(e, dims) for e in encoded])
            if cfg.record_trace:
                with mt.phase_timer("root_check"):
                    rhi, rlo = (np.asarray(x) for x in
                                self._fp_rows(jnp.asarray(rows_np)))
                    for idx, s in enumerate(init_states):
                        trace.roots.setdefault(
                            (int(rhi[idx]) << 32) | int(rlo[idx]), s)

        # Warm-up compilation before the duration clock starts.  Inputs go
        # through put_global so each controller materializes only its own
        # shards (multihost.py rule 3; identical single-host).
        zero_counts = mh.put_global(np.zeros((n,), np.int32),
                                    self.mesh, P("x"))
        with mt.phase_timer("warmup"):
            out = self._ingest(
                mh.put_global(np.zeros((n, B, sw), ROW_DTYPE),
                              self.mesh, P("x")),
                mh.put_global(np.zeros((n, B), bool), self.mesh, P("x")),
                qnext, next_counts, shi, slo, ssize, tbuf, tcount)
            qnext, next_counts, shi, slo, ssize, tbuf = out[:6]
            out = self._chunk(qcur, zero_counts, jnp.int32(0),
                              qnext, next_counts, shi, slo, ssize, tbuf,
                              tcount, jnp.int32(self._CH))
            qnext, next_counts, shi, slo, ssize, tbuf = out[:6]
            # Placement-fixpoint second call (engine/bfs.py warm-up
            # rationale): free when outputs already carry the input
            # shardings, and pre-compiles the output-placement variant
            # when they don't.
            out = self._chunk(qcur, zero_counts, jnp.int32(0),
                              qnext, next_counts, shi, slo, ssize, tbuf,
                              tcount, jnp.int32(self._CH))
            qnext, next_counts, shi, slo, ssize, tbuf = out[:6]
        t0 = time.time()
        last_progress = t0
        self._batch_ema = 0.0

        if resume is not None:
            # Rebuild shards from the flat key set: owner = fp_hi mod n.
            # Each controller materializes only its addressable shards, so
            # a checkpoint written by M controllers (piece group, merged
            # by checkpoint.load) resumes on any process count.
            shi, slo, ssize = self._shards_from_keys(
                np.asarray(resume.seen_hi, np.uint64),
                np.asarray(resume.seen_lo, np.uint64))
            fr = np.ascontiguousarray(resume.frontier).astype(
                ROW_DTYPE, casting="safe")
            level_rows = len(fr)
            if mp:
                # Disjoint frontier slices per controller; the union is
                # the checkpointed frontier.
                fr = fr[jax.process_index()::jax.process_count()]
            # Segment granularity = what one upload can take: this
            # controller's chips x QL rows (global n*QL single-host) — a
            # larger pre-split would make the consume loop's remainder
            # re-insert rewrite the pool head on every upload.
            seg_cap = QL * sum(
                1 for d in self.mesh.devices.flat
                if d.process_index == jax.process_index())
            # Pre-split into upload-sized segments (views).
            for i in range(0, len(fr), seg_cap):
                pending.append(fr[i:i + seg_cap])
            cur_counts_dev = zero_counts
            res.distinct = resume.distinct
            res.generated = resume.generated
            res.diameter = resume.diameter
            res.levels = list(resume.levels)
            res.action_counts = dict(resume.action_counts)
            # Coverage-only resume seeding (engine/bfs.py rule: registry
            # counters are process-cumulative and must not be re-seeded).
            coverage.seed_generated(resume.action_counts)
            t0 -= resume.wall_seconds
            if cfg.record_trace:
                if resume.distinct > 0 and resume.trace_fps.size == 0:
                    raise ValueError(
                        "checkpoint was written with trace recording "
                        "disabled; resume with record_trace=False or "
                        "restart from scratch")
                trace.add_batch(resume.trace_fps, resume.trace_parents,
                                resume.trace_actions)
                trace.roots.update(resume.roots)
            elif resume.trace_fps.size > 0 and cfg.checkpoint_dir is not None:
                raise ValueError(
                    "resuming a trace-carrying checkpoint with trace "
                    "recording disabled would write trace-less snapshots "
                    "into the same directory, shadowing the intact ones "
                    "for any later trace-on resume; use a different "
                    "checkpoint_dir or keep tracing enabled")
        else:
            # Ingest roots round-robin across chips in B-sized waves.
            per_chip = [rows_np[i::n] for i in range(n)]
            max_chunks = max((-(-len(p) // B) for p in per_chip), default=0)
            drained = 0       # next-level rows pushed to host pools (global)
            cur_sum = 0       # next-level rows on device (replicated psum)
            for c in range(max_chunks):
                # StopAfter covers ingest; the first wave always runs
                # (engine/bfs.py rationale).  Clock decisions are agreed
                # collectively under multi-controller.
                if c and cfg.max_seconds is not None:
                    over = time.time() - t0 > cfg.max_seconds
                    if any_flag is not None:
                        over = any_flag(over)
                    if over:
                        res.stop_reason = "duration_budget"
                        break
                if c and cfg.exit_conditions:
                    # "queue" during ingest: enqueued + landed spills +
                    # roots not yet ingested (engine/bfs.py rationale);
                    # pool rows psum-agreed under a process group.
                    pools = spill_next.total_rows()
                    if pool_sum is not None:
                        pools = pool_sum(pools)
                    hit = _exit_condition_hit(
                        cfg.exit_conditions, res,
                        cur_sum + pools
                        + sum(max(0, len(p) - c * B) for p in per_chip))
                    if hit:
                        res.stop_reason = hit
                        break
                wave = np.zeros((n, B, sw), ROW_DTYPE)
                valid = np.zeros((n, B), bool)
                for d in range(n):
                    part = per_chip[d][c * B:(c + 1) * B]
                    wave[d, :len(part)] = part
                    valid[d, :len(part)] = True
                with mt.phase_timer("ingest"):
                    out = self._ingest(
                        mh.put_global(wave, self.mesh, P("x")),
                        mh.put_global(valid, self.mesh, P("x")),
                        qnext, next_counts, shi, slo, ssize,
                        tbuf, tcount)
                    (qnext, next_counts, shi, slo, ssize, tbuf, tcount,
                     istats, ivrow, ivfp) = out
                    ist = np.asarray(istats)
                res.distinct += int(ist[0])
                mt.counter("engine/distinct", int(ist[0]))
                cur_sum = int(ist[3])
                if int(ist[1]):
                    raise RuntimeError("seen-set probe failure during "
                                       "ingest; raise seen_capacity")
                with mt.phase_timer("trace_flush"):
                    self._flush_trace(trace, tbuf, tcount)
                tcount = sharded_full((n,), _I32)
                (shi, slo, ssize, qnext, next_counts, tbuf,
                 t0) = self._grow_precompiled(shi, slo, ssize, qcur, qnext,
                                              next_counts, tbuf, tcount,
                                              t0, int(ist[6]))
                if int(ist[2]) > self._QTH:  # ingest adds <= B per wave
                    with mt.phase_timer("spill"):
                        rows = self._drain(
                            qnext, self._local_counts(next_counts))
                        if len(rows):
                            spill_next.append(rows)
                    evlog.emit("spill", rows=cur_sum, level=0,
                               where="ingest")
                    drained += cur_sum
                    cur_sum = 0
                    next_counts = sharded_full((n,), _I32)
                if self._check_violation_ingest(res, ist, ivrow, ivfp):
                    break
            level_rows = drained + cur_sum
            res.levels.append(level_rows)
            # Seen gauges refreshed BEFORE the level-0 emit (engine/
            # bfs.py rationale): its level_stats snapshot reads them,
            # and a warm shared registry would otherwise leak the
            # previous run's values into this run's level-0 row.  Same
            # per-chip convention as the chunk loop's gauge updates.
            mt.gauge("engine/seen_capacity", self._CL)
            mt.gauge("engine/seen_size", int(ist[6]))
            self._sample_skew(res, next_counts, ssize)
            self._emit_level_event(res, level_rows)
            qcur, qnext = qnext, qcur
            cur_counts_dev = next_counts
            next_counts = sharded_full((n,), _I32)
            pending, spill_next = spill_next, pending

        skip_ckpt_level = resume.diameter if resume is not None else -1
        last_ckpt = time.time() if resume is not None else float("-inf")
        while level_rows > 0 \
                and res.violation is None and res.stop_reason == "exhausted":
            if cfg.checkpoint_dir is not None \
                    and res.diameter % max(1, cfg.checkpoint_every) == 0 \
                    and res.diameter != skip_ckpt_level:
                want_ckpt = (time.time() - last_ckpt
                             >= cfg.checkpoint_interval_seconds)
                if any_flag is not None:
                    # Interval clocks differ per host; a piece group is
                    # only resumable when EVERY controller wrote its piece
                    # — agree, so groups are always complete.
                    want_ckpt = any_flag(want_ckpt)
                if want_ckpt:
                    with mt.phase_timer("checkpoint"):
                        self._write_checkpoint(qcur, cur_counts_dev,
                                               pending, shi, slo, res,
                                               trace,
                                               wall=time.time() - t0)
                    last_ckpt = time.time()
                    evlog.emit("checkpoint", level=res.diameter,
                               distinct=res.distinct)
            if cfg.max_diameter is not None \
                    and res.diameter >= cfg.max_diameter:
                res.stop_reason = "diameter_budget"
                break
            # Level loop over segments: device-resident rows first, then
            # host-pool segments (balanced re-uploads).  Budgeted runs
            # slow-start each level (engine/bfs.py rationale).  The level
            # width is derived in-program (pmax), so the sub-loop is
            # do-while: one call, then loop while the replicated offset
            # has not crossed the replicated width.
            calls_in_level = 0
            drained = 0
            cur_sum = 0
            while True:
                offset = 0
                while True:
                    allowed = self._CH
                    if cfg.max_seconds is not None:
                        remaining = cfg.max_seconds - (time.time() - t0)
                        over = remaining <= 0
                        if self._batch_ema:
                            # Half-window sizing + per-level slow-start
                            # (engine/bfs.py rationale)
                            allowed = max(1, min(
                                self._CH,
                                int(remaining / (2 * self._batch_ema)),
                                2 << min(calls_in_level, 9)))
                        else:
                            allowed = 1    # no estimate yet: probe batch
                                           # (engine/bfs.py rationale)
                        if budget_agree is not None:
                            # allowed is an input to a collective program:
                            # all controllers must pass the same value —
                            # one fused round trip agrees both the stop
                            # flag and the chunk budget.
                            over, allowed = budget_agree(over, allowed)
                            allowed = max(1, allowed)
                        if over:
                            res.stop_reason = "duration_budget"
                            break
                    calls_in_level += 1
                    if _faults.ACTIVE:
                        # Same deterministic sites as the single-chip
                        # loop (resilience/): mid-level kill and
                        # simulated RESOURCE_EXHAUSTED.
                        _faults.fire("kill", level=res.diameter,
                                     chunk=calls_in_level)
                        _faults.fire("oom", level=res.diameter,
                                     chunk=calls_in_level)
                    t_call = time.time()
                    # Device-profiler window (--xla-profile): the mesh
                    # brackets its sharded dispatch exactly like the
                    # single-chip loop — same "chunk" span name, same
                    # per-run capture object from _telemetry_run, one
                    # call site (profiled/unprofiled must not diverge).
                    cap = getattr(self, "_xla_capture", None)
                    step_cm = (cap.step() if cap is not None
                               and not cap.done
                               else contextlib.nullcontext())
                    with mt.phase_timer("chunk"), step_cm:
                        out = self._chunk(
                            qcur, cur_counts_dev,
                            jnp.int32(offset), qnext, next_counts, shi,
                            slo, ssize, tbuf, tcount, jnp.int32(allowed))
                        (qnext, next_counts, shi, slo, ssize, tbuf,
                         tcount, stats, drow_g, vrow_g, vfp_g) = out
                    # One blocking sync per chunk call (engine/bfs.py):
                    # this phase is the mesh's device compute + collective
                    # time.
                    with mt.phase_timer("stats_fetch"):
                        st = np.asarray(stats)
                    if self._perf is not None and int(st[1]):
                        # Launch accounting's dynamic half (obs/perf.py)
                        # — host arithmetic on the fetched stats only.
                        self._perf.add_chunk(int(st[1]),
                                             time.time() - t_call)
                    if int(st[1]):
                        per = (time.time() - t_call) / int(st[1])
                        # Conservative: jump up instantly, decay slowly
                        # (engine/bfs.py rationale).
                        self._batch_ema = (
                            per if not self._batch_ema else
                            max(per, 0.5 * self._batch_ema + 0.5 * per))
                    offset = int(st[0])
                    max_count = int(st[6])
                    cur_sum = int(st[8])
                    res.generated += int(st[2])
                    res.distinct += int(st[3])
                    # Packed-stats fetch feeds the registry (the one live
                    # counter source — engine/bfs.py rationale).
                    mt.counter("engine/generated", int(st[2]))
                    mt.counter("engine/distinct", int(st[3]))
                    mt.gauge("engine/seen_size", int(st[10]))
                    mt.gauge("engine/seen_capacity", self._CL)
                    mt.gauge("engine/next_count", cur_sum)
                    mt.gauge("engine/diameter", res.diameter)
                    F = len(dims.family_sizes)
                    if int(st[2]):
                        for name, c in zip(dims.family_names,
                                           st[16:16 + F]):
                            res.action_counts[name] = (
                                res.action_counts.get(name, 0) + int(c))
                    # Coverage from the same psum'd packed stats
                    # (obs/coverage.py; engine/bfs.py rationale).
                    coverage.add_chunk(int(st[15]), st[16:16 + F],
                                       st[16 + F:16 + 2 * F],
                                       st[16 + 2 * F:16 + 3 * F])
                    # Black-box progress snapshot (obs/flight.py;
                    # rate-limited inside progress()) — the mesh feeds
                    # the same watch/postmortem view as the single-chip
                    # loop.
                    _flight_rec.progress(
                        distinct=res.distinct, generated=res.generated,
                        diameter=res.diameter, frontier=int(st[9]),
                        offset=offset, next_count=cur_sum,
                        seen_size=int(st[10]),
                        elapsed=round(time.time() - t0, 3))
                    if int(st[4]):
                        raise RuntimeError(
                            f"{int(st[4])} successors exceeded fixed-width "
                            f"capacity (max_log={dims.max_log}, n_msg_slots"
                            f"={dims.n_msg_slots}) or wrapped the uint8 "
                            f"row; rerun with larger capacities/bounds")
                    if int(st[5]):
                        raise RuntimeError(
                            "seen-set probe failure (load spiked within "
                            "one chunk); raise seen_capacity or lower "
                            "sync_every")
                    with mt.phase_timer("trace_flush"):
                        self._flush_trace(trace, tbuf, tcount)
                    tcount = sharded_full((n,), _I32)
                    (shi, slo, ssize, qnext, next_counts, tbuf,
                     t0) = self._grow_precompiled(
                        shi, slo, ssize, qcur, qnext, next_counts, tbuf,
                        tcount, t0, int(st[10]))
                    if int(st[7]) > self._QTH:
                        # Watermark (replicated pmax): drain unless this is
                        # the level's very last chunk — then the boundary
                        # swap is cheaper.  "More segments?" is host-local
                        # state, agreed collectively when it matters.
                        more_here = offset < max_count
                        if not more_here:
                            more_here = (any_flag(bool(pending))
                                         if any_flag is not None
                                         else bool(pending))
                        if more_here:
                            resolve_spill()
                            with mt.phase_timer("spill"):
                                cnts = self._local_counts(next_counts)
                                qnext.copy_to_host_async()
                                inflight.append((qnext, cnts))
                                qnext = free_q.pop()
                                next_counts = sharded_full((n,), _I32)
                            evlog.emit("spill", rows=cur_sum,
                                       level=res.diameter,
                                       where="chunk_loop")
                            drained += cur_sum
                            cur_sum = 0
                    if int(st[11]):
                        vf = np.asarray(vfp_g)
                        res.violation = Violation(
                            invariant=self.inv_names[int(st[13])],
                            state=decode_state(unflatten_state(
                                np.asarray(vrow_g), dims), dims),
                            fingerprint=(int(vf[0]) << 32) | int(vf[1]))
                        res.stop_reason = "violation"
                        evlog.emit(
                            "violation",
                            invariant=res.violation.invariant,
                            fingerprint=hex(res.violation.fingerprint),
                            level=res.diameter)
                        break
                    if int(st[12]) and self._check_deadlock:
                        res.deadlock = decode_state(unflatten_state(
                            np.asarray(drow_g), dims), dims)
                        res.stop_reason = "deadlock"
                        evlog.emit("deadlock", level=res.diameter)
                        break
                    want_progress = bool(
                        cfg.progress_interval_seconds
                        and time.time() - last_progress
                        >= cfg.progress_interval_seconds)
                    if cfg.exit_conditions or want_progress:
                        # "queue" counts the FULL unexplored queue: this
                        # level's remainder (replicated psum) + next-level
                        # rows + landed and in-flight spill segments.
                        # Pool rows are per-controller; psum-agree them
                        # when a queue budget needs the global total.
                        local_pools = (
                            pending.total_rows() + spill_next.total_rows()
                            + sum(sum(c.values()) for _b, c in inflight))
                        if pool_sum is not None:
                            local_pools = pool_sum(local_pools)
                        queue_rows = (
                            int(st[9]) + cur_sum + local_pools)
                        if want_progress:
                            _progress_line(res, t0, queue_rows,
                                           int(st[14]), metrics=mt)
                            # Coverage on the same cadence (engine/
                            # bfs.py): registry gauges + one event.
                            coverage.feed_metrics(mt)
                            evlog.emit("coverage", level=res.diameter,
                                       actions=coverage.snapshot())
                            last_progress = time.time()
                        # Last: a violation/deadlock in the same chunk
                        # outranks a budget stop (engine/bfs.py rationale).
                        hit = _exit_condition_hit(
                            cfg.exit_conditions, res, queue_rows)
                        if hit:
                            res.stop_reason = hit
                            break
                    if offset >= max_count:
                        break
                more_segments = (any_flag(bool(pending))
                                 if any_flag is not None else bool(pending))
                if res.stop_reason != "exhausted" \
                        or res.violation is not None or not more_segments:
                    break
                # Upload the next host segment, balanced across this
                # controller's chips (each controller re-uploads its own
                # pool; the segment cap keeps any one upload within QL
                # rows per chip).
                with mt.phase_timer("upload"):
                    my_rows = [i for i, d in
                               enumerate(self.mesh.devices.flat)
                               if d.process_index == jax.process_index()]
                    cap = len(my_rows) * QL
                    seg = pending.pop(0) if pending else \
                        np.zeros((0, sw), ROW_DTYPE)
                    while len(seg) > cap:
                        pending.insert(0, seg[cap:])
                        seg = seg[:cap]
                    bufs = {}
                    cnts = np.zeros((n,), np.int32)
                    share = -(-len(seg) // len(my_rows)) if len(seg) else 0
                    for k, di in enumerate(my_rows):
                        part = seg[k * share:(k + 1) * share] if share \
                            else seg[:0]
                        b = np.zeros((QLA, sw), ROW_DTYPE)
                        b[:len(part)] = part
                        bufs[di] = b[None]
                        cnts[di] = len(part)
                    shq = NamedSharding(self.mesh, P("x"))
                    qcur = jax.make_array_from_callback(
                        (n, QLA, sw), shq, lambda idx: bufs[idx[0].start])
                    cur_counts_dev = jax.make_array_from_callback(
                        (n,), shq,
                        lambda idx: cnts[idx[0].start:idx[0].stop])
            if res.stop_reason != "exhausted" or res.violation is not None:
                break
            resolve_spill()      # level boundary: all drains must land
            res.diameter += 1
            level_rows = drained + cur_sum
            res.levels.append(level_rows)
            self._sample_skew(res, next_counts, ssize)
            self._emit_level_event(res, level_rows)
            qcur, qnext = qnext, qcur
            cur_counts_dev = next_counts
            next_counts = sharded_full((n,), _I32)
            pending, spill_next = spill_next, pending

        res.wall_seconds = time.time() - t0
        if mp and cfg.record_trace:
            # Every controller reaches this exit (stop decisions are
            # collectively agreed), so the piece group is always
            # complete; replay() merges the siblings on demand.
            self._write_trace_piece(trace)
            self._trace_merged = False
        return res

    # ------------------------------------------------------------------
    def _local_counts(self, counts) -> dict:
        """{global chip row -> count} for THIS controller's addressable
        shards (single-controller: all chips — behavior unchanged)."""
        return {s.index[0].start: int(np.asarray(s.data)[0])
                for s in counts.addressable_shards}

    def _drain(self, qnext, cnts: dict) -> np.ndarray:
        """This controller's queued rows -> one host array (spill).  Each
        controller drains only its addressable shards; the union across
        controllers is the global queue (multi-controller rule 2)."""
        segs = []
        for s in sorted(qnext.addressable_shards,
                        key=lambda s: s.index[0].start):
            c = cnts.get(s.index[0].start, 0)
            if c:
                segs.append(np.asarray(s.data)[0, :c])
        return np.concatenate(segs) if segs else \
            np.zeros((0, self._sw), ROW_DTYPE)

    def _maybe_grow(self, shi, slo, ssize, max_ssize):
        """``max_ssize`` is the psum-replicated pmax of shard loads (from
        the packed stats), so every controller takes the same branch."""
        if max_ssize <= self._CL // 2:
            return shi, slo, ssize
        self._grow_attempts = getattr(self, "_grow_attempts", 0) + 1
        if _faults.ACTIVE:
            # A growth OOM here propagates to the shared degradation
            # wrapper (halve batch + resume); the per-shard rebuild has
            # no safe mid-way retry point, unlike the single-chip table.
            _faults.fire("oom", grow=self._grow_attempts)
        return self._grow_seen(shi, slo, ssize)

    def _grow_precompiled(self, shi, slo, ssize, qcur, qnext, next_counts,
                          tbuf, tcount, t0, max_ssize):
        """Grow the seen shards when loaded past threshold, pre-compile
        the rebuilt programs at the new shape with a zero-trip call, and
        keep the rehash + compile off the duration clock (engine/bfs.py
        rule).  Returns (shi, slo, ssize, qnext, next_counts, tbuf, t0)."""
        t_grow = time.time()
        grown = self._maybe_grow(shi, slo, ssize, max_ssize)
        if grown[0] is not shi:
            shi, slo, ssize = grown
            from . import multihost as mh
            zero_counts = mh.put_global(
                np.zeros((self.n_dev,), np.int32), self.mesh, P("x"))
            out = self._chunk(
                qcur, zero_counts, jnp.int32(0), qnext,
                next_counts, shi, slo, ssize, tbuf, tcount,
                jnp.int32(1))
            qnext, next_counts, shi, slo, ssize, tbuf = out[:6]
            stall = time.time() - t_grow
            t0 += stall
            # Off the clock, but recorded (engine/bfs.py rationale): mesh
            # growth additionally re-inits + retraces both programs, the
            # expensive path VERDICT r3 weak #7 wants measured on silicon.
            # The stall IS the phase time (rehash + retrace + precompile),
            # so it is observed directly rather than via phase_timer.
            self._growth_stalls.append(
                (self.n_dev * self._CL, round(stall, 3)))
            from ..obs import PHASE_PREFIX, device_memory_stats
            self.metrics.observe(PHASE_PREFIX + "fpset_grow", stall)
            self.metrics.counter("engine/fpset_resizes")
            self._evlog.emit("fpset_resize",
                             capacity=self.n_dev * self._CL,
                             stall_seconds=round(stall, 3),
                             memory=device_memory_stats())
        return shi, slo, ssize, qnext, next_counts, tbuf, t0

    def _write_checkpoint(self, qcur, cur_counts, pending, shi, slo, res,
                          trace, wall):
        """Same snapshot format as the single-chip engine: flat frontier +
        flat key set (chip assignment is recomputed on resume)."""
        from ..engine import checkpoint as ckpt_mod
        import os
        if self.config.record_trace:
            tf, tp, ta = trace.export()
            roots = dict(trace.roots)
        else:
            tf = np.empty(0, np.uint64)
            tp = np.empty(0, np.uint64)
            ta = np.empty(0, np.int32)
            roots = {}
        # This controller's share only: its pool + device shards + seen
        # shards.  Multi-host writes one piece per controller (identical
        # replicated counters in each); checkpoint.load merges the group.
        frontier, front_cleanup = pending.concat_with(
            self._drain(qcur, self._local_counts(cur_counts)))
        keys_hi, keys_lo = [], []
        for s_hi, s_lo in zip(
                sorted(shi.addressable_shards,
                       key=lambda s: s.index[0].start),
                sorted(slo.addressable_shards,
                       key=lambda s: s.index[0].start)):
            hi_h = np.asarray(s_hi.data)[0]
            lo_h = np.asarray(s_lo.data)[0]
            real = ~((hi_h == SENTINEL) & (lo_h == SENTINEL))
            keys_hi.append(hi_h[real])
            keys_lo.append(lo_h[real])
        keys_hi = np.concatenate(keys_hi) if keys_hi else np.empty(0)
        keys_lo = np.concatenate(keys_lo) if keys_lo else np.empty(0)
        order = np.lexsort((keys_lo, keys_hi))
        ck = ckpt_mod.Checkpoint(
            dims=self.dims, frontier=frontier,
            seen_hi=keys_hi[order].astype(np.uint32),
            seen_lo=keys_lo[order].astype(np.uint32),
            distinct=res.distinct, generated=res.generated,
            diameter=res.diameter, levels=tuple(res.levels),
            action_counts=dict(res.action_counts),
            wall_seconds=wall,
            trace_fps=tf, trace_parents=tp, trace_actions=ta, roots=roots)
        if jax.process_count() > 1:
            path = ckpt_mod.piece_path(self.config.checkpoint_dir,
                                       res.diameter, jax.process_index(),
                                       jax.process_count())
        else:
            path = os.path.join(self.config.checkpoint_dir,
                                f"level_{res.diameter:05d}.npz")
        try:
            ckpt_mod.save(path, ck)
        finally:
            front_cleanup()
        # Retention after the successful write (engine/bfs.py rule).
        # Under a process group every controller runs the same gc over
        # the shared dir; deletions race benignly (missing files are
        # skipped) and only complete intact groups count toward keep.
        removed = ckpt_mod.gc(self.config.checkpoint_dir,
                              self.config.keep_checkpoints)
        if removed:
            self.metrics.counter("engine/checkpoints_gcd", removed)

    def _flush_trace(self, trace, tbuf, tcount):
        """Harvest trace records from this controller's ADDRESSABLE chip
        buffers only (single-controller: all chips — behavior unchanged).
        Under a process group, fetching the global arrays would be a
        cross-host gather; instead each controller's store accumulates
        the records its own chips produced, and the stores are merged
        through per-controller piece files at replay time
        (:meth:`_merge_trace_pieces`)."""
        if not self.config.record_trace:
            return
        counts = self._local_counts(tcount)
        if not any(counts.values()):
            return
        comps = [sorted(x.addressable_shards,
                        key=lambda s: s.index[0].start) for x in tbuf]
        for shard_set in zip(*comps):
            d = shard_set[0].index[0].start
            m = counts.get(d, 0)
            if m == 0:
                continue
            sh, sl, ph, pl, ac = (np.asarray(s.data)[0] for s in shard_set)
            fps = ((sh[:m].astype(np.uint64) << np.uint64(32))
                   | sl[:m].astype(np.uint64))
            parents = ((ph[:m].astype(np.uint64) << np.uint64(32))
                       | pl[:m].astype(np.uint64))
            trace.add_batch(fps, parents, ac[:m])

    # -- multi-host trace exchange (shared filesystem, like R8) ---------
    @property
    def _trace_exchange_dir(self) -> str:
        return self.config.trace_dir or self.config.checkpoint_dir

    def _trace_piece_path(self, i: int, m: int) -> str:
        # The collectively-agreed per-run id in the name keeps a reused
        # directory safe: without it, a controller's merge poll could
        # match a PREVIOUS run's piece (same (dir, i, m) name) written
        # before a slower sibling finishes fsyncing the current one, and
        # replay would silently miss that sibling's new records.
        return os.path.join(
            self._trace_exchange_dir,
            f"trace_run_{self._trace_run_id:08x}.p{i}of{m}.npz")

    def _write_trace_piece(self, trace) -> None:
        """One piece per controller, written at every run exit (all
        controllers take the same exit — control flow is collectively
        agreed), so the union of pieces is the global trace.  Same
        shared-filesystem assumption as multi-host checkpoints (R8) —
        which record_trace under a process group therefore requires
        (``trace_dir``, defaulting to ``checkpoint_dir``)."""
        tf, tp, ta = trace.export()
        if _faults.ACTIVE:
            # Injected slow sibling: exercises _merge_trace_pieces'
            # poll/deadline path without needing a genuinely slow host.
            _faults.fire("trace_piece_delay",
                         piece=jax.process_index())
        d = self._trace_exchange_dir
        os.makedirs(d, exist_ok=True)
        path = self._trace_piece_path(
            jax.process_index(), jax.process_count())
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            np.savez_compressed(f, fps=tf, parents=tp, actions=ta)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    def _merge_trace_pieces(self, timeout_s: Optional[float] = None) -> None:
        """Fold every sibling controller's trace piece into this store
        (idempotent; records are keyed by fingerprint).  Sibling files
        appear within the skew of the collective run exit; poll rather
        than requiring an extra barrier.

        Deadline: ``EngineConfig.trace_merge_timeout_seconds`` when set;
        otherwise a 30 s base plus an allowance proportional to THIS
        controller's piece size — pieces are written at the same exit
        with similar record counts, so a big local piece predicts
        siblings still compressing/fsyncing theirs (~8 MB/s floor)."""
        m = jax.process_count()
        my_piece = self._trace_piece_path(jax.process_index(), m)
        try:
            my_bytes = os.path.getsize(my_piece)
        except OSError:
            my_bytes = 0
        if timeout_s is None:
            timeout_s = self.config.trace_merge_timeout_seconds
        if timeout_s is None:
            timeout_s = 30.0 + my_bytes / (8 << 20)
        deadline = time.time() + timeout_s
        for i in range(m):
            if i == jax.process_index():
                continue
            path = self._trace_piece_path(i, m)
            while not os.path.exists(path):
                if time.time() > deadline:
                    raise FileNotFoundError(
                        f"trace piece {path} not written within "
                        f"{timeout_s:.0f}s — controller {i} may still be "
                        f"compressing its piece (this controller's was "
                        f"{my_bytes} bytes; larger traces take longer), "
                        f"or it exited the run abnormally.  If it is just "
                        f"slow, raise "
                        f"EngineConfig.trace_merge_timeout_seconds")
                time.sleep(0.05)
            with self.metrics.phase_timer("trace_merge"):
                with np.load(path) as z:
                    self.trace.add_batch(z["fps"], z["parents"],
                                         z["actions"])

    def _check_violation_ingest(self, res, ist, vrow, vfp) -> bool:
        """``ist``/``vrow``/``vfp`` are the ingest program's replicated
        stats and lowest-flagged-chip violation broadcast."""
        if not int(ist[4]):
            return False
        vf = np.asarray(vfp)
        res.violation = Violation(
            invariant=self.inv_names[int(ist[5])],
            state=decode_state(
                unflatten_state(np.asarray(vrow), self.dims), self.dims),
            fingerprint=(int(vf[0]) << 32) | int(vf[1]))
        res.stop_reason = "violation"
        # Same event every other violation path emits — consumers filter
        # on event=="violation" for the counterexample record.
        self._evlog.emit("violation", invariant=res.violation.invariant,
                         fingerprint=hex(res.violation.fingerprint),
                         level=0)
        return True

    # Replay shares the single-engine mechanism.  Under a process group
    # the trace chain crosses controllers (a child inserted on this
    # host's chips may have a parent recorded by another controller), so
    # the sibling piece files are folded in first — once.
    def replay(self, fp: int):
        from ..engine.bfs import BFSEngine  # reuse logic via duck typing
        from . import multihost as mh
        if (mh.is_multiprocess() and self.config.record_trace
                and not getattr(self, "_trace_merged", True)):
            self._merge_trace_pieces()
            self._trace_merged = True
        return BFSEngine.replay(self, fp)
