"""Mesh-sharded BFS — distributed TLC over a jax device mesh.

TLC scales with a multi-threaded worker pool and an RMI-based distributed
mode [TLC semantics — external; SURVEY §2.4 R7].  The TPU-native equivalent
shards the level-synchronous BFS over a 1-D ``jax.sharding.Mesh`` with
``shard_map``; collectives ride ICI (and DCN across hosts, transparently —
the program is identical):

- the frontier queue, next-level queue, and FPSet are sharded per chip;
- each chip expands its local batch and fingerprints its candidates;
- **fingerprint-owner dedup**: candidate fps are routed to their owner chip
  (``fp_hi mod n``) with one ``all_to_all``; the owner runs the same
  batched hash-table insert (ops/fpset.py) as the single-chip engine on the
  union of arriving queries, then a reverse ``all_to_all`` returns one
  novelty bit per query.  Exactly one copy of each globally-new state gets
  the bit, so states enqueue on the chip that *generated* them — only
  8-byte fingerprints ever cross the interconnect, never state rows;
- stats (new/generated/overflow/deadlock/violation) combine with ``psum``.

The host loop mirrors engine/bfs.py: offsets advance in lockstep batches
(chips with short local queues mask out), queues swap per level, scalars and
compacted trace records stream back per step.

Tested on a virtual 8-device CPU mesh (SURVEY §4.5); the program is
identical on a real TPU slice.
"""

from __future__ import annotations

import time
from functools import partial
from typing import Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ..engine.bfs import (EngineConfig, EngineResult, TraceStore, Violation,
                          build_root_check, find_root_violation,
                          make_trace_store)
from ..models.actions import build_expand
from ..models.dims import RaftDims
from ..models.invariants import build_inv_id
from ..models.pystate import PyState
from ..models.schema import (ROW_DTYPE, build_pack_guard, check_packable,
                             decode_state, encode_state, flatten_state,
                             state_width, unflatten_state)
from ..ops import fpset
from ..ops.fingerprint import SENTINEL, build_fingerprint

_I32 = jnp.int32
_U32 = jnp.uint32


class MeshBFSEngine:
    """Exhaustive checker sharded over an n-device mesh."""

    def __init__(self, dims: RaftDims,
                 invariants: Optional[Dict[str, Callable]] = None,
                 constraint: Optional[Callable] = None,
                 config: Optional[EngineConfig] = None,
                 devices=None):
        self.dims = dims
        self.config = config or EngineConfig()
        cfg = self.config
        devices = devices if devices is not None else jax.devices()
        self.n_dev = n = len(devices)
        self.mesh = Mesh(np.asarray(devices), ("x",))
        self.inv_names = list((invariants or {}).keys())
        inv_fns = list((invariants or {}).values())
        expand = build_expand(dims)
        fingerprint = build_fingerprint(dims)
        pack_ok = build_pack_guard(dims)
        sw = state_width(dims)
        B, G = cfg.batch, dims.n_instances
        K = B * G
        # Per-chip capacities.  None resolves through the same HBM
        # auto-sizing as the single-chip engine (per-chip budget); unlike
        # it, the mesh engine does not yet spill or grow — overflow is a
        # hard error here until the spill path lands in this engine too.
        from ..engine.bfs import _auto_capacities
        qreq, sreq = cfg.queue_capacity, cfg.seen_capacity
        if qreq is None or sreq is None:
            auto_q, auto_s = _auto_capacities(sw, B, cfg.record_trace)
            qreq = auto_q if qreq is None else qreq
            sreq = auto_s if sreq is None else sreq
        per_chip = -(-qreq // n)
        QL = max(B, -(-per_chip // B) * B)   # round up to a batch multiple
        # Per-chip hash-table shard: power of two for masked probing.
        CL = fpset._capacity(-(-sreq // n))
        self._sw, self._B, self._QL, self._CL = sw, B, QL, CL

        def local_absorb(crows, cands, en, parent_hi, parent_lo, actions,
                         qnext, next_count, shi, slo, ssize):
            """Per-chip tail with cross-chip owner dedup.  All arrays are
            this chip's shard (no leading device axis)."""
            k = crows.shape[0]
            fph, fpl = jax.vmap(fingerprint)(cands)
            fph = jnp.where(en, fph, SENTINEL)
            fpl = jnp.where(en, fpl, SENTINEL)

            # Route to owner = fp_hi mod n.
            owner = (fph % _U32(n)).astype(_I32)
            perm = jnp.argsort(owner, stable=True)
            osort = owner[perm]
            q_hi, q_lo = fph[perm], fpl[perm]
            block_start = jnp.searchsorted(osort, jnp.arange(n, dtype=_I32))
            rank = jnp.arange(k, dtype=_I32) - block_start[osort]
            bh = jnp.full((n, k), SENTINEL, _U32).at[osort, rank].set(q_hi)
            bl = jnp.full((n, k), SENTINEL, _U32).at[osort, rank].set(q_lo)
            bh = jax.lax.all_to_all(bh, "x", 0, 0, tiled=True)
            bl = jax.lax.all_to_all(bl, "x", 0, 0, tiled=True)

            # Owner side: one hash-table insert over the union of arriving
            # queries — in-batch dedup and seen-set probe/update in one
            # pass; exactly one arriving copy of each globally-new key gets
            # the novelty bit.
            rh, rl = bh.reshape(-1), bl.reshape(-1)
            rvalid = ~((rh == SENTINEL) & (rl == SENTINEL))
            seen_local = fpset.FPSet(hi=shi, lo=slo, size=ssize)
            seen_local, qnew, fail = fpset.insert(seen_local, rh, rl, rvalid)
            nov = jax.lax.all_to_all(qnew.reshape(n, k), "x", 0, 0,
                                     tiled=True)
            # Back on the origin chip: one novelty bit per local candidate.
            new_sortpos = nov[osort, rank]
            new = jnp.zeros((k,), bool).at[perm].set(new_sortpos)

            n_new = jnp.sum(new, dtype=_I32)      # local share of global new

            if inv_fns:
                inv = jax.vmap(build_inv_id(inv_fns))(cands)
            else:
                inv = jnp.full((k,), -1, _I32)
            viol = new & (inv >= 0)
            viol_any = jnp.any(viol)
            vpos = jnp.argmax(viol)

            if constraint is not None:
                cons_ok = jax.vmap(constraint)(cands)
            else:
                cons_ok = jnp.ones((k,), bool)
            enq = new & cons_ok
            pos = next_count + jnp.cumsum(enq.astype(_I32)) - 1
            pos = jnp.where(enq, pos, QL)
            qnext = qnext.at[pos].set(crows, mode="drop")
            next_count = next_count + jnp.sum(enq, dtype=_I32)

            tpos = jnp.where(new, jnp.cumsum(new.astype(_I32)) - 1, k)

            def compact(x):
                return jnp.zeros((k,), x.dtype).at[tpos].set(x, mode="drop")

            tr = (compact(fph), compact(fpl), compact(parent_hi),
                  compact(parent_lo), compact(actions))
            vinfo = (viol_any, inv[vpos], crows[vpos], fph[vpos], fpl[vpos])
            return (qnext, next_count, seen_local.hi, seen_local.lo,
                    seen_local.size, n_new, fail, tr, vinfo)

        def sharded_step(qcur, cur_count, offset, qnext, next_count,
                         shi, slo, ssize):
            # Shapes inside shard_map: qcur [1,QL,SW], counts [1], etc.
            qcur_l, qnext_l = qcur[0], qnext[0]
            cnt_l, ncnt_l = cur_count[0], next_count[0]
            shi_l, slo_l, ssz_l = shi[0], slo[0], ssize[0]
            rows = jax.lax.dynamic_slice_in_dim(qcur_l, offset, B, axis=0)
            valid = (offset + jnp.arange(B, dtype=_I32)) < cnt_l
            states = jax.vmap(unflatten_state, (0, None))(rows, dims)
            cands, en, ovf = jax.vmap(expand)(states)
            en = en & valid[:, None]
            # uint8-row wrap guard (schema.build_pack_guard): hard overflow.
            ovf = (ovf | (en & ~jax.vmap(jax.vmap(pack_ok))(cands))) \
                & valid[:, None]
            dead = valid & ~jnp.any(en, axis=1) & ~jnp.any(ovf, axis=1)
            dead_any = jnp.any(dead)
            drow = rows[jnp.argmax(dead)]

            cflat = jax.tree.map(
                lambda a: a.reshape((K,) + a.shape[2:]), cands)
            crows = jax.vmap(flatten_state, (0, None))(cflat, dims)
            php, plp = jax.vmap(fingerprint)(states)
            k_idx = jnp.arange(K, dtype=_I32)
            (qnext_l, ncnt_l, shi_l, slo_l, ssz_l, n_new, fail, tr,
             vinfo) = local_absorb(
                crows, cflat, en.reshape(-1), php[k_idx // G],
                plp[k_idx // G], k_idx % G, qnext_l, ncnt_l,
                shi_l, slo_l, ssz_l)
            g_new = jax.lax.psum(n_new, "x")
            g_gen = jax.lax.psum(jnp.sum(en, dtype=_I32), "x")
            g_ovf = jax.lax.psum(jnp.sum(ovf, dtype=_I32), "x")
            g_fail = jax.lax.psum(fail.astype(_I32), "x")
            stats = (g_new[None], g_gen[None], g_ovf[None], dead_any[None],
                     g_fail[None])
            return (qnext_l[None], ncnt_l[None], shi_l[None], slo_l[None],
                    ssz_l[None], stats,
                    tuple(x[None] for x in tr),
                    tuple(jnp.asarray(x)[None] for x in vinfo),
                    drow[None], n_new[None])

        def sharded_ingest(rows, valid, qnext, next_count, shi, slo, ssize):
            rows_l, valid_l = rows[0], valid[0]
            states = jax.vmap(unflatten_state, (0, None))(rows_l, dims)
            sent = jnp.zeros(rows_l.shape[:1], _U32)
            acts = jnp.full(rows_l.shape[:1], -1, _I32)
            (qnext_l, ncnt_l, shi_l, slo_l, ssz_l, n_new, fail, tr,
             vinfo) = local_absorb(
                rows_l, states, valid_l, sent, sent, acts,
                qnext[0], next_count[0], shi[0], slo[0], ssize[0])
            g_new = jax.lax.psum(n_new, "x")
            g_fail = jax.lax.psum(fail.astype(_I32), "x")
            return (qnext_l[None], ncnt_l[None], shi_l[None], slo_l[None],
                    ssz_l[None], g_new[None], g_fail[None],
                    tuple(x[None] for x in tr),
                    tuple(jnp.asarray(x)[None] for x in vinfo),
                    n_new[None])

        shard = partial(jax.shard_map, mesh=self.mesh, check_vma=False)
        sx = P("x")
        rep = P()
        self._step = jax.jit(shard(
            sharded_step,
            in_specs=(sx, sx, rep, sx, sx, sx, sx, sx),
            out_specs=(sx, sx, sx, sx, sx,
                       (sx, sx, sx, sx, sx), (sx,) * 5, (sx,) * 5, sx, sx)),
            donate_argnums=(3, 5, 6))
        self._ingest = jax.jit(shard(
            sharded_ingest,
            in_specs=(sx, sx, sx, sx, sx, sx, sx),
            out_specs=(sx, sx, sx, sx, sx, sx, sx,
                       (sx,) * 5, (sx,) * 5, sx)),
            donate_argnums=(2, 4, 5))

        def fp_rows(rows):
            return jax.vmap(fingerprint)(
                jax.vmap(unflatten_state, (0, None))(rows, dims))

        self._fp_rows = jax.jit(fp_rows)
        self._expand1 = jax.jit(expand)
        self._fp_batch = jax.jit(jax.vmap(fingerprint))
        self._root_check = (build_root_check(inv_fns, fingerprint)
                            if inv_fns else None)

    # ------------------------------------------------------------------
    def run(self, init_states: List[PyState]) -> EngineResult:
        dims, cfg = self.dims, self.config
        n, sw, B, QL, CL = self.n_dev, self._sw, self._B, self._QL, self._CL
        res = EngineResult()
        t_enter = time.time()   # for early returns before the budget clock
        trace = make_trace_store() if cfg.record_trace else TraceStore()
        self.trace = trace

        qcur = jnp.zeros((n, QL, sw), jnp.uint8)
        qnext = jnp.zeros((n, QL, sw), jnp.uint8)
        shi = jnp.full((n, CL), SENTINEL, _U32)
        slo = jnp.full((n, CL), SENTINEL, _U32)
        ssize = jnp.zeros((n,), _I32)
        next_counts = jnp.zeros((n,), _I32)

        encoded = [encode_state(s, dims) for s in init_states]
        # Pre-pack invariant check (engine/bfs.py build_root_check).
        if self._root_check is not None:
            v = find_root_violation(self._root_check, encoded, init_states,
                                    B, self.inv_names)
            if v is not None:     # before warm-up: no checking time elapsed
                res.violation = v
                res.stop_reason = "violation"
                res.levels.append(0)
                res.wall_seconds = time.time() - t_enter
                return res
        for e in encoded:         # reject silently-aliasing roots
            check_packable(e)
        rows_np = np.stack([flatten_state(e, dims) for e in encoded])
        if cfg.record_trace:
            rhi, rlo = (np.asarray(x) for x in
                        self._fp_rows(jnp.asarray(rows_np)))
            for idx, s in enumerate(init_states):
                trace.roots.setdefault(
                    (int(rhi[idx]) << 32) | int(rlo[idx]), s)

        # Warm-up compilation before the duration clock starts.
        out = self._ingest(jnp.zeros((n, B, sw), jnp.uint8),
                           jnp.zeros((n, B), bool),
                           qnext, next_counts, shi, slo, ssize)
        qnext, next_counts, shi, slo, ssize = out[:5]
        out = self._step(qcur, jnp.zeros((n,), _I32), jnp.int32(0),
                         qnext, next_counts, shi, slo, ssize)
        qnext, next_counts, shi, slo, ssize = out[:5]
        t0 = time.time()

        # Ingest roots round-robin across chips in B-sized waves.
        per_chip = [rows_np[i::n] for i in range(n)]
        max_chunks = max((-(-len(p) // B) for p in per_chip), default=0)
        for c in range(max_chunks):
            wave = np.zeros((n, B, sw), ROW_DTYPE)
            valid = np.zeros((n, B), bool)
            for d in range(n):
                part = per_chip[d][c * B:(c + 1) * B]
                wave[d, :len(part)] = part
                valid[d, :len(part)] = True
            out = self._ingest(jnp.asarray(wave), jnp.asarray(valid),
                               qnext, next_counts, shi, slo, ssize)
            (qnext, next_counts, shi, slo, ssize, g_new, g_fail, tr, vinfo,
             l_new) = out
            res.distinct += int(np.asarray(g_new)[0])
            self._record(trace, tr, np.asarray(l_new))
            self._capacity_check(next_counts, ssize,
                                 int(np.asarray(g_fail)[0]))
            if self._check_violation(res, vinfo):
                break

        res.levels.append(int(np.asarray(next_counts).sum()))
        qcur, qnext = qnext, qcur
        cur_counts = np.asarray(next_counts).copy()
        next_counts = jnp.zeros((n,), _I32)

        while cur_counts.sum() > 0 and res.violation is None \
                and res.stop_reason == "exhausted":
            if cfg.max_diameter is not None \
                    and res.diameter >= cfg.max_diameter:
                res.stop_reason = "diameter_budget"
                break
            offset = 0
            max_count = int(cur_counts.max())
            while offset < max_count:
                out = self._step(qcur, jnp.asarray(cur_counts, _I32),
                                 jnp.int32(offset), qnext, next_counts,
                                 shi, slo, ssize)
                (qnext, next_counts, shi, slo, ssize, stats, tr, vinfo,
                 drow, l_new) = out
                g_new = int(np.asarray(stats[0])[0])
                g_gen = int(np.asarray(stats[1])[0])
                g_ovf = int(np.asarray(stats[2])[0])
                dead = np.asarray(stats[3])
                if g_ovf:
                    raise RuntimeError(
                        f"{g_ovf} successors exceeded fixed-width capacity "
                        f"(max_log={dims.max_log}, "
                        f"n_msg_slots={dims.n_msg_slots})")
                res.distinct += g_new
                res.generated += g_gen
                self._record(trace, tr, np.asarray(l_new))
                self._capacity_check(next_counts, ssize,
                                     int(np.asarray(stats[4])[0]))
                if self._check_violation(res, vinfo):
                    break
                if dead.any() and cfg.check_deadlock:
                    d = int(np.argmax(dead))
                    res.deadlock = decode_state(
                        unflatten_state(np.asarray(drow)[d], dims), dims)
                    res.stop_reason = "deadlock"
                    break
                offset += B
                if (cfg.max_seconds is not None
                        and time.time() - t0 > cfg.max_seconds):
                    res.stop_reason = "duration_budget"
                    break
            if res.stop_reason != "exhausted" or res.violation is not None:
                break
            res.diameter += 1
            res.levels.append(int(np.asarray(next_counts).sum()))
            qcur, qnext = qnext, qcur
            cur_counts = np.asarray(next_counts).copy()
            next_counts = jnp.zeros((self.n_dev,), _I32)

        res.wall_seconds = time.time() - t0
        return res

    # ------------------------------------------------------------------
    def _capacity_check(self, next_counts, ssize, fail=0):
        if int(np.asarray(next_counts).max()) > self._QL:
            raise RuntimeError("per-chip queue capacity exceeded")
        if fail or int(np.asarray(ssize).max()) > self._CL:
            raise RuntimeError("per-chip seen-set capacity exceeded")

    def _record(self, trace, tr, l_new):
        if not self.config.record_trace:
            return
        sh, sl, ph, pl, ac = (np.asarray(x) for x in tr)
        for d in range(self.n_dev):
            m = int(l_new[d])
            if m == 0:
                continue
            fps = ((sh[d, :m].astype(np.uint64) << np.uint64(32))
                   | sl[d, :m].astype(np.uint64))
            parents = ((ph[d, :m].astype(np.uint64) << np.uint64(32))
                       | pl[d, :m].astype(np.uint64))
            trace.add_batch(fps, parents, ac[d, :m])

    def _check_violation(self, res, vinfo) -> bool:
        viol_any = np.asarray(vinfo[0])
        if not viol_any.any():
            return False
        d = int(np.argmax(viol_any))
        st = decode_state(
            unflatten_state(np.asarray(vinfo[2])[d], self.dims), self.dims)
        fp = (int(np.asarray(vinfo[3])[d]) << 32) | int(np.asarray(vinfo[4])[d])
        res.violation = Violation(
            invariant=self.inv_names[int(np.asarray(vinfo[1])[d])],
            state=st, fingerprint=fp)
        res.stop_reason = "violation"
        return True

    # Replay shares the single-engine mechanism.
    def replay(self, fp: int):
        from ..engine.bfs import BFSEngine  # reuse logic via duck typing
        return BFSEngine.replay(self, fp)
