"""Multi-host support — the DCN half of the distributed backend.

SURVEY §5.8 / §2.4 R7: the reference's implied runtime scales past one
machine with distributed TLC (RMI workers); the TPU-native equivalent is
multi-controller JAX — every host runs the SAME program over a global
``jax.sharding.Mesh`` spanning all processes' devices, and the XLA
collectives that dedup/aggregate across chips ride ICI within a host and
DCN between hosts with no code change in the compiled programs.

The compiled shard_map programs (parallel/mesh.py, parallel/simulate.py)
are already multi-host-clean: everything inside is per-shard compute plus
named-axis collectives.  What this module supplies is the HOST-side
contract that multi-controller execution demands:

- ``initialize()`` — process-group setup (wraps
  ``jax.distributed.initialize``; gloo on CPU, ICI/DCN on TPU pods).
- ``put_global(arr, mesh, spec)`` — build a sharded global array from a
  host value that every process computes identically; each process
  materializes only its addressable shards
  (``jax.make_array_from_callback``), so nothing is shipped cross-host.
  Works unchanged on a single-controller mesh.
- ``put_per_process(value, mesh)`` — a [n_devices] device vector where
  each process's shards carry ITS OWN value — the input to psum-style
  agreement on host-local facts (wall clocks differ per host; a stop
  decision must be collective or the next collective deadlocks).
- ``build_any(mesh)`` — a tiny jitted psum program turning per-process
  flags into one replicated boolean every process reads identically.

Host-loop rules for multi-controller engines (enforced by construction
in parallel/simulate.py):

1. every process executes the same sequence of compiled calls (trip
   counts must match — the programs contain collectives);
2. anything the host READS must be fully replicated output (psum'd in
   the program) — per-shard outputs are only fed back into the next
   call, never inspected;
3. anything the host WRITES into the mesh goes through put_global
   (identical everywhere) or put_per_process (explicitly local);
4. control-flow decisions from host-local state (clocks) go through
   build_any() agreement first.
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def initialize(coordinator: str = None, num_processes: int = None,
               process_id: int = None) -> None:
    """Join (or create) the process group.  Arguments default to the
    standard env vars (RAFT_COORDINATOR / RAFT_NUM_PROCESSES /
    RAFT_PROCESS_ID), so a launcher can export three variables and run
    the same command on every host."""
    coordinator = coordinator or os.environ.get("RAFT_COORDINATOR")
    if num_processes is None:
        num_processes = int(os.environ.get("RAFT_NUM_PROCESSES", "0")) or None
    if process_id is None:
        pid = os.environ.get("RAFT_PROCESS_ID")
        process_id = int(pid) if pid is not None else None
    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=num_processes,
                               process_id=process_id)


def is_multiprocess() -> bool:
    return jax.process_count() > 1


def put_global(arr: np.ndarray, mesh: Mesh, spec: P):
    """Shard an identically-computed-everywhere host array onto the mesh.
    Each process materializes only the shards its devices own."""
    sh = NamedSharding(mesh, spec)
    arr = np.asarray(arr)
    return jax.make_array_from_callback(arr.shape, sh, lambda idx: arr[idx])


def put_per_process(value: int, mesh: Mesh):
    """[n_devices] int32 vector where every device owned by this process
    holds this process's ``value`` (other processes fill their own)."""
    n = mesh.devices.size
    local = np.full((n,), np.int32(value))
    return jax.make_array_from_callback(
        (n,), NamedSharding(mesh, P("x")), lambda idx: local[idx])


def _build_agree(mesh: Mesh, reduce_fn):
    """One compiled psum/pmin-style reduction over per-process int32
    values: the shared plumbing behind every agreement primitive."""

    def agree(vals):
        return reduce_fn(vals[0], "x")

    from ..utils.platform import compat_shard_map
    return jax.jit(compat_shard_map(mesh)(
        agree, in_specs=P("x"), out_specs=P()))


def build_any(mesh: Mesh):
    """Agreement primitive: per-process flags -> one replicated 'did
    anyone flag?' boolean."""
    fn = _build_agree(mesh, jax.lax.psum)

    def any_flag(value: bool) -> bool:
        return bool(np.asarray(fn(put_per_process(int(value), mesh))) > 0)

    return any_flag


def build_min(mesh: Mesh):
    """Agreement primitive for VALUES: every process contributes an int,
    all read back the minimum — e.g. agreeing on a chunk-size budget
    derived from per-host clocks (the conservative choice never overshoots
    a deadline)."""
    fn = _build_agree(mesh, jax.lax.pmin)

    def min_val(value: int) -> int:
        return int(np.asarray(fn(put_per_process(int(value), mesh))))

    return min_val


def build_sum(mesh: Mesh):
    """Agreement primitive summing per-PROCESS ints (each process's value
    counted ONCE, not once per device: only the process's first device
    row carries it) — e.g. totalling the per-controller spill-pool rows
    for a global queue size."""
    fn = _build_agree(mesh, jax.lax.psum)
    n = mesh.devices.size
    me = jax.process_index()
    first = min((i for i, d in enumerate(mesh.devices.flat)
                 if d.process_index == me), default=0)

    # The device agreement runs in int32 (JAX x64 is off) and pool row
    # counts at the spill design scale can exceed it: saturate each
    # process's contribution so the device-side sum cannot wrap.  A
    # saturated total still trips every budget below ~2^31/N rows — it
    # can only over-report, never under-report.
    cap = ((1 << 31) - 1) // max(1, jax.process_count())

    def sum_val(value: int) -> int:
        local = np.zeros((n,), np.int32)
        local[first] = min(int(value), cap)
        arr = jax.make_array_from_callback(
            (n,), NamedSharding(mesh, P("x")),
            lambda idx: local[idx[0].start:idx[0].stop])
        return int(np.asarray(fn(arr)))

    return sum_val


def build_budget_agree(mesh: Mesh):
    """Fused per-chunk budget agreement — ONE cross-host round trip for
    the pair every budgeted chunk needs: (any process over deadline?,
    min of the per-process chunk-size budgets)."""
    n = mesh.devices.size

    def agree(vals):
        v = vals[0]
        return jnp.stack([jax.lax.psum(v[0], "x"),
                          jax.lax.pmin(v[1], "x")])

    from ..utils.platform import compat_shard_map
    fn = jax.jit(compat_shard_map(mesh)(
        agree, in_specs=P("x"), out_specs=P()))

    def budget(over: bool, allowed: int):
        local = np.tile(np.asarray([int(over), int(allowed)], np.int32),
                        (n, 1))
        arr = jax.make_array_from_callback(
            (n, 2), NamedSharding(mesh, P("x")), lambda idx: local[idx])
        out = np.asarray(fn(arr))
        return bool(out[0] > 0), int(out[1])

    return budget


def bcast_lowest_flagged(axis: str, flag, *values):
    """Inside a shard_map'd program: broadcast ``values`` from the
    lowest-axis-indexed shard whose ``flag`` is set, so every shard (and
    hence every controller) reads identical replicated results.  Returns
    (any_flag_set, broadcast_values...)."""
    idx = jax.lax.axis_index(axis)
    far = jnp.int32(1 << 30)
    chosen = jax.lax.pmin(jnp.where(flag, idx, far), axis)
    sel = flag & (idx == chosen)
    out = tuple(
        jax.lax.psum(jnp.where(sel, v, jnp.zeros_like(v)), axis)
        for v in values)
    return (chosen < far,) + out
