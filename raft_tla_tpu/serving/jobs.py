"""Job records and the persistent job journal.

A **job** is one queued unit of server work (a ``check`` or ``simulate``
request) with an identity, a tenant, and a fully observable lifecycle:

    queued -> admitted -> running -> done | failed
    queued -> admitted -> cancelled

``queued``     accepted past admission control, waiting in the bounded
               queue;
``admitted``   selected by the fair scheduler, handed to the executor
               (transient — the window in which a cancel can still win);
``running``    executing on the device (non-preemptible: one engine run
               owns the device, so a running job cannot be cancelled);
``done``       completed with an ``{"ok": true}`` response;
``failed``     completed with an error (engine exception, ``ok: false``
               response, or lost to repeated server restarts);
``cancelled``  terminal before any device work — a cancelled job NEVER
               ran and never has a result (the invariant the races test
               pins).

Durability: every submit and every state transition appends one line to
the **job journal** (``<base_dir>/jobs.jsonl``, the same append-only
JSONL idiom as the run-history ledger).  :func:`replay` folds the
journal back into the final job table, which is how a restarted server
resumes its queue — see ``serving/manager.py`` for the resume policy
(queued jobs re-enqueue; a job caught ``running`` by the crash is
re-run once, then marked failed with a postmortem pointer).

Zero-dependency and jax-free, like ``obs/`` — the journal must be
readable from tooling that never touches a device.
"""

from __future__ import annotations

import json
import os
import time
from typing import Dict, Optional, Tuple

#: Every state a job can be in, in lifecycle order.
JOB_STATES = ("queued", "admitted", "running", "done", "failed",
              "cancelled")

#: States a job never leaves.
TERMINAL_STATES = ("done", "failed", "cancelled")

#: Live (non-terminal) states — what "the job is alive" means for the
#: watch-idle interplay (server._serve_watch must not reap a watcher
#: while its job is in one of these).
LIVE_STATES = ("queued", "admitted", "running")


class QueueFullError(RuntimeError):
    """Admission reject: the bounded queue is at capacity.  The server
    renders this as a clean ``{"ok": false}`` line; the manager has
    already counted ``server/rejected/queue_full``."""


def new_job(job_id: str, tenant: str, request: dict, *,
            label: Optional[str] = None,
            cache_key: Optional[str] = None,
            slo_seconds: Optional[float] = None,
            ts: Optional[float] = None) -> dict:
    """A fresh job record (plain dict — journal lines and op responses
    serialize it directly).  Result payloads are kept OUT of the record
    (the manager stores them separately) so ``jobs``-op listings stay
    small no matter how big a check response is."""
    return {
        "id": job_id,
        "tenant": tenant,
        "label": label,
        "state": "queued",
        "request": request,
        "cache_key": cache_key,
        "slo_seconds": slo_seconds,
        "created_ts": round(time.time() if ts is None else ts, 6),
        # When the job last entered the queue: submit time, reset by a
        # restart's re-enqueue — the queue-wait base (a crash's
        # downtime is turnaround, never queueing).
        "enqueued_ts": round(time.time() if ts is None else ts, 6),
        "admitted_ts": None,
        "started_ts": None,
        "finished_ts": None,
        "queue_wait_seconds": None,
        "run_seconds": None,
        "turnaround_seconds": None,
        "restarts": 0,
        "cached": False,
        "events_out": None,      # per-job scoped JSONL event log
        "job_dir": None,         # per-job artifact dir (postmortem.json)
        "postmortem": None,      # pointer to a crash dump, when one exists
        "error": None,
        "note": None,
    }


#: Fields the ``jobs``/``status`` ops (and the HTTP /jobs endpoint)
#: expose — everything except the raw request (which can carry a whole
#: cfg_text) and the result (served by the ``result`` op only).
SUMMARY_FIELDS = ("id", "tenant", "label", "state", "created_ts",
                  "admitted_ts", "started_ts", "finished_ts",
                  "queue_wait_seconds", "run_seconds",
                  "turnaround_seconds", "restarts", "cached",
                  "events_out", "postmortem", "error", "note")


def summarize(job: dict, has_result: bool = False) -> dict:
    out = {k: job.get(k) for k in SUMMARY_FIELDS}
    out["has_result"] = has_result
    return out


# -- journal ---------------------------------------------------------------

def append_record(path: str, rec: dict) -> None:
    """One JSONL line, through the history ledger's single append
    idiom (``default=str``: job requests may carry caller objects)."""
    from ..obs.history import append_entry
    append_entry(path, rec, default=str)


def submit_record(job: dict) -> dict:
    return {"rec": "submit", "ts": round(time.time(), 6),
            "job": {k: v for k, v in job.items()}}


def state_record(job: dict, patch: Optional[dict] = None,
                 result: Optional[dict] = None) -> dict:
    rec = {"rec": "state", "ts": round(time.time(), 6),
           "id": job["id"], "state": job["state"]}
    if patch:
        rec["patch"] = dict(patch)
    if result is not None:
        # Terminal ``done`` lines carry the result so a restarted server
        # can still serve the ``result`` op for pre-restart jobs.
        rec["result"] = result
    return rec


def replay(path: str) -> Tuple[Dict[str, dict], Dict[str, dict],
                               list]:
    """Fold the journal into ``(jobs by id, results by id, problems)``
    — each job's record is its submit line with every subsequent state
    line's ``state``/``patch`` applied in order.

    Replay is TOLERANT by design: the journal is written best-effort
    (a full disk degrades to lost durability, never a dead server), so
    a torn trailing line from a crash or an orphan state record whose
    submit line was dropped are expected degradations, not reasons to
    refuse every future restart on this job dir.  Unusable lines are
    skipped and reported as ``problems`` — ``[(lineno, reason), ...]``
    — which the manager surfaces loudly (stderr + counter); a missing
    file is an empty table."""
    jobs: Dict[str, dict] = {}
    results: Dict[str, dict] = {}
    problems: list = []
    if not os.path.exists(path):
        return jobs, results, problems
    with open(path, encoding="utf-8") as f:
        for ln, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError as e:
                problems.append((ln, f"malformed line ({e})"))
                continue
            kind = rec.get("rec") if isinstance(rec, dict) else None
            if kind == "submit":
                job = rec.get("job")
                if not isinstance(job, dict) or "id" not in job:
                    problems.append((ln, "submit record without a job "
                                         "object"))
                    continue
                jobs[job["id"]] = dict(job)
            elif kind == "state":
                job = jobs.get(rec.get("id"))
                if job is None:
                    problems.append(
                        (ln, f"state record for unknown job "
                             f"{rec.get('id')!r} (its submit line was "
                             f"lost)"))
                    continue
                if rec.get("state") not in JOB_STATES:
                    problems.append(
                        (ln, f"unknown state {rec.get('state')!r}"))
                    continue
                job["state"] = rec["state"]
                patch = rec.get("patch")
                if isinstance(patch, dict):
                    job.update(patch)
                if "result" in rec:
                    results[job["id"]] = rec["result"]
            else:
                problems.append((ln, f"not a journal record: "
                                     f"{line[:80]}"))
    return jobs, results, problems
