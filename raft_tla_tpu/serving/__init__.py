"""Check-as-a-service job layer (ROADMAP item 3, first slice).

The checker service (server.py) historically ran one blocking check at
a time under the device lock with no job identity: a client that
disconnected lost its run, a slow model starved everyone behind it
invisibly, and nothing attributed device time to tenants.  This package
is the serving spine that fixes the *observability* half first — you
cannot schedule what you cannot name:

- :mod:`.jobs` — job records (states ``queued -> admitted -> running ->
  done|failed|cancelled``) and the append-only JSONL **job journal**
  that makes the registry survive a server restart;
- :mod:`.manager` — :class:`~.manager.JobManager`: bounded admission,
  per-tenant round-robin fair scheduling, a single executor thread
  (engine semantics untouched — one run still owns the device), journal
  replay with re-run/fail-with-postmortem semantics for the job a crash
  caught running, a fingerprint-keyed result cache, and per-tenant
  counters + queue-wait/turnaround/SLO histograms + by-state gauges in
  the shared MetricsRegistry.

server.py exposes it as the ``submit`` / ``status`` / ``result`` /
``cancel`` / ``jobs`` ops, per-job ``watch`` attach, and the
server-native HTTP ``/metrics`` + ``/jobs`` endpoints; the CLI client
side is ``python -m raft_tla_tpu submit|jobs|watch``.  README "Serving
& jobs" documents the op schemas and metric names.

Jax-free at import, like ``obs/``.
"""

from .jobs import (JOB_STATES, LIVE_STATES, QueueFullError,  # noqa: F401
                   TERMINAL_STATES)
from .manager import JobManager                              # noqa: F401
