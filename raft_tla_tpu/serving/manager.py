"""Async job manager — the observable admission/scheduling/execution
spine of check-as-a-service (ROADMAP item 3).

One :class:`JobManager` owns:

- a **persistent job registry** (serving/jobs.py): every submit and
  state transition journals to ``<base_dir>/jobs.jsonl``; a restarted
  manager replays the journal — terminal jobs keep their results,
  queued/admitted jobs re-enqueue, and a job caught ``running`` by the
  crash is re-queued once (``requeued_after_restart``) then, on a
  second loss, marked failed with a pointer to its postmortem dump;
- a **bounded admission queue** with per-tenant fair scheduling:
  round-robin across tenants (Index-Based Scheduling's fairness signal,
  PAPERS.md #5 — a queue-flooding tenant cannot starve the others;
  FIFO within a tenant), rejecting past ``queue_capacity`` with
  ``server/rejected/queue_full`` + per-tenant reject counters;
- a **single executor thread** that runs one job at a time through the
  caller-supplied ``executor(request, job)`` callable — the server
  wraps its existing ``_do_check``/``_do_simulate`` under the device
  lock, so engine semantics (one run owns the device) are untouched;
- a bounded **result cache** keyed by the submit op's content
  fingerprint (the history ledger's cfg-fingerprint idiom): a hit
  completes the job without a device run (``cached: true``), counted
  in ``jobs/result_cache/hits|misses``.

Observability is the product — every seam lands in the shared
MetricsRegistry:

counters    ``jobs/submitted/<tenant>``, ``jobs/done/<tenant>``,
            ``jobs/failed/<tenant>``, ``jobs/cancelled/<tenant>``,
            ``jobs/rejected/<tenant>``, ``jobs/slo_ok/<tenant>``,
            ``jobs/slo_miss/<tenant>``, ``server/rejected/queue_full``,
            ``jobs/result_cache/hits|misses``,
            ``jobs/requeued_after_restart``
gauges      ``jobs/queue_depth``, ``jobs/running``,
            ``jobs/state/<state>`` (one per lifecycle state)
histograms  ``jobs/queue_wait_seconds``, ``jobs/run_seconds``,
            ``jobs/turnaround_seconds`` (+ per-tenant queue-wait and
            turnaround) — the SLO surface: the registry's cumulative
            ``le`` buckets render as Prometheus histogram series, so
            "p99 turnaround under X s" is a stock PromQL query; the
            explicit ``slo_ok``/``slo_miss`` counters track the per-job
            ``slo_seconds`` target (manager default, overridable per
            submit).

Tenant metric names are client-controlled strings, which must never
grow the process-global registry without bound (the server's
metric-label rule): tenant labels are sanitized and capped — after
``tenant_cap`` distinct tenants, new ones fold into ``other``.

Jax-free: the manager only schedules; everything device-shaped lives in
the executor callable.
"""

from __future__ import annotations

import os
import re
import threading
import time
from collections import OrderedDict, deque
from typing import Callable, Dict, List, Optional

from . import jobs as jobs_mod
from .jobs import (LIVE_STATES, QueueFullError, TERMINAL_STATES,
                   new_job, state_record, submit_record, summarize)

_TENANT_RE = re.compile(r"[^a-zA-Z0-9_.-]+")


class JobManager:
    def __init__(self, base_dir: str, *,
                 executor: Callable[[dict, dict], dict],
                 metrics=None,
                 queue_capacity: int = 64,
                 max_restarts: int = 1,
                 slo_seconds: float = 60.0,
                 history_path: Optional[str] = None,
                 tenant_cap: int = 32,
                 result_cache_cap: int = 128,
                 max_terminal_jobs: int = 10000,
                 start: bool = True):
        if metrics is None:
            from ..obs import MetricsRegistry
            metrics = MetricsRegistry()
        self.base_dir = os.path.abspath(base_dir)
        self.journal_path = os.path.join(self.base_dir, "jobs.jsonl")
        self.queue_capacity = int(queue_capacity)
        self.max_restarts = int(max_restarts)
        # Terminal-job retention: the in-memory registry (and result
        # store) keeps at most this many done/failed/cancelled jobs,
        # evicting oldest-first — the journal on disk keeps the full
        # history, but a long-lived server must not grow without bound.
        self.max_terminal_jobs = int(max_terminal_jobs)
        self.slo_seconds = float(slo_seconds)
        self.history_path = history_path
        self.tenant_cap = int(tenant_cap)
        self.metrics = metrics
        self._executor = executor
        self._cond = threading.Condition()
        self._jobs: Dict[str, dict] = {}   # insertion-ordered (oldest first)
        self._results: Dict[str, dict] = {}
        # Incrementally maintained state census: admission depth checks
        # and the gauge refresh must stay O(1) per operation, not
        # O(total jobs ever submitted) — this is the long-lived-service
        # hot path.
        self._state_counts: Dict[str, int] = {
            s: 0 for s in jobs_mod.JOB_STATES}
        # Terminal jobs in completion order — the retention pruner's
        # eviction queue (O(excess) per eviction, no registry scan).
        self._terminal_order: deque = deque()
        # Fair scheduler state: FIFO per tenant, picked least-recently-
        # served first (ties broken by tenant join order) — exact
        # round-robin that stays fair when a tenant joins mid-stream,
        # which a rotating ring does not (the just-served tenant would
        # sit in front of the newcomer).
        self._queues: Dict[str, deque] = {}
        self._served_seq = 0
        self._join_seq = 0
        self._tenant_rank: Dict[str, tuple] = {}  # t -> (served, join)
        self._running_id: Optional[str] = None
        self._counter = 0
        self._tenants_seen: Dict[str, str] = {}   # tenant -> metric label
        self._cache: "OrderedDict[str, dict]" = OrderedDict()
        self._cache_cap = int(result_cache_cap)
        self._stop = False
        self._thread = None
        os.makedirs(self.base_dir, exist_ok=True)
        self._replay()
        self._update_gauges_locked()
        if start:
            self._thread = threading.Thread(
                target=self._loop, name="job-executor", daemon=True)
            self._thread.start()

    # -- admission -----------------------------------------------------
    def submit(self, request: dict, tenant: Optional[str] = None,
               *, label: Optional[str] = None,
               cache_key: Optional[str] = None,
               slo_seconds: Optional[float] = None) -> dict:
        """Admit one job (or raise :class:`QueueFullError`); returns the
        queued job's summary.  ``request`` is the inner check/simulate
        request the executor will run verbatim."""
        tenant = str(tenant or "default")
        tlabel = self._tenant_label(tenant)
        with self._cond:
            depth = self._state_counts["queued"]
            if depth >= self.queue_capacity:
                self.metrics.counter("server/rejected/queue_full")
                self.metrics.counter(f"jobs/rejected/{tlabel}")
                raise QueueFullError(
                    f"admission queue full ({depth} queued, capacity "
                    f"{self.queue_capacity}); retry later")
            self._counter += 1
            job_id = f"j{self._counter:06d}-{os.urandom(3).hex()}"
            job = new_job(job_id, tenant, dict(request), label=label,
                          cache_key=cache_key,
                          slo_seconds=(float(slo_seconds)
                                       if slo_seconds is not None
                                       else self.slo_seconds))
            job["job_dir"] = os.path.join(self.base_dir, job_id)
            if request.get("op") != "simulate":
                # Scoped event log for engine-backed jobs only: the
                # simulator has no run-event log, so the summary must
                # not advertise a file that will never exist.
                job["events_out"] = os.path.join(job["job_dir"],
                                                 "events.jsonl")
            self._register_locked(job)
            self._enqueue_locked(job)
            self._journal(submit_record(job))
            self.metrics.counter(f"jobs/submitted/{tlabel}")
            self._update_gauges_locked()
            self._cond.notify_all()
            return summarize(job)

    def cancel(self, job_id: str) -> dict:
        """queued/admitted -> cancelled.  Running jobs are NOT
        cancellable (a single-device engine run is non-preemptible) and
        terminal jobs stay terminal — both raise, which the server
        renders as a clean ``{"ok": false}``.  The cancelled-job
        invariant: it never reaches the executor, never has a result,
        and its state never changes again."""
        with self._cond:
            job = self._require(job_id)
            st = job["state"]
            if st in TERMINAL_STATES:
                raise ValueError(f"job {job_id} already {st}")
            if st == "running":
                raise ValueError(
                    f"job {job_id} is running; a single-device engine "
                    f"run is not preemptible")
            self._transition_locked(
                job, "cancelled",
                patch={"finished_ts": round(time.time(), 6)})
            self.metrics.counter(
                f"jobs/cancelled/{self._tenant_label(job['tenant'])}")
            self._update_gauges_locked()
            return summarize(job)

    # -- queries -------------------------------------------------------
    def get(self, job_id: str) -> dict:
        with self._cond:
            job = self._require(job_id)
            return summarize(job, has_result=job_id in self._results)

    def result_doc(self, job_id: str) -> dict:
        """``{"state": ..., "result": ...}`` read under ONE lock — the
        result op must never fetch a result and then lose the state
        read to a terminal-retention eviction between two locks."""
        with self._cond:
            job = self._require(job_id)
            if job["state"] not in TERMINAL_STATES:
                raise ValueError(f"job {job_id} is {job['state']}; "
                                 f"no result yet")
            res = self._results.get(job_id)
            if res is None:
                raise ValueError(f"job {job_id} {job['state']}"
                                 + (f": {job['error']}" if job["error"]
                                    else " with no result"))
            return {"state": job["state"], "result": dict(res)}

    def result(self, job_id: str) -> dict:
        return self.result_doc(job_id)["result"]

    def jobs_doc(self, tenant: Optional[str] = None,
                 state: Optional[str] = None,
                 limit: Optional[int] = None) -> dict:
        """The ``jobs`` op / HTTP ``/jobs`` document: summaries (oldest
        first) + the same queue-depth/running/by-state numbers the
        gauges carry, read in one locked snapshot so the two surfaces
        agree.  The registry is insertion-ordered by construction
        (submit appends, replay rebuilds sorted), so no per-call sort;
        ``limit`` keeps the NEWEST N rows — a periodic scraper against
        a 10k-job retention must not serialize megabytes under the
        manager lock per poll."""
        with self._cond:
            out: List[dict] = []
            for job in self._jobs.values():
                if tenant is not None and job["tenant"] != tenant:
                    continue
                if state is not None and job["state"] != state:
                    continue
                out.append(summarize(job,
                                     has_result=job["id"] in
                                     self._results))
            if limit is not None and limit > 0:
                out = out[-limit:]
            by_state = dict(self._state_counts)
            return {"jobs": out,
                    "queue_depth": by_state["queued"],
                    "running": by_state["running"],
                    "by_state": by_state,
                    "queue_capacity": self.queue_capacity}

    def running_job_id(self) -> Optional[str]:
        with self._cond:
            return self._running_id

    def has_live_jobs(self) -> bool:
        """Any job queued/admitted/running — the watch-idle liveness
        signal (server._serve_watch: a watcher is not idle while the
        manager still owes work)."""
        with self._cond:
            return any(self._state_counts[s] > 0 for s in LIVE_STATES)

    def close(self, wait: bool = True,
              wait_timeout: float = 600.0) -> bool:
        """Stop the executor thread (the in-flight job, if any, runs to
        completion).  Queued jobs stay queued — journaled, so the next
        manager on this base_dir resumes them.

        Returns True when the executor is known to be stopped (or was
        never started); False when ``wait`` timed out or was skipped
        while a job may still be running — the caller must NOT treat
        the journal as settled (starting a successor manager on this
        base_dir before the executor finishes would replay the
        'running' tail and execute that job twice)."""
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        t = self._thread
        if t is None or not t.is_alive():
            return True
        if not wait:
            return False
        t.join(timeout=wait_timeout)
        return not t.is_alive()

    # -- internals -----------------------------------------------------
    def _require(self, job_id: str) -> dict:
        job = self._jobs.get(job_id)
        if job is None:
            raise KeyError(f"unknown job {job_id!r}")
        return job

    def _tenant_label(self, tenant: str) -> str:
        """Sanitized, bounded metric label for a tenant (see module
        docstring): the registry must never grow one series per
        arbitrary client string.  Distinct tenants must also never
        MERGE: when two raw ids sanitize to the same label ('acme corp'
        vs 'acme_corp'), the later one gets a short content-hash
        suffix so per-tenant accounting stays per-tenant."""
        with self._cond:
            lbl = self._tenants_seen.get(tenant)
            if lbl is not None:
                return lbl
            if len(self._tenants_seen) >= self.tenant_cap:
                return "other"
            lbl = _TENANT_RE.sub("_", tenant)[:32] or "default"
            # "other" is RESERVED for the cap-overflow fold: a real
            # tenant whose id sanitizes to it must not absorb every
            # post-cap tenant's series.
            if lbl == "other" or lbl in self._tenants_seen.values():
                import hashlib
                lbl = (lbl[:25] + "-"
                       + hashlib.sha256(tenant.encode())
                       .hexdigest()[:6])
            self._tenants_seen[tenant] = lbl
            return lbl

    #: Fairness-memory bound: ranks for at most this many tenants are
    #: retained.  Tenant ids are raw client strings on an
    #: unauthenticated service, so every per-tenant structure must be
    #: bounded (the same rule as the metric-label cap) — evicting an
    #: idle tenant's rank only resets its fairness memory.
    TENANT_RANK_CAP = 4096

    def _enqueue_locked(self, job: dict) -> None:
        t = job["tenant"]
        q = self._queues.get(t)
        if q is None:
            q = self._queues[t] = deque()
        q.append(job["id"])
        if t not in self._tenant_rank:
            self._join_seq += 1
            self._tenant_rank[t] = (0, self._join_seq)
            if len(self._tenant_rank) > self.TENANT_RANK_CAP:
                idle = [(rank, name) for name, rank
                        in self._tenant_rank.items()
                        if name != t and not self._queues.get(name)]
                for _rank, name in sorted(idle)[:len(idle) // 2 + 1]:
                    del self._tenant_rank[name]

    def _pick_locked(self) -> Optional[dict]:
        """Fair pick: the least-recently-served tenant with a genuinely
        queued job (cancelled entries are dropped lazily), FIFO within
        the tenant."""
        while True:
            candidates = [t for t, q in self._queues.items() if q]
            if not candidates:
                return None
            t = min(candidates, key=lambda t: self._tenant_rank[t])
            q = self._queues[t]
            job = None
            while q:
                job = self._jobs.get(q.popleft())
                if job is not None and job["state"] == "queued":
                    break
                job = None
            if not q:
                del self._queues[t]
            if job is not None:
                self._served_seq += 1
                self._tenant_rank[t] = (self._served_seq,
                                        self._tenant_rank[t][1])
                return job

    def _journal(self, rec: dict) -> None:
        """Best-effort journal append: a full disk must degrade to a
        loudly-counted loss of restart durability, never kill the
        executor thread or strand the in-memory registry (the scheduler
        keeps the truth; the journal is its shadow)."""
        try:
            jobs_mod.append_record(self.journal_path, rec)
        except OSError as e:
            self.metrics.counter("jobs/journal_errors")
            import sys
            print(f"job journal append failed ({e}); registry stays "
                  f"in-memory-consistent, restart durability degraded",
                  file=sys.stderr)

    def _register_locked(self, job: dict) -> None:
        """Add a job to the registry + state census (submit/replay)."""
        self._jobs[job["id"]] = job
        self._state_counts[job["state"]] += 1

    def _transition_locked(self, job: dict, state: str,
                           patch: Optional[dict] = None,
                           result: Optional[dict] = None) -> None:
        self._state_counts[job["state"]] -= 1
        job["state"] = state
        self._state_counts[state] += 1
        if patch:
            job.update(patch)
        if result is not None:
            self._results[job["id"]] = result
        self._journal(state_record(job, patch=patch, result=result))
        if state in TERMINAL_STATES:
            self._terminal_order.append(job["id"])
            self._prune_terminal_locked()

    def _prune_terminal_locked(self) -> None:
        """Evict oldest terminal jobs past the retention cap (their
        journal history survives on disk; the ``result``/``status`` ops
        just stop answering for them).  Walks the completion-order
        deque, not the registry — O(excess) per call."""
        excess = (sum(self._state_counts[s] for s in TERMINAL_STATES)
                  - self.max_terminal_jobs)
        while excess > 0 and self._terminal_order:
            jid = self._terminal_order.popleft()
            job = self._jobs.get(jid)
            if job is None or job["state"] not in TERMINAL_STATES:
                continue
            self._state_counts[job["state"]] -= 1
            del self._jobs[jid]
            self._results.pop(jid, None)
            self.metrics.counter("jobs/evicted")
            excess -= 1

    def _update_gauges_locked(self) -> None:
        mt = self.metrics
        mt.gauge("jobs/queue_depth", self._state_counts["queued"])
        mt.gauge("jobs/running", self._state_counts["running"])
        for s, n in self._state_counts.items():
            mt.gauge(f"jobs/state/{s}", n)

    def _history_entry(self, job: dict, verdict: str) -> None:
        """Restart-resume bookkeeping in the run-history ledger (the
        per-run ``kind=server`` entries ride the executor path in
        server.py; these cover the jobs a restart touched without
        running them)."""
        if not self.history_path:
            return
        try:
            from ..obs import history as history_mod
            history_mod.append_entry(
                self.history_path,
                history_mod.make_entry(
                    "server", label=job.get("label") or job["id"],
                    verdict=verdict,
                    extra={"job_id": job["id"],
                           "tenant": job["tenant"]}))
        except Exception:
            pass         # ledger bookkeeping must never kill scheduling

    def _replay(self) -> None:
        """Journal replay (restart durability): rebuild the job table,
        re-enqueue the still-live jobs, and settle the job the crash
        caught ``running`` — re-queued up to ``max_restarts`` times
        (counted, noted), then failed with a pointer to its postmortem
        dump when one exists."""
        jobs, results, problems = jobs_mod.replay(self.journal_path)
        if problems:
            # Degraded journal (torn line, dropped record): recover
            # what parsed, say what was lost — loudly, but never
            # refuse to start (the brick-on-restart failure mode).
            self.metrics.counter("jobs/journal_skipped", len(problems))
            import sys
            for ln, reason in problems[:10]:
                print(f"job journal {self.journal_path}:{ln}: {reason} "
                      f"(skipped)", file=sys.stderr)
            if len(problems) > 10:
                print(f"job journal: ... and {len(problems) - 10} more "
                      f"skipped lines", file=sys.stderr)
        # Rebuild in created-order so the insertion-ordered registry
        # (the retention pruner's eviction order) matches history.
        self._jobs = dict(sorted(jobs.items(),
                                 key=lambda kv: (kv[1]["created_ts"],
                                                 kv[0])))
        self._results = results
        self._counter = len(jobs)
        for job in self._jobs.values():
            self._state_counts[job["state"]] += 1
        for job in list(self._jobs.values()):
            st = job["state"]
            if st in TERMINAL_STATES:
                self._terminal_order.append(job["id"])
                key = job.get("cache_key")
                if st == "done" and key and job["id"] in results:
                    self._cache[key] = results[job["id"]]
                    self._cache.move_to_end(key)
                    while len(self._cache) > self._cache_cap:
                        # Same bound as the live store path: a journal
                        # with years of cached jobs must not rebuild an
                        # unbounded result cache (newest entries win).
                        self._cache.popitem(last=False)
                continue
            if st in ("queued", "admitted"):
                self._transition_locked(
                    job, "queued",
                    # enqueued_ts resets: the queue-wait histogram must
                    # price THIS server's queue, not the downtime.
                    patch={"note": "resumed_after_restart",
                           "enqueued_ts": round(time.time(), 6)})
                self._enqueue_locked(job)
                continue
            # st == "running": the crash took this one mid-run.
            if job.get("restarts", 0) < self.max_restarts:
                self._transition_locked(
                    job, "queued",
                    patch={"restarts": job.get("restarts", 0) + 1,
                           "note": "requeued_after_restart",
                           "started_ts": None,
                           "enqueued_ts": round(time.time(), 6)})
                self.metrics.counter("jobs/requeued_after_restart")
                self._history_entry(job, "requeued-after-restart")
                self._enqueue_locked(job)
            else:
                pm = (os.path.join(job["job_dir"], "postmortem.json")
                      if job.get("job_dir") else None)
                if pm is not None and not os.path.exists(pm):
                    pm = None
                self._transition_locked(
                    job, "failed",
                    patch={"finished_ts": round(time.time(), 6),
                           "error": f"lost to {job['restarts'] + 1} "
                                    f"server restart(s) while running",
                           "postmortem": pm})
                self.metrics.counter(
                    f"jobs/failed/{self._tenant_label(job['tenant'])}")
                self._history_entry(job, "lost-after-restart")
        # The retention cap applies to the REPLAYED registry too: a
        # journal holding years of terminal history must not rebuild
        # into an unbounded in-memory table.
        self._prune_terminal_locked()

    # -- executor ------------------------------------------------------
    def _loop(self) -> None:
        """Executor thread main: one job at a time through
        ``_run_one``.  The outer guard exists so NOTHING — journal
        I/O, metrics, a pathological job record — can silently kill
        the thread and strand the queue; an iteration that blows up is
        counted, reported, and the loop continues."""
        while True:
            try:
                if not self._run_one():
                    return
            except Exception as e:
                self.metrics.counter("jobs/executor_errors")
                import sys
                print(f"job executor iteration failed "
                      f"({type(e).__name__}: {e}); continuing",
                      file=sys.stderr)
                time.sleep(0.25)     # never a tight crash loop

    def _run_one(self) -> bool:
        """Pick + run one job; returns False when stop was requested."""
        with self._cond:
            job = None
            while not self._stop:
                job = self._pick_locked()
                if job is not None:
                    break
                self._cond.wait(0.25)
            if self._stop and job is None:
                return False
            now = round(time.time(), 6)
            self._transition_locked(job, "admitted",
                                    patch={"admitted_ts": now})
            self._update_gauges_locked()
        # Per-job artifact dir outside the lock (filesystem work).
        try:
            os.makedirs(job["job_dir"], exist_ok=True)
        except OSError:
            pass
        with self._cond:
            if job["state"] != "queued" and job["state"] != "admitted":
                # A cancel won the admitted window: the job is
                # terminal and must never reach the executor.
                self._update_gauges_locked()
                return True
            now = round(time.time(), 6)
            # Queue wait is measured from the LAST enqueue (submit, or
            # a restart's re-enqueue) — a crash's downtime is turnaround,
            # not queueing, and must not pollute the queue-wait SLO.
            wait = now - (job.get("enqueued_ts") or job["created_ts"])
            self._transition_locked(
                job, "running",
                patch={"started_ts": now,
                       "queue_wait_seconds": round(wait, 6)})
            self._running_id = job["id"]
            self._update_gauges_locked()
        tlabel = self._tenant_label(job["tenant"])
        mt = self.metrics
        mt.observe("jobs/queue_wait_seconds", wait)
        mt.observe(f"jobs/queue_wait_seconds/{tlabel}", wait)
        resp, cached, err = None, False, None
        try:
            resp, cached = self._execute(job)
        except Exception as e:
            err = f"{type(e).__name__}: {e}"
        now = round(time.time(), 6)
        run_s = now - job["started_ts"]
        turnaround = now - job["created_ts"]
        ok = err is None and isinstance(resp, dict) \
            and resp.get("ok") is True
        with self._cond:
            patch = {"finished_ts": now,
                     "run_seconds": round(run_s, 6),
                     "turnaround_seconds": round(turnaround, 6),
                     "cached": cached}
            if cached:
                # No engine ran, so no scoped event log was written —
                # the summary must not advertise a file that does not
                # exist (same contract as simulate jobs).
                patch["events_out"] = None
            if not ok:
                patch["error"] = err or (resp or {}).get("error") \
                    or "executor returned no response"
                pm = os.path.join(job["job_dir"], "postmortem.json")
                patch["postmortem"] = pm if os.path.exists(pm) \
                    else None
            self._transition_locked(
                job, "done" if ok else "failed", patch=patch,
                result=resp if isinstance(resp, dict) else None)
            self._running_id = None
            self._update_gauges_locked()
            self._cond.notify_all()
        mt.counter(f"jobs/{'done' if ok else 'failed'}/{tlabel}")
        mt.observe("jobs/run_seconds", run_s)
        mt.observe("jobs/turnaround_seconds", turnaround)
        mt.observe(f"jobs/turnaround_seconds/{tlabel}", turnaround)
        slo = job.get("slo_seconds")
        if slo:
            mt.counter(f"jobs/slo_{'ok' if turnaround <= slo else 'miss'}"
                       f"/{tlabel}")
        return True

    def _execute(self, job: dict):
        """Result-cache check, then the real executor.  Returns
        ``(response, cached)``."""
        key = job.get("cache_key")
        if key is not None:
            with self._cond:
                hit = self._cache.get(key)
                if hit is not None:
                    self._cache.move_to_end(key)
            if hit is not None:
                self.metrics.counter("jobs/result_cache/hits")
                return dict(hit), True
            self.metrics.counter("jobs/result_cache/misses")
        resp = self._executor(job["request"], job)
        if key is not None and isinstance(resp, dict) and resp.get("ok"):
            with self._cond:
                self._cache[key] = dict(resp)
                self._cache.move_to_end(key)
                while len(self._cache) > self._cache_cap:
                    self._cache.popitem(last=False)
        return resp, False
