------------------------- MODULE TPUraftDelegate -------------------------
(* Stock-TLC front door for the TPU checker (SURVEY §2.4 R10).

   TPUCheck is a no-op at the TLA+ level; the module override in
   TPUraftOverride.java (same directory) replaces it at load time with a
   socket call to `python -m raft_tla_tpu.server`.  Checking this module
   with plain TLC therefore runs the full TPU-engine check of the .cfg
   named below and fails iff the TPU checker finds a violation.        *)
EXTENDS Naturals, TLC

CONSTANTS CfgPath, Host, Port

TPUCheck(path, host, port) == [ok |-> FALSE, distinct |-> 0,
                               generated |-> 0, diameter |-> 0]

VARIABLE done

Init == done = FALSE
Next == /\ done = FALSE
        /\ done' = TRUE
        /\ LET r == TPUCheck(CfgPath, Host, Port)
           IN Assert(r.ok, <<"TPU check failed", r>>)

Delegated == [][Next]_done
=============================================================================
