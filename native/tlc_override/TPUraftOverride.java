/*
 * TLC module override delegating to the raft_tla_tpu checker service
 * (SURVEY §2.4 R10: "the mechanism by which a stock TLC CLI can delegate
 * to the TPU engine").
 *
 * TLA+ side (TPUraftDelegate.tla in this directory) declares:
 *
 *     TPUCheck(cfgPath, host, port) == FALSE  \* overridden by this class
 *
 * and this class replaces that operator at TLC load time via the
 * tlc2.overrides.TLAPlusOperator mechanism: it opens a TCP connection to
 * the checker service (python -m raft_tla_tpu.server), sends one
 * newline-delimited JSON "check" request for the given .cfg, and returns
 * the response's headline statistics as a TLA+ record
 * [distinct |-> n, generated |-> n, diameter |-> n, ok |-> bool].
 * A violation reported by the service fails the operator (TLC reports the
 * error with the service's counterexample text in the message).
 *
 * Build (needs tla2tools.jar, not present in this image — this file is
 * shipped as source, compiled by the user; the socket protocol itself is
 * unit-tested in tests/test_server.py):
 *
 *     javac -cp tla2tools.jar TPUraftOverride.java
 *     jar cf tpuraft-override.jar tlc2/
 *     java -cp tla2tools.jar:tpuraft-override.jar tlc2.TLC \
 *          -config TPUraftDelegate.cfg TPUraftDelegate
 */
package tlc2.overrides;

import java.io.BufferedReader;
import java.io.InputStreamReader;
import java.io.OutputStreamWriter;
import java.io.Writer;
import java.net.Socket;
import java.nio.charset.StandardCharsets;

import tlc2.value.impl.BoolValue;
import tlc2.value.impl.IntValue;
import tlc2.value.impl.RecordValue;
import tlc2.value.impl.StringValue;
import tlc2.value.impl.Value;
import util.UniqueString;

public class TPUraftOverride {

    @TLAPlusOperator(identifier = "TPUCheck", module = "TPUraftDelegate",
                     warn = false)
    public static Value tpuCheck(final StringValue cfgPath,
                                 final StringValue host,
                                 final IntValue port) throws Exception {
        final String req = "{\"op\": \"check\", \"cfg\": \""
                + cfgPath.val.toString().replace("\\", "\\\\")
                             .replace("\"", "\\\"")
                + "\"}\n";
        try (Socket s = new Socket(host.val.toString(), port.val)) {
            final Writer w = new OutputStreamWriter(
                    s.getOutputStream(), StandardCharsets.UTF_8);
            w.write(req);
            w.flush();
            final BufferedReader r = new BufferedReader(
                    new InputStreamReader(s.getInputStream(),
                                          StandardCharsets.UTF_8));
            final String line = r.readLine();
            if (line == null) {
                throw new RuntimeException("checker service closed");
            }
            // Minimal JSON field extraction (flat integer fields only) —
            // avoids a JSON dependency inside the TLC classpath.
            final boolean ok = line.contains("\"ok\": true");
            if (!ok) {
                // Surface the service's own error text (bad cfg path,
                // parse failure, ...) instead of a -1-stats record.
                throw new RuntimeException(
                        "TPU checker service error: " + line);
            }
            final boolean violated = !line.contains("\"violation\": null");
            final boolean deadlocked = !line.contains("\"deadlock\": null");
            if (violated || deadlocked) {
                throw new RuntimeException(
                        "TPU checker reported a "
                        + (violated ? "violation" : "deadlock")
                        + ": " + line);
            }
            final UniqueString[] names = new UniqueString[] {
                UniqueString.uniqueStringOf("ok"),
                UniqueString.uniqueStringOf("distinct"),
                UniqueString.uniqueStringOf("generated"),
                UniqueString.uniqueStringOf("diameter"),
            };
            final Value[] values = new Value[] {
                ok ? BoolValue.ValTrue : BoolValue.ValFalse,
                IntValue.gen(extractInt(line, "distinct")),
                IntValue.gen(extractInt(line, "generated")),
                IntValue.gen(extractInt(line, "diameter")),
            };
            return new RecordValue(names, values, false);
        }
    }

    private static int extractInt(final String json, final String key) {
        final String needle = "\"" + key + "\": ";
        final int at = json.indexOf(needle);
        if (at < 0) {
            return -1;
        }
        int end = at + needle.length();
        int v = 0;
        boolean any = false;
        while (end < json.length()
                && Character.isDigit(json.charAt(end))) {
            v = v * 10 + (json.charAt(end) - '0');
            end++;
            any = true;
        }
        return any ? v : -1;
    }
}
