#!/usr/bin/env python3
"""Benchmark: distinct states/sec on the bounded 3-server MCraft model.

Runs the exhaustive BFS engine on ``configs/MCraft_bounded.cfg`` (MaxTerm=3,
MaxLogLen=2, MaxMsgCount=1 — BASELINE.json configs[1]) for a fixed wall
budget on the ambient jax platform (the real TPU chip under the driver;
falls back to CPU if no accelerator initializes), then prints ONE JSON line.

Baseline note: this environment has no Java, so real CPU TLC cannot be
measured here (BASELINE.md §b).  The recorded ``vs_baseline`` is the ratio
against the pure-Python oracle checker measured in the same process — an
interpreted explicit-state checker on this host's single CPU core, i.e. a
*conservative stand-in* for TLC (TLC's compiled Java evaluator is roughly
an order of magnitude faster than the Python oracle; both numbers are
reported so the comparison can be re-based when a TLC measurement exists).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

BENCH_SECONDS = float(os.environ.get("BENCH_SECONDS", "45"))
# Oracle window defaults to the engine's budget: comparable measurement
# windows (both all-fresh early levels first, duplicates later).
ORACLE_SECONDS = float(os.environ.get("BENCH_ORACLE_SECONDS",
                                      str(BENCH_SECONDS)))

_T0 = time.time()


def _mark(msg: str) -> None:
    """Timestamped stderr progress marker.  The first TPU-tunnel window
    (2026-07-31) died mid-bench with zero output after 900 s — these
    markers localize any future stall without polluting the one-line
    stdout JSON contract."""
    print(f"bench[{time.time() - _T0:7.1f}s] {msg}", file=sys.stderr,
          flush=True)


def _tpu_tunnel_alive(timeout_s: float = 180.0) -> bool:
    # 180 s matches the watchdog/session probes exactly: a tunnel that
    # passes their probe must not time out here and demote the session's
    # headline stage to a CPU run (burning a MAX_SESSION_FAILS credit).
    """Probe the accelerator in a SUBPROCESS with a hard timeout.

    A wedged TPU tunnel (observed: the axon relay accepts the connection
    but the remote terminal never answers) blocks ``jax.devices()``
    inside an uninterruptible recv — an in-process try/except can't help.
    Probing in a disposable child process turns "hang forever" into a
    recorded CPU-fallback run."""
    import subprocess
    try:
        r = subprocess.run(
            [sys.executable, "-c",
             "import jax; assert jax.devices()[0].platform != 'cpu'"],
            timeout=timeout_s, capture_output=True)
        return r.returncode == 0
    except subprocess.TimeoutExpired:
        return False


def _swarm_bench(setup, platform: str) -> None:
    """BENCH_MODE=swarm: the randomized-walk tier's bench dialect.

    Same contract as the exhaustive bench — one JSON line on stdout,
    the run-event log validated as a hard gate, optional BENCH_HISTORY
    ledger entry — but the headline metric is lockstep walk steps/sec
    (``value``), with walks/sec, visited/sec and the time-to-first-
    counterexample (``violation_at_seconds``) riding along.  There is
    no oracle window: the swarm is not measuring exhaustive coverage,
    so ``vs_baseline`` has no meaning here (scripts/bench_diff.py
    folds gracefully when one side of a diff is swarm-dialect).
    Knobs: BENCH_WALKS / BENCH_MAX_DEPTH / BENCH_RING / BENCH_CHUNK /
    BENCH_SEED / BENCH_NUM_STEPS (unset = run the BENCH_SECONDS wall
    budget) on top of the shared BENCH_BATCH / BENCH_PIPELINE /
    BENCH_SECONDS / BENCH_EVENTS_OUT / BENCH_HISTORY."""
    import tempfile

    import jax

    from raft_tla_tpu.engine.check import (initial_states,
                                           resolve_constraint,
                                           resolve_invariants)
    from raft_tla_tpu.engine.swarm import SwarmEngine

    walks = int(os.environ.get("BENCH_WALKS", "1024"))
    max_depth = int(os.environ.get("BENCH_MAX_DEPTH", "64"))
    ring = int(os.environ.get("BENCH_RING", "16"))
    chunk = int(os.environ.get("BENCH_CHUNK", "32"))
    seed = int(os.environ.get("BENCH_SEED", "0"))
    num_steps = (int(os.environ["BENCH_NUM_STEPS"])
                 if os.environ.get("BENCH_NUM_STEPS") else None)
    batch = int(os.environ.get("BENCH_BATCH", str(walks)))
    events_file = os.environ.get("BENCH_EVENTS_OUT")
    scratch_dir = None
    if events_file is None:
        scratch_dir = tempfile.mkdtemp(prefix="bench_obs_")
        events_file = os.path.join(scratch_dir, "events.jsonl")
    # Same perf/profiler knobs as the exhaustive bench: BENCH_PERF=0
    # disables launch accounting, BENCH_PROFILE_CHUNKS sets the
    # walk-kernel stage-sampling cadence (0 = off).
    perf_on = bool(int(os.environ.get("BENCH_PERF", "1")))
    profile_every = int(os.environ.get("BENCH_PROFILE_CHUNKS", "64"))
    eng = SwarmEngine(setup.dims,
                      invariants=resolve_invariants(setup),
                      constraint=resolve_constraint(setup),
                      walks=walks, max_depth=max_depth,
                      batch=min(batch, walks), chunk=chunk, ring=ring,
                      pipeline=os.environ.get("BENCH_PIPELINE", "auto"),
                      events_out=events_file, perf=perf_on,
                      profile_chunks_every=profile_every)
    _mark(f"swarm engine built (walks={walks}, depth={max_depth}, "
          f"ring={ring}); compiling + running "
          + (f"{num_steps} steps" if num_steps is not None
             else f"{BENCH_SECONDS:.0f}s budget"))
    res = eng.run(initial_states(setup, seed=seed), seed=seed,
                  num_steps=num_steps,
                  max_seconds=(None if num_steps is not None
                               else BENCH_SECONDS))
    _mark(f"swarm run done: {res.steps} steps / {res.visited} visited "
          f"in {res.wall_seconds:.1f}s")

    # Same telemetry-regression gate as the exhaustive bench: a swarm
    # run that leaves its event log missing/malformed fails loudly.
    from raft_tla_tpu.obs import validate_and_cleanup
    try:
        n_events = validate_and_cleanup(events_file, scratch_dir)
    except (OSError, ValueError) as e:
        print(f"bench: telemetry regression — run event log invalid: "
              f"{e}", file=sys.stderr)
        sys.exit(1)
    _mark(f"event log validated ({n_events} events)")

    from raft_tla_tpu.obs import host_fingerprint
    import secrets
    doc = {
        "run_id": secrets.token_hex(8),
        "metric": "swarm_steps_per_sec",
        "value": round(res.steps_per_second, 1),
        "unit": "steps/s",
        "mode": "swarm",
        "platform": platform,
        "devices": len(jax.devices()),
        "host_fingerprint": host_fingerprint(),
        "walks": res.walks,
        "steps": res.steps,
        "visited": res.visited,
        "traces": res.traces,
        # Ledger-dialect aliases (entry_from_bench's column names):
        # distinct = ring-fresh visits, generated = lockstep steps.
        "distinct_states": res.distinct,
        "generated_states": res.generated,
        "generated_per_sec": round(res.steps_per_second, 1),
        "steps_per_sec": round(res.steps_per_second, 1),
        "walks_per_sec": round(res.walks_per_second, 1),
        "visited_per_sec": round(res.states_per_second, 1),
        "violation_at_seconds": res.violation_at_seconds,
        "max_depth": max_depth,
        "ring": ring,
        "seed": seed,
        "wall_s": round(res.wall_seconds, 2),
        "budget_s": BENCH_SECONDS,
        "diameter": res.diameter,
        "stop_reason": res.stop_reason,
        "phases": {k: round(v, 4) for k, v in res.phases.items()},
        "pipeline": res.pipeline,
        "report": res.report,
        "perf": res.perf,
        "chunk_stages": {k: round(v, 6)
                         for k, v in res.chunk_stages.items()},
    }
    if res.report.get("hunt"):
        from raft_tla_tpu.obs import hunt as hunt_mod
        doc["hunt"] = hunt_mod.summarize(res.report["hunt"])
    print(json.dumps(doc))
    history_path = os.environ.get("BENCH_HISTORY")
    if history_path:
        from raft_tla_tpu.obs import history as history_mod
        history_mod.append_entry(
            history_path, history_mod.entry_from_bench(doc, kind="swarm"))
        _mark(f"history entry appended to {history_path}")


def main():
    # An explicit JAX_PLATFORMS=cpu must actually take effect: the boot
    # hook pins the axon backend by config, so the env var alone is
    # ignored and `import jax` would still block on a dead tunnel.
    from raft_tla_tpu.utils.platform import (
        enable_persistent_cache, neutralize_axon_if_cpu_requested)
    neutralize_axon_if_cpu_requested()
    # Otherwise probe the tunnel in a subprocess before touching it.
    if "cpu" not in os.environ.get("JAX_PLATFORMS", "") \
            and not _tpu_tunnel_alive():
        print("bench: TPU tunnel unresponsive; falling back to CPU",
              file=sys.stderr)
        from raft_tla_tpu.utils.platform import force_cpu
        force_cpu()
    _mark("tunnel probe done")
    enable_persistent_cache()
    import jax

    platform = None
    try:
        platform = jax.devices()[0].platform
    except RuntimeError:
        from raft_tla_tpu.utils.platform import force_cpu
        force_cpu()
        platform = jax.devices()[0].platform
    _mark(f"backend up: {platform}")

    on_accel = platform not in ("cpu",)
    from raft_tla_tpu.engine.bfs import EngineConfig
    from raft_tla_tpu.engine.check import initial_states, make_engine
    from raft_tla_tpu.utils.cfg import load_config

    here = os.path.dirname(os.path.abspath(__file__))
    setup = load_config(os.path.join(here, "configs/MCraft_bounded.cfg"))
    # Second product tier: BENCH_MODE=swarm benches the randomized-walk
    # engine (engine/swarm.py) on the same pinned model in its own
    # dialect (_swarm_bench); everything below is the exhaustive
    # headline measurement.
    bench_mode = os.environ.get("BENCH_MODE", "exhaustive")
    if bench_mode == "swarm":
        return _swarm_bench(setup, platform)
    if bench_mode != "exhaustive":
        print(f"bench: unknown BENCH_MODE {bench_mode!r} (expected "
              f"'exhaustive' or 'swarm')", file=sys.stderr)
        sys.exit(2)
    # Accelerator capacities are EXPLICIT and modest (~3.5 GB total), not
    # HBM-auto-sized: the only tunnel window ever observed (2026-07-31)
    # wedged during this bench's ~9 GB auto-sized allocation+compile and
    # never produced a number, while the profile stage's smaller footprint
    # ran fine minutes earlier.  A 45-60 s window generates < 2 M distinct
    # states — 2^21 queue rows and a 2^25-key table are ample, and the
    # spill path covers any overshoot.  Env overrides for experiments.
    qcap = int(os.environ.get("BENCH_QUEUE_CAP",
                              str(1 << 21 if on_accel else 1 << 19)))
    scap = int(os.environ.get("BENCH_SEEN_CAP",
                              str(1 << 25 if on_accel else 1 << 21)))
    # Run-event log (obs/): the bench is also the telemetry-regression
    # gate — after the run the file must exist and parse, else nonzero rc.
    # The default scratch dir is cleaned up after validation (repeated
    # CI runs must not accumulate orphans); an explicit BENCH_EVENTS_OUT
    # is the caller's to keep.
    import tempfile
    events_file = os.environ.get("BENCH_EVENTS_OUT")
    scratch_dir = None
    if events_file is None:
        scratch_dir = tempfile.mkdtemp(prefix="bench_obs_")
        events_file = os.path.join(scratch_dir, "events.jsonl")
    # Per-stage chunk profiling (obs/profile.py): sampled sparsely enough
    # (default every 64th chunk call) that the headline states/s stays a
    # throughput number while every bench JSON still carries the stage
    # decomposition bench_diff.py gates on.  BENCH_PROFILE_CHUNKS=0
    # disables; engine results are bit-identical either way.
    profile_every = int(os.environ.get("BENCH_PROFILE_CHUNKS", "64"))
    # Partial-order reduction (analysis/por.py): BENCH_POR=1 certifies
    # in-process at engine build, BENCH_POR_TABLE applies a pre-built
    # artifact.  The reduction (if any certificate proves) shows up in
    # the coverage object's "pruned" column and the generated/distinct
    # headline — bench_diff.py then reports generated-state reduction
    # alongside the distinct/s regression gate.
    # Successor pipeline (BENCH_PIPELINE=auto/v1/v2/v3/v4): v3 is the
    # fused Pallas chunk (ops/pipeline_v3.py), v4 the whole-chunk VMEM
    # megakernel (ops/pipeline_v4.py) — on TPU the real fused kernels,
    # off-TPU interpret mode for the Pallas stages the platform policy
    # keeps (the CI v2-vs-v3/v4 gates run this on CPU with
    # fold-to-common stages in bench_diff.py).  The run's resolved pipeline + per-stage
    # plan are embedded in the JSON so two benches are always
    # attributable.
    # Device-profiler capture (obs/profile.py XlaProfileCapture;
    # BENCH_XLA_PROFILE=N traces the first N chunk calls): the
    # hardware-truth artifacts for NORTHSTAR §d, landed under
    # BENCH_XLA_PROFILE_DIR (default artifacts/xla_profile).
    # Observational — the headline number is unaffected.
    xla_profile = int(os.environ.get("BENCH_XLA_PROFILE", "0"))
    # Performance observatory (obs/perf.py; BENCH_PERF=0 disables):
    # launch accounting + static roofline + fusion-advisor verdict,
    # embedded as the bench JSON's "perf" block — what bench_diff.py
    # gates with --launch-drift and bench_history.py renders with
    # --perf.  Observational: the headline number is unaffected (the
    # one-time jaxpr walk happens at engine build, before the clock).
    perf_on = bool(int(os.environ.get("BENCH_PERF", "1")))
    cfg = EngineConfig(
        batch=int(os.environ.get("BENCH_BATCH",
                                 str(2048 if on_accel else 512))),
        queue_capacity=qcap,
        seen_capacity=scap,
        check_deadlock=False,
        record_trace=False,          # raw engine throughput (trace store is
        max_seconds=BENCH_SECONDS,   # host-side; C++ store tracked separately)
        events_out=events_file,
        trace_out=os.environ.get("BENCH_TRACE_OUT"),
        # 0 passes through as explicitly-off so BENCH_PERF=1 cannot
        # re-enable a profiler BENCH_PROFILE_CHUNKS=0 turned off.
        profile_chunks_every=profile_every,
        xla_profile_chunks=xla_profile or None,
        xla_profile_dir=os.environ.get("BENCH_XLA_PROFILE_DIR",
                                       "artifacts/xla_profile"),
        pipeline=os.environ.get("BENCH_PIPELINE", "auto"),
        por=bool(int(os.environ.get("BENCH_POR", "0"))),
        por_table=os.environ.get("BENCH_POR_TABLE"),
        perf=perf_on)
    # "auto": on a multi-accelerator slice (e.g. v5e-8) the run shards
    # over all devices — the mesh engine is the product's scaling path
    # and the north-star target is defined on the full slice.
    n_dev = len(jax.devices())
    engine = make_engine(setup, cfg, engine_cls="auto")
    is_mesh = type(engine).__name__ == "MeshBFSEngine"
    # Live introspection for the tunnel session (obs/expose.py):
    # BENCH_METRICS_PORT serves /metrics (Prometheus) + /flight (the
    # watch console's feed) for the duration of the run, so
    # tpu_session.sh gets a live view of the measurement instead of
    # staring at a silent 60 s window.
    metrics_srv = None
    metrics_port = int(os.environ.get("BENCH_METRICS_PORT", "0"))
    if metrics_port:
        from raft_tla_tpu.obs import start_metrics_server
        from raft_tla_tpu.obs.flight import RECORDER
        try:
            metrics_srv, _t = start_metrics_server(metrics_port,
                                                   engine.metrics,
                                                   flight=RECORDER)
            _mark(f"metrics listener on 127.0.0.1:"
                  f"{metrics_srv.server_address[1]} (/metrics, /flight)")
        except OSError as e:
            # The listener is a nicety; the measurement is the point —
            # a busy port must not kill a scarce tunnel-window bench.
            metrics_srv = None
            _mark(f"metrics listener unavailable on port "
                  f"{metrics_port} ({e}); continuing without it")
    _mark(f"engine built ({'mesh' if is_mesh else 'single'}, "
          f"batch={cfg.batch}); compiling + running "
          f"{BENCH_SECONDS:.0f}s budget")
    try:
        res = engine.run(initial_states(setup))
    finally:
        if metrics_srv is not None:
            metrics_srv.shutdown()
            # server_close too: shutdown() alone leaves the bound
            # socket accepting into the kernel backlog, which turns the
            # watcher's clean connection-refused "listener gone" exit
            # into per-poll read timeouts for the rest of the process.
            metrics_srv.server_close()
    rate = res.distinct / res.wall_seconds if res.wall_seconds else 0.0
    _mark(f"engine run done: {res.distinct} distinct in "
          f"{res.wall_seconds:.1f}s; starting oracle window")

    # Telemetry-regression gate: a run that leaves its event log missing
    # or malformed fails the WHOLE bench loudly — an unobservable engine
    # is a regression even when its states/sec number looks fine.  The
    # path is re-resolved through the engine (a process group rewrites
    # events_out to a per-controller piece name); cleanup happens on
    # both outcomes (obs.validate_and_cleanup).
    from raft_tla_tpu.obs import validate_and_cleanup
    try:
        n_events = validate_and_cleanup(engine._events_path(), scratch_dir)
    except (OSError, ValueError) as e:
        print(f"bench: telemetry regression — run event log invalid: {e}",
              file=sys.stderr)
        sys.exit(1)
    _mark(f"event log validated ({n_events} events)")
    # Same contract for the span trace when one was requested: a
    # BENCH_TRACE_OUT file Perfetto would reject fails the bench.
    if cfg.trace_out:
        from raft_tla_tpu.obs import validate_chrome_trace
        try:
            n_spans = len(validate_chrome_trace(cfg.trace_out))
        except (OSError, ValueError) as e:
            print(f"bench: telemetry regression — Chrome trace invalid: "
                  f"{e}", file=sys.stderr)
            sys.exit(1)
        _mark(f"chrome trace validated ({n_spans} events)")

    # Python-oracle baseline on the same model (CPU, single core), over
    # the SAME wall budget from the same root — comparable windows, so the
    # ratio measures engine speed, not space structure (round-2 verdict
    # weak #2).  The oracle level-loop can't stop mid-level; its own wall
    # clock is reported so the rate is exact for the work done.
    from raft_tla_tpu.models import oracle as orc
    from raft_tla_tpu.models.invariants import constraint_py
    from raft_tla_tpu.models.pystate import init_state

    t0 = time.time()
    ores = orc.bfs([init_state(setup.dims)], setup.dims,
                   constraint=constraint_py(setup.bounds),
                   check_deadlock=False,
                   stop_predicate=lambda r: time.time() - t0 > ORACLE_SECONDS)
    base_wall = time.time() - t0
    base_rate = ores.distinct_states / base_wall if base_wall else 1.0
    _mark("oracle window done; emitting JSON")

    # Host identity (obs/flight.py host_fingerprint): bench_diff.py
    # prints a cross-host warning when two diffed benches disagree here
    # — the PR 7 trap where BENCH_r05's absolute 38.4k/s was silently
    # compared against a ~4x slower container.
    from raft_tla_tpu.obs import host_fingerprint

    # Per-run identity shared by the printed JSON and the BENCH_HISTORY
    # ledger line: bench_diff --history excludes the candidate's OWN
    # entry by this id, so the record-then-gate workflow never
    # self-compares even when the captured file is later annotated or
    # reformatted (doc-equality alone would miss it then).
    import secrets
    run_id = secrets.token_hex(8)

    doc = {
        "run_id": run_id,
        "metric": "distinct_states_per_sec",
        "value": round(rate, 1),
        "unit": "states/s",
        "vs_baseline": round(rate / base_rate, 2) if base_rate else None,
        "platform": platform,
        "devices": n_dev,
        "host_fingerprint": host_fingerprint(),
        "engine": "mesh" if is_mesh else "single",
        "distinct_states": res.distinct,
        "generated_states": res.generated,
        "generated_per_sec": round(res.generated / res.wall_seconds, 1)
        if res.wall_seconds else 0.0,
        "wall_s": round(res.wall_seconds, 2),
        "budget_s": BENCH_SECONDS,
        "diameter": res.diameter,
        "levels": res.levels,
        "stop_reason": res.stop_reason,
        "generated_by_action": res.action_counts,
        # Seen-set doublings as (capacity-after, off-clock stall seconds):
        # the cost evidence for sizing SEEN_CAPACITY up front.
        "growth_stalls": res.growth_stalls,
        # Host-side per-phase wall-time breakdown (obs/ phase timers):
        # chunk dispatch vs stats fetch vs spill vs growth — the pipeline
        # accounting BENCH_r06+ carries so hot-path work can be targeted
        # at the phase that actually dominates.
        "phases": {k: round(v, 4) for k, v in res.phases.items()},
        # Per-stage chunk decomposition (obs/profile.py; mean seconds per
        # sampled batch + the fused "total" reference) and the TLC-style
        # coverage object — the two new axes scripts/bench_diff.py gates
        # BENCH_r* trajectories on.
        "chunk_stages": {k: round(v, 6)
                         for k, v in res.chunk_stages.items()},
        # Which successor pipeline ran, and (v3) the per-stage lowering
        # plan — bench_diff folds mismatched chunk_stages granularities
        # across pipelines using this context.
        "pipeline": res.pipeline,
        "fused_stages": dict(res.fused_stages),
        "fused_reasons": dict(res.fused_reasons),
        "coverage": res.coverage,
        # Certified ample instances the run's POR table carried (0 = POR
        # off or an all-conservative certificate).
        "por_instances": res.por_instances,
        # TLC-parity statespace report (obs/report.py): collision
        # probability, per-level table, out-degree, seen-set load —
        # the semantic half of the trajectory the run ledger records.
        "report": res.report,
        # Performance observatory (obs/perf.py): launch accounting,
        # roofline rows with achieved-bandwidth fractions, and the
        # fusion advisor's verdict — bench_diff.py gates
        # launches_per_chunk (--launch-drift) and bandwidth drift on
        # this block; {} when BENCH_PERF=0.
        "perf": res.perf,
        "baseline_states_per_sec": round(base_rate, 1),
        "baseline_distinct": ores.distinct_states,
        "baseline_wall_s": round(base_wall, 2),
        "baseline_kind": "python-oracle-1core (no TLC/java available)",
    }
    print(json.dumps(doc))

    # Run-history ledger (obs/history.py): BENCH_HISTORY names the
    # append-only JSONL trajectory file — one entry per bench run,
    # embedding the full bench object so scripts/bench_diff.py
    # --history can auto-resolve its baseline (newest same-host entry)
    # instead of a hand-picked file (the BENCH_r05 cross-host trap).
    history_path = os.environ.get("BENCH_HISTORY")
    if history_path:
        from raft_tla_tpu.obs import history as history_mod
        history_mod.append_entry(
            history_path, history_mod.entry_from_bench(doc))
        _mark(f"history entry appended to {history_path}")


if __name__ == "__main__":
    main()
