#!/usr/bin/env python3
"""Benchmark: distinct states/sec on the bounded 3-server MCraft model.

Runs the exhaustive BFS engine on ``configs/MCraft_bounded.cfg`` (MaxTerm=3,
MaxLogLen=2, MaxMsgCount=1 — BASELINE.json configs[1]) for a fixed wall
budget on the ambient jax platform (the real TPU chip under the driver;
falls back to CPU if no accelerator initializes), then prints ONE JSON line.

Baseline note: this environment has no Java, so real CPU TLC cannot be
measured here (BASELINE.md §b).  The recorded ``vs_baseline`` is the ratio
against the pure-Python oracle checker measured in the same process — an
interpreted explicit-state checker on this host's single CPU core, i.e. a
*conservative stand-in* for TLC (TLC's compiled Java evaluator is roughly
an order of magnitude faster than the Python oracle; both numbers are
reported so the comparison can be re-based when a TLC measurement exists).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

BENCH_SECONDS = float(os.environ.get("BENCH_SECONDS", "45"))
# Oracle window defaults to the engine's budget: comparable measurement
# windows (both all-fresh early levels first, duplicates later).
ORACLE_SECONDS = float(os.environ.get("BENCH_ORACLE_SECONDS",
                                      str(BENCH_SECONDS)))


def _tpu_tunnel_alive(timeout_s: float = 120.0) -> bool:
    """Probe the accelerator in a SUBPROCESS with a hard timeout.

    A wedged TPU tunnel (observed: the axon relay accepts the connection
    but the remote terminal never answers) blocks ``jax.devices()``
    inside an uninterruptible recv — an in-process try/except can't help.
    Probing in a disposable child process turns "hang forever" into a
    recorded CPU-fallback run."""
    import subprocess
    try:
        r = subprocess.run(
            [sys.executable, "-c",
             "import jax; assert jax.devices()[0].platform != 'cpu'"],
            timeout=timeout_s, capture_output=True)
        return r.returncode == 0
    except subprocess.TimeoutExpired:
        return False


def main():
    # An explicit JAX_PLATFORMS=cpu must actually take effect: the boot
    # hook pins the axon backend by config, so the env var alone is
    # ignored and `import jax` would still block on a dead tunnel.
    from raft_tla_tpu.utils.platform import neutralize_axon_if_cpu_requested
    neutralize_axon_if_cpu_requested()
    # Otherwise probe the tunnel in a subprocess before touching it.
    if "cpu" not in os.environ.get("JAX_PLATFORMS", "") \
            and not _tpu_tunnel_alive():
        print("bench: TPU tunnel unresponsive; falling back to CPU",
              file=sys.stderr)
        from raft_tla_tpu.utils.platform import force_cpu
        force_cpu()
    import jax

    platform = None
    try:
        platform = jax.devices()[0].platform
    except RuntimeError:
        from raft_tla_tpu.utils.platform import force_cpu
        force_cpu()
        platform = jax.devices()[0].platform

    on_accel = platform not in ("cpu",)
    from raft_tla_tpu.engine.bfs import EngineConfig
    from raft_tla_tpu.engine.check import initial_states, make_engine
    from raft_tla_tpu.utils.cfg import load_config

    here = os.path.dirname(os.path.abspath(__file__))
    setup = load_config(os.path.join(here, "configs/MCraft_bounded.cfg"))
    cfg = EngineConfig(
        batch=2048 if on_accel else 512,
        # None => sized from the chip's reported HBM; the frontier spills
        # to host RAM past that, so no level size can crash the run.
        queue_capacity=None if on_accel else 1 << 19,
        seen_capacity=None if on_accel else 1 << 21,
        check_deadlock=False,
        record_trace=False,          # raw engine throughput (trace store is
        max_seconds=BENCH_SECONDS)   # host-side; C++ store tracked separately)
    # "auto": on a multi-accelerator slice (e.g. v5e-8) the run shards
    # over all devices — the mesh engine is the product's scaling path
    # and the north-star target is defined on the full slice.
    n_dev = len(jax.devices())
    engine = make_engine(setup, cfg, engine_cls="auto")
    is_mesh = type(engine).__name__ == "MeshBFSEngine"
    res = engine.run(initial_states(setup))
    rate = res.distinct / res.wall_seconds if res.wall_seconds else 0.0

    # Python-oracle baseline on the same model (CPU, single core), over
    # the SAME wall budget from the same root — comparable windows, so the
    # ratio measures engine speed, not space structure (round-2 verdict
    # weak #2).  The oracle level-loop can't stop mid-level; its own wall
    # clock is reported so the rate is exact for the work done.
    from raft_tla_tpu.models import oracle as orc
    from raft_tla_tpu.models.invariants import constraint_py
    from raft_tla_tpu.models.pystate import init_state

    t0 = time.time()
    ores = orc.bfs([init_state(setup.dims)], setup.dims,
                   constraint=constraint_py(setup.bounds),
                   check_deadlock=False,
                   stop_predicate=lambda r: time.time() - t0 > ORACLE_SECONDS)
    base_wall = time.time() - t0
    base_rate = ores.distinct_states / base_wall if base_wall else 1.0

    print(json.dumps({
        "metric": "distinct_states_per_sec",
        "value": round(rate, 1),
        "unit": "states/s",
        "vs_baseline": round(rate / base_rate, 2) if base_rate else None,
        "platform": platform,
        "devices": n_dev,
        "engine": "mesh" if is_mesh else "single",
        "distinct_states": res.distinct,
        "generated_states": res.generated,
        "generated_per_sec": round(res.generated / res.wall_seconds, 1)
        if res.wall_seconds else 0.0,
        "wall_s": round(res.wall_seconds, 2),
        "budget_s": BENCH_SECONDS,
        "diameter": res.diameter,
        "levels": res.levels,
        "stop_reason": res.stop_reason,
        "generated_by_action": res.action_counts,
        # Seen-set doublings as (capacity-after, off-clock stall seconds):
        # the cost evidence for sizing SEEN_CAPACITY up front.
        "growth_stalls": res.growth_stalls,
        "baseline_states_per_sec": round(base_rate, 1),
        "baseline_distinct": ores.distinct_states,
        "baseline_wall_s": round(base_wall, 2),
        "baseline_kind": "python-oracle-1core (no TLC/java available)",
    }))


if __name__ == "__main__":
    main()
