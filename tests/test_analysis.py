"""Static-analysis subsystem (analysis/): effects, bounds, lint, report.

The effect matrix is validated two ways: against the hand-written
per-family read/write footprint of the spec (raft.tla:136-430, the same
derivation as ``lane_map.FIELD_WRITERS``), and differentially against
the Python oracle — every field an oracle successor actually changes
must lie inside the traced write set of its action family.
"""

import json
import textwrap

import numpy as np
import pytest

from raft_tla_tpu.models.dims import RaftDims
from raft_tla_tpu.models.invariants import Bounds
from raft_tla_tpu.models.pystate import init_state
from raft_tla_tpu.models.schema import StateBatch, check_packable, encode_state
from raft_tla_tpu.analysis import lane_map, run_analysis
from raft_tla_tpu.analysis.report import ERROR, INFO, Finding, Report, WARNING

DIMS = RaftDims(n_servers=3, n_values=2, max_log=3, n_msg_slots=4)


@pytest.fixture(scope="module", autouse=True)
def _release_tracing_caches():
    """The analyzers trace every action kernel plus both full chunk
    bodies; the accumulated trace/lowering caches destabilize jaxlib's
    CPU heap enough that the big engine tests later in the suite can
    segfault.  Dropping the caches at module teardown keeps this module
    from taxing the rest of the run (analysis is trace-only — nothing
    here needs a warm executable cache afterwards)."""
    yield
    import gc

    import jax

    from raft_tla_tpu.analysis import interp
    interp.traced_kernels.cache_clear()
    jax.clear_caches()
    gc.collect()


@pytest.fixture(scope="module")
def effect_summary():
    from raft_tla_tpu.analysis import effects
    summary, findings = effects.analyze(DIMS)
    return summary, findings


# ---------------------------------------------------------------------------
# lane map


def test_fields_match_schema():
    assert lane_map.FIELDS == StateBatch._fields


def test_row_layout_covers_the_packed_row():
    from raft_tla_tpu.models.schema import state_width
    layout = lane_map.row_layout(DIMS)
    assert layout[0][1] == 0
    end = layout[-1][1] + layout[-1][2]
    assert end == state_width(DIMS)       # base layout (value_bytes == 1)
    f, idx = lane_map.decode_row_offset(DIMS, layout[3][1])
    assert f == "log_term" and idx == (0, 0)


# ---------------------------------------------------------------------------
# effects


def test_field_writers_table(effect_summary):
    """The spec-derived FIELD_WRITERS table equals the traced per-family
    write sets exactly — the cross-check that keeps the table from
    drifting when a kernel changes."""
    summary, _ = effect_summary
    traced = {f: set() for f in lane_map.FIELDS}
    for fam, d in summary.families.items():
        for f in d["writes"]:
            traced[f].add(fam)
    for f in lane_map.FIELDS:
        assert traced[f] == set(lane_map.FIELD_WRITERS[f]), f


#: Hand-written per-family footprints from the spec's variable writes
#: (raft.tla: Restart :136, Timeout :146, RequestVote :157, BecomeLeader
#: :195, ClientRequest :206, AdvanceCommitIndex :219, AppendEntries :171,
#: Receive :388 = union of all handlers, Duplicate :410, Drop :415).
ORACLE_WRITES = {
    "Restart": {"role", "votes_resp", "votes_gran", "next_idx",
                "match_idx", "commit"},
    "Timeout": {"role", "term", "voted_for", "votes_resp", "votes_gran"},
    "RequestVote": {"msg", "msg_cnt"},
    "BecomeLeader": {"role", "next_idx", "match_idx"},
    "ClientRequest": {"log_term", "log_val", "log_len"},
    "AdvanceCommitIndex": {"commit"},
    "AppendEntries": {"msg", "msg_cnt"},
    # Every handler's union; commit is absent because AppendEntriesAlreadyDone's
    # :309 write is conjoined with UNCHANGED logVars (:317, the replicated
    # upstream bug) — enabled only as a no-op.
    "Receive": {"term", "role", "voted_for", "log_term", "log_val",
                "log_len", "votes_resp", "votes_gran", "next_idx",
                "match_idx", "msg", "msg_cnt"},
    "DuplicateMessage": {"msg_cnt"},
    "DropMessage": {"msg", "msg_cnt"},
}

ORACLE_GUARD_READS = {
    "Restart": set(),                       # always enabled (raft.tla:136)
    "Timeout": {"role"},                    # :147
    "BecomeLeader": {"role", "votes_gran"},  # :196-197
    "ClientRequest": {"role", "log_len"},   # :207 + capacity guard
    "AdvanceCommitIndex": {"role"},         # :220
    "DuplicateMessage": {"msg_cnt"},        # slot occupied
    "DropMessage": {"msg_cnt"},
}


def test_family_write_sets_match_spec_footprints(effect_summary):
    summary, _ = effect_summary
    assert set(summary.families) == set(ORACLE_WRITES)
    for fam, expect in ORACLE_WRITES.items():
        assert summary.families[fam]["writes"] == expect, fam


def test_family_guard_reads_match_spec_guards(effect_summary):
    summary, _ = effect_summary
    for fam, expect in ORACLE_GUARD_READS.items():
        assert summary.families[fam]["guard_reads"] == expect, fam


def test_effects_differential_against_oracle(effect_summary):
    """Soundness against the reference interpreter: every field a real
    oracle transition changes is inside the traced write set of its
    family (on the canonical encoding both sides share)."""
    from raft_tla_tpu.models import oracle
    summary, _ = effect_summary
    writes = {fam: d["writes"] for fam, d in summary.families.items()}
    frontier, seen, checked = [init_state(DIMS)], set(), 0
    for _level in range(3):
        nxt = []
        for s in frontier:
            enc_s = encode_state(s, DIMS)
            for (fam_code, _params), succ in oracle.successors(s, DIMS):
                fam = DIMS.family_names[fam_code]
                enc_t = encode_state(succ, DIMS)
                changed = {f for f in lane_map.FIELDS
                           if not np.array_equal(getattr(enc_s, f),
                                                 getattr(enc_t, f))}
                assert changed <= writes[fam], (fam, changed - writes[fam])
                checked += 1
                if succ not in seen and len(seen) < 300:
                    seen.add(succ)
                    nxt.append(succ)
        frontier = nxt
    assert checked > 100


def test_dependence_matrix(effect_summary):
    summary, _ = effect_summary
    ind = summary.independent
    G = len(summary.instances)
    assert ind.shape == (G, G)
    assert not ind.diagonal().any()
    assert (ind == ind.T).all()
    by_fam = {}
    for k, inst in enumerate(summary.instances):
        by_fam.setdefault(inst.family, []).append(k)
    # Timeout writes term; Receive reads it: never independent.
    for a in by_fam["Timeout"]:
        for b in by_fam["Receive"]:
            assert not ind[a, b]
    # Timeout(i) and Timeout(j != i) touch disjoint server rows... but
    # guard-independence is the weaker relation POR needs and holds for
    # e.g. AdvanceCommitIndex vs DuplicateMessage.
    for a in by_fam["AdvanceCommitIndex"]:
        for b in by_fam["DuplicateMessage"]:
            assert summary.guard_independent[a, b]
            assert ind[a, b]


def test_no_dead_lanes_on_base_model(effect_summary):
    summary, _ = effect_summary
    dead = {f: int(m.sum()) for f, m in summary.dead_lanes.items()}
    assert all(v == 0 for v in dead.values()), dead


def test_dependence_matrices_serialized_and_roundtrip(effect_summary):
    """The analyze report carries the FULL per-instance matrices (hex
    row bitmasks + labels) — the stable artifact POR and BLEST-style
    batching consume instead of re-tracing — and the decoder inverts
    the packing exactly."""
    from raft_tla_tpu.analysis import effects
    summary, _ = effect_summary
    sj = effects.summary_json(summary)
    G = sj["n_instances"]
    assert len(sj["instances"]) == G
    assert len(sj["independent_hex"]) == G
    assert len(sj["guard_independent_hex"]) == G
    json.dumps(sj)                      # report-serializable as-is
    ind, gind = effects.matrices_from_json(sj)
    assert (ind == summary.independent).all()
    assert (gind == summary.guard_independent).all()


def test_read_set_self_check_clean_and_planted(effect_summary):
    """Analyzer-vs-analyzer consistency: every lane a kernel jaxpr
    demonstrably reads is inside the effects pass's reported read set
    (clean on the seed kernels); deleting a reported read makes the
    check fire — the sensitivity proof."""
    from raft_tla_tpu.analysis import lint
    summary, _ = effect_summary
    assert lint.read_set_check(DIMS, effect_summary=summary) == []
    reads = {fam: d["reads"] | d["guard_reads"]
             for fam, d in summary.families.items()}
    reads["DuplicateMessage"] = reads["DuplicateMessage"] - {"msg_cnt"}
    findings = lint.read_set_check(DIMS, family_reads=reads)
    assert len(findings) == 1
    f = findings[0]
    assert f.severity == ERROR and f.code == "read-set-mismatch"
    assert f.field == "DuplicateMessage"
    assert f.details["extra_reads"] == ["msg_cnt"]


# ---------------------------------------------------------------------------
# bounds


def test_bounds_proves_seed_dims_safe():
    from raft_tla_tpu.analysis import bounds
    summary, findings = bounds.analyze(DIMS, init_states=[init_state(DIMS)])
    assert summary["converged"]
    assert [f for f in findings if f.severity == ERROR] == []
    # Unbounded pack-guarded growth (term) stays visible as a WARNING.
    warns = {f.field for f in findings if f.severity == WARNING}
    assert "term" in warns


def test_bounds_catches_shrunken_term_lane():
    from raft_tla_tpu.analysis import bounds
    _summary, findings = bounds.analyze(
        DIMS, init_states=[init_state(DIMS)], lane_caps={"term": (0, 15)})
    errs = [f for f in findings
            if f.severity == ERROR and f.code == "lane-overflow"]
    assert errs and errs[0].field == "term"
    assert errs[0].witness.startswith("Timeout")   # the raising action


def test_bounds_cfg_constraints_prove_all_lanes():
    from raft_tla_tpu.analysis import bounds
    _summary, findings = bounds.analyze(
        DIMS, init_states=[init_state(DIMS)],
        bounds=Bounds(max_term=3, max_log_len=2, max_msg_count=3))
    assert [f for f in findings if f.severity != INFO] == []


def test_bounds_cfg_admitting_overflow_is_an_error():
    """MaxTerm = 300 > 255: every run would hard-stop on the pack guard
    inside the *intended* state space — ERROR, with the raiser named."""
    from raft_tla_tpu.analysis import bounds
    _summary, findings = bounds.analyze(
        DIMS, init_states=[init_state(DIMS)],
        bounds=Bounds(max_term=300, max_log_len=None, max_msg_count=None))
    errs = {f.field: f for f in findings if f.severity == ERROR}
    assert "term" in errs
    assert errs["term"].witness.startswith("Timeout")


# ---------------------------------------------------------------------------
# lint


def test_lint_clean_on_the_real_engine():
    from raft_tla_tpu.analysis import lint
    summary, findings = lint.analyze(DIMS)
    assert [f for f in findings if f.severity == ERROR] == []
    assert {"fingerprint", "fpset_insert", "bfs_step_v1",
            "bfs_step_v2"} <= set(summary["kernels"])


def test_lint_flags_planted_device_get(tmp_path):
    from raft_tla_tpu.analysis import lint
    fixture = tmp_path / "hot_loop.py"
    fixture.write_text(textwrap.dedent("""\
        import jax
        import numpy as np

        def drain(queue, mt):
            out = []
            while queue:
                x = queue.pop()
                out.append(jax.device_get(x))        # unsanctioned
                with mt.phase_timer("fetch"):
                    out.append(np.asarray(x))        # sanctioned sync
                if not out:
                    y = np.asarray(x)                # exit branch
                    break
            return out
    """))
    findings = lint.scan_host_loops(str(fixture))
    assert len(findings) == 1
    f = findings[0]
    assert f.severity == ERROR and f.code == "blocking-read-in-loop"
    assert ":8" in f.field                           # the device_get line


def test_lint_jaxpr_flags_host_callback_and_narrowing():
    import jax
    import jax.numpy as jnp
    from raft_tla_tpu.analysis import lint

    def bad(x):
        y = jax.pure_callback(
            lambda a: a, jax.ShapeDtypeStruct((), jnp.int32), x)
        return (y + x).astype(jnp.int8)

    _summary, findings = lint.lint_jaxpr(
        jax.make_jaxpr(bad)(jnp.int32(3)), "fixture")
    codes = {f.code: f.severity for f in findings}
    assert codes.get("host-callback") == ERROR
    assert codes.get("narrowing-convert") == WARNING


def test_lint_packing_convert_is_info_only():
    import jax
    import jax.numpy as jnp
    from raft_tla_tpu.analysis import lint

    _summary, findings = lint.lint_jaxpr(
        jax.make_jaxpr(lambda x: x.astype(jnp.uint8))(jnp.int32(3)),
        "fixture")
    assert {f.severity for f in findings} == {INFO}


# ---------------------------------------------------------------------------
# report / runner / CLI


def test_report_allowlist_downgrades_but_keeps_finding():
    rep = Report(allowlist=["lane-overflow:term"])
    rep.extend([Finding("bounds", ERROR, "lane-overflow", field="term",
                        message="x", witness="Timeout(i=0)"),
                Finding("bounds", ERROR, "lane-overflow", field="msg_cnt",
                        message="y")])
    assert not rep.ok                      # msg_cnt error still gates
    js = rep.to_json()
    f0 = js["passes"]["bounds"]["findings"][0]
    assert f0["severity"] == WARNING and f0["allowlisted"]


def test_run_analysis_wires_obs(tmp_path):
    from raft_tla_tpu.obs import MetricsRegistry, RunEventLog
    mt = MetricsRegistry()
    ev_path = tmp_path / "events.jsonl"
    with RunEventLog(str(ev_path)) as evlog:
        report = run_analysis(DIMS, init_states=[init_state(DIMS)],
                              passes=("bounds",),
                              lane_caps={"term": (0, 15)},
                              metrics=mt, evlog=evlog)
    assert not report.ok
    assert report.first_witness().startswith("Timeout")
    assert mt.counter_value("analysis/errors") >= 1
    events = [json.loads(line) for line in ev_path.read_text().splitlines()]
    assert [e["pass_name"] for e in events if e["event"] == "analysis"] \
        == ["bounds"]
    assert events[0]["witness"].startswith("Timeout")


def test_cli_analyze_gate(tmp_path, capsys):
    from raft_tla_tpu.cli import main
    out = tmp_path / "report.json"
    rc = main(["analyze", "--max-log", "3", "--n-msg-slots", "4",
               "--passes", "bounds", "--json", "--out", str(out)])
    assert rc == 0
    assert json.loads(capsys.readouterr().out)["ok"]
    rc = main(["analyze", "--max-log", "3", "--n-msg-slots", "4",
               "--passes", "bounds", "--shrink-lane", "term=15", "--json"])
    assert rc == 1
    rep = json.loads(capsys.readouterr().out)
    errs = [f for f in rep["passes"]["bounds"]["findings"]
            if f["severity"] == ERROR]
    assert errs and errs[0]["witness"].startswith("Timeout")
    # ... and the allowlist turns the same model green, visibly.
    rc = main(["analyze", "--max-log", "3", "--n-msg-slots", "4",
               "--passes", "bounds", "--shrink-lane", "term=15",
               "--allow", "lane-overflow:term", "--json"])
    assert rc == 0
    capsys.readouterr()


# ---------------------------------------------------------------------------
# satellite: check_packable error decoding


def test_check_packable_names_lane_and_writers():
    st = encode_state(init_state(DIMS), DIMS)
    bad = st._replace(term=np.array([0, 300, 0], np.int32))
    with pytest.raises(ValueError, match=r"term.*Timeout, Receive"):
        check_packable(bad, DIMS)
    msg = np.array(st.msg)
    msg[1, 4] = 200
    with pytest.raises(ValueError,
                       match=r"slot 1 column 4.*mlastLogTerm.*RequestVote"):
        check_packable(st._replace(msg=msg), DIMS)


# ---------------------------------------------------------------------------
# element-wise taint (the slot/column-granular footprints POR consumes)


def _field_taints(shapes):
    """State-input taints for a toy 'model' of named fields."""
    from raft_tla_tpu.analysis.interp import _taint
    out = []
    for f, shp in shapes:
        out.append(_taint({}, {f: np.ones(shp, bool)}, f,
                          np.zeros(shp, bool), np.zeros(shp, bool),
                          np.zeros(shp, np.int64), np.int32))
    return out


def test_interp_gather_known_index_is_element_precise():
    """arr[i] with a parameter-concrete index reads exactly element i;
    a state-dependent index widens to the whole axis — per element,
    with the index's own reads joined in."""
    import jax
    import jax.numpy as jnp
    from raft_tla_tpu.analysis.interp import TaintDomain, eval_jaxpr, \
        read_mask

    closed = jax.make_jaxpr(lambda a, i: a[i])(
        jnp.zeros(5, jnp.int32), jnp.int32(0))
    (arr,) = _field_taints([("X", (5,))])
    dom = TaintDomain()
    out = eval_jaxpr(closed, [arr, np.int32(3)], dom)[0]
    assert read_mask(out)["X"].tolist() == [0, 0, 0, 1, 0]

    # Two-level indexing: known row, state-dependent column -> the row.
    closed2 = jax.make_jaxpr(lambda a, ln, i: a[i, jnp.clip(ln[i], 0, 3)])(
        jnp.zeros((3, 4), jnp.int32), jnp.zeros(3, jnp.int32),
        jnp.int32(0))
    a2, ln2 = _field_taints([("A", (3, 4)), ("L", (3,))])
    out2 = eval_jaxpr(closed2, [a2, ln2, np.int32(1)], TaintDomain())[0]
    rm = read_mask(out2)
    assert rm["A"][1].all() and not rm["A"][0].any() and not rm["A"][2].any()
    assert rm["L"].tolist() == [0, 1, 0]

    # State-dependent index over the first axis: whole field.
    idx_dep = eval_jaxpr(closed, [arr, eval_jaxpr(
        closed, [arr, np.int32(0)], dom)[0]], dom)[0]
    assert read_mask(idx_dep)["X"].all()


def test_interp_select_point_update_masks():
    """where(arange == i, v, field): write diff confined to row i, and
    the positional read restriction keeps the read at row i too."""
    import jax
    import jax.numpy as jnp
    from raft_tla_tpu.analysis.interp import TaintDomain, eval_jaxpr
    from raft_tla_tpu.analysis.effects import _write_reads

    def set1(a, i):
        return jnp.where(jnp.arange(5) == i, a + 1, a)

    closed = jax.make_jaxpr(set1)(jnp.zeros(5, jnp.int32), jnp.int32(0))
    (arr,) = _field_taints([("X", (5,))])
    out = eval_jaxpr(closed, [arr, np.int32(2)], TaintDomain())[0]
    assert out.origin == "X"
    assert out.diff.tolist() == [0, 0, 1, 0, 0]
    reads = _write_reads(out, out.diff)
    assert reads["X"].tolist() == [0, 0, 1, 0, 0]


def test_interp_dynamic_update_slice_and_scatter_masks():
    """Known-position writes stay positionally confined (diff covers
    exactly the window); an unknown position widens diff to the whole
    array but keeps the operand's positional reads."""
    import jax
    import jax.numpy as jnp
    from raft_tla_tpu.analysis.interp import TaintDomain, eval_jaxpr

    def dus(a, v, k):
        return jax.lax.dynamic_update_slice(a, v[None], (k,))

    closed = jax.make_jaxpr(dus)(jnp.zeros(6, jnp.int32),
                                 jnp.int32(0), jnp.int32(0))
    (arr,) = _field_taints([("X", (6,))])
    opaque_v = eval_jaxpr(
        jax.make_jaxpr(lambda a: a.sum())(jnp.zeros(6, jnp.int32)),
        [arr], TaintDomain())[0]
    out = eval_jaxpr(closed, [arr, opaque_v, np.int32(4)],
                     TaintDomain())[0]
    assert out.origin == "X" and out.diff.tolist() == [0, 0, 0, 0, 1, 0]

    out_unk = eval_jaxpr(closed, [arr, opaque_v, opaque_v],
                         TaintDomain())[0]
    assert out_unk.origin == "X" and out_unk.diff.all()

    def at_set(a, k, v):
        return a.at[k].set(v)

    closed2 = jax.make_jaxpr(at_set)(jnp.zeros(6, jnp.int32),
                                     jnp.int32(0), jnp.int32(0))
    out2 = eval_jaxpr(closed2, [arr, np.int32(1), opaque_v],
                      TaintDomain())[0]
    assert out2.origin == "X" and out2.diff.tolist() == [0, 1, 0, 0, 0, 0]


def test_interp_planted_whole_field_widen_still_caught():
    """An unhandled primitive must still widen to the whole footprint
    AND surface an imprecision note — conservatism is load-bearing."""
    import jax
    import jax.numpy as jnp
    from raft_tla_tpu.analysis.interp import TaintDomain, eval_jaxpr, \
        read_mask

    closed = jax.make_jaxpr(lambda a: jnp.sort(a)[0])(
        jnp.zeros(5, jnp.int32))
    (arr,) = _field_taints([("X", (5,))])
    dom = TaintDomain()
    out = eval_jaxpr(closed, [arr], dom)[0]
    assert read_mask(out)["X"].all()
    assert any("sort" in n for n in dom.notes)


def test_elementwise_instance_footprints(effect_summary):
    """The headline precision wins: point actions read/write exactly
    their own rows/slots (the unlock ROADMAP item 2 named)."""
    summary, _ = effect_summary
    by_label = {i.label: i for i in summary.instances}
    t1 = by_label["Timeout(i=1)"]
    for f, m in t1.reads.items():
        assert m.sum() == 1 and m[1], (f, m)
    dup = by_label["DuplicateMessage(slot=2)"]
    assert {f: m.tolist() for f, m in dup.reads.items()} \
        == {"msg_cnt": [0, 0, 1, 0]}
    assert {f: m.tolist() for f, m in dup.writes.items()} \
        == {"msg_cnt": [0, 0, 1, 0]}
    # Receive's footprint stays whole-field — genuinely data-dependent.
    rcv = by_label["Receive(slot=0)"]
    assert rcv.reads["commit"].all() and rcv.writes["role"].all()


def test_elementwise_matrix_refines_field_granularity(effect_summary):
    """Pairs a field-granular analysis must call dependent commute at
    element granularity: same-family point actions on different servers
    and cross-family actions on disjoint rows."""
    summary, _ = effect_summary
    idx = {i.label: k for k, i in enumerate(summary.instances)}
    ind = summary.independent
    assert ind[idx["Timeout(i=0)"], idx["Timeout(i=1)"]]
    assert ind[idx["Restart(i=0)"], idx["AdvanceCommitIndex(i=1)"]]
    assert ind[idx["ClientRequest(i=0, v=1)"], idx["Timeout(i=2)"]]
    assert ind[idx["DuplicateMessage(slot=0)"],
               idx["DuplicateMessage(slot=3)"]]
    # ... while real element overlaps stay dependent.
    assert not ind[idx["Timeout(i=0)"], idx["Restart(i=0)"]]
    assert not ind[idx["Timeout(i=0)"], idx["Receive(slot=0)"]]


def test_footprints_serialized_versioned_roundtrip(effect_summary):
    """The versioned slot-level encoding: masks survive the hex
    round-trip exactly, and both decoders reject a version mismatch
    instead of misreading slot masks."""
    from raft_tla_tpu.analysis import effects
    summary, _ = effect_summary
    sj = effects.summary_json(summary)
    assert sj["footprints_version"] == effects.FOOTPRINTS_VERSION
    json.dumps(sj)
    fps = effects.footprints_from_json(sj)
    assert len(fps) == len(summary.instances)
    for fp, inst in zip(fps, summary.instances):
        for kind, masks in (("reads", inst.reads),
                            ("writes", inst.writes),
                            ("guard_reads", inst.guard_reads)):
            assert set(fp[kind]) == set(masks)
            for f, m in masks.items():
                assert (fp[kind][f] == m).all(), (inst.label, kind, f)
    stale = dict(sj, footprints_version=1)
    with pytest.raises(ValueError, match="footprint encoding"):
        effects.footprints_from_json(stale)
    with pytest.raises(ValueError, match="regenerate"):
        effects.matrices_from_json(stale)


def test_effects_differential_against_oracle_elementwise(effect_summary):
    """Element-level soundness against the reference interpreter: every
    ELEMENT a real oracle transition changes lies inside the traced
    per-family element-wise write mask union."""
    from raft_tla_tpu.models import oracle
    summary, _ = effect_summary
    fam_writes = {}
    for inst in summary.instances:
        masks = fam_writes.setdefault(inst.family, {})
        for f, m in inst.writes.items():
            masks[f] = masks.get(f, np.zeros_like(m)) | m
    frontier, seen, checked = [init_state(DIMS)], set(), 0
    for _level in range(3):
        nxt = []
        for s in frontier:
            enc_s = encode_state(s, DIMS)
            for (fam_code, _params), succ in oracle.successors(s, DIMS):
                fam = DIMS.family_names[fam_code]
                enc_t = encode_state(succ, DIMS)
                for f in lane_map.FIELDS:
                    delta = np.asarray(getattr(enc_s, f)) \
                        != np.asarray(getattr(enc_t, f))
                    if not delta.any():
                        continue
                    mask = fam_writes[fam].get(f)
                    assert mask is not None and bool(
                        (delta & ~mask).sum() == 0), (fam, f)
                checked += 1
                if succ not in seen and len(seen) < 300:
                    seen.add(succ)
                    nxt.append(succ)
        frontier = nxt
    assert checked > 100


def test_resolve_passes_dependencies():
    from raft_tla_tpu.analysis import resolve_passes
    assert resolve_passes(("por",)) == ("effects", "por")
    assert resolve_passes(("lint",)) == ("effects", "lint")
    assert resolve_passes(("bounds",)) == ("bounds",)
    assert resolve_passes(("por", "bounds")) == ("effects", "bounds", "por")
    with pytest.raises(ValueError, match="typo"):
        resolve_passes(("typo",))
    with pytest.raises(ValueError):
        resolve_passes(())
