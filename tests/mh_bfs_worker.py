"""Worker for the multi-host exhaustive-BFS test (not a pytest module).

Two processes, one global 4-device mesh: the full distributed pipeline —
expand -> fingerprint -> owner-routed all_to_all dedup ACROSS HOSTS ->
sharded FPSet insert -> enqueue, with per-controller spill pools — must
exhaust a bounded 2-server model and report the oracle-pinned counts
(4,779 distinct / diameter 25 / 12,584 generated) identically on every
controller."""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from raft_tla_tpu.utils.platform import neutralize_axon_if_cpu_requested

neutralize_axon_if_cpu_requested()

from raft_tla_tpu.parallel import multihost as mh  # noqa: E402

if os.environ.get("RAFT_COORDINATOR"):
    mh.initialize()    # single-controller mode otherwise (resume test b)

import jax  # noqa: E402

from raft_tla_tpu.engine.bfs import EngineConfig  # noqa: E402
from raft_tla_tpu.models.dims import RaftDims  # noqa: E402
from raft_tla_tpu.models.invariants import (Bounds, build_constraint,  # noqa: E402
                                            build_type_ok)
from raft_tla_tpu.models.pystate import init_state  # noqa: E402
from raft_tla_tpu.parallel.mesh import MeshBFSEngine  # noqa: E402


def main():
    dims = RaftDims(n_servers=2, n_values=1, max_log=2, n_msg_slots=8)
    ckpt_dir = os.environ.get("MH_CKPT_DIR")
    max_dia = os.environ.get("MH_MAX_DIAMETER")
    # MH_TRACE=1: record the trace across controllers (per-controller
    # stores + piece-file merge at replay) and hunt a NoLeader violation
    # whose counterexample chain crosses the process boundary.
    trace_on = bool(os.environ.get("MH_TRACE"))
    invariants = {"TypeOK": build_type_ok(dims)}
    if trace_on:
        import jax.numpy as jnp

        from raft_tla_tpu.models.dims import LEADER
        invariants["NoLeader"] = lambda st: jnp.all(st.role != LEADER)
    eng = MeshBFSEngine(
        dims,
        invariants=invariants,
        constraint=build_constraint(
            dims, Bounds(max_term=2, max_log_len=1, max_msg_count=1,
                         max_in_flight=1)),
        config=EngineConfig(batch=32, queue_capacity=1 << 10,
                            seen_capacity=1 << 14, check_deadlock=False,
                            record_trace=trace_on, sync_every=4,
                            checkpoint_dir=ckpt_dir,
                            max_diameter=int(max_dia) if max_dia else None,
                            exit_conditions=(
                                (("queue",
                                  float(os.environ["MH_QUEUE_BUDGET"])),)
                                if os.environ.get("MH_QUEUE_BUDGET")
                                else ())))
    assert eng.n_dev == len(jax.devices())    # the GLOBAL mesh
    if os.environ.get("MH_RESUME"):
        from raft_tla_tpu.engine import checkpoint as ckpt_mod
        path = ckpt_mod.latest(os.environ["MH_RESUME"])
        assert path is not None, "no resumable checkpoint found"
        res = eng.run(None, resume=path)
    else:
        res = eng.run([init_state(dims)])
    out = {
        "process": jax.process_index(),
        "global_devices": len(jax.devices()),
        "distinct": res.distinct,
        "generated": res.generated,
        "diameter": res.diameter,
        "levels": res.levels,
        "stop_reason": res.stop_reason,
        "violation": res.violation.invariant if res.violation else None,
    }
    if trace_on and res.violation is not None:
        steps = eng.replay(res.violation.fingerprint)
        assert steps[-1][1] == res.violation.state
        out["trace_len"] = len(steps)
        out["trace_path"] = [g for g, _s in steps]
    print(json.dumps(out))


if __name__ == "__main__":
    main()
