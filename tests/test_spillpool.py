"""SpillPool unit tests — both backends must be behaviorally identical.

The engines exercise the pool indirectly (spill/checkpoint differential
tests); these pin the container semantics directly, including the
disk-mode corners the engines only hit at scale: FIFO order across
pop/insert, empty-segment no-ops, concat_with's memmap assembly, and
file cleanup on consume/clear/finalize.
"""

import os

import numpy as np
import pytest

from raft_tla_tpu.engine.spillpool import SpillPool


def seg(lo, n, w=5):
    return (np.arange(lo, lo + n)[:, None]
            * np.ones((1, w))).astype(np.uint8)


@pytest.mark.parametrize("disk", [False, True])
def test_fifo_order_and_totals(tmp_path, disk):
    pool = SpillPool(str(tmp_path / "p") if disk else None)
    assert not pool and len(pool) == 0 and pool.total_rows() == 0
    pool.append(seg(0, 3))
    pool.append(seg(10, 4))
    pool.append(seg(20, 2))
    assert len(pool) == 3 and pool.total_rows() == 9
    # segments() iterates without consuming
    assert [len(s) for s in pool.segments()] == [3, 4, 2]
    assert len(pool) == 3
    a = pool.pop(0)
    np.testing.assert_array_equal(np.asarray(a), seg(0, 3))
    b = pool.pop(0)
    assert np.asarray(b)[0, 0] == 10
    assert pool.total_rows() == 2


@pytest.mark.parametrize("disk", [False, True])
def test_insert_front_and_empty_noops(tmp_path, disk):
    pool = SpillPool(str(tmp_path / "p") if disk else None)
    pool.append(seg(0, 3))
    big = pool.pop(0)
    pool.insert(0, np.asarray(big)[1:])        # put back the tail
    pool.append(seg(50, 1))
    # empty appends/inserts are no-ops in both modes
    pool.append(seg(0, 0))
    pool.insert(0, seg(0, 0))
    assert [len(s) for s in pool.segments()] == [2, 1]
    first = np.asarray(pool.pop(0))
    assert first[0, 0] == 1                    # tail of the original


@pytest.mark.parametrize("disk", [False, True])
def test_concat_with_and_cleanup(tmp_path, disk):
    d = tmp_path / "p"
    pool = SpillPool(str(d) if disk else None)
    head = seg(100, 2)
    # no segments: head returned as-is
    out, cleanup = pool.concat_with(head)
    np.testing.assert_array_equal(np.asarray(out), head)
    cleanup()
    pool.append(seg(0, 3))
    pool.append(seg(10, 1))
    out, cleanup = pool.concat_with(head)
    got = np.asarray(out).copy()
    want = np.concatenate([head, seg(0, 3), seg(10, 1)])
    np.testing.assert_array_equal(got, want)
    cleanup()
    # the pool still holds its segments after a checkpoint assembly
    assert pool.total_rows() == 4
    pool.clear()
    assert not pool
    if disk:
        assert list(d.iterdir()) == []         # all files gone


def test_disk_files_unlinked_on_pop_and_del(tmp_path):
    d = tmp_path / "p"
    pool = SpillPool(str(d))
    pool.append(seg(0, 3))
    pool.append(seg(10, 3))
    arr = pool.pop(0)
    # popped file is unlinked immediately; mapping stays readable
    assert len(list(d.iterdir())) == 1
    assert np.asarray(arr)[2, 0] == 2
    del pool                                    # finalizer clears leftovers
    import gc
    gc.collect()
    assert list(d.iterdir()) == []
