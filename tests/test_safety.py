"""Differential tests of the correctness-invariant suite (models/safety.py).

Mirrors the reference's proof tier (raft.tla:896-1180; SURVEY §2.3): every
safety invariant is evaluated two independent ways — pure-Python mirror vs
vectorized JAX kernel — over (a) reachable states of a small bounded model
(where the whole suite must hold) and (b) unstructured random states (where
violations are common, exercising the False paths of both implementations).
Hand-crafted violating states then pin each invariant's failure mode.
"""

import jax
import numpy as np
import pytest

from raft_tla_tpu.models import oracle as orc
from raft_tla_tpu.models import smoke
from raft_tla_tpu.models.dims import CANDIDATE, LEADER, RaftDims
from raft_tla_tpu.models.invariants import Bounds, constraint_py
from raft_tla_tpu.models.pystate import PyState, init_state
from raft_tla_tpu.models.safety import (SAFETY_INVARIANTS,
                                        SAFETY_INVARIANTS_PY)
from raft_tla_tpu.models.schema import encode_state, stack_states

DIMS2 = RaftDims(n_servers=2, n_values=1, max_log=3, n_msg_slots=12)
DIMS3 = RaftDims(n_servers=3, n_values=2, max_log=3, n_msg_slots=12)


def _eval_both(states, dims):
    """Evaluate every safety invariant via mirror and kernel; compare."""
    batch = stack_states([encode_state(s, dims) for s in states])
    results = {}
    for name, build in SAFETY_INVARIANTS.items():
        kern = jax.jit(jax.vmap(build(dims)))
        got = np.asarray(kern(batch))
        want = np.array([SAFETY_INVARIANTS_PY[name](s, dims)
                         for s in states])
        mism = np.nonzero(got != want)[0]
        assert mism.size == 0, (
            f"{name}: kernel/oracle disagree on {mism.size} states, "
            f"first at index {mism[0] if mism.size else None}:\n"
            f"{states[int(mism[0])] if mism.size else None}")
        results[name] = want
    return results


def test_suite_holds_on_reachable_and_matches_kernel():
    """On reachable states of a bounded 2-server model the entire suite
    holds, and mirror == kernel state-for-state."""
    bounds = Bounds(max_term=2, max_log_len=1, max_msg_count=1)
    res = orc.bfs([init_state(DIMS2)], DIMS2,
                  constraint=constraint_py(bounds), check_deadlock=False,
                  stop_predicate=lambda r: r.distinct_states >= 1200)
    states = list(res.parent.keys())
    assert len(states) >= 500
    results = _eval_both(states, DIMS2)
    for name, vals in results.items():
        assert vals.all(), f"{name} violated on a reachable state"


def test_kernel_matches_oracle_on_random_states():
    """Unstructured random states: many violate the suite; both sides must
    agree exactly (False paths included)."""
    states = smoke.random_states(DIMS2, 150, seed=7)
    results = _eval_both(states, DIMS2)
    # Sanity: the random set actually exercises violations somewhere.
    assert any((~vals).any() for vals in results.values())


def _base(dims, **kw):
    s = init_state(dims)
    return s.replace(**kw)


def _crafted_violations():
    """(invariant name, dims, violating state) for every suite member."""
    d2, d3 = DIMS2, DIMS3
    out = []
    # ElectionSafety raft.tla:1124-1129: leader 0 (term 2) lacks an entry
    # with its own term while server 1 has one.
    out.append(("ElectionSafety", d2, _base(
        d2, role=(LEADER, 0), current_term=(2, 2),
        log=((), ((2, 1),)))))
    # LogMatching raft.tla:1132-1136: same (index, term), different value.
    out.append(("LogMatching", d3, _base(
        d3, log=(((1, 1),), ((1, 2),), ()))))
    # LeaderVotesQuorum raft.tla:1033-1037: leader without any votes.
    out.append(("LeaderVotesQuorum", d2, _base(
        d2, role=(LEADER, 0), current_term=(2, 1))))
    # CandidateTermNotInLog raft.tla:1041-1047: electable candidate whose
    # term already appears in a log.
    out.append(("CandidateTermNotInLog", d2, _base(
        d2, role=(CANDIDATE, 0), current_term=(2, 2),
        log=((), ((2, 1),)))))
    # VotesGrantedInv raft.tla:1145-1153: 0 holds 1's vote at equal term but
    # misses 1's committed entry.
    out.append(("VotesGrantedInv", d2, _base(
        d2, votes_granted=(0b10, 0), log=((), ((1, 1),)),
        commit_index=(0, 1))))
    # QuorumLogInv raft.tla:1157-1161 (N=3): 0's committed entry is in no
    # other log -> a quorum {1, 2} exists with no holder.
    out.append(("QuorumLogInv", d3, _base(
        d3, log=(((1, 1),), (), ()), commit_index=(1, 0, 0))))
    # MoreUpToDateCorrect raft.tla:1167-1172: 0 is more up to date than 1
    # yet lacks 1's committed entry.
    out.append(("MoreUpToDateCorrect", d2, _base(
        d2, log=(((2, 1),), ((1, 1),)), commit_index=(0, 1))))
    # LeaderCompleteness raft.tla:1176-1180: leader misses a committed entry.
    out.append(("LeaderCompleteness", d2, _base(
        d2, role=(LEADER, 0), current_term=(2, 1),
        log=((), ((1, 1),)), commit_index=(0, 1))))
    # MessagesInv raft.tla:941-946 via RequestVoteRequestInv :915-920: a
    # candidate's vote request advertises a wrong lastLogIndex.
    out.append(("MessagesInv", d2, _base(
        d2, role=(CANDIDATE, 0), current_term=(2, 1),
        messages=frozenset({((0, 0, 1, 2, 0, 5), 1)}))))
    return [x for x in out if x is not None]


@pytest.mark.parametrize("name,dims,state",
                         _crafted_violations(),
                         ids=[x[0] for x in _crafted_violations()])
def test_crafted_violation_detected(name, dims, state):
    py = SAFETY_INVARIANTS_PY[name](state, dims)
    assert py is False, f"{name} mirror failed to flag the crafted state"
    kern = SAFETY_INVARIANTS[name](dims)
    got = bool(kern(encode_state(state, dims)))
    assert got is False, f"{name} kernel failed to flag the crafted state"


def test_registry_resolution(tmp_path):
    """A cfg naming the full suite resolves through the front-end registry."""
    from raft_tla_tpu.engine.check import resolve_invariants
    from raft_tla_tpu.utils.cfg import load_config
    cfg = tmp_path / "Safety2.cfg"
    cfg.write_text("""
CONSTANTS
    Server = {r1, r2}
    Value = {v1}
    MaxTerm = 2
    MaxLogLen = 1
    MaxMsgCount = 1
SPECIFICATION Spec
INVARIANTS TypeOK MessagesInv LeaderVotesQuorum CandidateTermNotInLog
           ElectionSafety LogMatching VotesGrantedInv QuorumLogInv
           MoreUpToDateCorrect LeaderCompleteness
CONSTRAINT BoundedSpace
""")
    setup = load_config(str(cfg))
    invs = resolve_invariants(setup)
    assert len(invs) == 10
