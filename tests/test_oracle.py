"""Golden tests for the pure-Python oracle, hand-derived from the spec text.

Every expected fact here is derivable by reading /root/reference/raft.tla
directly; these tests pin the oracle before it is used as the differential
baseline for the JAX kernels.
"""

from raft_tla_tpu.models import oracle as orc
from raft_tla_tpu.models.dims import (A_TIMEOUT, AEQ, AER, CANDIDATE,
                                      FOLLOWER, LEADER, NIL, RVQ, RVR,
                                      RaftDims)
from raft_tla_tpu.models.pystate import bag_add, init_state

DIMS = RaftDims(n_servers=3, n_values=2)


def test_init_state():
    s = init_state(DIMS)
    assert s.current_term == (1, 1, 1)
    assert s.role == (FOLLOWER,) * 3
    assert s.voted_for == (NIL,) * 3
    assert s.log == ((), (), ())
    assert s.next_index == ((1, 1, 1),) * 3
    assert s.match_index == ((0, 0, 0),) * 3
    assert s.messages == frozenset()


def test_successors_of_init():
    # From Init only Restart (self-loop) and Timeout are enabled:
    # no candidates => no RequestVote/BecomeLeader; no leaders => no
    # ClientRequest/AdvanceCommitIndex/AppendEntries; empty bag => no
    # message actions.  AdvanceCommitIndex/Restart require no guard beyond
    # role, so Restart contributes 3 self-loops.
    s = init_state(DIMS)
    succ = orc.successors(s, DIMS)
    assert len(succ) == 6  # 3x Restart + 3x Timeout
    sset = orc.successor_set(s, DIMS)
    assert s in sset       # Restart(i) on Init reproduces Init exactly
    assert len(sset) == 4  # Init + three Timeout(i) variants
    for (fam, params), t in succ:
        if fam == A_TIMEOUT:
            (i,) = params
            assert t.current_term[i] == 2 and t.role[i] == CANDIDATE


def test_bfs_level1():
    res = orc.bfs([init_state(DIMS)], DIMS, max_levels=1)
    assert res.distinct_states == 4
    assert res.levels[0] == 1 and res.levels[1] == 3


def test_candidate_flow_to_leader():
    """Drive one server through the full election pipeline by hand."""
    dims = DIMS
    s = init_state(dims)
    s = orc.timeout(s, dims, 0)
    assert s.role[0] == CANDIDATE and s.current_term[0] == 2

    # Candidate asks itself for a vote (i = j allowed, raft.tla:150).
    s = orc.request_vote(s, dims, 0, 0)
    (m, c), = s.messages
    assert m == (RVQ, 0, 0, 2, 0, 0) and c == 1

    # Receiving its own request: mterm (2) > currentTerm? no, equal; grants.
    s2 = orc.receive(s, dims, m)
    assert s2.voted_for[0] == 1  # voted for server 0 (encoded 0+1)
    (resp, c2), = s2.messages
    assert resp == (RVR, 0, 0, 2, 1, ()) and c2 == 1

    # Tally the vote.
    s3 = orc.receive(s2, dims, resp)
    assert s3.votes_responded[0] == 0b001
    assert s3.votes_granted[0] == 0b001
    assert s3.messages == frozenset()

    # One vote of three is not a quorum.
    assert orc.become_leader(s3, dims, 0) is None
    # Fake a second grant.
    s4 = s3.replace(votes_granted=(0b011, 0, 0))
    s5 = orc.become_leader(s4, dims, 0)
    assert s5.role[0] == LEADER
    assert s5.next_index[0] == (1, 1, 1)  # Len(log)+1 with empty log


def test_update_term_leaves_message_in_flight():
    """UpdateTerm (raft.tla:373-379) must not consume the message (:378)."""
    dims = DIMS
    s = init_state(dims)
    m = (RVQ, 1, 0, 5, 0, 0)  # term 5 > currentTerm 1
    s = s.replace(messages=bag_add(s.messages, m))
    t = orc.receive(s, dims, m)
    assert t.current_term[0] == 5 and t.role[0] == FOLLOWER
    assert t.messages == s.messages  # still in flight
    # Re-processing in the successor now takes the handler branch.
    t2 = orc.receive(t, dims, m)
    assert t2.voted_for[0] == 2  # granted to server 1
    assert (m, 1) not in t2.messages


def test_already_done_hidden_guard():
    """AppendEntriesAlreadyDone's :317 bug => enabled only when
    m.mcommitIndex = commitIndex[i]."""
    dims = DIMS
    s = init_state(dims)
    # Follower 0 at term 1 with empty log; heartbeat with prev=0, no entries.
    hb_ok = (AEQ, 1, 0, 1, 0, 0, (), 0)    # mcommitIndex = 0 = commitIndex[0]
    hb_bad = (AEQ, 1, 0, 1, 0, 0, (), 1)   # mcommitIndex = 1 != 0
    s_ok = s.replace(messages=bag_add(s.messages, hb_ok))
    t = orc.receive(s_ok, dims, hb_ok)
    assert t is not None
    (resp, _), = t.messages
    assert resp == (AER, 0, 1, 1, 1, 0)    # success, matchIndex=0
    s_bad = s.replace(messages=bag_add(s.messages, hb_bad))
    assert orc.receive(s_bad, dims, hb_bad) is None


def test_conflict_truncates_one_entry():
    """ConflictAppendEntriesRequest (raft.tla:319-325) drops exactly one
    trailing entry regardless of the conflict position."""
    dims = DIMS
    s = init_state(dims)
    log0 = ((1, 1), (2, 1), (2, 2))
    s = s.replace(log=(log0, (), ()),
                  current_term=(3, 3, 3))
    # Conflict at index 1 (prev=0 always logOk): entry term 3 != 1.
    m = (AEQ, 1, 0, 3, 0, 0, ((3, 2),), 0)
    s = s.replace(messages=bag_add(s.messages, m))
    t = orc.receive(s, dims, m)
    assert t.log[0] == ((1, 1), (2, 1))    # only the LAST entry dropped
    assert t.messages == s.messages        # no reply, message in flight


def test_duplicate_and_drop():
    dims = DIMS
    s = init_state(dims)
    m = (RVQ, 0, 1, 1, 0, 0)
    s = s.replace(messages=bag_add(s.messages, m))
    d = orc.duplicate_message(s, m)
    assert dict(d.messages)[m] == 2
    d2 = orc.drop_message(d, m)
    assert dict(d2.messages)[m] == 1
    d3 = orc.drop_message(d2, m)
    assert d3.messages == frozenset()


def test_advance_commit_requires_current_term_entry():
    """The §5.4.2 rule (raft.tla:229-230): only entries of the leader's own
    term are committed directly."""
    dims = DIMS
    s = init_state(dims)
    s = s.replace(role=(LEADER, FOLLOWER, FOLLOWER),
                  current_term=(2, 2, 2),
                  log=(((1, 1),), ((1, 1),), ((1, 1),)),
                  match_index=((0, 1, 1), (0, 0, 0), (0, 0, 0)))
    # Quorum agrees on index 1, but its term (1) != currentTerm (2): no move.
    t = orc.advance_commit_index(s, dims, 0)
    assert t.commit_index[0] == 0
    # Same with an own-term entry: commits.
    s2 = s.replace(log=(((2, 1),), ((2, 1),), ((2, 1),)))
    t2 = orc.advance_commit_index(s2, dims, 0)
    assert t2.commit_index[0] == 1


def test_bounded_bfs_is_finite_and_stable():
    """A tightly constrained space must terminate; the count is pinned as a
    regression oracle for the JAX engine (value observed from this oracle,
    then cross-checked by the independent JAX BFS in test_engine)."""
    dims = DIMS

    def constraint(t, d):
        return (max(t.current_term) <= 2
                and max(len(l) for l in t.log) <= 1
                and all(c <= 1 for _m, c in t.messages))

    res = orc.bfs([init_state(dims)], dims, constraint=constraint,
                  check_deadlock=False, max_levels=4)
    assert res.invariant_violation is None
    assert res.distinct_states > 100
    # Determinism: same run twice gives identical counts.
    res2 = orc.bfs([init_state(dims)], dims, constraint=constraint,
                   check_deadlock=False, max_levels=4)
    assert (res.distinct_states, res.diameter) == (res2.distinct_states,
                                                   res2.diameter)


def test_exhaust_digest_is_object_identity_insensitive():
    """scripts/oracle_exhaust.canon_digest must hash VALUES, not object
    graphs: two ==-equal states whose internals differ only in tuple
    sharing (an RVR's mlog being the sender's log tuple vs an equal
    copy) must digest identically.  Plain pickle.dumps emits a memo
    backreference for the shared case — that identity-sensitivity split
    48 spec-identical states at MCraft_bounded L13 into 96 digests (the
    'engine 48-state deficit' that wasn't: ROUND5_NOTES.md)."""
    import os
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(__file__),
                                    os.pardir, "scripts"))
    from oracle_exhaust import canon_digest
    from raft_tla_tpu.models.dims import RVR, RaftDims
    from raft_tla_tpu.models.pystate import init_state

    dims = RaftDims(n_servers=2, n_values=1, max_log=2, n_msg_slots=4)
    s = init_state(dims)
    log0 = ((1, 1),)
    # A value-equal copy built at runtime: a LITERAL ((1, 1),) would be
    # constant-folded by CPython to the same object as log0, silently
    # recreating the sharing this test must break.
    log0_copy = tuple((e[0], e[1]) for e in log0)
    assert log0 == log0_copy and log0 is not log0_copy
    assert log0[0] is not log0_copy[0]
    shared = s.replace(
        log=(log0, ()),
        messages=frozenset({((RVR, 0, 1, 1, 1, log0), 1)}))
    fresh = s.replace(
        log=(log0, ()),
        messages=frozenset({((RVR, 0, 1, 1, 1, log0_copy), 1)}))
    assert shared == fresh
    assert canon_digest(shared) == canon_digest(fresh)
