"""Counterexample explainer (engine/explain.py) + shared formatter tests.

The contract under test: a violation's replayed trace decodes through
the ONE canonical formatter (models/pystate.state_fields — the same one
format_state renders from) into TLC-style numbered states whose every
field matches the Python oracle's replay, renders as text/JSON/HTML,
lands automatically as <workdir>/counterexample.{txt,json} with the
path stamped into run_end, and (small spaces) the full reached graph
exports as DOT/GraphML.
"""

import json
import os
import xml.etree.ElementTree as ET

import pytest

import jax.numpy as jnp

from raft_tla_tpu.engine import explain
from raft_tla_tpu.engine.bfs import BFSEngine, EngineConfig
from raft_tla_tpu.models import oracle as orc
from raft_tla_tpu.models.dims import LEADER, RaftDims
from raft_tla_tpu.models.invariants import (Bounds, build_constraint,
                                            build_type_ok)
from raft_tla_tpu.models.pystate import (diff_states, format_state,
                                         init_state, state_fields)

DIMS = RaftDims(n_servers=3, n_values=2, max_log=4, n_msg_slots=32)
BOUNDS = Bounds(max_term=2, max_log_len=1, max_msg_count=1)


def seeded_root():
    """Candidate one vote short of quorum (test_engine's fast violation
    shape): the minimal NoLeader counterexample is two steps away."""
    return init_state(DIMS).replace(
        role=(1, 0, 0), current_term=(2, 2, 2), voted_for=(1, 1, 1),
        votes_responded=(0b001, 0, 0), votes_granted=(0b001, 0, 0),
        messages=frozenset({((1, 1, 0, 2, 1, ()), 1)}))


@pytest.fixture(scope="module")
def violation_run(tmp_path_factory):
    """One traced violating run with a counterexample workdir; shared
    by the rendering/artifact tests below."""
    tmp = tmp_path_factory.mktemp("explain")
    ev = str(tmp / "events.jsonl")
    inv = {"TypeOK": build_type_ok(DIMS),
           "NoLeader": lambda st: jnp.all(st.role != LEADER)}
    eng = BFSEngine(DIMS, invariants=inv,
                    constraint=build_constraint(DIMS, BOUNDS),
                    config=EngineConfig(
                        batch=32, queue_capacity=1 << 12,
                        seen_capacity=1 << 15, check_deadlock=False,
                        events_out=ev, counterexample_dir=str(tmp)))
    res = eng.run([seeded_root()])
    assert res.stop_reason == "violation"
    steps = eng.replay(res.violation.fingerprint)
    return eng, res, steps, str(tmp), ev


# ---------------------------------------------------------------------------
# The shared formatter (models/pystate.py): one source of truth.

def test_state_fields_is_the_single_formatter_substrate():
    s = init_state(DIMS)
    f = state_fields(s, DIMS)
    # Every server field keyed r<i>.<name>, plus the message bag.
    assert f["r1.role"] == "F" and f["r1.votedFor"] == "Nil"
    assert f["messages"] == []
    # format_state renders FROM state_fields — the fields it prints are
    # exactly the canonical view (spot-check the derived line).
    text = format_state(s, DIMS)
    assert "r1: term=1 role=F votedFor=Nil" in text
    assert "messages (0 distinct):" in text


def test_diff_states_reports_exactly_the_changed_fields():
    s = init_state(DIMS)
    t = orc.timeout(s, DIMS, 0)
    d = diff_states(s, t, DIMS)
    assert d["r1.role"] == ["F", "C"]
    assert d["r1.term"] == [1, 2]
    assert "r2.role" not in d and "messages.added" not in d
    # Message-bag deltas render as added/removed message lines.
    u = orc.request_vote(t, DIMS, 0, 1)
    d2 = diff_states(t, u, DIMS)
    assert list(d2) == ["messages.added"]
    assert "RequestVoteRequest" in d2["messages.added"][0]


# ---------------------------------------------------------------------------
# Decoded trace vs the Python oracle — field for field.

def test_decoded_trace_matches_oracle_replay_field_for_field(violation_run):
    eng, res, steps, _tmp, _ev = violation_run
    decoded = explain.decode_steps(steps, DIMS)
    assert decoded[0]["action"] == "Initial predicate"
    assert decoded[-1]["index"] == len(steps)
    # Oracle replay: every engine step must be a legal oracle successor,
    # and the DECODED fields must equal the canonical view of the very
    # oracle state that matched — field for field.
    prev = steps[0][1]
    assert decoded[0]["state"] == state_fields(prev, DIMS)
    for rec, (g, st) in zip(decoded[1:], steps[1:]):
        oracle_succ = orc.successor_set(prev, DIMS)
        assert st in oracle_succ
        oracle_match = next(o for o in oracle_succ if o == st)
        assert rec["state"] == state_fields(oracle_match, DIMS)
        # The action label decodes through the grid (family name match).
        fam = DIMS.family_names[DIMS.instance_info(g)[0]]
        assert rec["action"].startswith(fam)
        # The per-step diff is the oracle-visible delta.
        assert rec["changed"] == diff_states(prev, st, DIMS)
        assert rec["changed"], "a spec action must change something"
        prev = st
    assert steps[-1][1] == res.violation.state


# ---------------------------------------------------------------------------
# Renderings.

def test_render_text_is_tlc_shaped(violation_run):
    _eng, res, steps, _tmp, _ev = violation_run
    text = explain.render_text(steps, DIMS, violation=res.violation)
    assert "Error: Invariant NoLeader is violated" in text
    assert "State 1: <Initial predicate>" in text
    assert f"State {len(steps)}: <" in text
    assert "changed:" in text
    # Full states render through the shared format_state.
    assert format_state(steps[0][1], DIMS) in text


def test_render_json_roundtrips(violation_run):
    _eng, res, steps, _tmp, _ev = violation_run
    doc = json.loads(json.dumps(
        explain.render_json(steps, DIMS, violation=res.violation)))
    assert doc["invariant"] == "NoLeader"
    assert doc["length"] == len(steps)
    assert doc["depth"] == len(steps) - 1
    assert doc["states"][0]["state"]["r1.role"] == "C"
    assert doc["states"][-1]["state"]["r1.role"] == "L"


def test_render_html_is_standalone(violation_run):
    _eng, res, steps, _tmp, _ev = violation_run
    html = explain.render_html(steps, DIMS, violation=res.violation)
    assert html.startswith("<!doctype html>")
    assert "NoLeader" in html and "State 1:" in html
    assert "&lt;Initial predicate&gt;" in html     # escaped action labels
    assert "http" not in html.split("</style>")[1]  # no external assets


# ---------------------------------------------------------------------------
# Automatic artifact write + run_end stamping.

def test_counterexample_files_written_and_stamped(violation_run):
    from raft_tla_tpu.obs import validate_run_events
    _eng, res, steps, tmp, ev = violation_run
    assert res.counterexample["depth"] == len(steps) - 1
    txt, jsn = res.counterexample["txt"], res.counterexample["json"]
    assert os.path.dirname(txt) == tmp
    text = open(txt, encoding="utf-8").read()
    assert "Error: Invariant NoLeader is violated" in text
    doc = json.load(open(jsn, encoding="utf-8"))
    assert doc["length"] == len(steps)
    # The event log validates WITH the new statespace event, and
    # run_end carries the rendered path (satellite: obs/events.py).
    events = validate_run_events(ev)
    end = [e for e in events if e["event"] == "run_end"][-1]
    assert end["counterexample_path"] == txt
    assert any(e["event"] == "statespace" for e in events)


def test_no_workdir_means_no_autowrite(tmp_path):
    inv = {"NoLeader": lambda st: jnp.all(st.role != LEADER)}
    eng = BFSEngine(DIMS, invariants=inv,
                    constraint=build_constraint(DIMS, BOUNDS),
                    config=EngineConfig(batch=32, queue_capacity=1 << 12,
                                        seen_capacity=1 << 15,
                                        check_deadlock=False))
    res = eng.run([seeded_root()])
    assert res.stop_reason == "violation"
    assert res.counterexample == {}        # nowhere to write: disabled


# ---------------------------------------------------------------------------
# Full-graph export.

def test_graph_export_dot_and_graphml(violation_run):
    eng, _res, _steps, _tmp, _ev = violation_run
    dot = explain.export_graph(eng.trace, DIMS, fmt="dot")
    assert dot.startswith("digraph statespace")
    assert "->" in dot and "label=" in dot
    # Every root is a filled node.
    for fp in eng.trace.roots:
        assert f'"{fp:#018x}" [style=filled' in dot
    gml = explain.export_graph(eng.trace, DIMS, fmt="graphml")
    root = ET.fromstring(gml)              # well-formed XML or bust
    ns = "{http://graphml.graphdrawing.org/xmlns}"
    nodes = root.findall(f".//{ns}node")
    edges = root.findall(f".//{ns}edge")
    assert len(nodes) == len({n.get('id') for n in nodes})
    assert edges and all(e.find(f"{ns}data").text for e in edges)


def test_graph_export_cap_refuses_big_spaces(violation_run):
    eng, _res, _steps, _tmp, _ev = violation_run
    with pytest.raises(ValueError, match="graph-export cap"):
        explain.export_graph(eng.trace, DIMS, cap=1)
    with pytest.raises(ValueError, match="dot/graphml"):
        explain.export_graph(eng.trace, DIMS, fmt="png")


# ---------------------------------------------------------------------------
# CLI surfaces: check --render-trace/--history and the explain command.

TINY_CFG = """
CONSTANTS
    Server = {r1, r2}
    Value = {v1}
    Follower = Follower
    Candidate = Candidate
    Leader = Leader
    Nil = Nil
    RequestVoteRequest = RequestVoteRequest
    RequestVoteResponse = RequestVoteResponse
    AppendEntriesRequest = AppendEntriesRequest
    AppendEntriesResponse = AppendEntriesResponse
    MaxTerm = 2
    MaxLogLen = 1
    MaxMsgCount = 1
SPECIFICATION Spec
INVARIANT NoLeaderElected
CONSTRAINT BoundedSpace
CHECK_DEADLOCK FALSE
\\* TPU: BATCH = 64
\\* TPU: QUEUE_CAPACITY = 4096
\\* TPU: SEEN_CAPACITY = 16384
"""


def test_cli_check_render_trace_and_history(tmp_path, capsys):
    from raft_tla_tpu import cli
    from raft_tla_tpu.obs import history as history_mod
    cfg = tmp_path / "tiny.cfg"
    cfg.write_text(TINY_CFG)
    led = tmp_path / "ledger.jsonl"
    rc = cli.main(["check", str(cfg), "--platform", "cpu",
                   "--render-trace", "--counterexample-dir",
                   str(tmp_path), "--history", str(led),
                   "--progress-interval", "0"])
    assert rc == 1
    out = capsys.readouterr().out
    assert "State 1: <Initial predicate>" in out
    assert "Error: Invariant NoLeaderElected is violated" in out
    assert "fp collision prob" in out          # format_result report line
    assert "counterexample written" in out
    assert (tmp_path / "counterexample.txt").exists()
    assert (tmp_path / "counterexample.json").exists()
    entries = history_mod.read_history(str(led))
    assert entries[0]["kind"] == "check"
    assert entries[0]["verdict"] == "violation"
    assert entries[0]["cfg_fingerprint"]


def test_cli_explain_renders_and_exports_graph(tmp_path, capsys):
    from raft_tla_tpu import cli
    cfg = tmp_path / "tiny.cfg"
    cfg.write_text(TINY_CFG)
    dot = tmp_path / "g.dot"
    rc = cli.main(["explain", str(cfg), "--platform", "cpu",
                   "--graph", str(dot)])
    assert rc == 1
    out = capsys.readouterr().out
    assert "State 1: <Initial predicate>" in out
    assert "BecomeLeader" in out
    text = dot.read_text()
    assert text.startswith("digraph statespace") and "->" in text
    # Cap refusal on a VIOLATING model keeps check's exit-1 contract
    # (the verdict outranks the failed graph export, said on stderr).
    rc2 = cli.main(["explain", str(cfg), "--platform", "cpu",
                    "--graph", str(dot), "--graph-cap", "10"])
    assert rc2 == 1
    assert "graph-export cap" in capsys.readouterr().err


# ---------------------------------------------------------------------------
# The pinned violating cfg (configs/MCraft_noleader.cfg) end to end:
# check writes a rendered counterexample whose decoded states match the
# oracle replay exactly (the acceptance criterion, satellite 4).

@pytest.mark.slow   # ~1.5 min CPU; tier-1 keeps the depth-limited explain tests
def test_pinned_violation_cfg_renders_and_matches_oracle(tmp_path):
    from raft_tla_tpu.engine.check import run_check
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    res = run_check(os.path.join(repo, "configs/MCraft_noleader.cfg"))
    # The cfg's own backend directives size the engine; wire the
    # counterexample workdir through the engine the result carries.
    assert res.stop_reason == "violation"
    assert res.violation.invariant == "NoLeaderElected"
    eng = res.engine
    steps = eng.replay(res.violation.fingerprint)
    # Pinned: the minimal election under MaxTerm=2 is depth 9
    # (Timeout, two RequestVote sends, two grant round-trips,
    # BecomeLeader — BFS order makes it minimal).
    assert len(steps) - 1 == 9
    assert LEADER in res.violation.state.role
    setup_dims = eng.dims
    # The canary's oracle mirror agrees, and BFS minimality holds: every
    # state before the last still satisfies it (the FIRST leader is the
    # violation) — no_leader_py is the py-side definition of record.
    from raft_tla_tpu.models.invariants import no_leader_py
    assert not no_leader_py(res.violation.state, setup_dims)
    assert all(no_leader_py(st, setup_dims) for _g, st in steps[:-1])
    prev = steps[0][1]
    for g, st in steps[1:]:
        succ = orc.successor_set(prev, setup_dims)
        assert st in succ
        match = next(o for o in succ if o == st)
        assert state_fields(st, setup_dims) \
            == state_fields(match, setup_dims)
        prev = st
    # And the explainer writes the artifacts when given a workdir.
    out = explain.write_counterexample(eng, res, str(tmp_path))
    assert out["depth"] == 9
    assert "NoLeaderElected" in open(out["txt"], encoding="utf-8").read()
