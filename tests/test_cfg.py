"""cfg-parser tests: the reference configs are the source of truth."""

import os

import pytest

from raft_tla_tpu.utils.cfg import (load_config, parse_cfg,
                                    scan_module_definitions)

REF = "/root/reference"
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def reference():
    """Path to the read-only reference spec checkout, or a skip.

    The reference (lemmy/raft.tla + TLC harness configs) is mounted at
    /root/reference on the primary dev host but absent in plain CI /
    test containers; the four tests that parse the REAL reference files
    skip there with this reason instead of failing tier-1.  Everything
    those tests cover structurally is still exercised against the
    committed configs/ copies by the rest of this module."""
    if not os.path.isdir(REF):
        pytest.skip(f"reference specs not mounted ({REF} absent in this "
                    f"container); committed configs/ cover the grammar")
    return REF


def test_parse_mcraft_cfg(reference):
    s = load_config(f"{reference}/MCraft.cfg")
    assert s.dims.n_servers == 3 and s.dims.n_values == 2
    assert s.server_names == ("r1", "r2", "r3")
    assert s.value_names == ("v1", "v2")
    assert s.invariants == ["TypeOK"]
    assert s.constraints == [] and not s.smoke
    assert s.check_deadlock            # TLC default: on
    assert s.bounds.max_term is None   # MCraft.cfg is unbounded


def test_parse_smokeraft_cfg(reference):
    s = load_config(f"{reference}/Smokeraft.cfg")
    assert s.dims.n_servers == 3 and s.dims.n_values == 2
    assert s.smoke and s.smoke_k == 2          # Smokeraft.tla:17-19
    assert s.max_seconds == 1.0                # TLCGet("duration") > 1
    assert s.max_diameter == 100               # TLCGet("diameter") > 100
    assert not s.check_deadlock                # Smokeraft.cfg:48
    assert "StopAfter" not in s.constraints    # consumed into budgets


def test_parse_bounded_config():
    s = load_config(os.path.join(REPO, "configs/MCraft_bounded.cfg"))
    assert s.dims.n_servers == 3 and s.dims.n_values == 2
    assert (s.bounds.max_term, s.bounds.max_log_len,
            s.bounds.max_msg_count) == (3, 2, 1)
    assert s.constraints == ["BoundedSpace"]
    assert s.dims.max_log == 3     # MaxLogLen + 1 append headroom


def test_parse_raft5_config():
    s = load_config(os.path.join(REPO, "configs/raft5_bounded.cfg"))
    assert s.dims.n_servers == 5
    assert s.bounds.max_term == 4 and s.bounds.max_log_len == 4


def test_module_definition_scan():
    text = "foo == \n{a, b}\nk ==\n   2\nbar == {x}\n"
    d = scan_module_definitions(text)
    assert d == {"foo": ("a", "b"), "k": 2, "bar": ("x",)}


def test_stop_after_scan():
    from raft_tla_tpu.utils.cfg import scan_exit_operators
    text = ('StopAfter ==\n  \\/ TLCSet("exit", TLCGet("duration") > 7)\n'
            '  \\/ TLCSet("exit", TLCGet("diameter") > 42)\n')
    op = scan_exit_operators(text)["StopAfter"]
    assert op.conds == (("duration", 7.0), ("diameter", 42.0)) and op.pure


def test_unknown_constant_raises(tmp_path):
    cfgf = tmp_path / "broken.cfg"
    cfgf.write_text("CONSTANT Value = {v1}\nSPECIFICATION Spec\n")
    with pytest.raises(ValueError, match="Server"):
        load_config(str(cfgf))


def test_parse_tpu_backend_directives():
    """"\\* TPU:" comment directives select the engine backend while the
    file stays a valid stock-TLC cfg (BASELINE.json north star)."""
    s = load_config(os.path.join(REPO, "configs/TPUraft.cfg"))
    assert s.dims.n_servers == 5
    assert s.bounds.max_term == 4 and s.bounds.max_log_len == 4
    assert s.backend == {"BATCH": 8192, "QUEUE_CAPACITY": 1 << 22,
                         "SEEN_CAPACITY": 1 << 25, "N_MSG_SLOTS": 48,
                         "CHECKPOINT_INTERVAL": 300}
    assert s.dims.n_msg_slots == 48        # backend key reached dims
    # CLI flag wins over the directive.
    s2 = load_config(os.path.join(REPO, "configs/TPUraft.cfg"),
                     n_msg_slots=40)
    assert s2.dims.n_msg_slots == 40


def test_unknown_backend_key_raises(tmp_path):
    cfgf = tmp_path / "bad.cfg"
    cfgf.write_text("\\* TPU: BOGUS_KEY = 1\n"
                    "CONSTANT Server = {r1}\nCONSTANT Value = {v1}\n")
    with pytest.raises(ValueError, match="BOGUS_KEY"):
        load_config(str(cfgf))


def test_reference_cfgs_have_no_backend_keys(reference):
    assert load_config(f"{reference}/MCraft.cfg").backend == {}


def test_backend_directives_reach_engine_config():
    """API precedence: run_check/make_engine honor backend keys when no
    explicit EngineConfig is supplied (not just the CLI path)."""
    from raft_tla_tpu.engine.check import engine_config_from_backend
    s = load_config(os.path.join(REPO, "configs/TPUraft.cfg"))
    ec = engine_config_from_backend(s)
    assert ec.batch == 8192
    assert ec.queue_capacity == 1 << 22
    assert ec.seen_capacity == 1 << 25
    assert ec.checkpoint_interval_seconds == 300.0


def test_property_rejected_loudly(tmp_path):
    """A temporal PROPERTY must fail the load, mirroring ACTION_CONSTRAINT:
    silently dropping it would let the cfg 'pass' a property that was
    never checked (liveness needs a different algorithm than safety BFS)."""
    cfgf = tmp_path / "liveness.cfg"
    cfgf.write_text(
        "CONSTANTS\n    Server = {r1, r2, r3}\n    Value = {v1}\n"
        "    Follower = Follower\n    Candidate = Candidate\n"
        "    Leader = Leader\n    Nil = Nil\n"
        "    RequestVoteRequest = RequestVoteRequest\n"
        "    RequestVoteResponse = RequestVoteResponse\n"
        "    AppendEntriesRequest = AppendEntriesRequest\n"
        "    AppendEntriesResponse = AppendEntriesResponse\n"
        "SPECIFICATION Spec\nPROPERTY EventuallyLeader\n")
    with pytest.raises(NotImplementedError, match="EventuallyLeader"):
        load_config(str(cfgf))


def test_symmetry_rejected_loudly(tmp_path):
    """SYMMETRY quotients the state space — running without it would report
    non-TLC distinct-state counts with no warning (MCraft.cfg deliberately
    has none; SURVEY §1 L5), so the statement must fail the load by name."""
    cfgf = tmp_path / "sym.cfg"
    cfgf.write_text("CONSTANT Server = {r1}\nCONSTANT Value = {v1}\n"
                    "SYMMETRY Perms\n")
    with pytest.raises(NotImplementedError, match="SYMMETRY Perms"):
        load_config(str(cfgf))


def test_view_rejected_loudly(tmp_path):
    cfgf = tmp_path / "view.cfg"
    cfgf.write_text("CONSTANT Server = {r1}\nCONSTANT Value = {v1}\n"
                    "VIEW NoTermView\n")
    with pytest.raises(NotImplementedError, match="VIEW NoTermView"):
        load_config(str(cfgf))


def test_scan_exit_operators():
    """The general TLCGet/TLCSet coupling (SURVEY §5.5): any operator of the
    Smokeraft StopAfter shape is recognized, per counter; parameterized
    definitions bound operator bodies; block comments are stripped."""
    from raft_tla_tpu.utils.cfg import scan_exit_operators
    text = ('StopAfter ==\n'
            '    /\\ TLCSet("exit", TLCGet("duration") > 7)\n'
            '    /\\ TLCSet("exit", TLCGet("diameter") > 42)\n'
            'Helper(x) ==\n'
            '    TLCSet("exit", TLCGet("distinct") > 5)\n'
            'BigRun ==\n'
            '    TLCSet("exit", TLCGet("distinct") > 1000000)\n'
            'Mixed ==\n'
            '    /\\ TLCSet("exit", TLCGet("distinct") > 10)\n'
            '    /\\ x < 5\n'
            'Commented == (* TLCSet("exit", TLCGet("level") > 5) *) 3\n')
    ops = scan_exit_operators(text)
    assert ops["StopAfter"].conds == (("duration", 7.0), ("diameter", 42.0))
    assert ops["StopAfter"].pure
    # Helper(x)'s condition must NOT leak into StopAfter's body.
    assert ops["Helper"].conds == (("distinct", 5.0),)
    assert ops["BigRun"].conds == (("distinct", 1000000.0),)
    assert not ops["Mixed"].pure        # budget + predicate conjunct
    assert "Commented" not in ops       # block comment stripped


def test_unknown_exit_counter_rejected_only_when_used(tmp_path):
    """An unused operator with an unknown counter must not poison the load;
    naming it as CONSTRAINT must reject loudly."""
    cfg_path = _write_exit_model(tmp_path, "level", 10)
    with pytest.raises(NotImplementedError, match="level"):
        load_config(cfg_path)
    # Same operator, no CONSTRAINT naming it: loads fine.
    text = (tmp_path / "tiny.cfg").read_text()
    (tmp_path / "tiny.cfg").write_text(
        text.replace("CONSTRAINT StopEarly\n", ""))
    s = load_config(str(tmp_path / "tiny.cfg"))
    assert s.exit_conditions == ()


def test_mixed_budget_predicate_constraint_rejected(tmp_path):
    (tmp_path / "mix.tla").write_text(
        "---- MODULE mix ----\nEXTENDS raft\n"
        'Bounded ==\n    /\\ TLCSet("exit", TLCGet("distinct") > 10)\n'
        "    /\\ Len(log[r1]) < 5\n====\n")
    (tmp_path / "mix.cfg").write_text(
        "CONSTANTS\n    Server = {r1}\n    Value = {v1}\n"
        "SPECIFICATION Spec\nCONSTRAINT Bounded\n")
    with pytest.raises(NotImplementedError, match="Bounded"):
        load_config(str(tmp_path / "mix.cfg"))


def _write_exit_model(tmp_path, counter, threshold):
    (tmp_path / "tiny.tla").write_text(
        "---- MODULE tiny ----\nEXTENDS raft\n"
        f'StopEarly ==\n    TLCSet("exit", TLCGet("{counter}") '
        f"> {threshold})\n====\n")
    cfgf = tmp_path / "tiny.cfg"
    cfgf.write_text(
        "CONSTANTS\n    Server = {r1, r2, r3}\n    Value = {v1}\n"
        "    Follower = Follower\n    Candidate = Candidate\n"
        "    Leader = Leader\n    Nil = Nil\n"
        "    RequestVoteRequest = RequestVoteRequest\n"
        "    RequestVoteResponse = RequestVoteResponse\n"
        "    AppendEntriesRequest = AppendEntriesRequest\n"
        "    AppendEntriesResponse = AppendEntriesResponse\n"
        "SPECIFICATION Spec\nINVARIANT TypeOK\nCONSTRAINT StopEarly\n")
    return str(cfgf)


def test_distinct_budget_constraint_loads(tmp_path):
    """A cfg-defined constraint over TLCGet("distinct") needs no code
    changes: it loads as an exit condition, not a state predicate."""
    s = load_config(_write_exit_model(tmp_path, "distinct", 500))
    assert s.exit_conditions == (("distinct", 500.0),)
    assert s.constraints == []          # consumed as a budget
    assert s.max_seconds is None and s.max_diameter is None


def test_smokeraft_stopafter_still_routes_to_native_budgets(reference):
    s = load_config(f"{reference}/Smokeraft.cfg")
    assert s.max_seconds == 1.0 and s.max_diameter == 100
    assert s.exit_conditions == ()


def test_progress_seconds_backend_directive(tmp_path):
    """PROGRESS_SECONDS rides the same flag > directive > default chain as
    every other backend key."""
    cfgf = tmp_path / "p.cfg"
    cfgf.write_text("\\* TPU: PROGRESS_SECONDS = 300\n"
                    "CONSTANT Server = {r1}\nCONSTANT Value = {v1}\n")
    s = load_config(str(cfgf))
    assert s.backend["PROGRESS_SECONDS"] == 300
