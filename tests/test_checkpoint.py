"""Checkpoint/resume (SURVEY §2.4 R8): an interrupted run, resumed from its
last level-boundary snapshot, must finish with the same statistics, the same
verdict, and a working counterexample trace as an uninterrupted run."""

import dataclasses

import jax.numpy as jnp
import numpy as np
import pytest

from raft_tla_tpu.engine import checkpoint as ckpt_mod
from raft_tla_tpu.engine.bfs import BFSEngine, EngineConfig
from raft_tla_tpu.models.dims import LEADER, RaftDims
from raft_tla_tpu.models.invariants import Bounds, build_constraint
from raft_tla_tpu.models.pystate import init_state

DIMS = RaftDims(n_servers=2, n_values=1, max_log=2, n_msg_slots=8)
BOUNDS = Bounds(max_term=2, max_log_len=1, max_msg_count=1)


def make_engine(**kw):
    cfg = dict(batch=128, queue_capacity=1 << 12, seen_capacity=1 << 15,
               check_deadlock=False)
    cfg.update(kw)
    return BFSEngine(
        DIMS, invariants={"NoLeader": lambda st: jnp.all(st.role != LEADER)},
        constraint=build_constraint(DIMS, BOUNDS),
        config=EngineConfig(**cfg))


@pytest.fixture(scope="module")
def full_run():
    eng = make_engine()
    res = eng.run([init_state(DIMS)])
    assert res.stop_reason == "violation"
    return res


def test_interrupt_resume_matches_full_run(full_run, tmp_path):
    ckdir = str(tmp_path / "states")
    eng1 = make_engine(checkpoint_dir=ckdir, max_diameter=3)
    r1 = eng1.run([init_state(DIMS)])
    assert r1.stop_reason == "diameter_budget"
    path = ckpt_mod.latest(ckdir)
    assert path is not None and path.endswith("level_00003.npz")

    eng2 = make_engine()
    r2 = eng2.run(resume=path)
    assert r2.stop_reason == "violation"
    assert r2.violation.invariant == "NoLeader"
    assert r2.distinct == full_run.distinct
    assert r2.diameter == full_run.diameter
    assert r2.levels == full_run.levels
    assert r2.violation.fingerprint == full_run.violation.fingerprint

    # Counterexample reconstruction works across the resume boundary:
    # early trace records and roots come from the checkpoint.
    steps = eng2.replay(r2.violation.fingerprint)
    assert steps[0][0] == -1
    assert steps[-1][1] == r2.violation.state


def test_checkpoint_roundtrip_and_dims_guard(tmp_path):
    ckdir = str(tmp_path / "states")
    eng = make_engine(checkpoint_dir=ckdir, max_diameter=1)
    eng.run([init_state(DIMS)])
    # A truncated snapshot (crash mid-write) must not shadow the intact one.
    with open(str(tmp_path / "states" / "level_00099.npz"), "wb") as f:
        f.write(b"\x00garbage")
    path = ckpt_mod.latest(ckdir)
    assert path.endswith("level_00001.npz")
    ck = ckpt_mod.load(path)
    assert ck.dims == DIMS
    assert ck.diameter == 1
    assert ck.wall_seconds >= 0.0
    assert ck.frontier.shape[0] == ck.levels[-1]
    assert ck.seen_hi.shape == ck.seen_lo.shape
    # Keys are stored lex-sorted (resume pads them straight into the FPSet).
    keys = (ck.seen_hi.astype(np.uint64) << np.uint64(32)) \
        | ck.seen_lo.astype(np.uint64)
    assert (keys[1:] > keys[:-1]).all()   # unsigned compare: no diff overflow
    assert ck.roots  # the Init root travels with the snapshot

    other = BFSEngine(
        dataclasses.replace(DIMS, n_servers=3),
        config=EngineConfig(batch=8, queue_capacity=1 << 8,
                            seen_capacity=1 << 10))
    with pytest.raises(ValueError, match="dims"):
        other.run(resume=path)


def test_checkpoint_restores_dims_subclass(tmp_path):
    """A ReconfigDims run's snapshot must round-trip to ReconfigDims —
    v3 restore rebuilt every checkpoint as base RaftDims, so the variant
    (different row width: 2-byte value lanes) could not resume at all
    (advisor r4).  The resumed run must agree exactly with an
    uninterrupted one."""
    from raft_tla_tpu.models.reconfig import ReconfigDims
    from raft_tla_tpu.utils.cfg import load_config

    setup = load_config("configs/reconfig3.cfg")
    dims, bounds = setup.dims, setup.bounds
    assert isinstance(dims, ReconfigDims)
    common = dict(batch=128, queue_capacity=1 << 12,
                  seen_capacity=1 << 15, check_deadlock=False)

    full = BFSEngine(dims, constraint=build_constraint(dims, bounds),
                     config=EngineConfig(max_diameter=4, **common))
    rf = full.run([init_state(dims)])

    ckdir = str(tmp_path / "states")
    eng1 = BFSEngine(dims, constraint=build_constraint(dims, bounds),
                     config=EngineConfig(max_diameter=2,
                                         checkpoint_dir=ckdir, **common))
    eng1.run([init_state(dims)])
    path = ckpt_mod.latest(ckdir)
    ck = ckpt_mod.load(path)
    assert type(ck.dims) is ReconfigDims
    assert ck.dims == dims          # targets tuple survives the JSON trip

    eng2 = BFSEngine(dims, constraint=build_constraint(dims, bounds),
                     config=EngineConfig(max_diameter=4, **common))
    r2 = eng2.run(resume=path)
    assert (r2.distinct, r2.diameter, tuple(r2.levels)) \
        == (rf.distinct, rf.diameter, tuple(rf.levels))


def test_unregistered_dims_rejected_at_construction(tmp_path):
    """With checkpoint_dir set, an un-restorable dims class must be
    rejected when the ENGINE is built — not at the first level-boundary
    write, after a level of expansion is done and about to be lost."""
    class CustomDims(RaftDims):
        pass

    with pytest.raises(TypeError, match="CustomDims"):
        BFSEngine(CustomDims(n_servers=2, n_values=1, max_log=2,
                             n_msg_slots=8),
                  config=EngineConfig(batch=8, queue_capacity=1 << 8,
                                      seen_capacity=1 << 10,
                                      checkpoint_dir=str(tmp_path / "s")))


def test_unknown_checkpoint_dims_class_message(tmp_path):
    """A v4 snapshot naming a dims class this build doesn't know must be
    rejected with a diagnostic error, not a bare KeyError."""
    import json

    ckdir = str(tmp_path / "states")
    eng = make_engine(checkpoint_dir=ckdir, max_diameter=1)
    eng.run([init_state(DIMS)])
    path = ckpt_mod.latest(ckdir)
    with np.load(path) as z:
        arrs = {k: z[k] for k in z.files}
    meta = json.loads(bytes(arrs["meta"]).decode())
    meta["dims_class"] = "LeaseDims"
    arrs["meta"] = np.frombuffer(json.dumps(meta).encode(), np.uint8)
    hacked = str(tmp_path / "level_hacked.npz")
    np.savez_compressed(hacked, **arrs)
    with pytest.raises(ValueError, match="LeaseDims"):
        ckpt_mod.load(hacked)


def test_mixed_mode_resume_guards(tmp_path):
    """A trace-off resume must not shadow trace-carrying snapshots with
    empty-trace ones in the same directory, and a trace-on resume of a
    trace-less checkpoint must fail fast (replay could never reach a root)."""
    ckdir = str(tmp_path / "states")
    eng = make_engine(checkpoint_dir=ckdir, max_diameter=2)
    eng.run([init_state(DIMS)])
    path = ckpt_mod.latest(ckdir)

    with pytest.raises(ValueError, match="trace-less snapshots"):
        make_engine(record_trace=False, checkpoint_dir=ckdir).run(resume=path)
    # Without a checkpoint dir there is nothing to poison: allowed.
    r = make_engine(record_trace=False, max_diameter=3).run(resume=path)
    assert r.diameter == 3

    ckdir2 = str(tmp_path / "states_notrace")
    eng2 = make_engine(record_trace=False, checkpoint_dir=ckdir2,
                       max_diameter=2)
    eng2.run([init_state(DIMS)])
    with pytest.raises(ValueError, match="restart from scratch"):
        make_engine().run(resume=ckpt_mod.latest(ckdir2))
