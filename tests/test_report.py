"""TLC-parity statespace report (obs/report.py) + run-history ledger
(obs/history.py) tests.

The load-bearing contract: the report is pure host-side arithmetic over
counters the engines already fetch — engine counts are BIT-IDENTICAL
with the report on or off (single-chip and mesh), while the on-path
emits the ``statespace`` event, feeds the ``statespace/*`` gauges, and
surfaces ``EngineResult.report``.  The ledger records one line per run
and lets bench_diff auto-resolve a same-host baseline.
"""

import json
import os

import pytest

from raft_tla_tpu.engine.bfs import BFSEngine, EngineConfig
from raft_tla_tpu.models import oracle as orc
from raft_tla_tpu.models.dims import RaftDims
from raft_tla_tpu.models.invariants import (Bounds, build_constraint,
                                            build_type_ok, constraint_py,
                                            type_ok_py)
from raft_tla_tpu.models.pystate import init_state
from raft_tla_tpu.obs import history as history_mod
from raft_tla_tpu.obs import report as report_mod

DIMS = RaftDims(n_servers=3, n_values=2, max_log=4, n_msg_slots=32)
BOUNDS = Bounds(max_term=2, max_log_len=1, max_msg_count=1)
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def small_config(**kw):
    base = dict(batch=32, queue_capacity=1 << 12, seen_capacity=1 << 15,
                check_deadlock=False)
    base.update(kw)
    return EngineConfig(**base)


# ---------------------------------------------------------------------------
# Pure report math.

def test_collision_probability_is_tlcs_formula():
    # d * (g - d) / 2^64, zero when nothing was deduplicated.
    assert report_mod.collision_probability(10, 10) == 0.0
    p = report_mod.collision_probability(1 << 32, (1 << 33))
    # d = 2^32, dupes = 2^32 -> p = 2^64 / 2^64 = 1.
    assert p == pytest.approx(1.0)
    assert report_mod.collision_probability(0, 100) == 0.0


def test_build_report_table_and_render():
    class R:
        distinct, generated, diameter = 100, 400, 2
        levels = [1, 9, 90]
        stop_reason, violation, deadlock = "exhausted", None, None
        growth_stalls = [(2048, 0.5)]
    stats = [{"level": 1, "frontier": 9, "distinct": 10, "generated": 40,
              "seen_size": 10, "seen_capacity": 1024},
             {"level": 2, "frontier": 90, "distinct": 100,
              "generated": 400, "seen_size": 100, "seen_capacity": 1024}]
    rep = report_mod.build_report(R, level_stats=stats,
                                  seen_capacity=1024, seen_size=100)
    assert [r["frontier"] for r in rep["levels"]] == [1, 9, 90]
    assert rep["levels"][1]["seen_load"] == pytest.approx(10 / 1024,
                                                          abs=1e-4)
    assert rep["frontier_peak"] == {"level": 2, "frontier": 90}
    assert rep["collision"]["calculated"] == pytest.approx(
        100 * 300 / 2.0 ** 64)
    assert rep["seen_set"]["final_load"] == pytest.approx(100 / 1024,
                                                          abs=1e-4)
    text = report_mod.render_report(rep)
    assert "400 states generated, 100 distinct states found" in text
    assert "calculated (optimistic)" in text
    assert "widest level: 2" in text
    assert "1 growth(s)" in text
    # Summary projection (the ledger's report column).
    summ = report_mod.summarize(rep)
    assert summ["diameter"] == 2 and summ["frontier_peak"] == 90


# ---------------------------------------------------------------------------
# Engine integration: bit-identity on/off + the surfaces.

def run_once(report_on, tmp_path=None, diameter=3):
    cfg = small_config(max_diameter=diameter, statespace_report=report_on,
                       events_out=(str(tmp_path / "ev.jsonl")
                                   if tmp_path else None))
    eng = BFSEngine(DIMS, invariants={"TypeOK": build_type_ok(DIMS)},
                    constraint=build_constraint(DIMS, BOUNDS), config=cfg)
    return eng, eng.run([init_state(DIMS)])


def test_report_on_off_bit_identity_and_oracle(tmp_path):
    eng_on, on = run_once(True, tmp_path)
    _eng_off, off = run_once(False)
    # THE acceptance contract: identical engine counts either way.
    assert (on.distinct, on.generated, on.levels, on.diameter) \
        == (off.distinct, off.generated, off.levels, off.diameter)
    want = orc.bfs([init_state(DIMS)], DIMS,
                   invariants={"TypeOK": type_ok_py},
                   constraint=constraint_py(BOUNDS),
                   check_deadlock=False, max_levels=3)
    assert on.distinct == want.distinct_states
    assert on.levels == want.levels
    # Report-on surfaces...
    rep = on.report
    assert rep["distinct"] == on.distinct
    assert [r["frontier"] for r in rep["levels"]] == on.levels
    assert rep["collision"]["calculated"] == pytest.approx(
        report_mod.collision_probability(on.distinct, on.generated))
    assert rep["collision"]["observed_dual_key"] == 0
    assert rep["verdict"] == "ok"
    # Out-degree closes against the coverage accounting: mean * expanded
    # parents == generated (expansion phase).
    od = rep["out_degree"]
    gen = sum(v["generated"] for v in on.coverage.values())
    assert od["mean"] == pytest.approx(gen / od["expanded_parents"],
                                       abs=5e-5)   # 4-decimal rounding
    # ...gauges...
    snap = eng_on.metrics.snapshot()["gauges"]
    assert snap["statespace/diameter"] == on.diameter
    assert snap["statespace/collision_probability"] == pytest.approx(
        rep["collision"]["calculated"])
    # ...and report-off drops every surface.
    assert off.report == {} and off.level_stats == []


def test_statespace_event_validates(tmp_path):
    from raft_tla_tpu.obs import validate_run_events
    _eng, res = run_once(True, tmp_path)
    events = validate_run_events(str(tmp_path / "ev.jsonl"))
    ss = [e for e in events if e["event"] == "statespace"]
    assert len(ss) == 1
    assert ss[0]["report"]["distinct"] == res.distinct
    # Payload enforcement: a statespace event without its report object
    # must fail validation (KNOWN_EVENTS satellite).
    bad = tmp_path / "bad.jsonl"
    bad.write_text('{"event": "run_start", "ts": 1}\n'
                   '{"event": "statespace", "ts": 2}\n'
                   '{"event": "run_end", "ts": 3}\n')
    with pytest.raises(ValueError, match="statespace"):
        validate_run_events(str(bad))


def test_mesh_report_on_off_bit_identity():
    from raft_tla_tpu.parallel.mesh import MeshBFSEngine
    cons = build_constraint(DIMS, BOUNDS)
    runs = {}
    for flag in (True, False):
        eng = MeshBFSEngine(
            DIMS, constraint=cons,
            config=small_config(batch=16, max_diameter=2,
                                statespace_report=flag))
        res = eng.run([init_state(DIMS)])
        runs[flag] = res
    on, off = runs[True], runs[False]
    assert (on.distinct, on.generated, on.levels) \
        == (off.distinct, off.generated, off.levels)
    want = orc.bfs([init_state(DIMS)], DIMS,
                   constraint=constraint_py(BOUNDS),
                   check_deadlock=False, max_levels=2)
    assert on.distinct == want.distinct_states
    assert on.report["distinct"] == on.distinct
    assert [r["frontier"] for r in on.report["levels"]] == on.levels
    assert off.report == {}


@pytest.mark.slow
def test_report_on_off_pinned_L9_ground_truth():
    """The full acceptance differential: report on vs off on the pinned
    MCraft_bounded L0-L9 ground truths (505004 distinct / 1421121
    generated — tests/test_por.py's pinned values).  CPU-heavy, so
    tier-1 runs the L0-L3 + mesh variants above; this is the
    hardware/nightly form."""
    from raft_tla_tpu.engine.check import initial_states, make_engine
    from raft_tla_tpu.utils.cfg import load_config
    setup = load_config(os.path.join(REPO, "configs/MCraft_bounded.cfg"))
    out = {}
    for flag in (True, False):
        eng = make_engine(setup, EngineConfig(
            batch=512, queue_capacity=1 << 15, seen_capacity=1 << 21,
            record_trace=False, check_deadlock=False, max_diameter=9,
            statespace_report=flag))
        res = eng.run(initial_states(setup))
        out[flag] = (res.distinct, res.generated, res.levels)
    assert out[True] == out[False]
    assert out[True][0] == 505004 and out[True][1] == 1421121


# ---------------------------------------------------------------------------
# Run-history ledger (obs/history.py).

FP_A = {"cpu_model": "cpuA", "device_kind": "cpu", "device_count": 1,
        "platform": "cpu", "jax": "0.4", "jaxlib": "0.4",
        "hostname": "a"}
FP_B = dict(FP_A, cpu_model="cpuB")


def _bench_doc(value=1000.0, fp=FP_A):
    return {"metric": "distinct_states_per_sec", "value": value,
            "unit": "states/s", "generated_per_sec": 4 * value,
            "distinct_states": 50000, "generated_states": 200000,
            "diameter": 8, "wall_s": 50.0, "stop_reason":
            "duration_budget", "pipeline": "v2", "fused_stages": {},
            "host_fingerprint": fp,
            "phases": {"chunk": 30.0}, "coverage": {},
            "report": {"collision": {"calculated": 1e-12,
                                     "observed_dual_key": 0},
                       "diameter": 8, "verdict": "ok", "levels": [],
                       "frontier_peak": None, "out_degree": {},
                       "seen_set": {}}}


def test_history_entry_append_read_and_host_keys(tmp_path):
    led = str(tmp_path / "ledger.jsonl")
    history_mod.append_entry(led, history_mod.entry_from_bench(
        _bench_doc(), label="b1"))
    history_mod.append_entry(led, history_mod.entry_from_bench(
        _bench_doc(value=900.0, fp=FP_B), label="b2"))
    entries = history_mod.read_history(led)
    assert [e["label"] for e in entries] == ["b1", "b2"]
    assert entries[0]["distinct_per_sec"] == 1000.0
    assert entries[0]["bench"]["value"] == 1000.0
    assert entries[0]["report"]["diameter"] == 8
    # Host keys: stable per fingerprint, different across hosts,
    # hostname alone does NOT change identity.
    k1 = history_mod.host_key(FP_A)
    assert k1 == history_mod.host_key(dict(FP_A, hostname="elsewhere"))
    assert k1 != history_mod.host_key(FP_B)
    assert history_mod.host_key(None) is None
    assert history_mod.host_key({"hostname": "x"}) is None
    # The trajectory table flags the host change loudly.
    table = history_mod.render_table(entries)
    assert "HOST-CHANGE" in table
    assert "WARNING" in table and "not comparable" in table


def test_history_resolves_same_host_baseline(tmp_path):
    led = str(tmp_path / "ledger.jsonl")
    for i, (v, fp) in enumerate([(800.0, FP_A), (900.0, FP_B),
                                 (1000.0, FP_A)]):
        history_mod.append_entry(led, history_mod.entry_from_bench(
            _bench_doc(value=v, fp=fp), label=f"b{i}"))
    base = history_mod.resolve_baseline(led, FP_A)
    assert base["label"] == "b2"            # newest same-host, not B's
    assert base["bench"]["value"] == 1000.0
    assert history_mod.resolve_baseline(
        led, dict(FP_A, cpu_model="cpuC")) is None
    # Record-then-gate workflow: the candidate's OWN ledger line must
    # never resolve as its baseline (a self-compare gate is vacuous) —
    # excluding it falls back to the previous same-host entry.
    own = history_mod.resolve_baseline(
        led, FP_A, exclude_bench=_bench_doc(value=1000.0, fp=FP_A))
    assert own["label"] == "b0" and own["bench"]["value"] == 800.0
    # run_id identity survives the captured file being annotated: a
    # candidate with extra keys but the recorded run_id is STILL the
    # same run (doc equality alone would miss it).
    led_id = str(tmp_path / "led_id.jsonl")
    doc = dict(_bench_doc(value=700.0), run_id="abc123")
    history_mod.append_entry(led_id, history_mod.entry_from_bench(
        doc, label="only"))
    annotated = dict(doc, note="captured by hand")
    assert history_mod.resolve_baseline(
        led_id, FP_A, exclude_bench=annotated) is None


def test_history_entry_from_engine_result(tmp_path):
    _eng, res = run_once(True)
    entry = history_mod.entry_from_result(
        "check", res, cfg_text="INVARIANT TypeOK", dims=DIMS,
        host_fingerprint=FP_A, label="unit")
    assert entry["verdict"] == "ok"
    assert entry["distinct"] == res.distinct
    assert entry["report"]["diameter"] == res.diameter
    assert entry["cfg_fingerprint"] and entry["model_fingerprint"]
    led = str(tmp_path / "led.jsonl")
    history_mod.append_entry(led, entry)
    assert history_mod.read_history(led)[0]["label"] == "unit"


def test_history_rejects_corrupt_ledger(tmp_path):
    led = tmp_path / "led.jsonl"
    led.write_text('{"kind": "bench"}\nnot json\n')
    with pytest.raises(ValueError, match="malformed"):
        history_mod.read_history(str(led))
    with pytest.raises(FileNotFoundError):
        history_mod.read_history(str(tmp_path / "missing.jsonl"))


# ---------------------------------------------------------------------------
# scripts/bench_history.py + scripts/bench_diff.py --history.

def _load_script(name):
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "scripts", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_bench_history_imports_legacy_rounds(tmp_path, capsys):
    bh = _load_script("bench_history")
    led = str(tmp_path / "ledger.jsonl")
    assert bh.main([led, "--import-legacy"]) == 0
    entries = history_mod.read_history(led)
    labels = [e["label"] for e in entries]
    # The committed r01-r05 trajectory seeds the ledger, crash rounds
    # included.
    assert "BENCH_r05" in labels and "BENCH_r01" in labels
    assert "MULTICHIP_r05" in labels
    r01 = next(e for e in entries if e["label"] == "BENCH_r01")
    assert "no-json" in r01["verdict"]
    r05 = next(e for e in entries if e["label"] == "BENCH_r05")
    assert r05["distinct_per_sec"] == pytest.approx(38351.8)
    # Legacy rounds predate host fingerprints: flagged unknown-host —
    # the r05 cross-host anomaly rendered not-comparable.
    assert r05["host_key"] is None
    out = capsys.readouterr().out
    assert "host?" in out
    # Idempotent by label: re-import adds nothing.
    n = len(entries)
    assert bh.main([led, "--import-legacy"]) == 0
    assert len(history_mod.read_history(led)) == n


def test_bench_diff_resolves_baseline_from_history(tmp_path, capsys):
    bd = _load_script("bench_diff")
    led = str(tmp_path / "ledger.jsonl")
    history_mod.append_entry(led, history_mod.entry_from_bench(
        _bench_doc(value=1000.0), label="base"))
    new = tmp_path / "new.json"
    new.write_text(json.dumps(_bench_doc(value=980.0)))
    assert bd.main(["--history", led, str(new)]) == 0
    out = capsys.readouterr().out
    assert "auto-resolved from history ledger" in out
    assert "history:base" in out
    # A genuine regression still gates through the resolved baseline.
    new.write_text(json.dumps(_bench_doc(value=400.0)))
    assert bd.main(["--history", led, str(new)]) == 1
    capsys.readouterr()
    # The candidate's own ledger line never self-resolves: with ONLY
    # its own entry in the ledger the gate refuses (exit 2) instead of
    # vacuously passing a self-compare.
    led2 = str(tmp_path / "ledger2.jsonl")
    history_mod.append_entry(led2, history_mod.entry_from_bench(
        _bench_doc(value=980.0), label="self"))
    new.write_text(json.dumps(_bench_doc(value=980.0)))
    assert bd.main(["--history", led2, str(new)]) == 2
    # No same-host entry (candidate from a different host) -> exit 2.
    new.write_text(json.dumps(_bench_doc(value=990.0, fp=FP_B)))
    assert bd.main(["--history", led, str(new)]) == 2
    err = capsys.readouterr().err
    assert "no bench entry with host key" in err
    # Legacy candidate without a fingerprint -> exit 2 too.
    doc = _bench_doc(value=990.0)
    doc.pop("host_fingerprint")
    new.write_text(json.dumps(doc))
    assert bd.main(["--history", led, str(new)]) == 2
