"""Checker-service protocol tests (SURVEY §2.4 R10 delegation endpoint).

A live server on an ephemeral port, a socket client speaking the same
newline-delimited JSON the TLC override (native/tlc_override/
TPUraftOverride.java) sends.  Counts are asserted against the pinned
MCraft_bounded oracle profile, so the service is checked end-to-end
through the real engine, not a stub.
"""

import json
import os
import socket
import threading

import pytest

from raft_tla_tpu import server as srv_mod

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def server():
    srv = srv_mod.serve(port=0)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield srv.server_address
    srv.shutdown()


def roundtrip(addr, req: dict) -> dict:
    with socket.create_connection(addr, timeout=600) as s:
        s.sendall((json.dumps(req) + "\n").encode())
        buf = b""
        while not buf.endswith(b"\n"):
            chunk = s.recv(65536)
            if not chunk:
                break
            buf += chunk
    return json.loads(buf)


def test_ping(server):
    resp = roundtrip(server, {"op": "ping"})
    assert resp["ok"] is True
    assert resp["platform"] == "cpu"


def test_check_matches_pinned_profile(server):
    resp = roundtrip(server, {
        "op": "check",
        "cfg": os.path.join(REPO, "configs/MCraft_bounded.cfg"),
        "batch": 128, "max_diameter": 3,
        "queue_capacity": 1 << 12, "seen_capacity": 1 << 15,
        "check_deadlock": False})
    assert resp["ok"] is True, resp
    # Pinned oracle prefix (BASELINE.md §b): cumulative 113 distinct /
    # 222 generated through level 3.
    assert resp["distinct"] == 113
    assert resp["generated"] == 222
    assert resp["diameter"] == 3
    assert resp["levels"] == [1, 3, 18, 79]
    assert resp["violation"] is None


def test_check_engine_stays_warm_and_budgets_refresh(server):
    # Second request with a DIFFERENT diameter budget must reuse the
    # compiled engine but honor the new budget — budgets are host-side
    # and per-request, not baked into the cache entry.
    base = {"op": "check",
            "cfg": os.path.join(REPO, "configs/MCraft_bounded.cfg"),
            "batch": 128,
            "queue_capacity": 1 << 12, "seen_capacity": 1 << 15,
            "check_deadlock": False}
    r1 = roundtrip(server, dict(base, max_diameter=3))
    assert r1["ok"] and r1["distinct"] == 113
    r2 = roundtrip(server, dict(base, max_diameter=4))
    assert r2["ok"] and r2["distinct"] == 527     # pinned L4 cumulative
    assert r2["levels"] == [1, 3, 18, 79, 318]


def test_cfg_text_and_content_identity(server):
    # cfg_text requests work, and the engine cache keys on CONTENT: two
    # different texts (different MaxTerm) must give different models.
    with open(os.path.join(REPO, "configs/MCraft_bounded.cfg")) as f:
        text = f.read()
    r1 = roundtrip(server, {
        "op": "check", "cfg_text": text, "batch": 128, "max_diameter": 4,
        "queue_capacity": 1 << 12, "seen_capacity": 1 << 15,
        "check_deadlock": False})
    assert r1["ok"] and r1["distinct"] == 527     # pinned L4 cumulative
    text2 = text.replace("MaxTerm = 3", "MaxTerm = 2")
    assert text2 != text
    r2 = roundtrip(server, {
        "op": "check", "cfg_text": text2, "batch": 128, "max_diameter": 4,
        "queue_capacity": 1 << 12, "seen_capacity": 1 << 15,
        "check_deadlock": False})
    assert r2["ok"]
    assert r2["distinct"] < r1["distinct"]        # tighter term bound


def test_backend_directive_precedence(server):
    # Precedence: request field > cfg "\* TPU:" directive > default.  A
    # cfg_text carrying a BATCH directive must drive the engine batch
    # when the request leaves it unset.
    with open(os.path.join(REPO, "configs/MCraft_bounded.cfg")) as f:
        text = f.read() + "\n\\* TPU: BATCH = 64\n"
    r = roundtrip(server, {
        "op": "check", "cfg_text": text, "max_diameter": 2,
        "queue_capacity": 1 << 12, "seen_capacity": 1 << 15,
        "check_deadlock": False})
    assert r["ok"] and r["batch"] == 64 and r["distinct"] == 22
    r2 = roundtrip(server, {
        "op": "check", "cfg_text": text, "batch": 32, "max_diameter": 2,
        "queue_capacity": 1 << 12, "seen_capacity": 1 << 15,
        "check_deadlock": False})
    assert r2["ok"] and r2["batch"] == 32 and r2["distinct"] == 22


def test_simulate(server):
    resp = roundtrip(server, {
        "op": "simulate",
        "cfg": os.path.join(REPO, "configs/MCraft_bounded.cfg"),
        "batch": 64, "depth": 16, "num_steps": 256})
    assert resp["ok"] is True, resp
    assert resp["steps"] >= 256
    assert resp["traces"] >= 64
    assert resp["violation"] is None


def test_check_mesh_engine(server):
    # engine="mesh" routes through MeshBFSEngine on the virtual 8-device
    # CPU mesh (conftest) and must produce the same pinned counts.
    resp = roundtrip(server, {
        "op": "check",
        "cfg": os.path.join(REPO, "configs/MCraft_bounded.cfg"),
        "engine": "mesh", "batch": 16, "max_diameter": 3,
        "queue_capacity": 1 << 12, "seen_capacity": 1 << 15,
        "check_deadlock": False})
    assert resp["ok"] is True, resp
    assert resp["distinct"] == 113
    assert resp["levels"] == [1, 3, 18, 79]


def test_bad_request(server):
    resp = roundtrip(server, {"op": "nope"})
    assert resp["ok"] is False
    resp = roundtrip(server, {"op": "check"})
    assert resp["ok"] is False and "cfg" in resp["error"]


def test_metrics_op_parses_and_agrees_with_stats(server):
    """ISSUE 9 acceptance: the metrics op's output is valid Prometheus
    text exposition and agrees with the stats op's counters taken in
    the same instant (both render one snapshot of the same registry;
    the check counter cannot move between the two reads — neither op
    increments it)."""
    from raft_tla_tpu.obs import parse_prometheus
    from raft_tla_tpu.obs.expose import counter_sample
    r = roundtrip(server, {
        "op": "check",
        "cfg": os.path.join(REPO, "configs/MCraft_bounded.cfg"),
        "batch": 128, "max_diameter": 2,
        "queue_capacity": 1 << 12, "seen_capacity": 1 << 15,
        "check_deadlock": False})
    assert r["ok"]
    stats = roundtrip(server, {"op": "stats"})
    m = roundtrip(server, {"op": "metrics"})
    assert m["ok"] and m["content_type"].startswith("text/plain")
    samples = parse_prometheus(m["exposition"])     # raises if invalid
    counters = stats["metrics"]["counters"]
    assert counter_sample(samples, "server/requests/check") \
        == counters["server/requests/check"]
    assert counter_sample(samples, "engine/distinct") \
        == counters["engine/distinct"]
    # Histogram family for the request latencies made it over too.
    assert "raft_phase_request_check_bucket" in samples


def test_watch_op_streams_live_run_snapshots(server):
    """Run attach: a watch stream opened WHILE a check runs sees >= 1
    progress snapshot recorded by that run (seq ordering proves it is
    this run's telemetry, not a stale ring entry), then a done line
    carrying the run_end."""
    from raft_tla_tpu.obs.flight import RECORDER
    seq0 = RECORDER.seq()
    base = {"op": "check",
            "cfg": os.path.join(REPO, "configs/MCraft_bounded.cfg"),
            "batch": 128, "max_diameter": 6,
            "queue_capacity": 1 << 12, "seen_capacity": 1 << 15,
            "check_deadlock": False}
    out = {}
    th = threading.Thread(
        target=lambda: out.update(resp=roundtrip(server, base)))
    th.start()
    got = []
    with socket.create_connection(server, timeout=600) as s:
        s.sendall((json.dumps({"op": "watch", "interval": 0.2})
                   + "\n").encode())
        s.settimeout(600)
        for line in s.makefile("rb"):
            rec = json.loads(line)
            got.append(rec)
            if rec.get("done"):
                break
    th.join()
    assert out["resp"]["ok"], out["resp"]
    assert got and got[-1].get("done")
    snaps = [g["watch"] for g in got if "watch" in g]
    assert snaps, got
    fresh_progress = [s for s in snaps
                      if s.get("progress")
                      and s["progress"]["seq"] > seq0]
    assert fresh_progress, "watch never saw this run's progress"
    last = fresh_progress[-1]["progress"]
    assert last["distinct"] > 0 and "diameter" in last
    # The done line reports how the watched run ended.
    end = got[-1].get("run_end")
    assert end and end["seq"] > seq0
    assert end["stop_reason"] == "diameter_budget"
    # The attach left its mark in the run's durable event record too:
    # watch_attach rides the flight ring (and the evlog when one is
    # configured — the server runs file-less, so ring-only here).
    att = RECORDER.last_record("watch_attach")
    assert att is not None and att["client"]["transport"] == "server"


def test_stats_request_reports_requests_and_cache_counters(server):
    """The live-stats endpoint (obs/): request counts, per-op latency
    histograms, and LRU cache hit/miss counters.  Self-contained: two
    identical checks guarantee >= 1 engine-cache hit regardless of what
    ran before."""
    base = {"op": "check",
            "cfg": os.path.join(REPO, "configs/MCraft_bounded.cfg"),
            "batch": 128, "max_diameter": 2,
            "queue_capacity": 1 << 12, "seen_capacity": 1 << 15,
            "check_deadlock": False}
    r = roundtrip(server, base)
    assert r["ok"]
    # Per-run phase breakdown rides the check response too.
    assert r["phases"] and "chunk" in r["phases"]
    r = roundtrip(server, base)          # warm: engine-cache hit
    assert r["ok"]
    stats = roundtrip(server, {"op": "stats"})
    assert stats["ok"] is True
    counters = stats["metrics"]["counters"]
    assert counters["server/requests/check"] >= 2
    assert counters["server/engine_cache/hits"] >= 1
    assert counters["server/engine_cache/misses"] >= 1
    assert stats["engine_cache"]["size"] >= 1
    assert stats["engine_cache"]["capacity"] == srv_mod._CACHE_CAP
    # Latency histograms per op.
    assert stats["metrics"]["histograms"]["phase/request/check"][
        "count"] >= 2
    # The stats op never takes the engine lock, and counts itself.
    assert counters["server/requests/stats"] >= 1
