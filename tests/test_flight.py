"""Flight recorder, Prometheus exposition, and run-attach tests
(ISSUE 9: always-on black-box telemetry + live introspection).

The ring/exposition halves are tested standalone (zero-dep, jax-free);
the integration tests then pin the acceptance contract: a crashing run
leaves a postmortem dump holding its last progress snapshots and
chunk-stage samples (the hard-kill variant is exercised end-to-end by
``scripts/chaos_check.py`` in CI — here the in-process error path,
which shares the dump machinery), engine results are bit-identical
with ``--xla-profile`` / ``--metrics-port`` on vs off, and the
``watch`` HTTP transport serves live snapshots.
"""

import json
import os
import threading
import urllib.error
import urllib.request

import pytest

from raft_tla_tpu.engine.bfs import BFSEngine, EngineConfig
from raft_tla_tpu.models.dims import RaftDims
from raft_tla_tpu.models.invariants import Bounds, build_constraint
from raft_tla_tpu.models.pystate import init_state
from raft_tla_tpu.obs import (MetricsRegistry, parse_prometheus,
                              render_prometheus, validate_run_events)
from raft_tla_tpu.obs.expose import counter_sample, start_metrics_server
from raft_tla_tpu.obs.flight import RECORDER, FlightRecorder

DIMS = RaftDims(n_servers=3, n_values=2, max_log=4, n_msg_slots=32)
BOUNDS = Bounds(max_term=2, max_log_len=1, max_msg_count=1)


def small_config(**kw):
    base = dict(batch=32, queue_capacity=1 << 12, seen_capacity=1 << 15,
                check_deadlock=False, record_trace=False)
    base.update(kw)
    return EngineConfig(**base)


# ---------------------------------------------------------------------------
# FlightRecorder ring semantics

def test_ring_eviction_keeps_newest_per_kind():
    fr = FlightRecorder(capacity=8)
    for i in range(20):
        fr.record("progress", i=i)
    fr.record("event", event="run_start")
    snap = fr.snapshot()
    assert len(snap["progress"]) == 8
    assert [r["i"] for r in snap["progress"]] == list(range(12, 20))
    # A high-rate kind never evicts a rare one: per-kind rings.
    assert len(snap["event"]) == 1
    # seq is process-monotone across kinds.
    seqs = [r["seq"] for recs in snap.values() for r in recs]
    assert len(set(seqs)) == len(seqs)
    assert fr.last_record("progress")["i"] == 19
    assert fr.last_event("run_start")["event"] == "run_start"
    assert fr.last_event("run_end") is None


def test_ring_thread_safety():
    fr = FlightRecorder(capacity=4096)
    barrier = threading.Barrier(8)

    def work(k):
        barrier.wait()
        for i in range(200):
            fr.record(f"kind{k % 2}", worker=k, i=i)

    threads = [threading.Thread(target=work, args=(k,)) for k in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    snap = fr.snapshot()
    total = sum(len(v) for v in snap.values())
    assert total == 8 * 200
    assert fr.seq() == 8 * 200


def test_progress_rate_limit_first_always_lands():
    fr = FlightRecorder()
    fr.arm(None)                      # resets the limiter, armed bookkeeping
    assert fr.progress(distinct=1) is not None
    # Immediately after: suppressed by the rate limiter.
    assert fr.progress(distinct=2) is None
    assert fr.last_record("progress")["distinct"] == 1
    fr.disarm()
    assert not fr.armed


def test_dump_and_disarm(tmp_path):
    fr = FlightRecorder()
    path = str(tmp_path / "postmortem.json")
    mt = MetricsRegistry()
    mt.counter("engine/distinct", 7)
    fr.arm(path, metrics=mt, context={"engine": "T", "batch": 4})
    fr.record("progress", distinct=7)
    out = fr.dump("test_reason")
    assert out == path
    doc = json.loads(open(path).read())
    assert doc["postmortem"] is True and doc["reason"] == "test_reason"
    assert doc["context"]["engine"] == "T"
    assert doc["records"]["progress"][-1]["distinct"] == 7
    assert doc["records"]["run_context"][-1]["batch"] == 4
    assert doc["metrics"]["counters"]["engine/distinct"] == 7
    assert "cpu_model" in doc["host"]
    fr.disarm()
    # Disarmed: no implicit path, dump is a no-op.
    assert fr.dump("again") is None


# ---------------------------------------------------------------------------
# Prometheus exposition

def test_prometheus_render_parse_roundtrip():
    mt = MetricsRegistry()
    mt.counter("server/requests/check", 5)
    mt.gauge("engine/seen_size", 1234)
    for v in (0.001, 0.003, 0.004, 7.5):
        mt.observe("phase/chunk", v)
    text = render_prometheus(mt.snapshot(), labels={"host": "2"})
    samples = parse_prometheus(text)
    assert counter_sample(samples, "server/requests/check") == 5
    g = samples["raft_engine_seen_size"]
    assert g[0] == ({"host": "2"}, 1234.0)
    # Histogram: cumulative monotone buckets closing at +Inf == _count.
    buckets = samples["raft_phase_chunk_bucket"]
    inf = [v for l, v in buckets if l["le"] == "+Inf"]
    assert inf == [4.0]
    assert samples["raft_phase_chunk_count"][0][1] == 4.0
    assert abs(samples["raft_phase_chunk_sum"][0][1] - 7.508) < 1e-9
    counts = [v for _l, v in buckets]
    assert counts == sorted(counts)


def test_prometheus_parser_rejects_malformed():
    with pytest.raises(ValueError):
        parse_prometheus("not a metric line at all!\n")
    with pytest.raises(ValueError):                 # bad value
        parse_prometheus("raft_x{a=\"b\"} notanumber\n")
    with pytest.raises(ValueError):                 # duplicate TYPE
        parse_prometheus("# TYPE raft_x counter\n# TYPE raft_x counter\n"
                         "raft_x 1\n")
    # Histogram without +Inf bucket.
    with pytest.raises(ValueError):
        parse_prometheus("# TYPE raft_h histogram\n"
                         "raft_h_bucket{le=\"1\"} 1\n"
                         "raft_h_sum 1\nraft_h_count 1\n")
    # Non-monotone buckets.
    with pytest.raises(ValueError):
        parse_prometheus("# TYPE raft_h histogram\n"
                         "raft_h_bucket{le=\"1\"} 5\n"
                         "raft_h_bucket{le=\"2\"} 3\n"
                         "raft_h_bucket{le=\"+Inf\"} 5\n"
                         "raft_h_sum 1\nraft_h_count 5\n")


def test_metrics_http_listener_serves_metrics_and_flight():
    mt = MetricsRegistry()
    mt.counter("engine/distinct", 42)
    srv, _t = start_metrics_server(0, mt, flight=RECORDER)
    try:
        port = srv.server_address[1]
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=30) as r:
            text = r.read().decode()
            assert "version=0.0.4" in r.headers["Content-Type"]
        samples = parse_prometheus(text)
        assert counter_sample(samples, "engine/distinct") == 42
        seq_before = RECORDER.seq()
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/flight", timeout=30) as r:
            doc = json.loads(r.read().decode())
        assert doc["ok"] and "records" in doc
        # The poll itself leaves a watch_attach record in the ring.
        att = RECORDER.last_record("watch_attach")
        assert att is not None and att["seq"] > seq_before
        assert att["client"]["transport"] == "http"
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(f"http://127.0.0.1:{port}/nope",
                                   timeout=30)
    finally:
        srv.shutdown()


# ---------------------------------------------------------------------------
# Event-log schema: the new event types enforce their payload objects

def test_validate_events_new_payloads(tmp_path):
    def write(recs):
        p = tmp_path / "e.jsonl"
        p.write_text("".join(json.dumps(r) + "\n" for r in recs))
        return str(p)

    base = [{"event": "run_start", "ts": 1.0},
            {"event": "run_end", "ts": 2.0}]
    good = base + [
        {"event": "postmortem", "ts": 1.5, "dump": {"path": "x"}},
        {"event": "watch_attach", "ts": 1.6,
         "client": {"transport": "server"}},
        {"event": "xla_profile", "ts": 1.7,
         "capture": {"logdir": "d", "status": "ok"}}]
    assert len(validate_run_events(write(good))) == 5
    for bad in ({"event": "postmortem", "ts": 1.5},
                {"event": "watch_attach", "ts": 1.5, "client": "peer"},
                {"event": "xla_profile", "ts": 1.5, "capture": None}):
        with pytest.raises(ValueError):
            validate_run_events(write(base + [bad]))


def test_file_less_evlog_mirrors_into_flight():
    from raft_tla_tpu.obs import RunEventLog
    seq0 = RECORDER.seq()
    log = RunEventLog(None)
    assert not log.enabled
    log.emit("coverage", actions={"A": {}})
    rec = RECORDER.last_event("coverage")
    assert rec is not None and rec["seq"] > seq0
    assert rec["actions"] == {"A": {}}


# ---------------------------------------------------------------------------
# Engine integration

def test_error_exit_writes_postmortem_with_progress_and_stages(tmp_path):
    """The in-process half of the crash contract (the hard-kill half is
    scripts/chaos_check.py in CI, via the same dump machinery in
    faults._die): a run dying on an exception leaves postmortem.json
    with the last progress snapshots and chunk-stage samples, and its
    run_end event carries postmortem_path."""
    from raft_tla_tpu.resilience import faults
    ck = tmp_path / "states"
    ev = tmp_path / "e.jsonl"
    eng = BFSEngine(DIMS, constraint=build_constraint(DIMS, BOUNDS),
                    config=small_config(
                        checkpoint_dir=str(ck), events_out=str(ev),
                        checkpoint_interval_seconds=0.0,
                        profile_chunks_every=1,
                        degrade_on_oom=False, max_diameter=6))
    faults.install("oom@level=2", hard=False)
    try:
        with pytest.raises(Exception, match="RESOURCE_EXHAUSTED"):
            eng.run([init_state(DIMS)])
    finally:
        faults.clear()
    pm_path = os.path.join(str(ck), "postmortem.json")
    assert os.path.exists(pm_path)
    doc = json.loads(open(pm_path).read())
    assert doc["reason"].startswith("run error:")
    assert doc["records"]["progress"], "no progress snapshots in dump"
    assert doc["records"]["chunk_stage"], "no chunk-stage samples in dump"
    assert doc["context"]["engine"] == "BFSEngine"
    # run_end points at the dump; a postmortem event precedes it.
    events = validate_run_events(str(ev))
    end = [e for e in events if e["event"] == "run_end"][-1]
    assert end["stop_reason"] == "error"
    assert end["postmortem_path"] == pm_path
    assert any(e["event"] == "postmortem"
               and e["dump"]["path"] == pm_path for e in events)
    assert not RECORDER.armed          # error path still disarms


def test_clean_run_leaves_no_postmortem(tmp_path):
    ck = tmp_path / "states"
    eng = BFSEngine(DIMS, constraint=build_constraint(DIMS, BOUNDS),
                    config=small_config(checkpoint_dir=str(ck),
                                        max_diameter=2))
    res = eng.run([init_state(DIMS)])
    assert res.stop_reason == "diameter_budget"
    assert not os.path.exists(os.path.join(str(ck), "postmortem.json"))
    assert not RECORDER.armed


def test_xla_profile_and_metrics_port_are_observational(tmp_path):
    """Acceptance: bit-identical verdict/counts/levels with the device
    profiler window and the exposition listener on vs off."""
    plain = BFSEngine(DIMS, constraint=build_constraint(DIMS, BOUNDS),
                      config=small_config(max_diameter=3))
    base = plain.run([init_state(DIMS)])

    ev = tmp_path / "e.jsonl"
    instr = BFSEngine(
        DIMS, constraint=build_constraint(DIMS, BOUNDS),
        config=small_config(
            max_diameter=3, events_out=str(ev),
            xla_profile_chunks=2,
            xla_profile_dir=str(tmp_path / "xp")))
    srv, _t = start_metrics_server(0, instr.metrics, flight=RECORDER)
    try:
        port = srv.server_address[1]
        res = instr.run([init_state(DIMS)])
        # The exposition is live and valid right after the run.
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=30) as r:
            samples = parse_prometheus(r.read().decode())
        assert counter_sample(samples, "engine/distinct") is not None
    finally:
        srv.shutdown()
    assert (res.distinct, res.generated, res.levels, res.stop_reason) \
        == (base.distinct, base.generated, base.levels, base.stop_reason)
    # The capture landed its event; ok or a recorded failure, never
    # silence.
    events = validate_run_events(str(ev))
    caps = [e for e in events if e["event"] == "xla_profile"]
    assert len(caps) == 1
    cap = caps[0]["capture"]
    assert cap["chunks"] == 2 and cap["span_name"] == "chunk"
    if cap["status"] == "ok":        # CPU backend supports the profiler
        assert cap["steps"] >= 1
        assert os.path.isdir(str(tmp_path / "xp"))


def test_mesh_engine_has_flight_hooks():
    """MeshBFSEngine duck-types BFSEngine (no inheritance): every hook
    the shared _telemetry_run calls must exist on it explicitly — a
    missing one only explodes at run start on a multi-device box, which
    tier-1's budget may never reach (caught live: _xla_profile_dir)."""
    from raft_tla_tpu.parallel.mesh import MeshBFSEngine
    for hook in ("_postmortem_path", "_xla_profile_dir", "_events_path",
                 "_emit_level_event"):
        assert callable(getattr(MeshBFSEngine, hook, None)), hook


def test_watch_http_console_renders(tmp_path, capsys):
    """The watch CLI's HTTP transport against a live listener: at least
    one rendered line, clean exit on --count."""
    from raft_tla_tpu.cli import _watch_http
    mt = MetricsRegistry()
    RECORDER.record("progress", distinct=11, generated=22, diameter=1,
                    frontier=3, next_count=4, elapsed=1.0)
    srv, _t = start_metrics_server(0, mt, flight=RECORDER)
    try:
        port = srv.server_address[1]
        rc = _watch_http(f"http://127.0.0.1:{port}", interval=0.05,
                         count=2, timeout=30, as_json=False)
    finally:
        srv.shutdown()
    assert rc == 0
    out = capsys.readouterr().out
    assert "watch[" in out and "distinct 11" in out
