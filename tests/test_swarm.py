"""Swarm tier (engine/swarm.py + ops/walk_kernels.py) contract tests.

The pins that make swarm a *product* tier rather than a lucky fuzzer:

- **determinism / partition invariance** — a (seed, walks, depth) run
  has a bit-identical visited-fingerprint multiset and identical
  verdict across reruns AND across device batch-size and chunk-size
  changes (the counter-PRNG contract walk_kernels.py promises);
- **replayability** — a latched violation reconstructs into a full
  trace whose every step is a legal Python-oracle successor, decoded
  field-for-field through the one canonical formatter (the same
  contract test_explain.py pins for the exhaustive engines);
- **telemetry dialect** — swarm runs emit validate_run_events-clean
  logs with ``swarm_progress`` carrying its registered ``swarm``
  payload object, and run_end carries the same block;
- **serving admission** — an unknown ``mode`` is a clean protocol
  reject (``server/rejected/bad_mode``) at both the blocking check arm
  and job admission, never an executor-thread exception.
"""

import json
import os
import socket
import threading

import numpy as np
import pytest

import jax.numpy as jnp

from raft_tla_tpu.engine import explain
from raft_tla_tpu.engine.swarm import SwarmEngine
from raft_tla_tpu.models import oracle as orc
from raft_tla_tpu.models.dims import LEADER, RaftDims
from raft_tla_tpu.models.invariants import (Bounds, build_constraint,
                                            build_type_ok)
from raft_tla_tpu.models.pystate import (diff_states, init_state,
                                         state_fields)
from raft_tla_tpu.obs import validate_run_events
from raft_tla_tpu.ops.walk_kernels import (family_subset, masked_choice,
                                           preferred_choice, walk_bits)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

DIMS = RaftDims(n_servers=3, n_values=2, max_log=4, n_msg_slots=32)
BOUNDS = Bounds(max_term=2, max_log_len=1, max_msg_count=1)


def invariants():
    return {"TypeOK": build_type_ok(DIMS),
            "NoLeader": lambda st: jnp.all(st.role != LEADER)}


def seeded_root():
    """Candidate one vote short of quorum (test_explain's shape): the
    minimal NoLeader counterexample is two steps away."""
    return init_state(DIMS).replace(
        role=(1, 0, 0), current_term=(2, 2, 2), voted_for=(1, 1, 1),
        votes_responded=(0b001, 0, 0), votes_granted=(0b001, 0, 0),
        messages=frozenset({((1, 1, 0, 2, 1, ()), 1)}))


def safe_root():
    """Plain init state: no violation reachable quickly at these
    bounds within a short step budget — the determinism runs below
    must exercise restarts/rings, not stop at a latch."""
    return init_state(DIMS)


def run_swarm(*, batch=None, chunk=8, seed=5, walks=48, num_steps=24,
              **kw):
    eng = SwarmEngine(DIMS, invariants=invariants(),
                      constraint=build_constraint(DIMS, BOUNDS),
                      walks=walks, max_depth=12, batch=batch, chunk=chunk,
                      ring=8, collect_fingerprints=True, **kw)
    res = eng.run([safe_root()], seed=seed, num_steps=num_steps)
    fps = res.visited_fingerprints
    order = np.lexsort((fps[:, 1], fps[:, 0]))
    return eng, res, fps[order]


# ---------------------------------------------------------------------------
# Determinism: the counter-PRNG contract.

def test_multiset_bit_identical_across_batch_chunk_and_rerun():
    _e, ra, a = run_swarm(batch=48)
    _e, rb, b = run_swarm(batch=16)
    _e, rc, c = run_swarm(batch=7)
    _e, rd, d = run_swarm(batch=48, chunk=5)
    _e, ra2, a2 = run_swarm(batch=48)
    assert np.array_equal(a, b)          # batch slicing invisible
    assert np.array_equal(a, c)          # remainder slice too
    assert np.array_equal(a, d)          # chunk size invisible
    assert np.array_equal(a, a2)         # rerun bit-identical
    assert ra.visited == rb.visited == rc.visited == rd.visited
    assert (ra.stop_reason == rb.stop_reason == rc.stop_reason
            == rd.stop_reason)
    # The exact num_steps budget: every walk stepped exactly num_steps.
    assert ra.steps == 48 * 24
    assert ra.visited > 0 and ra.traces >= 48


def test_multiset_is_seed_sensitive():
    _e, _ra, a = run_swarm(seed=5)
    _e, _rb, b = run_swarm(seed=6)
    assert not np.array_equal(a, b)


# ---------------------------------------------------------------------------
# Walk-kernel primitives: the family-diversified draw.

def test_walk_bits_is_a_pure_function_and_stream_separated():
    ids = jnp.arange(7, dtype=jnp.int32)
    a = np.asarray(walk_bits(3, ids, 9, 0x9E3779B1))
    b = np.asarray(walk_bits(3, ids, 9, 0x9E3779B1))
    c = np.asarray(walk_bits(3, ids, 9, 0x85EBCA77))
    assert np.array_equal(a, b)
    assert not np.array_equal(a, c)      # streams decorrelated
    # Per-lane epoch arrays key the family mask: lanes with different
    # epochs draw different words, equal epochs draw equal words.
    ep = jnp.asarray([0, 0, 1, 1, 2, 2, 3], jnp.int32)
    m = np.asarray(walk_bits(3, ids, ep, 0x165667B1))
    m0 = np.asarray(walk_bits(3, ids, 0, 0x165667B1))
    assert m[0] == m0[0] and m[1] == m0[1] and m[2] != m0[2]


def test_preferred_choice_biases_and_never_stalls():
    fam = jnp.asarray([0, 0, 1, 1, 2, 2], jnp.int32)
    en = jnp.asarray([[True] * 6, [True] * 6, [False, True] + [False] * 4],
                     bool)
    # Mask keeping only family 1 (bit 1): lanes 2,3 preferred.
    keep1 = jnp.full((3,), 1 << 1, jnp.uint32)
    pref = family_subset(keep1, fam)
    bits = jnp.asarray([0, 1, 2], jnp.uint32)
    ch = np.asarray(preferred_choice(bits, en, pref))
    assert ch[0] in (2, 3) and ch[1] in (2, 3)
    # Lane 2's only enabled action (1, family 0) is OUTSIDE the kept
    # subset: the draw falls back to all-enabled — bias never stalls.
    assert ch[2] == 1
    # Empty mask word: every lane falls back to the unbiased draw.
    none = jnp.zeros((3,), jnp.uint32)
    ch2 = np.asarray(preferred_choice(bits, en, family_subset(none, fam)))
    assert np.array_equal(ch2, np.asarray(masked_choice(bits, en)))


# ---------------------------------------------------------------------------
# Violation: latch, replay, oracle agreement.

@pytest.fixture(scope="module")
def violation_run(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("swarm")
    ev = str(tmp / "events.jsonl")
    eng = SwarmEngine(DIMS, invariants=invariants(),
                      constraint=build_constraint(DIMS, BOUNDS),
                      walks=32, max_depth=8, chunk=8, ring=8,
                      events_out=ev, counterexample_dir=str(tmp))
    res = eng.run([seeded_root()], seed=1, num_steps=64)
    return eng, res, str(tmp), ev


def test_swarm_latches_the_seeded_violation(violation_run):
    _eng, res, _tmp, _ev = violation_run
    assert res.stop_reason == "violation"
    assert res.violation is not None
    assert res.violation.invariant == "NoLeader"
    assert res.violation_at_seconds is not None
    assert res.violation_trace is not None and len(res.violation_trace) >= 2


def test_replayed_trace_matches_oracle_field_for_field(violation_run):
    eng, res, _tmp, _ev = violation_run
    steps = eng.replay(res.violation.fingerprint)
    decoded = explain.decode_steps(steps, DIMS)
    assert decoded[0]["action"] == "Initial predicate"
    prev = steps[0][1]
    assert decoded[0]["state"] == state_fields(prev, DIMS)
    for rec, (g, st) in zip(decoded[1:], steps[1:]):
        oracle_succ = orc.successor_set(prev, DIMS)
        assert st in oracle_succ
        oracle_match = next(o for o in oracle_succ if o == st)
        assert rec["state"] == state_fields(oracle_match, DIMS)
        fam = DIMS.family_names[DIMS.instance_info(g)[0]]
        assert rec["action"].startswith(fam)
        assert rec["changed"] == diff_states(prev, st, DIMS)
        prev = st
    assert steps[-1][1] == res.violation.state


def test_counterexample_artifacts_land_in_workdir(violation_run):
    _eng, res, tmp, _ev = violation_run
    assert res.counterexample.get("txt")
    assert os.path.exists(os.path.join(tmp, "counterexample.txt"))
    with open(os.path.join(tmp, "counterexample.json")) as f:
        doc = json.load(f)
    assert doc["invariant"] == "NoLeader"


# ---------------------------------------------------------------------------
# Telemetry dialect.

def test_swarm_events_validate_and_carry_the_swarm_payload(violation_run):
    _eng, res, _tmp, ev = violation_run
    events = validate_run_events(ev)
    kinds = [e["event"] for e in events]
    assert kinds[0] == "run_start" and kinds[-1] == "run_end"
    assert "swarm_progress" in kinds and "violation" in kinds
    prog = next(e for e in events if e["event"] == "swarm_progress")
    assert isinstance(prog["swarm"], dict)
    assert prog["swarm"]["walks"] == 32
    end = events[-1]
    assert end["stop_reason"] == "violation"
    assert isinstance(end["swarm"], dict)
    assert end["swarm"]["steps"] == res.steps
    assert end["counterexample_path"]
    viol = next(e for e in events if e["event"] == "violation")
    assert viol["invariant"] == "NoLeader"
    assert viol["at_seconds"] == res.violation_at_seconds


def test_swarm_progress_without_payload_object_is_rejected(tmp_path):
    p = tmp_path / "ev.jsonl"
    lines = [{"event": "run_start", "ts": 0.0},
             {"event": "swarm_progress", "ts": 1.0},   # payload missing
             {"event": "run_end", "ts": 2.0}]
    p.write_text("".join(json.dumps(e) + "\n" for e in lines))
    with pytest.raises(ValueError, match="swarm_progress"):
        validate_run_events(str(p))


# ---------------------------------------------------------------------------
# Serving admission (satellite: unknown mode is a protocol reject).

@pytest.fixture(scope="module")
def server():
    from raft_tla_tpu import server as srv_mod
    srv = srv_mod.serve(port=0)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield srv.server_address
    srv.shutdown()


def roundtrip(addr, req: dict) -> dict:
    with socket.create_connection(addr, timeout=600) as s:
        s.sendall((json.dumps(req) + "\n").encode())
        buf = b""
        while not buf.endswith(b"\n"):
            chunk = s.recv(65536)
            if not chunk:
                break
            buf += chunk
    return json.loads(buf)


def test_server_swarm_check_and_mode_directive(server):
    cfg = os.path.join(REPO, "configs/MCraft_noleader.cfg")
    r = roundtrip(server, {"op": "check", "cfg": cfg, "mode": "swarm",
                           "walks": 32, "max_depth": 8, "num_steps": 16,
                           "seed": 5, "batch": 32})
    assert r["ok"] is True and r["mode"] == "swarm"
    assert r["walks"] == 32 and r["steps"] == 32 * 16
    assert isinstance(r["report"]["swarm"], dict)
    # The hunt report rides the response top-level (ISSUE 20).
    assert isinstance(r["hunt"], dict)
    assert 0.0 <= r["hunt"]["saturation"] <= 1.0
    assert r["hunt"]["observations"] > 0
    # The cfg MODE/WALKS directives drive the same path when the
    # request leaves mode unset.
    with open(cfg) as f:
        text = f.read()
    text += "\n\\* TPU: MODE = swarm\n\\* TPU: WALKS = 16\n"
    r2 = roundtrip(server, {"op": "check", "cfg_text": text,
                            "max_depth": 8, "num_steps": 16, "seed": 5})
    assert r2["ok"] is True and r2["mode"] == "swarm"
    assert r2["walks"] == 16


# ---------------------------------------------------------------------------
# Hunt observatory (obs/hunt.py): coverage estimation + walk analytics.

def test_hunt_is_purely_observational():
    """ISSUE 20 acceptance: the observatory can never perturb the hunt
    — verdict and visited-fingerprint multiset are bit-identical with
    hunt on vs off (the off engine builds a bare chunk with no bloom
    args at all, so this pins the whole analytics block out of the
    walk semantics)."""
    _e, ron, a = run_swarm(hunt=True)
    _e, roff, b = run_swarm(hunt=False)
    assert np.array_equal(a, b)
    assert ron.stop_reason == roff.stop_reason
    assert ron.visited == roff.visited and ron.steps == roff.steps
    assert ron.traces == roff.traces and ron.diameter == roff.diameter
    assert "hunt" in ron.report and "hunt" not in roff.report


def _hunt_run(num_steps):
    """TypeOK-only invariant set: no reachable violation, so the budget
    runs to completion at every size (the honesty pin needs growing
    samples, not a latch race)."""
    eng = SwarmEngine(DIMS, invariants={"TypeOK": build_type_ok(DIMS)},
                     constraint=build_constraint(DIMS, BOUNDS),
                     walks=48, max_depth=12, chunk=8, ring=8,
                     collect_fingerprints=True)
    return eng, eng.run([safe_root()], seed=5, num_steps=num_steps)


def _species_counts(fps):
    key = (fps[:, 0].astype(np.uint64) << np.uint64(32)
           | fps[:, 1].astype(np.uint64))
    uniq, counts = np.unique(key, return_counts=True)
    return len(key), len(uniq), int((counts == 1).sum())


def test_hunt_estimator_is_honest_against_exact_recount():
    """Estimator honesty: the device Bloom tallies must reproduce the
    exact species counts recomputed on host from the full collected
    fingerprint multiset (the oracle for this run), within the pinned
    collision tolerance — and the saturation estimate must grow toward
    1 as the walk budget grows."""
    sats, distincts = [], []
    for num_steps in (8, 64, 512):
        _eng, res = _hunt_run(num_steps)
        h = res.report["hunt"]
        n, distinct, n1 = _species_counts(res.visited_fingerprints)
        # The observation stream IS the accepted-visit multiset.
        assert h["observations"] == n
        # Oracle recount: distinct species, singletons, saturation.
        # Tolerances pin the only permitted error source — two-probe
        # Bloom collisions — at these loads (~1k species in 2^20
        # cells) they are near zero.
        assert abs(h["distinct_observed"] - distinct) \
            <= max(2, 0.01 * distinct)
        assert abs(h["singletons"] - n1) <= max(2, 0.02 * n1)
        sat_exact = 1.0 - (n1 / n if n else 1.0)
        assert abs(h["saturation"] - sat_exact) <= 0.01
        sats.append(h["saturation"])
        distincts.append(h["distinct_observed"])
    assert sats == sorted(sats)                 # never regresses
    assert sats[-1] > sats[0] + 0.01            # and genuinely grows
    assert distincts[-1] > distincts[0]


def test_hunt_report_schema_and_partitions():
    from raft_tla_tpu.obs.hunt import RESTART_REASONS
    eng, res, _fps = run_swarm()
    h = res.report["hunt"]
    # Good-Turing identities.
    assert abs(h["saturation"] + h["unseen_mass"] - 1.0) <= 2e-6
    assert (h["singletons"] + h["doubletons_plus"]
            == h["distinct_observed"])
    assert 0 < h["distinct_observed"] <= h["observations"]
    assert h["steps"] == res.steps
    # Restart census partitions cleanly and every completed trace is
    # one restart (walks still in flight at budget end are not traces).
    r = h["restarts"]
    assert r["total"] == sum(r[k] for k in RESTART_REASONS)
    d = h["depth"]
    assert sum(d["histogram"]) == d["traces"] == r["total"]
    assert len(d["histogram"]) == eng.max_depth + 1
    assert 0 <= d["p50"] <= d["p90"] <= eng.max_depth
    # Family efficacy table: canonical names, nested tallies, and the
    # Holzmann diversification visibly spreading the hunt.
    fams = h["families"]
    assert [f["family"] for f in fams] == list(DIMS.family_names)
    for f in fams:
        assert 0 <= f["fresh"] <= f["accepted"] <= f["chosen"]
    assert sum(1 for f in fams if f["fresh"]) >= 2
    # Estimator-health block: filter geometry + audited collision bias.
    b = h["bloom"]
    assert b["cells"] == eng.hunt_cells
    assert 0.0 < b["load"] <= 1.0
    assert b["collision_probability"] == round(b["load"] ** 2, 8)
    # Novelty curve: bounded, rates in [0, 1], step axis monotone.
    curve = h["novelty_curve"]
    assert 0 < len(curve) <= 2048
    assert all(0.0 <= p[1] <= 1.0 for p in curve)
    assert [p[0] for p in curve] == sorted(p[0] for p in curve)
    assert h["time_to_violation_seconds"] is None
    assert h["wall_seconds"] > 0


def test_hunt_event_and_progress_embed_the_report(violation_run):
    """The ``hunt`` run event validates with its registered payload
    object, agrees with ``SwarmResult.report["hunt"]``, and the
    enriched ``swarm_progress``/``run_end`` swarm blocks carry the live
    snapshot; a violating hunt stamps time-to-violation."""
    _eng, res, _tmp, ev = violation_run
    h = res.report["hunt"]
    assert h["time_to_violation_seconds"] == res.violation_at_seconds
    events = validate_run_events(ev)
    hunts = [e for e in events if e["event"] == "hunt"]
    assert len(hunts) == 1
    assert hunts[0]["hunt"]["saturation"] == h["saturation"]
    assert hunts[0]["hunt"]["observations"] == h["observations"]
    prog = next(e for e in events if e["event"] == "swarm_progress")
    assert 0.0 <= prog["swarm"]["hunt"]["saturation"] <= 1.0
    end = events[-1]
    assert end["event"] == "run_end"
    assert end["swarm"]["hunt"]["distinct_observed"] \
        == h["distinct_observed"]


def test_hunt_event_without_payload_object_is_rejected(tmp_path):
    p = tmp_path / "ev.jsonl"
    lines = [{"event": "run_start", "ts": 0.0},
             {"event": "hunt", "ts": 1.0, "hunt": "saturated"},
             {"event": "run_end", "ts": 2.0}]
    p.write_text("".join(json.dumps(e) + "\n" for e in lines))
    with pytest.raises(ValueError, match="hunt"):
        validate_run_events(str(p))


def test_server_rejects_unknown_mode_cleanly(server):
    cfg = os.path.join(REPO, "configs/MCraft_noleader.cfg")
    r = roundtrip(server, {"op": "check", "cfg": cfg, "mode": "zigzag"})
    assert r["ok"] is False
    assert "mode" in r["error"]
    # Job admission rejects BEFORE the executor thread ever sees it.
    r2 = roundtrip(server, {"op": "submit",
                            "job": {"op": "check", "cfg": cfg,
                                    "mode": "zigzag"}})
    assert r2["ok"] is False
    assert "mode" in r2["error"]
    st = roundtrip(server, {"op": "stats"})
    assert st["metrics"]["counters"]["server/rejected/bad_mode"] >= 2
    assert st["swarm_cache"]["capacity"] >= 1
