"""Static partial-order reduction (analysis/por.py + EngineConfig.por).

Three layers of evidence, mirroring the pass's own soundness gates:

- **Certificates**: on the base Raft alphabet the pass is honestly
  conservative — every instance fails the dependence-closure condition
  (``Receive``'s whole-bag reply-slot scan makes it statically dependent
  on everything), so the certified set is EMPTY, each family carries a
  surfaced WARNING naming the blocking condition, and POR-on checking is
  bit-identical to full expansion.  The pinned L0-L9 MCraft_bounded
  ground truths (scripts/oracle_exhaust.py) are re-checked POR-on.
- **Table integrity**: the packed reduction table is fingerprinted over
  its payload; a hand-edited mask, a different model, or a run checking
  predicates outside the certified set is rejected at admission.
- **Engine machinery**: a test-forged table (simulating a model where
  certificates prove) drives the masked expansion path end-to-end:
  generated/distinct drop, the reduced distinct-state set is a subset of
  the full run's (trace-fingerprint check), and the coverage accounting
  closes exactly (``expanded * family_size == generated + disabled +
  pruned`` per family).
"""

import json

import numpy as np
import pytest

import jax.numpy as jnp

from raft_tla_tpu.analysis import por
from raft_tla_tpu.engine.bfs import BFSEngine, EngineConfig
from raft_tla_tpu.models import oracle as orc
from raft_tla_tpu.models.dims import LEADER, RaftDims
from raft_tla_tpu.models.invariants import (Bounds, build_constraint,
                                            build_type_ok, constraint_py,
                                            type_ok_py)
from raft_tla_tpu.models.pystate import init_state

DIMS = RaftDims(n_servers=3, n_values=2, max_log=4, n_msg_slots=8)
BOUNDS = Bounds(max_term=2, max_log_len=1, max_msg_count=1)


@pytest.fixture(scope="module", autouse=True)
def _release_tracing_caches():
    """Same contract as tests/test_analysis.py: the pass traces every
    kernel and predicate; drop the caches at module teardown so the
    accumulated trace churn never taxes other modules."""
    yield
    import gc

    import jax

    from raft_tla_tpu.analysis import interp
    interp.traced_kernels.cache_clear()
    jax.clear_caches()
    gc.collect()


@pytest.fixture(scope="module")
def pass_result():
    from raft_tla_tpu.analysis import effects
    summary, _ = effects.analyze(DIMS)
    return por.analyze(DIMS, bounds=BOUNDS, effect_summary=summary)


@pytest.fixture(scope="module")
def real_table():
    """The genuinely-certified table for (DIMS, TypeOK, BoundedSpace):
    conservative — zero ample instances on the Raft alphabet."""
    return por.build_table(
        DIMS, invariants={"TypeOK": build_type_ok(DIMS)},
        constraint=build_constraint(DIMS, BOUNDS))


def small_config(**kw):
    base = dict(batch=32, queue_capacity=1 << 12, seen_capacity=1 << 15,
                check_deadlock=False, max_diameter=3)
    base.update(kw)
    return EngineConfig(**base)


def forged_dup_table(dims=DIMS, predicates=("TypeOK", "CONSTRAINT")):
    """A table certifying every DuplicateMessage instance — NOT a sound
    certificate for Raft (the pass proves it cannot be); it exists to
    drive the engine's masking machinery in tests, standing in for a
    model whose certificates do prove."""
    G = dims.n_instances
    mask = np.zeros(G, bool)
    f = dims.family_names.index("DuplicateMessage")
    off, sz = dims.family_offsets[f], dims.family_sizes[f]
    mask[off:off + sz] = True
    return por.PorTable(model=repr(dims), n_instances=G, ample_mask=mask,
                        priority=np.arange(G, dtype=np.int32),
                        predicates=tuple(predicates))


# ---------------------------------------------------------------------------
# The pass: conservative certificates on the real model


def test_pass_is_clean_and_honestly_conservative(pass_result):
    summary, findings = pass_result
    assert [f for f in findings if f.severity == "ERROR"] == []
    # Honest negative result: nothing certifies on the Raft alphabet.
    assert summary["certified"] == 0
    widened = {f.field for f in findings if f.code == "por-widened"}
    assert widened == set(DIMS.family_names)
    # Every family's blocking conditions are recorded; closure is the
    # universal blocker (Receive genuinely addresses any server and its
    # reply allocation scans the whole bag), and each family carries
    # its top blocking (family, field, slot) triples as the worklist.
    for fam, d in summary["families"].items():
        assert d["certified"] == 0
        assert d["blocked_by"].get("closure", 0) == d["instances"], fam
        top = d["blocking_elements"]
        assert top and {"family", "element", "kind", "pairs"} \
            <= set(top[0]), fam


def test_closure_block_is_machine_checked_impossible(pass_result):
    """The impossibility notes: every instance blocked on closure has a
    CONCRETE two-action non-commutation witness (or an interval proof
    it can never execute) — so the zero-certified result is inherent to
    the Raft alphabet, pinned, and can never be mistaken for analyzer
    imprecision."""
    summary, findings = pass_result
    ref = summary["closure_refutation"]
    assert ref["ran"]
    assert ref["open"] == []
    assert ref["witnessed"] + ref["vacuous"] == summary["n_instances"]
    imposs = {f.field for f in findings if f.code == "por-impossible"}
    assert imposs == set(DIMS.family_names)
    # The witness detail names the conflicting instance and the kind.
    fam = summary["families"]["DuplicateMessage"]
    w = fam["closure_refutation"]["witnesses"][0]
    assert w["status"] == "witnessed"
    assert w["kind"] in ("disables", "disabled-by", "diamond")
    assert w["conflicts_with"]
    # The vacuous instances are exactly the never-enabled grid corners
    # (AppendEntries(i, i) — guard has i != j parameter-concrete).
    ae = summary["families"]["AppendEntries"]["closure_refutation"]
    assert ae["vacuous"] == DIMS.n_servers


def test_receive_case_split_slot_local(pass_result):
    """The mtype/(i, j) case-split: each case's server-field writes are
    row-local to the case's dest server, the union over cases stays
    inside the instance's conservative footprint, and the por summary
    records it — the machine-readable reason the whole-field union is
    forced by reachable headers."""
    from raft_tla_tpu.analysis import effects
    summary, _ = pass_result
    cs = summary["families"]["Receive"]["case_split"]
    assert cs["cases"] == 4 * DIMS.n_servers * DIMS.n_servers
    assert cs["server_writes_row_local"] == cs["cases"]
    cases = effects.receive_case_effects(DIMS, slot=0)
    eff, _f = effects.analyze(DIMS)
    recv = next(i for i in eff.instances if i.label == "Receive(slot=0)")
    server_fields = {"term", "role", "voted_for", "votes_resp",
                     "votes_gran", "log_term", "log_val", "log_len",
                     "next_idx", "match_idx"}
    for (t, i, j), fp in cases.items():
        for f, m in fp["writes"].items():
            assert bool((m & ~recv.writes[f]).sum() == 0), (t, i, j, f)
            if f in server_fields:
                rows = set(np.nonzero(m)[0].tolist())
                assert rows <= {i}, (t, i, j, f, rows)
    # AER on a known (i, j): the handler's footprint is cell-local.
    aer = cases[(3, 1, 2)]["writes"]
    assert aer["next_idx"].tolist()[1][2] and aer["next_idx"].sum() == 1
    assert aer["msg_cnt"].tolist() == [1] + [0] * (DIMS.n_msg_slots - 1)


def test_predicate_read_sets(pass_result):
    summary, _ = pass_result
    reads = summary["predicates"]
    # TypeOK reads every packed field — the visibility condition that
    # (correctly) forbids pruning anything TypeOK-visible.
    from raft_tla_tpu.analysis.lane_map import FIELDS
    assert set(reads["TypeOK"]) == set(FIELDS)
    # The CONSTRAINT predicate's reads are exactly its bounded counters.
    assert set(reads["CONSTRAINT"]) == {"term", "log_len", "msg_cnt"}


def test_self_disabling_proof():
    """C3: a guard proved false on the kernel's own successor envelope.
    A one-shot toy action (guard ``role[0] == 0``, write ``role[0] = 1``)
    proves; Timeout (a candidate can time out again) must not."""
    from raft_tla_tpu.analysis.interp import trace_family, traced_kernels

    def one_shot(st):
        en = st.role[0] == 0
        succ = st._replace(
            role=jnp.where(jnp.arange(st.role.shape[0]) == 0, 1, st.role))
        return en, jnp.bool_(False), tuple(succ)

    closed = trace_family(one_shot, DIMS, 0)
    env = por._envelope_intervals(DIMS, BOUNDS)
    proved, _notes = por.self_disabling(closed, (), env)
    assert proved

    timeout_closed = next(c for name, c, _p in traced_kernels(DIMS)
                          if name == "Timeout")
    proved, _notes = por.self_disabling(timeout_closed, (0,), env)
    assert not proved


# ---------------------------------------------------------------------------
# Table integrity


def test_table_roundtrip_and_falsified_mask_rejected(real_table, tmp_path):
    path = tmp_path / "por.json"
    real_table.save(str(path))
    loaded = por.load_table(str(path))
    assert loaded.fingerprint == real_table.fingerprint
    assert loaded.certified == 0

    # Hand-edit the mask (certify instance 0) without refreshing the
    # fingerprint: the artifact must be rejected at load.
    doc = json.loads(path.read_text())
    doc["ample_mask"][0] = 1
    path.write_text(json.dumps(doc))
    with pytest.raises(ValueError, match="fingerprint mismatch"):
        por.load_table(str(path))


def test_engine_rejects_falsified_artifact(real_table, tmp_path):
    """The engine-side gate of the same property: a tampered artifact
    never reaches the masking path."""
    path = tmp_path / "por.json"
    doc = real_table.to_json()
    doc["ample_mask"][0] = 1      # stale fingerprint now lies
    path.write_text(json.dumps(doc))
    with pytest.raises(ValueError, match="fingerprint mismatch"):
        BFSEngine(DIMS, invariants={"TypeOK": build_type_ok(DIMS)},
                  constraint=build_constraint(DIMS, BOUNDS),
                  config=small_config(por_table=str(path)))


def test_table_v1_artifact_rejected(real_table):
    """A field-granular (version-1) artifact must be refused with a
    regenerate pointer: its certificates were proved under a coarser
    footprint encoding than the analyzer now emits."""
    doc = real_table.to_json()
    doc["version"] = 1
    doc.pop("granularity")
    with pytest.raises(ValueError, match="coarser footprint|regenerate"):
        por.PorTable.from_json(doc)
    doc2 = real_table.to_json()
    doc2["granularity"] = "field"
    with pytest.raises(ValueError, match="granularity"):
        por.PorTable.from_json(doc2)


def test_chunk_body_rejects_malformed_por_arrays():
    """The engine-side admission re-check at the compilation boundary:
    a mask that does not cover the instance grid (or carries the wrong
    dtype) fails before any lane is masked."""
    import jax.numpy as jnp
    from raft_tla_tpu.engine.chunk import build_chunk_body

    def build(mask, pri):
        return build_chunk_body(
            dims=DIMS, expand=None, fingerprint=None, pack_ok=None,
            inv_fns=None, constraint=None, B=8, G=DIMS.n_instances,
            K=8, Q=8, TQ=8, record_static=True, compactor=None,
            insert_fn=None, por_mask=mask, por_priority=pri)

    G = DIMS.n_instances
    with pytest.raises(ValueError, match="instance grid"):
        build(jnp.zeros(G - 1, jnp.bool_), jnp.zeros(G - 1, jnp.int32))
    with pytest.raises(ValueError, match="bool/int32"):
        build(jnp.zeros(G, jnp.int32), jnp.zeros(G, jnp.int32))
    with pytest.raises(ValueError, match="given together"):
        build(jnp.zeros(G, jnp.bool_), None)


def test_table_admission_checks(real_table):
    other = RaftDims(n_servers=2, n_values=1, max_log=2, n_msg_slots=4)
    with pytest.raises(ValueError, match="certified for model"):
        por.check_table(real_table, other)
    # A run checking an invariant outside the certified predicate set
    # must be rejected — its reads were never part of the visibility
    # condition.
    with pytest.raises(ValueError, match="NoLeader"):
        por.check_table(real_table, DIMS,
                        invariant_names=["TypeOK", "NoLeader"])
    # A forged certifying table without a CONSTRAINT predicate cannot be
    # applied to a constrained run.
    forged = forged_dup_table(predicates=("TypeOK",))
    with pytest.raises(ValueError, match="CONSTRAINT"):
        por.check_table(forged, DIMS, invariant_names=["TypeOK"],
                        has_constraint=True)


# ---------------------------------------------------------------------------
# Engine: POR-on vs POR-off (the oracle differential)


def test_por_smoke_on_off_counters(real_table):
    """The CI POR smoke: POR-on checking with the genuinely-certified
    (conservative, empty-mask) table is bit-identical to full expansion,
    and both match the Python oracle."""
    cons = build_constraint(DIMS, BOUNDS)
    inv = {"TypeOK": build_type_ok(DIMS)}
    off = BFSEngine(DIMS, invariants=inv, constraint=cons,
                    config=small_config()).run([init_state(DIMS)])
    on = BFSEngine(DIMS, invariants=inv, constraint=cons,
                   config=small_config(por_table=real_table)
                   ).run([init_state(DIMS)])
    assert on.por_instances == 0
    assert (on.distinct, on.generated, on.levels, on.diameter) \
        == (off.distinct, off.generated, off.levels, off.diameter)
    want = orc.bfs([init_state(DIMS)], DIMS,
                   invariants={"TypeOK": type_ok_py},
                   constraint=constraint_py(BOUNDS),
                   check_deadlock=False, max_levels=3)
    assert want.invariant_violation is None
    assert on.violation is None
    assert on.distinct == want.distinct_states
    assert on.levels == want.levels
    # Full coverage accounting still closes with the POR column at zero.
    assert sum(v["pruned"] for v in on.coverage.values()) == 0


def test_por_true_certifies_in_process():
    """EngineConfig.por=True runs the pass at engine build against this
    run's exact invariants + constraint; on Raft that yields the
    conservative empty mask and full-expansion counts."""
    cons = build_constraint(DIMS, BOUNDS)
    eng = BFSEngine(DIMS, invariants={"TypeOK": build_type_ok(DIMS)},
                    constraint=cons, config=small_config(por=True))
    assert eng._por_table is not None
    assert eng._por_table.certified == 0
    res = eng.run([init_state(DIMS)])
    assert res.por_instances == 0
    assert res.violation is None


def test_violation_still_found_with_por_on(real_table):
    """Verdict preservation on a violating model: the POR-on run must
    find the same invariant violation the oracle proves reachable, and
    its replayed counterexample must stay a legal spec path."""
    inv = {"TypeOK": build_type_ok(DIMS),
           "NoLeader": lambda st: jnp.all(st.role != LEADER)}
    # NoLeader is outside the table's certified predicates — admission
    # must reject the stale certificate...
    with pytest.raises(ValueError, match="NoLeader"):
        BFSEngine(DIMS, invariants=inv,
                  constraint=build_constraint(DIMS, BOUNDS),
                  config=small_config(por_table=real_table))
    # ...and in-process certification against the run's own invariant
    # set is the supported route.
    eng = BFSEngine(DIMS, invariants=inv,
                    constraint=build_constraint(DIMS, BOUNDS),
                    config=small_config(por=True))
    s0 = init_state(DIMS).replace(
        role=(1, 0, 0), current_term=(2, 2, 2), voted_for=(1, 1, 1),
        votes_responded=(0b001, 0, 0), votes_granted=(0b001, 0, 0),
        messages=frozenset({((1, 1, 0, 2, 1, ()), 1)}))
    res = eng.run([s0])
    assert res.stop_reason == "violation"
    assert res.violation.invariant == "NoLeader"
    want = orc.bfs([s0], DIMS,
                   invariants={"NoLeader": lambda s, d: LEADER not in s.role},
                   constraint=constraint_py(BOUNDS), check_deadlock=False)
    assert want.invariant_violation is not None
    steps = eng.replay(res.violation.fingerprint)
    for (s_prev, s_next) in zip(steps, steps[1:]):
        assert s_next[1] in orc.successor_set(s_prev[1], DIMS)


def test_forced_table_reduces_and_accounting_closes():
    """The masking machinery itself, driven by a forged certifying
    table: fewer generated/distinct states, the reduced distinct set is
    a SUBSET of the full run's, per-family accounting closes exactly,
    and the reduction is deterministic."""
    cons = build_constraint(DIMS, BOUNDS)
    inv = {"TypeOK": build_type_ok(DIMS)}
    full_eng = BFSEngine(DIMS, invariants=inv, constraint=cons,
                         config=small_config(record_trace=True))
    full = full_eng.run([init_state(DIMS)])
    table = forged_dup_table()
    red_eng = BFSEngine(DIMS, invariants=inv, constraint=cons,
                        config=small_config(record_trace=True,
                                            por_table=table))
    red = red_eng.run([init_state(DIMS)])
    assert red.por_instances == DIMS.n_msg_slots
    assert red.distinct < full.distinct
    assert red.generated < full.generated
    assert all(r <= f for r, f in zip(red.levels, full.levels))

    # Subset: every distinct state of the reduced run (trace fps plus
    # roots) appears in the full run's distinct set.
    full_fps = set(int(x) for x in full_eng.trace.export()[0]) \
        | set(full_eng.trace.roots)
    red_fps = set(int(x) for x in red_eng.trace.export()[0]) \
        | set(red_eng.trace.roots)
    assert red_fps <= full_fps

    # Reduced-vs-full accounting (obs/coverage.py): the expanded base
    # reconstructed from generated+disabled+pruned is one shared number
    # across families, and pruning actually happened.
    sizes = dict(zip(DIMS.family_names, DIMS.family_sizes))
    base = {n: (v["generated"] + v["disabled"] + v["pruned"]) / sizes[n]
            for n, v in red.coverage.items()}
    assert len(set(base.values())) == 1
    assert sum(v["pruned"] for v in red.coverage.values()) > 0
    # Pruned lanes concentrate outside the ample family by construction.
    assert red.coverage["DuplicateMessage"]["pruned"] == 0

    again = BFSEngine(DIMS, invariants=inv, constraint=cons,
                      config=small_config(record_trace=True,
                                          por_table=table)
                      ).run([init_state(DIMS)])
    assert (again.distinct, again.generated, again.levels) \
        == (red.distinct, red.generated, red.levels)


def test_forced_table_render_table_shows_pruned():
    """The run-end coverage table gains the pruned column only when the
    mask dropped something."""
    from raft_tla_tpu.obs import ActionCoverage
    cov = ActionCoverage(("A", "B"), (2, 3))
    cov.add_chunk(10, (5, 6), (1, 2))
    assert "pruned" not in cov.render_table()
    cov.add_chunk(0, (0, 0), (0, 0), (3, 0))
    out = cov.render_table()
    assert "POR pruned: 3" in out and "pruned" in out
    assert cov.disabled("A") == 10 * 2 - 5 - 3
    snap = cov.snapshot()
    assert snap["A"]["pruned"] == 3 and snap["B"]["pruned"] == 0


@pytest.mark.slow   # ~2 min CPU; tier-1 keeps the L0-L6 differentials
def test_oracle_differential_pinned_L0_L9(real_table):
    """The acceptance differential on the pinned MCraft_bounded L0-L9
    ground truths (scripts/oracle_exhaust.py, oracle_exhaust.jsonl
    level 9): a POR-on run with the genuinely-certified table matches
    the Python oracle's verdict and counts exactly.

    With the machine-checked impossibility result (zero certified on
    the Raft alphabet — see test_closure_block_is_machine_checked_
    impossible), POR-on IS full expansion, so distinct == full and
    every oracle state is reached by construction, with pruned == 0.
    If analyzer precision ever flips a family to certified, the same
    assertions become the real reduced-vs-full differential: the
    reduced run must still reproduce the full run's distinct-state
    count, levels, and verdict, now with pruned > 0 — the conditional
    branch below activates without edits here."""
    import os
    from raft_tla_tpu.engine.check import initial_states, make_engine
    from raft_tla_tpu.utils.cfg import load_config
    from tests.test_engine import MCRAFT_BOUNDED_LEVELS
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    setup = load_config(os.path.join(here, "configs/MCraft_bounded.cfg"))
    table = por.build_table(
        setup.dims, invariants={"TypeOK": build_type_ok(setup.dims)},
        constraint=build_constraint(setup.dims, setup.bounds))
    eng = make_engine(setup, EngineConfig(
        batch=512, queue_capacity=1 << 15, seen_capacity=1 << 20,
        check_deadlock=False, record_trace=False, sync_every=16,
        max_diameter=9, por_table=table))
    res = eng.run(initial_states(setup))
    # Pinned by the independent digest-based oracle sweep
    # (oracle_exhaust.jsonl level 9, 2026-07-29).
    assert res.levels == MCRAFT_BOUNDED_LEVELS[:10]
    assert res.distinct == 505004
    assert res.generated == 1421121
    assert res.violation is None          # oracle verdict: no violation
    assert res.por_instances == table.certified
    pruned = sum(v["pruned"] for v in res.coverage.values())
    if table.certified:
        # A newly certified family must show up as real reduction while
        # preserving the exhaustive result exactly (asserted above).
        assert pruned > 0
    else:
        assert pruned == 0
        # ... and the zero must be the machine-checked kind: the pass
        # proves the closure block inherent on this model too.
        summary, _f = por.analyze(
            setup.dims, bounds=setup.bounds,
            invariants={"TypeOK": build_type_ok(setup.dims)},
            constraint=build_constraint(setup.dims, setup.bounds),
            init_states=initial_states(setup))
        ref = summary["closure_refutation"]
        assert ref["ran"] and ref["open"] == []


# ---------------------------------------------------------------------------
# CLI


def test_cli_analyze_por_pass_and_artifact(tmp_path, capsys):
    from raft_tla_tpu.cli import main
    art = tmp_path / "por_table.json"
    rc = main(["analyze", "--max-log", "3", "--n-msg-slots", "4",
               "--passes", "effects,por", "--json",
               "--por-artifact", str(art)])
    assert rc == 0
    rep = json.loads(capsys.readouterr().out)
    assert rep["ok"]
    por_summary = rep["passes"]["por"]["summary"]
    assert por_summary["certified"] == 0
    assert por_summary["table"]["fingerprint"]
    warned = [f for f in rep["passes"]["por"]["findings"]
              if f["code"] == "por-widened"]
    assert warned
    table = por.load_table(str(art))      # artifact round-trips verified
    assert table.certified == 0


def test_cli_analyze_single_pass_resolves_deps(tmp_path, capsys):
    """`analyze --passes por` no longer requires the user to spell out
    the effects prerequisite: pass dependencies resolve topologically,
    the effects summary rides along in the report, and the text
    rendering carries the per-family POR table."""
    from raft_tla_tpu.cli import main
    rc = main(["analyze", "--max-log", "3", "--n-msg-slots", "4",
               "--passes", "por", "--json"])
    assert rc == 0
    rep = json.loads(capsys.readouterr().out)
    assert rep["ok"]
    assert {"effects", "por"} <= set(rep["passes"])
    assert rep["passes"]["effects"]["summary"]["independent_pairs"] > 0
    assert rep["passes"]["por"]["summary"]["certified"] == 0
    # Text mode: the rendered worklist table.
    rc = main(["analyze", "--max-log", "3", "--n-msg-slots", "4",
               "--passes", "por"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "top blocking element" in out
    assert "inherent" in out
    assert "closure refutation:" in out


def test_cli_analyze_unknown_pass_exits_2(tmp_path, capsys):
    from raft_tla_tpu.cli import main
    rc = main(["analyze", "--max-log", "3", "--n-msg-slots", "4",
               "--passes", "effects,typo"])
    assert rc == 2
    err = capsys.readouterr().err
    assert "typo" in err and "por" in err and "effects" in err
    # Empty pass list is the same usage error, not a silent OK.
    rc = main(["analyze", "--max-log", "3", "--n-msg-slots", "4",
               "--passes", ","])
    assert rc == 2
    capsys.readouterr()


def test_cli_check_with_por_artifact(tmp_path, capsys):
    """check --por-table consumes the analyze-produced artifact end to
    end (the artifact workflow, tiny model)."""
    from raft_tla_tpu.cli import main
    cfg = tmp_path / "tiny.cfg"
    cfg.write_text(
        "CONSTANTS\n    Server = {r1, r2}\n    Value = {v1}\n"
        "    MaxTerm = 2\n    MaxLogLen = 1\n    MaxMsgCount = 1\n"
        "SPECIFICATION Spec\nINVARIANT TypeOK\nCONSTRAINT BoundedSpace\n"
        "CHECK_DEADLOCK FALSE\n"
        "\\* TPU: MAX_LOG = 2\n\\* TPU: N_MSG_SLOTS = 8\n")
    art = tmp_path / "por_table.json"
    rc = main(["analyze", str(cfg), "--passes", "effects,por",
               "--por-artifact", str(art)])
    assert rc == 0
    capsys.readouterr()
    rc = main(["check", str(cfg), "--platform", "cpu", "--batch", "32",
               "--max-diameter", "2", "--queue-capacity", "4096",
               "--seen-capacity", "32768", "--progress-interval", "0",
               "--por-table", str(art)])
    assert rc == 0
    assert "distinct states" in capsys.readouterr().out


def test_refutation_totals_exclude_certified_instances():
    """A certified instance has no non-commutation witness by
    construction — the witness tally must scope to closure-BLOCKED
    instances only, so a partially certified family never reads as
    'open' precision worklist (review finding on the aggregation)."""
    certified = por.Certificate(
        grid_index=0, family="X", label="X(i=0)",
        conditions={c: (True, "ok") for c in por.CONDITIONS})
    blocked = por.Certificate(
        grid_index=1, family="X", label="X(i=1)",
        conditions=dict({c: (True, "ok") for c in por.CONDITIONS},
                        closure=(False, "dependent")))
    refs = {"X(i=0)": por.ClosureRefutation("X(i=0)", "open"),
            "X(i=1)": por.ClosureRefutation(
                "X(i=1)", "witnessed", "Y(i=1)", "diamond", 0)}
    totals = por._refutation_totals([certified, blocked], refs)
    assert totals == {"ran": True, "witnessed": 1, "vacuous": 0,
                      "open": []}
    assert por._refutation_totals([certified, blocked], {}) \
        == {"ran": False, "witnessed": 0, "vacuous": 0, "open": []}
