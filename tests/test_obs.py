"""Telemetry subsystem tests (obs/): registry semantics, event-log
schema, and the engine/CLI integrations.

The registry/event-log halves are tested standalone (they are zero-dep
and must stay importable without jax); the integration tests then assert
the ISSUE acceptance contract end-to-end: a run's JSONL log contains
run_start, level_complete events whose per-phase timings account for the
wall clock, and run_end — through both the BFSEngine API and the CLI.
"""

import json
import os
import threading

import jax.numpy as jnp
import pytest

from raft_tla_tpu.engine.bfs import BFSEngine, EngineConfig
from raft_tla_tpu.models.dims import LEADER, RaftDims
from raft_tla_tpu.models.invariants import Bounds, build_constraint
from raft_tla_tpu.models.pystate import init_state
from raft_tla_tpu.obs import (MetricsRegistry, RunEventLog,
                              events_path, phase_delta,
                              validate_run_events)

DIMS = RaftDims(n_servers=3, n_values=2, max_log=4, n_msg_slots=32)
BOUNDS = Bounds(max_term=2, max_log_len=1, max_msg_count=1)


def small_config(**kw):
    base = dict(batch=32, queue_capacity=1 << 12, seen_capacity=1 << 15,
                check_deadlock=False)
    base.update(kw)
    return EngineConfig(**base)


# ---------------------------------------------------------------------------
# MetricsRegistry semantics

def test_counters_accumulate_and_gauges_overwrite():
    mt = MetricsRegistry()
    mt.counter("a")
    mt.counter("a", 4)
    mt.gauge("g", 7)
    mt.gauge("g", 3)
    snap = mt.snapshot()
    assert snap["counters"]["a"] == 5
    assert snap["gauges"]["g"] == 3
    assert mt.counter_value("a") == 5
    assert mt.counter_value("missing") == 0


def test_histogram_summary_and_buckets():
    mt = MetricsRegistry()
    for v in (0.001, 0.002, 0.004, 10.0):
        mt.observe("h", v)
    h = mt.snapshot()["histograms"]["h"]
    assert h["count"] == 4
    assert h["min"] == 0.001 and h["max"] == 10.0
    assert abs(h["total"] - 10.007) < 1e-9
    assert abs(h["mean"] - 10.007 / 4) < 1e-9
    # 1-2-5 ladder: 0.001 -> "0.001" bucket, 0.002 -> "0.002",
    # 0.004 -> "0.005", 10.0 -> "10"; counts sum to the observation count.
    assert sum(h["buckets"].values()) == 4
    assert h["buckets"]["0.001"] == 1 and h["buckets"]["0.005"] == 1


def test_phase_timer_accumulates_into_phase_seconds():
    mt = MetricsRegistry()
    for _ in range(3):
        with mt.phase_timer("stage"):
            pass
    ph = mt.phase_seconds()
    assert set(ph) == {"stage"}
    assert ph["stage"] >= 0.0
    assert mt.snapshot()["histograms"]["phase/stage"]["count"] == 3
    # phase_timer records even when the body raises (finally-path).
    with pytest.raises(RuntimeError):
        with mt.phase_timer("stage"):
            raise RuntimeError("boom")
    assert mt.snapshot()["histograms"]["phase/stage"]["count"] == 4


def test_phase_delta_scopes_to_a_baseline():
    mt = MetricsRegistry()
    with mt.phase_timer("a"):
        pass
    base = mt.phase_seconds()
    with mt.phase_timer("b"):
        pass
    d = phase_delta(mt.phase_seconds(), base)
    assert "b" in d and "a" not in d     # a advanced by zero since base
    assert phase_delta({"x": 1.0}, None) == {"x": 1.0}


def test_registry_is_thread_safe():
    mt = MetricsRegistry()

    def work():
        for _ in range(1000):
            mt.counter("n")
            mt.observe("h", 0.001)

    threads = [threading.Thread(target=work) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert mt.counter_value("n") == 8000
    assert mt.snapshot()["histograms"]["h"]["count"] == 8000


# ---------------------------------------------------------------------------
# RunEventLog + validation

def test_event_log_writes_schema_lines(tmp_path):
    p = str(tmp_path / "ev.jsonl")
    with RunEventLog(p) as log:
        assert log.enabled
        log.emit("run_start", foo=1)
        log.emit("run_end", bar="x")
    recs = [json.loads(l) for l in open(p)]
    assert [r["event"] for r in recs] == ["run_start", "run_end"]
    for r in recs:
        assert "ts" in r and "elapsed_seconds" in r
    assert recs[0]["foo"] == 1 and recs[1]["bar"] == "x"
    assert validate_run_events(p)[0]["event"] == "run_start"


def test_event_log_null_sink_noops():
    log = RunEventLog(None)
    assert not log.enabled
    log.emit("run_start")           # must not raise
    log.close()


def test_validate_rejects_missing_malformed_and_incomplete(tmp_path):
    with pytest.raises(FileNotFoundError):
        validate_run_events(str(tmp_path / "nope.jsonl"))
    bad = tmp_path / "bad.jsonl"
    bad.write_text('{"event": "run_start", "ts": 1}\nnot json\n')
    with pytest.raises(ValueError, match="malformed"):
        validate_run_events(str(bad))
    partial = tmp_path / "partial.jsonl"
    partial.write_text('{"event": "run_start", "ts": 1}\n')
    with pytest.raises(ValueError, match="run_end"):
        validate_run_events(str(partial))


def test_events_path_resolution(tmp_path):
    assert events_path(None, None) is None
    assert events_path("/x/e.jsonl", "/ck") == "/x/e.jsonl"
    assert events_path(None, "/ck") == os.path.join("/ck", "events.jsonl")
    # Per-controller piece suffix under a process group.
    assert events_path("/x/e.jsonl", None, 1, 4) == "/x/e.p1of4.jsonl"


# ---------------------------------------------------------------------------
# Engine integration (the acceptance contract)

def run_and_load_events(tmp_path, engine_cls=BFSEngine, **cfg_kw):
    ev = str(tmp_path / "events.jsonl")
    eng = engine_cls(DIMS, constraint=build_constraint(DIMS, BOUNDS),
                     config=small_config(max_diameter=3, events_out=ev,
                                         **cfg_kw))
    res = eng.run([init_state(DIMS)])
    return res, validate_run_events(ev)


def test_engine_run_emits_complete_event_log(tmp_path):
    res, events = run_and_load_events(tmp_path)
    kinds = [e["event"] for e in events]
    assert kinds[0] == "run_start" and kinds[-1] == "run_end"
    levels = [e for e in events if e["event"] == "level_complete"]
    # Root-ingest level 0 plus the three expanded levels.
    assert len(levels) == len(res.levels) == 4
    assert [e["level"] for e in levels] == [0, 1, 2, 3]
    assert [e["frontier_rows"] for e in levels] == res.levels
    assert levels[-1]["distinct"] == res.distinct
    # Phase accounting: cumulative per-phase seconds + the unattributed
    # remainder == elapsed (exact by construction), AND the named phases
    # cover most of the wall — the breakdown is real, not rounding dust.
    last = levels[-1]
    ph = last["phase_seconds"]
    covered = sum(ph.values())
    assert abs(covered + last["unattributed_seconds"]
               - last["elapsed_seconds"]) < 0.05
    assert covered >= 0.5 * last["elapsed_seconds"]
    assert {"warmup", "chunk", "stats_fetch"} <= set(ph)
    # run_end carries the final snapshot, mirrored on the result object.
    end = events[-1]
    assert end["stop_reason"] == "diameter_budget" == res.stop_reason
    assert end["distinct"] == res.distinct
    assert res.phases and set(ph) <= set(res.phases)


def test_engine_metrics_registry_feeds_counters(tmp_path):
    ev = str(tmp_path / "e.jsonl")
    mt = MetricsRegistry()
    eng = BFSEngine(DIMS, constraint=build_constraint(DIMS, BOUNDS),
                    config=small_config(max_diameter=2, events_out=ev,
                                        metrics=mt))
    res = eng.run([init_state(DIMS)])
    assert eng.metrics is mt     # shared registry honored
    assert mt.counter_value("engine/distinct") == res.distinct
    assert mt.counter_value("engine/generated") == res.generated
    assert mt.snapshot()["gauges"]["engine/seen_size"] > 0


def test_violation_event_and_depth0_replay(tmp_path):
    # Mid-run violation -> a violation event in the log.
    ev = str(tmp_path / "v.jsonl")
    inv = {"NoLeader": lambda st: jnp.all(st.role != LEADER)}
    eng = BFSEngine(DIMS, invariants=inv,
                    constraint=build_constraint(DIMS, BOUNDS),
                    config=small_config(events_out=ev))
    s0 = init_state(DIMS).replace(
        role=(1, 0, 0), current_term=(2, 2, 2), voted_for=(1, 1, 1),
        votes_responded=(0b001, 0, 0), votes_granted=(0b001, 0, 0),
        messages=frozenset({((1, 1, 0, 2, 1, ()), 1)}))
    res = eng.run([s0])
    assert res.stop_reason == "violation"
    ev_kinds = [e["event"] for e in validate_run_events(ev)]
    assert "violation" in ev_kinds

    # Depth-0 violation (a root violates): replay() must return the
    # one-state trace instead of raising KeyError (ADVICE r5 /
    # mesh root-violation fix; same contract single-chip).
    viol_root = init_state(DIMS).replace(role=(2, 0, 0))
    eng2 = BFSEngine(DIMS, invariants=inv,
                     constraint=build_constraint(DIMS, BOUNDS),
                     config=small_config())
    res2 = eng2.run([viol_root])
    assert res2.stop_reason == "violation"
    steps = eng2.replay(res2.violation.fingerprint)
    assert steps == [(-1, viol_root)]


def test_mesh_engine_emits_events_too(tmp_path):
    from raft_tla_tpu.parallel.mesh import MeshBFSEngine
    res, events = run_and_load_events(tmp_path, engine_cls=MeshBFSEngine,
                                      batch=16)
    kinds = [e["event"] for e in events]
    assert kinds[0] == "run_start" and kinds[-1] == "run_end"
    levels = [e for e in events if e["event"] == "level_complete"]
    assert [e["frontier_rows"] for e in levels] == res.levels
    assert res.phases and "stats_fetch" in res.phases


def test_mesh_depth0_root_violation_replayable():
    from raft_tla_tpu.parallel.mesh import MeshBFSEngine
    inv = {"NoLeader": lambda st: jnp.all(st.role != LEADER)}
    viol_root = init_state(DIMS).replace(role=(2, 0, 0))
    eng = MeshBFSEngine(DIMS, invariants=inv,
                        constraint=build_constraint(DIMS, BOUNDS),
                        config=small_config(batch=16))
    res = eng.run([viol_root])
    assert res.stop_reason == "violation"
    assert eng.replay(res.violation.fingerprint) == [(-1, viol_root)]


# ---------------------------------------------------------------------------
# CLI integration (--events-out / --metrics-out / --progress-interval)

def test_cli_check_writes_events_and_metrics(tmp_path, capsys):
    from raft_tla_tpu.cli import main as cli_main
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    ev = str(tmp_path / "cli_events.jsonl")
    mo = str(tmp_path / "cli_metrics.json")
    rc = cli_main([
        "check", os.path.join(here, "configs/MCraft_bounded.cfg"),
        "--engine", "single", "--batch", "64",
        "--queue-capacity", str(1 << 12), "--seen-capacity", str(1 << 15),
        "--max-diameter", "2", "--events-out", ev, "--metrics-out", mo,
        "--progress-interval", "0"])
    assert rc == 0
    events = validate_run_events(ev)
    kinds = [e["event"] for e in events]
    assert kinds[0] == "run_start" and "level_complete" in kinds \
        and kinds[-1] == "run_end"
    snap = json.load(open(mo))
    assert snap["counters"]["engine/distinct"] == 22   # pinned L2 prefix
    assert any(k.startswith("phase/") for k in snap["histograms"])
    out = capsys.readouterr().out
    assert "distinct states    22" in out


# ---------------------------------------------------------------------------
# Span tracing (obs/tracing.py): recorder semantics, Chrome-trace shape,
# thread safety, and the phase_timer mirror.

def test_span_tracer_nesting_roundtrip(tmp_path):
    from raft_tla_tpu.obs import SpanTracer, validate_chrome_trace
    path = str(tmp_path / "t.json")
    tr = SpanTracer(path)
    with tr.span("outer", level=1):
        with tr.span("inner"):
            pass
    tr.instant("mark", n=3)
    assert tr.write() == path
    events = validate_chrome_trace(path)
    by_name = {e["name"]: e for e in events}
    # Metadata anchors for Perfetto + cross-process merge.
    assert by_name["process_name"]["ph"] == "M"
    assert "unix_seconds" in by_name["trace_start_unix"]["args"]
    outer, inner = by_name["outer"], by_name["inner"]
    assert outer["ph"] == inner["ph"] == "X"
    assert outer["args"] == {"level": 1}
    # Nesting is by ts/dur containment on one tid — inner inside outer.
    assert inner["tid"] == outer["tid"]
    assert outer["ts"] <= inner["ts"]
    assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"] + 1e-3
    assert by_name["mark"]["ph"] == "i"


def test_span_tracer_disabled_is_noop():
    from raft_tla_tpu.obs import SpanTracer
    tr = SpanTracer(None)
    with tr.span("x"):
        tr.instant("y")
    assert len(tr) == 0 and tr.write() is None and not tr.enabled


def test_span_tracer_thread_safety(tmp_path):
    from raft_tla_tpu.obs import SpanTracer, validate_chrome_trace
    path = str(tmp_path / "mt.json")
    tr = SpanTracer(path)
    N_THREADS, N_SPANS = 8, 50
    # All threads alive simultaneously (distinct idents — the OS reuses
    # an exited thread's ident) and recording concurrently.
    gate = threading.Barrier(N_THREADS)

    def work(i):
        gate.wait()
        for j in range(N_SPANS):
            with tr.span(f"w{i}", j=j):
                pass

    threads = [threading.Thread(target=work, args=(i,), name=f"worker-{i}")
               for i in range(N_THREADS)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    tr.write()
    events = validate_chrome_trace(path)
    spans = [e for e in events if e["ph"] == "X"]
    assert len(spans) == N_THREADS * N_SPANS      # none lost to races
    # Each thread got its own lane + exactly one thread_name metadata.
    tids = {e["tid"] for e in spans}
    assert len(tids) == N_THREADS
    names = [e for e in events if e["name"] == "thread_name"]
    assert len({e["tid"] for e in names}) == len(names)


def test_phase_timer_mirrors_into_tracer(tmp_path):
    from raft_tla_tpu.obs import SpanTracer, validate_chrome_trace
    mt = MetricsRegistry()
    mt.tracer = SpanTracer(str(tmp_path / "p.json"))
    with mt.phase_timer("roundtrip"):
        pass
    mt.tracer.write()
    events = validate_chrome_trace(str(tmp_path / "p.json"))
    assert any(e["name"] == "roundtrip" and e["ph"] == "X"
               for e in events)
    # Registry histogram and span agree it happened once.
    assert mt.snapshot()["histograms"]["phase/roundtrip"]["count"] == 1


def test_validate_chrome_trace_rejects(tmp_path):
    from raft_tla_tpu.obs import validate_chrome_trace
    p = tmp_path / "bad.json"
    with pytest.raises(FileNotFoundError):
        validate_chrome_trace(str(tmp_path / "missing.json"))
    p.write_text("{not json")
    with pytest.raises(ValueError, match="not valid JSON"):
        validate_chrome_trace(str(p))
    p.write_text('{"traceEvents": []}')        # object form: rejected
    with pytest.raises(ValueError, match="JSON array"):
        validate_chrome_trace(str(p))
    p.write_text('[{"ph": "X"}]')              # event without name
    with pytest.raises(ValueError, match="name"):
        validate_chrome_trace(str(p))
    p.write_text('[{"name": "a", "ph": "X"}]')  # non-metadata needs ts
    with pytest.raises(ValueError, match="ts"):
        validate_chrome_trace(str(p))
    p.write_text('[{"name": "m", "ph": "M"}]')  # metadata needs no ts
    assert validate_chrome_trace(str(p))


def test_validate_run_events_new_event_payloads(tmp_path):
    from raft_tla_tpu.obs import KNOWN_EVENTS
    assert {"chunk_profile", "coverage"} <= set(KNOWN_EVENTS)
    p = tmp_path / "ev.jsonl"
    ok = [{"event": "run_start", "ts": 0.0},
          {"event": "coverage", "ts": 1.0, "actions": {"Timeout": {}}},
          {"event": "chunk_profile", "ts": 2.0, "stages": {}},
          {"event": "run_end", "ts": 3.0}]
    p.write_text("".join(json.dumps(e) + "\n" for e in ok))
    assert len(validate_run_events(str(p))) == 4
    # A half-written emitter (payload missing) must fail the gate.
    bad = list(ok)
    bad[1] = {"event": "coverage", "ts": 1.0}
    p.write_text("".join(json.dumps(e) + "\n" for e in bad))
    with pytest.raises(ValueError, match="actions"):
        validate_run_events(str(p))
    bad = list(ok)
    bad[2] = {"event": "chunk_profile", "ts": 2.0, "stages": 7}
    p.write_text("".join(json.dumps(e) + "\n" for e in bad))
    with pytest.raises(ValueError, match="stages"):
        validate_run_events(str(p))


# ---------------------------------------------------------------------------
# Deep-profiling integration: --trace-out spans + --profile-chunks stage
# accounting + coverage, through a real (small) engine run.

def test_engine_trace_profile_coverage_end_to_end(tmp_path):
    from raft_tla_tpu.obs import validate_chrome_trace
    ev = str(tmp_path / "e.jsonl")
    trace = str(tmp_path / "trace.json")
    mt = MetricsRegistry()
    eng = BFSEngine(DIMS, constraint=build_constraint(DIMS, BOUNDS),
                    config=small_config(
                        max_diameter=3, events_out=ev, trace_out=trace,
                        profile_chunks_every=1, metrics=mt))
    res = eng.run([init_state(DIMS)])

    # -- Chrome trace: valid array, a span per level, >=1 chunk span,
    #    one run span bracketing everything.
    events = validate_chrome_trace(trace)
    levels = [e for e in events if e["name"] == "level"]
    assert len(levels) == len(res.levels)
    assert sum(1 for e in events if e["name"] == "chunk") >= 1
    runs = [e for e in events if e["name"] == "run"]
    assert len(runs) == 1 and runs[0]["ph"] == "X"

    # -- Profiler: per-stage histograms in the registry, consistent
    #    with the result's stage means (the wall-time-closure claim has
    #    its own post-compile test below — phase/profile here includes
    #    the stage programs' compile).
    snap = mt.snapshot()
    from raft_tla_tpu.obs.profile import STAGES
    hists = snap["histograms"]
    samples = hists["chunk_stage/total"]["count"]
    assert samples >= 1
    for s in STAGES:
        assert hists[f"chunk_stage/{s}"]["count"] == samples
        assert abs(hists[f"chunk_stage/{s}"]["total"] / samples
                   - res.chunk_stages[s]) < 1e-9
    assert set(res.chunk_stages) == set(STAGES) | {"total"}
    assert hists["phase/profile"]["total"] > 0

    # -- chunk_profile event with its stages payload.
    recs = validate_run_events(ev)
    prof_evs = [e for e in recs if e["event"] == "chunk_profile"]
    assert len(prof_evs) == 1
    assert set(prof_evs[0]["stages"]) == set(STAGES)

    # -- Coverage: per-family generated matches action_counts EXACTLY
    #    (one packed-stats source), distinct partitions distinct minus
    #    the root, disabled = expanded*size - generated.
    cov = res.coverage
    assert {a: v["generated"] for a, v in cov.items()} == res.action_counts
    assert sum(v["generated"] for v in cov.values()) == res.generated
    assert sum(v["distinct"] for v in cov.values()) == res.distinct - 1

    # -- run_end memory satellites: peak RSS + per-device stats list
    #    (CPU devices contribute {} but the field is present).
    end = recs[-1]
    assert end["event"] == "run_end"
    assert end["host_rss_peak_bytes"] is None \
        or end["host_rss_peak_bytes"] > 0
    assert isinstance(end["devices_memory"], list)
    assert len(end["devices_memory"]) >= 1


def test_profiling_is_observational(tmp_path):
    """Engine results are bit-identical with profiling on or off (the
    acceptance contract: the profiler re-expands samples on the side)."""
    plain = BFSEngine(DIMS, constraint=build_constraint(DIMS, BOUNDS),
                      config=small_config(max_diameter=3))
    res0 = plain.run([init_state(DIMS)])
    prof = BFSEngine(DIMS, constraint=build_constraint(DIMS, BOUNDS),
                     config=small_config(
                         max_diameter=3,
                         trace_out=str(tmp_path / "t.json"),
                         profile_chunks_every=1))
    res1 = prof.run([init_state(DIMS)])
    assert (res0.distinct, res0.generated, res0.levels) \
        == (res1.distinct, res1.generated, res1.levels)
    assert res0.action_counts == res1.action_counts
    assert res0.coverage == res1.coverage
    assert res1.chunk_stages and not res0.chunk_stages


def test_coverage_events_on_progress_interval(tmp_path, capsys):
    """A tiny progress interval fires a coverage event at every chunk
    boundary and prints the run-end coverage table on stderr."""
    ev = str(tmp_path / "e.jsonl")
    eng = BFSEngine(DIMS, constraint=build_constraint(DIMS, BOUNDS),
                    config=small_config(max_diameter=2, events_out=ev,
                                        progress_interval_seconds=1e-9))
    res = eng.run([init_state(DIMS)])
    recs = validate_run_events(ev)
    cov_evs = [e for e in recs if e["event"] == "coverage"]
    assert len(cov_evs) >= 2                  # interval events + final
    assert cov_evs[-1].get("final") is True
    total_gen = sum(v["generated"]
                    for v in cov_evs[-1]["actions"].values())
    assert total_gen == res.generated
    err = capsys.readouterr().err
    assert "coverage (actions:" in err
    assert "fpset load" in err                # enriched progress line


def test_stage_sum_accounts_for_staged_wall():
    """The fencing does not distort the decomposition: the sum of the
    fenced per-stage means is within 20% of the same staged pipeline's
    unfenced wall (dispatch all four programs, block once) — measured
    post-compile.  This is the acceptance criterion's closure claim in
    its hardware-honest form (the fused ``total`` row legitimately
    differs: XLA elides inter-stage materialization)."""
    import time

    import jax
    import numpy as np

    from raft_tla_tpu.obs.profile import (STAGES, ChunkProfiler,
                                          build_stage_programs)
    from raft_tla_tpu.models.schema import (encode_state, flatten_state,
                                            state_width)

    # B=256 empirically sits well clear of CPU timer jitter (the staged
    # wall is ~85 ms/iter; B=64's ~15 ms wobbles past 20% under load).
    B, K, CAP, N = 256, 4096, 1 << 14, 8
    root = np.asarray(
        flatten_state(encode_state(init_state(DIMS), DIMS), DIMS))
    rows = np.tile(root, (B, 1))
    valid = np.ones((B,), bool)

    prof = ChunkProfiler(DIMS, batch=B, lanes=K, seen_capacity=CAP)
    for _ in range(N):
        prof.sample(rows, valid)      # first call compiles (untimed)
    fenced_sum = sum(prof.stage_means()[s] for s in STAGES)

    # Unfenced reference on the already-compiled programs: fresh tables
    # (same load trajectory as the profiler's first samples).
    progs = build_stage_programs(DIMS, B, K)
    seen = progs["empty_seen"](CAP)
    qnext = jax.numpy.zeros(
        (progs["queue_rows"], state_width(DIMS)), jax.numpy.uint8)
    rows_j = jax.numpy.asarray(rows)
    valid_j = jax.numpy.asarray(valid)

    def staged_once(seen, qnext):
        cflat, lane_id, kvalid = progs["expand"](rows_j, valid_j)
        kstates, kh, kl = progs["fingerprint"](cflat, lane_id)
        seen, new, _f = progs["dedup_insert"](seen, kh, kl, kvalid)
        qnext = progs["enqueue"](qnext, kstates, new)
        return seen, qnext

    seen, qnext = staged_once(seen, qnext)     # warm (compile cache)
    jax.block_until_ready((seen, qnext))
    t0 = time.perf_counter()
    for _ in range(N):
        seen, qnext = staged_once(seen, qnext)
    jax.block_until_ready((seen, qnext))
    unfenced = (time.perf_counter() - t0) / N

    assert abs(fenced_sum - unfenced) <= 0.2 * max(fenced_sum, unfenced), \
        f"fenced sum {fenced_sum * 1e3:.2f} ms vs unfenced staged wall " \
        f"{unfenced * 1e3:.2f} ms"


def test_warm_engine_trace_resets_per_run(tmp_path):
    """A reused engine's second run rewrites the trace as ONE run —
    tracer.reset() at run start, not append (one trace file = one run)."""
    from raft_tla_tpu.obs import validate_chrome_trace
    trace = str(tmp_path / "t.json")
    eng = BFSEngine(DIMS, constraint=build_constraint(DIMS, BOUNDS),
                    config=small_config(max_diameter=1, trace_out=trace))
    eng.run([init_state(DIMS)])
    eng.run([init_state(DIMS)])
    events = validate_chrome_trace(trace)
    assert sum(1 for e in events if e["name"] == "run") == 1
    assert sum(1 for e in events if e["name"] == "trace_start_unix") == 1
