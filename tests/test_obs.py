"""Telemetry subsystem tests (obs/): registry semantics, event-log
schema, and the engine/CLI integrations.

The registry/event-log halves are tested standalone (they are zero-dep
and must stay importable without jax); the integration tests then assert
the ISSUE acceptance contract end-to-end: a run's JSONL log contains
run_start, level_complete events whose per-phase timings account for the
wall clock, and run_end — through both the BFSEngine API and the CLI.
"""

import json
import os
import threading

import jax.numpy as jnp
import pytest

from raft_tla_tpu.engine.bfs import BFSEngine, EngineConfig
from raft_tla_tpu.models.dims import LEADER, RaftDims
from raft_tla_tpu.models.invariants import Bounds, build_constraint
from raft_tla_tpu.models.pystate import init_state
from raft_tla_tpu.obs import (MetricsRegistry, RunEventLog,
                              events_path, phase_delta,
                              validate_run_events)

DIMS = RaftDims(n_servers=3, n_values=2, max_log=4, n_msg_slots=32)
BOUNDS = Bounds(max_term=2, max_log_len=1, max_msg_count=1)


def small_config(**kw):
    base = dict(batch=32, queue_capacity=1 << 12, seen_capacity=1 << 15,
                check_deadlock=False)
    base.update(kw)
    return EngineConfig(**base)


# ---------------------------------------------------------------------------
# MetricsRegistry semantics

def test_counters_accumulate_and_gauges_overwrite():
    mt = MetricsRegistry()
    mt.counter("a")
    mt.counter("a", 4)
    mt.gauge("g", 7)
    mt.gauge("g", 3)
    snap = mt.snapshot()
    assert snap["counters"]["a"] == 5
    assert snap["gauges"]["g"] == 3
    assert mt.counter_value("a") == 5
    assert mt.counter_value("missing") == 0


def test_histogram_summary_and_buckets():
    mt = MetricsRegistry()
    for v in (0.001, 0.002, 0.004, 10.0):
        mt.observe("h", v)
    h = mt.snapshot()["histograms"]["h"]
    assert h["count"] == 4
    assert h["min"] == 0.001 and h["max"] == 10.0
    assert abs(h["total"] - 10.007) < 1e-9
    assert abs(h["mean"] - 10.007 / 4) < 1e-9
    # 1-2-5 ladder: 0.001 -> "0.001" bucket, 0.002 -> "0.002",
    # 0.004 -> "0.005", 10.0 -> "10"; counts sum to the observation count.
    assert sum(h["buckets"].values()) == 4
    assert h["buckets"]["0.001"] == 1 and h["buckets"]["0.005"] == 1


def test_phase_timer_accumulates_into_phase_seconds():
    mt = MetricsRegistry()
    for _ in range(3):
        with mt.phase_timer("stage"):
            pass
    ph = mt.phase_seconds()
    assert set(ph) == {"stage"}
    assert ph["stage"] >= 0.0
    assert mt.snapshot()["histograms"]["phase/stage"]["count"] == 3
    # phase_timer records even when the body raises (finally-path).
    with pytest.raises(RuntimeError):
        with mt.phase_timer("stage"):
            raise RuntimeError("boom")
    assert mt.snapshot()["histograms"]["phase/stage"]["count"] == 4


def test_phase_delta_scopes_to_a_baseline():
    mt = MetricsRegistry()
    with mt.phase_timer("a"):
        pass
    base = mt.phase_seconds()
    with mt.phase_timer("b"):
        pass
    d = phase_delta(mt.phase_seconds(), base)
    assert "b" in d and "a" not in d     # a advanced by zero since base
    assert phase_delta({"x": 1.0}, None) == {"x": 1.0}


def test_registry_is_thread_safe():
    mt = MetricsRegistry()

    def work():
        for _ in range(1000):
            mt.counter("n")
            mt.observe("h", 0.001)

    threads = [threading.Thread(target=work) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert mt.counter_value("n") == 8000
    assert mt.snapshot()["histograms"]["h"]["count"] == 8000


# ---------------------------------------------------------------------------
# RunEventLog + validation

def test_event_log_writes_schema_lines(tmp_path):
    p = str(tmp_path / "ev.jsonl")
    with RunEventLog(p) as log:
        assert log.enabled
        log.emit("run_start", foo=1)
        log.emit("run_end", bar="x")
    recs = [json.loads(l) for l in open(p)]
    assert [r["event"] for r in recs] == ["run_start", "run_end"]
    for r in recs:
        assert "ts" in r and "elapsed_seconds" in r
    assert recs[0]["foo"] == 1 and recs[1]["bar"] == "x"
    assert validate_run_events(p)[0]["event"] == "run_start"


def test_event_log_null_sink_noops():
    log = RunEventLog(None)
    assert not log.enabled
    log.emit("run_start")           # must not raise
    log.close()


def test_validate_rejects_missing_malformed_and_incomplete(tmp_path):
    with pytest.raises(FileNotFoundError):
        validate_run_events(str(tmp_path / "nope.jsonl"))
    bad = tmp_path / "bad.jsonl"
    bad.write_text('{"event": "run_start", "ts": 1}\nnot json\n')
    with pytest.raises(ValueError, match="malformed"):
        validate_run_events(str(bad))
    partial = tmp_path / "partial.jsonl"
    partial.write_text('{"event": "run_start", "ts": 1}\n')
    with pytest.raises(ValueError, match="run_end"):
        validate_run_events(str(partial))


def test_events_path_resolution(tmp_path):
    assert events_path(None, None) is None
    assert events_path("/x/e.jsonl", "/ck") == "/x/e.jsonl"
    assert events_path(None, "/ck") == os.path.join("/ck", "events.jsonl")
    # Per-controller piece suffix under a process group.
    assert events_path("/x/e.jsonl", None, 1, 4) == "/x/e.p1of4.jsonl"


# ---------------------------------------------------------------------------
# Engine integration (the acceptance contract)

def run_and_load_events(tmp_path, engine_cls=BFSEngine, **cfg_kw):
    ev = str(tmp_path / "events.jsonl")
    eng = engine_cls(DIMS, constraint=build_constraint(DIMS, BOUNDS),
                     config=small_config(max_diameter=3, events_out=ev,
                                         **cfg_kw))
    res = eng.run([init_state(DIMS)])
    return res, validate_run_events(ev)


def test_engine_run_emits_complete_event_log(tmp_path):
    res, events = run_and_load_events(tmp_path)
    kinds = [e["event"] for e in events]
    assert kinds[0] == "run_start" and kinds[-1] == "run_end"
    levels = [e for e in events if e["event"] == "level_complete"]
    # Root-ingest level 0 plus the three expanded levels.
    assert len(levels) == len(res.levels) == 4
    assert [e["level"] for e in levels] == [0, 1, 2, 3]
    assert [e["frontier_rows"] for e in levels] == res.levels
    assert levels[-1]["distinct"] == res.distinct
    # Phase accounting: cumulative per-phase seconds + the unattributed
    # remainder == elapsed (exact by construction), AND the named phases
    # cover most of the wall — the breakdown is real, not rounding dust.
    last = levels[-1]
    ph = last["phase_seconds"]
    covered = sum(ph.values())
    assert abs(covered + last["unattributed_seconds"]
               - last["elapsed_seconds"]) < 0.05
    assert covered >= 0.5 * last["elapsed_seconds"]
    assert {"warmup", "chunk", "stats_fetch"} <= set(ph)
    # run_end carries the final snapshot, mirrored on the result object.
    end = events[-1]
    assert end["stop_reason"] == "diameter_budget" == res.stop_reason
    assert end["distinct"] == res.distinct
    assert res.phases and set(ph) <= set(res.phases)


def test_engine_metrics_registry_feeds_counters(tmp_path):
    ev = str(tmp_path / "e.jsonl")
    mt = MetricsRegistry()
    eng = BFSEngine(DIMS, constraint=build_constraint(DIMS, BOUNDS),
                    config=small_config(max_diameter=2, events_out=ev,
                                        metrics=mt))
    res = eng.run([init_state(DIMS)])
    assert eng.metrics is mt     # shared registry honored
    assert mt.counter_value("engine/distinct") == res.distinct
    assert mt.counter_value("engine/generated") == res.generated
    assert mt.snapshot()["gauges"]["engine/seen_size"] > 0


def test_violation_event_and_depth0_replay(tmp_path):
    # Mid-run violation -> a violation event in the log.
    ev = str(tmp_path / "v.jsonl")
    inv = {"NoLeader": lambda st: jnp.all(st.role != LEADER)}
    eng = BFSEngine(DIMS, invariants=inv,
                    constraint=build_constraint(DIMS, BOUNDS),
                    config=small_config(events_out=ev))
    s0 = init_state(DIMS).replace(
        role=(1, 0, 0), current_term=(2, 2, 2), voted_for=(1, 1, 1),
        votes_responded=(0b001, 0, 0), votes_granted=(0b001, 0, 0),
        messages=frozenset({((1, 1, 0, 2, 1, ()), 1)}))
    res = eng.run([s0])
    assert res.stop_reason == "violation"
    ev_kinds = [e["event"] for e in validate_run_events(ev)]
    assert "violation" in ev_kinds

    # Depth-0 violation (a root violates): replay() must return the
    # one-state trace instead of raising KeyError (ADVICE r5 /
    # mesh root-violation fix; same contract single-chip).
    viol_root = init_state(DIMS).replace(role=(2, 0, 0))
    eng2 = BFSEngine(DIMS, invariants=inv,
                     constraint=build_constraint(DIMS, BOUNDS),
                     config=small_config())
    res2 = eng2.run([viol_root])
    assert res2.stop_reason == "violation"
    steps = eng2.replay(res2.violation.fingerprint)
    assert steps == [(-1, viol_root)]


def test_mesh_engine_emits_events_too(tmp_path):
    from raft_tla_tpu.parallel.mesh import MeshBFSEngine
    res, events = run_and_load_events(tmp_path, engine_cls=MeshBFSEngine,
                                      batch=16)
    kinds = [e["event"] for e in events]
    assert kinds[0] == "run_start" and kinds[-1] == "run_end"
    levels = [e for e in events if e["event"] == "level_complete"]
    assert [e["frontier_rows"] for e in levels] == res.levels
    assert res.phases and "stats_fetch" in res.phases


def test_mesh_depth0_root_violation_replayable():
    from raft_tla_tpu.parallel.mesh import MeshBFSEngine
    inv = {"NoLeader": lambda st: jnp.all(st.role != LEADER)}
    viol_root = init_state(DIMS).replace(role=(2, 0, 0))
    eng = MeshBFSEngine(DIMS, invariants=inv,
                        constraint=build_constraint(DIMS, BOUNDS),
                        config=small_config(batch=16))
    res = eng.run([viol_root])
    assert res.stop_reason == "violation"
    assert eng.replay(res.violation.fingerprint) == [(-1, viol_root)]


# ---------------------------------------------------------------------------
# CLI integration (--events-out / --metrics-out / --progress-interval)

def test_cli_check_writes_events_and_metrics(tmp_path, capsys):
    from raft_tla_tpu.cli import main as cli_main
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    ev = str(tmp_path / "cli_events.jsonl")
    mo = str(tmp_path / "cli_metrics.json")
    rc = cli_main([
        "check", os.path.join(here, "configs/MCraft_bounded.cfg"),
        "--engine", "single", "--batch", "64",
        "--queue-capacity", str(1 << 12), "--seen-capacity", str(1 << 15),
        "--max-diameter", "2", "--events-out", ev, "--metrics-out", mo,
        "--progress-interval", "0"])
    assert rc == 0
    events = validate_run_events(ev)
    kinds = [e["event"] for e in events]
    assert kinds[0] == "run_start" and "level_complete" in kinds \
        and kinds[-1] == "run_end"
    snap = json.load(open(mo))
    assert snap["counters"]["engine/distinct"] == 22   # pinned L2 prefix
    assert any(k.startswith("phase/") for k in snap["histograms"])
    out = capsys.readouterr().out
    assert "distinct states    22" in out
