"""Serving-layer tests (ISSUE 13): the async job manager + server ops.

Two halves:

- **Manager unit tests** against a stub executor (no engine, no device):
  lifecycle + journal, least-recently-served fairness, bounded
  admission, cancel races and the cancelled-never-ran invariant, the
  result cache, and journal replay (queued jobs resume; the job a crash
  caught running is re-run once, then failed with a postmortem
  pointer).
- **Server integration tests** through the real checker service + real
  engine on the pinned MCraft_bounded profile: concurrent multi-tenant
  submits bit-identical to sequential direct checks, per-job scoped
  event logs, per-tenant metrics + SLO histograms agreeing between the
  stats op and the server-native HTTP /metrics endpoint, per-job watch
  streams, the idle-timeout-vs-watch regression, and restart replay.
"""

import json
import os
import socket
import threading
import time
import urllib.request

import pytest

from raft_tla_tpu import server as srv_mod
from raft_tla_tpu.serving import (JobManager, QueueFullError,
                                  TERMINAL_STATES)
from raft_tla_tpu.serving import jobs as jobs_mod

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CFG = os.path.join(REPO, "configs/MCraft_bounded.cfg")


# ---------------------------------------------------------------------------
# Manager unit tests (stub executor — no engine, no device lock).

def wait_terminal(m, timeout=30.0):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        doc = m.jobs_doc()
        if all(j["state"] in TERMINAL_STATES for j in doc["jobs"]):
            return doc
        time.sleep(0.02)
    raise AssertionError(f"jobs never settled: {m.jobs_doc()}")


def test_lifecycle_metrics_and_journal(tmp_path):
    ran = []

    def ex(req, job):
        ran.append(job["id"])
        return {"ok": True, "distinct": req["n"]}

    m = JobManager(str(tmp_path), executor=ex, slo_seconds=60.0)
    try:
        s = m.submit({"op": "check", "n": 7}, tenant="acme",
                     label="lbl")
        assert s["state"] == "queued" and s["tenant"] == "acme"
        doc = wait_terminal(m)
        assert doc["by_state"]["done"] == 1
        job = m.get(s["id"])
        assert job["state"] == "done" and job["has_result"]
        # Timestamps + derived durations are populated and ordered.
        assert job["created_ts"] <= job["admitted_ts"] \
            <= job["started_ts"] <= job["finished_ts"]
        assert job["queue_wait_seconds"] >= 0
        assert job["turnaround_seconds"] >= job["run_seconds"]
        assert m.result(s["id"]) == {"ok": True, "distinct": 7}
        snap = m.metrics.snapshot()
        assert snap["counters"]["jobs/submitted/acme"] == 1
        assert snap["counters"]["jobs/done/acme"] == 1
        assert snap["counters"]["jobs/slo_ok/acme"] == 1
        for h in ("jobs/queue_wait_seconds", "jobs/run_seconds",
                  "jobs/turnaround_seconds",
                  "jobs/turnaround_seconds/acme"):
            assert snap["histograms"][h]["count"] == 1, h
        assert snap["gauges"]["jobs/state/done"] == 1
        assert snap["gauges"]["jobs/queue_depth"] == 0
        # The journal replays to the same terminal picture, cleanly.
        jobs, results, problems = jobs_mod.replay(m.journal_path)
        assert jobs[s["id"]]["state"] == "done"
        assert results[s["id"]]["distinct"] == 7
        assert problems == []
    finally:
        m.close()


def test_fair_scheduling_least_recently_served(tmp_path):
    order = []
    gate = threading.Event()

    def ex(req, job):
        gate.wait(10)
        order.append((job["tenant"], req["n"]))
        return {"ok": True}

    # start=False: enqueue everything first, then run the loop, so the
    # pick order is purely the scheduler's.
    m = JobManager(str(tmp_path), executor=ex, start=False)
    for n in (1, 2, 3):
        m.submit({"op": "check", "n": n}, tenant="a")
    m.submit({"op": "check", "n": 10}, tenant="b")
    m.submit({"op": "check", "n": 11}, tenant="b")
    m.submit({"op": "check", "n": 20}, tenant="c")
    gate.set()
    m._thread = threading.Thread(target=m._loop, daemon=True)
    m._thread.start()
    try:
        wait_terminal(m)
        # Round-robin across tenants (a queue-flooding tenant cannot
        # starve b/c), FIFO within a tenant, ties by join order.
        assert order == [("a", 1), ("b", 10), ("c", 20),
                         ("a", 2), ("b", 11), ("a", 3)], order
    finally:
        m.close()


def test_queue_overflow_rejects_cleanly(tmp_path):
    def ex(req, job):
        return {"ok": True}

    m = JobManager(str(tmp_path), executor=ex, queue_capacity=2,
                   start=False)      # nothing drains: depth is exact
    try:
        m.submit({"op": "check"}, tenant="t")
        m.submit({"op": "check"}, tenant="t")
        with pytest.raises(QueueFullError, match="queue full"):
            m.submit({"op": "check"}, tenant="t")
        snap = m.metrics.snapshot()
        assert snap["counters"]["server/rejected/queue_full"] == 1
        assert snap["counters"]["jobs/rejected/t"] == 1
        # The reject did not corrupt the registry: still 2 queued.
        assert m.jobs_doc()["queue_depth"] == 2
    finally:
        m.close(wait=False)


def test_cancel_invariants_and_submit_cancel_races(tmp_path):
    executed = []
    gate = threading.Event()

    def ex(req, job):
        gate.wait(10)
        executed.append(job["id"])
        return {"ok": True}

    m = JobManager(str(tmp_path), executor=ex)
    try:
        first = m.submit({"op": "check"}, tenant="t")
        victims = [m.submit({"op": "check"}, tenant="t")
                   for _ in range(6)]
        # Concurrent cancels racing each other and the scheduler: each
        # job is cancelled by exactly one winner; double-cancel raises.
        errs = []

        def do_cancel(jid):
            try:
                m.cancel(jid)
            except (ValueError, KeyError) as e:
                errs.append(str(e))

        ts = [threading.Thread(target=do_cancel, args=(v["id"],))
              for v in victims for _ in (0, 1)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        gate.set()
        doc = wait_terminal(m)
        assert doc["by_state"]["cancelled"] == 6
        assert doc["by_state"]["done"] == 1
        # Exactly one cancel per job won; the other raced and raised.
        assert len(errs) == 6 and all("already cancelled" in e
                                      for e in errs)
        # THE invariant: a cancelled job never reached the executor,
        # has no result, and its terminal state stuck.
        assert executed == [first["id"]]
        for v in victims:
            job = m.get(v["id"])
            assert job["state"] == "cancelled"
            assert job["started_ts"] is None
            assert not job["has_result"]
            with pytest.raises(ValueError, match="no result"):
                m.result(v["id"])
        assert m.metrics.snapshot()["counters"]["jobs/cancelled/t"] == 6
    finally:
        m.close(wait=False)


def test_cancel_running_refused(tmp_path):
    gate = threading.Event()
    release = threading.Event()

    def ex(req, job):
        gate.set()
        release.wait(10)
        return {"ok": True}

    m = JobManager(str(tmp_path), executor=ex)
    try:
        s = m.submit({"op": "check"}, tenant="t")
        assert gate.wait(10)
        assert m.running_job_id() == s["id"]
        assert m.has_live_jobs()
        with pytest.raises(ValueError, match="not preemptible"):
            m.cancel(s["id"])
        release.set()
        wait_terminal(m)
        assert m.get(s["id"])["state"] == "done"
        with pytest.raises(ValueError, match="already done"):
            m.cancel(s["id"])
    finally:
        release.set()
        m.close()


def test_result_cache_hit_and_miss(tmp_path):
    calls = []

    def ex(req, job):
        calls.append(job["id"])
        return {"ok": True, "distinct": 42}

    m = JobManager(str(tmp_path), executor=ex)
    try:
        a = m.submit({"op": "check"}, tenant="t", cache_key="K")
        wait_terminal(m)
        b = m.submit({"op": "check"}, tenant="t", cache_key="K")
        c = m.submit({"op": "check"}, tenant="t", cache_key="K2")
        wait_terminal(m)
        assert len(calls) == 2          # a (miss) + c (miss); b hit
        jb = m.get(b["id"])
        assert jb["state"] == "done" and jb["cached"] is True
        assert m.get(a["id"])["cached"] is False
        assert m.result(b["id"]) == m.result(a["id"])
        snap = m.metrics.snapshot()["counters"]
        assert snap["jobs/result_cache/hits"] == 1
        assert snap["jobs/result_cache/misses"] == 2
        # Replay seeds the cache from done jobs: a restarted manager
        # still hits.
        m.close()
        m2 = JobManager(str(tmp_path), executor=ex)
        d = m2.submit({"op": "check"}, tenant="t", cache_key="K")
        wait_terminal(m2)
        assert m2.get(d["id"])["cached"] is True
        assert len(calls) == 2
        m2.close()
    finally:
        m.close(wait=False)


def test_failed_job_records_error(tmp_path):
    def ex(req, job):
        raise RuntimeError("engine exploded")

    m = JobManager(str(tmp_path), executor=ex)
    try:
        s = m.submit({"op": "check"}, tenant="t")
        wait_terminal(m)
        job = m.get(s["id"])
        assert job["state"] == "failed"
        assert "engine exploded" in job["error"]
        assert m.metrics.snapshot()["counters"]["jobs/failed/t"] == 1
        with pytest.raises(ValueError, match="engine exploded"):
            m.result(s["id"])
    finally:
        m.close()


def test_replay_resumes_queued_jobs(tmp_path):
    def ex(req, job):
        return {"ok": True, "n": req["n"]}

    m1 = JobManager(str(tmp_path), executor=ex, start=False)
    a = m1.submit({"op": "check", "n": 1}, tenant="t")
    b = m1.submit({"op": "check", "n": 2}, tenant="t")
    m1.close(wait=False)     # "restart": nothing ever ran
    m2 = JobManager(str(tmp_path), executor=ex)
    try:
        wait_terminal(m2)
        for s, n in ((a, 1), (b, 2)):
            job = m2.get(s["id"])
            assert job["state"] == "done"
            assert job["note"] == "resumed_after_restart"
            assert m2.result(s["id"])["n"] == n
    finally:
        m2.close()


def _craft_running_journal(tmp_path, restarts, with_postmortem):
    """A journal whose last word on job jX is ``running`` — the shape a
    crash leaves behind."""
    base = str(tmp_path)
    journal = os.path.join(base, "jobs.jsonl")
    job = jobs_mod.new_job("jX-cafe42", "acme", {"op": "check"})
    job["job_dir"] = os.path.join(base, job["id"])
    job["events_out"] = os.path.join(job["job_dir"], "events.jsonl")
    jobs_mod.append_record(journal, jobs_mod.submit_record(job))
    job["state"] = "running"
    job["restarts"] = restarts
    jobs_mod.append_record(
        journal, jobs_mod.state_record(
            job, patch={"restarts": restarts,
                        "started_ts": round(time.time(), 6)}))
    if with_postmortem:
        os.makedirs(job["job_dir"], exist_ok=True)
        with open(os.path.join(job["job_dir"], "postmortem.json"),
                  "w") as f:
            json.dump({"postmortem": True, "reason": "test"}, f)
    return job["id"]


def test_replay_reruns_job_caught_running_once(tmp_path):
    ran = []

    def ex(req, job):
        ran.append(job["id"])
        return {"ok": True}

    jid = _craft_running_journal(tmp_path, restarts=0,
                                 with_postmortem=False)
    m = JobManager(str(tmp_path), executor=ex)
    try:
        wait_terminal(m)
        job = m.get(jid)
        assert job["state"] == "done" and ran == [jid]
        assert job["restarts"] == 1
        assert job["note"] == "requeued_after_restart"
        assert m.metrics.snapshot()["counters"][
            "jobs/requeued_after_restart"] == 1
    finally:
        m.close()


def test_replay_fails_twice_lost_job_with_postmortem(tmp_path):
    ran = []

    def ex(req, job):
        ran.append(job["id"])
        return {"ok": True}

    hist = str(tmp_path / "ledger.jsonl")
    jid = _craft_running_journal(tmp_path, restarts=1,
                                 with_postmortem=True)
    m = JobManager(str(tmp_path), executor=ex, history_path=hist)
    try:
        job = m.get(jid)
        assert job["state"] == "failed" and ran == []
        assert "restart" in job["error"]
        assert job["postmortem"] and job["postmortem"].endswith(
            "postmortem.json")
        assert os.path.exists(job["postmortem"])
        # The loss is on the history ledger too (kind=server, job id).
        from raft_tla_tpu.obs import history as history_mod
        entries = history_mod.read_history(hist)
        assert entries[-1]["kind"] == "server"
        assert entries[-1]["verdict"] == "lost-after-restart"
        assert entries[-1]["job_id"] == jid
        assert entries[-1]["tenant"] == "acme"
    finally:
        m.close(wait=False)


def test_terminal_retention_evicts_oldest(tmp_path):
    def ex(req, job):
        return {"ok": True, "n": req["n"]}

    m = JobManager(str(tmp_path), executor=ex, max_terminal_jobs=2)
    try:
        subs = [m.submit({"op": "check", "n": n}, tenant="t")
                for n in range(4)]
        wait_terminal(m)
        doc = m.jobs_doc()
        assert doc["by_state"]["done"] == 2           # census pruned too
        kept = {j["id"] for j in doc["jobs"]}
        assert kept == {subs[2]["id"], subs[3]["id"]}  # oldest evicted
        with pytest.raises(KeyError):
            m.result(subs[0]["id"])
        assert m.result(subs[3]["id"])["n"] == 3
        assert m.metrics.snapshot()["counters"]["jobs/evicted"] == 2
    finally:
        m.close()


def test_journal_failure_does_not_kill_executor(tmp_path):
    """Review fix: a full disk (journal append OSError) must degrade to
    a counted durability loss — the executor keeps draining the queue
    and the in-memory registry stays consistent."""
    def ex(req, job):
        return {"ok": True}

    m = JobManager(str(tmp_path), executor=ex)
    try:
        # Point the journal at a DIRECTORY: every append now raises
        # IsADirectoryError (an OSError) inside submit + transitions.
        broken = tmp_path / "broken.jsonl"
        broken.mkdir()
        m.journal_path = str(broken)
        a = m.submit({"op": "check"}, tenant="t")
        b = m.submit({"op": "check"}, tenant="t")
        wait_terminal(m)
        assert m.get(a["id"])["state"] == "done"
        assert m.get(b["id"])["state"] == "done"
        assert m.metrics.snapshot()["counters"]["jobs/journal_errors"] \
            >= 2
    finally:
        m.close()


def test_requeued_job_queue_wait_excludes_downtime(tmp_path):
    """Review fix: a restart-requeued job's queue_wait must price THIS
    server's queue (enqueued_ts base), not the pre-crash run + the
    downtime (created_ts base) — turnaround still spans the whole
    customer wait."""
    def ex(req, job):
        return {"ok": True}

    jid = _craft_running_journal(tmp_path, restarts=0,
                                 with_postmortem=False)
    # Age the journal's created_ts far into the past.
    journal = os.path.join(str(tmp_path), "jobs.jsonl")
    lines = [json.loads(ln) for ln in open(journal)]
    lines[0]["job"]["created_ts"] -= 600.0
    lines[0]["job"]["enqueued_ts"] -= 600.0
    with open(journal, "w") as f:
        for rec in lines:
            f.write(json.dumps(rec) + "\n")
    m = JobManager(str(tmp_path), executor=ex)
    try:
        wait_terminal(m)
        job = m.get(jid)
        assert job["state"] == "done"
        assert job["queue_wait_seconds"] < 30, job["queue_wait_seconds"]
        assert job["turnaround_seconds"] > 590, job["turnaround_seconds"]
    finally:
        m.close()


def test_degraded_journal_replays_tolerantly(tmp_path):
    """Round-4 review fix: a journal degraded by best-effort writes (a
    torn trailing line, an orphan state record whose submit line was
    lost) must replay what it can and start — never permanently brick
    every restart on this job dir."""
    def ex(req, job):
        return {"ok": True}

    m1 = JobManager(str(tmp_path), executor=ex, start=False)
    good = m1.submit({"op": "check"}, tenant="t")
    m1.close(wait=False)
    journal = os.path.join(str(tmp_path), "jobs.jsonl")
    with open(journal, "a") as f:
        # Orphan state record (its submit line was lost to a full
        # disk) + a torn line from a crash mid-write.
        f.write(json.dumps({"rec": "state", "id": "j-lost",
                            "state": "running", "ts": 1.0}) + "\n")
        f.write('{"rec": "state", "id": "j-torn", "sta')
    jobs, _results, problems = jobs_mod.replay(journal)
    assert good["id"] in jobs
    assert len(problems) == 2, problems
    m2 = JobManager(str(tmp_path), executor=ex)
    try:
        wait_terminal(m2)
        assert m2.get(good["id"])["state"] == "done"
        assert m2.metrics.snapshot()["counters"][
            "jobs/journal_skipped"] == 2
    finally:
        m2.close()


def test_tenant_label_collision_gets_suffix(tmp_path):
    def ex(req, job):
        return {"ok": True}

    m = JobManager(str(tmp_path), executor=ex, start=False)
    try:
        m.submit({"op": "check"}, tenant="acme corp")
        m.submit({"op": "check"}, tenant="acme_corp")
        counters = m.metrics.snapshot()["counters"]
        labels = [k.split("/")[-1] for k in counters
                  if k.startswith("jobs/submitted/")]
        # Both tenants submitted once, into DISTINCT series.
        assert len(labels) == 2 and len(set(labels)) == 2, labels
        assert all(counters[f"jobs/submitted/{lb}"] == 1
                   for lb in labels)
    finally:
        m.close(wait=False)


def test_tenant_metric_labels_bounded(tmp_path):
    def ex(req, job):
        return {"ok": True}

    m = JobManager(str(tmp_path), executor=ex, tenant_cap=2,
                   start=False)
    try:
        m.submit({"op": "check"}, tenant="t/1 weird\nname")
        m.submit({"op": "check"}, tenant="t2")
        m.submit({"op": "check"}, tenant="t3-overflows-the-cap")
        counters = m.metrics.snapshot()["counters"]
        assert counters["jobs/submitted/t_1_weird_name"] == 1
        assert counters["jobs/submitted/t2"] == 1
        # Past the cap, tenants fold into one bounded label.
        assert counters["jobs/submitted/other"] == 1
    finally:
        m.close(wait=False)


# ---------------------------------------------------------------------------
# Server integration (real engine, pinned MCraft_bounded profile).

@pytest.fixture(scope="module")
def jobsrv(tmp_path_factory):
    base = tmp_path_factory.mktemp("serving")
    hist = str(base / "ledger.jsonl")
    srv = srv_mod.serve(port=0, job_dir=str(base / "jobs"),
                        history=hist, metrics_port=0)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield srv, hist
    srv.shutdown()
    srv.server_close()


def roundtrip(addr, req: dict) -> dict:
    with socket.create_connection(addr, timeout=600) as s:
        s.sendall((json.dumps(req) + "\n").encode())
        buf = b""
        while not buf.endswith(b"\n"):
            chunk = s.recv(65536)
            if not chunk:
                break
            buf += chunk
    return json.loads(buf)


BASE = {"op": "check", "cfg": CFG, "batch": 128,
        "queue_capacity": 1 << 12, "seen_capacity": 1 << 15,
        "check_deadlock": False}


def _wait_jobs_settled(addr, ids, timeout=600.0):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        doc = roundtrip(addr, {"op": "jobs"})
        byid = {j["id"]: j for j in doc["jobs"]}
        if all(byid[i]["state"] in TERMINAL_STATES for i in ids):
            return doc
        time.sleep(0.1)
    raise AssertionError(f"jobs never settled: {doc}")


def test_concurrent_multi_tenant_jobs_bitidentical(jobsrv):
    """ISSUE 13 acceptance: N concurrent jobs from >= 2 tenants all
    reach terminal states with results bit-identical to the same
    checks run sequentially through the blocking check op, while the
    jobs observably overlapped in queued/admitted states."""
    srv, _hist = jobsrv
    addr = srv.server_address
    seq3 = roundtrip(addr, dict(BASE, max_diameter=3))
    seq4 = roundtrip(addr, dict(BASE, max_diameter=4))
    assert seq3["ok"] and seq3["distinct"] == 113
    assert seq4["ok"] and seq4["distinct"] == 527
    subs = []
    for tenant, d in (("t1", 3), ("t2", 4), ("t1", 4)):
        r = roundtrip(addr, {"op": "submit", "tenant": tenant,
                             "job": dict(BASE, max_diameter=d)})
        assert r["ok"], r
        assert r["job"]["state"] == "queued"
        subs.append((r["job"]["id"], seq3 if d == 3 else seq4))
    # Overlap is observable: right after the submits, >= 2 jobs are
    # live at once and >= 1 is still waiting in the queue.
    doc = roundtrip(addr, {"op": "jobs"})
    live = [j for j in doc["jobs"]
            if j["state"] in ("queued", "admitted", "running")]
    assert len(live) >= 2, doc
    assert doc["queue_depth"] >= 1, doc
    doc = _wait_jobs_settled(addr, [jid for jid, _ in subs])
    assert doc["by_state"]["failed"] == 0
    for jid, want in subs:
        res = roundtrip(addr, {"op": "result", "job_id": jid})
        assert res["ok"], res
        got = res["result"]
        assert (got["distinct"], got["generated"], got["levels"]) \
            == (want["distinct"], want["generated"], want["levels"])


def test_per_job_event_logs_and_job_metrics(jobsrv):
    """Every executed job has a scoped JSONL event log that
    validate_run_events accepts, and the queue-wait/turnaround/SLO
    surfaces are populated in both the stats op and the server-native
    Prometheus endpoint (which must agree)."""
    from raft_tla_tpu.obs import parse_prometheus, validate_run_events
    from raft_tla_tpu.obs.expose import counter_sample
    srv, _hist = jobsrv
    addr = srv.server_address
    doc = roundtrip(addr, {"op": "jobs", "state": "done"})
    assert doc["jobs"], "run test_concurrent_multi_tenant_jobs first"
    for j in doc["jobs"]:
        evs = validate_run_events(j["events_out"])
        kinds = {e["event"] for e in evs}
        assert {"run_start", "run_end"} <= kinds, (j["id"], kinds)
        assert j["queue_wait_seconds"] is not None
        assert j["turnaround_seconds"] >= (j["run_seconds"] or 0)
    stats = roundtrip(addr, {"op": "stats"})
    counters = stats["metrics"]["counters"]
    hists = stats["metrics"]["histograms"]
    assert counters["jobs/submitted/t1"] >= 2
    assert counters["jobs/submitted/t2"] >= 1
    assert counters["jobs/done/t1"] >= 2
    assert hists["jobs/queue_wait_seconds"]["count"] >= 3
    assert hists["jobs/turnaround_seconds"]["count"] >= 3
    assert counters["jobs/slo_ok/t1"] + counters.get("jobs/slo_miss/t1",
                                                     0) >= 2
    # by-state gauges mirror the jobs op's registry view.
    alldoc = roundtrip(addr, {"op": "jobs"})
    assert stats["metrics"]["gauges"]["jobs/state/done"] \
        == alldoc["by_state"]["done"]
    # Server-native HTTP endpoint: same registry, same numbers.
    hp = srv.metrics_http.server_address
    body = urllib.request.urlopen(
        f"http://{hp[0]}:{hp[1]}/metrics", timeout=60).read().decode()
    samples = parse_prometheus(body)        # raises if invalid
    assert "raft_jobs_queue_wait_seconds_bucket" in samples
    assert counter_sample(samples, "jobs/submitted/t1") \
        == counters["jobs/submitted/t1"]
    jd = json.loads(urllib.request.urlopen(
        f"http://{hp[0]}:{hp[1]}/jobs", timeout=60).read())
    assert jd["ok"] and jd["by_state"]["done"] \
        == alldoc["by_state"]["done"]
    # /flight still serves (the watch console's poll target).
    fd = json.loads(urllib.request.urlopen(
        f"http://{hp[0]}:{hp[1]}/flight?last=4", timeout=60).read())
    assert fd["ok"] and "records" in fd


def test_server_history_ledger_served_traffic(jobsrv):
    """Satellite: server-executed checks land kind=server ledger
    entries (host_key + job/tenant ids) renderable by bench_history
    alongside CLI runs."""
    from raft_tla_tpu.obs import history as history_mod
    srv, hist = jobsrv
    entries = history_mod.read_history(hist)
    server_entries = [e for e in entries if e["kind"] == "server"]
    assert server_entries, "no served-traffic entries"
    jobful = [e for e in server_entries if e.get("job_id")]
    direct = [e for e in server_entries if e.get("job_id") is None]
    assert jobful and direct            # jobs AND blocking checks
    for e in server_entries:
        assert e["host_key"], e         # same-host comparability key
        assert e["verdict"] == "ok"
        assert e["distinct"] in (113, 527)
    assert {e["tenant"] for e in jobful} >= {"t1", "t2"}
    # The trajectory table renders them (kind column = server).
    table = history_mod.render_table(entries)
    assert "server" in table


def test_queue_overflow_op_rejects_cleanly():
    """Satellite: a queue-overflow submit answers a clean
    ``{"ok": false}`` line (the connection stays usable) and bumps the
    ``server/rejected/queue_full`` + per-tenant counters."""
    import tempfile
    srv = srv_mod.serve(port=0, job_dir=tempfile.mkdtemp(),
                        job_queue_capacity=1)
    srv.jobs.close(wait=False)          # executor off: depth is exact
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        addr = srv.server_address
        before = srv_mod._METRICS.counter_value(
            "server/rejected/queue_full")
        r1 = roundtrip(addr, {"op": "submit", "tenant": "flood",
                              "job": dict(BASE, max_diameter=2)})
        assert r1["ok"], r1
        r2 = roundtrip(addr, {"op": "submit", "tenant": "flood",
                              "job": dict(BASE, max_diameter=2)})
        assert r2["ok"] is False and "queue full" in r2["error"], r2
        counters = roundtrip(addr, {"op": "stats"})["metrics"][
            "counters"]
        assert counters["server/rejected/queue_full"] == before + 1
        assert counters["jobs/rejected/flood"] >= 1
        # The queued job is intact and the registry consistent.
        doc = roundtrip(addr, {"op": "jobs", "tenant": "flood"})
        assert doc["queue_depth"] >= 1
    finally:
        srv.shutdown()
        srv.server_close()


def test_cancel_op_terminal_invariants(jobsrv):
    """Cancel through the op: terminal-state invariants over the wire
    against a saturated executor."""
    srv, _hist = jobsrv
    addr = srv.server_address
    # Saturate: a wall-clock-budgeted job occupies the executor while
    # we queue more behind it.
    slow = dict(BASE, max_diameter=None, max_seconds=2.0)
    r1 = roundtrip(addr, {"op": "submit", "tenant": "t1", "job": slow})
    r2 = roundtrip(addr, {"op": "submit", "tenant": "t2",
                          "job": dict(BASE, max_diameter=3)})
    assert r1["ok"] and r2["ok"]
    c = roundtrip(addr, {"op": "cancel", "job_id": r2["job"]["id"]})
    if c["ok"]:          # r2 could already be running on a warm engine
        assert c["job"]["state"] == "cancelled"
        res = roundtrip(addr, {"op": "result",
                               "job_id": r2["job"]["id"]})
        assert not res["ok"] and "no result" in res["error"]
        # A cancelled job's terminal state sticks.
        again = roundtrip(addr, {"op": "cancel",
                                 "job_id": r2["job"]["id"]})
        assert not again["ok"] and "already cancelled" in again["error"]
    bogus = roundtrip(addr, {"op": "cancel", "job_id": "nope"})
    assert not bogus["ok"] and "unknown job" in bogus["error"]
    _wait_jobs_settled(addr, [r1["job"]["id"], r2["job"]["id"]])


def test_submit_cache_flag_and_rejects(jobsrv):
    srv, _hist = jobsrv
    addr = srv.server_address
    req = {"op": "submit", "tenant": "t1", "cache": True,
           "job": dict(BASE, max_diameter=2)}
    r1 = roundtrip(addr, req)
    assert r1["ok"], r1
    _wait_jobs_settled(addr, [r1["job"]["id"]])
    r2 = roundtrip(addr, req)
    assert r2["ok"], r2
    _wait_jobs_settled(addr, [r2["job"]["id"]])
    j2 = roundtrip(addr, {"op": "status", "job_id": r2["job"]["id"]})
    assert j2["job"]["cached"] is True
    a = roundtrip(addr, {"op": "result", "job_id": r1["job"]["id"]})
    b = roundtrip(addr, {"op": "result", "job_id": r2["job"]["id"]})
    assert a["result"] == b["result"]
    stats = roundtrip(addr, {"op": "stats"})
    assert stats["metrics"]["counters"]["jobs/result_cache/hits"] >= 1
    # A wall-clock-budgeted request is not cacheable.
    bad = roundtrip(addr, {"op": "submit", "cache": True,
                           "job": dict(BASE, max_seconds=1.0)})
    assert not bad["ok"] and "max_seconds" in bad["error"]
    # Submit without a proper inner job is a clean error.
    bad = roundtrip(addr, {"op": "submit", "job": {"op": "nope"}})
    assert not bad["ok"]


def test_watch_job_sees_own_progress(jobsrv):
    """Per-job run attach: the stream's snapshots carry THIS job's
    registry state, ring progress attributed via the job-tagged
    run_context (seq-ordered), and a done line with the terminal
    job."""
    from raft_tla_tpu.obs.flight import RECORDER
    srv, _hist = jobsrv
    addr = srv.server_address
    seq0 = RECORDER.seq()
    r = roundtrip(addr, {"op": "submit", "tenant": "t1",
                         "job": dict(BASE, max_diameter=6)})
    assert r["ok"], r
    jid = r["job"]["id"]
    got = []
    with socket.create_connection(addr, timeout=600) as s:
        s.sendall((json.dumps({"op": "watch", "job": jid,
                               "interval": 0.1}) + "\n").encode())
        s.settimeout(600)
        for line in s.makefile("rb"):
            rec = json.loads(line)
            got.append(rec)
            if rec.get("done"):
                break
    assert got[-1].get("done") and got[-1]["job"]["state"] == "done"
    snaps = [g["watch"] for g in got if "watch" in g]
    assert all(s["job"]["id"] == jid for s in snaps)
    tagged = [s for s in snaps if s.get("run")]
    assert tagged, "watch never saw the job's armed run"
    assert all(s["run"]["job_id"] == jid and s["run"]["tenant"] == "t1"
               for s in tagged)
    fresh = [s["progress"] for s in snaps
             if s.get("progress") and s["progress"]["seq"] > seq0]
    assert fresh, "watch never saw this job's progress lines"
    assert fresh[-1]["distinct"] > 0
    # Watching an unknown job is a clean one-line error.
    bad = roundtrip(addr, {"op": "watch", "job": "nope",
                           "interval": 0.1})
    assert not bad["ok"] and "unknown job" in bad["error"]


def test_watch_swarm_job_streams_progress_and_hunt(jobsrv):
    """ISSUE 20 satellite regression: a watch attached to a SWARM job
    streams that job's swarm_progress + hunt flight records with job
    attribution — records newer than the job-tagged run_context
    (seq-ordered), never a stale line from a previous run."""
    from raft_tla_tpu.obs.flight import RECORDER
    srv, _hist = jobsrv
    addr = srv.server_address
    cfg = os.path.join(REPO, "configs/MCraft_noleader.cfg")
    seq0 = RECORDER.seq()
    r = roundtrip(addr, {"op": "submit", "tenant": "t1",
                         "job": {"op": "check", "cfg": cfg,
                                 "mode": "swarm", "walks": 64,
                                 "max_depth": 12, "num_steps": 512,
                                 "seed": 5, "batch": 32,
                                 "progress_seconds": 0.2}})
    assert r["ok"], r
    jid = r["job"]["id"]
    got = []
    with socket.create_connection(addr, timeout=600) as s:
        s.sendall((json.dumps({"op": "watch", "job": jid,
                               "interval": 0.05}) + "\n").encode())
        s.settimeout(600)
        for line in s.makefile("rb"):
            rec = json.loads(line)
            got.append(rec)
            if rec.get("done"):
                break
    assert got[-1].get("done") and got[-1]["job"]["state"] == "done"
    snaps = [g["watch"] for g in got if "watch" in g]
    assert all(s["job"]["id"] == jid for s in snaps)
    tagged = [s for s in snaps if s.get("run")]
    assert tagged, "watch never saw the swarm job's armed run"
    assert all(s["run"]["job_id"] == jid for s in tagged)
    # Swarm progress lines, attributed and fresh (seq > submit point).
    prog = [s["progress"] for s in snaps
            if s.get("progress") and s["progress"]["seq"] > seq0]
    assert prog, "watch never saw the swarm job's progress lines"
    assert all(p["mode"] == "swarm" for p in prog)
    assert prog[-1]["steps"] > 0
    # Hunt snapshots ride the same stream with the same attribution.
    hunts = [s["hunt"] for s in snaps
             if s.get("hunt") and s["hunt"]["seq"] > seq0]
    assert hunts, "watch never saw the swarm job's hunt snapshots"
    assert all(0.0 <= h["saturation"] <= 1.0 for h in hunts)
    assert hunts[-1]["observations"] > 0
    # The job's result carries the full hunt report.
    res = roundtrip(addr, {"op": "result", "job_id": jid})
    assert res["ok"] and isinstance(res["result"]["hunt"], dict)


def test_watch_outlives_idle_timeout_while_job_queued():
    """ISSUE 13 satellite regression: a watcher attached to a QUEUED
    job must not be reaped while the job is alive — neither by the
    socket idle timeout nor by the count-0 no-run grace window, both
    set well below the queue wait here.  The stream closes only on the
    job's terminal state (a cancel, delivered to the watcher)."""
    import tempfile
    srv = srv_mod.serve(port=0, job_dir=tempfile.mkdtemp(),
                        idle_timeout_seconds=0.6)
    srv.watch_grace_seconds = 0.5
    srv.jobs.close(wait=False)      # executor off: jobs stay queued
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        addr = srv.server_address
        r = roundtrip(addr, {"op": "submit", "tenant": "t",
                             "job": dict(BASE, max_diameter=2)})
        assert r["ok"], r
        jid = r["job"]["id"]
        got = []
        t0 = time.monotonic()
        with socket.create_connection(addr, timeout=60) as s:
            s.sendall((json.dumps({"op": "watch", "job": jid,
                                   "interval": 0.15}) + "\n").encode())
            s.settimeout(60)
            f = s.makefile("rb")
            cancelled = False
            for line in f:
                rec = json.loads(line)
                got.append(rec)
                if rec.get("done"):
                    break
                elapsed = time.monotonic() - t0
                if elapsed > 1.6 and not cancelled:
                    # Well past both the 0.6 s idle timeout and the
                    # 0.5 s grace: still streaming.  Now end the job.
                    cancelled = True
                    c = roundtrip(addr, {"op": "cancel", "job_id": jid})
                    assert c["ok"], c
        elapsed = time.monotonic() - t0
        assert elapsed > 1.6, f"watcher reaped early ({elapsed:.2f}s)"
        assert got[-1].get("done")
        assert got[-1]["job"]["state"] == "cancelled"
        queued = [g for g in got
                  if g.get("watch", {}).get("job", {}).get("state")
                  == "queued"]
        assert len(queued) >= 6, len(queued)
        # Plain (runless) count-0 watch: live queued jobs also hold it
        # open past the grace window.
        r2 = roundtrip(addr, {"op": "submit", "tenant": "t",
                              "job": dict(BASE, max_diameter=2)})
        assert r2["ok"]
        n = 0
        t0 = time.monotonic()
        with socket.create_connection(addr, timeout=60) as s:
            s.sendall((json.dumps({"op": "watch", "interval": 0.15})
                       + "\n").encode())
            s.settimeout(60)
            f = s.makefile("rb")
            for line in f:
                rec = json.loads(line)
                if rec.get("done"):
                    pytest.fail("plain watch reaped while a job was "
                                "queued")
                n += 1
                if time.monotonic() - t0 > 1.5:
                    break               # still live well past grace
        assert n >= 6
    finally:
        srv.shutdown()
        srv.server_close()


def test_server_restart_replays_job_journal(tmp_path):
    """ISSUE 13 acceptance: a restart mid-queue replays the journal —
    queued jobs resume on the new server and reach terminal states
    with the pinned results, observable via the jobs op."""
    jobdir = str(tmp_path / "jobs")
    srv1 = srv_mod.serve(port=0, job_dir=jobdir)
    srv1.jobs.close(wait=False)     # executor off: simulate dying mid-queue
    t1 = threading.Thread(target=srv1.serve_forever, daemon=True)
    t1.start()
    addr1 = srv1.server_address
    subs = []
    for tenant, d in (("t1", 3), ("t2", 3)):
        r = roundtrip(addr1, {"op": "submit", "tenant": tenant,
                              "job": dict(BASE, max_diameter=d)})
        assert r["ok"], r
        subs.append(r["job"]["id"])
    doc = roundtrip(addr1, {"op": "jobs"})
    assert doc["by_state"]["queued"] == 2
    srv1.shutdown()
    srv1.server_close()
    # The restarted server on the same --job-dir resumes the queue.
    srv2 = srv_mod.serve(port=0, job_dir=jobdir)
    t2 = threading.Thread(target=srv2.serve_forever, daemon=True)
    t2.start()
    try:
        addr2 = srv2.server_address
        doc = _wait_jobs_settled(addr2, subs)
        assert doc["by_state"]["done"] == 2, doc
        for jid in subs:
            st = roundtrip(addr2, {"op": "status", "job_id": jid})
            assert st["job"]["note"] == "resumed_after_restart"
            res = roundtrip(addr2, {"op": "result", "job_id": jid})
            assert res["result"]["distinct"] == 113
    finally:
        srv2.shutdown()
        srv2.server_close()
