"""Mesh-sharded BFS tests on the virtual 8-device CPU mesh.

The distributed engine must produce bit-identical statistics to the
single-device engine (and hence the oracle): fingerprint-owner dedup over
all_to_all must count each global state exactly once regardless of which
chip generates it, and the union of per-chip FPSet shards must behave as one
set.
"""

import os

import jax
import pytest

if os.cpu_count() == 1:
    # On a single-core host, jaxlib 0.4.36's CPU client nondeterministically
    # corrupts the glibc heap while executing the 8-virtual-device sharded
    # programs (~50% of module runs; concentrated in the shard-growth
    # dryrun pin, also seen as wrong-resume shard frontiers on the
    # checkpoint test and a "corrupted double-linked list" abort).
    # Observed 2026-08-07 on an untouched seed tree across every cache
    # state (cold, warm, suite-pure, disabled), test order, process
    # isolation, and both CPU runtimes (thunk and legacy) — a native race
    # in concurrent device threads that only a multi-core host avoids.  A
    # crashed pytest process loses the whole invocation's results, so the
    # module skips rather than coin-flips; CI and any multi-core dev host
    # run it in full.
    pytest.skip("8-virtual-device mesh programs crash jaxlib 0.4.36's CPU "
                "client on single-core hosts (native race; see module "
                "comment)", allow_module_level=True)

from raft_tla_tpu.engine.bfs import BFSEngine, EngineConfig
from raft_tla_tpu.models import oracle as orc
from raft_tla_tpu.models.dims import LEADER, RaftDims
from raft_tla_tpu.models.invariants import (Bounds, build_constraint,
                                            constraint_py)
from raft_tla_tpu.models.pystate import init_state
from raft_tla_tpu.parallel.mesh import MeshBFSEngine

DIMS = RaftDims(n_servers=3, n_values=2, max_log=4, n_msg_slots=24)
BOUNDS = Bounds(max_term=2, max_log_len=1, max_msg_count=1)


@pytest.fixture(scope="module", autouse=True)
def _compile_fresh_no_aot_cache():
    """Force this module's mesh programs to COMPILE, never AOT-load.

    jaxlib 0.4.36's CPU client is heap-layout fragile under the big
    sharded programs (see utils/platform.py): when the mesh chunk /
    resume executables come back through the persistent-cache AOT
    deserializer instead of the compiler, this module reproduces
    wrong-resume garbage (checkpoint resume reading corrupt shard
    frontiers) followed by a glibc "corrupted double-linked list"
    abort in test_dryrun_ground_truth_pinned — even with a suite-pure
    cache written by a green cold run of this very suite (observed
    2026-08-07 on a single-core host; compile path green every time,
    load path corrupt every time).  Cache namespacing (conftest's
    "unit8" tag) is not enough: the load path itself is the hazard for
    THIS module, so it opts out of the persistent cache entirely and
    restores it on exit.

    The opt-out is necessary but not sufficient: after a few hundred
    other tests have warm-loaded their programs, the corruption fires
    here even on the compile path, so tier-1 (ROADMAP.md) additionally
    runs this module as its own pytest invocation in a fresh process.
    On single-core hosts neither helps — the module-level skip above
    applies there instead."""
    old = jax.config.jax_compilation_cache_dir
    jax.config.update("jax_compilation_cache_dir", None)
    yield
    jax.config.update("jax_compilation_cache_dir", old)


def test_eight_device_mesh_available():
    assert len(jax.devices()) == 8


def test_mesh_counts_match_single_device():
    cons = build_constraint(DIMS, BOUNDS)
    mesh_eng = MeshBFSEngine(
        DIMS, constraint=cons,
        config=EngineConfig(batch=16, queue_capacity=1 << 12,
                            seen_capacity=1 << 15, check_deadlock=False,
                            max_diameter=3))
    mres = mesh_eng.run([init_state(DIMS)])
    want = orc.bfs([init_state(DIMS)], DIMS, constraint=constraint_py(BOUNDS),
                   check_deadlock=False, max_levels=3)
    assert mres.distinct == want.distinct_states
    assert mres.levels == want.levels
    assert mres.generated == want.generated_states


def test_mesh_trace_replay():
    import jax.numpy as jnp
    cons = build_constraint(DIMS, Bounds(max_term=3, max_log_len=1,
                                         max_msg_count=1))
    s0 = init_state(DIMS).replace(
        role=(1, 0, 0), current_term=(2, 2, 2), voted_for=(1, 1, 1),
        votes_responded=(0b001, 0, 0), votes_granted=(0b001, 0, 0),
        messages=frozenset({((1, 1, 0, 2, 1, ()), 1)}))
    eng = MeshBFSEngine(
        DIMS, invariants={"NoLeader": lambda st: jnp.all(st.role != LEADER)},
        constraint=cons,
        config=EngineConfig(batch=16, queue_capacity=1 << 12,
                            seen_capacity=1 << 15, check_deadlock=False))
    res = eng.run([s0])
    assert res.stop_reason == "violation"
    steps = eng.replay(res.violation.fingerprint)
    assert steps[-1][1] == res.violation.state
    for (g_prev, s_prev), (g, s_next) in zip(steps, steps[1:]):
        assert s_next in orc.successor_set(s_prev, DIMS)


def small_mesh_config(**kw):
    base = dict(batch=16, queue_capacity=1 << 12, seen_capacity=1 << 15,
                check_deadlock=False)
    base.update(kw)
    return EngineConfig(**base)


def test_mesh_spill_to_host_matches_roomy():
    """Per-chip queue overflow must drain to the host pool (and re-upload
    balanced) without changing any count — single-chip parity for the
    spill path the round-2 mesh engine lacked."""
    cons = build_constraint(DIMS, BOUNDS)
    want = MeshBFSEngine(DIMS, constraint=cons,
                         config=small_mesh_config(max_diameter=4)).run(
        [init_state(DIMS)])
    # queue_capacity 8/chip rounds up to one batch (= B*G watermark 0):
    # every chunk spills.
    got = MeshBFSEngine(DIMS, constraint=cons,
                        config=small_mesh_config(
                            batch=8, queue_capacity=8, sync_every=4,
                            max_diameter=4)).run([init_state(DIMS)])
    assert got.distinct == want.distinct
    assert got.levels == want.levels
    assert got.generated == want.generated


def test_mesh_seen_set_grows():
    """Shard growth (host rehash at half load) must keep counts exact."""
    cons = build_constraint(DIMS, BOUNDS)
    want = MeshBFSEngine(DIMS, constraint=cons,
                         config=small_mesh_config(max_diameter=3)).run(
        [init_state(DIMS)])
    small = MeshBFSEngine(DIMS, constraint=cons,
                          config=small_mesh_config(
                              batch=8, sync_every=1, seen_capacity=8,
                              max_diameter=3))
    got = small.run([init_state(DIMS)])
    assert got.distinct == want.distinct
    assert got.levels == want.levels
    # (Per-shard capacity is floored at fpset's minimum, so this run does
    # not grow; growth evidence is asserted by
    # test_dryrun_ground_truth_pinned.)


def test_mesh_checkpoint_resumes_on_mesh_and_single(tmp_path):
    """Mesh checkpoints use the single-chip snapshot format: a run
    interrupted on the mesh must resume bit-exactly BOTH on a mesh (even a
    different device count) and on the single-chip engine."""
    cons = build_constraint(DIMS, BOUNDS)
    want = MeshBFSEngine(DIMS, constraint=cons,
                         config=small_mesh_config(max_diameter=4)).run(
        [init_state(DIMS)])
    ck = str(tmp_path / "ck")
    MeshBFSEngine(DIMS, constraint=cons,
                  config=small_mesh_config(
                      max_diameter=3, record_trace=False,
                      checkpoint_dir=ck)).run([init_state(DIMS)])
    from raft_tla_tpu.engine import checkpoint as ckpt_mod
    path = ckpt_mod.latest(ck)
    assert path is not None

    import jax as _jax
    got_mesh = MeshBFSEngine(
        DIMS, constraint=cons,
        config=small_mesh_config(max_diameter=4, record_trace=False),
        devices=_jax.devices()[:4]).run(resume=path)
    assert got_mesh.distinct == want.distinct
    assert got_mesh.levels == want.levels
    assert got_mesh.diameter == want.diameter

    got_single = BFSEngine(
        DIMS, constraint=cons,
        config=small_mesh_config(max_diameter=4, record_trace=False,
                                 queue_capacity=1 << 13)).run(resume=path)
    assert got_single.distinct == want.distinct
    assert got_single.levels == want.levels


def test_mesh_disk_backed_spill_matches_ram(tmp_path):
    """spill_dir on the mesh engine: tiny per-chip queues force constant
    drains through the disk-backed pool (and the oversized-segment
    re-insert path); counts must match the roomy in-RAM run, and all
    segment files must be consumed."""
    cons = build_constraint(DIMS, BOUNDS)
    want = MeshBFSEngine(DIMS, constraint=cons,
                         config=small_mesh_config(max_diameter=4)).run(
        [init_state(DIMS)])
    spill = tmp_path / "spill"
    got = MeshBFSEngine(DIMS, constraint=cons,
                        config=small_mesh_config(
                            batch=8, queue_capacity=8, sync_every=4,
                            spill_dir=str(spill),
                            max_diameter=4)).run([init_state(DIMS)])
    assert got.distinct == want.distinct
    assert got.levels == want.levels
    assert got.generated == want.generated
    import gc
    gc.collect()
    assert list(spill.iterdir()) == []


def test_mesh_progress_limiting_with_tiny_compact_buffer():
    """P-limiting under the pmin-replicated offset advance (ops/
    compact.py reduce_p): a compact buffer too small for a batch's
    fan-out must not change any count on the mesh — every chip advances
    by the same replicated P, so lockstep trip counts hold even when
    chips see different fan-outs."""
    cons = build_constraint(DIMS, BOUNDS)
    want = MeshBFSEngine(DIMS, constraint=cons,
                         config=small_mesh_config(max_diameter=3)).run(
        [init_state(DIMS)])
    got = MeshBFSEngine(DIMS, constraint=cons,
                        config=small_mesh_config(
                            batch=32, compact_lanes=1,
                            max_diameter=3)).run([init_state(DIMS)])
    assert got.distinct == want.distinct
    assert got.levels == want.levels
    assert got.generated == want.generated


def test_mesh_order_independence():
    """Root permutation and batch-boundary changes must not change mesh
    counts (guards the owner-routed all_to_all dedup)."""
    cons = build_constraint(DIMS, BOUNDS)
    s = init_state(DIMS)
    roots = [s,
             s.replace(role=(1, 0, 0), current_term=(2, 1, 1)),
             s.replace(role=(0, 1, 0), current_term=(1, 2, 1)),
             s.replace(role=(2, 0, 0), votes_granted=(0b11, 0, 0))]
    want = MeshBFSEngine(DIMS, constraint=cons,
                         config=small_mesh_config(max_diameter=2)).run(
        list(roots))
    got = MeshBFSEngine(DIMS, constraint=cons,
                        config=small_mesh_config(batch=8, max_diameter=2)
                        ).run([roots[i] for i in (3, 1, 0, 2)])
    assert got.distinct == want.distinct
    assert got.levels == want.levels
    assert got.generated == want.generated


def test_dryrun_ground_truth_pinned():
    """The driver's dryrun_multichip model (__graft_entry__.py) asserts
    46,553 distinct / diameter 31 — re-derive that constant here from BOTH
    the independent Python oracle and the mesh engine, so kernel or oracle
    drift fails the suite before it fails a driver-side dryrun (SURVEY §4
    differential contract)."""
    dims = RaftDims(n_servers=2, n_values=1, max_log=2, n_msg_slots=8)
    bounds = Bounds(max_term=2, max_log_len=1, max_msg_count=1,
                    max_in_flight=2)
    want = orc.bfs([init_state(dims)], dims,
                   constraint=constraint_py(bounds), check_deadlock=False)
    assert want.distinct_states == 46553
    assert len(want.levels) - 1 == 31    # diameter
    # Exactly the driver's dryrun_multichip config (__graft_entry__.py):
    # batch 64 keeps the per-shard table floor at 8K=8192, so the 46.5k-key
    # run crosses the half-load threshold and exercises shard growth too.
    eng = MeshBFSEngine(
        dims, constraint=build_constraint(dims, bounds),
        config=EngineConfig(batch=64, queue_capacity=1 << 12,
                            seen_capacity=1 << 16, check_deadlock=False,
                            record_trace=False, sync_every=8))
    res = eng.run([init_state(dims)])
    assert res.stop_reason == "exhausted"
    assert res.distinct == 46553 and res.diameter == 31
    assert res.generated == want.generated_states
    # 46,553 keys over 8 shards in 8k-per-shard tables: shard growth must
    # fire and be recorded as (total-capacity-after, stall seconds).
    caps = [c for c, _s in res.growth_stalls]
    assert caps and caps == sorted(caps) and len(set(caps)) == len(caps)


def test_mesh_distinct_budget_stops_run(tmp_path):
    """The TLCGet("distinct") budget must stop the mesh engine too (the
    counters are psum-accumulated on the host side, same as single-chip)."""
    from raft_tla_tpu.engine.check import initial_states, make_engine
    from tests.test_cfg import _write_exit_model
    from raft_tla_tpu.utils.cfg import load_config
    setup = load_config(_write_exit_model(tmp_path, "distinct", 500))
    eng = make_engine(setup, EngineConfig(
        batch=16, queue_capacity=1 << 13, seen_capacity=1 << 16,
        record_trace=False, sync_every=4), engine_cls=MeshBFSEngine)
    res = eng.run(initial_states(setup))
    assert res.stop_reason == "distinct_budget"
    assert res.distinct > 500


def test_mesh_progress_lines_emitted(capfd):
    eng = MeshBFSEngine(DIMS, constraint=build_constraint(DIMS, BOUNDS),
                        config=small_mesh_config(
                            max_diameter=3, progress_interval_seconds=1e-6))
    eng.run([init_state(DIMS)])
    err = capfd.readouterr().err
    assert "progress:" in err and "queue" in err
