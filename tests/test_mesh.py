"""Mesh-sharded BFS tests on the virtual 8-device CPU mesh.

The distributed engine must produce bit-identical statistics to the
single-device engine (and hence the oracle): fingerprint-owner dedup over
all_to_all must count each global state exactly once regardless of which
chip generates it, and the union of per-chip FPSet shards must behave as one
set.
"""

import jax
import pytest

from raft_tla_tpu.engine.bfs import BFSEngine, EngineConfig
from raft_tla_tpu.models import oracle as orc
from raft_tla_tpu.models.dims import LEADER, RaftDims
from raft_tla_tpu.models.invariants import (Bounds, build_constraint,
                                            constraint_py)
from raft_tla_tpu.models.pystate import init_state
from raft_tla_tpu.parallel.mesh import MeshBFSEngine

DIMS = RaftDims(n_servers=3, n_values=2, max_log=4, n_msg_slots=24)
BOUNDS = Bounds(max_term=2, max_log_len=1, max_msg_count=1)


def test_eight_device_mesh_available():
    assert len(jax.devices()) == 8


def test_mesh_counts_match_single_device():
    cons = build_constraint(DIMS, BOUNDS)
    mesh_eng = MeshBFSEngine(
        DIMS, constraint=cons,
        config=EngineConfig(batch=16, queue_capacity=1 << 12,
                            seen_capacity=1 << 15, check_deadlock=False,
                            max_diameter=3))
    mres = mesh_eng.run([init_state(DIMS)])
    want = orc.bfs([init_state(DIMS)], DIMS, constraint=constraint_py(BOUNDS),
                   check_deadlock=False, max_levels=3)
    assert mres.distinct == want.distinct_states
    assert mres.levels == want.levels
    assert mres.generated == want.generated_states


def test_mesh_trace_replay():
    import jax.numpy as jnp
    cons = build_constraint(DIMS, Bounds(max_term=3, max_log_len=1,
                                         max_msg_count=1))
    s0 = init_state(DIMS).replace(
        role=(1, 0, 0), current_term=(2, 2, 2), voted_for=(1, 1, 1),
        votes_responded=(0b001, 0, 0), votes_granted=(0b001, 0, 0),
        messages=frozenset({((1, 1, 0, 2, 1, ()), 1)}))
    eng = MeshBFSEngine(
        DIMS, invariants={"NoLeader": lambda st: jnp.all(st.role != LEADER)},
        constraint=cons,
        config=EngineConfig(batch=16, queue_capacity=1 << 12,
                            seen_capacity=1 << 15, check_deadlock=False))
    res = eng.run([s0])
    assert res.stop_reason == "violation"
    steps = eng.replay(res.violation.fingerprint)
    assert steps[-1][1] == res.violation.state
    for (g_prev, s_prev), (g, s_next) in zip(steps, steps[1:]):
        assert s_next in orc.successor_set(s_prev, DIMS)
