"""Performance observatory tests (obs/perf.py + obs/roofline.py).

Three contracts from the ISSUE acceptance criteria:

- the static roofline's byte model is cross-checked against HAND-
  COMPUTED traffic for the fingerprint (v1) and compact (v3) stages on
  the seed dims — the walk's windowed-gather/full-read rules are pinned
  to arithmetic a reviewer can redo on paper;
- launch counts are PINNED per pipeline (v1/v2/v3) on the tiny model:
  the counts are deterministic jaxpr device-op totals, so a chunk-body
  change that un-fuses a stage (e.g. the v3 fused tail silently falling
  back to the split insert+enqueue, +128 ops here) moves the pin and
  fails CI instead of landing as an invisible slowdown.  Re-pin ONLY
  after confirming the delta is intentional (a jax upgrade that
  re-lowers primitives also legitimately moves these);
- engine counts are bit-identical with the perf surfaces on or off,
  single-chip and mesh (the observational contract every obs leg
  keeps).

This module traces full chunk programs through the analyzer walk —
trace-churn-heavy, so it runs in tests/conftest.py's trailing slot with
the other analyzer modules.
"""

import gzip
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raft_tla_tpu.engine.bfs import BFSEngine, EngineConfig
from raft_tla_tpu.models.dims import RaftDims
from raft_tla_tpu.models.invariants import Bounds, build_constraint
from raft_tla_tpu.models.pystate import init_state
from raft_tla_tpu.obs import validate_run_events

# obs.perf / obs.roofline are imported INSIDE the tests, not here:
# pytest imports every test module at collection time, BEFORE any test
# runs, so a module-level import would inject the new modules into the
# heap history of every pre-existing test — the perturbation class the
# conftest trace-heavy-last reorder exists to prevent (jaxlib's CPU
# client is heap-layout fragile under the big mesh tests; kept off the
# collection path as a precaution).


def _roofline():
    from raft_tla_tpu.obs import roofline
    return roofline

DIMS = RaftDims(n_servers=3, n_values=2, max_log=4, n_msg_slots=32)
BOUNDS = Bounds(max_term=2, max_log_len=1, max_msg_count=1)
B, K = 32, 512


def small_config(**kw):
    base = dict(batch=B, queue_capacity=1 << 12, seen_capacity=1 << 15,
                check_deadlock=False)
    base.update(kw)
    return EngineConfig(**base)


# ---------------------------------------------------------------------------
# Roofline byte model vs hand-computed traffic


def test_fingerprint_stage_bytes_match_hand_computed():
    """v1 fingerprint stage: every candidate field array [B*G, ...] is
    consumed ONLY through the lane_id gather, so the modeled read is K
    window rows per field (+ the K-lane index vector); the write is the
    gathered K-lane struct + the two 32-bit hash lanes.  The walk must
    reproduce that arithmetic exactly — windowed-read attribution is
    the whole point of reusing the interp shape walk."""
    import jax.tree_util as jtu

    from raft_tla_tpu.models.schema import state_width
    from raft_tla_tpu.obs.profile import build_stage_programs
    progs = build_stage_programs(DIMS, B, K)
    rows = jax.ShapeDtypeStruct((B, state_width(DIMS)), jnp.uint8)
    valid = jax.ShapeDtypeStruct((B,), jnp.bool_)
    cflat, lane_id, _kvalid = jax.eval_shape(progs["expand"], rows, valid)

    roofline = _roofline()
    traffic = roofline.stage_traffic(DIMS, B, K, pipeline="v1")
    got = traffic["fingerprint"]

    def nbytes(a):
        n = 1
        for d in a.shape:
            n *= d
        return n * np.dtype(a.dtype).itemsize

    leaves, _ = jtu.tree_flatten(cflat)
    # reads: K gathered rows per field (row bytes = leaf bytes / B*G
    # lanes) + the [K] int32 lane_id itself.
    exp_read = sum(K * (nbytes(a) // a.shape[0]) for a in leaves) + K * 4
    kstates, kh, kl = jax.eval_shape(progs["fingerprint"], cflat, lane_id)
    wleaves, _ = jtu.tree_flatten(kstates)
    exp_write = sum(nbytes(a) for a in wleaves) + nbytes(kh) + nbytes(kl)
    assert got["bytes_read"] == exp_read
    assert got["bytes_written"] == exp_write


def test_compact_stage_bytes_match_hand_computed():
    """v3 compact stage: reads the [B, G] bool enabled mask (1 byte per
    lane), writes the [K] int32 lane ids + [K] bool validity."""
    roofline = _roofline()
    traffic = roofline.stage_traffic(DIMS, B, K, pipeline="v3")
    got = traffic["compact"]
    assert got["bytes_read"] == B * DIMS.n_instances
    assert got["bytes_written"] == K * 4 + K


def test_roofline_rows_and_advisor():
    """Floors + measured means join into fractions; the advisor ranks by
    launch tax + headroom and names a stage."""
    roofline = _roofline()
    traffic = roofline.stage_traffic(DIMS, B, K, pipeline="v1")
    peak = {"bytes_per_sec": 100e9, "source": "test"}
    means = {s: 0.010 for s in traffic}      # 10 ms/stage measured
    rows = roofline.build_roofline(traffic, means, peak)
    for s, r in rows.items():
        assert r["floor_seconds"] == pytest.approx(
            traffic[s]["bytes_total"] / 100e9, abs=1e-9)
        assert r["bandwidth_fraction"] == pytest.approx(
            traffic[s]["bytes_total"] / 0.010 / 100e9, abs=1e-6)
        assert r["headroom_seconds"] <= 0.010
    adv = roofline.advise(rows, overhead_seconds=5e-6)
    assert adv["top"] in traffic
    assert adv["top"] in adv["verdict"]
    # With near-equal headrooms the launch tax breaks the tie toward
    # the op-heaviest stage (expand: the hundreds-of-kernels story).
    assert adv["ranking"][0]["score_seconds"] >= \
        adv["ranking"][-1]["score_seconds"]


# ---------------------------------------------------------------------------
# Pinned launch counts (the CI un-fusing gate)

#: Deterministic jaxpr device-op counts of the REAL chunk programs on
#: the tiny model above (batch=32, trace on, deadlock off).  These move
#: only when the chunk body (or a jax upgrade's lowering) changes — an
#: intentional change re-pins with the delta explained in its PR.  The
#: v3 pin sits BELOW v2 by the fused tail's retired split-path ops: the
#: fused probe/insert->enqueue kernel replacing the XLA insert + row
#: scatter is directly visible here.  The v1 pin moved 1948 -> 2119
#: with the BLEST family grouping (models/actions.py): the stacked
#: group kernels add where-cascade selects to the PRE-fusion eqn count
#: while cutting the per-family launch fan-out XLA must schedule.  The
#: v4 pin is the megakernel story: the whole front (masks + compact +
#: fingerprint) plus the fused tail collapse ~2900 device ops into two
#: Pallas launches + the fixed chunk scaffolding.
LAUNCH_PINS = {
    "v1": {"launches_per_batch": 2119, "launches_fixed": 6},
    "v2": {"launches_per_batch": 3178, "launches_fixed": 6},
    "v3": {"launches_per_batch": 3050, "launches_fixed": 6},
    "v4": {"launches_per_batch": 257, "launches_fixed": 6},
}


@pytest.mark.parametrize("pipe", ["v1", "v2", "v3", "v4"])
def test_launch_counts_pinned_per_pipeline(pipe):
    eng = BFSEngine(DIMS, constraint=build_constraint(DIMS, BOUNDS),
                    config=small_config(perf=True, pipeline=pipe))
    lm = eng._perf.launch_model
    assert lm is not None, "launch model failed to build"
    got = {k: lm[k] for k in ("launches_per_batch", "launches_fixed")}
    assert got == LAUNCH_PINS[pipe], (
        f"{pipe} chunk-program launch count moved: {got} != pinned "
        f"{LAUNCH_PINS[pipe]}.  If the chunk body changed "
        f"intentionally (or jax re-lowered primitives), re-pin WITH "
        f"the delta explained; otherwise a stage just un-fused.")


#: Swarm chunk program on the same tiny model (walks=batch=32,
#: depth=12, ring=8, chunk=8, TypeOK+NoLeader, hunt_cells=2^16).  Keyed
#: by the hunt flag: the +147-op delta IS the observatory's whole
#: static footprint (bloom probes/pushes + the O(B^2) same-fingerprint
#: prior + depth/family tallies), pinned so analytics creep into the
#: walk hot loop fails CI the same way an un-fused stage does.  Only 3
#: fixed ops (vs the BFS engines' 6): the swarm scaffolding is the
#: scan wrapper alone — no queue/frontier plumbing.
SWARM_LAUNCH_PINS = {
    False: {"launches_per_batch": 3104, "launches_fixed": 3},
    True: {"launches_per_batch": 3251, "launches_fixed": 3},
}


@pytest.mark.parametrize("hunt", [False, True])
def test_swarm_launch_counts_pinned(hunt):
    from raft_tla_tpu.engine.swarm import SwarmEngine
    from raft_tla_tpu.models.dims import LEADER
    from raft_tla_tpu.models.invariants import build_type_ok
    eng = SwarmEngine(
        DIMS,
        invariants={"TypeOK": build_type_ok(DIMS),
                    "NoLeader": lambda st: jnp.all(st.role != LEADER)},
        constraint=build_constraint(DIMS, BOUNDS),
        walks=32, max_depth=12, batch=32, chunk=8, ring=8,
        hunt=hunt, hunt_cells=1 << 16, perf=True)
    lm = eng._perf.launch_model
    assert lm is not None, "swarm launch model failed to build"
    got = {k: lm[k] for k in ("launches_per_batch", "launches_fixed")}
    assert got == SWARM_LAUNCH_PINS[hunt], (
        f"swarm chunk-program launch count moved (hunt={hunt}): {got} "
        f"!= pinned {SWARM_LAUNCH_PINS[hunt]}.  If the walk body or "
        f"hunt tallies changed intentionally, re-pin WITH the delta "
        f"explained; otherwise the walk loop just grew device ops.")
    # The observatory's footprint is bounded: hunt adds device ops to
    # the scan body but never an order of magnitude.
    assert SWARM_LAUNCH_PINS[True]["launches_per_batch"] \
        <= 1.10 * SWARM_LAUNCH_PINS[False]["launches_per_batch"]


def test_v3_fused_tail_retires_launches():
    """The relation (not just the absolute pins): v3's fused tail must
    count FEWER device ops than v2's split insert+enqueue — the
    fused-vs-unfused delta as a first-class assertion."""
    assert LAUNCH_PINS["v3"]["launches_per_batch"] \
        < LAUNCH_PINS["v2"]["launches_per_batch"]


def test_v4_megakernel_quarter_of_v2():
    """ISSUE 15 acceptance criterion as an assertion: v4's static
    per-chunk device-op count must be at MOST 25% of v2's — the
    megakernel's whole point.  (Measured: ~8%.)"""
    assert LAUNCH_PINS["v4"]["launches_per_batch"] \
        <= 0.25 * LAUNCH_PINS["v2"]["launches_per_batch"]


def test_v3_plan_reports_stage_launches():
    from raft_tla_tpu.models.schema import state_width
    from raft_tla_tpu.ops import pipeline_v3
    G = DIMS.n_instances
    plan = pipeline_v3.resolve_plan(B, G, K, Q=4096,
                                    sw=state_width(DIMS))
    # CPU policy: fused tail (interpret), XLA compact.
    assert plan.stages["insert"] == "fused"
    assert plan.launches["insert"] == 1
    assert plan.launches["enqueue"] == 0       # shares the fused kernel
    assert plan.launches["compact"] is None    # XLA: the walk's to count
    forced = pipeline_v3.resolve_plan(B, G, K, Q=4096,
                                      sw=state_width(DIMS),
                                      force={"insert": "xla"})
    assert forced.launches["insert"] is None


def test_v4_plan_reports_stage_launches():
    """v4 plan launch accounting: a built front is ONE launch covering
    masks/compact/fingerprint (the grouped stages count 0), the fused
    tail one more; degrading any front member nulls the whole group
    (XLA ops counted by the jaxpr walk instead)."""
    from raft_tla_tpu.models.actions2 import build_v2
    from raft_tla_tpu.models.schema import state_width
    from raft_tla_tpu.ops import pipeline_v4
    G = DIMS.n_instances
    ctx = {"dims": DIMS, "v2": build_v2(DIMS), "constraint": None,
           "inv_fns": None, "por_mask": None, "por_priority": None}
    plan = pipeline_v4.resolve_plan(B, G, K, Q=4096,
                                    sw=state_width(DIMS), front_ctx=ctx)
    assert plan.stages == {"masks": "fused", "compact": "fused",
                           "fingerprint": "fused", "insert": "fused",
                           "enqueue": "fused"}
    assert plan.launches["masks"] == 1
    assert plan.launches["compact"] == 0
    assert plan.launches["fingerprint"] == 0
    assert plan.launches["insert"] == 1
    assert plan.launches["enqueue"] == 0
    degraded = pipeline_v4.resolve_plan(B, G, K, Q=4096,
                                        sw=state_width(DIMS),
                                        front_ctx=ctx,
                                        force={"compact": "xla"})
    assert degraded.front is None
    assert degraded.launches["masks"] is None
    # shape-only resolve (no build context) degrades with a reason
    shp = pipeline_v4.resolve_plan(B, G, K, Q=4096, sw=state_width(DIMS))
    assert shp.front is None
    assert any("front" in r for r in shp.reasons.values())


# ---------------------------------------------------------------------------
# Observational contract + event surfaces


def test_perf_observational_single_chip(tmp_path):
    """Engine counts bit-identical with --perf on vs off; the perf
    event validates, carries launch accounting + a roofline fraction
    for every profiled stage, and the advisor names one of them.  Also
    pins the per-level HBM watermark field (None on CPU devices that
    report no memory stats — present either way)."""
    plain = BFSEngine(DIMS, constraint=build_constraint(DIMS, BOUNDS),
                      config=small_config(max_diameter=3))
    res0 = plain.run([init_state(DIMS)])
    ev = str(tmp_path / "events.jsonl")
    eng = BFSEngine(DIMS, constraint=build_constraint(DIMS, BOUNDS),
                    config=small_config(max_diameter=3, perf=True,
                                        events_out=ev))
    res1 = eng.run([init_state(DIMS)])
    assert (res0.distinct, res0.generated, res0.levels) \
        == (res1.distinct, res1.generated, res1.levels)
    assert res0.action_counts == res1.action_counts

    recs = validate_run_events(ev)              # payload schema enforced
    perf_evs = [e for e in recs if e["event"] == "perf"]
    assert len(perf_evs) == 1
    perf = perf_evs[0]["perf"]
    assert perf == res1.perf
    launch = perf["launch"]
    assert launch["launches_per_batch"] == \
        LAUNCH_PINS["v2"]["launches_per_batch"]   # auto resolves to v2
    assert launch["launches_per_chunk"] > 0
    assert launch["chunk_calls"] > 0
    assert 0.0 <= launch["launch_overhead_share"] <= 1.0
    assert launch["per_level"], "end_level never fired"
    stages = perf["roofline"]["stages"]
    assert set(stages) == {"expand", "fingerprint", "dedup_insert",
                           "enqueue"}
    for r in stages.values():                  # profiler ran: measured
        assert r["mean_seconds"] is not None
        assert r["bandwidth_fraction"] is not None
    assert perf["advisor"]["top"] in stages
    # perf gauges landed
    g = eng.metrics.snapshot()["gauges"]
    assert g.get("perf/launches_per_chunk", 0) > 0
    # per-level HBM watermark field present on every level row
    assert res1.level_stats
    assert all("hbm_peak_bytes" in row for row in res1.level_stats)


def test_perf_observational_mesh_dryrun_and_skew(tmp_path):
    """Mesh dryrun: counts bit-identical perf on/off; the perf block
    carries the mesh launch model + modeled collective share; skew
    telemetry lands balance gauges, level_complete fields, and (with a
    1.0 threshold — any imbalance) skew warning events."""
    from raft_tla_tpu.parallel.mesh import MeshBFSEngine
    base = dict(batch=16, queue_capacity=1 << 12, seen_capacity=1 << 15,
                check_deadlock=False, max_diameter=2)
    res0 = MeshBFSEngine(
        DIMS, constraint=build_constraint(DIMS, BOUNDS),
        config=EngineConfig(**base)).run([init_state(DIMS)])
    ev = str(tmp_path / "mesh_events.jsonl")
    eng = MeshBFSEngine(
        DIMS, constraint=build_constraint(DIMS, BOUNDS),
        config=EngineConfig(**base, perf=True, events_out=ev,
                            skew_warn_ratio=1.0))
    res1 = eng.run([init_state(DIMS)])
    assert (res0.distinct, res0.generated, res0.levels) \
        == (res1.distinct, res1.generated, res1.levels)

    recs = validate_run_events(ev)
    perf = [e for e in recs if e["event"] == "perf"][0]["perf"]
    assert perf["launch"]["launches_per_batch"] > 0
    assert perf["collectives"]["collectives_per_batch"] > 0
    assert perf["collectives"]["probe_seconds"] > 0
    levels = [e for e in recs if e["event"] == "level_complete"]
    assert any(e.get("frontier_skew") is not None for e in levels)
    assert any(isinstance(e.get("shard_frontier"), list) for e in levels)
    skews = [e for e in recs if e["event"] == "skew"]
    assert skews, "threshold 1.0 must warn on any imbalance"
    bal = skews[0]["balance"]
    assert bal["frontier_skew"] >= 1.0
    assert len(bal["shard_frontier"]) == eng.n_dev
    g = eng.metrics.snapshot()["gauges"]
    assert "mesh/frontier_skew" in g


# ---------------------------------------------------------------------------
# bench_diff --launch-drift + xplane_summary


def _bench_doc(lpc, frac=0.5, value=1000.0):
    return {"value": value, "unit": "states/s",
            "distinct_states": 1000, "generated_states": 3000,
            "perf": {"launch": {"launches_per_chunk": lpc},
                     "roofline": {"stages": {
                         "expand": {"bandwidth_fraction": frac}}},
                     "advisor": {"top": "expand"}}}


def test_bench_diff_gates_launch_drift(tmp_path):
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "scripts"))
    import bench_diff

    old = tmp_path / "old.json"
    old.write_text(json.dumps(_bench_doc(1000.0)))
    ok = tmp_path / "ok.json"
    ok.write_text(json.dumps(_bench_doc(1100.0)))     # +10% < 25%
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps(_bench_doc(2000.0)))    # +100%
    slowbw = tmp_path / "slowbw.json"
    slowbw.write_text(json.dumps(_bench_doc(1000.0, frac=0.1)))
    legacy = tmp_path / "legacy.json"
    legacy.write_text(json.dumps(
        {"value": 1000.0, "distinct_states": 1000}))

    assert bench_diff.main([str(old), str(ok)]) == 0
    assert bench_diff.main([str(old), str(bad)]) == 1
    assert bench_diff.main([str(old), str(bad),
                            "--launch-drift", "2.0"]) == 0
    assert bench_diff.main([str(old), str(slowbw)]) == 1
    # one side predates the perf block: noted, never gated
    assert bench_diff.main([str(legacy), str(bad)]) == 0
    assert bench_diff.main([str(old), str(legacy)]) == 0


def _write_fake_xplane(logdir, chunks=4, kernels_per_chunk=50):
    run = os.path.join(logdir, "plugins", "profile", "2026_08_04")
    os.makedirs(run, exist_ok=True)
    events = [
        {"ph": "M", "pid": 1, "name": "process_name",
         "args": {"name": "/device:TPU:0"}},
        {"ph": "M", "pid": 9, "name": "process_name",
         "args": {"name": "python host"}},
    ]
    t = 0
    for c in range(chunks):
        events.append({"ph": "X", "pid": 1, "tid": 0, "name": "chunk",
                       "ts": t, "dur": 1000})
        for k in range(kernels_per_chunk):
            events.append({"ph": "X", "pid": 1, "tid": 0,
                           "name": f"fusion.{k % 7}",
                           "ts": t + k, "dur": 10})
        # host-side noise must not count as kernels
        events.append({"ph": "X", "pid": 9, "tid": 0,
                       "name": "python_call", "ts": t, "dur": 500})
        # device work BETWEEN chunk windows (per-level ingest /
        # profiler re-executions) must not inflate launches_per_chunk
        events.append({"ph": "X", "pid": 1, "tid": 0,
                       "name": "ingest.fusion", "ts": t + 1500,
                       "dur": 10})
        t += 2000
    path = os.path.join(run, "host.trace.json.gz")
    with gzip.open(path, "wt", encoding="utf-8") as f:
        json.dump({"traceEvents": events}, f)
    return path


def test_xplane_summary_counts_and_ledger(tmp_path, capsys):
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "scripts"))
    import bench_diff
    import xplane_summary

    logdir = str(tmp_path / "xla_profile")
    _write_fake_xplane(logdir, chunks=4, kernels_per_chunk=50)
    out = str(tmp_path / "summary.json")
    ledger = str(tmp_path / "ledger.jsonl")
    rc = xplane_summary.main([logdir, "--out", out, "--history", ledger,
                              "--label", "xplane_test"])
    assert rc == 0
    doc = json.loads(open(out).read())
    launch = doc["perf"]["launch"]
    assert launch["chunk_calls"] == 4
    # host noise AND out-of-window device work excluded
    assert launch["kernel_events"] == 200
    assert launch["launches_per_chunk"] == 50.0
    assert doc["top_kernels"]

    from raft_tla_tpu.obs import history as history_mod
    entries = history_mod.read_history(ledger)
    assert entries[0]["kind"] == "xplane"
    assert entries[0]["bench"]["perf"]["launch"][
        "launches_per_chunk"] == 50.0
    # the dialect diffs + gates through bench_diff like any bench pair
    worse = str(tmp_path / "worse")
    _write_fake_xplane(worse, chunks=4, kernels_per_chunk=100)
    out2 = str(tmp_path / "summary2.json")
    assert xplane_summary.main([worse, "--out", out2]) == 0
    assert bench_diff.main([out, out2]) == 1           # 2x launches
    assert bench_diff.main([out2, out]) == 0           # improvement
    # empty capture dir fails loudly (rc 2)
    assert xplane_summary.main([str(tmp_path / "nothing")]) == 2


def test_perf_event_requires_payload(tmp_path):
    """The validator's schema table knows the new events: a perf/skew
    record without its payload object is a malformed log."""
    p = tmp_path / "ev.jsonl"
    p.write_text(json.dumps({"event": "run_start", "ts": 1.0}) + "\n"
                 + json.dumps({"event": "perf", "ts": 2.0}) + "\n"
                 + json.dumps({"event": "run_end", "ts": 3.0}) + "\n")
    with pytest.raises(ValueError, match="perf"):
        validate_run_events(str(p))
    p2 = tmp_path / "ev2.jsonl"
    p2.write_text(json.dumps({"event": "run_start", "ts": 1.0}) + "\n"
                  + json.dumps({"event": "skew", "ts": 2.0,
                                "balance": {"frontier_skew": 3.0}}) + "\n"
                  + json.dumps({"event": "run_end", "ts": 3.0}) + "\n")
    assert len(validate_run_events(str(p2))) == 3
