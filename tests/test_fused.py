"""v3 fused Pallas chunk pipeline: interpret-mode bit-identity vs XLA.

Every Pallas stage of the v3 chunk (ops/compact_pallas.py,
ops/fused_tail_pallas.py, plus the two pre-existing kernels
ops/fpset_pallas.py and ops/enqueue_pallas.py) is proven bit-identical
to its XLA reference on CPU via interpret mode — property-style over
random batches at the kernel level, then end-to-end against pinned
MCraft_bounded oracle prefixes at the engine level (the chaos_check /
test_actions2 pattern).  The full pinned L0-L9 single-chip and
46,553-state mesh-dryrun differentials run under ``--pipeline v3`` as
well but take ~10 CPU-minutes in interpret mode; the depth-limited
versions here keep tier-1 affordable while covering the identical code
paths (same kernels, same plan, more steps at L9 — verified once at PR
time, recorded in CHANGES.md).

This module is listed in tests/conftest.py's trace-heavy-last reorder:
it builds several full engines (v2 + two v3 plans + a mesh), which is
exactly the trace-churn profile that destabilizes jaxlib's CPU client
when run before the big engine/mesh tests.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raft_tla_tpu.engine.bfs import BFSEngine, EngineConfig
from raft_tla_tpu.models.invariants import build_constraint
from raft_tla_tpu.ops import compact, compact_pallas, fpset
from raft_tla_tpu.ops import enqueue_pallas, fused_tail_pallas
from raft_tla_tpu.ops import pipeline_v3
from raft_tla_tpu.utils.cfg import load_config

_I32 = jnp.int32


# ---------------------------------------------------------------------------
# Kernel-level bit-identity (property-style over random batches).


def test_compact_pallas_bit_identical():
    """Pallas sequential-scan compaction vs BOTH XLA lowerings: same
    P/total/lane_id/kvalid on random masks across densities, including
    the progress-limited (fan-out > K) and all-dead corners."""
    B, G, K = 24, 132, 256
    xla_sc = compact.build_compactor(B, G, K, method="scatter")
    xla_ss = compact.build_compactor(B, G, K, method="searchsorted")
    pal = compact_pallas.build_compactor(B, G, K)
    rng = np.random.RandomState(7)
    for density in (0.0, 0.06, 0.3, 1.0):
        en = jnp.asarray(rng.rand(B, G) < density)
        want = tuple(np.asarray(x) for x in xla_sc(en))
        want_ss = tuple(np.asarray(x) for x in xla_ss(en))
        got = tuple(np.asarray(x) for x in pal(en))
        for w, ws, g in zip(want, want_ss, got):
            assert (w == ws).all()      # the two XLA methods agree...
            assert (w == g).all()       # ...and Pallas matches them


def test_fpset_pallas_bit_identical():
    """Sequential-grid Pallas insert vs the XLA sort+claim insert:
    identical is_new/size/fail and stored key SET over random duplicate-
    heavy batches (the ops/fpset_pallas.py contract, property-style)."""
    from raft_tla_tpu.ops import fpset_pallas
    rng = np.random.RandomState(3)
    s_x = fpset.empty(4096)
    s_p = fpset.empty(4096)
    for _ in range(4):
        pool = rng.randint(0, 300, size=(512, 2)).astype(np.uint32)
        qhi, qlo = jnp.asarray(pool[:, 0]), jnp.asarray(pool[:, 1])
        valid = jnp.asarray(rng.rand(512) < 0.8)
        s_x, new_x, fail_x = fpset.insert(s_x, qhi, qlo, valid)
        s_p, new_p, fail_p = fpset_pallas.insert(s_p, qhi, qlo, valid)
        assert (np.asarray(new_x) == np.asarray(new_p)).all()
        assert bool(fail_x) == bool(fail_p)
        assert int(s_x.size) == int(s_p.size)
        assert (np.sort(np.asarray(s_x.hi)) ==
                np.sort(np.asarray(s_p.hi))).all()
        assert (np.sort(np.asarray(s_x.lo)) ==
                np.sort(np.asarray(s_p.lo))).all()


def test_enqueue_pallas_live_rows_bit_identical():
    """Run-coalesced DMA append vs the scatter enqueue: identical live
    region [0, next_count + new_n) for random masks including empty,
    full, and sparse runs (trash regions differ by design — the
    'window' precedent)."""
    rng = np.random.RandomState(5)
    K, SW, Q = 128, 37, 512
    for density in (0.0, 0.06, 0.5, 1.0):
        krows = jnp.asarray(rng.randint(0, 255, (K, SW)), jnp.uint8)
        enq = jnp.asarray(rng.rand(K) < density)
        nc = jnp.int32(rng.randint(0, Q - K))
        got = enqueue_pallas.enqueue(
            jnp.zeros((Q + K, SW), jnp.uint8), nc, krows, enq)
        pos = nc + jnp.cumsum(enq.astype(_I32)) - 1
        pos = jnp.where(enq, pos, Q + jnp.arange(K, dtype=_I32))
        want = jnp.zeros((Q + K, SW), jnp.uint8).at[pos].set(krows)
        hi = int(nc) + int(enq.sum())
        assert (np.asarray(got)[:hi] == np.asarray(want)[:hi]).all()


def test_fused_tail_bit_identical_incl_trash():
    """The fused probe/insert->enqueue kernel vs the split XLA pair:
    is_new/fail/size/key set AND the whole queue buffer byte-for-byte —
    the fused tail reproduces even the scatter lowering's per-lane
    trash addresses.  1024 queries = multiple grid programs, so the
    running enqueue cursor is exercised across program boundaries."""
    rng = np.random.RandomState(11)
    K, SW, Q = 1024, 37, 1024
    for trial in range(3):
        pool = rng.randint(0, 400, size=(K, 2)).astype(np.uint32)
        qhi, qlo = jnp.asarray(pool[:, 0]), jnp.asarray(pool[:, 1])
        valid = jnp.asarray(rng.rand(K) < 0.8)
        cons = jnp.asarray(rng.rand(K) < 0.7)
        krows = jnp.asarray(rng.randint(0, 255, (K, SW)), jnp.uint8)
        nc = jnp.int32(rng.randint(0, 64))
        s_x, new_x, fail_x = fpset.insert(fpset.empty(8192),
                                          qhi, qlo, valid)
        enq = new_x & cons
        pos = nc + jnp.cumsum(enq.astype(_I32)) - 1
        pos = jnp.where(enq, pos, Q + jnp.arange(K, dtype=_I32))
        want_q = jnp.zeros((Q + K, SW), jnp.uint8).at[pos].set(krows)
        s_p, new_p, fail_p, got_q = fused_tail_pallas.insert_enqueue(
            fpset.empty(8192), qhi, qlo, valid, krows, cons,
            jnp.zeros((Q + K, SW), jnp.uint8), nc, Q)
        assert (np.asarray(new_x) == np.asarray(new_p)).all(), trial
        assert bool(fail_x) == bool(fail_p)
        assert int(s_x.size) == int(s_p.size)
        assert (np.sort(np.asarray(s_x.hi)) ==
                np.sort(np.asarray(s_p.hi))).all()
        assert (np.asarray(want_q) == np.asarray(got_q)).all(), trial


# ---------------------------------------------------------------------------
# Stage-plan resolution (automatic fallback is the contract).


def test_plan_policy_and_reasons():
    plan = pipeline_v3.resolve_plan(16, 132, 256, Q=512)
    # CPU policy: fused tail on, compact falls back with a reason.
    assert plan.stages["insert"] == "fused"
    assert plan.stages["enqueue"] == "fused"
    assert plan.tail is not None
    assert plan.stages["masks"] == "xla" and "masks" in plan.reasons
    assert plan.stages["fingerprint"] == "xla"
    if jax.devices()[0].platform != "tpu":
        assert plan.stages["compact"] == "xla"
        assert "interpret" in plan.reasons["compact"]
    mesh_plan = pipeline_v3.resolve_plan(16, 132, 256, Q=512, mesh=True)
    assert mesh_plan.tail is None
    assert mesh_plan.stages["insert"] == "xla"
    assert "collective" in mesh_plan.reasons["insert"]
    assert mesh_plan.stages["enqueue"] == "pallas"
    # force is honored where it is sound...
    forced = pipeline_v3.resolve_plan(16, 132, 256, Q=512,
                                      force={"compact": "pallas"})
    assert forced.stages["compact"] == "pallas"
    assert forced.compactor is not None
    # ...and the mesh's collective-stage constraints override it: a
    # forced fused insert or Pallas compact must NOT produce a plan
    # claiming a lowering the mesh engine would never run.
    mesh_forced = pipeline_v3.resolve_plan(16, 132, 256, Q=512, mesh=True,
                                           force={"insert": "fused",
                                                  "compact": "pallas"})
    assert mesh_forced.tail is None
    assert mesh_forced.stages["compact"] == "xla"
    assert mesh_forced.compactor is None
    # A typo'd force must raise, not silently fall back to the policy
    # (a "forced full-Pallas" differential would then pass vacuously).
    with pytest.raises(ValueError, match="v3_force_stages"):
        pipeline_v3.resolve_plan(16, 132, 256, Q=512,
                                 force={"compact": "Pallas"})
    with pytest.raises(ValueError, match="v3_force_stages"):
        pipeline_v3.resolve_plan(16, 132, 256, Q=512,
                                 force={"tail": "fused"})
    # Every non-Pallas stage records why — including explicitly forced
    # ones (the reasons dict rides EngineResult.fused_reasons).
    off = pipeline_v3.resolve_plan(16, 132, 256, Q=512,
                                   force={"compact": "xla",
                                          "insert": "xla"})
    assert off.reasons["compact"] == "forced to xla"
    assert off.reasons["insert"] == "forced to xla"


def test_plan_falls_back_when_stage_cannot_build(monkeypatch):
    """A Pallas stage that cannot even construct must degrade to XLA
    with a recorded reason, never fail the engine build."""
    from raft_tla_tpu.ops import compact_pallas as cp

    def boom(*a, **kw):
        raise RuntimeError("no mosaic for you")

    monkeypatch.setattr(cp, "build_compactor", boom)
    plan = pipeline_v3.resolve_plan(16, 132, 256, Q=512,
                                    force={"compact": "pallas"})
    assert plan.stages["compact"] == "xla"
    assert "no mosaic for you" in plan.reasons["compact"]
    assert plan.compactor is None


def test_v3_requires_v2_kernels():
    """pipeline='v3' on a dims variant without v2 kernels must raise
    (the v2 rule: never silently run the slow path when asked to fuse)."""
    from raft_tla_tpu.engine.bfs import _resolve_pipeline
    from raft_tla_tpu.models.actions2 import V2Unavailable
    from raft_tla_tpu.models.dims import RaftDims

    class NoV2(RaftDims):
        @property
        def extra_families(self):
            return (("Mystery", 2),)

    nov2 = NoV2(n_servers=2, n_values=1, max_log=2, n_msg_slots=8)
    with pytest.raises(V2Unavailable):
        _resolve_pipeline("v3", nov2)


# ---------------------------------------------------------------------------
# Engine-level differentials (pinned oracle prefixes; the L0-L9 and
# mesh-dryrun full differentials are the same code paths at more depth).


def test_v3_engine_matches_v2_pinned_prefix():
    """Single-chip --pipeline v3 vs v2 through L6 (pinned oracle: 9,457
    cumulative distinct): same counts, levels, verdict, AND the same
    replayed counterexample-path trace links — the v3 trace buffer must
    record identical (parent fp, action) rows, not just totals.  Run
    for both the platform plan and the forced full-Pallas chain (the
    interpret-mode acceptance path)."""
    from raft_tla_tpu.models.pystate import init_state
    setup = load_config("configs/MCraft_bounded.cfg")
    dims = setup.dims

    results = {}
    fps = {}
    for name, pipe, force in (("v2", "v2", None),
                              ("v3", "v3", None),
                              ("v3full", "v3", {"compact": "pallas"})):
        eng = BFSEngine(
            dims, constraint=build_constraint(dims, setup.bounds),
            config=EngineConfig(batch=128, queue_capacity=1 << 14,
                                seen_capacity=1 << 16, record_trace=True,
                                check_deadlock=False, max_diameter=6,
                                pipeline=pipe, v3_force_stages=force))
        res = eng.run([init_state(dims)])
        results[name] = (res.distinct, res.generated, res.levels,
                         res.diameter)
        assert res.distinct == 9457      # pinned oracle L6 cumulative
        # Trace-content identity: the recorded (fp, parent fp, action)
        # link set must match across pipelines, not just the totals.
        tf, tp, ta = eng.trace.export()
        fps[name] = set(zip(tf.tolist(), tp.tolist(), ta.tolist()))
        if name.startswith("v3"):
            assert res.pipeline == "v3"
            assert res.fused_stages["insert"] == "fused"
    assert results["v2"] == results["v3"] == results["v3full"]
    assert fps["v2"] == fps["v3"] == fps["v3full"]


def test_v3_mesh_matches_v2():
    """Mesh --pipeline v3 (XLA collective stages + Pallas enqueue inside
    shard_map) vs v2 on the virtual 8-device mesh: identical counts and
    levels — the dryrun-path acceptance differential at tier-1 depth."""
    from raft_tla_tpu.models.dims import RaftDims
    from raft_tla_tpu.models.invariants import Bounds
    from raft_tla_tpu.models.pystate import init_state
    from raft_tla_tpu.parallel.mesh import MeshBFSEngine
    dims = RaftDims(n_servers=3, n_values=2, max_log=4, n_msg_slots=24)
    bounds = Bounds(max_term=2, max_log_len=1, max_msg_count=1)
    out = {}
    for pipe in ("v2", "v3"):
        eng = MeshBFSEngine(
            dims, constraint=build_constraint(dims, bounds),
            config=EngineConfig(batch=16, queue_capacity=1 << 12,
                                seen_capacity=1 << 15,
                                check_deadlock=False, max_diameter=3,
                                pipeline=pipe))
        res = eng.run([init_state(dims)])
        out[pipe] = (res.distinct, res.generated, res.levels)
        if pipe == "v3":
            assert res.pipeline == "v3"
            assert res.fused_stages["enqueue"] == "pallas"
            assert res.fused_stages["insert"] == "xla"
    assert out["v2"] == out["v3"]


def test_v3_profiler_fused_stage_granularity():
    """--profile-chunks on a v3 engine: the profiler samples the
    fused-stage decomposition (masks/compact/fingerprint/
    insert_enqueue), renders a coherent table ('-' where the NORTHSTAR
    v1 budget has no row), and EngineResult.chunk_stages carries the
    v3 keys bench_diff folds."""
    from raft_tla_tpu.engine.check import initial_states, make_engine
    setup = load_config("configs/MCraft_bounded.cfg")
    eng = make_engine(setup, EngineConfig(
        batch=32, queue_capacity=1 << 12, seen_capacity=1 << 14,
        record_trace=False, check_deadlock=False, max_diameter=3,
        pipeline="v3", profile_chunks_every=1))
    res = eng.run(initial_states(setup))
    assert set(res.chunk_stages) == {"masks", "compact", "fingerprint",
                                     "insert_enqueue", "total"}
    prof = eng._profiler
    table = prof.render_table()
    assert "insert_enqueue" in table and "v3 stages" in table
    summary = prof.summary()
    assert summary["pipeline"] == "v3"
    assert summary["stages"]["insert_enqueue"]["budget_ms_b2048"] is None
