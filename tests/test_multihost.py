"""Multi-host (multi-controller) end-to-end: two OS processes, two
virtual CPU devices each, one global 4-device mesh, collectives over the
gloo CPU backend — the same program shape that rides ICI/DCN on a TPU
pod (SURVEY §2.4 R7 distributed mode; parallel/multihost.py).

The assertion that matters: BOTH processes complete the same number of
chunks and report the SAME psum-replicated results (steps, traces, the
violation and its reconstructed trace length) — i.e. the host loop is
multi-controller-safe, not merely non-crashing."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _spawn(pid, nproc, port, script="mh_sim_worker.py", extra_env=None):
    env = dict(os.environ,
               JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=2",
               RAFT_COORDINATOR=f"127.0.0.1:{port}",
               RAFT_NUM_PROCESSES=str(nproc),
               RAFT_PROCESS_ID=str(pid))
    env.update(extra_env or {})
    return subprocess.Popen(
        [sys.executable, os.path.join(REPO, "tests", script)],
        env=env, cwd=REPO, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        text=True)


def _run_pair(script, timeout=900, extra_env=None):
    port = _free_port()
    procs = [_spawn(i, 2, port, script, extra_env) for i in range(2)]
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("multi-host worker timed out (collective deadlock?)")
        if p.returncode != 0 and \
                "aren't implemented on the CPU backend" in err:
            # This jaxlib build lacks multiprocess collectives on the
            # CPU backend (gloo path not compiled in) — an environment
            # capability, not a code regression.  Real worker failures
            # still assert below.
            for q in procs:
                q.kill()
            pytest.skip("jaxlib CPU backend lacks multiprocess "
                        "collectives in this environment")
        assert p.returncode == 0, f"worker failed:\n{err[-3000:]}"
        outs.append(json.loads(out.strip().splitlines()[-1]))
    return sorted(outs, key=lambda r: r["process"])


def _free_port():
    import socket
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_simulation_agrees():
    a, b = _run_pair("mh_sim_worker.py", timeout=600)
    assert (a["process"], b["process"]) == (0, 1)
    assert a["global_devices"] == b["global_devices"] == 4
    assert a["local_devices"] == b["local_devices"] == 2
    # The replicated outputs must agree bit-for-bit across hosts.
    for k in ("steps", "traces", "violation", "trace_len"):
        assert a[k] == b[k], (k, a, b)
    # And the run must have actually found the seeded NoLeader violation
    # and reconstructed a real trace on both hosts.
    assert a["violation"] == "NoLeader"
    # Minimal counterexample from the seeded root: root state, Receive
    # (the pending grant), BecomeLeader — 3 trace entries.
    assert a["trace_len"] and a["trace_len"] >= 3


def test_put_global_matches_device_put_single_host():
    """put_global is the single-host-compatible path: same values as a
    plain device_put for both sharded and replicated specs."""
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    from raft_tla_tpu.parallel import multihost as mh
    devs = jax.devices()
    mesh = Mesh(np.asarray(devs), ("x",))
    arr = np.arange(len(devs) * 3, dtype=np.int32).reshape(len(devs), 3)
    got = mh.put_global(arr, mesh, P("x"))
    want = jax.device_put(arr, NamedSharding(mesh, P("x")))
    assert np.array_equal(np.asarray(got), np.asarray(want))
    rep = mh.put_global(arr, mesh, P())
    assert np.array_equal(np.asarray(rep), arr)
    assert not mh.is_multiprocess()


def test_two_process_exhaustive_bfs_matches_oracle():
    """The full distributed BFS pipeline across two controllers: owner-
    routed all_to_all dedup crosses the process boundary, each controller
    spills/re-uploads only its own shards, and BOTH report the oracle-
    pinned exhaustion (4,779 distinct / diameter 25 / 12,584 generated,
    models.oracle.bfs on the 2-server MaxInFlight=1 model)."""
    a, b = _run_pair("mh_bfs_worker.py")
    assert a["global_devices"] == b["global_devices"] == 4
    for k in ("distinct", "generated", "diameter", "levels", "stop_reason",
              "violation"):
        assert a[k] == b[k], (k, a, b)
    assert a["stop_reason"] == "exhausted"
    assert a["violation"] is None
    assert a["distinct"] == 4779
    assert a["diameter"] == 25
    assert a["generated"] == 12584


def test_multihost_trace_records_and_replays(tmp_path):
    """Multi-host trace recording (the one capability where multi-host
    used to be strictly weaker than single-host): each controller's
    store holds its own chips' records, the stores are exchanged as
    piece files on the shared filesystem, and BOTH controllers replay
    the SAME violation to the SAME counterexample path even though the
    chain's links were recorded on different hosts."""
    ck = str(tmp_path / "ck")
    a, b = _run_pair("mh_bfs_worker.py",
                     extra_env={"MH_TRACE": "1", "MH_CKPT_DIR": ck})
    assert a["violation"] == b["violation"] == "NoLeader"
    assert a["stop_reason"] == b["stop_reason"] == "violation"
    # Identical replayed paths on both controllers, long enough to be a
    # real election (Timeout -> RequestVote -> grant exchange ->
    # BecomeLeader), and the piece group is on disk.
    assert a["trace_path"] == b["trace_path"]
    assert a["trace_len"] == b["trace_len"] >= 5
    pieces = sorted(n for n in os.listdir(ck) if n.startswith("trace_run_"))
    assert len(pieces) == 2
    # One agreed run id across controllers, both pieces of the group.
    assert pieces[0].split(".")[0] == pieces[1].split(".")[0]
    assert pieces[0].endswith(".p0of2.npz")
    assert pieces[1].endswith(".p1of2.npz")


def test_multihost_checkpoint_resumes_everywhere(tmp_path):
    """Checkpoint portability across controller counts: two controllers
    write a piece group mid-run; (a) two controllers resume it to
    exhaustion, (b) ONE controller (plain single-host engine path) resumes
    the same group — both must land on the oracle-pinned totals."""
    ck = str(tmp_path / "ck")
    a, b = _run_pair("mh_bfs_worker.py", extra_env={
        "MH_CKPT_DIR": ck, "MH_MAX_DIAMETER": "12"})
    assert a["stop_reason"] == b["stop_reason"] == "diameter_budget"
    import glob
    pieces = sorted(glob.glob(ck + "/*.p*of2.npz"))
    assert len(pieces) >= 2          # a complete group per written level

    # (a) two-controller resume to exhaustion.
    a2, b2 = _run_pair("mh_bfs_worker.py", extra_env={"MH_RESUME": ck})
    for k in ("distinct", "generated", "diameter", "levels", "stop_reason"):
        assert a2[k] == b2[k], (k, a2, b2)
    assert a2["stop_reason"] == "exhausted"
    assert a2["distinct"] == 4779 and a2["diameter"] == 25
    assert a2["generated"] == 12584

    # (b) single-controller resume of the piece group (merged by
    # checkpoint.load): same totals.
    import os as _os
    import subprocess as _sp
    import sys as _sys
    env = dict(_os.environ, JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=4",
               MH_RESUME=ck)
    env.pop("RAFT_COORDINATOR", None)
    p = _sp.run([_sys.executable,
                 _os.path.join(REPO, "tests", "mh_bfs_worker.py")],
                env=env, cwd=REPO, capture_output=True, text=True,
                timeout=600)
    assert p.returncode == 0, p.stderr[-3000:]
    r = json.loads(p.stdout.strip().splitlines()[-1])
    assert r["stop_reason"] == "exhausted"
    assert r["distinct"] == 4779 and r["diameter"] == 25
    assert r["generated"] == 12584


def test_multihost_queue_budget_agrees(tmp_path):
    """TLCGet("queue") under a process group: the per-controller pool
    totals are psum-agreed, so both controllers stop at the same chunk
    with the same counters."""
    a, b = _run_pair("mh_bfs_worker.py",
                     extra_env={"MH_QUEUE_BUDGET": "150"})
    for k in ("distinct", "generated", "diameter", "stop_reason"):
        assert a[k] == b[k], (k, a, b)
    assert a["stop_reason"] == "queue_budget"
    assert a["distinct"] < 4779      # stopped well before exhaustion
