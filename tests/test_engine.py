"""Differential tests: the device BFS engine vs the oracle BFS.

The engine (engine/bfs.py: batched expand + fingerprint dedup + sorted FPSet)
and the oracle (models/oracle.py: Python sets of PyStates) must agree on
distinct-state counts, per-level frontier sizes, and diameters — TLC's
primary observable statistics (SURVEY §4 differential oracle).  Fingerprint
collisions would show up here as count mismatches.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from raft_tla_tpu.engine.bfs import BFSEngine, EngineConfig
from raft_tla_tpu.models import oracle as orc
from raft_tla_tpu.models.dims import LEADER, RaftDims
from raft_tla_tpu.models.invariants import (Bounds, build_constraint,
                                            build_type_ok, constraint_py,
                                            type_ok_py)
from raft_tla_tpu.models.pystate import init_state

DIMS = RaftDims(n_servers=3, n_values=2, max_log=4, n_msg_slots=32)
BOUNDS = Bounds(max_term=2, max_log_len=1, max_msg_count=1)


def small_config(**kw):
    base = dict(batch=32, queue_capacity=1 << 12, seen_capacity=1 << 15,
                check_deadlock=False)
    base.update(kw)
    return EngineConfig(**base)


@pytest.fixture(scope="module")
def engine():
    return BFSEngine(DIMS, invariants={"TypeOK": build_type_ok(DIMS)},
                     constraint=build_constraint(DIMS, BOUNDS),
                     config=small_config(max_diameter=3))


def test_counts_match_oracle_through_level3(engine):
    res = engine.run([init_state(DIMS)])
    want = orc.bfs([init_state(DIMS)], DIMS,
                   invariants={"TypeOK": type_ok_py},
                   constraint=constraint_py(BOUNDS),
                   check_deadlock=False, max_levels=3)
    assert res.violation is None and want.invariant_violation is None
    assert res.distinct == want.distinct_states
    assert res.levels == want.levels
    assert res.stop_reason == "diameter_budget"
    assert res.generated == want.generated_states
    # Per-action-family stats (TLC's per-action counts) partition the
    # generated total.
    assert sum(res.action_counts.values()) == res.generated
    assert res.action_counts.get("Timeout", 0) > 0
    # TLC-style coverage (obs/coverage.py) derives from the same packed
    # stats: generated matches action_counts bit-exactly, distinct
    # partitions the distinct count minus the root, and disabled counts
    # close the guard-evaluation accounting per family size.
    cov = res.coverage
    assert {a: v["generated"] for a, v in cov.items()} == res.action_counts
    assert sum(v["generated"] for v in cov.values()) == res.generated
    assert sum(v["distinct"] for v in cov.values()) == res.distinct - 1
    sizes = dict(zip(DIMS.family_names, DIMS.family_sizes))
    expanded = {name: (v["generated"] + v["disabled"]) / sizes[name]
                for name, v in cov.items()}
    assert len(set(expanded.values())) == 1   # one shared expanded base
    assert next(iter(expanded.values())) > 0


def test_violation_found_at_min_depth_and_replays():
    inv = {"TypeOK": build_type_ok(DIMS),
           "NoLeader": lambda st: jnp.all(st.role != LEADER)}
    eng = BFSEngine(DIMS, invariants=inv,
                    constraint=build_constraint(DIMS, BOUNDS),
                    config=small_config())
    # Seed a candidate one vote short of quorum: the minimal counterexample
    # (receive the pending grant, then BecomeLeader) is a few levels deep,
    # keeping the single-core CPU run fast while exercising the full
    # violation + trace machinery.
    s0 = init_state(DIMS).replace(
        role=(1, 0, 0), current_term=(2, 2, 2), voted_for=(1, 1, 1),
        votes_responded=(0b001, 0, 0), votes_granted=(0b001, 0, 0),
        messages=frozenset({((1, 1, 0, 2, 1, ()), 1)}))  # RVR grant r2->r1
    res = eng.run([s0])
    assert res.stop_reason == "violation"
    assert res.violation.invariant == "NoLeader"
    assert LEADER in res.violation.state.role

    # Oracle agrees on the minimal counterexample depth.
    want = orc.bfs([s0], DIMS,
                   invariants={"NoLeader": lambda s, d: LEADER not in s.role},
                   constraint=constraint_py(BOUNDS), check_deadlock=False)
    want_depth = len(want.trace_to(want.invariant_violation[1])) - 1

    # Kernel replay: every step is a legal spec transition (oracle-checked),
    # and the trace ends in the violating state at the oracle's depth.
    steps = eng.replay(res.violation.fingerprint)
    assert len(steps) - 1 == want_depth
    assert steps[-1][1] == res.violation.state
    for (s_prev, s_next) in zip(steps, steps[1:]):
        assert s_next[1] in orc.successor_set(s_prev[1], DIMS)


def test_replay_from_real_init_through_message_actions():
    """Regression: replay must survive message-slot reordering.  Queue rows
    keep the kernel's slot arrangement while replay re-encodes canonically
    (sorted slots), so a deep trace from the true Init that passes through
    multiple in-flight messages used to diverge on slot-indexed actions;
    replay now matches children by fingerprint (engine/bfs.py replay)."""
    dims = RaftDims(n_servers=2, n_values=1, max_log=2, n_msg_slots=8)
    bounds = Bounds(max_term=2, max_log_len=1, max_msg_count=1)
    eng = BFSEngine(dims, invariants={
        "NoLeader": lambda st: jnp.all(st.role != LEADER)},
        constraint=build_constraint(dims, bounds),
        config=small_config(batch=128))
    res = eng.run([init_state(dims)])
    assert res.stop_reason == "violation"
    steps = eng.replay(res.violation.fingerprint)
    # The minimal election needs both RequestVote sends in flight at once,
    # so the trace necessarily crosses multi-message states.
    assert len(steps) >= 5
    assert steps[-1][1] == res.violation.state
    for (s_prev, s_next) in zip(steps, steps[1:]):
        assert s_next[1] in orc.successor_set(s_prev[1], dims)


def test_multiple_init_states(engine_cls=BFSEngine):
    """Several roots (the smoke-mode shape): counts still match."""
    dims = DIMS
    inits = [init_state(dims)]
    # a couple of hand-built variants: one server already candidate/leader
    s = init_state(dims)
    inits.append(s.replace(role=(1, 0, 0), current_term=(2, 1, 1)))
    inits.append(s.replace(role=(2, 0, 0), votes_granted=(0b11, 0, 0)))
    eng = engine_cls(dims, constraint=build_constraint(dims, BOUNDS),
                     config=small_config(max_diameter=2))
    res = eng.run(inits)
    want = orc.bfs(inits, dims, constraint=constraint_py(BOUNDS),
                   check_deadlock=False, max_levels=2)
    assert res.distinct == want.distinct_states
    assert res.levels == want.levels


# MCraft_bounded exact level profile (frontier sizes per level), measured
# by the independent digest-based oracle sweep of 2026-07-29
# (scripts/oracle_exhaust.py; BASELINE.md §b).  The engine must reproduce
# this prefix exactly — the SURVEY §4 differential contract at real depth.
MCRAFT_BOUNDED_LEVELS = [1, 3, 18, 79, 318, 1218, 4433, 15510, 52467,
                         172129, 548904, 1703703, 5151868, 15187022]
MCRAFT_BOUNDED_DISTINCT_L7 = 37054     # cumulative distinct through L7
# (includes constraint-violating states: counted, never expanded)
MCRAFT_BOUNDED_GEN_L7 = 99489          # cumulative generated through L7


def test_levels_match_pinned_oracle_profile():
    """Engine vs the pinned full-scale oracle profile, through level 7
    (37k distinct — deep enough to cross several spills/growths of a tiny
    engine, cheap enough for the single-core CPU suite)."""
    from raft_tla_tpu.engine.check import initial_states, make_engine
    from raft_tla_tpu.utils.cfg import load_config
    import os
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    setup = load_config(os.path.join(here, "configs/MCraft_bounded.cfg"))
    eng = make_engine(setup, small_config(
        batch=256, queue_capacity=1 << 13, seen_capacity=1 << 14,
        max_diameter=7, record_trace=False))
    res = eng.run(initial_states(setup))
    assert res.levels == MCRAFT_BOUNDED_LEVELS[:8]
    assert res.distinct == MCRAFT_BOUNDED_DISTINCT_L7
    assert res.generated == MCRAFT_BOUNDED_GEN_L7
    assert res.violation is None


@pytest.mark.slow   # ~2 min CPU differential; nightly/hardware tier
def test_five_server_north_star_model_matches_oracle():
    """The north-star model (configs/TPUraft.cfg: 5 servers, MaxTerm=4,
    MaxLogLen=4) against a pinned oracle prefix — extends the
    differential contract beyond the 3-server bench model.  Pinned by
    models.oracle.bfs (max_levels=7, 706,142 distinct), 2026-07-30."""
    from raft_tla_tpu.engine.check import initial_states, make_engine
    from raft_tla_tpu.utils.cfg import load_config
    setup = load_config("configs/TPUraft.cfg")
    eng = make_engine(setup, small_config(
        batch=512, queue_capacity=1 << 19, seen_capacity=1 << 21,
        max_diameter=7, record_trace=False))
    res = eng.run(initial_states(setup))
    assert res.levels == [1, 5, 45, 310, 1995, 12306, 72870, 417420]
    assert res.distinct == 706142
    assert res.generated == 2265410
    assert res.violation is None


def test_duration_budget_stops():
    eng = BFSEngine(DIMS, constraint=build_constraint(DIMS, BOUNDS),
                    config=small_config(max_seconds=0.0))
    res = eng.run([init_state(DIMS)])
    assert res.stop_reason == "duration_budget"
    assert res.distinct >= 1


def test_duration_budget_promptness():
    """StopAfter must be honored to within ~a batch, not a whole
    sync_every chunk (round-2 BENCH overshot a 45 s budget by 66%).  The
    engine sizes each chunk call from its measured per-batch cost, so the
    overshoot is bounded by a few batches regardless of sync_every."""
    budget = 2.0
    eng = BFSEngine(DIMS, constraint=build_constraint(DIMS, BOUNDS),
                    config=small_config(max_seconds=budget, sync_every=64))
    res = eng.run([init_state(DIMS)])
    if res.stop_reason == "exhausted":
        pytest.skip("machine fast enough to exhaust inside the budget")
    assert res.stop_reason == "duration_budget"
    # Slack: a few batches at the measured cost, floored for 1-core
    # timing jitter (this guards against the round-2 failure mode of
    # overshooting by a whole sync_every chunk / 66% of the budget —
    # not against scheduler noise).
    slack = max(5 * eng._batch_ema, 2.0)
    assert res.wall_seconds <= budget + slack, \
        (res.wall_seconds, budget, eng._batch_ema)


def test_disk_backed_spill_matches_ram(tmp_path):
    """spill_dir memory-maps level segments to disk (TLC's disk-backed
    state queue); a tiny device queue forces constant spills and the
    counts must match the in-RAM run bit-for-bit.  Segment files are
    unlinked as they are consumed/cleared."""
    cons = build_constraint(DIMS, BOUNDS)
    want = BFSEngine(DIMS, constraint=cons,
                     config=small_config(max_diameter=3)).run(
        [init_state(DIMS)])
    spill = tmp_path / "spill"
    eng = BFSEngine(DIMS, constraint=cons,
                    config=small_config(batch=16, queue_capacity=16,
                                        spill_dir=str(spill),
                                        max_diameter=3))
    got = eng.run([init_state(DIMS)])
    assert got.distinct == want.distinct
    assert got.levels == want.levels
    assert got.generated == want.generated
    assert list(spill.iterdir()) == []      # all segments consumed
    # An early (budget) stop strands queued segments in the pools; they
    # must still be cleaned up when the run ends (pool finalizer).
    eng2 = BFSEngine(DIMS, constraint=cons,
                     config=small_config(batch=16, queue_capacity=16,
                                         spill_dir=str(spill),
                                         max_diameter=4))
    eng2.run([init_state(DIMS)])
    import gc
    gc.collect()
    assert list(spill.iterdir()) == []      # no leaked segment files


def test_progress_limiting_with_tiny_compact_buffer():
    """Results are invariant under the compacted-lane budget (ops/
    compact.py): a K too small for a whole batch's fan-out must advance
    fewer parents per step, never drop states.  K floors at max(G, B), so
    a large batch with the minimum K forces P < B on every busy step."""
    base = BFSEngine(DIMS, constraint=build_constraint(DIMS, BOUNDS),
                     config=small_config(max_diameter=3))
    want = base.run([init_state(DIMS)])
    eng = BFSEngine(DIMS, constraint=build_constraint(DIMS, BOUNDS),
                    config=small_config(batch=64, compact_lanes=1,
                                        max_diameter=3))
    assert eng._K == 256          # floor: _pow2(max(1, G=132, B=64))
    got = eng.run([init_state(DIMS)])
    assert got.distinct == want.distinct
    assert got.levels == want.levels
    assert got.generated == want.generated
    assert got.diameter == want.diameter


def test_order_independence_of_exploration():
    """Metamorphic (SURVEY §5.2, the race-detector analog): the distinct
    count, per-level sizes, and diameter are invariant under (a) frontier
    permutation and (b) batch-boundary changes — guards the claim-scatter
    insert protocol and in-batch dedup against order effects."""
    s = init_state(DIMS)
    roots = [s,
             s.replace(role=(1, 0, 0), current_term=(2, 1, 1)),
             s.replace(role=(0, 1, 0), current_term=(1, 2, 1)),
             s.replace(role=(2, 0, 0), votes_granted=(0b11, 0, 0))]
    base = BFSEngine(DIMS, constraint=build_constraint(DIMS, BOUNDS),
                     config=small_config(max_diameter=3))
    want = base.run(list(roots))
    for perm, batch in (([3, 1, 0, 2], 32), ([2, 0, 3, 1], 8)):
        eng = BFSEngine(DIMS, constraint=build_constraint(DIMS, BOUNDS),
                        config=small_config(batch=batch, max_diameter=3))
        got = eng.run([roots[i] for i in perm])
        assert got.distinct == want.distinct
        assert got.levels == want.levels
        assert got.generated == want.generated
        assert got.diameter == want.diameter


def test_smokeraft_cfg_end_to_end():
    """The reference Smokeraft.cfg (randomized init, StopAfter budgets,
    CHECK_DEADLOCK FALSE) runs unmodified through the cfg front-end and the
    engine: budget stop (or exhaustion of the random slice) with nonzero
    distinct states and no violation."""
    import os
    import pytest
    if not os.path.isdir("/root/reference"):
        # Same rule as tests/test_cfg.py's ``reference`` fixture: the
        # reference checkout is absent in plain containers — skip with
        # the reason, don't fail tier-1.
        pytest.skip("reference specs not mounted (/root/reference absent "
                    "in this container)")
    from raft_tla_tpu.engine.check import run_check
    res = run_check("/root/reference/Smokeraft.cfg",
                    engine_config=small_config(batch=128))
    assert res.violation is None
    assert res.distinct > 0
    assert res.stop_reason in ("duration_budget", "diameter_budget",
                               "exhausted")


def test_spill_to_host_matches_unspilled():
    """Frontier overflow must spill to host memory (TLC's disk queue) and
    change nothing observable: a run whose device queue is far smaller than
    the peak level size must report exactly the counts of a roomy run."""
    roomy = BFSEngine(DIMS, constraint=build_constraint(DIMS, BOUNDS),
                      config=small_config(max_diameter=4))
    want = roomy.run([init_state(DIMS)])
    # Peak level through diameter 4 is >> 64 rows, so this run spills
    # (queue_capacity rounds up to one batch = 32 rows; watermark is 0).
    tiny = BFSEngine(DIMS, constraint=build_constraint(DIMS, BOUNDS),
                     config=small_config(batch=32, queue_capacity=32,
                                         max_diameter=4, record_trace=False))
    got = tiny.run([init_state(DIMS)])
    assert got.distinct == want.distinct
    assert got.levels == want.levels
    assert got.generated == want.generated
    assert got.diameter == want.diameter


def test_ingest_spill_with_many_roots():
    """Root INGEST can overflow the device queue too (a k=3 smoke run has
    19,683 roots): the ingest-phase watermark must drain to the host pool
    without changing any count vs a roomy run."""
    from raft_tla_tpu.models.smoke import smoke_init_states
    sdims = RaftDims(n_servers=3, n_values=2, max_log=4, n_msg_slots=24)
    roots = smoke_init_states(sdims, k=2, seed=7)   # ~512 random roots
    assert len(roots) > 64
    cons = build_constraint(
        sdims, Bounds(max_term=2, max_log_len=1, max_msg_count=1))
    want = BFSEngine(sdims, constraint=cons,
                     config=small_config(max_diameter=1)).run(list(roots))
    # queue 32 rows << root count: every ingest wave crosses the
    # watermark and drains to the host pool before exploration starts.
    got = BFSEngine(sdims, constraint=cons,
                    config=small_config(batch=32, queue_capacity=32,
                                        max_diameter=1,
                                        record_trace=False)).run(list(roots))
    assert got.distinct == want.distinct
    assert got.levels == want.levels
    assert got.generated == want.generated


def test_seen_set_grows_in_place():
    """The FPSet must double (rehash) as load passes the threshold instead
    of dying; counts stay exact across growths."""
    roomy = BFSEngine(DIMS, constraint=build_constraint(DIMS, BOUNDS),
                      config=small_config(max_diameter=3))
    want = roomy.run([init_state(DIMS)])
    # batch 8 / sync 1 keeps per-host-check insertions well under the free
    # half of the table, so growth always fires before probes could fail.
    small = BFSEngine(DIMS, constraint=build_constraint(DIMS, BOUNDS),
                      config=small_config(batch=8, sync_every=1,
                                          seen_capacity=256, max_diameter=3))
    got = small.run([init_state(DIMS)])
    assert got.distinct == want.distinct
    assert got.levels == want.levels
    # (Capacities are floored at fpset's minimum table size, so this tiny
    # run exercises the small-capacity insert path, not growth; growth
    # evidence is asserted by test_spillpool_midscale_profile.)


def test_checkpoint_resume_across_spill(tmp_path):
    """A checkpoint written while part of the level lives in host spill
    segments must resume bit-exactly."""
    ck = str(tmp_path / "ck")
    full = BFSEngine(DIMS, constraint=build_constraint(DIMS, BOUNDS),
                     config=small_config(max_diameter=4, record_trace=False))
    want = full.run([init_state(DIMS)])
    first = BFSEngine(DIMS, constraint=build_constraint(DIMS, BOUNDS),
                      config=small_config(batch=32, queue_capacity=32,
                                          max_diameter=3, record_trace=False,
                                          checkpoint_dir=ck))
    first.run([init_state(DIMS)])
    from raft_tla_tpu.engine import checkpoint as ckpt_mod
    path = ckpt_mod.latest(ck)
    assert path is not None
    second = BFSEngine(DIMS, constraint=build_constraint(DIMS, BOUNDS),
                       config=small_config(batch=32, queue_capacity=32,
                                           max_diameter=4,
                                           record_trace=False))
    got = second.run(resume=path)
    assert got.distinct == want.distinct
    assert got.levels == want.levels
    assert got.diameter == want.diameter


def test_distinct_budget_stops_run(tmp_path):
    """A5 proper (SURVEY §5.5): a cfg-defined constraint consulting
    TLCGet("distinct") stops the run without any code changes — the general
    metrics-control coupling, not a special-cased budget."""
    from raft_tla_tpu.engine.check import initial_states, make_engine
    from tests.test_cfg import _write_exit_model
    from raft_tla_tpu.utils.cfg import load_config
    setup = load_config(_write_exit_model(tmp_path, "distinct", 500))
    eng = make_engine(setup, EngineConfig(
        batch=64, queue_capacity=1 << 14, seen_capacity=1 << 16,
        record_trace=False, sync_every=4))
    res = eng.run(initial_states(setup))
    assert res.stop_reason == "distinct_budget"
    assert res.distinct > 500
    # Promptness: one sync_every chunk (4 batches x G lanes) past the
    # threshold at most — not a whole level of the unbounded model.
    assert res.distinct < 500 + 4 * 64 * setup.dims.n_instances
    assert res.violation is None


def test_generated_budget_stops_run(tmp_path):
    from raft_tla_tpu.engine.check import initial_states, make_engine
    from tests.test_cfg import _write_exit_model
    from raft_tla_tpu.utils.cfg import load_config
    setup = load_config(_write_exit_model(tmp_path, "generated", 2000))
    eng = make_engine(setup, EngineConfig(
        batch=64, queue_capacity=1 << 14, seen_capacity=1 << 16,
        record_trace=False, sync_every=4))
    res = eng.run(initial_states(setup))
    assert res.stop_reason == "generated_budget"
    assert res.generated > 2000


@pytest.mark.slow   # ~3 min CPU spill stress; nightly/hardware tier
def test_spillpool_midscale_profile(tmp_path):
    """Mid-scale spill stress (VERDICT r3 weak #2): ~795k distinct states
    through a deliberately small queue so the level-11 frontier (548,904
    rows) flows through MANY disk-backed segments — the largest CPU-
    affordable test of SpillPool segment bookkeeping before a north-star
    TPU run.  The level profile must match the pinned full-scale oracle
    exactly, and every segment file must be consumed."""
    import os
    from raft_tla_tpu.engine.check import initial_states, make_engine
    from raft_tla_tpu.utils.cfg import load_config
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    setup = load_config(os.path.join(repo, "configs/MCraft_bounded.cfg"))
    spill = tmp_path / "spill"
    eng = make_engine(setup, EngineConfig(
        batch=512, queue_capacity=1 << 15, seen_capacity=1 << 21,
        record_trace=False, check_deadlock=False, sync_every=16,
        spill_dir=str(spill), max_diameter=10))
    res = eng.run(initial_states(setup))
    assert res.stop_reason == "diameter_budget"
    assert res.levels == MCRAFT_BOUNDED_LEVELS[:11]
    # Pinned by the independent oracle runner (oracle_exhaust.jsonl level
    # 10): distinct counts constraint-violating states too (counted, never
    # expanded), so it exceeds sum(levels).
    assert res.distinct == 1769309
    assert res.generated == 5053467
    # 1.77M keys through a 2M-capacity table: growth must fire, and each
    # doubling is recorded as (capacity-after, off-clock stall seconds)
    # with strictly increasing capacities.
    caps = [c for c, _s in res.growth_stalls]
    assert caps and caps == sorted(caps) and len(set(caps)) == len(caps)
    import gc
    gc.collect()
    assert list(spill.iterdir()) == []


def test_queue_budget_counts_full_unexplored_queue(tmp_path):
    """TLCGet("queue") must measure the FULL unexplored queue (current
    level's remainder + pending host segments + next-level rows + spills),
    not just the next-frontier device rows — a memory bound that missed
    the current level would let the queue blow 5x past the budget."""
    from raft_tla_tpu.engine.check import initial_states, make_engine
    from tests.test_cfg import _write_exit_model
    from raft_tla_tpu.utils.cfg import load_config
    setup = load_config(_write_exit_model(tmp_path, "queue", 3000))
    eng = make_engine(setup, EngineConfig(
        batch=64, queue_capacity=1 << 14, seen_capacity=1 << 16,
        record_trace=False, sync_every=4))
    res = eng.run(initial_states(setup))
    assert res.stop_reason == "queue_budget"
    # The unbounded 3-server model's levels grow ~4x per level; the stop
    # must land well before a whole extra level (re-derive the bound from
    # the run: last completed frontier + enqueued when stopped).
    assert res.levels[-1] <= 3000 * 5


def test_duplicate_duration_budgets_min_wins(tmp_path):
    """TLC exits when ANY TLCSet("exit", ...) trips: two CONSTRAINTs
    bounding the same counter must keep the SMALLEST threshold."""
    (tmp_path / "two.tla").write_text(
        "---- MODULE two ----\nEXTENDS raft\n"
        'StopShort ==\n    TLCSet("exit", TLCGet("duration") > 5)\n'
        'StopLong ==\n    TLCSet("exit", TLCGet("duration") > 600)\n'
        'DiaA ==\n    TLCSet("exit", TLCGet("diameter") > 40)\n'
        'DiaB ==\n    TLCSet("exit", TLCGet("diameter") > 7)\n====\n')
    (tmp_path / "two.cfg").write_text(
        "CONSTANTS\n    Server = {r1}\n    Value = {v1}\n"
        "SPECIFICATION Spec\nCONSTRAINT StopShort\nCONSTRAINT StopLong\n"
        "CONSTRAINT DiaA\nCONSTRAINT DiaB\n")
    from raft_tla_tpu.utils.cfg import load_config
    s = load_config(str(tmp_path / "two.cfg"))
    assert s.max_seconds == 5.0
    assert s.max_diameter == 7


def test_progress_lines_emitted(capfd):
    """progress_interval_seconds produces TLC-style stderr progress lines
    with live counters; the default (0) stays silent."""
    eng = BFSEngine(DIMS, constraint=build_constraint(DIMS, BOUNDS),
                    config=small_config(max_diameter=3,
                                        progress_interval_seconds=1e-6))
    eng.run([init_state(DIMS)])
    err = capfd.readouterr().err
    assert "progress:" in err and "queue" in err and "distinct" in err

    quiet = BFSEngine(DIMS, constraint=build_constraint(DIMS, BOUNDS),
                      config=small_config(max_diameter=3))
    quiet.run([init_state(DIMS)])
    assert "progress:" not in capfd.readouterr().err


def test_path_to_state_recovers_minimal_counterexample():
    """path_to_state extracts a minimal action path to any concrete state
    — the counterexample route for trace-less (e.g. multi-host) runs,
    which report the violating state but record no trace."""
    from raft_tla_tpu.engine.check import path_to_state
    want = orc.bfs([init_state(DIMS)], DIMS,
                   constraint=constraint_py(BOUNDS),
                   check_deadlock=False, max_levels=4)
    # Deepest layer: a state whose minimal depth is exactly 4.
    target = next(s for s in want.parent
                  if len(want.trace_to(s)) - 1 == 4)
    steps = path_to_state(
        DIMS, target, constraint=build_constraint(DIMS, BOUNDS),
        config=small_config(record_trace=True))
    assert steps[-1][1] == target
    assert len(steps) - 1 == 4          # minimal depth (BFS order)
    for (s_prev, s_next) in zip(steps, steps[1:]):
        assert s_next[1] in orc.successor_set(s_prev[1], DIMS)


def test_run_emits_level_complete_events(tmp_path):
    """Telemetry contract (obs/): any events_out run logs run_start, one
    level_complete per BFS level with live counters and a per-phase
    wall-time breakdown, and run_end; the result object carries the same
    phase totals.  (Schema details in tests/test_obs.py.)"""
    from raft_tla_tpu.obs import validate_run_events
    ev = str(tmp_path / "events.jsonl")
    eng = BFSEngine(DIMS, constraint=build_constraint(DIMS, BOUNDS),
                    config=small_config(max_diameter=3, events_out=ev))
    res = eng.run([init_state(DIMS)])
    events = validate_run_events(ev)
    levels = [e for e in events if e["event"] == "level_complete"]
    assert [e["frontier_rows"] for e in levels] == res.levels
    assert levels[-1]["distinct"] == res.distinct
    assert levels[-1]["phase_seconds"]
    assert res.phases.get("chunk", 0) > 0


def test_path_to_state_edge_cases():
    """Robustness of the extractor contract: a trace-less caller config
    must not break replay, a root target yields the trivial path, and
    deadlock states on shallower levels must not abort the search."""
    from raft_tla_tpu.engine.check import path_to_state
    # Root target: trivial path, no BFS.
    assert path_to_state(DIMS, init_state(DIMS)) == [(-1, init_state(DIMS))]
    # A config with record_trace=False and deadlock checking on (the
    # multi-host run shape) is overridden internally.
    want = orc.bfs([init_state(DIMS)], DIMS,
                   constraint=constraint_py(BOUNDS),
                   check_deadlock=False, max_levels=2)
    target = next(s for s in want.parent
                  if len(want.trace_to(s)) - 1 == 2)
    steps = path_to_state(
        DIMS, target, constraint=build_constraint(DIMS, BOUNDS),
        config=small_config(record_trace=False, check_deadlock=True))
    assert steps[-1][1] == target and len(steps) - 1 == 2
