"""Differential tests: the device BFS engine vs the oracle BFS.

The engine (engine/bfs.py: batched expand + fingerprint dedup + sorted FPSet)
and the oracle (models/oracle.py: Python sets of PyStates) must agree on
distinct-state counts, per-level frontier sizes, and diameters — TLC's
primary observable statistics (SURVEY §4 differential oracle).  Fingerprint
collisions would show up here as count mismatches.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from raft_tla_tpu.engine.bfs import BFSEngine, EngineConfig
from raft_tla_tpu.models import oracle as orc
from raft_tla_tpu.models.dims import LEADER, RaftDims
from raft_tla_tpu.models.invariants import (Bounds, build_constraint,
                                            build_type_ok, constraint_py,
                                            type_ok_py)
from raft_tla_tpu.models.pystate import init_state

DIMS = RaftDims(n_servers=3, n_values=2, max_log=4, n_msg_slots=32)
BOUNDS = Bounds(max_term=2, max_log_len=1, max_msg_count=1)


def small_config(**kw):
    base = dict(batch=32, queue_capacity=1 << 12, seen_capacity=1 << 15,
                check_deadlock=False)
    base.update(kw)
    return EngineConfig(**base)


@pytest.fixture(scope="module")
def engine():
    return BFSEngine(DIMS, invariants={"TypeOK": build_type_ok(DIMS)},
                     constraint=build_constraint(DIMS, BOUNDS),
                     config=small_config(max_diameter=3))


def test_counts_match_oracle_through_level3(engine):
    res = engine.run([init_state(DIMS)])
    want = orc.bfs([init_state(DIMS)], DIMS,
                   invariants={"TypeOK": type_ok_py},
                   constraint=constraint_py(BOUNDS),
                   check_deadlock=False, max_levels=3)
    assert res.violation is None and want.invariant_violation is None
    assert res.distinct == want.distinct_states
    assert res.levels == want.levels
    assert res.stop_reason == "diameter_budget"
    assert res.generated == want.generated_states


def test_violation_found_at_min_depth_and_replays():
    inv = {"TypeOK": build_type_ok(DIMS),
           "NoLeader": lambda st: jnp.all(st.role != LEADER)}
    eng = BFSEngine(DIMS, invariants=inv,
                    constraint=build_constraint(DIMS, BOUNDS),
                    config=small_config())
    # Seed a candidate one vote short of quorum: the minimal counterexample
    # (receive the pending grant, then BecomeLeader) is a few levels deep,
    # keeping the single-core CPU run fast while exercising the full
    # violation + trace machinery.
    s0 = init_state(DIMS).replace(
        role=(1, 0, 0), current_term=(2, 2, 2), voted_for=(1, 1, 1),
        votes_responded=(0b001, 0, 0), votes_granted=(0b001, 0, 0),
        messages=frozenset({((1, 1, 0, 2, 1, ()), 1)}))  # RVR grant r2->r1
    res = eng.run([s0])
    assert res.stop_reason == "violation"
    assert res.violation.invariant == "NoLeader"
    assert LEADER in res.violation.state.role

    # Oracle agrees on the minimal counterexample depth.
    want = orc.bfs([s0], DIMS,
                   invariants={"NoLeader": lambda s, d: LEADER not in s.role},
                   constraint=constraint_py(BOUNDS), check_deadlock=False)
    want_depth = len(want.trace_to(want.invariant_violation[1])) - 1

    # Kernel replay: every step is a legal spec transition (oracle-checked),
    # and the trace ends in the violating state at the oracle's depth.
    steps = eng.replay(res.violation.fingerprint)
    assert len(steps) - 1 == want_depth
    assert steps[-1][1] == res.violation.state
    for (s_prev, s_next) in zip(steps, steps[1:]):
        assert s_next[1] in orc.successor_set(s_prev[1], DIMS)


def test_replay_from_real_init_through_message_actions():
    """Regression: replay must survive message-slot reordering.  Queue rows
    keep the kernel's slot arrangement while replay re-encodes canonically
    (sorted slots), so a deep trace from the true Init that passes through
    multiple in-flight messages used to diverge on slot-indexed actions;
    replay now matches children by fingerprint (engine/bfs.py replay)."""
    dims = RaftDims(n_servers=2, n_values=1, max_log=2, n_msg_slots=8)
    bounds = Bounds(max_term=2, max_log_len=1, max_msg_count=1)
    eng = BFSEngine(dims, invariants={
        "NoLeader": lambda st: jnp.all(st.role != LEADER)},
        constraint=build_constraint(dims, bounds),
        config=small_config(batch=128))
    res = eng.run([init_state(dims)])
    assert res.stop_reason == "violation"
    steps = eng.replay(res.violation.fingerprint)
    # The minimal election needs both RequestVote sends in flight at once,
    # so the trace necessarily crosses multi-message states.
    assert len(steps) >= 5
    assert steps[-1][1] == res.violation.state
    for (s_prev, s_next) in zip(steps, steps[1:]):
        assert s_next[1] in orc.successor_set(s_prev[1], dims)


def test_multiple_init_states(engine_cls=BFSEngine):
    """Several roots (the smoke-mode shape): counts still match."""
    dims = DIMS
    inits = [init_state(dims)]
    # a couple of hand-built variants: one server already candidate/leader
    s = init_state(dims)
    inits.append(s.replace(role=(1, 0, 0), current_term=(2, 1, 1)))
    inits.append(s.replace(role=(2, 0, 0), votes_granted=(0b11, 0, 0)))
    eng = engine_cls(dims, constraint=build_constraint(dims, BOUNDS),
                     config=small_config(max_diameter=2))
    res = eng.run(inits)
    want = orc.bfs(inits, dims, constraint=constraint_py(BOUNDS),
                   check_deadlock=False, max_levels=2)
    assert res.distinct == want.distinct_states
    assert res.levels == want.levels


def test_duration_budget_stops():
    eng = BFSEngine(DIMS, constraint=build_constraint(DIMS, BOUNDS),
                    config=small_config(max_seconds=0.0))
    res = eng.run([init_state(DIMS)])
    assert res.stop_reason == "duration_budget"
    assert res.distinct >= 1


def test_spill_to_host_matches_unspilled():
    """Frontier overflow must spill to host memory (TLC's disk queue) and
    change nothing observable: a run whose device queue is far smaller than
    the peak level size must report exactly the counts of a roomy run."""
    roomy = BFSEngine(DIMS, constraint=build_constraint(DIMS, BOUNDS),
                      config=small_config(max_diameter=4))
    want = roomy.run([init_state(DIMS)])
    # Peak level through diameter 4 is >> 64 rows, so this run spills
    # (queue_capacity rounds up to one batch = 32 rows; watermark is 0).
    tiny = BFSEngine(DIMS, constraint=build_constraint(DIMS, BOUNDS),
                     config=small_config(batch=32, queue_capacity=32,
                                         max_diameter=4, record_trace=False))
    got = tiny.run([init_state(DIMS)])
    assert got.distinct == want.distinct
    assert got.levels == want.levels
    assert got.generated == want.generated
    assert got.diameter == want.diameter


def test_seen_set_grows_in_place():
    """The FPSet must double (rehash) as load passes the threshold instead
    of dying; counts stay exact across growths."""
    roomy = BFSEngine(DIMS, constraint=build_constraint(DIMS, BOUNDS),
                      config=small_config(max_diameter=3))
    want = roomy.run([init_state(DIMS)])
    # batch 8 / sync 1 keeps per-host-check insertions well under the free
    # half of the table, so growth always fires before probes could fail.
    small = BFSEngine(DIMS, constraint=build_constraint(DIMS, BOUNDS),
                      config=small_config(batch=8, sync_every=1,
                                          seen_capacity=256, max_diameter=3))
    got = small.run([init_state(DIMS)])
    assert got.distinct == want.distinct
    assert got.levels == want.levels


def test_checkpoint_resume_across_spill(tmp_path):
    """A checkpoint written while part of the level lives in host spill
    segments must resume bit-exactly."""
    ck = str(tmp_path / "ck")
    full = BFSEngine(DIMS, constraint=build_constraint(DIMS, BOUNDS),
                     config=small_config(max_diameter=4, record_trace=False))
    want = full.run([init_state(DIMS)])
    first = BFSEngine(DIMS, constraint=build_constraint(DIMS, BOUNDS),
                      config=small_config(batch=32, queue_capacity=32,
                                          max_diameter=3, record_trace=False,
                                          checkpoint_dir=ck))
    first.run([init_state(DIMS)])
    from raft_tla_tpu.engine import checkpoint as ckpt_mod
    path = ckpt_mod.latest(ck)
    assert path is not None
    second = BFSEngine(DIMS, constraint=build_constraint(DIMS, BOUNDS),
                       config=small_config(batch=32, queue_capacity=32,
                                           max_diameter=4,
                                           record_trace=False))
    got = second.run(resume=path)
    assert got.distinct == want.distinct
    assert got.levels == want.levels
    assert got.diameter == want.diameter
