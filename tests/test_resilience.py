"""Resilience subsystem (resilience/): deterministic fault injection,
crash-resume, graceful degradation, checkpoint retention, and server
hardening.  The process-death faults run SOFT here (FaultInjected raise
instead of os._exit — same file state, survivable by pytest); the real
hard-crash path is exercised end-to-end by ``scripts/chaos_check.py``."""

import dataclasses
import json
import os
import socket
import sys
import threading
import time

import jax.numpy as jnp
import numpy as np
import pytest

from raft_tla_tpu.engine import checkpoint as ckpt_mod
from raft_tla_tpu.engine.bfs import BFSEngine, EngineConfig
from raft_tla_tpu.engine.spillpool import SpillPool
from raft_tla_tpu.models.dims import LEADER, RaftDims
from raft_tla_tpu.models.invariants import Bounds, build_constraint
from raft_tla_tpu.models.pystate import init_state
from raft_tla_tpu.resilience import faults
from raft_tla_tpu.resilience.faults import (FaultInjected, FaultPlan,
                                            SimulatedResourceExhausted,
                                            is_resource_exhausted)
from raft_tla_tpu.resilience.supervisor import (run_supervised,
                                                strip_supervisor_flags)

DIMS = RaftDims(n_servers=2, n_values=1, max_log=2, n_msg_slots=8)
BOUNDS = Bounds(max_term=2, max_log_len=1, max_msg_count=1)


@pytest.fixture(autouse=True)
def _clear_faults():
    yield
    faults.clear()


def make_engine(**kw):
    cfg = dict(batch=128, queue_capacity=1 << 12, seen_capacity=1 << 15,
               check_deadlock=False)
    cfg.update(kw)
    return BFSEngine(
        DIMS, invariants={"NoLeader": lambda st: jnp.all(st.role != LEADER)},
        constraint=build_constraint(DIMS, BOUNDS),
        config=EngineConfig(**cfg))


@pytest.fixture(scope="module")
def full_run():
    eng = make_engine()
    res = eng.run([init_state(DIMS)])
    assert res.stop_reason == "violation"
    return res


def read_events(path):
    with open(path, encoding="utf-8") as f:
        return [json.loads(line) for line in f if line.strip()]


# -- fault plan parsing / firing ----------------------------------------
def test_fault_plan_grammar():
    plan = FaultPlan.parse("ckpt_torn_write@level=3,kill@level=5,oom@grow=1",
                           hard=False)
    assert [f.site for f in plan.faults] == \
        ["ckpt_torn_write", "kill", "oom"]
    assert plan.faults[0].params == {"level": 3}     # int-typed
    with pytest.raises(ValueError, match="unknown fault site"):
        FaultPlan.parse("explode@level=1")
    with pytest.raises(ValueError, match="key=value"):
        FaultPlan.parse("kill@level")
    with pytest.raises(ValueError, match="empty fault plan"):
        FaultPlan.parse(" , ")


def test_fault_fires_once_and_markers_persist(tmp_path):
    sd = str(tmp_path / "markers")
    plan = FaultPlan.parse("oom@grow=1", state_dir=sd, hard=False)
    with pytest.raises(SimulatedResourceExhausted) as ei:
        plan.fire("oom", grow=1)
    assert is_resource_exhausted(ei.value)
    assert plan.fire("oom", grow=1) is False         # fired already
    # A NEW plan instance (a restarted process) sees the same marker.
    plan2 = FaultPlan.parse("oom@grow=1", state_dir=sd, hard=False)
    assert plan2.fire("oom", grow=1) is False


def test_action_params_do_not_gate_matching():
    """``seconds`` configures trace_piece_delay's ACTION; no call site
    passes it as context, so matching must ignore it or the documented
    plan grammar can never fire."""
    plan = FaultPlan.parse("trace_piece_delay@seconds=0", hard=False)
    assert plan.fire("trace_piece_delay", piece=0) is True
    assert plan.fire("trace_piece_delay", piece=0) is False   # once


def test_ckpt_piece_missing_skips_the_write(tmp_path):
    ckdir = str(tmp_path / "states")
    make_engine(checkpoint_dir=ckdir, max_diameter=1).run(
        [init_state(DIMS)])
    ck = ckpt_mod.load(ckpt_mod.latest(ckdir))
    faults.install("ckpt_piece_missing@level=5;piece=1", hard=False)
    ckpt_mod.save(ckpt_mod.piece_path(ckdir, 5, 0, 2), ck)    # p0 lands
    ckpt_mod.save(ckpt_mod.piece_path(ckdir, 5, 1, 2), ck)    # p1 skipped
    assert os.path.exists(ckpt_mod.piece_path(ckdir, 5, 0, 2))
    assert not os.path.exists(ckpt_mod.piece_path(ckdir, 5, 1, 2))
    # The incomplete group must not be offered for resume.
    assert ckpt_mod.latest(ckdir).endswith("level_00001.npz")


def test_fault_param_mismatch_does_not_fire():
    plan = FaultPlan.parse("kill@level=5", hard=False)
    assert plan.fire("kill", level=4, chunk=1) is False
    assert plan.fire("oom", level=5) is False        # different site
    with pytest.raises(FaultInjected):
        plan.fire("kill", level=5, chunk=1)


# -- torn checkpoint write ----------------------------------------------
def test_torn_write_leaves_latest_on_previous_snapshot(tmp_path):
    ckdir = str(tmp_path / "states")
    faults.install("ckpt_torn_write@level=2", hard=False)
    eng = make_engine(checkpoint_dir=ckdir)
    with pytest.raises(FaultInjected):
        eng.run([init_state(DIMS)])
    # The crash window left the complete tmp behind, never renamed...
    assert os.path.exists(os.path.join(ckdir, "level_00002.npz.tmp"))
    assert not os.path.exists(os.path.join(ckdir, "level_00002.npz"))
    # ...and auto-resume falls back to the previous good snapshot.
    path = ckpt_mod.latest(ckdir)
    assert path is not None and path.endswith("level_00001.npz")
    ckpt_mod.load(path)                              # intact


def test_torn_write_then_resume_matches_full_run(full_run, tmp_path):
    ckdir = str(tmp_path / "states")
    faults.install("ckpt_torn_write@level=2", hard=False)
    with pytest.raises(FaultInjected):
        make_engine(checkpoint_dir=ckdir).run([init_state(DIMS)])
    faults.clear()
    r2 = make_engine().run(resume=ckpt_mod.latest(ckdir))
    assert r2.stop_reason == "violation"
    assert (r2.distinct, r2.generated, r2.diameter, r2.levels) == \
        (full_run.distinct, full_run.generated, full_run.diameter,
         full_run.levels)
    assert r2.violation.fingerprint == full_run.violation.fingerprint


# -- mid-level kill + resume --------------------------------------------
def test_mid_level_kill_resume_matches_full_run(full_run, tmp_path):
    ckdir = str(tmp_path / "states")
    faults.install("kill@level=2", hard=False)
    eng1 = make_engine(checkpoint_dir=ckdir)
    with pytest.raises(FaultInjected):
        eng1.run([init_state(DIMS)])
    faults.clear()
    path = ckpt_mod.latest(ckdir)
    assert path.endswith("level_00002.npz")   # died PAST the snapshot
    eng2 = make_engine()
    r2 = eng2.run(resume=path)
    assert (r2.distinct, r2.generated, r2.diameter, r2.levels) == \
        (full_run.distinct, full_run.generated, full_run.diameter,
         full_run.levels)
    # Counterexample replay works across the crash-resume boundary.
    steps = eng2.replay(r2.violation.fingerprint)
    assert steps[0][0] == -1
    assert steps[-1][1] == r2.violation.state


# -- graceful degradation (simulated RESOURCE_EXHAUSTED) -----------------
def test_oom_degrades_batch_and_completes(full_run, tmp_path):
    ckdir = str(tmp_path / "states")
    ev = str(tmp_path / "events.jsonl")
    faults.install("oom@level=2", hard=False)
    eng = make_engine(checkpoint_dir=ckdir, events_out=ev)
    res = eng.run([init_state(DIMS)])
    # Slow-but-correct: the run COMPLETED, at half the batch.
    assert res.stop_reason == "violation"
    assert eng.config.batch == 64
    assert (res.distinct, res.generated, res.diameter, res.levels) == \
        (full_run.distinct, full_run.generated, full_run.diameter,
         full_run.levels)
    degraded = [e for e in read_events(ev) if e["event"] == "degraded"]
    assert degraded and degraded[0]["new_batch"] == 64
    assert degraded[0]["resume_from"].endswith("level_00002.npz")
    assert eng.metrics.counter_value("engine/degraded") == 1


def test_oom_without_checkpoint_dir_restarts_from_scratch(full_run):
    faults.install("oom@level=1", hard=False)
    eng = make_engine()                     # no checkpoint_dir at all
    res = eng.run([init_state(DIMS)])
    assert res.stop_reason == "violation"
    assert res.distinct == full_run.distinct
    assert eng.config.batch == 64


def test_oom_respects_min_batch_floor():
    faults.install("oom@level=1", hard=False)
    eng = make_engine(batch=128, min_batch=128)   # halving would go under
    with pytest.raises(SimulatedResourceExhausted):
        eng.run([init_state(DIMS)])


def test_no_degrade_flag_fails_fast():
    faults.install("oom@level=1", hard=False)
    eng = make_engine(degrade_on_oom=False)
    with pytest.raises(SimulatedResourceExhausted):
        eng.run([init_state(DIMS)])


def test_grow_oom_retries_after_releasing_old_table():
    from raft_tla_tpu.ops import fpset
    eng = make_engine()
    n = 700                                  # past half of a 1024 table
    hi = np.arange(1, n + 1, dtype=np.uint32)
    lo = np.arange(1, n + 1, dtype=np.uint32)
    seen = fpset.from_host_keys(hi, lo, 1 << 10)
    faults.install("oom@grow=1", hard=False)
    grown = eng._maybe_grow_seen(seen)
    assert grown.hi.shape[0] == 1 << 11      # doubled despite the OOM
    assert int(grown.size) == n
    assert eng.metrics.counter_value("engine/degraded") == 1


# -- checkpoint retention GC --------------------------------------------
def test_keep_checkpoints_bounds_the_dir(tmp_path):
    ckdir = str(tmp_path / "states")
    eng = make_engine(checkpoint_dir=ckdir, keep_checkpoints=2,
                      max_diameter=4)
    eng.run([init_state(DIMS)])
    snaps = sorted(n for n in os.listdir(ckdir) if n.endswith(".npz"))
    assert snaps == ["level_00003.npz", "level_00004.npz"]
    assert ckpt_mod.latest(ckdir).endswith("level_00004.npz")


def test_gc_never_counts_garbage_toward_keep(tmp_path):
    ckdir = str(tmp_path / "states")
    make_engine(checkpoint_dir=ckdir, max_diameter=2).run(
        [init_state(DIMS)])
    # Two torn higher-level files must not evict the good snapshots.
    for lvl in (7, 8):
        with open(os.path.join(ckdir, f"level_{lvl:05d}.npz"), "wb") as f:
            f.write(b"\x00garbage")
    removed = ckpt_mod.gc(ckdir, keep=2)
    assert ckpt_mod.latest(ckdir).endswith("level_00002.npz")
    assert os.path.exists(os.path.join(ckdir, "level_00001.npz"))
    assert removed >= 1                      # level_00000 went


def test_gc_negative_keep_means_keep_all(tmp_path):
    ckdir = str(tmp_path / "states")
    make_engine(checkpoint_dir=ckdir, max_diameter=2).run(
        [init_state(DIMS)])
    before = sorted(os.listdir(ckdir))
    assert ckpt_mod.gc(ckdir, keep=-1) == 0  # never "delete everything"
    assert ckpt_mod.gc(ckdir, keep=None) == 0
    assert sorted(os.listdir(ckdir)) == before


def test_gc_collects_old_torn_tmp_debris(tmp_path):
    """Crash debris below the retention cutoff — orphaned .tmp files,
    incomplete piece groups — must be collected too, or a long
    supervised run with repeated crashes grows the dir without bound."""
    ckdir = str(tmp_path / "states")
    make_engine(checkpoint_dir=ckdir, max_diameter=3).run(
        [init_state(DIMS)])
    with open(os.path.join(ckdir, "level_00001.npz.tmp"), "wb") as f:
        f.write(b"torn")                     # a torn write's leftover
    with open(os.path.join(ckdir, "level_00000.p0of2.npz"), "wb") as f:
        f.write(b"lonely piece")             # incomplete old group
    ckpt_mod.gc(ckdir, keep=2)               # keeps levels 3 and 2
    left = sorted(n for n in os.listdir(ckdir) if n.startswith("level_"))
    assert left == ["level_00002.npz", "level_00003.npz"]


# -- mixed-generation piece groups --------------------------------------
def test_latest_skips_mixed_generation_piece_group(tmp_path):
    ckdir = str(tmp_path / "states")
    make_engine(checkpoint_dir=ckdir, max_diameter=1).run(
        [init_state(DIMS)])
    good = ckpt_mod.latest(ckdir)
    assert good.endswith("level_00001.npz")
    ck = ckpt_mod.load(good)
    # A level-5 piece group whose halves disagree on counters — the
    # footprint of a crash BETWEEN piece overwrites.
    ckpt_mod.save(ckpt_mod.piece_path(ckdir, 5, 0, 2), ck)
    ckpt_mod.save(ckpt_mod.piece_path(ckdir, 5, 1, 2),
                  dataclasses.replace(ck, distinct=ck.distinct + 1))
    # load() on the group still raises (the guard this fallback covers)…
    with pytest.raises(ValueError, match="generations"):
        ckpt_mod.load(ckpt_mod.piece_path(ckdir, 5, 0, 2))
    # …but latest() now SKIPS it instead of handing resume a dead path.
    assert ckpt_mod.latest(ckdir) == good


# -- spill write retry ---------------------------------------------------
def test_spill_write_failure_retries_once(tmp_path):
    faults.install("spill_write@attempt=1", hard=False)
    pool = SpillPool(str(tmp_path / "spill"))
    rows = np.arange(64, dtype=np.uint8).reshape(8, 8)
    pool.append(rows)                        # first attempt fails inside
    assert pool.total_rows() == 8
    np.testing.assert_array_equal(np.asarray(pool.pop(0)), rows)


def test_spill_write_two_failures_surface(tmp_path):
    faults.install("spill_write@attempt=1,spill_write@attempt=2",
                   hard=False)
    pool = SpillPool(str(tmp_path / "spill"))
    with pytest.raises(OSError, match="twice"):
        pool.append(np.zeros((4, 4), np.uint8))
    assert pool.total_rows() == 0            # no torn segment queued


# -- supervisor ----------------------------------------------------------
def test_supervisor_restarts_crashing_child(tmp_path):
    marker = str(tmp_path / "crashed_once")
    ev = str(tmp_path / "events.jsonl")
    script = (
        "import os, sys\n"
        f"m = {marker!r}\n"
        "if not os.path.exists(m):\n"
        "    open(m, 'w').close(); sys.exit(86)\n"
        "sys.exit(0)\n")
    rc = run_supervised([sys.executable, "-c", script],
                        checkpoint_dir=str(tmp_path / "states"),
                        events_out=ev, max_restarts=3,
                        backoff_seconds=0.01)
    assert rc == 0
    events = read_events(ev)
    restarts = [e for e in events if e["event"] == "restart"]
    assert len(restarts) == 1
    assert restarts[0]["exit_code"] == 86
    assert restarts[0]["attempt"] == 1
    assert [e for e in events if e["event"] == "supervised_done"]


def test_supervisor_gives_up_after_budget(tmp_path):
    ev = str(tmp_path / "events.jsonl")
    rc = run_supervised([sys.executable, "-c", "import sys; sys.exit(9)"],
                        checkpoint_dir=str(tmp_path / "states"),
                        events_out=ev, max_restarts=2,
                        backoff_seconds=0.01)
    assert rc == 9
    events = read_events(ev)
    assert len([e for e in events if e["event"] == "restart"]) == 2
    assert [e for e in events if e["event"] == "supervise_giveup"]


def _exit1_child(ev_path, stop_reason):
    """Fake child: append a run_end with ``stop_reason`` and exit 1 —
    the two faces of a 1-exit the supervisor must tell apart."""
    return (
        "import json, sys\n"
        f"open({ev_path!r}, 'a').write(json.dumps("
        f"{{'event': 'run_end', 'ts': 0, 'stop_reason': "
        f"{stop_reason!r}}}) + '\\n')\n"
        "sys.exit(1)\n")


def test_supervisor_treats_violation_exit_as_done(tmp_path):
    ev = str(tmp_path / "events.jsonl")
    rc = run_supervised([sys.executable, "-c", _exit1_child(ev, "violation")],
                        checkpoint_dir=str(tmp_path / "states"),
                        events_out=ev, max_restarts=3,
                        backoff_seconds=0.01)
    assert rc == 1                  # counterexample found == completed
    assert not [e for e in read_events(ev) if e["event"] == "restart"]


def test_supervisor_retries_exception_exit_1(tmp_path):
    """An uncaught Python exception ALSO exits 1 — without the run_end
    completion receipt it must be retried, not reported as a result."""
    ev = str(tmp_path / "events.jsonl")
    rc = run_supervised([sys.executable, "-c", _exit1_child(ev, "error")],
                        checkpoint_dir=str(tmp_path / "states"),
                        events_out=ev, max_restarts=2,
                        backoff_seconds=0.01)
    assert rc == 1
    assert len([e for e in read_events(ev)
                if e["event"] == "restart"]) == 2


def test_supervisor_honors_initial_resume_on_first_attempt(tmp_path):
    argv_log = str(tmp_path / "argvs")
    script = ("import sys\n"
              f"open({argv_log!r}, 'a').write("
              "' '.join(sys.argv[1:]) + '\\n')\n"
              "sys.exit(0)\n")
    rc = run_supervised([sys.executable, "-c", script],
                        checkpoint_dir=str(tmp_path / "states"),
                        events_out=str(tmp_path / "ev.jsonl"),
                        initial_resume="auto", backoff_seconds=0.01)
    assert rc == 0
    with open(argv_log) as f:
        assert f.read().splitlines() == ["--resume auto"]


def test_supervisor_restart_ignores_preexisting_stale_snapshot(tmp_path):
    """A reused states/ dir: the child crashed before writing ANY
    snapshot of its own, so the restart must run from scratch — not
    resume a previous run's stale image (load() validates only dims)."""
    ckdir = str(tmp_path / "states")
    make_engine(checkpoint_dir=ckdir, max_diameter=1).run(
        [init_state(DIMS)])                  # the "previous run's" image
    argv_log = str(tmp_path / "argvs")
    marker = str(tmp_path / "crashed_once")
    script = ("import os, sys\n"
              f"open({argv_log!r}, 'a').write("
              "' '.join(sys.argv[1:]) + '\\n')\n"
              f"m = {marker!r}\n"
              "if not os.path.exists(m):\n"
              "    open(m, 'w').close(); sys.exit(86)\n"
              "sys.exit(0)\n")
    rc = run_supervised([sys.executable, "-c", script],
                        checkpoint_dir=ckdir,
                        events_out=str(tmp_path / "ev.jsonl"),
                        max_restarts=2, backoff_seconds=0.01)
    assert rc == 0
    with open(argv_log) as f:
        assert f.read().splitlines() == ["", ""]   # no --resume either time


def test_supervisor_does_not_retry_usage_errors(tmp_path):
    ev = str(tmp_path / "events.jsonl")
    rc = run_supervised([sys.executable, "-c", "import sys; sys.exit(2)"],
                        checkpoint_dir=str(tmp_path / "states"),
                        events_out=ev, max_restarts=3,
                        backoff_seconds=0.01)
    assert rc == 2
    events = read_events(ev)
    assert not [e for e in events if e["event"] == "restart"]
    assert [e for e in events if e["event"] == "supervise_giveup"]


def test_strip_supervisor_flags():
    assert strip_supervisor_flags(
        ["check", "m.cfg", "--supervise", "5", "--batch", "64"]) == \
        ["check", "m.cfg", "--batch", "64"]
    assert strip_supervisor_flags(
        ["check", "m.cfg", "--supervise=5", "--resume", "auto"]) == \
        ["check", "m.cfg"]
    assert strip_supervisor_flags(
        ["check", "m.cfg", "--resume=auto", "--supervise"]) == \
        ["check", "m.cfg"]
    assert strip_supervisor_flags(
        ["check", "--supervise", "--no-trace", "m.cfg"]) == \
        ["check", "--no-trace", "m.cfg"]


# -- server hardening ----------------------------------------------------
@pytest.fixture()
def hardened_server():
    from raft_tla_tpu import server as srv_mod
    srv = srv_mod.serve(port=0, max_request_bytes=1024,
                        idle_timeout_seconds=1.0)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield srv.server_address
    srv.shutdown()


def test_server_rejects_oversized_request_line(hardened_server):
    with socket.create_connection(hardened_server, timeout=30) as s:
        s.sendall(b'{"op": "ping", "junk": "' + b"x" * 4096 + b'"}\n')
        buf = b""
        while not buf.endswith(b"\n"):
            chunk = s.recv(65536)
            if not chunk:
                break
            buf += chunk
        resp = json.loads(buf)
        assert resp["ok"] is False
        assert "exceeds" in resp["error"]
        # The connection is CLOSED after the reject (no resync possible).
        s.settimeout(10)
        assert s.recv(1) == b""


def test_server_drops_idle_connection(hardened_server):
    with socket.create_connection(hardened_server, timeout=30) as s:
        # A live request first: the timeout is per-read, not per-conn.
        s.sendall(b'{"op": "ping"}\n')
        buf = b""
        while not buf.endswith(b"\n"):
            buf += s.recv(65536)
        assert json.loads(buf)["ok"] is True
        time.sleep(1.5)                      # past the 1 s idle timeout
        # Silent close — no unsolicited error line that a pooled client
        # could misread as the response to its NEXT request.
        s.settimeout(10)
        assert s.recv(65536) == b""
