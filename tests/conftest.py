"""Test harness configuration.

Tests run on a virtual 8-device CPU mesh (SURVEY §4.5).  The ambient
environment pins jax to the real-TPU tunnel: a ``sitecustomize`` hook
registers the ``axon`` PJRT plugin at interpreter start and sets
``jax_platforms="axon,cpu"`` by config (so env vars set later are
ineffective), and any backend initialization then blocks on the TPU relay.
Unit tests must never touch the relay, so before any test imports run we
(1) point ``jax_platforms`` back at cpu, (2) drop the registered axon
factory, and (3) request 8 virtual CPU devices for the mesh-sharding tests.
Real-TPU execution is exercised by ``bench.py`` / ``__graft_entry__.py``
under the ambient environment, never by the unit suite.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402  (imported before force_cpu touches its config)

from raft_tla_tpu.utils.platform import force_cpu  # noqa: E402

force_cpu()

# Persistent compilation cache: the expand/step programs take tens of
# seconds to compile on this single-core CPU; caching makes re-runs cheap.
# Shared with every tool/script via the per-host-keyed helper (a cache
# written by a different machine must never be loaded — SIGILL hazard).
from raft_tla_tpu.utils.platform import enable_persistent_cache  # noqa: E402

# The suite gets its OWN cache namespace (see the tag rationale in
# utils/platform.py): entries written by 1-device CLI/bench/server runs
# interleaving with the suite's 8-virtual-device entries change the
# compile-vs-load history enough to abort the fragile mesh tests.
enable_persistent_cache(tag="unit8")


def pytest_collection_modifyitems(config, items):
    """Run the static-analysis tests LAST.

    The analyzers (analysis/) trace every action kernel plus both full
    chunk bodies without executing anything, which front-loads a large
    amount of trace/lowering cache churn into the process.  jaxlib's CPU
    client is heap-layout fragile under the big engine/mesh tests: with
    the analysis module collected in its default alphabetical slot
    (before test_cfg), the shifted heap history makes a later
    mesh/spillpool test segfault deterministically — even with the
    module-teardown ``jax.clear_caches()`` in test_analysis.py.  Moving
    the trace-heavy module to the end keeps the heap history of every
    pre-existing test identical to what it was before analysis/ existed;
    the analysis tests themselves are trace-only and order-independent."""
    def heavy(it):
        # test_por traces the same kernel set (plus every invariant
        # predicate) through the analyzers — same churn, same slot.
        # test_fused builds several whole engines (v2 + two v3 plans +
        # a mesh) back to back — the same trace-churn profile, so it
        # runs in the same trailing slot.
        # test_perf traces full chunk programs (all four pipelines +
        # a mesh) through the analyzer walk — same churn, same slot.
        # test_v4 builds a v2 baseline plus the forced-fallback engine
        # lattice — the heaviest engine-churn module of all.
        return ("test_analysis" in it.nodeid or "test_por" in it.nodeid
                or "test_fused" in it.nodeid or "test_perf" in it.nodeid
                or "test_v4" in it.nodeid)

    analysis = [it for it in items if heavy(it)]
    if analysis and len(analysis) < len(items):
        items[:] = [it for it in items if not heavy(it)] + analysis
