"""Test harness configuration.

Tests run on a virtual 8-device CPU mesh (SURVEY §4.5): the environment
variables below MUST be set before jax initializes its backend, which is why
they live at conftest import time.  Real-TPU execution is exercised by
``bench.py`` / ``__graft_entry__.py``, not the unit suite.
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
