"""Construction-time lane-width audit (schema.audit_lane_widths).

The reconfig value-wrap bug (ROUND5_NOTES: ``CFG_BASE + (old << 8) + new``
wrapping mod 256 in the uint8 queue rows, invisible at every depth where
no leader exists) was fixed point-wise with 2-byte value lanes; this
audit is the bug-CLASS killer: any packed field whose static domain
exceeds its lane width must fail at dims CONSTRUCTION with the field
named — never reach an engine where it would alias silently.
"""

import pytest

from raft_tla_tpu.models.dims import RaftDims
from raft_tla_tpu.models.reconfig import CFG_BASE, ReconfigDims


def test_valid_dims_pass_the_audit_across_the_domain():
    """Every legal base/reconfig dims constructs (the audit is not
    over-strict): sweep the corners of the constructor domain."""
    for n in range(1, 9):
        for v in (1, 255):
            for L in (1, 127):
                RaftDims(n_servers=n, n_values=v, max_log=L, n_msg_slots=4)
    for n in range(1, 8):
        ReconfigDims(n_servers=n, n_values=2, max_log=3, n_msg_slots=4,
                     targets=(1,))


def test_overflowing_value_domain_raises_at_build_with_field_named():
    """The historical bug shape: encoded values far beyond the value
    lane.  A variant declaring reconfig-style values but leaving
    value_bytes at 1 (exactly the pre-fix layout) must be rejected at
    construction, naming the value lane."""

    class WrapBugDims(RaftDims):
        # Pre-fix reconfig: joint encodings >= CFG_BASE in 1-byte lanes.
        @property
        def max_log_value(self):
            full = (1 << self.n_servers) - 1
            return CFG_BASE + (full << 8) + full

    with pytest.raises(ValueError, match="log_val"):
        WrapBugDims(n_servers=3, n_values=2, max_log=3, n_msg_slots=4)


def test_overflowing_two_byte_lane_raises_too():
    """Widening to 2 bytes shifts the bound, not the rule: a domain past
    65535 must still fail at build."""

    class Huge(ReconfigDims):
        @property
        def max_log_value(self):
            return 1 << 17

    with pytest.raises(ValueError, match="log_val"):
        Huge(n_servers=3, n_values=2, max_log=3, n_msg_slots=4,
             targets=(1,))


def test_reconfig_eight_servers_rejected_with_the_rule_named():
    """N=8 reconfig needs 17-bit joint encodings; the variant's own
    bound (clearer than the generic audit message) fires first."""
    with pytest.raises(ValueError, match="7 servers"):
        ReconfigDims(n_servers=8, n_values=2, max_log=3, n_msg_slots=4,
                     targets=(1,))


def test_audit_is_exercised_by_construction_not_only_directly():
    """The audit must run from __post_init__ itself (a variant author
    gets it for free), not require an explicit call."""

    class BigVals(RaftDims):
        @property
        def max_log_value(self):
            return 300   # > 255 in 1-byte lanes

    with pytest.raises(ValueError, match="max_log_value"):
        BigVals(n_servers=2, n_values=2, max_log=2, n_msg_slots=4)
