"""Simulation-mode tests: random walks, restarts, violation trace replay."""

import jax.numpy as jnp

from raft_tla_tpu.engine.simulate import Simulator
from raft_tla_tpu.models import oracle as orc
from raft_tla_tpu.models.dims import LEADER, RaftDims
from raft_tla_tpu.models.invariants import Bounds, build_constraint
from raft_tla_tpu.models.pystate import init_state

DIMS = RaftDims(n_servers=3, n_values=2, max_log=4, n_msg_slots=24)


def test_walkers_advance_and_restart():
    sim = Simulator(DIMS, constraint=build_constraint(
        DIMS, Bounds(max_term=2, max_log_len=1, max_msg_count=1)),
        batch=16, depth=8, chunk=32)
    res = sim.run([init_state(DIMS)], num_steps=16 * 32, seed=1)
    assert res.steps == 16 * 32
    assert res.traces > 16          # depth-8 bound forces restarts
    assert res.violation_invariant is None


def test_simulation_finds_violation_and_replays():
    # Seed one vote short of quorum so random walks stumble onto a leader.
    s0 = init_state(DIMS).replace(
        role=(1, 0, 0), current_term=(2, 2, 2), voted_for=(1, 1, 1),
        votes_responded=(0b001, 0, 0), votes_granted=(0b001, 0, 0),
        messages=frozenset({((1, 1, 0, 2, 1, ()), 1)}))
    sim = Simulator(
        DIMS, invariants={"NoLeader": lambda st: jnp.all(st.role != LEADER)},
        constraint=build_constraint(
            DIMS, Bounds(max_term=3, max_log_len=1, max_msg_count=1)),
        batch=32, depth=16, chunk=64)
    res = sim.run([s0], num_steps=32 * 64 * 8, seed=0)
    assert res.violation_invariant == "NoLeader"
    assert LEADER in res.violation_state.role
    # The latched trace replays to the violating state through legal
    # spec transitions (oracle-checked).
    trace = res.violation_trace
    assert trace[0][1] == s0
    assert trace[-1][1] == res.violation_state
    for (g_prev, s_prev), (g, s_next) in zip(trace, trace[1:]):
        assert s_next in orc.successor_set(s_prev, DIMS)


def test_simulation_checks_root_states():
    """TLC checks invariants on initial states; so must simulation mode
    (e.g. Smokeraft roots can violate TypeOK via negative matchIndex)."""
    from raft_tla_tpu.models.invariants import build_type_ok
    bad_root = init_state(DIMS).replace(match_index=((0, -1, 0),) + ((0,) * 3,) * 2)
    sim = Simulator(DIMS, invariants={"TypeOK": build_type_ok(DIMS)},
                    batch=8, depth=4, chunk=8)
    res = sim.run([bad_root], num_steps=64, seed=0)
    assert res.violation_invariant == "TypeOK"
    assert res.violation_state == bad_root
    assert res.violation_trace == [(-1, bad_root)]


def test_mesh_simulator_runs_and_finds_violation():
    """MeshSimulator: n independent walker fleets on the virtual 8-device
    mesh.  Clean model runs clean; the seeded near-election model latches
    a NoLeader violation on some chip and replays it to a legal trace."""
    from raft_tla_tpu.parallel.simulate import MeshSimulator
    cons = build_constraint(
        DIMS, Bounds(max_term=2, max_log_len=1, max_msg_count=1))
    sim = MeshSimulator(DIMS, constraint=cons, batch=8, depth=8, chunk=16)
    res = sim.run([init_state(DIMS)], num_steps=sim.n_dev * 8 * 16, seed=1)
    assert res.steps == sim.n_dev * 8 * 16
    assert res.traces > sim.n_dev * 8
    assert res.violation_invariant is None

    s0 = init_state(DIMS).replace(
        role=(1, 0, 0), current_term=(2, 2, 2), voted_for=(1, 1, 1),
        votes_responded=(0b001, 0, 0), votes_granted=(0b001, 0, 0),
        messages=frozenset({((1, 1, 0, 2, 1, ()), 1)}))
    sim = MeshSimulator(
        DIMS, invariants={"NoLeader": lambda st: jnp.all(st.role != LEADER)},
        constraint=build_constraint(
            DIMS, Bounds(max_term=3, max_log_len=1, max_msg_count=1)),
        batch=16, depth=16, chunk=32)
    res = sim.run([s0], num_steps=sim.n_dev * 16 * 32 * 8, seed=0)
    assert res.violation_invariant == "NoLeader"
    assert LEADER in res.violation_state.role
    trace = res.violation_trace
    assert trace[0][1] == s0
    assert trace[-1][1] == res.violation_state
    for (g_prev, s_prev), (g, s_next) in zip(trace, trace[1:]):
        assert s_next in orc.successor_set(s_prev, DIMS)
