"""v4 whole-chunk megakernel pipeline: plan policy + fallback lattice.

The v4 chunk (ops/pipeline_v4.py + ops/chunk_front_pallas.py) is the v2
delta pipeline with both halves fused: ONE front Pallas launch covering
masks + POR + compact + delta fingerprints (the parent-struct window
never leaves VMEM), then the same fused probe/insert->enqueue tail v3
ships.  Contracts proven here:

- the plan resolves the front as an atomic stage GROUP (forcing or
  failing any of masks/compact/fingerprint degrades all three, after
  which compact re-resolves per the v3 platform policy), fused tail and
  mesh constraints as in v3, with a recorded reason per non-fused stage;
- the RAFT_V4_FORCE env override ("stage=impl,...") merges over
  ``EngineConfig.v4_force_stages`` with env winning per stage — the
  no-plumbing hook the lattice test uses;
- the FALLBACK LATTICE: every v4 stage individually forced to its XLA
  fallback stays bit-identical to v2 on the pinned oracle prefix —
  counts, levels, and the recorded trace-link set — so degradation is
  invisible except to the launch accounting;
- mesh dryrun: --pipeline v4 on the virtual 8-device mesh (front
  degraded by the collective constraint) matches v2 exactly;
- the BLEST family grouping (models/actions.py family_groups) is
  attributed end-to-end: EngineResult.family_groups -> statespace
  report -> history-ledger summary.

Depth-limited prefixes keep tier-1 affordable (the full pinned L0-L9
and 46,553-state mesh dryrun differentials run the identical code
paths at more depth — verified at PR time, recorded in CHANGES.md).
Listed in tests/conftest.py's trace-heavy-last reorder: this module
builds more whole engines back to back than any other.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raft_tla_tpu.engine.bfs import BFSEngine, EngineConfig
from raft_tla_tpu.models.invariants import build_constraint
from raft_tla_tpu.ops import pipeline_v4
from raft_tla_tpu.utils.cfg import load_config

# ---------------------------------------------------------------------------
# Stage-plan resolution


def _front_ctx(dims):
    from raft_tla_tpu.models.actions2 import build_v2
    return {"dims": dims, "v2": build_v2(dims), "constraint": None,
            "inv_fns": None, "por_mask": None, "por_priority": None}


def test_v4_plan_policy_and_reasons():
    setup = load_config("configs/MCraft_bounded.cfg")
    dims = setup.dims
    from raft_tla_tpu.models.schema import state_width
    B, G, K = 16, dims.n_instances, 256
    sw = state_width(dims)
    ctx = _front_ctx(dims)

    plan = pipeline_v4.resolve_plan(B, G, K, Q=512, sw=sw, front_ctx=ctx)
    assert plan.front is not None and plan.tail is not None
    assert plan.stages == {s: "fused" for s in pipeline_v4.STAGES}
    assert pipeline_v4.describe(plan).startswith("masks=fused")

    # Forcing ANY front member degrades the whole group (the megakernel
    # has no partial configuration)...
    for member in pipeline_v4.FRONT_STAGES:
        deg = pipeline_v4.resolve_plan(B, G, K, Q=512, sw=sw,
                                       front_ctx=ctx,
                                       force={member: "xla"})
        assert deg.front is None
        for s in pipeline_v4.FRONT_STAGES:
            assert deg.stages[s] != "fused"
            assert member in deg.reasons[s] or "forced" in deg.reasons[s]
        # ...but the tail stays fused independently.
        assert deg.stages["insert"] == "fused"

    # Shape-only resolve (profiler probes, mesh precheck) degrades the
    # front with the no-context reason, never an exception.
    shp = pipeline_v4.resolve_plan(B, G, K, Q=512, sw=sw)
    assert shp.front is None
    assert "front" in shp.reasons["masks"]

    # Mesh: collectives keep both the front and the insert on XLA,
    # enqueue on the shard_map Pallas path — the v3 arrangement.
    mesh_plan = pipeline_v4.resolve_plan(B, G, K, Q=512, sw=sw,
                                         mesh=True, front_ctx=ctx)
    assert mesh_plan.front is None and mesh_plan.tail is None
    assert "collective" in mesh_plan.reasons["masks"]
    assert mesh_plan.stages["insert"] == "xla"
    assert mesh_plan.stages["enqueue"] == "pallas"

    # Typo'd force raises — a silently-ignored override would let a
    # forced-fallback differential pass vacuously.
    with pytest.raises(ValueError, match="v4_force_stages"):
        pipeline_v4.resolve_plan(B, G, K, Q=512, sw=sw,
                                 force={"masks": "Fused"})
    with pytest.raises(ValueError, match="v4_force_stages"):
        pipeline_v4.resolve_plan(B, G, K, Q=512, sw=sw,
                                 force={"front": "xla"})


def test_v4_env_force_overrides_config(monkeypatch):
    """RAFT_V4_FORCE merges over the config dict with env winning per
    stage; malformed env entries raise instead of silently running the
    kernel the test meant to disable."""
    monkeypatch.setenv(pipeline_v4.ENV_FORCE, "insert=xla")
    merged = pipeline_v4._merged_force({"insert": "fused",
                                        "compact": "xla"})
    assert merged == {"insert": "xla", "compact": "xla"}
    plan = pipeline_v4.resolve_plan(16, 132, 256, Q=512, sw=40)
    assert plan.tail is None
    assert plan.stages["insert"] == "xla"
    monkeypatch.setenv(pipeline_v4.ENV_FORCE, "insert")
    with pytest.raises(ValueError, match="RAFT_V4_FORCE"):
        pipeline_v4.resolve_plan(16, 132, 256, Q=512, sw=40)


def test_v4_plan_falls_back_when_front_cannot_build(monkeypatch):
    """A front kernel that cannot even construct must degrade the group
    to XLA with the failure recorded, never fail the engine build."""
    from raft_tla_tpu.ops import chunk_front_pallas as cfp
    setup = load_config("configs/MCraft_bounded.cfg")
    dims = setup.dims
    from raft_tla_tpu.models.schema import state_width

    def boom(**kw):
        raise RuntimeError("no mosaic for you")

    monkeypatch.setattr(cfp, "build_front", boom)
    plan = pipeline_v4.resolve_plan(16, dims.n_instances, 256, Q=512,
                                    sw=state_width(dims),
                                    front_ctx=_front_ctx(dims))
    assert plan.front is None
    assert "no mosaic for you" in plan.reasons["masks"]
    assert plan.stages["insert"] == "fused"   # tail unaffected


def test_v4_requires_v2_kernels():
    """pipeline='v4' on a dims variant without v2 kernels must raise
    (same rule as v3: never silently run the slow path when asked to
    fuse)."""
    from raft_tla_tpu.engine.bfs import _resolve_pipeline
    from raft_tla_tpu.models.actions2 import V2Unavailable
    from raft_tla_tpu.models.dims import RaftDims

    class NoV2(RaftDims):
        @property
        def extra_families(self):
            return (("Mystery", 2),)

    nov2 = NoV2(n_servers=2, n_values=1, max_log=2, n_msg_slots=8)
    with pytest.raises(V2Unavailable):
        _resolve_pipeline("v4", nov2)


# ---------------------------------------------------------------------------
# Engine-level differentials: the fallback lattice


def _run(dims, bounds, pipe, depth, force=None, env=None,
         monkeypatch=None):
    from raft_tla_tpu.models.pystate import init_state
    if env is not None:
        monkeypatch.setenv(pipeline_v4.ENV_FORCE, env)
    try:
        eng = BFSEngine(
            dims, constraint=build_constraint(dims, bounds),
            config=EngineConfig(batch=128, queue_capacity=1 << 14,
                                seen_capacity=1 << 16, record_trace=True,
                                check_deadlock=False, max_diameter=depth,
                                pipeline=pipe, v4_force_stages=force))
        res = eng.run([init_state(dims)])
        tf, tp, ta = eng.trace.export()
        links = set(zip(tf.tolist(), tp.tolist(), ta.tolist()))
        return res, links
    finally:
        if env is not None:
            monkeypatch.delenv(pipeline_v4.ENV_FORCE)


def test_v4_engine_matches_v2_pinned_prefix():
    """Single-chip --pipeline v4 (both megakernels fused) vs v2 through
    L6 (pinned oracle: 9,457 cumulative distinct): same counts, levels,
    verdict, AND the same recorded trace-link set."""
    setup = load_config("configs/MCraft_bounded.cfg")
    dims = setup.dims
    out = {}
    for pipe in ("v2", "v4"):
        res, links = _run(dims, setup.bounds, pipe, 6)
        assert res.distinct == 9457      # pinned oracle L6 cumulative
        out[pipe] = (res.distinct, res.generated, res.levels,
                     res.diameter, links)
        if pipe == "v4":
            assert res.pipeline == "v4"
            assert res.fused_stages == {s: "fused"
                                        for s in pipeline_v4.STAGES}
    assert out["v2"] == out["v4"]


@pytest.mark.slow   # five extra engine builds; nightly tier — tier-1
                    # keeps the all-fused prefix + mesh differentials
def test_v4_fallback_lattice_bit_identical(monkeypatch):
    """EVERY v4 stage individually forced to its XLA fallback via the
    RAFT_V4_FORCE env override stays bit-identical to v2 on the pinned
    prefix — counts, levels, and trace links.  Depth 4 keeps five extra
    engine builds affordable; the stage kernels run every chunk either
    way."""
    setup = load_config("configs/MCraft_bounded.cfg")
    dims = setup.dims
    base, base_links = _run(dims, setup.bounds, "v2", 4)
    want = (base.distinct, base.generated, base.levels, base_links)
    for stage in pipeline_v4.STAGES:
        res, links = _run(dims, setup.bounds, "v4", 4,
                          env=f"{stage}=xla", monkeypatch=monkeypatch)
        got = (res.distinct, res.generated, res.levels, links)
        assert got == want, f"forcing {stage}=xla broke bit-identity"
        assert res.fused_stages[stage] != "fused"
        if stage in pipeline_v4.FRONT_STAGES:
            # the whole front group degraded together
            assert all(res.fused_stages[s] != "fused"
                       for s in pipeline_v4.FRONT_STAGES)
            assert res.fused_stages["insert"] == "fused"


def test_v4_mesh_matches_v2():
    """Mesh --pipeline v4 on the virtual 8-device mesh: the front
    degrades by the collective constraint, results match v2 exactly —
    the dryrun-path acceptance differential at tier-1 depth."""
    from raft_tla_tpu.models.dims import RaftDims
    from raft_tla_tpu.models.invariants import Bounds
    from raft_tla_tpu.models.pystate import init_state
    from raft_tla_tpu.parallel.mesh import MeshBFSEngine
    dims = RaftDims(n_servers=3, n_values=2, max_log=4, n_msg_slots=24)
    bounds = Bounds(max_term=2, max_log_len=1, max_msg_count=1)
    out = {}
    for pipe in ("v2", "v4"):
        eng = MeshBFSEngine(
            dims, constraint=build_constraint(dims, bounds),
            config=EngineConfig(batch=16, queue_capacity=1 << 12,
                                seen_capacity=1 << 15,
                                check_deadlock=False, max_diameter=3,
                                pipeline=pipe))
        res = eng.run([init_state(dims)])
        out[pipe] = (res.distinct, res.generated, res.levels)
        if pipe == "v4":
            assert res.pipeline == "v4"
            assert res.fused_stages["masks"] == "xla"
            assert res.fused_stages["enqueue"] == "pallas"
    assert out["v2"] == out["v4"]


# ---------------------------------------------------------------------------
# Profiler granularity + BLEST family-group attribution


def test_v4_profiler_front_granularity():
    """--profile-chunks on a v4 engine samples the megakernel
    decomposition (front / insert_enqueue) and the result carries the
    v4 keys bench_diff folds."""
    from raft_tla_tpu.engine.check import initial_states, make_engine
    setup = load_config("configs/MCraft_bounded.cfg")
    eng = make_engine(setup, EngineConfig(
        batch=32, queue_capacity=1 << 12, seen_capacity=1 << 14,
        record_trace=False, check_deadlock=False, max_diameter=3,
        pipeline="v4", profile_chunks_every=1))
    res = eng.run(initial_states(setup))
    assert set(res.chunk_stages) == {"front", "insert_enqueue", "total"}
    prof = eng._profiler
    assert prof.summary()["pipeline"] == "v4"
    assert "front" in prof.render_table()


def test_family_groups_metadata_and_ledger(tmp_path):
    """models/actions.py family_groups: the base alphabet stacks into
    the four parameter-shape groups (10 families -> 4 launches), the
    grouping rides EngineResult -> statespace report -> history-ledger
    summary, so the BLEST win is attributable per family."""
    from raft_tla_tpu.models.actions import family_groups
    from raft_tla_tpu.models.pystate import init_state
    from raft_tla_tpu.obs import history as history_mod
    from raft_tla_tpu.obs.report import summarize
    setup = load_config("configs/MCraft_bounded.cfg")
    dims = setup.dims

    groups = family_groups(dims)
    by_name = {g["group"]: g for g in groups}
    assert set(by_name) == {"server", "server_pair", "server_value",
                            "slot"}
    assert by_name["server"]["kernels"] == 4
    assert by_name["server"]["families"] == ["Restart", "Timeout",
                                             "BecomeLeader",
                                             "AdvanceCommitIndex"]
    assert sum(g["lanes"] for g in groups) == dims.n_instances

    eng = BFSEngine(dims, constraint=build_constraint(dims, setup.bounds),
                    config=EngineConfig(batch=64, queue_capacity=1 << 12,
                                        seen_capacity=1 << 14,
                                        check_deadlock=False,
                                        max_diameter=2))
    res = eng.run([init_state(dims)])
    assert res.family_groups == groups
    assert res.report.get("family_groups") == groups
    summ = summarize(res.report)
    assert summ["family_groups"] == {"server": 4, "server_pair": 2,
                                     "server_value": 1, "slot": 3}

    ledger = str(tmp_path / "ledger.jsonl")
    history_mod.append_entry(
        ledger, history_mod.entry_from_result("check", res,
                                              label="v4_test"))
    entry = history_mod.read_history(ledger)[0]
    assert entry["report"]["family_groups"]["server"] == 4
