"""Every scripts/*.py entry point must run from a fresh clone (round 2
proved they rot silently; VERDICT r3 item 9).  Each is smoke-invoked in a
subprocess on CPU with tiny sizes — exit 0 and a sanity-check of stdout is
the contract; real measurement happens on hardware via tpu_session.sh."""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_script(args, extra_env=None, timeout=600):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)      # scripts run single-device
    env.update(extra_env or {})
    proc = subprocess.run([sys.executable] + args, cwd=REPO, env=env,
                          capture_output=True, text=True, timeout=timeout)
    assert proc.returncode == 0, (
        f"{args} failed rc={proc.returncode}\n--- stdout\n{proc.stdout}"
        f"\n--- stderr\n{proc.stderr[-3000:]}")
    return proc.stdout


@pytest.mark.slow   # ~2 min CPU; the hardware form is tpu_session stage 3
def test_profile_step_runs():
    out = run_script(["scripts/profile_step.py", "64"])
    assert "expand" in out and "insert" in out


def test_profile_fpset_runs():
    out = run_script(["scripts/profile_fpset.py"],
                     extra_env={"FPSET_C": str(1 << 14),
                                "FPSET_K": str(1 << 10)})
    assert "hash insert" in out


@pytest.mark.slow   # ~1 min CPU; hardware form is tpu_session stage 2
def test_true_bench_runs():
    out = run_script(["scripts/true_bench.py"],
                     extra_env={"TB_BATCH": "64"})
    assert "ms/iter" in out


@pytest.mark.slow   # ~2 min CPU; hardware form is tpu_session stage 4
def test_leader_bench_runs():
    """The leader-rich bench must actually exercise the log-machinery
    kernels (ClientRequest/AppendEntries/AdvanceCommitIndex > 0 is asserted
    inside the script itself)."""
    out = run_script(["scripts/leader_bench.py", "3", "64"])
    rec = json.loads(out.strip().splitlines()[-1])
    assert rec["leader_family_share"] > 0.05
    assert rec["seeds"] > 0


def test_oracle_exhaust_level_capped(tmp_path):
    out = run_script(["scripts/oracle_exhaust.py",
                      "configs/MCraft_bounded.cfg",
                      str(tmp_path / "oracle.jsonl"), "2"])
    rec = json.loads(out.strip().splitlines()[-1])
    # Level-2 prefix of the pinned MCraft_bounded profile
    # (tests/test_engine.py::MCRAFT_BOUNDED_LEVELS, oracle_exhaust.jsonl).
    assert rec["levels"] == [1, 3, 18]
    assert rec["distinct"] == 22 and rec["generated"] == 33
    assert rec["diameter"] == 2


@pytest.mark.slow   # ~1 min CPU; bench.py is exercised by the CI bench_diff steps
def test_bench_runs_with_tiny_budget():
    out = run_script(["bench.py"], extra_env={"BENCH_SECONDS": "3"},
                     timeout=900)
    rec = json.loads(out.strip().splitlines()[-1])
    assert {"metric", "value", "unit", "vs_baseline"} <= set(rec)
    # Telemetry (obs/): the per-phase wall-time breakdown BENCH_r06+
    # carries; the script itself exits nonzero if the run's event log is
    # missing or malformed, so reaching here also proves that gate.
    assert rec["phases"] and "stats_fetch" in rec["phases"]


# ---------------------------------------------------------------------------
# scripts/bench_diff.py — the regression gate (no jax; imported in-process
# so the rc contract is tested without a subprocess per case).

def _bench_diff_main():
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "bench_diff", os.path.join(REPO, "scripts", "bench_diff.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod.main


def _fake_bench(value=1000.0, gen=4000.0, **over):
    doc = {"metric": "distinct_states_per_sec", "value": value,
           "unit": "states/s", "generated_per_sec": gen,
           "distinct_states": 100000,
           "phases": {"chunk": 40.0, "stats_fetch": 5.0, "warmup": 2.0},
           "chunk_stages": {"expand": 0.050, "fingerprint": 0.010,
                            "dedup_insert": 0.015, "enqueue": 0.020,
                            "total": 0.060},
           "coverage": {"Timeout": {"generated": 600, "distinct": 300,
                                    "disabled": 0},
                        "Receive": {"generated": 400, "distinct": 100,
                                    "disabled": 200}}}
    doc.update(over)
    return doc


def test_bench_diff_trajectory_and_self_compare_pass(capsys):
    main = _bench_diff_main()
    # The real BENCH_r* trajectory (wrapper form) must stay green...
    assert main([os.path.join(REPO, "BENCH_r04.json"),
                 os.path.join(REPO, "BENCH_r05.json")]) == 0
    # ...and self-compare is exactly zero-delta.
    assert main([os.path.join(REPO, "BENCH_r05.json"),
                 os.path.join(REPO, "BENCH_r05.json")]) == 0
    assert "PASS" in capsys.readouterr().out


def test_bench_diff_flags_regressions(tmp_path, capsys):
    main = _bench_diff_main()
    old, new = tmp_path / "old.json", tmp_path / "new.json"
    old.write_text(json.dumps(_fake_bench()))
    # 2x headline slowdown -> rc 1 (the acceptance case).
    new.write_text(json.dumps(_fake_bench(value=500.0, gen=2000.0)))
    assert main([str(old), str(new)]) == 1
    assert "REGRESSION" in capsys.readouterr().out
    # A single chunk stage blowing past its threshold -> rc 1.
    stages = dict(_fake_bench()["chunk_stages"], dedup_insert=0.200)
    new.write_text(json.dumps(_fake_bench(chunk_stages=stages)))
    assert main([str(old), str(new)]) == 1
    assert "dedup_insert" in capsys.readouterr().out
    # Coverage-mix drift (action shares shifted well past 5 pts) -> rc 1.
    cov = {"Timeout": {"generated": 100, "distinct": 50, "disabled": 0},
           "Receive": {"generated": 900, "distinct": 200, "disabled": 0}}
    new.write_text(json.dumps(_fake_bench(coverage=cov)))
    assert main([str(old), str(new)]) == 1
    assert "coverage mix drift" in capsys.readouterr().out
    # Within-threshold wobble passes.
    new.write_text(json.dumps(_fake_bench(value=950.0, gen=3900.0)))
    assert main([str(old), str(new)]) == 0
    # Thresholds are configurable: the same wobble fails at 1%.
    assert main([str(old), str(new), "--max-regress", "0.01"]) == 1


def test_bench_diff_pruned_fraction_is_gated(tmp_path, capsys):
    """The POR pruned fraction is a first-class compared metric: a
    collapsed reduction (baseline pruned, candidate back to full
    expansion) regresses; matched fractions pass with the note; runs
    that never pruned stay silent on the axis."""
    main = _bench_diff_main()
    old, new = tmp_path / "old.json", tmp_path / "new.json"

    def cov(pruned_t, pruned_r):
        return {"Timeout": {"generated": 600, "distinct": 300,
                            "disabled": 0, "pruned": pruned_t},
                "Receive": {"generated": 400, "distinct": 100,
                            "disabled": 200, "pruned": pruned_r}}

    old.write_text(json.dumps(_fake_bench(coverage=cov(100, 50))))
    new.write_text(json.dumps(_fake_bench(coverage=cov(100, 50))))
    assert main([str(old), str(new)]) == 0
    assert "POR pruned expansions" in capsys.readouterr().out
    # Collapse to zero pruning -> regression past --pruned-drift.
    new.write_text(json.dumps(_fake_bench(coverage=cov(0, 0))))
    assert main([str(old), str(new)]) == 1
    out = capsys.readouterr().out
    assert "pruned fraction fell" in out
    # ... but an explicit loose threshold lets it through.
    assert main([str(old), str(new), "--pruned-drift", "50"]) == 0
    capsys.readouterr()
    # No pruning anywhere: the axis stays silent (legacy benches).
    old.write_text(json.dumps(_fake_bench()))
    new.write_text(json.dumps(_fake_bench()))
    assert main([str(old), str(new)]) == 0
    assert "POR pruned" not in capsys.readouterr().out


def test_bench_diff_folds_mismatched_stage_granularities(tmp_path, capsys):
    """A v2 bench (classical stage keys) vs a v3 bench (fused-stage
    keys) must still diff: both sides fold to the common coarse stages
    (front / fingerprint / tail / total) with a note — a cross-pipeline
    comparison is a diff, not a refusal, and a genuine folded-stage
    blow-up still gates."""
    main = _bench_diff_main()
    old, new = tmp_path / "v2.json", tmp_path / "v3.json"
    old.write_text(json.dumps(_fake_bench()))
    v3_stages = {"masks": 0.030, "compact": 0.018, "fingerprint": 0.011,
                 "insert_enqueue": 0.037, "total": 0.058}
    new.write_text(json.dumps(_fake_bench(
        chunk_stages=v3_stages, pipeline="v3",
        fused_stages={"insert": "fused", "enqueue": "fused"})))
    assert main([str(old), str(new)]) == 0
    out = capsys.readouterr().out
    assert "granularities differ" in out and "folded to common" in out
    assert "chunk stage front" in out and "chunk stage tail" in out
    # The folded comparison still gates: a fused tail 10x the old
    # insert+enqueue sum regresses.
    v3_bad = dict(v3_stages, insert_enqueue=0.350)
    new.write_text(json.dumps(_fake_bench(chunk_stages=v3_bad,
                                          pipeline="v3")))
    assert main([str(old), str(new)]) == 1
    assert "chunk stage 'tail'" in capsys.readouterr().out


def test_bench_diff_malformed_inputs_exit_2(tmp_path, capsys):
    main = _bench_diff_main()
    ok = tmp_path / "ok.json"
    ok.write_text(json.dumps(_fake_bench()))
    # Missing file.
    assert main([str(tmp_path / "nope.json"), str(ok)]) == 2
    # Not JSON at all.
    bad = tmp_path / "bad.json"
    bad.write_text("{nope")
    assert main([str(bad), str(ok)]) == 2
    # A BENCH_r* wrapper whose run never emitted JSON (parsed: null).
    bad.write_text(json.dumps({"cmd": "x", "rc": 1, "parsed": None}))
    assert main([str(ok), str(bad)]) == 2
    err = capsys.readouterr().err
    assert "bench_diff:" in err
