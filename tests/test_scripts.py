"""Every scripts/*.py entry point must run from a fresh clone (round 2
proved they rot silently; VERDICT r3 item 9).  Each is smoke-invoked in a
subprocess on CPU with tiny sizes — exit 0 and a sanity-check of stdout is
the contract; real measurement happens on hardware via tpu_session.sh."""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_script(args, extra_env=None, timeout=600):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)      # scripts run single-device
    env.update(extra_env or {})
    proc = subprocess.run([sys.executable] + args, cwd=REPO, env=env,
                          capture_output=True, text=True, timeout=timeout)
    assert proc.returncode == 0, (
        f"{args} failed rc={proc.returncode}\n--- stdout\n{proc.stdout}"
        f"\n--- stderr\n{proc.stderr[-3000:]}")
    return proc.stdout


def test_profile_step_runs():
    out = run_script(["scripts/profile_step.py", "64"])
    assert "expand" in out and "insert" in out


def test_profile_fpset_runs():
    out = run_script(["scripts/profile_fpset.py"],
                     extra_env={"FPSET_C": str(1 << 14),
                                "FPSET_K": str(1 << 10)})
    assert "hash insert" in out


def test_true_bench_runs():
    out = run_script(["scripts/true_bench.py"],
                     extra_env={"TB_BATCH": "64"})
    assert "ms/iter" in out


def test_leader_bench_runs():
    """The leader-rich bench must actually exercise the log-machinery
    kernels (ClientRequest/AppendEntries/AdvanceCommitIndex > 0 is asserted
    inside the script itself)."""
    out = run_script(["scripts/leader_bench.py", "3", "64"])
    rec = json.loads(out.strip().splitlines()[-1])
    assert rec["leader_family_share"] > 0.05
    assert rec["seeds"] > 0


def test_oracle_exhaust_level_capped(tmp_path):
    out = run_script(["scripts/oracle_exhaust.py",
                      "configs/MCraft_bounded.cfg",
                      str(tmp_path / "oracle.jsonl"), "2"])
    rec = json.loads(out.strip().splitlines()[-1])
    # Level-2 prefix of the pinned MCraft_bounded profile
    # (tests/test_engine.py::MCRAFT_BOUNDED_LEVELS, oracle_exhaust.jsonl).
    assert rec["levels"] == [1, 3, 18]
    assert rec["distinct"] == 22 and rec["generated"] == 33
    assert rec["diameter"] == 2


def test_bench_runs_with_tiny_budget():
    out = run_script(["bench.py"], extra_env={"BENCH_SECONDS": "3"},
                     timeout=900)
    rec = json.loads(out.strip().splitlines()[-1])
    assert {"metric", "value", "unit", "vs_baseline"} <= set(rec)
    # Telemetry (obs/): the per-phase wall-time breakdown BENCH_r06+
    # carries; the script itself exits nonzero if the run's event log is
    # missing or malformed, so reaching here also proves that gate.
    assert rec["phases"] and "stats_fetch" in rec["phases"]
