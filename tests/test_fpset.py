"""Direct FPSet property tests (the engines exercise it indirectly).

The insert path has two performance-driven subtleties that need their own
regression coverage:

- scatters are value-neutral (identity-element combiners), never routed to
  a shared drop index — see the design notes in ops/fpset.py;
- the claim table may be smaller than the key table (``CLAIM_CAP``), so
  distinct slots can alias one claim entry; claims are round-tagged
  (``r*kp + lane`` under a max combiner), so a round-r attempt always
  supersedes any stale entry from an earlier round — no reset scatter,
  and an alias can never eclipse a later round's attempt (without the
  tags, stale winner ids would starve aliased lanes into spurious
  ``fail``).

The test forces the capped path with a tiny cap and checks exact set
semantics against a Python set under heavy duplication across many batches.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from raft_tla_tpu.ops import fpset
import raft_tla_tpu.ops.fpset as fp


@pytest.mark.parametrize("claim_cap", [1 << 10, 1 << 30])
def test_insert_matches_set_semantics(claim_cap, monkeypatch):
    """Exact distinct counting vs a Python set, duplicate-heavy batches,
    load driven past 0.25, both the capped and uncapped claim paths."""
    monkeypatch.setattr(fp, "CLAIM_CAP", claim_cap)
    rng = np.random.RandomState(7)
    s = fpset.empty(1 << 16)
    ins = jax.jit(fp.insert)
    ref = set()
    for it in range(8):
        # keys drawn from a small universe => heavy in-batch duplication
        keys = rng.randint(0, 1 << 14, size=2048).astype(np.uint64)
        hi = jnp.asarray((keys >> 32).astype(np.uint32) | np.uint32(it))
        lo = jnp.asarray(keys.astype(np.uint32))
        valid = jnp.asarray(rng.rand(2048) < 0.7)
        s, new, fail = ins(s, hi, lo, valid)
        assert not bool(fail), f"spurious probe failure at iter {it}"
        pairs = {(int(h) | it, int(l))
                 for h, l, v in zip(keys >> 32, keys, np.asarray(valid))
                 if v}
        fresh = pairs - ref
        assert int(new.sum()) == len(fresh)
        ref |= pairs
        assert int(s.size) == len(ref)
    hi = jnp.asarray(np.array([h for h, _ in ref], np.uint32))
    lo = jnp.asarray(np.array([l for _, l in ref], np.uint32))
    assert bool(fp.contains(s, hi, lo).all())
    # absent keys (drawn far outside the key universe) report False
    assert not bool(fp.contains(
        s, hi | jnp.uint32(1 << 20), lo).any())


def test_insert_reports_fail_when_genuinely_full():
    """Overfilling a tiny table must set fail, never silently drop keys."""
    s = fpset.empty(1 << 8)
    hi = jnp.asarray(np.arange(512, dtype=np.uint32))
    lo = jnp.asarray(np.arange(512, dtype=np.uint32) * 7 + 1)
    s, new, fail = fp.insert(s, hi, lo, jnp.ones((512,), bool))
    assert bool(fail)
