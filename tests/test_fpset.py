"""Direct FPSet property tests (the engines exercise it indirectly).

The insert path has two performance-driven subtleties that need their own
regression coverage:

- scatters are value-neutral (identity-element combiners), never routed to
  a shared drop index — see the design notes in ops/fpset.py;
- the claim table may be smaller than the key table (``CLAIM_CAP``), so
  distinct slots can alias one claim entry; claims are round-tagged
  (``r*kp + lane`` under a max combiner), so a round-r attempt always
  supersedes any stale entry from an earlier round — no reset scatter,
  and an alias can never eclipse a later round's attempt (without the
  tags, stale winner ids would starve aliased lanes into spurious
  ``fail``).

The test forces the capped path with a tiny cap and checks exact set
semantics against a Python set under heavy duplication across many batches.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from raft_tla_tpu.ops import fpset
import raft_tla_tpu.ops.fpset as fp


@pytest.mark.parametrize("claim_cap", [1 << 10, 1 << 30])
def test_insert_matches_set_semantics(claim_cap, monkeypatch):
    """Exact distinct counting vs a Python set, duplicate-heavy batches,
    load driven past 0.25, both the capped and uncapped claim paths."""
    monkeypatch.setattr(fp, "CLAIM_CAP", claim_cap)
    rng = np.random.RandomState(7)
    s = fpset.empty(1 << 16)
    ins = jax.jit(fp.insert)
    ref = set()
    for it in range(8):
        # keys drawn from a small universe => heavy in-batch duplication
        keys = rng.randint(0, 1 << 14, size=2048).astype(np.uint64)
        hi = jnp.asarray((keys >> 32).astype(np.uint32) | np.uint32(it))
        lo = jnp.asarray(keys.astype(np.uint32))
        valid = jnp.asarray(rng.rand(2048) < 0.7)
        s, new, fail = ins(s, hi, lo, valid)
        assert not bool(fail), f"spurious probe failure at iter {it}"
        pairs = {(int(h) | it, int(l))
                 for h, l, v in zip(keys >> 32, keys, np.asarray(valid))
                 if v}
        fresh = pairs - ref
        assert int(new.sum()) == len(fresh)
        ref |= pairs
        assert int(s.size) == len(ref)
    hi = jnp.asarray(np.array([h for h, _ in ref], np.uint32))
    lo = jnp.asarray(np.array([l for _, l in ref], np.uint32))
    assert bool(fp.contains(s, hi, lo).all())
    # absent keys (drawn far outside the key universe) report False
    assert not bool(fp.contains(
        s, hi | jnp.uint32(1 << 20), lo).any())


def test_insert_reports_fail_when_genuinely_full():
    """Overfilling a tiny table must set fail, never silently drop keys."""
    s = fpset.empty(1 << 8)
    hi = jnp.asarray(np.arange(512, dtype=np.uint32))
    lo = jnp.asarray(np.arange(512, dtype=np.uint32) * 7 + 1)
    s, new, fail = fp.insert(s, hi, lo, jnp.ones((512,), bool))
    assert bool(fail)


def test_pallas_insert_matches_xla_insert():
    """ops/fpset_pallas.py: the sequential-grid Pallas insert must match
    the XLA claim-protocol insert on the observable contract — is_new
    (exactly one query per distinct new key, same index), size, fail,
    stored key set, and subsequent `contains` — across duplicate-heavy
    batches on BOTH tables as they fill.  Raw slot layout may differ
    (documented in the module header), so tables are compared as sorted
    key sets, not arrays."""
    from raft_tla_tpu.ops import fpset_pallas

    rng = np.random.RandomState(11)
    s_x = fpset.empty(1 << 12)
    s_p = fpset.empty(1 << 12)
    ins_x = jax.jit(fp.insert)
    ref = set()
    for it in range(6):
        keys = rng.randint(0, 1 << 10, size=512).astype(np.uint64)
        hi = jnp.asarray((keys >> 5).astype(np.uint32) + np.uint32(it * 131))
        lo = jnp.asarray(keys.astype(np.uint32))
        valid = jnp.asarray(rng.rand(512) < 0.75)
        s_x, new_x, fail_x = ins_x(s_x, hi, lo, valid)
        s_p, new_p, fail_p = fpset_pallas.insert(s_p, hi, lo, valid)
        assert (np.asarray(new_x) == np.asarray(new_p)).all(), f"iter {it}"
        assert bool(fail_x) == bool(fail_p) == False  # noqa: E712
        assert int(s_x.size) == int(s_p.size)
        ref |= {(int(h), int(l))
                for h, l, v in zip(np.asarray(hi), np.asarray(lo),
                                   np.asarray(valid)) if v}
        assert int(s_p.size) == len(ref)
    kx = fpset.to_host_keys(s_x)
    kp = fpset.to_host_keys(s_p)
    assert (kx[0] == kp[0]).all() and (kx[1] == kp[1]).all()
    # Cross-membership: keys inserted by the Pallas path are found by the
    # XLA probe over the Pallas-laid-out table (the chain invariant holds
    # for sequential layouts too).
    hi = jnp.asarray(np.array([h for h, _ in sorted(ref)], np.uint32))
    lo = jnp.asarray(np.array([l for _, l in sorted(ref)], np.uint32))
    assert bool(fp.contains(s_p, hi, lo).all())
    assert not bool(fp.contains(s_p, hi ^ jnp.uint32(1 << 30), lo).any())


def test_pallas_enqueue_matches_scatter_reference():
    """ops/enqueue_pallas.py: live queue rows [0, next_count') identical
    to the scatter lowering for adversarial masks — empty, full, single
    lanes, runs ending at K-1, run lengths straddling the SEG quantum —
    and the overhang never lands outside [next_count'+0, +SEG)."""
    from raft_tla_tpu.ops import enqueue_pallas as ep

    rng = np.random.RandomState(3)
    K, SW, QA = 256, 37, 1024
    masks = [
        np.zeros(K, bool),
        np.ones(K, bool),
        np.eye(1, K, 0, dtype=bool)[0],           # single first lane
        np.eye(1, K, K - 1, dtype=bool)[0],       # single last lane
    ]
    m = np.zeros(K, bool)
    m[5:5 + ep.SEG + 3] = True                    # one run straddling SEG
    masks.append(m)
    for _ in range(6):
        masks.append(rng.rand(K) < rng.choice([0.1, 0.5, 0.9]))
    for t, mask in enumerate(masks):
        krows = jnp.asarray(rng.randint(0, 255, (K, SW)), jnp.uint8)
        base = jnp.asarray(rng.randint(0, 255, (QA, SW)), jnp.uint8)
        nc = int(rng.randint(0, QA - 2 * K))
        enq = jnp.asarray(mask)
        got = np.asarray(ep.enqueue(base, jnp.int32(nc), krows, enq))
        # scatter reference (chunk.py semantics, live region only)
        want = np.asarray(base).copy()
        want[nc:nc + int(mask.sum())] = np.asarray(krows)[mask]
        end = nc + int(mask.sum())
        assert (got[:end] == want[:end]).all(), f"mask {t}: live rows"
        # overhang confined to < SEG rows past the live region
        assert (got[end + ep.SEG:] == want[end + ep.SEG:]).all(), \
            f"mask {t}: wrote beyond the overhang window"


def test_pallas_insert_reports_fail_when_genuinely_full():
    s = fpset.empty(1 << 8)
    from raft_tla_tpu.ops import fpset_pallas
    hi = jnp.asarray(np.arange(512, dtype=np.uint32))
    lo = jnp.asarray(np.arange(512, dtype=np.uint32) * 7 + 1)
    _s, _new, fail = fpset_pallas.insert(s, hi, lo, jnp.ones((512,), bool))
    assert bool(fail)
