"""Worker for the multi-host simulation test (not a pytest module).

Launched twice by tests/test_multihost.py; each process owns 2 virtual
CPU devices and they form one global 4-device mesh.  Prints one JSON line
with the replicated results — the test asserts both processes report the
SAME violation (the whole point: every host reads identical psum'd
outputs)."""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from raft_tla_tpu.utils.platform import neutralize_axon_if_cpu_requested

neutralize_axon_if_cpu_requested()

from raft_tla_tpu.parallel import multihost as mh  # noqa: E402

mh.initialize()    # RAFT_COORDINATOR / RAFT_NUM_PROCESSES / RAFT_PROCESS_ID

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from raft_tla_tpu.models.dims import LEADER, RaftDims  # noqa: E402
from raft_tla_tpu.models.invariants import Bounds, build_constraint  # noqa: E402
from raft_tla_tpu.models.pystate import init_state  # noqa: E402
from raft_tla_tpu.parallel.simulate import MeshSimulator  # noqa: E402


def main():
    assert jax.process_count() == int(os.environ["RAFT_NUM_PROCESSES"])
    dims = RaftDims(n_servers=3, n_values=2, max_log=4, n_msg_slots=24)
    sim = MeshSimulator(
        dims,
        invariants={"NoLeader": lambda st: jnp.all(st.role != LEADER)},
        constraint=build_constraint(
            dims, Bounds(max_term=2, max_log_len=1, max_msg_count=1)),
        batch=16, depth=24, chunk=8)
    assert sim.n_dev == len(jax.devices())    # the GLOBAL mesh
    # Root a candidate one vote short of quorum (tests/test_engine.py
    # seeding trick): random walkers reach BecomeLeader within a couple of
    # steps, so the latch + cross-host broadcast path actually fires.
    s0 = init_state(dims).replace(
        role=(1, 0, 0), current_term=(2, 2, 2), voted_for=(1, 1, 1),
        votes_responded=(0b001, 0, 0), votes_granted=(0b001, 0, 0),
        messages=frozenset({((1, 1, 0, 2, 1, ()), 1)}))  # RVR grant r2->r1
    res = sim.run([s0], num_steps=1 << 16, seed=7)
    print(json.dumps({
        "process": jax.process_index(),
        "global_devices": len(jax.devices()),
        "local_devices": len(jax.local_devices()),
        "steps": res.steps,
        "traces": res.traces,
        "violation": res.violation_invariant,
        "trace_len": (len(res.violation_trace)
                      if res.violation_trace else None),
    }))


if __name__ == "__main__":
    main()
