"""Joint-consensus reconfiguration (models/reconfig.py) tests.

Three tiers: semantic unit tests of the new actions and the joint-quorum
rule on hand-built states; differential tests (JAX kernels vs the Python
oracle, both extended through the RaftDims variant hooks); and an
end-to-end engine run on configs/reconfig3.cfg whose distinct-state count
must match the oracle BFS exactly.
"""

import jax
import pytest

from raft_tla_tpu.models import oracle as orc
from raft_tla_tpu.models.actions import build_expand
from raft_tla_tpu.models.dims import CANDIDATE, LEADER
from raft_tla_tpu.models.pystate import init_state
from raft_tla_tpu.models.reconfig import (A_FINALIZE, A_INITRECONFIG,
                                          CFG_BASE, ReconfigDims,
                                          config_of_py, final_value,
                                          joint_value)
from raft_tla_tpu.models.schema import StateBatch, decode_state, encode_state

DIMS = ReconfigDims(n_servers=3, n_values=1, max_log=5, n_msg_slots=16,
                    targets=(3, 7))
FULL = 7


@pytest.fixture(scope="module")
def expand():
    return jax.jit(build_expand(DIMS))


def kernel_successors(expand, s):
    st = encode_state(s, DIMS)
    cands, enabled, overflow = jax.device_get(expand(st))
    assert not overflow.any(), "fixed-width overflow on test state"
    out = []
    for g in range(DIMS.n_instances):
        if enabled[g]:
            row = jax.tree.map(lambda a: a[g], cands)
            out.append(decode_state(StateBatch(*row), DIMS))
    return out


def assert_matches_oracle(expand, s):
    got = kernel_successors(expand, s)
    want = orc.successors(s, DIMS)
    assert len(got) == len(want), (
        f"enabled-instance count {len(got)} != oracle {len(want)}\n{s}")
    assert set(got) == {t for _a, t in want}, f"successor sets differ for\n{s}"


def leader_state(log=(), commit=0, votes=0b111):
    """A term-2 leader r0 with the given log, others followers."""
    s = init_state(DIMS)
    return s.replace(
        role=(LEADER, 0, 0),
        current_term=(2, 1, 1),
        votes_granted=(votes, 0, 0),
        log=(tuple(log), (), ()),
        commit_index=(commit, 0, 0),
        next_index=((len(log) + 1,) * 3, (1,) * 3, (1,) * 3))


# ---------------------------------------------------------------------------
# config_of / encoding

def test_config_of_default_is_full_membership():
    assert config_of_py((), 3) == (0, FULL, 0)
    assert config_of_py(((2, 1),), 3) == (0, FULL, 0)   # client entry only


def test_config_of_latest_entry_wins():
    log = ((2, joint_value(7, 3)), (2, 1), (2, final_value(3)))
    assert config_of_py(log, 3) == (0, 3, 3)
    assert config_of_py(log[:2], 3) == (7, 3, 1)        # joint is latest


def test_value_ok_accepts_config_entries():
    assert DIMS.value_ok_py(1)
    assert not DIMS.value_ok_py(2)              # only one client value
    assert DIMS.value_ok_py(joint_value(7, 3))
    assert DIMS.value_ok_py(final_value(3))
    assert not DIMS.value_ok_py(CFG_BASE)       # new_mask must be nonempty


# ---------------------------------------------------------------------------
# action semantics (oracle side)

def test_initiate_requires_leader_with_final_config():
    s = leader_state()
    succ = dict(DIMS.extra_successors_py(s))
    # r0 may initiate a move to {r1,r2} (mask 3) but not to the current
    # config (mask 7 == default full membership).
    keys = list(succ)
    assert (A_INITRECONFIG, (0, 3)) in keys
    assert (A_INITRECONFIG, (0, 7)) not in keys
    assert not any(k[0] == A_FINALIZE for k in keys)
    t = succ[(A_INITRECONFIG, (0, 3))]
    assert t.log[0][-1] == (2, joint_value(7, 3))


def test_no_overlapping_reconfig():
    """A leader whose latest config is joint cannot initiate another."""
    s = leader_state(log=((2, joint_value(7, 3)),))
    keys = [k for k, _t in DIMS.extra_successors_py(s)]
    assert not any(k[0] == A_INITRECONFIG for k in keys)


def test_finalize_only_after_joint_committed():
    joint_log = ((2, joint_value(7, 3)),)
    uncommitted = leader_state(log=joint_log, commit=0)
    assert not any(k[0] == A_FINALIZE
                   for k, _t in DIMS.extra_successors_py(uncommitted))
    committed = leader_state(log=joint_log, commit=1)
    succ = dict(DIMS.extra_successors_py(committed))
    t = succ[(A_FINALIZE, (0,))]
    assert t.log[0][-1] == (2, final_value(3))


def test_joint_quorum_needs_both_majorities():
    """Under C_old,new = ({r1,r2,r3}, {r1,r2}), {r1,r3} is a majority of
    C_old but not of C_new — not a quorum; {r1,r2} is a majority of both."""
    s = leader_state(log=((2, joint_value(7, 3)),))
    assert not DIMS.quorum_py(s, 0, 0b101)
    assert DIMS.quorum_py(s, 0, 0b011)
    # Under the final config {r1,r2}, r1+r2 remains a quorum and r1+r3
    # is not ({r3} contributes nothing to C_new).
    s2 = leader_state(log=((2, final_value(3)),))
    assert DIMS.quorum_py(s2, 0, 0b011)
    assert not DIMS.quorum_py(s2, 0, 0b101)


def test_election_under_joint_config():
    """A candidate with votes {r1,r3} wins under the full config but NOT
    when its log holds the joint entry C_{r1r2r3},{r1,r2}."""
    base = init_state(DIMS)
    cand = base.replace(role=(CANDIDATE, 0, 0), current_term=(2, 1, 1),
                        votes_granted=(0b101, 0, 0))
    assert orc.become_leader(cand, DIMS, 0) is not None
    joint = cand.replace(log=(((1, joint_value(7, 3)),), (), ()))
    assert orc.become_leader(joint, DIMS, 0) is None
    both = cand.replace(log=(((1, joint_value(7, 3)),), (), ()),
                        votes_granted=(0b011, 0, 0))
    assert orc.become_leader(both, DIMS, 0) is not None


def test_truncation_reverts_configuration():
    """ConflictAppendEntriesRequest semantics: losing the tail config entry
    falls back to the previous configuration."""
    log = ((2, final_value(3)), (2, joint_value(3, 7)))
    assert config_of_py(log, 3) == (3, 7, 2)
    assert config_of_py(log[:1], 3) == (0, 3, 1)


# ---------------------------------------------------------------------------
# differential: kernels vs oracle

def test_init_successors(expand):
    assert_matches_oracle(expand, init_state(DIMS))


def test_two_bfs_levels(expand):
    res = orc.bfs([init_state(DIMS)], DIMS, max_levels=2)
    for s in res.parent:
        assert_matches_oracle(expand, s)


def test_reconfig_rich_states(expand):
    """States seeded with config entries in every phase of a membership
    change (joint pending, joint committed, finalized), plus their BFS
    offspring."""
    seeds = [
        leader_state(log=((2, joint_value(7, 3)),)),
        leader_state(log=((2, joint_value(7, 3)),), commit=1),
        leader_state(log=((2, final_value(3)), (2, 1))),
        leader_state(log=((2, final_value(3)), (2, joint_value(3, 7))),
                     commit=1),
    ]
    res = orc.bfs(seeds, DIMS, max_levels=1)
    for s in res.parent:
        assert_matches_oracle(expand, s)


def test_deeper_reachable_sample(expand):
    def constraint(t, d):
        return (max(t.current_term) <= 3
                and max(len(l) for l in t.log) <= 2
                and all(c <= 1 for _m, c in t.messages))
    res = orc.bfs([init_state(DIMS)], DIMS, constraint=constraint,
                  max_levels=4)
    sample = sorted(res.parent, key=hash)[::11][:60]
    for s in sample:
        assert_matches_oracle(expand, s)


# ---------------------------------------------------------------------------
# end-to-end: engine vs oracle on the bounded reconfig config

def test_engine_matches_oracle_on_reconfig3():
    import os

    from raft_tla_tpu.engine.bfs import EngineConfig
    from raft_tla_tpu.engine.check import initial_states, make_engine
    from raft_tla_tpu.models.invariants import (Bounds, constraint_py,
                                                type_ok_py)
    from raft_tla_tpu.utils.cfg import load_config

    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    setup = load_config(os.path.join(here, "configs/reconfig3.cfg"))
    assert isinstance(setup.dims, ReconfigDims)
    assert setup.dims.targets == (3, 7)

    bounds = Bounds(max_term=3, max_log_len=2, max_msg_count=1)
    oracle_res = orc.bfs(
        [init_state(setup.dims)], setup.dims,
        invariants={"TypeOK": type_ok_py},
        constraint=constraint_py(bounds),
        max_levels=3)

    eng = make_engine(setup, EngineConfig(
        batch=128, queue_capacity=1 << 14, seen_capacity=1 << 16,
        record_trace=False, max_diameter=3))
    res = eng.run(initial_states(setup))
    assert res.stop_reason == "diameter_budget"
    assert res.violation is None
    assert res.distinct == oracle_res.distinct_states
    assert res.levels[:4] == oracle_res.levels[:4]


def test_mesh_engine_matches_single_on_reconfig3():
    """The joint-consensus variant through the mesh engine (its extra
    kernels flow through the shared chunk body and the owner-routed
    dedup): counts must match the single-chip engine exactly."""
    import os

    from raft_tla_tpu.engine.bfs import EngineConfig
    from raft_tla_tpu.engine.check import initial_states, make_engine
    from raft_tla_tpu.parallel.mesh import MeshBFSEngine
    from raft_tla_tpu.utils.cfg import load_config

    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    setup = load_config(os.path.join(here, "configs/reconfig3.cfg"))
    want = make_engine(setup, EngineConfig(
        batch=128, queue_capacity=1 << 14, seen_capacity=1 << 16,
        record_trace=False, max_diameter=3)).run(initial_states(setup))
    got = make_engine(setup, EngineConfig(
        batch=16, queue_capacity=1 << 12, seen_capacity=1 << 15,
        record_trace=False, max_diameter=3),
        engine_cls=MeshBFSEngine).run(initial_states(setup))
    assert got.distinct == want.distinct
    assert got.levels == want.levels
    assert got.generated == want.generated
    assert got.violation is None


def test_engine_matches_oracle_from_leader_roots_deep():
    """Config entries only exist once a leader runs InitiateReconfig, and
    no leader exists within the shallow from-Init diameters the other
    end-to-end tests use — so they never packed a configuration value.
    Seed leader-holding roots and go deep enough that joint entries are
    appended, replicated through AppendEntries messages, and re-expanded
    from packed queue rows: this caught the uint8 value-wrap bug
    (CFG_BASE + (old << 8) + new === new_mask mod 256, silently aliasing
    a joint entry to a client value; fixed by dims.value_bytes == 2
    high-byte planes in the packed row)."""
    import os
    import sys

    from raft_tla_tpu.engine.bfs import BFSEngine, EngineConfig
    from raft_tla_tpu.models.invariants import (build_constraint,
                                                constraint_py)
    from raft_tla_tpu.utils.cfg import load_config
    sys.path.insert(0, os.path.join(os.path.dirname(__file__),
                                    os.pardir, "scripts"))
    from leader_bench import leader_states

    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    setup = load_config(os.path.join(here, "configs/reconfig3.cfg"))
    dims, bounds = setup.dims, setup.bounds
    seeds = leader_states(dims, bounds, 0)
    assert seeds, "leader seeding failed"
    # Depth 4 from a fresh leader covers: InitiateReconfig (level 1),
    # AppendEntries carrying the joint entry (level 2), the follower
    # appending it (level 3), and expansions of all of those (level 4).
    ores = orc.bfs(seeds, dims, constraint=constraint_py(bounds),
                   check_deadlock=False, max_levels=4)
    eng = BFSEngine(dims, constraint=build_constraint(dims, bounds),
                    config=EngineConfig(batch=128, queue_capacity=1 << 14,
                                        seen_capacity=1 << 17,
                                        record_trace=False,
                                        check_deadlock=False,
                                        max_diameter=4))
    res = eng.run(seeds)
    assert res.distinct == ores.distinct_states == 3733
    assert res.levels[:5] == ores.levels[:5]
