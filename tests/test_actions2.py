"""v2 (delta) pipeline vs v1 expand: bit-identical contract.

The v2 pipeline (models/actions2.py) must match v1 (models/actions.py +
ops/fingerprint.py + the chunk-level pack guard) EXACTLY — enabled and
overflow masks over the whole action grid, fingerprints, and every field
of every enabled successor — because the engines treat the two paths as
interchangeable (shared checkpoints, shared differential baselines).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raft_tla_tpu.models import oracle as orc
from raft_tla_tpu.models.actions import build_expand
from raft_tla_tpu.models.actions2 import build_v2
from raft_tla_tpu.models.invariants import constraint_py
from raft_tla_tpu.models.pystate import init_state
from raft_tla_tpu.models.schema import build_pack_guard, encode_state
from raft_tla_tpu.ops.fingerprint import build_fingerprint
from raft_tla_tpu.utils.cfg import load_config


@pytest.fixture(scope="module")
def rig():
    setup = load_config("configs/MCraft_bounded.cfg")
    dims = setup.dims
    expand = build_expand(dims)
    fp = build_fingerprint(dims)
    pack_ok = build_pack_guard(dims)
    v2 = build_v2(dims)
    G = dims.n_instances

    @jax.jit
    def v1_all(st):
        cands, en, ovf = expand(st)
        pk = jax.vmap(pack_ok)(cands)
        h, l = jax.vmap(fp)(cands)
        return cands, en, ovf | (en & ~pk), h, l

    @jax.jit
    def v2_all(st):
        en, ovf = v2.masks(st)
        ph = v2.parent_hash(st)
        h, l, succ = jax.vmap(v2.lane_out, (None, None, 0))(
            st, ph, jnp.arange(G, dtype=jnp.int32))
        phi, plo = v2.parent_fp(ph)
        return succ, en, ovf, h, l, phi, plo

    return setup, dims, jax.jit(fp), v1_all, v2_all


def _assert_state_matches(rig_, s, ctx=""):
    setup, dims, fp1, v1_all, v2_all = rig_
    st = jax.tree.map(jnp.asarray, encode_state(s, dims))
    c1, en1, ovf1, h1, l1 = v1_all(st)
    c2, en2, ovf2, h2, l2, phi, plo = v2_all(st)
    rh, rl = fp1(st)
    assert (int(phi), int(plo)) == (int(rh), int(rl)), f"parent fp {ctx}"
    en1, en2, ovf1, ovf2 = map(np.asarray, (en1, en2, ovf1, ovf2))
    bad_en = np.nonzero(en1 != en2)[0]
    assert bad_en.size == 0, \
        f"enabled mismatch {ctx} at " \
        f"{[dims.describe_instance(int(g)) for g in bad_en[:4]]}"
    bad_ovf = np.nonzero(ovf1 != ovf2)[0]
    assert bad_ovf.size == 0, \
        f"overflow mismatch {ctx} at " \
        f"{[dims.describe_instance(int(g)) for g in bad_ovf[:4]]}"
    h1, l1, h2, l2 = map(np.asarray, (h1, l1, h2, l2))
    for g in np.nonzero(en1)[0]:
        gi = int(g)
        assert h1[g] == h2[g] and l1[g] == l2[g], \
            f"fp mismatch {ctx} {dims.describe_instance(gi)}"
        for name, a, b in zip(
                c1._fields,
                jax.tree.map(lambda a: np.asarray(a)[g], c1),
                jax.tree.map(lambda a: np.asarray(a)[g], c2)):
            assert (a == b).all(), \
                f"succ field {name} {ctx} {dims.describe_instance(gi)}"


def test_v2_matches_v1_on_reachable_states(rig):
    setup, dims = rig[0], rig[1]
    res = orc.bfs([init_state(dims)], dims,
                  constraint=constraint_py(setup.bounds),
                  check_deadlock=False, max_levels=5)
    states = list(res.parent)[:120]
    assert len(states) >= 100
    for i, s in enumerate(states):
        _assert_state_matches(rig, s, ctx=f"reachable[{i}]")


def test_v2_matches_v1_on_leader_and_pack_edge_states(rig):
    setup, dims = rig[0], rig[1]
    import sys
    sys.path.insert(0, "scripts")
    from leader_bench import leader_states
    extra = leader_states(dims, setup.bounds, 1)[:40]
    assert extra, "leader seeding failed"
    base = extra[0]
    s_cnt = orc.timeout(init_state(dims), dims, 0)
    mm = sorted(s_cnt.replace(messages=s_cnt.messages).messages)[0][0] \
        if s_cnt.messages else None
    crafted = [
        # term at the uint8 edge: Timeout must overflow-flag, not wrap.
        base.replace(current_term=tuple(255 for _ in base.current_term)),
        base.replace(current_term=(254, 255, 255)),
        # lastLogTerm > 127 breaks the signed msg column 4: RequestVote
        # sends must overflow-flag (schema.build_pack_guard).
        base.replace(current_term=(200, 200, 200),
                     log=(((200, 1),), ((200, 2),), ())),
    ]
    if mm is not None:
        crafted.append(s_cnt.replace(messages=frozenset({(mm, 255)})))
        crafted.append(s_cnt.replace(messages=frozenset({(mm, 254)})))
    # Bag at slot capacity: every send must take the overflow path
    # (enabled=False, overflow=True), and receives must still work.
    full_bag = frozenset(
        ((0, src, dst, t, 1, 0), 1)
        for src in range(dims.n_servers) for dst in range(dims.n_servers)
        for t in range(1, 1 + dims.n_msg_slots
                       // (dims.n_servers * dims.n_servers) + 1)
    )
    full_bag = frozenset(list(full_bag)[:dims.n_msg_slots])
    crafted.append(s_cnt.replace(messages=full_bag))
    for i, s in enumerate(extra + crafted):
        _assert_state_matches(rig, s, ctx=f"corner[{i}]")


def test_v2_rejects_unsupported_variant_dims():
    """A variant that declares extra families without v2 kernels must be
    rejected loudly (engines then fall back to v1 under 'auto')."""
    from raft_tla_tpu.models.dims import RaftDims

    class NoV2Dims(RaftDims):
        @property
        def extra_families(self):
            return (("Mystery", 2),)

    with pytest.raises(NotImplementedError):
        build_v2(NoV2Dims(n_servers=2, n_values=1, max_log=2,
                          n_msg_slots=8))


def test_v2_matches_v1_on_reconfig_variant():
    """The joint-consensus variant through the delta pipeline: bit-equal
    enabled/overflow/fingerprints/successors on leader states carrying
    real configuration entries (InitiateReconfig/FinalizeReconfig lanes
    included)."""
    import os
    import sys

    from raft_tla_tpu.models.invariants import constraint_py
    sys.path.insert(0, os.path.join(os.path.dirname(__file__),
                                    os.pardir, "scripts"))
    from leader_bench import leader_states

    setup = load_config("configs/reconfig3.cfg")
    dims, bounds = setup.dims, setup.bounds
    expand = build_expand(dims)
    fp = build_fingerprint(dims)
    pack_ok = build_pack_guard(dims)
    v2 = build_v2(dims)
    G = dims.n_instances

    @jax.jit
    def v1_all(st):
        cands, en, ovf = expand(st)
        pk = jax.vmap(pack_ok)(cands)
        h, l = jax.vmap(fp)(cands)
        return cands, en, ovf | (en & ~pk), h, l

    @jax.jit
    def v2_all(st):
        en, ovf = v2.masks(st)
        ph = v2.parent_hash(st)
        h, l, succ = jax.vmap(v2.lane_out, (None, None, 0))(
            st, ph, jnp.arange(G, dtype=jnp.int32))
        phi, plo = v2.parent_fp(ph)
        return succ, en, ovf, h, l, phi, plo

    rig_ = (setup, dims, jax.jit(fp), v1_all, v2_all)
    seeds = leader_states(dims, bounds, 0)
    assert seeds
    # grow a few levels so InitiateReconfig fires and its config entries
    # replicate; states WITH config entries must be among the parents
    res = orc.bfs(seeds, dims, constraint=constraint_py(bounds),
                  check_deadlock=False, max_levels=3)
    from raft_tla_tpu.models.reconfig import CFG_BASE
    states = list(res.parent)
    with_cfg = [s for s in states
                if any(e[1] >= CFG_BASE for lg in s.log for e in lg)]
    assert len(with_cfg) >= 10, "no config-entry states generated"
    for i, s in enumerate(with_cfg[:40] + states[:60]):
        _assert_state_matches(rig_, s, ctx=f"reconfig[{i}]")

    # Pack-edge parents: the guards-only extra masks reuse
    # pack_ok(parent) (reconfig.build_extra_masks_v2), so the ~pack_ok
    # branch of the EXTRA lanes' overflow must match the v1 evaluation
    # (en & ~pack_ok(successor)) even on unpackable parents.  Engine
    # parents are always packable (they come from uint8 rows) and the
    # core v2 masks rely on that, so only the extra lanes are compared
    # here; force the edge by pushing a term past the uint8 bound.
    n_extra = sum(size for _name, size in dims.extra_families)
    lo = dims.n_instances - n_extra
    for i, s in enumerate(with_cfg[:6]):
        edge = s.replace(current_term=(256,) + s.current_term[1:])
        st = jax.tree.map(jnp.asarray, encode_state(edge, dims))
        _c1, en1, ovf1, _h1, _l1 = v1_all(st)
        _c2, en2, ovf2, _h2, _l2, _p, _q = v2_all(st)
        assert (np.asarray(en1)[lo:] == np.asarray(en2)[lo:]).all(), \
            f"pack-edge[{i}] extra enabled"
        assert (np.asarray(ovf1)[lo:] == np.asarray(ovf2)[lo:]).all(), \
            f"pack-edge[{i}] extra overflow"


def test_extra_masks_v2_shape_mismatch_rejected():
    """A variant whose build_extra_masks_v2 disagrees with its family
    count must fail at build time, not silently mis-zip kernels."""
    from raft_tla_tpu.models.reconfig import ReconfigDims

    class BadMasks(ReconfigDims):
        def build_extra_masks_v2(self):
            return super().build_extra_masks_v2()[:1]

    setup = load_config("configs/reconfig3.cfg")
    d = setup.dims
    with pytest.raises(ValueError, match="build_extra_masks_v2"):
        build_v2(BadMasks(n_servers=d.n_servers, n_values=d.n_values,
                          max_log=d.max_log, n_msg_slots=d.n_msg_slots,
                          targets=d.targets))


def test_auto_pipeline_propagates_accidental_errors():
    """pipeline='auto' falls back to v1 ONLY on V2Unavailable (the
    dedicated no-v2-kernels signal); an accidental NotImplementedError
    deep inside a variant's build_extra_v2 must propagate, not silently
    select the slow path (advisor r4).  The resolved pipeline is
    recorded on EngineResult so fallbacks are observable."""
    from raft_tla_tpu.engine.bfs import _resolve_pipeline
    from raft_tla_tpu.models.actions2 import V2Unavailable
    from raft_tla_tpu.models.dims import RaftDims

    base = RaftDims(n_servers=2, n_values=1, max_log=2, n_msg_slots=8)
    assert _resolve_pipeline("auto", base) is not None   # base dims -> v2

    class NoV2(RaftDims):
        @property
        def extra_families(self):
            return (("Mystery", 2),)

    nov2 = NoV2(n_servers=2, n_values=1, max_log=2, n_msg_slots=8)
    with pytest.raises(V2Unavailable):
        build_v2(nov2)
    assert _resolve_pipeline("auto", nov2) is None       # clean fallback

    class Buggy(RaftDims):
        def build_extra_v2(self, fp_helpers):
            raise NotImplementedError("accidental: unfinished kernel")

    with pytest.raises(NotImplementedError, match="accidental"):
        _resolve_pipeline("auto",
                          Buggy(n_servers=2, n_values=1, max_log=2,
                                n_msg_slots=8))


def test_compactor_methods_identical():
    """ops/compact.py: the searchsorted lowering must produce the exact
    (P, total, lane_id, kvalid) of the scatter lowering — including the
    spread addresses in dead slots."""
    from raft_tla_tpu.ops.compact import build_compactor
    rng = np.random.RandomState(7)
    for B, G, K, p in ((8, 12, 16, 0.1), (16, 33, 64, 0.5),
                       (4, 5, 8, 0.0), (8, 7, 8, 1.0)):
        c1 = build_compactor(B, G, K, method="scatter")
        c2 = build_compactor(B, G, K, method="searchsorted")
        for _ in range(5):
            en = jnp.asarray(rng.rand(B, G) < p)
            r1 = c1(en)
            r2 = c2(en)
            for a, b, nm in zip(r1, r2, ("P", "total", "lane_id",
                                         "kvalid")):
                assert (np.asarray(a) == np.asarray(b)).all(), \
                    f"{nm} differs at B={B} G={G} K={K} p={p}"


def test_simulator_pipelines_agree_seeded():
    """engine/simulate.py: v1 and v2 walker fleets draw identical actions
    (masks are bit-identical), so a seeded run's step/trace/violation
    accounting must agree exactly across pipelines."""
    from raft_tla_tpu.engine.simulate import Simulator
    from raft_tla_tpu.models.invariants import (build_constraint,
                                                build_type_ok)
    setup = load_config("configs/MCraft_bounded.cfg")
    dims = setup.dims
    roots = [init_state(dims)]
    kw = dict(invariants={"TypeOK": build_type_ok(dims)},
              constraint=build_constraint(dims, setup.bounds),
              batch=32, depth=16, chunk=8)
    r1 = Simulator(dims, pipeline="v1", **kw).run(roots, 512, seed=11)
    r2 = Simulator(dims, pipeline="v2", **kw).run(roots, 512, seed=11)
    assert (r1.steps, r1.traces, r1.violation_invariant) \
        == (r2.steps, r2.traces, r2.violation_invariant)


def test_enqueue_methods_identical_results():
    """engine/chunk.py 'window' enqueue vs 'scatter': identical distinct
    counts and level profile, AND identical replayed counterexample
    paths — the windowed trace buffer must record the same (parent,
    action) links, not just the same counts."""
    from raft_tla_tpu.engine.bfs import BFSEngine, EngineConfig
    from raft_tla_tpu.models.invariants import build_constraint
    setup = load_config("configs/MCraft_bounded.cfg")
    dims = setup.dims
    # Fingerprint of a concrete depth-5 reachable state to replay in both
    # engines: the recorded trace content, not only counts, must agree.
    res5 = orc.bfs([init_state(dims)], dims,
                   constraint=constraint_py(setup.bounds),
                   check_deadlock=False, max_levels=5)
    target = sorted(res5.parent, key=lambda s: (len(s.messages),
                                                s.current_term))[-1]
    fp1 = build_fingerprint(dims)
    h, l = jax.jit(fp1)(jax.tree.map(jnp.asarray,
                                     encode_state(target, dims)))
    target_fp = (int(h) << 32) | int(l)
    results, paths = {}, {}
    for meth in ("scatter", "window", "pallas"):
        eng = BFSEngine(
            dims, constraint=build_constraint(dims, setup.bounds),
            config=EngineConfig(batch=128, queue_capacity=1 << 14,
                                seen_capacity=1 << 16, record_trace=True,
                                check_deadlock=False, max_diameter=6,
                                enqueue_method=meth,
                                compact_method="searchsorted"))
        res = eng.run([init_state(dims)])
        results[meth] = (res.distinct, res.generated, res.levels,
                         res.diameter)
        assert res.distinct == 9457    # pinned oracle L6 cumulative
        trace = eng.replay(target_fp)
        assert trace and trace[-1][1] == target
        paths[meth] = [g for g, _s in trace]
    assert results["scatter"] == results["window"] == results["pallas"]
    assert paths["scatter"] == paths["window"] == paths["pallas"]
    assert len(paths["scatter"]) >= 5


def test_insert_methods_identical_results():
    """engine/bfs.py insert_method='pallas' (ops/fpset_pallas.py,
    interpret mode on CPU) vs 'xla': identical distinct/generated/level
    profile and identical replayed counterexample path — the whole
    engine is bit-identical because the insert contract (is_new flags)
    is."""
    from raft_tla_tpu.engine.bfs import BFSEngine, EngineConfig
    from raft_tla_tpu.models.invariants import build_constraint
    setup = load_config("configs/MCraft_bounded.cfg")
    dims = setup.dims
    res4 = orc.bfs([init_state(dims)], dims,
                   constraint=constraint_py(setup.bounds),
                   check_deadlock=False, max_levels=4)
    target = sorted(res4.parent, key=lambda s: (len(s.messages),
                                                s.current_term))[-1]
    fp1 = build_fingerprint(dims)
    h, l = jax.jit(fp1)(jax.tree.map(jnp.asarray,
                                     encode_state(target, dims)))
    target_fp = (int(h) << 32) | int(l)
    results, paths = {}, {}
    for meth in ("xla", "pallas"):
        eng = BFSEngine(
            dims, constraint=build_constraint(dims, setup.bounds),
            config=EngineConfig(batch=64, queue_capacity=1 << 13,
                                seen_capacity=1 << 14, record_trace=True,
                                check_deadlock=False, max_diameter=5,
                                insert_method=meth))
        res = eng.run([init_state(dims)])
        results[meth] = (res.distinct, res.generated, res.levels,
                         res.diameter)
        assert res.distinct == 2300    # pinned oracle L5 cumulative
        trace = eng.replay(target_fp)
        assert trace and trace[-1][1] == target
        paths[meth] = [g for g, _s in trace]
    assert results["xla"] == results["pallas"]
    assert paths["xla"] == paths["pallas"] and len(paths["xla"]) >= 4
