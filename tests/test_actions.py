"""Differential tests: JAX action kernels vs the pure-Python oracle.

The kernels (models/actions.py) and the oracle (models/oracle.py) are two
independent transcriptions of /root/reference/raft.tla; for any state their
successor multisets must agree exactly.  Coverage comes from three sources:
the unique Init state, every state reachable within two BFS levels, and
unstructured random states over the smoke domains (which exercise negative
mprevLogIndex, src=dst messages, term-0 messages, arbitrary role mixes —
the corners the reachable space hits only rarely).
"""

import jax
import pytest

from raft_tla_tpu.models import oracle as orc
from raft_tla_tpu.models import smoke
from raft_tla_tpu.models.actions import build_expand
from raft_tla_tpu.models.dims import RaftDims
from raft_tla_tpu.models.pystate import init_state
from raft_tla_tpu.models.schema import decode_state, encode_state, StateBatch

DIMS = RaftDims(n_servers=3, n_values=2, max_log=6, n_msg_slots=24)


@pytest.fixture(scope="module")
def expand():
    return jax.jit(build_expand(DIMS))


def kernel_successors(expand, s):
    """Run the expand kernel on one PyState; decode enabled candidates."""
    st = encode_state(s, DIMS)
    cands, enabled, overflow = jax.device_get(expand(st))
    assert not overflow.any(), "fixed-width overflow on test state"
    out = []
    for g in range(DIMS.n_instances):
        if enabled[g]:
            row = jax.tree.map(lambda a: a[g], cands)
            out.append(decode_state(StateBatch(*row), DIMS))
    return out


def assert_matches_oracle(expand, s):
    got = kernel_successors(expand, s)
    want = orc.successors(s, DIMS)
    assert len(got) == len(want), (
        f"enabled-instance count {len(got)} != oracle {len(want)}\n{s}")
    assert set(got) == {t for _a, t in want}, f"successor sets differ for\n{s}"


def test_init_successors(expand):
    assert_matches_oracle(expand, init_state(DIMS))


def test_two_bfs_levels(expand):
    """Every state reachable from Init within 2 levels matches the oracle."""
    res = orc.bfs([init_state(DIMS)], DIMS, max_levels=2)
    for s in res.parent:
        assert_matches_oracle(expand, s)


def test_random_smoke_states(expand):
    for s in smoke.random_states(DIMS, count=60, seed=7):
        assert_matches_oracle(expand, s)


def test_deeper_reachable_sample(expand):
    """A deeper slice: expand a sample of level-4 states (logs, messages and
    elections now in play) and compare."""
    def constraint(t, d):
        return (max(t.current_term) <= 3
                and max(len(l) for l in t.log) <= 2
                and all(c <= 2 for _m, c in t.messages))
    res = orc.bfs([init_state(DIMS)], DIMS, constraint=constraint,
                  max_levels=4)
    sample = sorted(res.parent, key=hash)[::7][:80]
    for s in sample:
        assert_matches_oracle(expand, s)


def test_smoke_init_product_structure():
    states = smoke.smoke_init_states(DIMS, k=2, seed=3)
    assert len(states) == 2 ** 9        # Smokeraft.tla:17-19
    assert len(set(states)) == 2 ** 9
    bags = {s.messages for s in states}
    assert len(bags) == 1               # one shared bag, multiplicity 1
    assert all(c == 1 for _m, c in next(iter(bags)))


# ---------------------------------------------------------------------------
# Golden successor vectors — hand-derived from the raft.tla TEXT, not from
# either implementation.  The differential tests above compare two
# transcriptions by the same author; a shared misreading would pass them all.
# These vectors pin the nastiest branch semantics directly: each constructs a
# state + one in-flight message, writes down the exact successor(s) the cited
# spec lines require, and asserts BOTH the kernel and the oracle produce
# exactly that (for the Receive family, at most one successor per message —
# the disjuncts are pairwise mutually exclusive, SURVEY §3.3).

from raft_tla_tpu.models.dims import (A_RECEIVE, AEQ, AER, RVQ, RVR,
                                      CANDIDATE, FOLLOWER, LEADER, NIL)


def receive_successors_both(expand, s):
    """(kernel, oracle) successor lists restricted to the Receive family."""
    return family_successors_both(expand, s, A_RECEIVE)


def assert_golden(expand, s, expected):
    """Both implementations must yield exactly ``expected`` (a list of
    PyStates) for Receive over s's single in-flight message."""
    kout, oout = receive_successors_both(expand, s)
    assert oout == list(expected), f"oracle disagrees with spec text\n{s}"
    assert kout == list(expected), f"kernel disagrees with spec text\n{s}"


def bag(*msgs):
    return frozenset((m, 1) for m in msgs)


def test_golden_alreadydone_hidden_guard_blocks(expand):
    """raft.tla:301-317: AppendEntriesAlreadyDone sets commitIndex' to
    m.mcommitIndex (:309) AND asserts UNCHANGED logVars (:317), and logVars
    includes commitIndex (:51) — so with mcommitIndex /= commitIndex[i] the
    conjunction is unsatisfiable and Receive(m) has NO successor: every
    sibling branch is also disabled (Reject needs stale term or ~logOk :282-285,
    ReturnToFollower needs Candidate :297, Conflict/NoConflict need nonempty
    entries :320/:328, UpdateTerm needs mterm > currentTerm :374)."""
    aeq = (AEQ, 1, 0, 2, 1, 2, (), 1)   # mprev=1, mprevterm=2, entries=(), mcommit=1
    s = init_state(DIMS).replace(
        current_term=(2, 2, 2), log=(((2, 1),), (), ()),
        messages=bag(aeq))
    assert_golden(expand, s, [])


def test_golden_alreadydone_fires_on_equal_commit(expand):
    """Same state but mcommitIndex = commitIndex[i] = 0: the :309/:317
    contradiction vanishes and AlreadyDone replies success with
    mmatchIndex = mprevLogIndex + Len(mentries) = 1 + 0 (:313); Reply
    consumes the request and adds the response atomically (:102-103);
    serverVars and logVars unchanged (:317)."""
    aeq = (AEQ, 1, 0, 2, 1, 2, (), 0)
    s = init_state(DIMS).replace(
        current_term=(2, 2, 2), log=(((2, 1),), (), ()),
        messages=bag(aeq))
    aer = (AER, 0, 1, 2, 1, 1)          # success=TRUE, mmatchIndex=1
    assert_golden(expand, s, [s.replace(messages=bag(aer))])


def test_golden_alreadydone_entry_already_present(expand):
    """raft.tla:302-305: nonempty entries with Len(log[i]) >= index and
    log[i][index].term = m.mentries[1].term is the 'already done' case —
    the entry is NOT appended again; reply mmatchIndex = 0 + 1 (:313)."""
    aeq = (AEQ, 1, 0, 2, 0, 0, ((2, 1),), 0)   # mprev=0, entries=<<[term 2]>>
    s = init_state(DIMS).replace(
        current_term=(2, 2, 2), log=(((2, 1),), (), ()),
        messages=bag(aeq))
    aer = (AER, 0, 1, 2, 1, 1)
    assert_golden(expand, s, [s.replace(messages=bag(aer))])


def test_golden_conflict_truncates_exactly_one_entry(expand):
    """raft.tla:319-325: on a term conflict at index, the new log is
    [index2 \\in 1..(Len(log[i]) - 1) |-> log[i][index2]] (:323-324) —
    exactly ONE trailing entry is removed, regardless of where the conflict
    index sits, and the message is NOT consumed (messages unchanged :325),
    so the same request re-fires against the shorter log."""
    aeq = (AEQ, 1, 0, 2, 1, 1, ((2, 1),), 0)   # conflict at index 2
    s = init_state(DIMS).replace(
        current_term=(2, 2, 2), log=(((1, 1), (1, 2)), (), ()),
        messages=bag(aeq))
    assert_golden(expand, s, [s.replace(log=(((1, 1),), (), ()))])


def test_golden_noconflict_appends_without_consuming(expand):
    """raft.tla:327-331: Len(log[i]) = mprevLogIndex appends mentries[1];
    messages UNCHANGED (:331) — the accept branches reply only via
    AlreadyDone, so the request stays in flight after the append."""
    aeq = (AEQ, 1, 0, 2, 0, 0, ((2, 1),), 0)
    s = init_state(DIMS).replace(
        current_term=(2, 2, 2), messages=bag(aeq))
    assert_golden(expand, s, [s.replace(log=(((2, 1),), (), ()))])


def test_golden_updateterm_is_exclusive_and_keeps_message(expand):
    """raft.tla:373-379 + :393: for a REQUEST with mterm > currentTerm[i],
    only UpdateTerm is enabled (HandleAppendEntriesRequest requires
    mterm <= currentTerm :352): adopt the term, become Follower, reset
    votedFor (:375-377), and leave the message in flight (:378) to be
    re-processed in a later state."""
    aeq = (AEQ, 1, 0, 3, 0, 0, (), 0)
    s = init_state(DIMS).replace(
        role=(CANDIDATE, FOLLOWER, FOLLOWER), voted_for=(1, 0, 0),
        messages=bag(aeq))
    want = s.replace(current_term=(3, 1, 1),
                     role=(FOLLOWER, FOLLOWER, FOLLOWER),
                     voted_for=(NIL, 0, 0))
    assert_golden(expand, s, [want])


def test_golden_updateterm_on_response(expand):
    """Responses with mterm > currentTerm[i] also take only UpdateTerm
    (:393; HandleAppendEntriesResponse requires = :361, DropStaleResponse
    requires < :383) — the response survives the term adoption."""
    aer = (AER, 1, 0, 3, 1, 1)
    s = init_state(DIMS).replace(messages=bag(aer))
    assert_golden(expand, s, [s.replace(current_term=(3, 1, 1),
                                        voted_for=(NIL, 0, 0))])


def test_golden_stale_request_still_answered(expand):
    """Guard asymmetry, request side (raft.tla:251): HandleRequestVoteRequest
    accepts mterm <= currentTerm[i], so a STALE request is processed — the
    grant conjunct requires equal terms (:248) so it is refused, and the
    reply carries the receiver's own currentTerm (:255) and full log as
    mlog (:259)."""
    rvq = (RVQ, 1, 0, 2, 0, 0)          # mlastLogTerm=0, mlastLogIndex=0
    s = init_state(DIMS).replace(current_term=(3, 3, 3), messages=bag(rvq))
    rvr = (RVR, 0, 1, 3, 0, ())         # granted=FALSE, mlog=<<>>
    assert_golden(expand, s, [s.replace(messages=bag(rvr))])


def test_golden_stale_response_dropped_silently(expand):
    """Guard asymmetry, response side (raft.tla:382-385 vs :361): a response
    with mterm < currentTerm[i] matches only DropStaleResponse — discarded
    with every other variable unchanged (no reply, no cursor update)."""
    aer = (AER, 1, 0, 2, 1, 1)
    s = init_state(DIMS).replace(
        current_term=(3, 3, 3), role=(LEADER, FOLLOWER, FOLLOWER),
        messages=bag(aer))
    assert_golden(expand, s, [s.replace(messages=frozenset())])


def test_golden_vote_granted_sets_votedfor(expand):
    """raft.tla:244-262: equal term + logOk + votedFor in {Nil, j} grants:
    votedFor' = j (:252) and the reply carries mvoteGranted = TRUE and
    mlog = log[i] (:256-259); Reply consumes the request (:102-103)."""
    rvq = (RVQ, 1, 0, 2, 0, 0)
    s = init_state(DIMS).replace(current_term=(2, 2, 2), messages=bag(rvq))
    rvr = (RVR, 0, 1, 2, 1, ())
    assert_golden(expand, s,
                  [s.replace(voted_for=(2, 0, 0), messages=bag(rvr))])


def test_golden_vote_refused_when_already_voted(expand):
    """raft.tla:250: votedFor[i] already names another server -> grant is
    FALSE; votedFor is UNCHANGED (:253) and the refusal is still sent."""
    rvq = (RVQ, 1, 0, 2, 0, 0)
    s = init_state(DIMS).replace(current_term=(2, 2, 2),
                                 voted_for=(1, 0, 0),   # voted for r1 (self)
                                 messages=bag(rvq))
    rvr = (RVR, 0, 1, 2, 0, ())
    assert_golden(expand, s, [s.replace(messages=bag(rvr))])


def test_golden_candidate_returns_to_follower_keeping_message(expand):
    """raft.tla:295-299: an AE request at the candidate's own term -> step
    down to Follower with messages UNCHANGED (:299); Reject is disabled
    (needs stale term or Follower+~logOk :282-285) and Accept is disabled
    (needs Follower :336), so stepping down is the only successor."""
    aeq = (AEQ, 1, 0, 2, 0, 0, (), 0)
    s = init_state(DIMS).replace(
        current_term=(2, 2, 2), role=(CANDIDATE, FOLLOWER, FOLLOWER),
        voted_for=(1, 0, 0), messages=bag(aeq))
    assert_golden(expand, s,
                  [s.replace(role=(FOLLOWER, FOLLOWER, FOLLOWER))])


def test_golden_ae_response_updates_cursors(expand):
    """raft.tla:360-370: success -> nextIndex'[i][j] = mmatchIndex + 1 and
    matchIndex'[i][j] = mmatchIndex (:363-365); failure -> nextIndex
    decrements but never below 1, Max({nextIndex - 1, 1}) (:366-368);
    both Discard the response (:369)."""
    ok = (AER, 1, 0, 2, 1, 2)           # success, mmatchIndex=2
    s = init_state(DIMS).replace(
        current_term=(2, 2, 2), role=(LEADER, FOLLOWER, FOLLOWER),
        log=(((2, 1), (2, 2)), (), ()),
        next_index=((1, 3, 1), (1, 1, 1), (1, 1, 1)),
        messages=bag(ok))
    assert_golden(expand, s, [s.replace(
        next_index=((1, 3, 1), (1, 1, 1), (1, 1, 1)),
        match_index=((0, 2, 0), (0, 0, 0), (0, 0, 0)),
        messages=frozenset())])

    fail = (AER, 1, 0, 2, 0, 0)
    s2 = s.replace(messages=bag(fail))
    assert_golden(expand, s2, [s2.replace(
        next_index=((1, 2, 1), (1, 1, 1), (1, 1, 1)),
        messages=frozenset())])

    # Already at 1: Max({0, 1}) = 1 — the cursor floors, not underflows.
    s3 = s.replace(next_index=((1, 1, 1), (1, 1, 1), (1, 1, 1)),
                   messages=bag(fail))
    assert_golden(expand, s3, [s3.replace(messages=frozenset())])


# --- spontaneous-family golden vectors (same method: derived from the
# spec TEXT, asserted against both implementations) ---------------------

from raft_tla_tpu.models.dims import (A_ADVANCECOMMIT, A_APPENDENTRIES,
                                      A_BECOMELEADER, A_RESTART, A_TIMEOUT)


def family_successors_both(expand, s, fam):
    """(kernel, oracle) successor lists restricted to one action family."""
    st = encode_state(s, DIMS)
    cands, enabled, overflow = jax.device_get(expand(st))
    assert not overflow.any()
    kout = []
    for g in range(DIMS.n_instances):
        if enabled[g] and DIMS.instance_info(g)[0] == fam:
            row = jax.tree.map(lambda a: a[g], cands)
            kout.append(decode_state(StateBatch(*row), DIMS))
    oout = [t for (f, _p), t in orc.successors(s, DIMS) if f == fam]
    return kout, oout


def assert_family_golden(expand, s, fam, expected):
    kout, oout = family_successors_both(expand, s, fam)
    assert sorted(oout, key=hash) == sorted(expected, key=hash), \
        f"oracle disagrees with spec text\n{s}"
    assert sorted(kout, key=hash) == sorted(expected, key=hash), \
        f"kernel disagrees with spec text\n{s}"


def test_golden_advance_commit_current_term(expand):
    """raft.tla:219-236: Agree(index) = {i} + servers with matchIndex >=
    index; commit Max(agreeIndexes) only when THAT entry's term equals
    currentTerm (the Raft §5.4.2 rule, :229-230)."""
    s = init_state(DIMS).replace(
        current_term=(3, 3, 3), role=(LEADER, 0, 0),
        log=(((2, 1), (3, 1)), (), ()),
        match_index=((0, 2, 0), (0, 0, 0), (0, 0, 0)))
    assert_family_golden(expand, s, A_ADVANCECOMMIT,
                         [s.replace(commit_index=(2, 0, 0))])


def test_golden_advance_commit_blocked_by_old_term(expand):
    """raft.tla:228-233: the term check applies to Max(agreeIndexes) ONLY
    — here entry 1 has the current term and quorum agreement, but entry 2
    (the max agreed index) is old-term, so newCommitIndex falls back to
    the UNCHANGED commitIndex and the action self-loops (a generated
    successor equal to the source state)."""
    s = init_state(DIMS).replace(
        current_term=(3, 3, 3), role=(LEADER, 0, 0),
        log=(((3, 1), (2, 2)), (), ()),
        match_index=((0, 2, 0), (0, 0, 0), (0, 0, 0)))
    assert_family_golden(expand, s, A_ADVANCECOMMIT, [s])


def test_golden_append_entries_payload(expand):
    """raft.tla:171-192: prevLogTerm via the guarded lookup (:177-180),
    entries = SubSeq(log, nextIndex, Min({Len, nextIndex})) — at most ONE
    entry (:181-183) — and mcommitIndex = Min({commitIndex, lastEntry})
    (:189), NOT the raw commitIndex."""
    base = init_state(DIMS).replace(
        current_term=(3, 3, 3), role=(LEADER, 0, 0),
        log=(((2, 1), (3, 2)), (), ()), commit_index=(2, 0, 0))

    # nextIndex[0][1]=2: prev=(1, term 2), entries=<<log[2]>>, mcommit=2.
    s = base.replace(next_index=((1, 2, 1), (1, 1, 1), (1, 1, 1)))
    k, o = family_successors_both(expand, s, A_APPENDENTRIES)
    want = s.replace(messages=bag((2, 0, 1, 3, 1, 2, ((3, 2),), 2)))
    assert want in o and want in k

    # nextIndex[0][1]=3 (past the end): empty entries heartbeat with
    # prevLogTerm from the guarded lookup (prev=2 <= Len).
    s2 = base.replace(next_index=((1, 3, 1), (1, 1, 1), (1, 1, 1)))
    k2, o2 = family_successors_both(expand, s2, A_APPENDENTRIES)
    want2 = s2.replace(messages=bag((2, 0, 1, 3, 2, 3, (), 2)))
    assert want2 in o2 and want2 in k2

    # nextIndex[0][1]=1: prevLogIndex=0 -> prevLogTerm=0 (:178-180), and
    # mcommitIndex = Min({2, lastEntry=1}) = 1 — the Min clamp observable.
    s3 = base.replace(next_index=((1, 1, 1), (1, 1, 1), (1, 1, 1)))
    k3, o3 = family_successors_both(expand, s3, A_APPENDENTRIES)
    want3 = s3.replace(messages=bag((2, 0, 1, 3, 0, 0, ((2, 1),), 1)))
    assert want3 in o3 and want3 in k3


def test_golden_timeout_does_not_self_vote(expand):
    """raft.tla:146-154: ->Candidate, term+1, votedFor -> Nil (the spec
    deliberately does NOT self-vote, comment :149-150), vote sets
    cleared; logVars and leaderVars untouched."""
    s = init_state(DIMS).replace(voted_for=(3, 0, 0),
                                 votes_granted=(0b111, 0, 0),
                                 votes_responded=(0b111, 0, 0))
    want = s.replace(role=(1, 0, 0), current_term=(2, 1, 1),
                     voted_for=(0, 0, 0), votes_granted=(0, 0, 0),
                     votes_responded=(0, 0, 0))
    kout, oout = family_successors_both(expand, s, A_TIMEOUT)
    assert want in kout and want in oout


def test_golden_restart_keeps_stable_storage(expand):
    """raft.tla:136-143: Restart preserves currentTerm/votedFor/log (the
    stable storage) but resets role, vote sets, cursors, AND commitIndex
    to 0 — the volatile state."""
    s = init_state(DIMS).replace(
        current_term=(3, 2, 2), role=(LEADER, 0, 0),
        voted_for=(1, 1, 1), log=(((2, 1), (3, 2)), (), ()),
        commit_index=(2, 0, 0), votes_granted=(0b011, 0, 0),
        votes_responded=(0b111, 0, 0),
        next_index=((3, 3, 3), (1, 1, 1), (1, 1, 1)),
        match_index=((0, 2, 0), (0, 0, 0), (0, 0, 0)))
    want = s.replace(role=(0, 0, 0), votes_granted=(0, 0, 0),
                     votes_responded=(0, 0, 0), commit_index=(0, 0, 0),
                     next_index=((1, 1, 1), (1, 1, 1), (1, 1, 1)),
                     match_index=((0, 0, 0), (0, 0, 0), (0, 0, 0)))
    kout, oout = family_successors_both(expand, s, A_RESTART)
    assert want in kout and want in oout


def test_golden_become_leader_cursor_init(expand):
    """raft.tla:195-203: quorum of granted votes -> Leader with
    nextIndex[j] = Len(log)+1 for EVERY j (self included) and
    matchIndex[j] = 0; term/votedFor/log untouched."""
    s = init_state(DIMS).replace(
        current_term=(2, 2, 2), role=(1, 0, 0), voted_for=(1, 1, 1),
        log=(((2, 1),), (), ()), votes_granted=(0b011, 0, 0),
        votes_responded=(0b011, 0, 0))
    want = s.replace(role=(LEADER, 0, 0),
                     next_index=((2, 2, 2), (1, 1, 1), (1, 1, 1)),
                     match_index=((0, 0, 0), (0, 0, 0), (0, 0, 0)))
    assert_family_golden(expand, s, A_BECOMELEADER, [want])


def test_kernel_rows_fingerprint_canonically(expand):
    """Every candidate row the kernel emits must fingerprint identically
    to the canonical re-encoding of its decoded state — i.e., kernel
    successor rows carry no semantic-field deviation from the canonical
    encoding (slot ORDER may differ; the bag hash is order-invariant).
    A violation here would be an aliasing/cleanliness hole of exactly the
    kind investigated for the L13 48-state deficit (ROUND4_NOTES.md)."""
    import numpy as np
    from raft_tla_tpu.models.schema import encode_state as enc
    from raft_tla_tpu.ops.fingerprint import build_fingerprint
    fingerprint = jax.jit(build_fingerprint(DIMS))

    def check_state(s):
        st = enc(s, DIMS)
        cands, enabled, overflow = jax.device_get(expand(st))
        assert not overflow.any()
        for g in range(DIMS.n_instances):
            if not enabled[g]:
                continue
            row = jax.tree.map(lambda a: a[g], cands)
            batch = StateBatch(*row)
            kh, kl = (int(x) for x in fingerprint(batch))
            canon = enc(decode_state(batch, DIMS), DIMS)
            ch, cl = (int(x) for x in fingerprint(canon))
            assert (kh, kl) == (ch, cl), (
                f"kernel row for instance {DIMS.describe_instance(g)} "
                f"fingerprints differently from its canonical re-encoding"
                f"\nstate: {s}")

    res = orc.bfs([init_state(DIMS)], DIMS, max_levels=3)
    rng = np.random.RandomState(11)
    sample = sorted(res.parent, key=hash)
    for s in (sample[::5][:120]
              + list(smoke.random_states(DIMS, count=40, seed=23))):
        check_state(s)
