"""Differential tests: JAX action kernels vs the pure-Python oracle.

The kernels (models/actions.py) and the oracle (models/oracle.py) are two
independent transcriptions of /root/reference/raft.tla; for any state their
successor multisets must agree exactly.  Coverage comes from three sources:
the unique Init state, every state reachable within two BFS levels, and
unstructured random states over the smoke domains (which exercise negative
mprevLogIndex, src=dst messages, term-0 messages, arbitrary role mixes —
the corners the reachable space hits only rarely).
"""

import jax
import pytest

from raft_tla_tpu.models import oracle as orc
from raft_tla_tpu.models import smoke
from raft_tla_tpu.models.actions import build_expand
from raft_tla_tpu.models.dims import RaftDims
from raft_tla_tpu.models.pystate import init_state
from raft_tla_tpu.models.schema import decode_state, encode_state, StateBatch

DIMS = RaftDims(n_servers=3, n_values=2, max_log=6, n_msg_slots=24)


@pytest.fixture(scope="module")
def expand():
    return jax.jit(build_expand(DIMS))


def kernel_successors(expand, s):
    """Run the expand kernel on one PyState; decode enabled candidates."""
    st = encode_state(s, DIMS)
    cands, enabled, overflow = jax.device_get(expand(st))
    assert not overflow.any(), "fixed-width overflow on test state"
    out = []
    for g in range(DIMS.n_instances):
        if enabled[g]:
            row = jax.tree.map(lambda a: a[g], cands)
            out.append(decode_state(StateBatch(*row), DIMS))
    return out


def assert_matches_oracle(expand, s):
    got = kernel_successors(expand, s)
    want = orc.successors(s, DIMS)
    assert len(got) == len(want), (
        f"enabled-instance count {len(got)} != oracle {len(want)}\n{s}")
    assert set(got) == {t for _a, t in want}, f"successor sets differ for\n{s}"


def test_init_successors(expand):
    assert_matches_oracle(expand, init_state(DIMS))


def test_two_bfs_levels(expand):
    """Every state reachable from Init within 2 levels matches the oracle."""
    res = orc.bfs([init_state(DIMS)], DIMS, max_levels=2)
    for s in res.parent:
        assert_matches_oracle(expand, s)


def test_random_smoke_states(expand):
    for s in smoke.random_states(DIMS, count=60, seed=7):
        assert_matches_oracle(expand, s)


def test_deeper_reachable_sample(expand):
    """A deeper slice: expand a sample of level-4 states (logs, messages and
    elections now in play) and compare."""
    def constraint(t, d):
        return (max(t.current_term) <= 3
                and max(len(l) for l in t.log) <= 2
                and all(c <= 2 for _m, c in t.messages))
    res = orc.bfs([init_state(DIMS)], DIMS, constraint=constraint,
                  max_levels=4)
    sample = sorted(res.parent, key=hash)[::7][:80]
    for s in sample:
        assert_matches_oracle(expand, s)


def test_smoke_init_product_structure():
    states = smoke.smoke_init_states(DIMS, k=2, seed=3)
    assert len(states) == 2 ** 9        # Smokeraft.tla:17-19
    assert len(set(states)) == 2 ** 9
    bags = {s.messages for s in states}
    assert len(bags) == 1               # one shared bag, multiplicity 1
    assert all(c == 1 for _m, c in next(iter(bags)))
