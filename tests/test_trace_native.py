"""Tests for the C++ trace store (native/trace_store.cpp via ctypes) and
its drop-in equivalence with the Python fallback."""

import numpy as np
import pytest

from raft_tla_tpu import native
from raft_tla_tpu.engine.trace import (NativeTraceStore, PyTraceStore,
                                       make_trace_store)


def _fill(store, n=5000, seed=3):
    rng = np.random.default_rng(seed)
    fps = rng.integers(1, 1 << 63, n, dtype=np.uint64)
    parents = rng.integers(1, 1 << 63, n, dtype=np.uint64)
    actions = rng.integers(0, 99, n, dtype=np.int32)
    store.add_batch(fps, parents, actions)
    return fps, parents, actions


def test_native_lib_builds():
    assert native.load() is not None, "g++ build of trace_store.cpp failed"


def test_native_matches_python_store():
    lib = native.load()
    assert lib is not None
    ns, ps = NativeTraceStore(lib, 1024), PyTraceStore()
    fps, parents, actions = _fill(ns)
    _fill(ps)
    # Duplicate batch: first insert must win in both.
    ns.add_batch(fps, parents[::-1].copy(), actions[::-1].copy())
    ps.add_batch(fps, parents[::-1].copy(), actions[::-1].copy())
    assert len(ns) == len(ps)
    rng = np.random.default_rng(9)
    for fp in rng.choice(fps, 200, replace=False):
        assert ns.get(int(fp)) == ps.get(int(fp))
    assert ns.get(12345) is None and ps.get(12345) is None


def test_native_growth_and_export():
    lib = native.load()
    assert lib is not None
    ns = NativeTraceStore(lib, 1024)       # forces several grows
    fps, parents, actions = _fill(ns, n=50000, seed=11)
    uniq = len(np.unique(fps))
    assert len(ns) == uniq
    efps, eparents, eactions = ns.export()
    assert len(efps) == uniq
    # Export round-trips through a fresh store.
    ns2 = NativeTraceStore(lib, 16)
    ns2.add_batch(efps, eparents, eactions)
    for fp in fps[:100]:
        assert ns2.get(int(fp)) == ns.get(int(fp))


def test_chain_walkback():
    store = make_trace_store()
    # Root 100 (action -1), chain 100 -> 200 -> 300.
    store.add_batch(np.array([100, 200, 300], np.uint64),
                    np.array([0, 100, 200], np.uint64),
                    np.array([-1, 5, 7], np.int32))
    assert store.chain(300) == [(100, -1), (200, 5), (300, 7)]
    assert store.chain(100) == [(100, -1)]
    assert store.chain(999) == []
