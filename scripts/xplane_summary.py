#!/usr/bin/env python3
"""Summarize ``jax.profiler`` capture artifacts into the perf JSON
dialect — the XPlane ingestion leg of the performance observatory.

``--xla-profile`` (PR 9; ``tpu_session.sh`` stage 5b) lands device-
profiler artifacts under ``<logdir>/plugins/profile/<run>/``: an XPlane
proto plus a Perfetto/Chrome-trace JSON of the ACTUAL kernels the
hardware ran.  Those artifacts are the launch-count truth the static
model in ``obs/perf.py`` can only bound — but until now they were
profiler screenshots: nothing machine-readable entered the ledger.

This script parses the capture's Chrome-trace JSON (the zero-dep half
of the artifact pair; the ``.xplane.pb`` proto needs the tensorboard
profile plugin and is deliberately not required) and emits ONE JSON
object in the bench/perf dialect:

- kernel events on device tracks, bucketed by the ``chunk`` step
  annotation both engines bracket their dispatches with (obs/profile.py
  XlaProfileCapture — the shared span name is the correlation
  contract), giving **measured** ``launches_per_chunk``;
- total device time + the top kernels by accumulated duration — what
  NORTHSTAR §d's launch-bound-vs-bandwidth-bound question reads.

Because the ``perf`` block shape matches ``bench.py``'s,
``scripts/bench_diff.py`` gates these summaries with ``--launch-drift``
like any bench pair, and ``--history`` appends the summary to the run
ledger (kind ``xplane``) so the first TPU tunnel window lands directly
in the trajectory ``scripts/bench_history.py --perf`` renders.

    python scripts/xplane_summary.py artifacts/xla_profile_v3
    python scripts/xplane_summary.py artifacts/xla_profile_v3 \\
        --out v3.json --history artifacts/history.jsonl --label xplane_v3

Exit codes: 0 ok, 2 unreadable/empty capture (the bench_diff
convention: a tool that cannot read its evidence fails loudly).
"""

import argparse
import bisect
import glob
import gzip
import json
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

#: Track/process names that mark DEVICE timelines in jax profiler
#: traces ("/device:TPU:0 ...", "TPU:0", "GPU:0", "XLA Op" lanes); host
#: python/TSL tracks never match.
DEVICE_RE = re.compile(r"device|tpu|gpu|xla", re.IGNORECASE)

#: Event names that are annotations/steps, not kernels, on any track.
_NOT_KERNEL = re.compile(r"^(chunk|\$|Steps?$|step\b)", re.IGNORECASE)


def find_trace_file(logdir: str):
    """The newest ``*.trace.json(.gz)`` under ``logdir`` (searched
    directly and under the ``plugins/profile/<run>/`` layout
    jax.profiler writes).  None when the capture left no trace JSON."""
    pats = ("*.trace.json.gz", "*.trace.json")
    cands = []
    for pat in pats:
        cands += glob.glob(os.path.join(logdir, pat))
        cands += glob.glob(os.path.join(logdir, "plugins", "profile",
                                        "*", pat))
        cands += glob.glob(os.path.join(logdir, "*", pat))
    if not cands:
        return None
    return max(cands, key=os.path.getmtime)


def load_trace(path: str) -> list:
    op = gzip.open if path.endswith(".gz") else open
    with op(path, "rt", encoding="utf-8") as f:
        doc = json.load(f)
    if isinstance(doc, dict):
        return doc.get("traceEvents") or []
    return doc if isinstance(doc, list) else []


def summarize_events(events: list) -> dict:
    """Chrome-trace events -> the measured launch summary.  Device
    tracks are found via process/thread metadata names; with none
    matching (a host-only CPU capture) EVERY complete event counts,
    with a note — shape over silence."""
    pid_names, tid_names = {}, {}
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "process_name":
            pid_names[e.get("pid")] = (e.get("args") or {}).get("name", "")
        elif e.get("ph") == "M" and e.get("name") == "thread_name":
            tid_names[(e.get("pid"), e.get("tid"))] = \
                (e.get("args") or {}).get("name", "")
    device_pids = {p for p, n in pid_names.items() if DEVICE_RE.search(n)}
    device_tids = {pt for pt, n in tid_names.items()
                   if DEVICE_RE.search(n)}
    notes = []
    if not device_pids and not device_tids:
        notes.append("no device track metadata; counting every "
                     "complete event (host-only capture?)")

    def on_device(e):
        if not device_pids and not device_tids:
            return True
        return (e.get("pid") in device_pids
                or (e.get("pid"), e.get("tid")) in device_tids)

    # Chunk steps counted PER TRACK, then the busiest track taken:
    # captures mirror the StepTraceAnnotation onto both the host thread
    # and a device Steps lane, and counting the union would double the
    # denominator (halving launches_per_chunk — a deflated ledger
    # baseline would then flag the next correct capture as a launch
    # regression).
    chunk_tracks = {}
    for e in events:
        if e.get("ph") != "X":
            continue
        name = e.get("name") or ""
        if name == "chunk" or name.startswith("chunk "):
            key = (e.get("pid"), e.get("tid"))
            chunk_tracks.setdefault(key, []).append(
                (float(e.get("ts") or 0.0), float(e.get("dur") or 0.0)))
    steps = (max(chunk_tracks.values(), key=len) if chunk_tracks
             else [])
    chunks = len(steps)
    if not chunks:
        notes.append("no 'chunk' step annotations found; "
                     "launches_per_chunk unavailable (raw kernel count "
                     "reported)")
    # Kernels are bucketed by midpoint-in-chunk-window, so non-chunk
    # device work the capture window also recorded (per-level ingest,
    # profiler stage re-executions, oracle kernels) cannot inflate
    # launches_per_chunk and flip --launch-drift on interleave alone.
    intervals = []
    for ts, dur in sorted(s for s in steps if s[1] > 0):
        if intervals and ts <= intervals[-1][1]:
            intervals[-1][1] = max(intervals[-1][1], ts + dur)
        else:
            intervals.append([ts, ts + dur])
    if chunks and not intervals:
        notes.append("chunk steps carry no duration; counting every "
                     "device event")
    starts = [iv[0] for iv in intervals]

    def in_chunk_window(ts, dur):
        if not intervals:
            return True        # no usable windows: count everything
        mid = ts + dur / 2.0
        i = bisect.bisect_right(starts, mid) - 1
        return i >= 0 and mid <= intervals[i][1]

    kernels = 0
    outside = 0
    device_us = 0.0
    by_name = {}
    for e in events:
        if e.get("ph") != "X":
            continue
        name = e.get("name") or ""
        if name == "chunk" or name.startswith("chunk "):
            continue
        if not on_device(e) or _NOT_KERNEL.match(name):
            continue
        ts = float(e.get("ts") or 0.0)
        dur = float(e.get("dur") or 0.0)
        if not in_chunk_window(ts, dur):
            outside += 1
            continue
        kernels += 1
        device_us += dur
        agg = by_name.setdefault(name, [0, 0.0])
        agg[0] += 1
        agg[1] += dur
    if outside:
        notes.append(f"{outside} device events outside the chunk step "
                     f"windows excluded")
    top = sorted(((n, c, round(us / 1e3, 3))
                  for n, (c, us) in by_name.items()),
                 key=lambda t: -t[2])[:10]
    lpc = round(kernels / chunks, 1) if chunks else None
    return {
        "chunks": chunks, "kernel_events": kernels,
        "launches_per_chunk": lpc,
        "device_time_ms": round(device_us / 1e3, 3),
        "top_kernels": [{"name": n, "count": c, "total_ms": ms}
                        for n, c, ms in top],
        "notes": notes,
    }


def build_doc(logdir: str, trace_path: str, summary: dict) -> dict:
    """The perf-dialect JSON object: same ``perf.launch`` shape as
    bench.py's block (bench_diff's --launch-drift gate reads it
    identically), with ``model`` marking these as MEASURED launches."""
    try:
        from raft_tla_tpu.obs import host_fingerprint
        fp = host_fingerprint()
    except Exception:
        fp = None
    return {
        "metric": "xplane_summary",
        "source": os.path.relpath(trace_path),
        "logdir": logdir,
        "host_fingerprint": fp,
        "perf": {
            "pipeline": None,
            "launch": {
                "model": "xplane device events (measured)",
                "launches_per_chunk": summary["launches_per_chunk"],
                "chunk_calls": summary["chunks"],
                "kernel_events": summary["kernel_events"],
                "device_time_ms": summary["device_time_ms"],
                "notes": summary["notes"],
            },
            "roofline": {"stages": {}},
            "advisor": {"ranking": [], "top": None,
                        "verdict": "measured capture (no static model)"},
        },
        "top_kernels": summary["top_kernels"],
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="summarize jax.profiler artifacts into perf JSON")
    p.add_argument("logdir", help="--xla-profile directory (or any dir "
                                  "containing *.trace.json[.gz])")
    p.add_argument("--out", default=None, metavar="FILE",
                   help="write the JSON here (default: stdout)")
    p.add_argument("--history", default=None, metavar="LEDGER",
                   help="append a kind='xplane' entry embedding this "
                        "summary to the run-history ledger "
                        "(obs/history.py)")
    p.add_argument("--label", default=None,
                   help="ledger entry label (e.g. xplane_v3)")
    args = p.parse_args(argv)

    trace_path = find_trace_file(args.logdir)
    if trace_path is None:
        print(f"xplane_summary: no *.trace.json[.gz] under "
              f"{args.logdir!r} — did the capture run? (the XPlane "
              f".pb alone is not parseable without the tensorboard "
              f"profile plugin)", file=sys.stderr)
        return 2
    try:
        events = load_trace(trace_path)
    except (OSError, json.JSONDecodeError, EOFError) as e:
        print(f"xplane_summary: cannot parse {trace_path}: {e}",
              file=sys.stderr)
        return 2
    if not events:
        print(f"xplane_summary: {trace_path} holds no trace events",
              file=sys.stderr)
        return 2
    doc = build_doc(args.logdir, trace_path, summarize_events(events))
    blob = json.dumps(doc)
    if args.out:
        with open(args.out, "w", encoding="utf-8") as f:
            f.write(blob + "\n")
        print(f"xplane_summary: {doc['perf']['launch']['kernel_events']}"
              f" kernel events, launches/chunk="
              f"{doc['perf']['launch']['launches_per_chunk']} "
              f"-> {args.out}", file=sys.stderr)
    else:
        print(blob)
    if args.history:
        from raft_tla_tpu.obs import history as history_mod
        history_mod.append_entry(args.history, history_mod.make_entry(
            "xplane", label=args.label,
            host_fingerprint=doc.get("host_fingerprint"),
            verdict="ok", bench=doc))
        print(f"xplane_summary: ledger entry appended to {args.history}",
              file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
