#!/bin/bash
# Round-4 TPU watchdog: probe the tunnel until it answers, then run the
# full measurement session. Every probe is timestamped to the log — if
# the tunnel stays dead all round, the log IS the hardware-evidence
# artifact (VERDICT r3, next-round item 1).
#
# RAFT_SESSION_ALLOW_CPU=1 smoke-tests the whole pipeline without an
# accelerator (the probe and the session both honor it). A failing
# session is retried at most MAX_SESSION_FAILS times — a deterministic
# stage bug must not relaunch the multi-stage session forever.
set -u
cd "$(dirname "$0")/.."
mkdir -p artifacts
LOG=artifacts/tpu_watchdog_r05.log
NS_BUDGET="${1:-900}"
MAX_SESSION_FAILS="${MAX_SESSION_FAILS:-3}"
fails=0
echo "$(date -u +%FT%TZ) watchdog start (pid $$)" >> "$LOG"
probe() {
    [ "${RAFT_SESSION_ALLOW_CPU:-0}" = "1" ] && return 0
    timeout 180 python -c \
        "import jax; assert jax.devices()[0].platform != 'cpu'" 2>/dev/null
}
while true; do
    if probe; then
        echo "$(date -u +%FT%TZ) probe OK - launching tpu_session" >> "$LOG"
        bash scripts/tpu_session.sh "$NS_BUDGET" >> artifacts/tpu_session_r05.out 2>&1
        rc=$?
        echo "$(date -u +%FT%TZ) tpu_session exit rc=$rc" >> "$LOG"
        [ $rc -eq 0 ] && exit 0
        # Count the failure only if the tunnel is still alive (a stage bug,
        # not a mid-session tunnel drop — drops are what we wait out).
        if probe; then
            fails=$((fails + 1))
            if [ $fails -ge "$MAX_SESSION_FAILS" ]; then
                echo "$(date -u +%FT%TZ) giving up: $fails failures with tunnel alive" >> "$LOG"
                exit 1
            fi
        else
            echo "$(date -u +%FT%TZ) session died with tunnel (uncounted)" >> "$LOG"
        fi
        sleep 120
    else
        echo "$(date -u +%FT%TZ) probe FAIL (timeout-or-cpu)" >> "$LOG"
        sleep 180
    fi
done
