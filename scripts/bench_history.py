#!/usr/bin/env python3
"""Render (and seed) the run-history ledger — the bench trajectory tool.

The ledger (obs/history.py; written by ``BENCH_HISTORY`` / ``check
--history`` / the ``HISTORY`` directive) is an append-only JSONL file of
per-run entries: cfg/model/host fingerprints, verdict, counts, headline
rates, pipeline plan, report summary, and (for bench runs) the embedded
bench JSON that lets ``bench_diff.py --history`` auto-resolve baselines.

    python scripts/bench_history.py LEDGER.jsonl
        render the trajectory table: one row per entry with its host
        key, plus explicit HOST-CHANGE / unknown-host flags — the
        BENCH_r05 trap (an absolute rate silently compared across a
        ~4x slower container) rendered impossible to miss.  Swarm-tier
        rows (kind=swarm — ``check --mode swarm`` / BENCH_MODE=swarm)
        render their steps/s headline with a ``steps/s`` dialect flag;
        they carry real host fingerprints, so they never read as host
        anomalies.

    python scripts/bench_history.py LEDGER.jsonl --import-legacy [DIR]
        one-time seeding from the committed BENCH_r01..r05 /
        MULTICHIP_r01..r05 round files (DIR defaults to the repo root)
        so the trajectory is non-empty from day one.  Legacy files
        predate host fingerprints, so every imported entry carries
        host_key null — rendered as ``host?``/not-comparable, which IS
        the honest statement about those numbers.  Idempotent: a label
        already in the ledger is skipped.

Exit codes: 0 ok, 2 unreadable/malformed ledger (the bench_diff
convention — a tool that cannot read its evidence fails loudly).
No jax; runs from a fresh clone.
"""

import argparse
import glob
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from raft_tla_tpu.obs import history as history_mod  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def import_legacy(ledger: str, repo: str) -> int:
    """Seed the ledger from the committed round files; returns the
    number of entries appended."""
    have = set()
    if os.path.exists(ledger):
        have = {e.get("label") for e in history_mod.read_history(ledger)}
    added = 0
    for path in sorted(glob.glob(os.path.join(repo, "BENCH_r*.json"))):
        label = os.path.splitext(os.path.basename(path))[0]
        if label in have:
            continue
        with open(path, encoding="utf-8") as f:
            wrapper = json.load(f)
        parsed = wrapper.get("parsed")
        if parsed:
            entry = history_mod.entry_from_bench(parsed, label=label)
        else:
            # A round whose bench never emitted JSON (BENCH_r01's queue
            # overflow): recorded as a failed run, not silently dropped
            # — the trajectory should show the crash too.
            entry = history_mod.make_entry(
                "bench", label=label,
                verdict=f"no-json (rc {wrapper.get('rc')})")
        history_mod.append_entry(ledger, entry)
        added += 1
    for path in sorted(glob.glob(os.path.join(repo, "MULTICHIP_r*.json"))):
        label = os.path.splitext(os.path.basename(path))[0]
        if label in have:
            continue
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
        verdict = ("ok" if doc.get("ok")
                   else "skipped" if doc.get("skipped")
                   else f"failed (rc {doc.get('rc')})")
        history_mod.append_entry(ledger, history_mod.make_entry(
            "multichip", label=label, verdict=verdict))
        added += 1
    return added


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="render / seed the run-history ledger")
    p.add_argument("ledger", help="JSONL ledger file (obs/history.py)")
    p.add_argument("--import-legacy", nargs="?", const=REPO, default=None,
                   metavar="DIR",
                   help="seed from the committed BENCH_r*/MULTICHIP_r* "
                        "files in DIR (default: repo root) before "
                        "rendering; idempotent by label")
    p.add_argument("--perf", action="store_true",
                   help="add the performance-observatory columns "
                        "(launches/chunk + fusion-advisor pick, from "
                        "each bench entry's embedded perf block) — the "
                        "trajectory view of whether fusion work is "
                        "retiring launches across rounds; entries "
                        "predating the metric render '--'")
    p.add_argument("--hunt", action="store_true",
                   help="add the hunt-observatory columns (coverage "
                        "saturation + novelty rate + time-to-violation "
                        "from each swarm entry's hunt summary, "
                        "obs/hunt.py) — the trajectory view of whether "
                        "successive hunts are saturating sooner or "
                        "latching faster; exhaustive rows render '--'")
    args = p.parse_args(argv)

    if args.import_legacy is not None:
        repo = args.import_legacy
        try:
            added = import_legacy(args.ledger, repo)
        except (OSError, ValueError) as e:
            print(f"bench_history: {e}", file=sys.stderr)
            return 2
        print(f"bench_history: imported {added} legacy entr"
              f"{'y' if added == 1 else 'ies'} from {repo}")

    try:
        entries = history_mod.read_history(args.ledger)
    except (OSError, ValueError) as e:
        print(f"bench_history: {e}", file=sys.stderr)
        return 2
    print(history_mod.render_table(entries, perf=args.perf,
                                   hunt=args.hunt))
    return 0


if __name__ == "__main__":
    sys.exit(main())
