"""Exhaust a bounded model with the pure-Python oracle and pin the count.

The differential contract (SURVEY §4) needs a ground-truth distinct-state
count for the primary bench model that does NOT come from the JAX kernels.
`models.oracle.bfs` keeps a parent pointer per state (for traces), which is
too heavy for a full-space run; this runner strips the walk down to the
counting essentials:

- seen-set entries are 16-byte BLAKE2b digests of a canonical serialization
  (messages sorted — the frozenset's iteration order is not canonical), so
  100M states cost ~6 GB instead of ~100 GB of live tuples;
- per-level counts stream to a JSONL progress file as they complete, so a
  partial run still yields a level-profile prefix to diff the engine
  against.

Collision note: 128-bit digests over <2^30 states give a birthday bound of
~2^-69 — the same "morally exact" regime as TLC's own 64-bit fingerprints
(which it trusts at 10^10 states), with 64 bits more margin.

Usage: python scripts/oracle_exhaust.py [cfg] [out.jsonl]
"""

import json
import os
import pickle
import sys
import time
from hashlib import blake2b

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from raft_tla_tpu.models import oracle as orc
from raft_tla_tpu.models.invariants import constraint_py, type_ok_py
from raft_tla_tpu.models.pystate import init_state
from raft_tla_tpu.utils.cfg import load_config


def _fresh(x):
    """Deep-rebuild nested tuples so no container object occurs twice."""
    return tuple(_fresh(e) for e in x) if isinstance(x, tuple) else x


def canon_digest(s) -> bytes:
    canon = (s.current_term, s.role, s.voted_for, s.log, s.commit_index,
             s.votes_responded, s.votes_granted, s.next_index,
             s.match_index, tuple(sorted(s.messages)))
    # Memoization-free bytes: plain ``pickle.dumps`` emits a 2-byte memo
    # backreference when a container object appears twice (e.g. an RVR
    # response's mlog IS the sender's log tuple on one action path, but
    # an equal copy on another), so byte-equality depended on object
    # IDENTITY, not value — which split 48 spec-identical states at
    # MCraft_bounded level 13 into 96 digests (the infamous "48-state
    # engine deficit" of ROUND4_NOTES: the ENGINE was right, this digest
    # overcounted).  ``_fresh`` rebuilds every container, so nothing is
    # ever memoized (ints/bools are pickled inline, containers are all
    # new objects) and the bytes are a pure function of the VALUE.
    return blake2b(pickle.dumps(_fresh(canon), protocol=5),
                   digest_size=16).digest()


def main():
    cfg_path = sys.argv[1] if len(sys.argv) > 1 else "configs/MCraft_bounded.cfg"
    out_path = sys.argv[2] if len(sys.argv) > 2 else "oracle_exhaust.jsonl"
    max_levels = int(sys.argv[3]) if len(sys.argv) > 3 else None
    setup = load_config(cfg_path)
    dims, bounds = setup.dims, setup.bounds
    constraint = constraint_py(bounds)
    t0 = time.time()

    seen = set()
    distinct = generated = 0
    inv_violation = None
    frontier = []
    for s0 in [init_state(dims)]:
        d = canon_digest(s0)
        seen.add(d)
        distinct += 1
        if not type_ok_py(s0, dims):
            inv_violation = ("TypeOK", s0)
        if constraint(s0, dims):
            frontier.append(s0)

    level = 0
    levels = [len(frontier)]
    out = open(out_path, "w")

    def emit(done=False, reason="running"):
        rec = {"cfg": cfg_path, "level": level, "frontier": levels[-1],
               "distinct": distinct, "generated": generated,
               "wall_s": round(time.time() - t0, 1),
               "violation": inv_violation[0] if inv_violation else None,
               "done": done, "stop_reason": reason}
        out.write(json.dumps(rec) + "\n")
        out.flush()

    emit()
    while frontier and inv_violation is None and (
            max_levels is None or level < max_levels):
        nxt = []
        for s in frontier:
            succ = orc.successors(s, dims)
            generated += len(succ)
            for _act, t in succ:
                d = canon_digest(t)
                if d in seen:
                    continue
                seen.add(d)
                distinct += 1
                if not type_ok_py(t, dims):
                    inv_violation = ("TypeOK", t)
                if constraint(t, dims):
                    nxt.append(t)
        level += 1
        levels.append(len(nxt))
        frontier = nxt
        emit()
    emit(done=True,
         reason="violation" if inv_violation else
         ("level_budget" if frontier else "exhausted"))
    print(json.dumps({"cfg": cfg_path, "distinct": distinct,
                      "generated": generated, "diameter": level,
                      "levels": levels,
                      "violation": inv_violation[0] if inv_violation else None,
                      "wall_s": round(time.time() - t0, 1)}))


if __name__ == "__main__":
    main()
