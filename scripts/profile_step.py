"""Time one real BFS engine step on the ambient platform, separating
device compute from host round-trips — to find where the states/sec go."""

import sys
import time

sys.path.insert(0, ".")

import jax
import jax.numpy as jnp
import numpy as np

from raft_tla_tpu.engine.bfs import EngineConfig
from raft_tla_tpu.engine.check import make_engine
from raft_tla_tpu.models.pystate import init_state
from raft_tla_tpu.models.schema import encode_state, flatten_state
from raft_tla_tpu.utils.cfg import load_config
from raft_tla_tpu.ops import fpset


def main():
    print("platform:", jax.devices()[0].platform)
    setup = load_config("configs/MCraft_bounded.cfg")
    cfg = EngineConfig(batch=2048, queue_capacity=1 << 20,
                       seen_capacity=1 << 23, record_trace=False)
    eng = make_engine(setup, cfg)
    dims = setup.dims
    print("dims:", dims, "G:", dims.n_instances, "SW:", eng._sw)

    row = flatten_state(encode_state(init_state(dims), dims), dims)
    Q = eng._Q
    qcur = jnp.asarray(np.tile(row[None, :], (Q, 1)).astype(np.int32))
    B = cfg.batch

    def fresh():
        return (jnp.zeros((Q, eng._sw), jnp.int32),
                fpset.empty(cfg.seen_capacity))

    # Warm-up/compile.
    qnext, seen = fresh()
    out = eng._step(qcur, jnp.int32(B), jnp.int32(0), qnext, jnp.int32(0),
                    seen)
    jax.block_until_ready(out)

    # Pure device time: run 10 steps, sync once at the end.
    n = 10
    qnext, seen = fresh()
    nc = jnp.int32(0)
    t0 = time.time()
    for _ in range(n):
        out = eng._step(qcur, jnp.int32(B), jnp.int32(0), qnext, nc, seen)
        qnext, nc, seen = out[0], out[1], out[2]
    jax.block_until_ready(out)
    dev_ms = (time.time() - t0) / n * 1e3
    print(f"device-only step                    {dev_ms:9.2f} ms")

    # Step + the host scalar fetches the run loop does.
    qnext, seen = fresh()
    nc = jnp.int32(0)
    t0 = time.time()
    for _ in range(n):
        out = eng._step(qcur, jnp.int32(B), jnp.int32(0), qnext, nc, seen)
        qnext, nc, seen, stats = out[0], out[1], out[2], out[3]
        _ = (int(stats[0]), int(stats[1]), int(stats[2]), bool(stats[3]),
             bool(stats[4]))
        _ = int(seen.size)
        _ = int(nc)
        _ = bool(out[5][0])
    sync_ms = (time.time() - t0) / n * 1e3
    print(f"step + host scalar fetches          {sync_ms:9.2f} ms")

    # One scalar round-trip (tunnel RTT floor).
    x = jnp.int32(7)
    t0 = time.time()
    for _ in range(n):
        _ = int(x + 1)
    print(f"single scalar device->host fetch    "
          f"{(time.time() - t0) / n * 1e3:9.2f} ms")


if __name__ == "__main__":
    main()
