"""Decompose one BFS batch into its device kernels and time each on the
ambient platform (TPU under the driver; CPU anywhere).  This is the
instrument for the round-3 performance work: run it before and after any
engine change and commit the numbers.

The staged decomposition (expand / fingerprint / dedup_insert /
enqueue, fenced between stages) comes from the shared
``obs.profile`` API — the same programs ``--profile-chunks`` samples
inside a live engine run — so this script's numbers and an engine
run's ``chunk_profile`` event are the same instrument.  On top of
that, this script times what the in-engine profiler can't:

  compact[searchsorted]  the alternate compaction lowering
  fpset_pallas.insert    Mosaic sequential-probe insert (TPU only)
  enqueue pallas         run-coalesced DMA append (TPU only)
  CHUNK                  the engine's real fused chunk program
  CHUNK x8               ditto, 8 batches per call (sync_every)
  CHUNK v2 / v2+ss+win   the delta pipeline + full candidate config
  v3 staged + CHUNK v3   the fused Pallas pipeline (ops/pipeline_v3.py):
                         per-stage masks/compact/fingerprint/
                         insert_enqueue timings and the whole v3 chunk —
                         THE measurement row that resolves NORTHSTAR §d's
                         fused-chunk decision at the next tunnel window

Run:  python scripts/profile_step.py [batch]

CAVEAT: under the axon TPU tunnel, repeated same-input timings have shown
1000x session-to-session swings (block_until_ready is not a reliable
barrier there).  Cross-check any surprising number against
scripts/true_bench.py (fori_loop-chained, host-fetch barrier) and against
an end-to-end engine run before acting on it.
"""

import sys
import time

sys.path.insert(0, ".")

from raft_tla_tpu.utils.platform import neutralize_axon_if_cpu_requested

neutralize_axon_if_cpu_requested()   # honor JAX_PLATFORMS=cpu

import jax
import jax.numpy as jnp
import numpy as np

from raft_tla_tpu.engine.bfs import EngineConfig
from raft_tla_tpu.engine.check import initial_states, make_engine
from raft_tla_tpu.models.actions import build_expand
from raft_tla_tpu.models.schema import flatten_state, unflatten_state
from raft_tla_tpu.ops import fpset
from raft_tla_tpu.utils.cfg import load_config


def bench(label, fn, *args, n=10, **kw):
    out = fn(*args, **kw)          # compile
    jax.block_until_ready(out)
    t0 = time.time()
    for _ in range(n):
        out = fn(*args, **kw)
    jax.block_until_ready(out)
    ms = (time.time() - t0) / n * 1e3
    print(f"{label:42s} {ms:9.2f} ms")
    return ms, out


def main():
    print("platform:", jax.devices()[0].platform)
    B = int(sys.argv[1]) if len(sys.argv) > 1 else 2048
    from raft_tla_tpu.utils.platform import enable_persistent_cache
    enable_persistent_cache()
    setup = load_config("configs/MCraft_bounded.cfg")
    dims = setup.dims
    # The per-stage parts below instrument the v1 pipeline's components;
    # the fused-CHUNK section at the end times BOTH pipelines (v1 expand
    # vs the actions2 delta path) on the same warm frontier.
    cfg = EngineConfig(batch=B, queue_capacity=1 << 20,
                       seen_capacity=1 << 23, record_trace=False,
                       check_deadlock=False, pipeline="v1")
    eng = make_engine(setup, cfg)
    G, SW, Q, K = eng._G, eng._sw, eng._Q, eng._K
    QA = Q + eng._PAD
    BG = B * G
    print(f"dims: {dims}  B={B} G={G} SW={SW} B*G={BG} K={K}")

    # A realistic frontier: run the engine for a few levels and snapshot a
    # mid-level frontier, so the benchmarked batch has representative
    # duplication/occupancy (tiled roots would collapse to ~G distinct
    # candidates and flatter the dedup path).
    warm = make_engine(setup, EngineConfig(
        batch=B, queue_capacity=1 << 20, seen_capacity=1 << 23,
        record_trace=False, check_deadlock=False, max_diameter=4))
    wres = warm.run(initial_states(setup))
    wrows = warm._last_frontier
    print(f"warm-up frontier: {len(wrows)} states at diameter "
          f"{wres.diameter} ({wres.distinct} distinct seen)")
    reps = -(-QA // len(wrows))
    qcur = jnp.asarray(np.tile(wrows, (reps, 1))[:QA])

    # The staged decomposition — the SAME programs --profile-chunks runs
    # inside a live engine (obs/profile.py), so a number printed here
    # and a chunk_profile event disagree only if the hardware does.
    from raft_tla_tpu.obs.profile import (STAGES, build_stage_programs,
                                          profile_stages)
    rows = qcur[:B]
    means = profile_stages(dims, np.asarray(rows), lanes=K,
                           seen_capacity=cfg.seen_capacity, n=10)
    for s in STAGES:
        print(f"{s + ' (staged, fenced)':42s} {means[s] * 1e3:9.2f} ms")
    staged_sum = sum(means[s] for s in STAGES)
    print(f"{'sum(stages)':42s} {staged_sum * 1e3:9.2f} ms")
    print(f"{'staged total (one jit, non-donating)':42s} "
          f"{means['total'] * 1e3:9.2f} ms")

    # Beyond the shared stages: the alternate compaction lowering...
    expand = build_expand(dims)
    from raft_tla_tpu.ops.compact import build_compactor
    compactor_ss = build_compactor(B, G, K, method="searchsorted")

    @jax.jit
    def part_compact_ss(rows):
        states = jax.vmap(unflatten_state, (0, None))(rows, dims)
        cands, en, ovf = jax.vmap(expand)(states)
        cflat = jax.tree.map(
            lambda a: a.reshape((BG,) + a.shape[2:]), cands)
        _P, _total, lane_id, kvalid = compactor_ss(en)
        return (cflat, lane_id, kvalid)

    bench("expand + compact[searchsorted]", part_compact_ss, rows)

    # ...and the Pallas lowerings, fed from the shared stage programs'
    # own intermediates (no re-derived pipeline).
    progs = build_stage_programs(dims, B, K)
    valid = jnp.ones((B,), bool)
    cflat, lane_id, kvalid = progs["expand"](rows, valid)
    kstates, kh, kl = progs["fingerprint"](cflat, lane_id)
    seen = fpset.empty(cfg.seen_capacity)
    # Pallas sequential-grid insert (ops/fpset_pallas.py): same contract,
    # no sort/claims; prices Mosaic scalar-DMA probing — the datum for
    # NORTHSTAR.md §d's fused-chunk decision.  Tolerant of a Mosaic
    # lowering failure (unmeasured until a window runs it on real TPU).
    try:
        from raft_tla_tpu.ops import fpset_pallas
        seen_p = fpset.empty(cfg.seen_capacity)
        bench("fpset_pallas.insert (sequential kernel)",
              fpset_pallas.insert, seen_p, kh, kl, kvalid)
    except Exception as e:  # noqa: BLE001 — report, keep profiling
        print(f"fpset_pallas.insert                        FAILED: {e!r}")
    krows = jax.vmap(flatten_state, (0, None))(kstates, dims)
    qnext = jnp.zeros((QA, SW), jnp.uint8)
    # Pallas run-coalesced enqueue (ops/enqueue_pallas.py): the
    # contiguous-append formulation of the 14.5 ms scatter stage —
    # the other half of NORTHSTAR §d's fused-chunk pricing.
    try:
        from raft_tla_tpu.ops import enqueue_pallas
        qnext2 = jnp.zeros((QA, SW), jnp.uint8)
        bench("enqueue pallas (run-coalesced DMA)", enqueue_pallas.enqueue,
              qnext2, jnp.int32(0), krows, kvalid)
    except Exception as e:  # noqa: BLE001 — report, keep profiling
        print(f"enqueue_pallas                             FAILED: {e!r}")

    # The engine's own fused chunk program (qnext/seen/tbuf are donated:
    # thread the outputs back through).
    tbuf = tuple(jnp.zeros((eng._TA,), d) for d in
                 (jnp.uint32, jnp.uint32, jnp.uint32, jnp.uint32, jnp.int32))

    def chunk_once(qnext, seen, tbuf):
        return eng._chunk(qcur, jnp.int32(B), jnp.int32(0), qnext,
                          jnp.int32(0), seen, tbuf, jnp.int32(0),
                          jnp.int32(1))

    out = chunk_once(qnext, seen, tbuf)     # compile + warm
    jax.block_until_ready(out)
    n = 10
    t0 = time.time()
    for _ in range(n):
        out = chunk_once(out[0], out[1], out[2])
    jax.block_until_ready(out)
    print(f"{'CHUNK (1 batch, fused program)':42s} "
          f"{(time.time() - t0) / n * 1e3:9.2f} ms")
    st = np.asarray(out[3])
    print(f"  chunk stats: offset={st[0]} steps={st[1]} next={st[2]} "
          f"seen={st[3]} gen={st[5]} new={st[6]}")

    def chunk8(qnext, seen, tbuf):
        return eng._chunk(qcur, jnp.int32(8 * B), jnp.int32(0), qnext,
                          jnp.int32(0), seen, tbuf, jnp.int32(0),
                          jnp.int32(8))

    out = chunk8(out[0], out[1], out[2])    # warm (same compiled program)
    jax.block_until_ready(out)
    t0 = time.time()
    for _ in range(n):
        out = chunk8(out[0], out[1], out[2])
    jax.block_until_ready(out)
    print(f"{'CHUNK x8 (8 batches per call)':42s} "
          f"{(time.time() - t0) / n / 8 * 1e3:9.2f} ms/batch")

    # The same fused chunk, v2 (delta) pipeline — models/actions2.py.
    eng2 = make_engine(setup, EngineConfig(
        batch=B, queue_capacity=1 << 20, seen_capacity=1 << 23,
        record_trace=False, check_deadlock=False, pipeline="v2"))
    qnext2 = jnp.zeros((QA, SW), jnp.uint8)
    seen2 = fpset.empty(cfg.seen_capacity)
    tbuf2 = tuple(jnp.zeros((eng2._TA,), d) for d in
                  (jnp.uint32, jnp.uint32, jnp.uint32, jnp.uint32,
                   jnp.int32))
    out2 = eng2._chunk(qcur, jnp.int32(B), jnp.int32(0), qnext2,
                       jnp.int32(0), seen2, tbuf2, jnp.int32(0),
                       jnp.int32(1))
    jax.block_until_ready(out2)
    t0 = time.time()
    for _ in range(n):
        out2 = eng2._chunk(qcur, jnp.int32(B), jnp.int32(0), out2[0],
                           jnp.int32(0), out2[1], out2[2], jnp.int32(0),
                           jnp.int32(1))
    jax.block_until_ready(out2)
    print(f"{'CHUNK v2 (1 batch, delta pipeline)':42s} "
          f"{(time.time() - t0) / n * 1e3:9.2f} ms")

    def chunk8_v2(qnext, seen, tbuf):
        return eng2._chunk(qcur, jnp.int32(8 * B), jnp.int32(0), qnext,
                           jnp.int32(0), seen, tbuf, jnp.int32(0),
                           jnp.int32(8))

    out2 = chunk8_v2(out2[0], out2[1], out2[2])
    jax.block_until_ready(out2)
    t0 = time.time()
    for _ in range(n):
        out2 = chunk8_v2(out2[0], out2[1], out2[2])
    jax.block_until_ready(out2)
    print(f"{'CHUNK v2 x8 (8 batches per call)':42s} "
          f"{(time.time() - t0) / n / 8 * 1e3:9.2f} ms/batch")

    # Full candidate config: v2 + searchsorted compaction + window
    # enqueue — the three profile-justified lowerings together.
    eng3 = make_engine(setup, EngineConfig(
        batch=B, queue_capacity=1 << 20, seen_capacity=1 << 23,
        record_trace=False, check_deadlock=False, pipeline="v2",
        compact_method="searchsorted", enqueue_method="window"))
    qnext3 = jnp.zeros((QA, SW), jnp.uint8)
    seen3 = fpset.empty(cfg.seen_capacity)
    tbuf3 = tuple(jnp.zeros((eng3._TA,), d) for d in
                  (jnp.uint32, jnp.uint32, jnp.uint32, jnp.uint32,
                   jnp.int32))

    def chunk8_v3(qnext, seen, tbuf, nb):
        return eng3._chunk(qcur, jnp.int32(nb * B), jnp.int32(0), qnext,
                           jnp.int32(0), seen, tbuf, jnp.int32(0),
                           jnp.int32(nb))

    out3 = chunk8_v3(qnext3, seen3, tbuf3, 1)
    jax.block_until_ready(out3)
    out3 = chunk8_v3(out3[0], out3[1], out3[2], 8)
    jax.block_until_ready(out3)
    t0 = time.time()
    for _ in range(n):
        out3 = chunk8_v3(out3[0], out3[1], out3[2], 8)
    jax.block_until_ready(out3)
    print(f"{'CHUNK v2+ss+win x8 (full candidate)':42s} "
          f"{(time.time() - t0) / n / 8 * 1e3:9.2f} ms/batch")

    # The v3 fused Pallas pipeline (NORTHSTAR §d decision row): the
    # fused-stage decomposition, then the engine's whole v3 chunk.  On
    # TPU this prices the real Mosaic kernels (Pallas compact + fused
    # probe/insert->enqueue tail); off-TPU it runs interpret mode — a
    # correctness instrument, not a perf number.  Tolerant of a Mosaic
    # lowering failure: the plan's per-stage fallback is part of what
    # this row measures, so a fallen-back stage prints as such instead
    # of aborting the session.
    try:
        from raft_tla_tpu.obs.profile import STAGES_V3
        means3 = profile_stages(dims, np.asarray(rows), lanes=K,
                                seen_capacity=cfg.seen_capacity, n=10,
                                pipeline="v3")
        for s in STAGES_V3:
            print(f"{'v3 ' + s + ' (staged, fenced)':42s} "
                  f"{means3[s] * 1e3:9.2f} ms")
        print(f"{'v3 staged total (one jit)':42s} "
              f"{means3['total'] * 1e3:9.2f} ms")
        engv3 = make_engine(setup, EngineConfig(
            batch=B, queue_capacity=1 << 20, seen_capacity=1 << 23,
            record_trace=False, check_deadlock=False, pipeline="v3"))
        from raft_tla_tpu.ops.pipeline_v3 import describe
        print(f"{'v3 plan':42s} {describe(engv3._v3_plan)}")
        qnextf = jnp.zeros((QA, SW), jnp.uint8)
        seenf = fpset.empty(cfg.seen_capacity)
        tbuff = tuple(jnp.zeros((engv3._TA,), d) for d in
                      (jnp.uint32, jnp.uint32, jnp.uint32, jnp.uint32,
                       jnp.int32))

        def chunk_f(qnext, seen, tbuf, nb):
            return engv3._chunk(qcur, jnp.int32(nb * B), jnp.int32(0),
                                qnext, jnp.int32(0), seen, tbuf,
                                jnp.int32(0), jnp.int32(nb))

        outf = chunk_f(qnextf, seenf, tbuff, 1)
        jax.block_until_ready(outf)
        t0 = time.time()
        for _ in range(n):
            outf = chunk_f(outf[0], outf[1], outf[2], 1)
        jax.block_until_ready(outf)
        print(f"{'CHUNK v3 (1 batch, fused pipeline)':42s} "
              f"{(time.time() - t0) / n * 1e3:9.2f} ms")
        outf = chunk_f(outf[0], outf[1], outf[2], 8)
        jax.block_until_ready(outf)
        t0 = time.time()
        for _ in range(n):
            outf = chunk_f(outf[0], outf[1], outf[2], 8)
        jax.block_until_ready(outf)
        print(f"{'CHUNK v3 x8 (8 batches per call)':42s} "
              f"{(time.time() - t0) / n / 8 * 1e3:9.2f} ms/batch")
    except Exception as e:  # noqa: BLE001 — report, keep the session
        print(f"v3 pipeline                                FAILED: {e!r}")


if __name__ == "__main__":
    main()
