"""Throughput on a LEADER-RICH frontier — the measurement the plain bench
never reaches (VERDICT r3 weak #4: at diameter <= 9 the MCraft space is
virtually leader-free, so ClientRequest / AppendEntries / AdvanceCommitIndex
— the log-machinery kernels — sit at ~0 in the measured mix).

Seeding: for each server, the oracle walks the canonical election
(Timeout -> RequestVote x2 -> deliver both grants -> BecomeLeader,
raft.tla:146-279,195-203), then a short oracle BFS from those leader states
collects every reachable state that still has a leader — a frontier where
the leader families are enabled at the same density a deep exhaustive level
would show.  The engine then expands that frontier under a duration budget
and reports states/s plus the per-family generated counts (which the run
asserts are leader-heavy: the three leader families must all be nonzero).

Usage:  python scripts/leader_bench.py [seconds] [batch]
Env:    LB_SEED_DEPTH (default 2) - oracle BFS depth for frontier growth.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from raft_tla_tpu.utils.platform import (enable_persistent_cache,
                                         neutralize_axon_if_cpu_requested)

neutralize_axon_if_cpu_requested()   # honor JAX_PLATFORMS=cpu
enable_persistent_cache()

from raft_tla_tpu.engine.bfs import BFSEngine, EngineConfig  # noqa: E402
from raft_tla_tpu.models import oracle as orc  # noqa: E402
from raft_tla_tpu.models.dims import LEADER, RVR  # noqa: E402
from raft_tla_tpu.models.invariants import (Bounds, build_constraint,  # noqa: E402
                                            constraint_py)
from raft_tla_tpu.models.pystate import init_state  # noqa: E402
from raft_tla_tpu.utils.cfg import load_config  # noqa: E402


def leader_states(dims, bounds, depth):
    """Leader-holding states within ``depth`` steps of a fresh election."""
    roots = []
    n = dims.n_servers
    for lead in range(n):
        s = orc.timeout(init_state(dims), dims, lead)
        for j in range(n):
            if j != lead:
                s = orc.request_vote(s, dims, lead, j)
        # Deliver messages to quiescence: each RVQ takes TWO receives (the
        # first is UpdateTerm — message left in flight, raft.tla:378 — the
        # second grants and queues the RVR), then the grants come home.
        for _ in range(6 * n):
            nxt = None
            for m, _c in sorted(s.messages):
                nxt = orc.receive(s, dims, m)
                if nxt is not None:
                    s = nxt
                    break
            if nxt is None:
                break
        s = s.replace(messages=frozenset())      # clean election aftermath
        s = orc.become_leader(s, dims, lead)
        assert s is not None and s.role[lead] == LEADER
        roots.append(s)
    res = orc.bfs(roots, dims, constraint=constraint_py(bounds),
                  check_deadlock=False, max_levels=depth)
    return [t for t in res.parent if LEADER in t.role]


def main():
    seconds = float(sys.argv[1]) if len(sys.argv) > 1 else 30.0
    batch = int(sys.argv[2]) if len(sys.argv) > 2 else 2048
    depth = int(os.environ.get("LB_SEED_DEPTH", 2))

    setup = load_config(os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "configs", "MCraft_bounded.cfg"))
    dims, bounds = setup.dims, setup.bounds

    t0 = time.time()
    seeds = leader_states(dims, bounds, depth)
    seed_s = time.time() - t0
    # One ingest wave only: the engine's duration budget applies between
    # ingest batches (StopAfter semantics), so a multi-wave ingest under a
    # small budget would stop before any expansion.  A batch-sized seed
    # set is still leader-rich, and the TPU-sized invocation (batch 2048)
    # ingests every seed anyway.
    # COMPARABILITY: this truncation makes the measured frontier a
    # function of ``batch`` — numbers taken at different batch sizes are
    # different workloads, not the same bench at another setting.  The
    # record therefore carries both ``seeds`` and ``seeds_total``; compare
    # rows across rounds only at equal (batch, seeds) (advisor r4).
    seeds_total = len(seeds)
    seeds = seeds[:batch]

    common = dict(batch=batch, queue_capacity=1 << 22,
                  seen_capacity=1 << 24, record_trace=False,
                  check_deadlock=False)
    # Warm-up: compile the ingest + chunk programs OUTSIDE the measured
    # budget (the persistent cache makes the measured engine's identical
    # programs near-instant to build).  Without this, a small budget is
    # consumed entirely by XLA compilation and the run expands nothing.
    warm = BFSEngine(dims, constraint=build_constraint(dims, bounds),
                     config=EngineConfig(max_diameter=1, **common))
    warm.run(seeds[:1])

    eng = BFSEngine(
        dims, constraint=build_constraint(dims, bounds),
        config=EngineConfig(max_seconds=seconds, **common))
    res = eng.run(seeds)

    leader_fams = ("ClientRequest", "AppendEntries", "AdvanceCommitIndex")
    leader_gen = sum(res.action_counts.get(f, 0) for f in leader_fams)
    rec = {
        "metric": "leader_rich_distinct_per_s",
        "value": round(res.states_per_second, 1),
        "unit": "distinct states/s",
        "seeds": len(seeds), "seeds_total": seeds_total,
        "seed_build_s": round(seed_s, 1),
        "distinct": res.distinct, "generated": res.generated,
        "diameter": res.diameter, "wall_s": round(res.wall_seconds, 2),
        "stop_reason": res.stop_reason,
        "leader_family_generated": {
            f: res.action_counts.get(f, 0) for f in leader_fams},
        "leader_family_share": round(
            leader_gen / max(1, res.generated), 4),
    }
    assert all(rec["leader_family_generated"][f] > 0 for f in leader_fams), (
        "leader-rich bench failed to exercise the log-machinery kernels: "
        f"{rec['leader_family_generated']}")
    print(json.dumps(rec))


if __name__ == "__main__":
    main()
