"""Diff two bench JSONs and gate on regressions — the enforceable form
of the BENCH_r* trajectory.

``bench.py`` (and the round-note harness that wraps it into
``BENCH_rNN.json``) emits one JSON object per run: headline
states/s, per-phase host seconds, per-stage chunk means
(``chunk_stages``, obs/profile.py), and the TLC-style ``coverage``
object (obs/coverage.py).  This script compares OLD vs NEW along all
four axes and exits nonzero when NEW regresses past the thresholds —
so CI (and a human mid-perf-PR) gets a yes/no instead of two JSON
blobs to eyeball.

    python scripts/bench_diff.py BENCH_r04.json BENCH_r05.json
    python scripts/bench_diff.py old.json new.json --max-regress 0.05

Input forms accepted: the raw bench.py object, or the ``BENCH_rNN``
wrapper ``{"cmd", "rc", "tail", "parsed": {...}}`` (the parsed object
is used; a null ``parsed`` — a bench run that never emitted JSON — is
malformed input, exit 2).

Comparison rules (each axis only when BOTH runs carry it — early
BENCH_r01–r05 files predate chunk_stages/coverage and still diff):

- headline ``value`` (distinct states/s) and ``generated_per_sec``:
  regression when NEW < OLD * (1 - max_regress).
- per-phase seconds: normalized to seconds per million distinct states
  (budget-length independence), compared per phase when the OLD phase
  is at least ``--phase-floor`` of total phase time (noise floor for
  sub-percent phases); threshold ``--phase-max-regress``.
- per-stage chunk means (``chunk_stages``): direct per-stage ratio,
  threshold ``--stage-max-regress``; the fused ``total`` row is
  compared too (it is the engine-shaped number).  Runs profiled at
  DIFFERENT stage granularities (the v1/v2 decomposition vs the v3
  fused-stage keys, obs/profile.py STAGES vs STAGES_V3) are folded
  onto common coarse stages — front (expand | masks+compact),
  fingerprint, tail (dedup_insert+enqueue | insert_enqueue), total —
  with a note, instead of silently comparing an empty intersection
  (or refusing the diff).
- performance observatory (``perf`` block, obs/perf.py — also the
  ``scripts/xplane_summary.py`` dialect): ``launches_per_chunk`` rising
  past ``--launch-drift`` regresses (a stage un-fusing is visible
  before any wall-clock moves), as does a stage's achieved-bandwidth
  fraction falling by the same margin; one side predating the block
  folds to a note.
- POR pruned fraction (``pruned / (pruned + generated)`` from the
  coverage object): compared whenever either side pruned anything; a
  candidate whose fraction falls more than ``--pruned-drift`` points
  below the baseline regresses — a certified reduction collapsing back
  to full expansion must fail loudly.
- coverage mix: per-action share of total generated; an action whose
  share moves more than ``--coverage-drift`` (absolute percentage
  points) is flagged.  This is a semantics drift detector, not a perf
  number — identical models must produce identical mixes up to
  duration-budget truncation — so it defaults loose (5 pts).

- swarm dialect (``BENCH_MODE=swarm`` documents, ``mode: "swarm"``):
  when BOTH sides are swarm, the steps/s headline plus walks/s,
  visited/s, and the time-to-first-counterexample are gated; when the
  two sides speak DIFFERENT dialects, the diff folds to a note with
  both headlines reported and nothing gated — an exhaustive distinct/s
  number and a swarm steps/s number measure different things.  When
  both sides also embed a hunt summary (obs/hunt.py), the coverage
  saturation and per-bucket novelty trajectory are gated under
  ``--hunt-drift``: a novelty curve that moved means the walks are
  exploring differently, which is a semantics change, not a perf one.

Additionally, when both runs embed a ``host_fingerprint`` (bench.py,
BENCH_r06+), mismatched hardware/stack identity prints a loud
cross-host WARNING note — absolute rates measured on different hosts
must never be silently read as a trajectory.

Improvements are reported but never fail.  Exit codes: 0 pass, 1 at
least one regression, 2 malformed input/usage (consistent with the
validate_run_events convention: a gate that cannot read its evidence
fails loudly, not silently green).
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

PHASE_PREFIX_SKIP = ("profile",)   # measurement overhead, not engine work


def load_bench(path: str) -> dict:
    """Load a bench JSON in either accepted form; raise ValueError on
    anything that is not a bench result object."""
    try:
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        raise ValueError(f"{path}: cannot load bench JSON: {e}")
    if isinstance(data, dict) and "parsed" in data:
        data = data["parsed"]           # BENCH_rNN wrapper
    # "value" is the classic bench headline; a perf-only document (the
    # scripts/xplane_summary.py dialect: measured launch counts from
    # device-profiler artifacts, no states/s headline) diffs too — the
    # headline axis simply has nothing to compare.
    if not isinstance(data, dict) or ("value" not in data
                                      and "perf" not in data):
        raise ValueError(
            f"{path}: not a bench result (no 'value' or 'perf' field; a "
            f"BENCH_r* wrapper whose run emitted no JSON has "
            f"parsed=null)")
    return data


def _ratio_regress(old: float, new: float, thresh: float) -> bool:
    """True when NEW is worse than OLD by more than ``thresh`` (rates:
    lower is worse — callers flip sign for costs)."""
    return old > 0 and new < old * (1.0 - thresh)


class Diff:
    """Accumulates findings; renders the report and the exit code."""

    def __init__(self):
        self.regressions = []
        self.notes = []

    def regress(self, msg: str) -> None:
        self.regressions.append(msg)

    def note(self, msg: str) -> None:
        self.notes.append(msg)

    def render(self, stream=sys.stdout) -> int:
        for n in self.notes:
            print(f"  {n}", file=stream)
        for r in self.regressions:
            print(f"  REGRESSION: {r}", file=stream)
        verdict = ("FAIL" if self.regressions else "PASS")
        print(f"bench_diff: {verdict} "
              f"({len(self.regressions)} regression(s))", file=stream)
        return 1 if self.regressions else 0


#: host_fingerprint keys that make absolute rates incomparable when
#: they differ (hostname alone does not: same container class, new pod).
#: ONE definition, shared with the run ledger's host_key
#: (obs/history.py) — the cross-host WARNING here and resolve_baseline's
#: same-host matching must never disagree about what "same host" means.
from raft_tla_tpu.obs.history import HOST_KEYS as _FINGERPRINT_KEYS  # noqa: E402


def diff_host(old: dict, new: dict, d: Diff):
    """Cross-host guard: when both benches carry a host_fingerprint
    (bench.py, obs/flight.py) and they disagree on hardware/stack
    identity, say so LOUDLY in the notes — the BENCH_r05 trap was an
    absolute number silently compared across a ~4x slower container.
    A note, not a regression: cross-host diffs are sometimes exactly
    what the operator wants (e.g. CPU vs TPU), they just must never be
    read as a regression gate."""
    of, nf = old.get("host_fingerprint"), new.get("host_fingerprint")
    if not of or not nf:
        return
    diffs = [k for k in _FINGERPRINT_KEYS if of.get(k) != nf.get(k)]
    if diffs:
        d.note("WARNING: benches ran on DIFFERENT hosts/stacks — "
               "absolute rates are not comparable; fields: "
               + ", ".join(f"{k}: {of.get(k)!r} -> {nf.get(k)!r}"
                           for k in diffs))
    else:
        d.note("host fingerprints match "
               f"({of.get('cpu_model') or 'unknown cpu'}, "
               f"{of.get('device_kind') or of.get('platform')})")


def diff_headline(old: dict, new: dict, d: Diff, max_regress: float):
    # The headline's direction follows its unit: rates (".../s",
    # bench.py) regress downward, costs ("ms/iter", true_bench.py TB_JSON)
    # regress upward.
    unit = old.get("unit", "states/s")
    higher_is_better = not unit.startswith("ms")
    for key, label in (("value", f"headline ({unit})"),
                       ("generated_per_sec", "generated states/s")):
        ov, nv = old.get(key), new.get(key)
        if ov is None or nv is None:
            continue
        pct = (nv - ov) / ov * 100.0 if ov else 0.0
        d.note(f"{label}: {ov:,.1f} -> {nv:,.1f} ({pct:+.1f}%)")
        worse = (_ratio_regress(ov, nv, max_regress) if higher_is_better
                 else ov > 0 and nv > ov * (1.0 + max_regress))
        if worse:
            d.regress(f"{label} moved {pct:+.1f}% "
                      f"(> {max_regress:.0%} allowed): {ov:,.1f} -> "
                      f"{nv:,.1f}")


def bench_mode(doc: dict) -> str:
    """Which bench dialect a document speaks: ``swarm`` (bench.py
    BENCH_MODE=swarm — steps/s headline, walks/visited rates,
    violation_at_seconds) or ``exhaustive`` (the classic distinct/s
    headline; legacy files predate the key)."""
    return doc.get("mode", "exhaustive")


def diff_swarm(old: dict, new: dict, d: Diff, max_regress: float):
    """Swarm-dialect axes (both sides BENCH_MODE=swarm): the walk and
    visit rates regress like the headline, and the time-to-first-
    counterexample regresses when the candidate finds its violation
    slower than allowed — or stops finding one the baseline found."""
    for key, label in (("walks_per_sec", "walks/s"),
                       ("visited_per_sec", "visited states/s")):
        ov, nv = old.get(key), new.get(key)
        if ov is None or nv is None:
            continue
        pct = (nv - ov) / ov * 100.0 if ov else 0.0
        d.note(f"swarm {label}: {ov:,.1f} -> {nv:,.1f} ({pct:+.1f}%)")
        if _ratio_regress(ov, nv, max_regress):
            d.regress(f"swarm {label} moved {pct:+.1f}% "
                      f"(> {max_regress:.0%} allowed): {ov:,.1f} -> "
                      f"{nv:,.1f}")
    ov, nv = old.get("violation_at_seconds"), new.get("violation_at_seconds")
    if ov is None and nv is None:
        return
    d.note(f"violation found at: "
           f"{'-' if ov is None else f'{ov:.2f}s'} -> "
           f"{'-' if nv is None else f'{nv:.2f}s'}")
    if ov is not None and nv is None:
        d.regress(f"baseline found its violation at {ov:.2f}s; the "
                  f"candidate found none in its budget")
    elif ov is not None and nv is not None \
            and ov > 0 and nv > ov * (1.0 + max_regress):
        d.regress(f"time-to-violation rose "
                  f"{(nv - ov) / ov * 100.0:.1f}% "
                  f"(> {max_regress:.0%} allowed): {ov:.2f}s -> "
                  f"{nv:.2f}s")


def diff_hunt(old: dict, new: dict, d: Diff, drift: float):
    """Hunt-observatory axes (both sides swarm with a ``hunt`` summary
    — obs/hunt.py summarize): coverage saturation and the novelty rate
    are reported, and the novelty CURVE is drift-gated — same seed and
    budget should trace the same novelty trajectory, so any bucket of
    the refolded curve moving more than ``--hunt-drift`` (absolute
    novel-rate points) flags a behavioral change in the walk decisions
    (diversification, ring, PRNG), not mere throughput noise.  A
    saturation estimate falling more than the same drift regresses
    too: the candidate's hunt is measurably further from done."""
    oh, nh = old.get("hunt"), new.get("hunt")
    if not isinstance(oh, dict) or not isinstance(nh, dict):
        if isinstance(oh, dict) or isinstance(nh, dict):
            d.note("hunt summary present on one side only "
                   "(observatory toggled?) — hunt axes skipped")
        return
    for key, label, pct in (("saturation", "hunt saturation", True),
                            ("novel_rate", "hunt novel rate", True),
                            ("distinct_observed",
                             "hunt distinct observed", False)):
        ov, nv = oh.get(key), nh.get(key)
        if ov is None or nv is None:
            continue
        if pct:
            d.note(f"{label}: {ov:.1%} -> {nv:.1%}")
        else:
            d.note(f"{label}: {ov:,} -> {nv:,}")
    ov, nv = oh.get("saturation"), nh.get("saturation")
    if ov is not None and nv is not None and ov - nv > drift:
        d.regress(f"hunt saturation fell {ov - nv:.2f} "
                  f"(> {drift:g} allowed): {ov:.1%} -> {nv:.1%} — "
                  f"the candidate's hunt is further from saturated "
                  f"on the same budget")
    oc = {int(k): r for k, r in (oh.get("novelty_curve") or [])}
    nc = {int(k): r for k, r in (nh.get("novelty_curve") or [])}
    worst = None
    for k in sorted(set(oc) & set(nc)):
        delta = abs(nc[k] - oc[k])
        if worst is None or delta > worst[1]:
            worst = (k, delta)
        if delta > drift:
            d.regress(f"novelty curve drift at step {k}: novel rate "
                      f"{oc[k]:.1%} -> {nc[k]:.1%} (|delta| "
                      f"{delta:.2f} > {drift:g} allowed) — the walks "
                      f"are exploring differently, not just "
                      f"slower/faster")
    if worst is not None:
        d.note(f"novelty curve: {len(set(oc) & set(nc))} comparable "
               f"buckets, worst drift {worst[1]:.3f} at step "
               f"{worst[0]}")


def diff_phases(old: dict, new: dict, d: Diff, max_regress: float,
                floor: float):
    op, np_ = old.get("phases") or {}, new.get("phases") or {}
    od, nd = old.get("distinct_states"), new.get("distinct_states")
    if not op or not np_ or not od or not nd:
        return
    ototal = sum(op.values()) or 1.0
    for phase in sorted(set(op) & set(np_)):
        if phase in PHASE_PREFIX_SKIP:
            continue
        if op[phase] / ototal < floor:
            continue        # sub-floor phases are timer noise
        # Seconds per 1M distinct states: compares runs of different
        # duration budgets on the same model.
        oc = op[phase] / od * 1e6
        nc = np_[phase] / nd * 1e6
        pct = (nc - oc) / oc * 100.0 if oc else 0.0
        d.note(f"phase {phase}: {oc:.2f} -> {nc:.2f} s/M-distinct "
               f"({pct:+.1f}%)")
        if oc > 0 and nc > oc * (1.0 + max_regress):
            d.regress(f"phase '{phase}' cost rose {pct:.1f}% "
                      f"(> {max_regress:.0%} allowed): {oc:.2f} -> "
                      f"{nc:.2f} s/M-distinct")


# chunk_stages key -> coarse common stage, across every profiler
# granularity (obs/profile.py STAGES, STAGES_V3, STAGES_V4).  "front"
# is everything up to and including the fingerprint (v1's expand row
# already folds compaction in; v3 splits masks/compact; v4's megakernel
# row covers the whole trio — folding the fingerprint into "front"
# everywhere keeps all three granularities comparable), "tail" is
# everything after it.
STAGE_FOLD = {
    "expand": "front", "masks": "front", "compact": "front",
    "fingerprint": "front", "front": "front",
    "dedup_insert": "tail", "enqueue": "tail", "insert_enqueue": "tail",
    "total": "total",
}


def fold_stages(stages: dict):
    """Sum a chunk_stages dict onto the coarse common stages; unknown
    keys are returned separately (reported, never silently dropped)."""
    out, unknown = {}, []
    for key, val in stages.items():
        coarse = STAGE_FOLD.get(key)
        if coarse is None:
            unknown.append(key)
        else:
            out[coarse] = out.get(coarse, 0.0) + val
    return out, unknown


def diff_stages(old: dict, new: dict, d: Diff, max_regress: float):
    os_, ns = old.get("chunk_stages") or {}, new.get("chunk_stages") or {}
    if not os_ or not ns:
        return
    if set(os_) != set(ns):
        # Mismatched granularities (e.g. a v2 bench vs a v3 bench, whose
        # profiler emits the fused-stage keys): fold both sides onto the
        # common coarse stages and diff those — a cross-pipeline diff
        # stays a diff, not a refusal.
        os_, o_unk = fold_stages(os_)
        ns, n_unk = fold_stages(ns)
        d.note("chunk_stages granularities differ "
               f"(old: {old.get('pipeline') or 'v1/v2'} keys, "
               f"new: {new.get('pipeline') or 'v1/v2'} keys); folded to "
               "common stages front(expand|masks+compact) / fingerprint "
               "/ tail(insert+enqueue)")
        for side, unk in (("old", o_unk), ("new", n_unk)):
            if unk:
                d.note(f"  unrecognized {side} stage keys not folded: "
                       f"{', '.join(sorted(unk))}")
    for stage in sorted(set(os_) & set(ns)):
        oc, nc = os_[stage], ns[stage]
        pct = (nc - oc) / oc * 100.0 if oc else 0.0
        d.note(f"chunk stage {stage}: {oc * 1e3:.2f} -> {nc * 1e3:.2f} "
               f"ms/batch ({pct:+.1f}%)")
        if oc > 0 and nc > oc * (1.0 + max_regress):
            d.regress(f"chunk stage '{stage}' rose {pct:.1f}% "
                      f"(> {max_regress:.0%} allowed): {oc * 1e3:.2f} -> "
                      f"{nc * 1e3:.2f} ms/batch")


def diff_perf(old: dict, new: dict, d: Diff, launch_drift: float):
    """Performance-observatory axis (obs/perf.py ``perf`` block, also
    the scripts/xplane_summary.py dialect): launches_per_chunk rising
    more than ``--launch-drift`` (fractional) regresses — a stage
    un-fusing shows up here before any wall-clock number moves — and a
    stage's achieved-bandwidth fraction falling by more than the same
    fraction regresses too.  Folds gracefully when one side predates
    the metric (legacy BENCH_r* files): reported, never gated."""
    op, np_ = old.get("perf") or {}, new.get("perf") or {}
    if not op and not np_:
        return
    if not op or not np_:
        side = "baseline" if not op else "candidate"
        have = np_ if np_ else op
        lpc = (have.get("launch") or {}).get("launches_per_chunk")
        d.note(f"perf block present on one side only ({side} predates "
               f"it); launches/chunk "
               + (f"{lpc:,.0f}" if lpc is not None else "unknown")
               + " not gated")
        return
    ol = (op.get("launch") or {}).get("launches_per_chunk")
    nl = (np_.get("launch") or {}).get("launches_per_chunk")
    if ol is not None and nl is not None:
        pct = (nl - ol) / ol * 100.0 if ol else 0.0
        d.note(f"launches/chunk: {ol:,.0f} -> {nl:,.0f} ({pct:+.1f}%)")
        if ol > 0 and nl > ol * (1.0 + launch_drift):
            d.regress(f"launches_per_chunk rose {pct:.1f}% "
                      f"(> {launch_drift:.0%} allowed): {ol:,.0f} -> "
                      f"{nl:,.0f} — a stage un-fused or the chunk "
                      f"program grew kernels")
    osr = ((op.get("roofline") or {}).get("stages")) or {}
    nsr = ((np_.get("roofline") or {}).get("stages")) or {}
    for stage in sorted(set(osr) & set(nsr)):
        of = osr[stage].get("bandwidth_fraction")
        nf = nsr[stage].get("bandwidth_fraction")
        if of is None or nf is None:
            continue
        d.note(f"achieved bandwidth {stage}: {of:.2%} -> {nf:.2%} "
               f"of peak")
        if of > 0 and nf < of * (1.0 - launch_drift):
            d.regress(f"achieved-bandwidth fraction of '{stage}' fell "
                      f"{(of - nf) / of:.0%} (> {launch_drift:.0%} "
                      f"allowed): {of:.2%} -> {nf:.2%} of peak")
    oa = (op.get("advisor") or {}).get("top")
    na = (np_.get("advisor") or {}).get("top")
    if oa or na:
        d.note(f"fusion advisor top candidate: {oa or '-'} -> "
               f"{na or '-'}")


def pruned_fraction(cov: dict):
    """(pruned count, pruned share of attempted expansions in %) from a
    coverage object — the POR reduction's first-class metric."""
    pr = sum(v.get("pruned", 0) for v in cov.values())
    gen = sum(v.get("generated", 0) for v in cov.values())
    total = pr + gen
    return pr, (pr / total * 100.0) if total else 0.0


def diff_pruned(old: dict, new: dict, d: Diff, drift_pts: float):
    """POR reduced-vs-full accounting as a first-class compared metric:
    the pruned FRACTION (pruned / (pruned + generated) expansions).  A
    candidate whose fraction falls more than ``--pruned-drift``
    percentage points below the baseline regresses — a certified
    reduction that silently collapsed back to full expansion must fail
    the gate, not hide inside an unchanged headline.  Gains are noted
    (the distinct/s gates stay the arbiter of whether pruning pays)."""
    ocov = old.get("coverage") or {}
    ncov = new.get("coverage") or {}
    op, of = pruned_fraction(ocov)
    np_, nf = pruned_fraction(ncov)
    if not op and not np_:
        return
    if not ocov or not ncov:
        # Legacy bench without a coverage object on one side: the
        # fraction cannot be compared, but a pruning run diffed against
        # (or serving as) a legacy baseline still reports the number.
        side = "baseline" if not ocov else "candidate"
        d.note(f"POR pruned expansions: {op:,} ({of:.2f}%) -> "
               f"{np_:,} ({nf:.2f}%) — {side} has no coverage object, "
               "fraction not gated")
        return
    d.note(f"POR pruned expansions: {op:,} ({of:.2f}%) -> "
           f"{np_:,} ({nf:.2f}%)")
    if of - nf > drift_pts:
        d.regress(f"POR pruned fraction fell {of - nf:.2f} pts "
                  f"({of:.2f}% -> {nf:.2f}%, > {drift_pts:g} pts "
                  "allowed) — the reduction collapsed toward full "
                  "expansion")


def diff_coverage(old: dict, new: dict, d: Diff, drift_pts: float):
    # generated_by_action predates the coverage object and carries the
    # same generated series — accept either so old BENCH files diff.
    ocov = old.get("coverage") or {}
    ncov = new.get("coverage") or {}
    og = ({a: v["generated"] for a, v in ocov.items()} if ocov
          else old.get("generated_by_action") or {})
    ng = ({a: v["generated"] for a, v in ncov.items()} if ncov
          else new.get("generated_by_action") or {})
    if not og or not ng:
        return
    ot, nt = sum(og.values()), sum(ng.values())
    if not ot or not nt:
        return
    for action in sorted(set(og) | set(ng)):
        oshare = og.get(action, 0) / ot * 100.0
        nshare = ng.get(action, 0) / nt * 100.0
        delta = nshare - oshare
        if abs(delta) >= drift_pts:
            d.regress(f"coverage mix drift: '{action}' share moved "
                      f"{delta:+.1f} pts ({oshare:.1f}% -> {nshare:.1f}%"
                      f", > {drift_pts:g} pts allowed) — same-model "
                      f"runs should agree; different model/bounds means "
                      f"the two benches are not comparable")
        elif delta:
            d.note(f"coverage {action}: {oshare:.1f}% -> {nshare:.1f}% "
                   f"of generated")


def resolve_history_baseline(ledger: str, new: dict):
    """``--history``: the baseline is the newest ledger entry whose
    host key matches the candidate's host fingerprint (obs/history.py
    resolve_baseline) — never a cross-host number.  Returns (bench
    dict, describing label); raises ValueError when it cannot resolve
    (no fingerprint on the candidate, no same-host entry, unreadable
    ledger) — exit 2, the cannot-read-evidence convention."""
    from raft_tla_tpu.obs import history as history_mod
    fp = new.get("host_fingerprint")
    if not history_mod.host_key(fp):
        raise ValueError(
            "--history needs the candidate bench to embed a "
            "host_fingerprint (bench.py emits one; legacy files do "
            "not) — without it a same-host baseline cannot be chosen")
    try:
        # exclude_bench=new: the candidate's own ledger line (the
        # documented record-then-gate workflow appends it first) must
        # never be chosen — a self-compare gate is vacuously green.
        entry = history_mod.resolve_baseline(ledger, fp,
                                             exclude_bench=new)
    except (OSError, ValueError) as e:
        raise ValueError(f"cannot read ledger {ledger}: {e}")
    if entry is None:
        raise ValueError(
            f"{ledger}: no bench entry with host key "
            f"{history_mod.host_key(fp)} other than the candidate "
            f"itself — run a bench with BENCH_HISTORY on this host "
            f"first (cross-host baselines must be picked explicitly, "
            f"never auto-resolved)")
    label = entry.get("label") or f"ts {entry.get('ts')}"
    return entry["bench"], f"history:{label}"


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="diff two bench JSONs; nonzero exit on regression")
    p.add_argument("old", nargs="?", default=None,
                   help="baseline bench JSON (raw or BENCH_r* wrapper); "
                        "omit with --history to auto-resolve it from "
                        "the run ledger")
    p.add_argument("new", nargs="?", default=None,
                   help="candidate bench JSON")
    p.add_argument("--history", default=None, metavar="LEDGER",
                   help="resolve the baseline from this run-history "
                        "ledger (obs/history.py): the newest bench "
                        "entry with the SAME host fingerprint as the "
                        "candidate.  Usage: bench_diff.py --history "
                        "LEDGER new.json")
    p.add_argument("--max-regress", type=float, default=0.10,
                   help="allowed fractional drop in headline rates "
                        "(default 0.10 = 10%%)")
    p.add_argument("--phase-max-regress", type=float, default=0.35,
                   help="allowed fractional rise in per-phase "
                        "s/M-distinct (noisier than the headline; "
                        "default 0.35)")
    p.add_argument("--stage-max-regress", type=float, default=0.35,
                   help="allowed fractional rise in per-stage chunk "
                        "means (default 0.35)")
    p.add_argument("--phase-floor", type=float, default=0.02,
                   help="ignore phases below this fraction of total "
                        "phase time in the baseline (default 0.02)")
    p.add_argument("--coverage-drift", type=float, default=5.0,
                   help="allowed absolute drift (percentage points) in "
                        "any action's share of generated states "
                        "(default 5.0)")
    p.add_argument("--launch-drift", type=float, default=0.25,
                   help="allowed fractional rise in launches_per_chunk "
                        "(and fall in per-stage achieved-bandwidth "
                        "fraction) from the perf block (obs/perf.py; "
                        "default 0.25).  Only gated when BOTH benches "
                        "carry the block — legacy files fold to a note")
    p.add_argument("--pruned-drift", type=float, default=1.0,
                   help="allowed drop (percentage points) in the POR "
                        "pruned fraction (pruned/(pruned+generated)) "
                        "vs the baseline — a collapsed reduction fails "
                        "(default 1.0; only checked when either side "
                        "pruned anything)")
    p.add_argument("--hunt-drift", type=float, default=0.25,
                   help="(swarm) allowed absolute drift in each "
                        "refolded novelty-curve bucket's novel rate "
                        "and in the saturation estimate vs the "
                        "baseline (default 0.25) — same seed and "
                        "budget tracing a different novelty "
                        "trajectory means the walk DECISIONS changed, "
                        "not just the throughput")
    args = p.parse_args(argv)

    try:
        if args.history is not None:
            # One positional: the candidate (argparse fills `old`
            # first, so accept either slot).
            new_path = args.new or args.old
            if new_path is None or (args.new and args.old):
                raise ValueError(
                    "--history takes exactly one bench JSON (the "
                    "candidate); the baseline comes from the ledger")
            new = load_bench(new_path)
            old, old_label = resolve_history_baseline(args.history, new)
        else:
            if args.old is None or args.new is None:
                raise ValueError("need OLD and NEW bench JSONs "
                                 "(or --history LEDGER NEW)")
            old, new = load_bench(args.old), load_bench(args.new)
            old_label, new_path = args.old, args.new
    except ValueError as e:
        print(f"bench_diff: {e}", file=sys.stderr)
        return 2

    print(f"bench_diff: {old_label} -> {new_path}")
    if args.history is not None:
        print(f"  baseline auto-resolved from history ledger "
              f"{args.history} ({old_label})")
    d = Diff()
    diff_host(old, new, d)
    om, nm = bench_mode(old), bench_mode(new)
    if om != nm:
        # Cross-dialect diff: an exhaustive distinct/s headline and a
        # swarm steps/s headline measure different things — folding
        # them into one regression ratio would gate noise.  The
        # STAGE_FOLD rule applies: the diff stays a diff (both
        # headlines reported, host guard above still live), nothing is
        # gated.
        d.note(f"bench modes differ (baseline: {om}, candidate: {nm}) "
               f"— dialect rates are not comparable; reported, not "
               f"gated")
        for side, doc in (("baseline", old), ("candidate", new)):
            val = doc.get("value")
            if val is not None:
                d.note(f"  {side} [{bench_mode(doc)}]: {val:,.1f} "
                       f"{doc.get('unit', '?')}")
        return d.render()
    diff_headline(old, new, d, args.max_regress)
    diff_phases(old, new, d, args.phase_max_regress, args.phase_floor)
    if om == "swarm":
        # Swarm-dialect axes, then the shared perf/stage axes (swarm
        # docs now embed a perf block and walk-kernel chunk_stages —
        # the launch-drift and stage gates apply unchanged); the
        # exhaustive pruned/coverage axes have no meaning for a walker
        # and fall through as silent no-ops anyway.
        diff_swarm(old, new, d, args.max_regress)
        diff_hunt(old, new, d, args.hunt_drift)
        diff_stages(old, new, d, args.stage_max_regress)
        diff_perf(old, new, d, args.launch_drift)
        return d.render()
    diff_stages(old, new, d, args.stage_max_regress)
    diff_perf(old, new, d, args.launch_drift)
    diff_pruned(old, new, d, args.pruned_drift)
    diff_coverage(old, new, d, args.coverage_drift)
    return d.render()


if __name__ == "__main__":
    sys.exit(main())
