"""Analyze artifacts/row_alias_pairs.pkl (from row_dedup_sweep.py).

Groups captured states by row digest and, for each group, reports:
- whether the pair is VALUE-EQUAL as spec states (=> the oracle's
  canon_digest split one spec state into two: oracle overcount, engine
  right), or
- the exact structural diff (=> the engine's canonical encoding merges
  two spec-distinct states: encoding injectivity hole, engine wrong),
plus the decode(encode(s)) round-trip for each member, which localizes
any lost field immediately.

Usage: python scripts/inspect_alias_pairs.py [pkl]
"""

import os
import pickle
import sys
from collections import defaultdict

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from raft_tla_tpu.models.schema import decode_state, encode_state
from raft_tla_tpu.utils.cfg import load_config


def diff_states(a, b):
    out = []
    for f in ("current_term", "role", "voted_for", "log", "commit_index",
              "votes_responded", "votes_granted", "next_index",
              "match_index"):
        va, vb = getattr(a, f), getattr(b, f)
        if va != vb:
            out.append((f, va, vb))
    if a.messages != b.messages:
        only_a = sorted(set(a.messages) - set(b.messages))
        only_b = sorted(set(b.messages) - set(a.messages))
        out.append(("messages", only_a, only_b))
    return out


def main():
    pkl = sys.argv[1] if len(sys.argv) > 1 else \
        "artifacts/row_alias_pairs.pkl"
    cfg = sys.argv[2] if len(sys.argv) > 2 else "configs/MCraft_bounded.cfg"
    setup = load_config(cfg)
    dims = setup.dims
    with open(pkl, "rb") as f:
        hits = pickle.load(f)
    print(f"{len(hits)} captured states")
    groups = defaultdict(list)
    dedup = set()
    for h in hits:
        # A phase-2 sweep revisits both members of a pair, so a pkl from
        # an older sweep may hold a state twice; keep each state once.
        k2 = (h["rowdigest"], h["state"])
        if k2 in dedup:
            continue
        dedup.add(k2)
        groups[h["rowdigest"]].append(h)
    print(f"{len(groups)} alias groups")
    for rd, members in sorted(groups.items()):
        print(f"\n=== row {rd[:16]}…  ({len(members)} members, levels "
              f"{sorted(m['level'] for m in members)}, phases "
              f"{sorted(m['phase'] for m in members)})")
        states = [m["state"] for m in members]
        for k, s in enumerate(states):
            rt = decode_state(encode_state(s, dims), dims)
            tag = "round-trip OK" if rt == s else \
                f"ROUND-TRIP LOSSY: {diff_states(s, rt)}"
            print(f"  member {k}: {tag}")
        if len(states) >= 2:
            d = diff_states(states[0], states[1])
            if not d:
                print("  PAIR VALUE-EQUAL -> oracle canon_digest artifact "
                      "(engine right)")
            else:
                print(f"  PAIR DIFFERS -> encoding alias; diff: {d}")


if __name__ == "__main__":
    main()
