"""Localize the L13 48-state deficit: digest-dedup vs engine-row-dedup.

Context (ROUND4_NOTES.md, fingerprint.py docstring): at MCraft_bounded
level 13 the engine counts 63,312,389 distinct vs the oracle's 63,312,437
(-48), bit-identically under two independent fingerprint designs — so the
deficit is NOT hash collisions.  Two mutually-exclusive explanations
remain, and this sweep decides between them while capturing the exact
pairs:

(a) ENGINE ENCODING HOLE: 48 pairs of spec-distinct states alias to the
    same canonical StateBatch content (the fingerprint's input), so the
    engine merges them.  Then the pair's two PyStates differ structurally.
(b) ORACLE OVERCOUNT: oracle_exhaust.py's canon_digest pickles raw state
    tuples; any value-equal-but-representation-different states (or a
    non-canonical detail the spec does not distinguish) split one spec
    state into two digests.  Then the pair's two PyStates are value-equal.

Method: one oracle BFS sweep (dedup by the same BLAKE digest as
oracle_exhaust.py) that ALSO maps every state to a digest of its
ENGINE-CANONICAL ROW — a pure-Python, type-normalized mirror of
models/schema.py's encode_state content with the message bag as a sorted
(row, count) multiset, exactly the information ops/fingerprint.py hashes.
When two digest-distinct states map to one row digest, the second arrival
is pickled immediately; a second, targeted sweep then captures the first
arrivals (phase 2 — only runs if phase 1 flagged anything).

Usage: python scripts/row_dedup_sweep.py [cfg] [out.jsonl] [max_levels]
Artifacts: artifacts/row_alias_pairs.pkl (list of {rowdigest, phase,
           level, state}), out.jsonl (per-level digest vs row counts).
"""

import json
import os
import pickle
import sys
import time
from hashlib import blake2b

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from raft_tla_tpu.models import oracle as orc
from raft_tla_tpu.models.dims import AEQ, RVQ, RVR
from raft_tla_tpu.models.invariants import constraint_py
from raft_tla_tpu.models.pystate import init_state
from raft_tla_tpu.utils.cfg import load_config


def canon_digest(s) -> bytes:
    """Spec-side digest as oracle_exhaust.canon_digest had it BEFORE the
    memoization fix — kept memo-SENSITIVE deliberately: this sweep's job
    was to demonstrate that this digest splits value-equal states (it
    does: 48 pairs at L13, every pair PyState-==; see ROUND5_NOTES.md).
    oracle_exhaust.py now hashes with Pickler.fast (memo-free)."""
    canon = (s.current_term, s.role, s.voted_for, s.log, s.commit_index,
             s.votes_responded, s.votes_granted, s.next_index,
             s.match_index, tuple(sorted(s.messages)))
    return blake2b(pickle.dumps(canon, protocol=5), digest_size=16).digest()


def build_row_digest(dims):
    """Engine-canonical-row digest: the information content of
    models/schema.py encode_state + ops/fingerprint.py's bag treatment
    (multiset of (packed row, count)), with every value normalized to a
    Python int so representation differences cannot split a row."""
    L, W = dims.max_log, dims.msg_width

    def encode_msg(m):
        """Mirror of schema.encode_message, as a W-tuple of ints."""
        w = [0] * W
        mtype, src, dst, mterm = int(m[0]), int(m[1]), int(m[2]), int(m[3])
        w[0], w[1], w[2], w[3] = mtype + 1, src + 1, dst + 1, mterm
        if mtype == RVQ:
            w[4], w[5] = int(m[4]), int(m[5])
        elif mtype == RVR:
            granted, mlog = m[4], m[5]
            w[4], w[5] = int(granted), len(mlog)
            for k, (t, v) in enumerate(mlog):
                w[6 + k] = int(t)
                w[6 + L + k] = int(v)
        elif mtype == AEQ:
            prev, pterm, entries, mcommit = m[4], m[5], m[6], m[7]
            w[4], w[5], w[6] = int(prev), int(pterm), len(entries)
            if entries:
                w[7], w[8] = int(entries[0][0]), int(entries[0][1])
            w[9] = int(mcommit)
        else:
            w[4], w[5] = int(m[4]), int(m[5])
        return tuple(w)

    def row_digest(s) -> bytes:
        logs = tuple(
            (tuple(int(t) for t, _ in lg) + (0,) * (L - len(lg)),
             tuple(int(v) for _, v in lg) + (0,) * (L - len(lg)),
             len(lg))
            for lg in s.log)
        bag = tuple(sorted(
            (encode_msg(m), int(c)) for m, c in s.messages))
        canon = (tuple(int(x) for x in s.current_term),
                 tuple(int(x) for x in s.role),
                 tuple(int(x) for x in s.voted_for),
                 logs,
                 tuple(int(x) for x in s.commit_index),
                 tuple(int(x) for x in s.votes_responded),
                 tuple(int(x) for x in s.votes_granted),
                 tuple(tuple(int(x) for x in r) for r in s.next_index),
                 tuple(tuple(int(x) for x in r) for r in s.match_index),
                 bag)
        return blake2b(pickle.dumps(canon, protocol=5),
                       digest_size=16).digest()

    return row_digest


def sweep(setup, max_levels, out_path, flagged_rows=None):
    """One BFS sweep.  Phase 1 (flagged_rows=None): build row->canon map,
    log second arrivals of any row collision.  Phase 2 (flagged_rows=set):
    no map, just capture every state whose row digest is flagged."""
    dims, bounds = setup.dims, setup.bounds
    constraint = constraint_py(bounds)
    row_digest = build_row_digest(dims)
    t0 = time.time()

    seen = set()
    row_map = {} if flagged_rows is None else None
    hits = []
    distinct = generated = 0
    frontier = []
    for s0 in [init_state(dims)]:
        d = canon_digest(s0)
        seen.add(d)
        distinct += 1
        rd = row_digest(s0)
        if row_map is not None:
            row_map[rd] = d
        elif rd in flagged_rows:
            hits.append({"rowdigest": rd.hex(), "phase": 2, "level": 0,
                         "state": s0})
        if constraint(s0, dims):
            frontier.append(s0)

    level = 0
    out = open(out_path, "a" if flagged_rows else "w")

    def emit(reason="running"):
        nrows = len(row_map) if row_map is not None else -1
        rec = {"phase": 1 if flagged_rows is None else 2, "level": level,
               "frontier": len(frontier), "distinct": distinct,
               "row_distinct": nrows, "generated": generated,
               "aliases": len(hits), "wall_s": round(time.time() - t0, 1),
               "stop_reason": reason}
        out.write(json.dumps(rec) + "\n")
        out.flush()
        print(rec, flush=True)

    emit()
    while frontier and (max_levels is None or level < max_levels):
        nxt = []
        for s in frontier:
            succ = orc.successors(s, dims)
            generated += len(succ)
            for _act, t in succ:
                d = canon_digest(t)
                if d in seen:
                    continue
                seen.add(d)
                distinct += 1
                rd = row_digest(t)
                if row_map is not None:
                    prev = row_map.get(rd)
                    if prev is None:
                        row_map[rd] = d
                    else:
                        # Digest-distinct, row-equal: the second arrival
                        # of an alias pair.  Capture it NOW (its partner
                        # is phase 2's job).
                        hits.append({"rowdigest": rd.hex(), "phase": 1,
                                     "level": level + 1, "state": t})
                elif rd in flagged_rows:
                    hits.append({"rowdigest": rd.hex(), "phase": 2,
                                 "level": level + 1, "state": t})
                if constraint(t, dims):
                    nxt.append(t)
        level += 1
        frontier = nxt
        emit()
    emit("done")
    out.close()
    return hits, distinct, (len(row_map) if row_map is not None else None)


def main():
    cfg_path = sys.argv[1] if len(sys.argv) > 1 else \
        "configs/MCraft_bounded.cfg"
    out_path = sys.argv[2] if len(sys.argv) > 2 else \
        "artifacts/row_dedup_sweep.jsonl"
    max_levels = int(sys.argv[3]) if len(sys.argv) > 3 else 13
    setup = load_config(cfg_path)

    hits, distinct, row_distinct = sweep(setup, max_levels, out_path)
    print(json.dumps({"phase": 1, "digest_distinct": distinct,
                      "row_distinct": row_distinct,
                      "alias_second_arrivals": len(hits)}), flush=True)
    pkl = "artifacts/row_alias_pairs.pkl"
    if hits:
        flagged = {bytes.fromhex(h["rowdigest"]) for h in hits}
        hits2, _, _ = sweep(setup, max_levels, out_path,
                            flagged_rows=flagged)
        # Phase 2 revisits BOTH members of each pair; drop the second
        # arrivals already captured in phase 1 so the pkl holds each
        # state exactly once (inspect_alias_pairs groups by rowdigest).
        seen1 = {canon_digest(h["state"]) for h in hits}
        hits2 = [h for h in hits2
                 if canon_digest(h["state"]) not in seen1]
        with open(pkl, "wb") as f:
            pickle.dump(hits + hits2, f)
        print(json.dumps({"phase": 2, "captured": len(hits) + len(hits2),
                          "pkl": pkl}), flush=True)
    else:
        with open(pkl, "wb") as f:
            pickle.dump([], f)
        print(json.dumps({"phase": 2, "captured": 0,
                          "note": "no aliases found"}), flush=True)


if __name__ == "__main__":
    main()
