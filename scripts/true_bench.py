"""Trustworthy device timings under the axon tunnel.

``block_until_ready`` does not reliably block on this backend, so every
measurement here loops the op N times inside ONE jitted ``lax.fori_loop``
(data-chained so iterations can't collapse) and ends with a host fetch of a
scalar — a true barrier.  Reported per-iteration time subtracts nothing;
with N=8 the dispatch+RTT overhead is amortized to noise.

``TB_JSON=path`` additionally writes the measurements as one JSON
object in the bench.py dialect — ``ms`` (this script's fori-loop
numbers), ``chunk_stages`` (the shared obs/profile.py staged
decomposition over the same warm frontier), and ``coverage`` (the
warm run's TLC-style per-action object) — so scripts/bench_diff.py
can gate tunnel-measured trajectories exactly like bench.py ones.
"""

import json
import os
import sys
import time

sys.path.insert(0, ".")

from raft_tla_tpu.utils.platform import neutralize_axon_if_cpu_requested

neutralize_axon_if_cpu_requested()   # honor JAX_PLATFORMS=cpu

import jax
import jax.numpy as jnp
import numpy as np

from raft_tla_tpu.models.actions import build_expand
from raft_tla_tpu.models.schema import flatten_state, unflatten_state
from raft_tla_tpu.ops import fpset
from raft_tla_tpu.ops.fingerprint import SENTINEL, build_fingerprint
from raft_tla_tpu.utils.cfg import load_config

N = 4

#: name -> ms/iter, what TB_JSON serializes.
RESULTS = {}


def timed(name, jitted, *args):
    out = jitted(*args)
    _ = float(np.asarray(jax.tree.leaves(out)[0]).ravel()[0])  # barrier
    t0 = time.time()
    out = jitted(*args)
    _ = float(np.asarray(jax.tree.leaves(out)[0]).ravel()[0])  # barrier
    dt = (time.time() - t0) / N * 1e3
    print(f"{name:46s} {dt:9.2f} ms/iter")
    RESULTS[name] = round(dt, 3)
    return dt


def main():
    print("platform:", jax.devices()[0].platform, " N =", N)
    setup = load_config("configs/MCraft_bounded.cfg")
    dims = setup.dims
    B = int(os.environ.get("TB_BATCH", 2048))
    G = dims.n_instances
    K = B * G
    # Workload generated in-process (runs from a fresh clone): a few real
    # BFS levels supply a representative mid-level frontier, and one
    # expand+fingerprint pass over it supplies real candidate keys.
    from raft_tla_tpu.engine.bfs import EngineConfig
    from raft_tla_tpu.engine.check import initial_states, make_engine
    # The warm-up run doubles as the telemetry-regression gate (same
    # contract as bench.py): its event log must exist and parse, or the
    # whole measurement exits nonzero — microbenchmark numbers from an
    # unobservable engine are not trustworthy evidence.
    import tempfile
    scratch_dir = tempfile.mkdtemp(prefix="tb_obs_")
    warm = make_engine(setup, EngineConfig(
        batch=B, queue_capacity=1 << 20, seen_capacity=1 << 23,
        record_trace=False, check_deadlock=False, max_diameter=4,
        events_out=os.path.join(scratch_dir, "events.jsonl")))
    wres = warm.run(initial_states(setup))
    # Engine-resolved path + cleanup-on-both-outcomes, shared with
    # bench.py (obs.validate_and_cleanup).
    from raft_tla_tpu.obs import validate_and_cleanup
    try:
        validate_and_cleanup(warm._events_path(), scratch_dir)
    except (OSError, ValueError) as e:
        print(f"true_bench: telemetry regression — event log invalid: {e}",
              file=sys.stderr)
        sys.exit(1)
    wrows = warm._last_frontier
    rows = jnp.asarray(np.tile(wrows, (-(-B // len(wrows)), 1))[:B])
    expand = build_expand(dims)
    fingerprint = build_fingerprint(dims)

    @jax.jit
    def mkkeys(rows):
        states = jax.vmap(unflatten_state, (0, None))(rows, dims)
        cands, en, _ovf = jax.vmap(expand)(states)
        cflat = jax.tree.map(lambda a: a.reshape((K,) + a.shape[2:]), cands)
        crows = jax.vmap(flatten_state, (0, None))(cflat, dims)
        st2 = jax.vmap(unflatten_state, (0, None))(crows, dims)
        fh, fl = jax.vmap(fingerprint)(st2)
        return fh, fl, en.reshape(-1)

    fph, fpl, enf = mkkeys(rows)
    C = 1 << 23

    @jax.jit
    def loop_insert(fph, fpl, enf):
        s = fpset.empty(C)

        def body(i, carry):
            s, acc = carry
            s2, new, fail = fpset.insert(s, fph ^ i.astype(jnp.uint32),
                                         fpl, enf)
            return s2, acc + jnp.sum(new, dtype=jnp.int32)

        s, acc = jax.lax.fori_loop(0, N, body, (s, jnp.int32(0)))
        return acc

    timed("insert 270k real keys", loop_insert, fph, fpl, enf)

    @jax.jit
    def loop_dedup(fph, fpl, enf):
        def body(i, acc):
            (sh, sl), order, first = fpset.dedup_batch(
                fph ^ i.astype(jnp.uint32), fpl, enf)
            return acc + jnp.sum(first, dtype=jnp.int32)

        return jax.lax.fori_loop(0, N, body, jnp.int32(0))

    timed("dedup_batch (sort 270k)", loop_dedup, fph, fpl, enf)

    @jax.jit
    def loop_bigsort(fph):
        base = jnp.full((C,), SENTINEL, jnp.uint32)

        def body(i, acc):
            ch = jnp.concatenate([base, fph ^ i.astype(jnp.uint32)])
            sh, _sl = jax.lax.sort((ch, ch), num_keys=2)
            return acc + sh[0].astype(jnp.int32)

        return jax.lax.fori_loop(0, N, body, jnp.int32(0))

    timed("merge-sort 8M+270k (old FPSet)", loop_bigsort, fph)

    @jax.jit
    def loop_expand(rows):
        def body(i, acc):
            states = jax.vmap(unflatten_state, (0, None))(
                rows.at[0, 0].add(i.astype(rows.dtype)), dims)
            cands, en, ovf = jax.vmap(expand)(states)
            cflat = jax.tree.map(
                lambda a: a.reshape((K,) + a.shape[2:]), cands)
            crows = jax.vmap(flatten_state, (0, None))(cflat, dims)
            return acc + jnp.sum(crows[:, 0], dtype=jnp.int32) \
                + jnp.sum(en, dtype=jnp.int32)

        return jax.lax.fori_loop(0, N, body, jnp.int32(0))

    timed("expand+flatten 2048 states", loop_expand, rows)

    @jax.jit
    def loop_fp(rows):
        def body(i, acc):
            states = jax.vmap(unflatten_state, (0, None))(
                rows.at[0, 0].add(i.astype(rows.dtype)), dims)
            cands, en, ovf = jax.vmap(expand)(states)
            cflat = jax.tree.map(
                lambda a: a.reshape((K,) + a.shape[2:]), cands)
            crows = jax.vmap(flatten_state, (0, None))(cflat, dims)
            st2 = jax.vmap(unflatten_state, (0, None))(crows, dims)
            fh, fl = jax.vmap(fingerprint)(st2)
            return acc + jnp.sum(fh, dtype=jnp.uint32).astype(jnp.int32)

        return jax.lax.fori_loop(0, N, body, jnp.int32(0))

    t_fp = timed("expand+flatten+fingerprint", loop_fp, rows)

    Q = 1 << 20
    crows = jnp.zeros((K, 473), jnp.uint8)

    @jax.jit
    def loop_enqueue(crows, enf):
        qnext = jnp.zeros((Q, 473), jnp.uint8)

        def body(i, carry):
            qnext, acc = carry
            enq = enf
            pos = jnp.cumsum(enq.astype(jnp.int32)) - 1
            pos = jnp.where(enq, pos + i, Q)
            qnext = qnext.at[pos].set(crows, mode="drop")
            return qnext, acc + qnext[0, 0].astype(jnp.int32)

        qnext, acc = jax.lax.fori_loop(0, N, body, (qnext, jnp.int32(0)))
        return acc

    timed("enqueue row-scatter 270k->1M", loop_enqueue, crows, enf)

    @jax.jit
    def loop_gather_rows(crows, enf):
        order = jnp.argsort(~enf)           # enabled rows first

        def body(i, acc):
            sel = crows[order + i - i]      # row gather 270k x 473
            return acc + sel[0, 0].astype(jnp.int32)

        return jax.lax.fori_loop(0, N, body, jnp.int32(0))

    timed("row-gather 270k x 473", loop_gather_rows, crows, enf)

    out_path = os.environ.get("TB_JSON")
    if out_path:
        # bench.py-dialect JSON: chunk_stages + coverage are the two
        # axes scripts/bench_diff.py gates on; "ms" carries this
        # script's own fori-loop numbers for eyeballing.
        from raft_tla_tpu.obs.profile import profile_stages
        stage_means = profile_stages(
            dims, np.asarray(rows), seen_capacity=1 << 23, n=max(N, 2))
        doc = {
            "metric": "true_bench_ms",
            "value": RESULTS.get("expand+flatten+fingerprint", 0.0),
            "unit": "ms/iter",
            "platform": jax.devices()[0].platform,
            "batch": B,
            "n_iters": N,
            "ms": RESULTS,
            "chunk_stages": {k: round(v, 6)
                             for k, v in stage_means.items()},
            "coverage": wres.coverage,
            "distinct_states": wres.distinct,
            "generated_states": wres.generated,
        }
        with open(out_path, "w", encoding="utf-8") as f:
            json.dump(doc, f, indent=1)
            f.write("\n")
        print(f"true_bench: wrote {out_path}")


if __name__ == "__main__":
    main()
