"""Bisect the BFS step cost: time expand / flatten / fingerprint / insert /
enqueue in isolation on the ambient platform."""

import sys
import time

sys.path.insert(0, ".")

import jax
import jax.numpy as jnp
import numpy as np

from raft_tla_tpu.models.actions import build_expand
from raft_tla_tpu.models.invariants import build_type_ok, build_inv_id
from raft_tla_tpu.models.pystate import init_state
from raft_tla_tpu.models.schema import (encode_state, flatten_state,
                                        state_width, unflatten_state)
from raft_tla_tpu.ops import fpset
from raft_tla_tpu.ops.fingerprint import build_fingerprint
from raft_tla_tpu.utils.cfg import load_config


def timeit(name, fn, *args, n=5):
    jax.block_until_ready(fn(*args))
    t0 = time.time()
    for _ in range(n):
        out = fn(*args)
    jax.block_until_ready(out)
    print(f"{name:44s} {(time.time() - t0) / n * 1e3:9.2f} ms")


def main():
    print("platform:", jax.devices()[0].platform)
    setup = load_config("configs/MCraft_bounded.cfg")
    dims = setup.dims
    B, G, SW = 2048, dims.n_instances, state_width(dims)
    print(f"B={B} G={G} SW={SW}  B*G={B*G}")
    expand = build_expand(dims)
    fingerprint = build_fingerprint(dims)

    row = flatten_state(encode_state(init_state(dims), dims), dims)
    rows = jnp.asarray(np.tile(row[None, :], (B, 1)).astype(np.int32))

    @jax.jit
    def just_expand(rows):
        states = jax.vmap(unflatten_state, (0, None))(rows, dims)
        cands, en, ovf = jax.vmap(expand)(states)
        return jax.tree.map(lambda a: jnp.sum(a), cands), en.sum(), ovf.sum()

    @jax.jit
    def expand_flatten(rows):
        states = jax.vmap(unflatten_state, (0, None))(rows, dims)
        cands, en, ovf = jax.vmap(expand)(states)
        cflat = jax.tree.map(lambda a: a.reshape((B * G,) + a.shape[2:]),
                             cands)
        crows = jax.vmap(flatten_state, (0, None))(cflat, dims)
        return crows, en, ovf

    @jax.jit
    def fp_of_rows(crows):
        states = jax.vmap(unflatten_state, (0, None))(crows, dims)
        return jax.vmap(fingerprint)(states)

    inv = build_type_ok(dims)

    @jax.jit
    def inv_of_rows(crows):
        states = jax.vmap(unflatten_state, (0, None))(crows, dims)
        return jax.vmap(build_inv_id([inv]))(states)

    timeit("expand only (reduced)", just_expand, rows)
    timeit("expand + flatten -> crows", expand_flatten, rows)
    crows, en, _ = expand_flatten(rows)
    crows = jax.block_until_ready(crows)
    timeit("fingerprint 270k rows", fp_of_rows, crows)
    timeit("TypeOK 270k rows", inv_of_rows, crows)

    fph, fpl = fp_of_rows(crows)
    seen = fpset.empty(1 << 23)
    timeit("hash insert 270k", jax.jit(fpset.insert), seen, fph, fpl,
           en.reshape(-1))

    Q = 1 << 20
    qnext = jnp.zeros((Q, SW), jnp.int32)

    @jax.jit
    def enqueue(qnext, crows, enq):
        pos = jnp.cumsum(enq.astype(jnp.int32)) - 1
        pos = jnp.where(enq, pos, Q)
        return qnext.at[pos].set(crows, mode="drop")

    timeit("enqueue scatter 270k rows", enqueue, qnext, crows,
           en.reshape(-1))


if __name__ == "__main__":
    main()
