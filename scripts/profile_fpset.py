"""Microbenchmark of FPSet primitive costs on the ambient platform.

Times, per call: one big scatter; one big gather; the hash-insert (static
rounds vs while_loop); the old sorted-merge (full lax.sort) and
binary-search probe — to decide which dedup design the TPU actually wants.

CAVEAT (measured round 3): under the axon TPU tunnel,
``block_until_ready`` on repeated same-input calls does not reliably
reflect device time — numbers here flip-flopped by 1000x between
sessions.  Treat these as CPU-backend sanity numbers; for trustworthy TPU
timings use scripts/true_bench.py (fori_loop-chained iterations, host
scalar fetch as the barrier) or end-to-end engine runs.
"""

import sys
import os
import time

sys.path.insert(0, ".")

from raft_tla_tpu.utils.platform import neutralize_axon_if_cpu_requested

neutralize_axon_if_cpu_requested()   # honor JAX_PLATFORMS=cpu

import jax
import jax.numpy as jnp
import numpy as np

from raft_tla_tpu.ops import fpset
from raft_tla_tpu.ops.fingerprint import SENTINEL

C = int(os.environ.get("FPSET_C", 1 << 23))   # table capacity
K = int(os.environ.get("FPSET_K", 1 << 18))   # keys per insert


def timeit(name, fn, *args, n=5):
    jax.block_until_ready(fn(*args))
    t0 = time.time()
    for _ in range(n):
        out = fn(*args)
    jax.block_until_ready(out)
    print(f"{name:40s} {(time.time() - t0) / n * 1e3:9.2f} ms")


def main():
    print("platform:", jax.devices()[0].platform)
    rng = np.random.RandomState(0)
    qhi = jnp.asarray(rng.randint(0, 1 << 32, K, np.uint64).astype(np.uint32))
    qlo = jnp.asarray(rng.randint(0, 1 << 32, K, np.uint64).astype(np.uint32))
    valid = jnp.ones((K,), bool)
    idx = jnp.asarray(rng.randint(0, C, K, np.int64).astype(np.int32))
    big = jnp.zeros((C,), jnp.uint32)
    upd = qhi

    timeit("scatter 256k -> 8M", jax.jit(
        lambda b, i, u: b.at[i].set(u, mode="drop")), big, idx, upd)
    timeit("scatter-max 256k -> 8M", jax.jit(
        lambda b, i, u: b.at[i].max(u, mode="drop")), big, idx, upd)
    timeit("gather 256k <- 8M", jax.jit(lambda b, i: b[i]), big, idx)
    timeit("sort 256k (3 lanes)", jax.jit(
        lambda a, b: jax.lax.sort((a, b, jnp.arange(K, dtype=jnp.int32)),
                                  num_keys=2)), qhi, qlo)
    bighi = jnp.full((C,), SENTINEL, jnp.uint32)
    timeit("sort 8M+256k (2 lanes, old merge)", jax.jit(
        lambda bh, nh: jax.lax.sort(
            (jnp.concatenate([bh, nh]), jnp.concatenate([bh, nh])),
            num_keys=2)), bighi, qhi)

    s = fpset.empty(C)
    ins = jax.jit(fpset.insert)
    timeit("hash insert 256k -> empty 8M", ins, s, qhi, qlo, valid)
    # Table at ~50% load.
    s50 = fpset.empty(C)
    half = C // 2
    fill_hi = jnp.asarray(
        rng.randint(0, 1 << 32, half, np.uint64).astype(np.uint32))
    fill_lo = jnp.asarray(
        rng.randint(0, 1 << 32, half, np.uint64).astype(np.uint32))
    ins_d = jax.jit(fpset.insert, donate_argnums=(0,))
    for b in range(0, half, K):
        s50, _, _ = ins_d(s50, fill_hi[b:b + K], fill_lo[b:b + K], valid)
    timeit("hash insert 256k -> 50%-load 8M", ins, s50, qhi, qlo, valid)
    timeit("hash contains 256k in 50%-load 8M", jax.jit(fpset.contains),
           s50, qhi, qlo)


if __name__ == "__main__":
    main()
